"""avida-tpu: a TPU-native digital-evolution framework.

A ground-up reimplementation of the capabilities of Avida (reference:
fortunalab/avida) designed for TPUs: the entire population is stepped in
lockstep by a jit-compiled SIMD bytecode interpreter (JAX/XLA), with genomes,
registers, heads, stacks, phenotypes, the world grid and resources resident in
HBM as structure-of-arrays tensors.  The reference's organism-at-a-time
scheduler (cPopulation::ProcessStep, avida-core/source/main/cPopulation.cc:5703)
collapses into per-update execution budgets realised as masked micro-steps.

Layer map (mirrors SURVEY.md §1, re-architected):
  config/    -- host-side parsers for avida.cfg / instset / .org /
                environment.cfg / events.cfg (ref: cAvidaConfig, cInstSet,
                cEnvironment::Load, cEventList)
  core/      -- population state pytrees + PRNG discipline
  models/    -- virtual hardware definitions (heads CPU, ...) as semantic
                instruction tables (ref: source/cpu/cHardware*)
  ops/       -- the jitted compute path: SIMD interpreter, scheduler,
                tasks/reactions, birth engine, the update step
  parallel/  -- device mesh, sharded update, migration collectives
                (ref: cMultiProcessWorld -> shard_map + collectives)
  utils/     -- .dat output writers, .spop checkpointing, stats
"""

__version__ = "0.1.0"


def __getattr__(name):
    # lazy re-export (PEP 562): importing World pulls in jax/flax and the
    # whole engine, which `python -m avida_tpu --status DIR` -- the
    # outside-the-process heartbeat reader -- must never pay for.  Plain
    # `import avida_tpu` stays lightweight; `avida_tpu.World` and
    # `from avida_tpu import World` resolve on first touch.
    if name == "World":
        from avida_tpu.world import World
        return World
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
