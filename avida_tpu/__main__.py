"""Command-line driver: the `avida` executable equivalent.

Mirrors the reference CLI (targets/avida/primitive.cc:36 main;
Avida::Util::ProcessCmdLineArgs, source/util/CmdLine.cc:205):

  python -m avida_tpu [-c <dir>] [-s <seed>] [-set NAME VALUE]...
                      [-d <data_dir>] [-u <max_updates>] [-a] [-v]

  -c DIR     config directory (avida.cfg / environment.cfg / events.cfg /
             instruction set / .org files); defaults built in when absent
  -s SEED    random seed override (RANDOM_SEED)
  -set N V   any config variable override (repeatable)
  -d DIR     data output directory
  -u N       stop after N updates (overrides events-driven exit)
  -a         analyze mode: run ANALYZE_FILE (analyze.cfg) through the
             batch VM instead of an evolution run (ANALYZE_MODE=1)
  -a CKPT_DIR / --analyze CKPT_DIR
             checkpoint-native analytics (analyze/pipeline.py): load the
             newest CRC-valid native checkpoint generation (falling back
             past corrupt ones exactly like --resume), reconstruct the
             population + systematics tables, and run the batched
             phenotype census, knockout attribution and dominant-lineage
             replay offline -- census/knockout/lineage .dat tables under
             DATA_DIR/analysis/, {"record":"analytics"} runlog lines and
             DATA_DIR/analytics.prom.  No World.run, no donated-buffer
             compile; the update_step jaxpr is untouched.
  -v         verbose

TPU-build extras (no reference equivalent):

  --telemetry        enable the runtime telemetry subsystem
                     (avida_tpu/observability/): per-update phase timers,
                     device counters and a telemetry.jsonl run log in the
                     data dir.  Shorthand for -set TPU_TELEMETRY 1.
                     Telemetry runs per-update with fenced phases --
                     expect lower throughput than the fused default.
  --profile-dir DIR  with --telemetry: capture a jax.profiler (XProf)
                     trace of the first few updates into DIR
                     (TPU_PROFILE_UPDATES controls how many).
  --resume [DIR]     restore the newest valid native checkpoint
                     generation (utils/checkpoint.py) before running;
                     DIR defaults to TPU_CKPT_DIR.  With TPU_CKPT_DIR
                     set, SIGTERM/SIGINT preemption saves a final
                     checkpoint and exits 0, so a preempt/restart cycle
                     of `--resume` runs is bit-exact with an
                     uninterrupted run.
  --trace            enable the device-side flight recorder
                     (observability/tracer.py): structured events
                     recorded inside the jitted update, drained to
                     {"record":"trace"} runlog lines at chunk
                     boundaries, plus the metrics.prom heartbeat.
                     Shorthand for -set TPU_TRACE 1.
  --status DIR       print the last heartbeat of the run writing to
                     data dir DIR (reads DIR/metrics.prom; no JAX
                     import, works while the run is live) and exit.
  --max-age SEC      with --status: exit 2 when the heartbeat is
                     missing or older than SEC seconds (0 fresh,
                     1 no metrics file) -- consumable by external
                     watchdogs and cron.
  --fleet SPOOL      run the fleet orchestrator (service/fleet.py):
                     drain SPOOL of JSON job specs and drive up to
                     --max-jobs concurrent supervised runs, each in its
                     own fault domain, with a crash-safe journal
                     (fleet.jsonl), admission control, a crash-storm
                     circuit breaker and graceful SIGTERM drain.
                     --serve keeps polling an empty spool instead of
                     exiting.  `--status SPOOL` prints the aggregate
                     fleet summary; scripts/fleet_tool.py
                     submits/lists/cancels/requeues jobs.
  --worlds SEEDS|MANIFEST
                     multi-world device batching (parallel/multiworld.py):
                     advance W static-equal worlds in ONE compiled
                     update_scan.  SEEDS is a comma list ("7,8,9"; world
                     k writes to DATA_DIR/w00k, checkpoints to
                     TPU_CKPT_DIR/w00k); MANIFEST is a worlds.json path
                     ([{"name","seed","data_dir","ckpt_dir"}] -- the
                     fleet's device-lane packing writes one per
                     coalesced batch).  Every world is bit-exact vs its
                     solo run and writes solo-compatible per-world
                     checkpoints; --resume restores all members aligned
                     on one common update.  The root DATA_DIR gets the
                     aggregate metrics.prom heartbeat plus per-world
                     rows in multiworld.prom.
  --serve-worlds CONTROL
                     continuous serving child (parallel/multiworld.py
                     ServeBatch): a fixed power-of-two-width batch whose
                     slots hold live tenant worlds or inert ghosts, with
                     membership reconciled against the CONTROL json at
                     every checkpoint boundary -- tenants are promoted
                     into ghost slots (resuming from their own
                     checkpoints) and demoted back out without a
                     recompile on either side.  The fleet serve pool
                     (service/serve.py, `--fleet SPOOL --dynamic`)
                     writes the control; see README "Fleet serving".
  --supervise        run under the self-healing supervisor
                     (service/supervisor.py): the remaining arguments
                     become the child run's command line (needs -d DIR
                     and -set TPU_CKPT_DIR DIR).  The supervisor
                     watchdogs the heartbeat, SIGKILLs hung runs,
                     restarts with backoff + --resume, rolls back past
                     audit violations and degrades Pallas failures to
                     the XLA path.  --fault-plan gives boot i the i-th
                     '/'-separated TPU_FAULT spec (chaos testing;
                     utils/faultinject.py).

Failure-classified exit codes (consumed by the supervisor):
  65  a state-invariant audit violation escaped the run
  66  --resume found checkpoints but no valid generation
  67  a scrub (shadow re-execution) caught silent data corruption
      (StateDivergenceError; the integrity plane, TPU_SCRUB_EVERY)
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def _worlds_main(args, overrides) -> int:
    """--worlds: the multi-world batched run (parallel/multiworld.py)."""
    from avida_tpu.parallel.multiworld import MultiWorld
    from avida_tpu.service import EXIT_AUDIT, EXIT_CKPT, EXIT_SDC
    from avida_tpu.utils.audit import StateInvariantError
    from avida_tpu.utils.checkpoint import (CheckpointError,
                                            CheckpointMismatchError,
                                            restore_candidates)
    from avida_tpu.utils.integrity import StateDivergenceError

    spec = args.worlds
    try:
        seeds = [int(s) for s in spec.split(",") if s.strip()]
    except ValueError:
        seeds = None
    try:
        if seeds:
            mw = MultiWorld.from_seeds(seeds, config_dir=args.config_dir,
                                       overrides=overrides,
                                       data_dir=args.data_dir or "data")
        elif os.path.exists(spec):
            mw = MultiWorld.from_manifest(spec,
                                          config_dir=args.config_dir,
                                          overrides=overrides,
                                          data_dir=args.data_dir)
        else:
            print(f"--worlds: {spec!r} is neither a comma seed list nor "
                  f"a worlds.json manifest path", file=sys.stderr)
            return 2
    except ValueError as e:
        # batch-ineligible config (telemetry/tracing/reversion/
        # generation triggers, shared dirs, ...): a deterministic
        # usage error, not a crash -- exit 2 with the reason on one
        # line so a supervisor's log shows WHY instead of a traceback
        print(f"[avida-tpu] --worlds refused: {e}", file=sys.stderr)
        return 2

    if args.resume is not None:
        if args.resume:
            # the solo path honors `--resume DIR`; a batch has one
            # checkpoint dir PER WORLD (TPU_CKPT_DIR subdirs or the
            # manifest's ckpt_dir entries), so a single override
            # directory is ambiguous -- refuse loudly rather than
            # silently resuming from somewhere else
            print("[avida-tpu] --worlds resumes from each world's own "
                  "checkpoint dir; --resume takes no directory here "
                  "(set TPU_CKPT_DIR / the manifest ckpt_dir instead)",
                  file=sys.stderr)
            return 2
        # restart-loop friendly like solo --resume: no member has any
        # checkpoint -> start fresh; all members have one -> resume
        # aligned; a PARTIAL set is unresumable (the batch cannot
        # straddle updates) -> classified exit 66
        have = [bool(w._ckpt_base() and restore_candidates(w._ckpt_base()))
                for w in mw.worlds]
        if all(have):
            try:
                at = mw.resume()
            except CheckpointMismatchError:
                raise
            except CheckpointError as e:
                print(f"[avida-tpu] resume failed: {e}", file=sys.stderr)
                return EXIT_CKPT
            except StateInvariantError as e:
                print(f"[avida-tpu] {e}", file=sys.stderr)
                return EXIT_AUDIT
            if args.verbose:
                print(f"resumed {mw.num_worlds} worlds at update {at}",
                      file=sys.stderr)
        elif any(have):
            # a PARTIAL set means no update is common to every member
            # (e.g. a crash landed between the batch's very first
            # per-world saves).  Starting everyone fresh is bit-exact
            # -- trajectories are pure functions of the seeds -- and
            # self-heals the wedge a hard refusal would loop on; the
            # loud warning covers the rarer lost-a-member's-dir case,
            # where peers deliberately roll back with the batch
            print("[avida-tpu] WARNING: only some worlds have "
                  "checkpoints (torn first save, or a member's dir was "
                  "lost); no common update exists, so the whole batch "
                  "restarts FRESH -- deterministic replay makes this "
                  "bit-exact for the torn-save case", file=sys.stderr)
        else:
            print("[avida-tpu] no checkpoints under any world; starting "
                  "fresh", file=sys.stderr)

    t0 = time.time()
    try:
        mw.run(max_updates=args.updates)
    except StateDivergenceError as e:
        # silent corruption caught by the integrity plane's scrub:
        # classified exit so the supervisor quarantines the suspect
        # generations and rolls back to a digest-verified one
        print(f"[avida-tpu] {e}", file=sys.stderr)
        return EXIT_SDC
    except StateInvariantError as e:
        print(f"[avida-tpu] {e}", file=sys.stderr)
        return EXIT_AUDIT
    if mw.preempted:
        print(f"[avida-tpu] preempted at update {mw.update}; "
              f"{mw.num_worlds} world checkpoints saved", file=sys.stderr)
        return 0
    if args.verbose:
        orgs = sum(w.num_organisms for w in mw.worlds)
        print(f"{mw.update} updates x {mw.num_worlds} worlds, "
              f"{orgs} organisms, {time.time() - t0:.1f}s",
              file=sys.stderr)
    return 0


def _serve_main(args, overrides) -> int:
    """--serve-worlds: the continuous-serving child
    (parallel/multiworld.ServeBatch).  The control file names the padded
    width and the desired membership; the fleet serve pool
    (service/serve.py) rewrites it to promote/demote tenants at
    checkpoint boundaries.  `--resume` is accepted and implicit:
    admission resumes any member whose checkpoint dir holds
    generations, so one fixed command line both starts and restarts a
    serve child bit-exactly."""
    import json

    from avida_tpu.parallel.multiworld import ServeBatch
    from avida_tpu.service import EXIT_AUDIT, EXIT_SDC
    from avida_tpu.utils.audit import StateInvariantError
    from avida_tpu.utils.integrity import StateDivergenceError

    control = args.serve_worlds
    try:
        with open(control) as f:
            width = int(json.load(f).get("width", 0))
    except (OSError, ValueError) as e:
        print(f"[avida-tpu] --serve-worlds: unreadable control file "
              f"{control!r} ({e})", file=sys.stderr)
        return 2
    if width < 1:
        print(f"[avida-tpu] --serve-worlds: {control!r} needs a "
              f"positive integer 'width'", file=sys.stderr)
        return 2
    data_dir = args.data_dir or os.path.dirname(control) or "data"
    try:
        sb = ServeBatch(width, control, data_dir,
                        config_dir=args.config_dir, overrides=overrides)
    except ValueError as e:
        print(f"[avida-tpu] --serve-worlds refused: {e}", file=sys.stderr)
        return 2
    t0 = time.time()
    try:
        sb.serve()
    except StateDivergenceError as e:
        # batch-wide divergence (a GHOST slot changed): every tenant is
        # suspect, so exit classified -- per-tenant corruption never
        # lands here (the serve loop demotes the tenant alone)
        print(f"[avida-tpu] {e}", file=sys.stderr)
        return EXIT_SDC
    except StateInvariantError as e:
        print(f"[avida-tpu] {e}", file=sys.stderr)
        return EXIT_AUDIT
    if sb.preempted:
        print(f"[avida-tpu] preempted; {sb.num_live} live tenant "
              f"checkpoints saved", file=sys.stderr)
        return 0
    if args.verbose:
        print(f"served {sb.admissions} tenants over {sb.boundaries} "
              f"boundaries, {time.time() - t0:.1f}s", file=sys.stderr)
    return 0


def main(argv=None):
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if "--supervise" in argv:
        # dispatched before anything heavy is imported: the supervisor
        # must never load jax (it has to outlive a wedged child runtime)
        from avida_tpu.service.supervisor import supervise_main
        return supervise_main(argv)
    if "--fleet" in argv:
        # same host-only rule: the orchestrator multiplexes many
        # supervised runs and must outlive every one of their runtimes
        from avida_tpu.service.fleet import fleet_main
        return fleet_main(argv)

    p = argparse.ArgumentParser(prog="avida_tpu", add_help=True)
    p.add_argument("-c", "--config-dir", default=None)
    p.add_argument("-s", "--seed", type=int, default=None)
    p.add_argument("-set", dest="overrides", nargs=2, action="append",
                   default=[], metavar=("NAME", "VALUE"))
    p.add_argument("-d", "--data-dir", default=None)
    p.add_argument("-u", "--updates", type=int, default=None)
    p.add_argument("-a", "--analyze", nargs="?", const=True, default=None,
                   metavar="CKPT_DIR")
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("--telemetry", action="store_true")
    p.add_argument("--profile-dir", default=None)
    p.add_argument("--resume", nargs="?", const="", default=None,
                   metavar="DIR")
    p.add_argument("--trace", action="store_true")
    p.add_argument("--worlds", default=None, metavar="SEEDS|MANIFEST")
    p.add_argument("--serve-worlds", default=None, metavar="CONTROL")
    p.add_argument("--status", default=None, metavar="DIR")
    p.add_argument("--max-age", type=float, default=None, metavar="SEC")
    args = p.parse_args(argv)

    if args.status is not None:
        # outside-the-process observability: read the metrics.prom /
        # fleet.prom heartbeat only -- no World, no JAX device init.  A
        # fleet spool (fleet.prom or fleet.jsonl present) gets the
        # aggregate per-job summary instead of the single-run view.
        if os.path.exists(os.path.join(args.status, "fleet.prom")) \
                or os.path.exists(os.path.join(args.status,
                                               "fleet.jsonl")):
            from avida_tpu.service.fleet import fleet_status_main
            return fleet_status_main(args.status, max_age=args.max_age)
        from avida_tpu.observability.exporter import status_main
        return status_main(args.status, max_age=args.max_age)

    overrides = list(map(tuple, args.overrides))
    if args.seed is not None:
        overrides.append(("RANDOM_SEED", args.seed))
    if args.telemetry:
        overrides.append(("TPU_TELEMETRY", 1))
    if args.trace:
        overrides.append(("TPU_TRACE", 1))
    if args.profile_dir:
        overrides.append(("TPU_TELEMETRY", 1))
        overrides.append(("TPU_PROFILE_DIR", args.profile_dir))

    if isinstance(args.analyze, str):
        # checkpoint-native analytics (analyze/pipeline.py): offline
        # census/knockout/lineage over an archived run's native
        # checkpoints -- builds its own config-resolved World (never run).
        # Guard against argparse swallowing a non-directory token (e.g.
        # the legacy bundled `-av`, which now parses as analyze='v'):
        # fail LOUDLY instead of silently rerouting
        if not os.path.isdir(args.analyze):
            print(f"[avida-tpu] --analyze: {args.analyze!r} is not a "
                  f"checkpoint directory (bare -a runs the analyze VM; "
                  f"-a/--analyze CKPT_DIR runs checkpoint analytics -- "
                  f"note bundled short flags like -av no longer parse)",
                  file=sys.stderr)
            return 2
        from avida_tpu.analyze.pipeline import cli_main as analyze_ckpt
        return analyze_ckpt(args.analyze, config_dir=args.config_dir,
                            overrides=overrides, data_dir=args.data_dir,
                            verbose=args.verbose)

    if args.serve_worlds is not None:
        return _serve_main(args, overrides)

    if args.worlds is not None:
        return _worlds_main(args, overrides)

    from avida_tpu.world import World
    world = World(config_dir=args.config_dir, overrides=overrides,
                  data_dir=args.data_dir)

    if args.analyze:
        from avida_tpu.analyze.analyzer import Analyzer
        az = Analyzer(world.params, world.instset,
                      data_dir=world.data_dir, verbose=args.verbose)
        path = (os.path.join(args.config_dir, world.cfg.ANALYZE_FILE)
                if args.config_dir else world.cfg.ANALYZE_FILE)
        az.run_file(path)
        return 0

    from avida_tpu.service import EXIT_AUDIT, EXIT_CKPT, EXIT_SDC
    from avida_tpu.utils.audit import StateInvariantError
    from avida_tpu.utils.checkpoint import (CheckpointError,
                                            CheckpointMismatchError)
    from avida_tpu.utils.integrity import StateDivergenceError

    if args.resume is not None:
        # restart-loop friendly: a preemptible job launches with ONE fixed
        # command line including --resume; on the very first boot the
        # checkpoint directory is empty, which means "start fresh", not
        # "crash" (generations that exist but fail verification still
        # fail -- classified exit 66 so a supervisor can tell "nothing
        # resumable" from a generic crash)
        from avida_tpu.utils.checkpoint import restore_candidates
        base = args.resume or world._ckpt_base()
        if base and not restore_candidates(base):
            print(f"[avida-tpu] no checkpoint under {base}; starting fresh",
                  file=sys.stderr)
        else:
            try:
                at = world.resume(args.resume or None)
            except CheckpointMismatchError:
                raise
            except CheckpointError as e:
                print(f"[avida-tpu] resume failed: {e}", file=sys.stderr)
                return EXIT_CKPT
            except StateInvariantError as e:
                # restore-time audit tripped: the restored generation is
                # internally corrupt (CRC-valid but bad state, e.g. saved
                # with TPU_CKPT_AUDIT=0) -- classified exit so the
                # supervisor quarantines it instead of blindly retrying
                print(f"[avida-tpu] {e}", file=sys.stderr)
                return EXIT_AUDIT
            if args.verbose:
                print(f"resumed at update {at}", file=sys.stderr)

    t0 = time.time()
    try:
        world.run(max_updates=args.updates)
    except StateDivergenceError as e:
        # silent corruption caught by the integrity plane's scrub: the
        # classified exit carries the last-verified-update marker the
        # supervisor's sdc rollback reads from this very line
        print(f"[avida-tpu] {e}", file=sys.stderr)
        return EXIT_SDC
    except StateInvariantError as e:
        # corruption caught by the auditor: exit with the classified
        # code so the supervisor rolls back instead of blindly retrying
        print(f"[avida-tpu] {e}", file=sys.stderr)
        return EXIT_AUDIT
    dt = time.time() - t0
    if world.preempted:
        # preemption is a CLEAN exit: the final checkpoint is on disk and
        # a follow-up `--resume` run continues bit-exactly
        print(f"[avida-tpu] preempted at update {world.update}; "
              f"checkpoint saved", file=sys.stderr)
        return 0
    if args.verbose:
        print(f"{world.update} updates, {world.num_organisms} organisms, "
              f"{dt:.1f}s", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
