"""Command-line driver: the `avida` executable equivalent.

Mirrors the reference CLI (targets/avida/primitive.cc:36 main;
Avida::Util::ProcessCmdLineArgs, source/util/CmdLine.cc:205):

  python -m avida_tpu [-c <dir>] [-s <seed>] [-set NAME VALUE]...
                      [-d <data_dir>] [-u <max_updates>] [-a] [-v]

  -c DIR     config directory (avida.cfg / environment.cfg / events.cfg /
             instruction set / .org files); defaults built in when absent
  -s SEED    random seed override (RANDOM_SEED)
  -set N V   any config variable override (repeatable)
  -d DIR     data output directory
  -u N       stop after N updates (overrides events-driven exit)
  -a         analyze mode: run ANALYZE_FILE (analyze.cfg) through the
             batch VM instead of an evolution run (ANALYZE_MODE=1)
  -v         verbose

TPU-build extras (no reference equivalent):

  --telemetry        enable the runtime telemetry subsystem
                     (avida_tpu/observability/): per-update phase timers,
                     device counters and a telemetry.jsonl run log in the
                     data dir.  Shorthand for -set TPU_TELEMETRY 1.
                     Telemetry runs per-update with fenced phases --
                     expect lower throughput than the fused default.
  --profile-dir DIR  with --telemetry: capture a jax.profiler (XProf)
                     trace of the first few updates into DIR
                     (TPU_PROFILE_UPDATES controls how many).
  --resume [DIR]     restore the newest valid native checkpoint
                     generation (utils/checkpoint.py) before running;
                     DIR defaults to TPU_CKPT_DIR.  With TPU_CKPT_DIR
                     set, SIGTERM/SIGINT preemption saves a final
                     checkpoint and exits 0, so a preempt/restart cycle
                     of `--resume` runs is bit-exact with an
                     uninterrupted run.
  --trace            enable the device-side flight recorder
                     (observability/tracer.py): structured events
                     recorded inside the jitted update, drained to
                     {"record":"trace"} runlog lines at chunk
                     boundaries, plus the metrics.prom heartbeat.
                     Shorthand for -set TPU_TRACE 1.
  --status DIR       print the last heartbeat of the run writing to
                     data dir DIR (reads DIR/metrics.prom; no JAX
                     import, works while the run is live) and exit.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main(argv=None):
    p = argparse.ArgumentParser(prog="avida_tpu", add_help=True)
    p.add_argument("-c", "--config-dir", default=None)
    p.add_argument("-s", "--seed", type=int, default=None)
    p.add_argument("-set", dest="overrides", nargs=2, action="append",
                   default=[], metavar=("NAME", "VALUE"))
    p.add_argument("-d", "--data-dir", default=None)
    p.add_argument("-u", "--updates", type=int, default=None)
    p.add_argument("-a", "--analyze", action="store_true")
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("--telemetry", action="store_true")
    p.add_argument("--profile-dir", default=None)
    p.add_argument("--resume", nargs="?", const="", default=None,
                   metavar="DIR")
    p.add_argument("--trace", action="store_true")
    p.add_argument("--status", default=None, metavar="DIR")
    args = p.parse_args(argv)

    if args.status is not None:
        # outside-the-process observability: read the metrics.prom
        # heartbeat only -- no World, no JAX device init
        from avida_tpu.observability.exporter import status_main
        return status_main(args.status)

    overrides = list(map(tuple, args.overrides))
    if args.seed is not None:
        overrides.append(("RANDOM_SEED", args.seed))
    if args.telemetry:
        overrides.append(("TPU_TELEMETRY", 1))
    if args.trace:
        overrides.append(("TPU_TRACE", 1))
    if args.profile_dir:
        overrides.append(("TPU_TELEMETRY", 1))
        overrides.append(("TPU_PROFILE_DIR", args.profile_dir))

    from avida_tpu.world import World
    world = World(config_dir=args.config_dir, overrides=overrides,
                  data_dir=args.data_dir)

    if args.analyze:
        from avida_tpu.analyze.analyzer import Analyzer
        az = Analyzer(world.params, world.instset,
                      data_dir=world.data_dir, verbose=args.verbose)
        path = (os.path.join(args.config_dir, world.cfg.ANALYZE_FILE)
                if args.config_dir else world.cfg.ANALYZE_FILE)
        az.run_file(path)
        return 0

    if args.resume is not None:
        # restart-loop friendly: a preemptible job launches with ONE fixed
        # command line including --resume; on the very first boot the
        # checkpoint directory is empty, which means "start fresh", not
        # "crash" (generations that exist but fail verification still
        # raise -- that needs a human)
        from avida_tpu.utils.checkpoint import list_generations
        base = args.resume or world._ckpt_base()
        if base and not list_generations(base):
            print(f"[avida-tpu] no checkpoint under {base}; starting fresh",
                  file=sys.stderr)
        else:
            at = world.resume(args.resume or None)
            if args.verbose:
                print(f"resumed at update {at}", file=sys.stderr)

    t0 = time.time()
    world.run(max_updates=args.updates)
    dt = time.time() - t0
    if world.preempted:
        # preemption is a CLEAN exit: the final checkpoint is on disk and
        # a follow-up `--resume` run continues bit-exactly
        print(f"[avida-tpu] preempted at update {world.update}; "
              f"checkpoint saved", file=sys.stderr)
        return 0
    if args.verbose:
        print(f"{world.update} updates, {world.num_organisms} organisms, "
              f"{dt:.1f}s", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
