"""Analyze package: batched Test CPU, the analyze VM and the
checkpoint-native analytics pipeline.

Lazy re-exports (PEP 562, the avida_tpu/__init__ pattern): importing
`avida_tpu.analyze.pipeline` for its host-only pieces
(checkpoint_detail, the .dat writers -- scripts/ckpt_tool.py's --detail
triage column) must not pull jax in through an eager testcpu import;
`from avida_tpu.analyze import evaluate_genomes` still resolves on
first touch."""


def __getattr__(name):
    if name in ("evaluate_genomes", "TestResult"):
        from avida_tpu.analyze import testcpu
        return getattr(testcpu, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
