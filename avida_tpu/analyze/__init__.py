from avida_tpu.analyze.testcpu import evaluate_genomes, TestResult  # noqa: F401
