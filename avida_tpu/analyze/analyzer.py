"""Analyze mode: the offline genotype post-processing VM.

TPU-native equivalent of cAnalyze (avida-core/source/analyze/cAnalyze.cc —
101 commands registered at cc:11205-11330, batch model cGenotypeBatch,
threaded job queue cAnalyzeJobQueue).  The reference evaluates genotypes one
at a time on worker threads; here every batch operation that needs fitness
data feeds the WHOLE batch through the lockstep Test CPU at once
(analyze/testcpu.py), so "parallel analyze jobs" become one device program.

Supported commands (the working core of the reference set; the registry
pattern makes additions one-liners):
  LOAD <file.spop>          load genotypes into the current batch
  LOAD_SEQUENCE <seq>       load one genome from its letter sequence
  SET_BATCH <i> / DUPLICATE <from> [<to>] / PURGE_BATCH [<i>]
  RECALCULATE               run the batch through the Test CPU
  FILTER <field> <op> <value>   keep genotypes matching (e.g. fitness > 0)
  FIND_GENOTYPE [num_cpus|total_cpus|fitness]   keep the best genotype
  DETAIL <file> [fields...] write a genotype table (.dat format)
  TRACE [dir]               per-cycle hardware trace of each genotype
  LANDSCAPE [file]          one-step mutational landscape of the batch
  ANALYZE_KNOCKOUTS [file]  per-site knockout viability/fitness
  CENSUS [file]             pipeline-backed phenotype-census table of
                            the batch (task profile / fitness /
                            gestation per genotype; analyze/pipeline.py)
  LINEAGE [file [field]]    pipeline-backed lineage replay: reduce to
                            the ancestral lineage (FIND_LINEAGE),
                            RECALCULATE each step, write the per-depth
                            fitness/task-acquisition table
  VERBOSE / SYSTEM <cmd>    utility commands
"""

from __future__ import annotations

import os
import shlex

import numpy as np

from avida_tpu.analyze.testcpu import evaluate_genomes
from avida_tpu.utils.output import DatFile
from avida_tpu.utils import spop as spop_mod


class AnalyzeGenotype:
    """Batch entry (ref cAnalyzeGenotype)."""

    def __init__(self, sequence, gid=0, name="", num_cpus=1, total_cpus=1):
        self.sequence = np.asarray(sequence, np.int8)
        self.id = gid
        self.name = name or f"org-{gid}"
        self.num_cpus = num_cpus          # live organism count at save
        self.total_cpus = total_cpus
        # filled by RECALCULATE
        self.viable = None
        self.fitness = 0.0
        self.merit = 0.0
        self.gestation_time = 0
        self.copied_size = 0
        self.executed_size = 0
        self.task_counts = None

    @property
    def length(self):
        return len(self.sequence)


class Analyzer:
    """Interpret an analyze.cfg program (ref cAnalyze::RunFile)."""

    def __init__(self, params, instset, data_dir="data", verbose=False):
        self.params = params
        self.instset = instset
        self.data_dir = data_dir
        self.batches: dict[int, list[AnalyzeGenotype]] = {}
        self.current = 0
        self.verbose = verbose
        self._next_id = 1

    @property
    def batch(self) -> list[AnalyzeGenotype]:
        return self.batches.setdefault(self.current, [])

    # ---- program driver -------------------------------------------------

    def run_file(self, path: str):
        with open(path) as f:
            self.run_lines(f.read().splitlines())

    def run_lines(self, lines):
        for raw in lines:
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            self.run_command(line)

    def run_command(self, line: str):
        tokens = shlex.split(line)
        cmd, args = tokens[0].upper(), tokens[1:]
        handler = getattr(self, f"_cmd_{cmd}", None)
        if handler is None:
            raise ValueError(f"unknown analyze command {cmd!r}")
        if self.verbose:
            print(f"analyze: {line}")
        return handler(args)

    # ---- batch management ----------------------------------------------

    def _cmd_SET_BATCH(self, args):
        self.current = int(args[0])

    def _cmd_DUPLICATE(self, args):
        src = int(args[0])
        dst = int(args[1]) if len(args) > 1 else self.current
        self.batches.setdefault(dst, []).extend(
            AnalyzeGenotype(g.sequence.copy(), self._take_id(), g.name,
                            g.num_cpus, g.total_cpus)
            for g in self.batches.get(src, []))

    def _cmd_PURGE_BATCH(self, args):
        idx = int(args[0]) if args else self.current
        self.batches[idx] = []

    def _take_id(self):
        i = self._next_id
        self._next_id += 1
        return i

    # ---- loading --------------------------------------------------------

    def _cmd_LOAD(self, args):
        orgs = spop_mod.load_population(args[0], self.params, None)
        seen = {}
        for o in orgs:
            key = o["genome"].tobytes()
            if key in seen:
                seen[key].num_cpus += 1
                seen[key].total_cpus += 1
            else:
                g = AnalyzeGenotype(o["genome"], self._take_id())
                g.src_id = o.get("id", -1)
                g.parent_src = o.get("parent", -1)
                g.depth = o.get("depth", -1)
                seen[key] = g
                self.batch.append(g)

    def _cmd_LOAD_SEQUENCE(self, args):
        seq = spop_mod._string_to_seq(args[0])
        self.batch.append(AnalyzeGenotype(seq, self._take_id()))

    # ---- evaluation ------------------------------------------------------

    def _padded(self, genotypes):
        L = self.params.max_memory
        G = len(genotypes)
        buf = np.zeros((G, L), np.int8)
        lens = np.zeros(G, np.int32)
        for i, g in enumerate(genotypes):
            n = min(g.length, L)
            buf[i, :n] = g.sequence[:n]
            lens[i] = n
        return buf, lens

    def _cmd_RECALCULATE(self, args):
        if not self.batch:
            return
        buf, lens = self._padded(self.batch)
        r = evaluate_genomes(self.params, buf, lens)
        for i, g in enumerate(self.batch):
            g.viable = bool(r.viable[i])
            g.fitness = float(r.fitness[i])
            g.merit = float(r.merit[i])
            g.gestation_time = int(r.gestation_time[i])
            g.copied_size = int(r.copied_size[i])
            g.executed_size = int(r.executed_size[i])
            g.task_counts = np.asarray(r.task_counts[i])

    # ---- filtering -------------------------------------------------------

    _OPS = {"==": lambda a, b: a == b, "!=": lambda a, b: a != b,
            ">": lambda a, b: a > b, "<": lambda a, b: a < b,
            ">=": lambda a, b: a >= b, "<=": lambda a, b: a <= b}

    def _cmd_FILTER(self, args):
        field, op, value = args[0], args[1], float(args[2])
        f = self._OPS[op]
        self.batches[self.current] = [
            g for g in self.batch if f(float(getattr(g, field)), value)]

    def _cmd_FIND_GENOTYPE(self, args):
        if not self.batch:
            return
        crit = args[0] if args else "num_cpus"
        best = max(self.batch, key=lambda g: getattr(g, crit))
        self.batches[self.current] = [best]

    # ---- output ----------------------------------------------------------

    _DETAIL_DEFAULT = ["id", "fitness", "merit", "gestation_time", "length"]

    def _cmd_DETAIL(self, args):
        fname = args[0] if args else "detail.dat"
        fields = args[1:] if len(args) > 1 else self._DETAIL_DEFAULT
        f = DatFile(os.path.join(self.data_dir, fname),
                    "Avida analyze details", fields)
        for g in self.batch:
            row = []
            for fd in fields:
                if fd == "sequence":
                    row.append(spop_mod._seq_to_string(g.sequence))
                elif fd == "viable":
                    row.append(int(bool(g.viable)))
                else:
                    row.append(getattr(g, fd))
            f.write_row(row)
        f.close()

    def _cmd_TRACE(self, args):
        from avida_tpu.analyze.trace import trace_genome
        outdir = os.path.join(self.data_dir, args[0] if args else "trace")
        os.makedirs(outdir, exist_ok=True)
        for g in self.batch:
            path = os.path.join(outdir, f"org-{g.id}.trace")
            trace_genome(self.params, self.instset, g.sequence, path)

    # ---- genetics --------------------------------------------------------

    def _cmd_LANDSCAPE(self, args):
        """One-step mutational landscape of each batch genotype
        (ref cLandscape::Process, main/cLandscape.cc)."""
        fname = args[0] if args else "landscape.dat"
        f = DatFile(os.path.join(self.data_dir, fname), "Mutational landscape",
                    ["genotype id", "base fitness", "num mutants",
                     "frac lethal", "frac detrimental", "frac neutral",
                     "frac beneficial", "average fitness",
                     "max mutant fitness"])
        ni = self.params.num_insts
        for g in self.batch:
            base = self._recalc_one(g)
            L = g.length
            muts = []
            for site in range(L):
                for op in range(ni):
                    if op == g.sequence[site]:
                        continue
                    m = g.sequence.copy()
                    m[site] = op
                    muts.append(m)
            buf, lens = self._padded(
                [AnalyzeGenotype(m) for m in muts])
            r = evaluate_genomes(self.params, buf, lens)
            fit = np.where(r.viable, r.fitness, 0.0)
            base_f = max(base, 1e-30)
            rel = fit / base_f
            f.write_row([
                g.id, base, len(muts),
                float((fit <= 0).mean()),
                float(((fit > 0) & (rel < 0.95)).mean()),
                float(((rel >= 0.95) & (rel <= 1.05)).mean()),
                float((rel > 1.05).mean()),
                float(fit.mean()), float(fit.max())])
        f.close()

    def _cmd_ANALYZE_KNOCKOUTS(self, args):
        """Replace each site with the null instruction and test viability
        (ref cAnalyze KNOCKOUT machinery; classification shared with the
        checkpoint-native pipeline via pipeline.knockout_profile)."""
        from avida_tpu.analyze.pipeline import knockout_profile
        fname = args[0] if args else "knockouts.dat"
        f = DatFile(os.path.join(self.data_dir, fname), "Knockout analysis",
                    ["genotype id", "length", "num lethal", "num detrimental",
                     "num neutral", "num beneficial"])
        for g in self.batch:
            base = self._recalc_one(g)
            prof = knockout_profile(self.params, g.sequence, base)
            # length column = SITES SWEPT (knockout_profile truncates
            # genomes wider than the memory buffer), so the four class
            # counts always partition it
            f.write_row([
                g.id, prof["length"], prof["lethal"],
                prof["detrimental"], prof["neutral"],
                prof["beneficial"]])
        f.close()

    def _cmd_CENSUS(self, args):
        """CENSUS [file]: pipeline-backed phenotype-census table of the
        current batch (analyze/pipeline.write_census_dat -- the same
        schema `--analyze CKPT_DIR` writes, with num_cpus standing in
        for live units and src depth when the batch came from a .spop)."""
        from avida_tpu.analyze.pipeline import tasks_mask, write_census_dat
        fname = args[0] if args else "census.dat"
        self._recalc_missing()
        rows = []
        for g in self.batch:
            tasks = (np.asarray(g.task_counts)
                     if g.task_counts is not None
                     else np.zeros(self.params.num_reactions, np.int64))
            rows.append({
                "gid": g.id, "num_units": g.num_cpus,
                "depth": getattr(g, "depth", -1), "length": g.length,
                "viable": bool(g.viable), "fitness": g.fitness,
                "merit": g.merit, "gestation": g.gestation_time,
                "tasks_mask": tasks_mask(tasks),
                "task_counts": [int(x) for x in tasks],
            })
        write_census_dat(os.path.join(self.data_dir, fname), rows)

    def _cmd_LINEAGE(self, args):
        """LINEAGE [file [field]]: pipeline-backed lineage replay over
        the loaded batch -- FIND_LINEAGE's parent-link walk, then a
        RECALCULATE of every step and the per-depth fitness /
        task-acquisition table (analyze/pipeline.write_lineage_dat)."""
        from avida_tpu.analyze.pipeline import tasks_mask, write_lineage_dat
        fname = args[0] if args else "lineage.dat"
        self._cmd_FIND_LINEAGE(args[1:2])
        self._recalc_missing()
        rows, prev_mask = [], 0
        for depth, g in enumerate(self.batch):       # root first
            tasks = (np.asarray(g.task_counts)
                     if g.task_counts is not None
                     else np.zeros(self.params.num_reactions, np.int64))
            mask = tasks_mask(tasks)
            # id columns stay in ONE id space: the .spop source ids when
            # the batch was LOADed (parent_src lives there), else the
            # batch ids (parent then -1) -- so Parent ID always joins
            # against a Genotype ID row
            src = getattr(g, "src_id", -1)
            rows.append({
                "depth": depth, "gid": src if src >= 0 else g.id,
                "parent_gid": (getattr(g, "parent_src", -1)
                               if src >= 0 else -1),
                "update_born": -1, "length": g.length,
                "fitness": g.fitness, "gestation": g.gestation_time,
                "tasks_mask": mask, "tasks_gained": mask & ~prev_mask,
            })
            prev_mask = mask
        write_lineage_dat(os.path.join(self.data_dir, fname), rows)

    def _cmd_ANALYZE_MODULARITY(self, args):
        """Functional modularity via site knockouts
        (cModularityAnalysis::CalcFunctionalModularity,
        analyze/cModularityAnalysis.cc:54-240): null each site, batch-test
        through the Test CPU, and mark site x task entries where the
        knockout completely removes a task the base genotype performs.
        Columns follow the reference's ADD_GDATA list (cc:42-50, scalar
        subset)."""
        fname = args[0] if args else "modularity.dat"
        f = DatFile(
            os.path.join(self.data_dir, fname), "Modularity analysis",
            ["genotype id", "Number of Tasks Performed",
             "Number of Instructions Involved in Tasks",
             "Proportion of Sites in Tasks",
             "Average Number of Tasks Per Site",
             "Average Number of Sites Per Task",
             "Average Task Overlap"])
        nop = 0
        for g in self.batch:
            buf, lens = self._padded([g])
            rbase = evaluate_genomes(self.params, buf, lens)
            base = float(rbase.fitness[0]) if bool(rbase.viable[0]) else 0.0
            base_tasks = rbase.task_counts[0] > 0
            if base <= 0 or not base_tasks.any():
                f.write_row([g.id, 0, 0, 0.0, 0.0, 0.0, 0.0])
                continue
            L = g.length
            kos = []
            for site in range(L):
                m = g.sequence.copy()
                m[site] = nop
                kos.append(AnalyzeGenotype(m))
            buf, lens = self._padded(kos)
            r = evaluate_genomes(self.params, buf, lens)
            fit = np.where(r.viable, r.fitness, 0.0)
            # mod_matrix[task, site] = 1 iff the knockout (still viable)
            # FULLY removes a task the base does (binary criterion, cc:119)
            tdone = r.task_counts > 0                       # [L, R]
            mod = (base_tasks[None, :] & ~tdone
                   & (fit > 0)[:, None]).T                  # [R, L]
            sites_per_task = mod.sum(axis=1)
            tasks_per_site = mod.sum(axis=0)
            total_task = int((sites_per_task > 0).sum())
            total_inst = int((tasks_per_site > 0).sum())
            total_all = int(mod.sum())
            # average task overlap (cc:157-176)
            sum_overlap = 0.0
            if total_task > 1:
                ov = (mod.astype(np.int64) @ mod.T.astype(np.int64))
                for i in range(mod.shape[0]):
                    if ov[i, i]:
                        other = int(ov[i].sum() - ov[i, i])
                        sum_overlap += other / (ov[i, i] * (total_task - 1))
            f.write_row([
                g.id, total_task, total_inst,
                total_inst / max(L, 1),
                (total_all / total_inst) if total_inst else 0.0,
                (total_all / total_task) if total_task else 0.0,
                (sum_overlap / total_task) if total_task else 0.0])
        f.close()

    def _recalc_missing(self):
        """RECALCULATE only when some batch member has never been
        scored: `RECALCULATE; CENSUS; LINEAGE` scripts must not pay the
        batched gestation sweep three times over the same genotypes."""
        if any(g.task_counts is None for g in self.batch):
            self._cmd_RECALCULATE([])

    def _recalc_one(self, g) -> float:
        buf, lens = self._padded([g])
        r = evaluate_genomes(self.params, buf, lens)
        g.fitness = float(r.fitness[0])
        g.viable = bool(r.viable[0])
        return g.fitness if g.viable else 0.0

    # ---- misc ------------------------------------------------------------

    def _cmd_ALIGN(self, args):
        """Progressive alignment of the batch against its first genotype
        (ref cAnalyze::CommandAlign, cAnalyze.cc: gaps written as "_").
        Stores g.alignment (letter sequence with gaps); DETAIL can emit
        the `alignment` field afterwards."""
        if not self.batch:
            return
        ref_seq = self.batch[0].sequence

        def lcs_align(a, b):
            # O(len(a)*len(b)) LCS table; emits aligned letter strings
            la, lb = len(a), len(b)
            D = np.zeros((la + 1, lb + 1), np.int32)
            for i in range(la - 1, -1, -1):
                for j in range(lb - 1, -1, -1):
                    best = max(D[i + 1][j + 1] + (1 if a[i] == b[j] else 0),
                               D[i + 1][j], D[i][j + 1])
                    D[i][j] = best
            # traceback
            out_a, out_b = [], []
            i = j = 0
            while i < la and j < lb:
                if a[i] == b[j] and D[i][j] == D[i + 1][j + 1] + 1:
                    out_a.append(a[i]); out_b.append(b[j]); i += 1; j += 1
                elif D[i][j] == D[i + 1][j]:
                    out_a.append(a[i]); out_b.append(-1); i += 1
                else:
                    out_a.append(-1); out_b.append(b[j]); j += 1
            while i < la:
                out_a.append(a[i]); out_b.append(-1); i += 1
            while j < lb:
                out_a.append(-1); out_b.append(b[j]); j += 1
            return out_a, out_b

        def to_str(seq):
            return "".join("_" if x < 0 else spop_mod._seq_to_string(
                np.asarray([x], np.int8)) for x in seq)

        for g in self.batch:
            ra, rb = lcs_align(list(ref_seq), list(g.sequence))
            g.alignment = to_str(rb)
        self.batch[0].alignment = to_str(list(ref_seq))

    def _cmd_MAP_MUTATIONS(self, args):
        """Per-site x per-instruction mutant fitness map for each batch
        genotype (ref cAnalyze::CommandMapMutations): one file per
        genotype, row = site, column = replacement instruction, value =
        fitness relative to the base genotype."""
        outdir = os.path.join(self.data_dir, args[0] if args else "mutmap")
        os.makedirs(outdir, exist_ok=True)
        ni = self.params.num_insts
        for g in self.batch:
            base = max(self._recalc_one(g), 1e-30)
            L = g.length
            muts = []
            for site in range(L):
                for op in range(ni):
                    m = g.sequence.copy()
                    m[site] = op
                    muts.append(AnalyzeGenotype(m))
            buf, lens = self._padded(muts)
            r = evaluate_genomes(self.params, buf, lens)
            fit = np.where(r.viable, r.fitness, 0.0).reshape(L, ni) / base
            with open(os.path.join(outdir, f"mut-map-{g.id}.dat"), "w") as f:
                f.write("# Mutation map: rows = sites, cols = instructions; "
                        "entries = mutant fitness / base fitness\n")
                for site in range(L):
                    f.write(" ".join(f"{fit[site, o]:.4f}"
                                     for o in range(ni)) + "\n")

    def _cmd_FIND_LINEAGE(self, args):
        """Reduce the batch to the ancestral lineage of the chosen
        genotype (ref cAnalyze::CommandFindLineage): walk parent links
        (from the loaded .spop systematics columns) from the best
        genotype back to the root."""
        if not self.batch:
            return
        field = args[0] if args else "num_cpus"
        best = max(self.batch,
                   key=lambda g: getattr(g, field, 0) or 0)
        by_src = {getattr(g, "src_id", -1): g for g in self.batch}
        lineage = []
        cur = best
        seen = set()
        while cur is not None and id(cur) not in seen:
            seen.add(id(cur))
            lineage.append(cur)
            cur = by_src.get(getattr(cur, "parent_src", -1))
        self.batch[:] = lineage[::-1]        # root first

    def _cmd_RECOMBINE(self, args):
        """Cross consecutive batch pairs with one-region swap (ref
        cAnalyze::CommandRecombine; region-swap semantics shared with
        cBirthChamber::RegionSwap): appends the recombinants to the
        batch."""
        reps = int(args[0]) if args else 1
        # advance the stream per invocation (the reference draws from the
        # advancing global RNG; a fixed seed would repeat crossover points)
        self._recomb_seed = getattr(self, "_recomb_seed", 0) + 1
        rng = np.random.default_rng(self._recomb_seed)
        out = []
        for _ in range(reps):
            for i in range(0, len(self.batch) - 1, 2):
                a = self.batch[i].sequence
                b = self.batch[i + 1].sequence
                la, lb = len(a), len(b)
                f0, f1 = sorted(rng.random(2))
                s0, e0 = int(f0 * la), int(f1 * la)
                s1, e1 = int(f0 * lb), int(f1 * lb)
                child = np.concatenate([a[:s0], b[s1:e1], a[e0:]])
                if len(child) >= self.params.min_genome_len and \
                        len(child) <= self.params.max_memory:
                    out.append(AnalyzeGenotype(child, self._take_id()))
        self.batch.extend(out)

    def _cmd_VERBOSE(self, args):
        self.verbose = not args or args[0] not in ("0", "off")

    def _cmd_SYSTEM(self, args):
        os.system(" ".join(args))
