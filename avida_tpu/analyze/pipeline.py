"""Checkpoint-native run analytics: census, knockouts, lineage replay.

The analyze VM (analyze/analyzer.py) answers questions about `.spop`
saves; THIS module answers them about native checkpoints -- the format
every production run, supervised tenant and fleet job actually writes
(utils/checkpoint.py).  It composes ingredients that already exist into
an offline pipeline (ROADMAP item 5):

  * **loader** -- the newest CRC-valid generation, falling back past
    corrupt/torn generations exactly like World.resume (same
    restore_candidates order, same verification), reconstructing the
    population arrays and the systematics tables
    (GenotypeArbiter.from_snapshot; a checkpoint written with
    TPU_SYSTEMATICS=0 gets a content-keyed table rebuilt from the live
    population, depth restarting at 0 -- the same documented
    approximation the resume path uses);
  * **phenotype census** -- task profile / fitness / gestation for every
    live genotype through the batched Test CPU, content-keyed via
    systematics/test_metrics.GenomeTestMetrics so repeat genotypes cost
    nothing and incremental refreshes only evaluate NEW genotypes;
  * **knockout attribution** -- per-site NOP-substitution sweeps over the
    dominant + threshold genotypes (the `_cmd_ANALYZE_KNOCKOUTS`
    classification, shared via `knockout_profile`);
  * **lineage replay** -- walk the arbiter parent chain from the dominant
    genotype to the ancestor, RECALCULATE each step, and emit the
    fitness/task-acquisition trajectory per depth.

Results flow out through the existing observability spine:

  * `{"record": "analytics"}` lines appended crash-safe (rotation-capped)
    to `DATA_DIR/analysis/analytics.jsonl` via runlog.append_record;
  * `.dat`-style tables (census.dat / knockout.dat / lineage.dat) under
    `DATA_DIR/analysis/`;
  * `DATA_DIR/analytics.prom` rendered by exporter.render_families, the
    Prometheus face `--status` and the fleet status view read.

Entry points: `python -m avida_tpu --analyze CKPT_DIR` /
`scripts/analyze_tool.py` (offline), and `LiveAnalytics` (TPU_ANALYTICS=1:
World.run refreshes an incremental census at checkpoint boundaries and
run exit, so `--status` shows dominant lineage depth / census age /
tasks-held on a RUNNING world).  Everything is host-orchestrated with
separate jits -- the production `update_step` jaxpr digest is untouched
(tests/test_analyze_pipeline.py gates this).

Import discipline: module import stays numpy-only (scripts/ckpt_tool.py
pulls `checkpoint_detail` for spool triage without paying a jax import);
anything that evaluates genotypes defers its jax-importing dependencies
into the call.
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import dataclass

import numpy as np

from avida_tpu.systematics.genotypes import GenotypeArbiter
from avida_tpu.utils import checkpoint as ckpt_mod
from avida_tpu.utils.output import DatFile

ANALYSIS_DIR = "analysis"
ANALYTICS_LOG = "analytics.jsonl"
ANALYTICS_METRICS_FILE = "analytics.prom"

# rotation cap for the analytics journal (runlog.append_record semantics)
ANALYTICS_LOG_MAX_BYTES = 16 << 20


def tasks_mask(task_counts) -> int:
    """Bitmask with bit i set when task i was performed (environment
    task order -- bit 8 is EQU in the stock logic-9 ladder)."""
    return int(sum(1 << i for i, c in enumerate(np.asarray(task_counts))
                   if c > 0))


# ---------------------------------------------------------------------------
# table reconstruction (checkpoint or live world)
# ---------------------------------------------------------------------------

@dataclass
class RunTables:
    """Population + systematics tables reconstructed from one checkpoint
    generation (or snapshotted from a live world for the in-run census)."""
    update: int
    alive: np.ndarray             # bool[N]
    genome: np.ndarray            # int8[N, L]
    genome_len: np.ndarray        # int32[N]
    task_counts: np.ndarray | None  # int32[N, R] last-gestation counts
    arbiter: GenotypeArbiter
    path: str | None = None       # generation dir (None = live tables)
    rebuilt: bool = False         # arbiter rebuilt (no systematics sidecar)


def _rebuild_arbiter(alive, genome, genome_len, update) -> GenotypeArbiter:
    """Content-keyed genotype table from the live population (the same
    ancestry-free approximation checkpoint restore uses when the
    systematics sidecar is absent: depth/lineage restart at 0).

    Cost note: O(live cells) host work (tobytes + dict per cell).  In
    live mode with TPU_SYSTEMATICS=0 (the packed-chunk engine) this
    runs per checkpoint boundary; at production world sizes it is a
    few ms next to the save's array-write+fsync.  If it ever shows in
    a profile, dedupe rows first (np.unique over packed genome bytes)
    or cache the table and reclassify only changed cells."""
    arb = GenotypeArbiter(int(alive.shape[0]))
    for c in np.nonzero(alive)[0]:
        arb.classify_seed(int(c), genome[c, : int(genome_len[c])],
                          update=int(update))
    return arb


def tables_from_generation(path: str, manifest: dict, arrays: dict,
                           files: dict) -> RunTables:
    alive = np.asarray(arrays["state.alive"]).astype(bool)
    genome = np.asarray(arrays["state.genome"])
    genome_len = np.asarray(arrays["state.genome_len"])
    tasks = arrays.get("state.last_task_count")
    update = int(manifest["update"])
    if "systematics.json" in files:
        arb = GenotypeArbiter.from_snapshot(
            json.loads(files["systematics.json"].decode()))
        rebuilt = False
    else:
        arb = _rebuild_arbiter(alive, genome, genome_len, update)
        rebuilt = True
    return RunTables(update=update, alive=alive, genome=genome,
                     genome_len=genome_len,
                     task_counts=(None if tasks is None
                                  else np.asarray(tasks)),
                     arbiter=arb, path=path, rebuilt=rebuilt)


def load_run_tables(ckpt_dir: str, on_skip=None) -> RunTables:
    """RunTables from the newest VALID generation under `ckpt_dir`.

    Corrupt or torn generations are skipped newest-to-oldest with a
    warning (`on_skip(path, error)` when given), falling back to the
    previous retained one -- byte-for-byte the ordering and verification
    World.resume uses (restore_candidates + CRC manifest check), so the
    pipeline analyzes exactly the generation a resume would restore."""
    candidates = ckpt_mod.restore_candidates(ckpt_dir)
    if not candidates:
        raise ckpt_mod.CheckpointError(
            f"no checkpoints under {ckpt_dir!r}")
    last_err = None
    for path in candidates:
        try:
            manifest, arrays, files = ckpt_mod.read_generation(path)
        except ckpt_mod.CheckpointError as e:
            last_err = e
            if on_skip is not None:
                on_skip(path, e)
            else:
                print(f"[avida-tpu] analytics: skipping corrupt "
                      f"generation {path} ({e})", file=sys.stderr)
            continue
        return tables_from_generation(path, manifest, arrays, files)
    raise ckpt_mod.CheckpointError(
        f"no valid checkpoint under {ckpt_dir!r} (last error: {last_err})")


def tables_from_world(world) -> RunTables:
    """Snapshot the live world's tables for an in-run census.  Pure
    read: no PRNG key is consumed and no state field is touched, so the
    evolved trajectory is bit-identical with analytics on or off."""
    st = world.state
    alive = np.asarray(st.alive).astype(bool)
    genome = np.asarray(st.genome)
    genome_len = np.asarray(st.genome_len)
    arb = world.systematics
    rebuilt = False
    if arb is None:
        arb = _rebuild_arbiter(alive, genome, genome_len, world.update)
        rebuilt = True
    return RunTables(update=int(world.update), alive=alive, genome=genome,
                     genome_len=genome_len,
                     task_counts=np.asarray(st.last_task_count),
                     arbiter=arb, path=None, rebuilt=rebuilt)


# ---------------------------------------------------------------------------
# cheap triage (no Test CPU, no jax): ckpt_tool --list --detail
# ---------------------------------------------------------------------------

def checkpoint_detail(path: str) -> dict:
    """Spool-triage summary of ONE generation: dominant genotype id /
    units / depth, live organism count and the tasks-held bitmask (from
    the saved per-cell last-gestation task counts) -- manifest + two
    arrays + the systematics sidecar, no sandbox evaluation, so
    `ckpt_tool --list --detail` stays an ops-shell command."""
    with open(os.path.join(path, ckpt_mod.MANIFEST)) as f:
        manifest = json.load(f)
    out = {"update": manifest.get("update"), "live": None,
           "tasks_mask": None, "genotypes": None, "dominant_gid": None,
           "dominant_units": None, "dominant_depth": None}

    def _arr(name):
        spec = manifest.get("arrays", {}).get(name)
        if not spec:
            return None
        try:
            return np.load(os.path.join(path, spec["file"]))
        except Exception:
            return None

    alive = _arr("state.alive")
    if alive is not None:
        alive = alive.astype(bool)
        out["live"] = int(alive.sum())
        tasks = _arr("state.last_task_count")
        if tasks is not None:
            held = (tasks[alive] > 0).any(axis=0) if alive.any() \
                else np.zeros(tasks.shape[1], bool)
            out["tasks_mask"] = tasks_mask(held)
    if "systematics.json" in manifest.get("files", {}):
        try:
            with open(os.path.join(path, "systematics.json")) as f:
                snap = json.load(f)
        except (OSError, json.JSONDecodeError):
            return out
        live_g = [g for g in snap.get("genotypes", ())
                  if g.get("num_units", 0) > 0]
        out["genotypes"] = len(live_g)
        if live_g:
            # same ordering as GenotypeArbiter.dominant (abundance,
            # then lowest gid)
            best = max(live_g, key=lambda g: (g["num_units"], -g["gid"]))
            out["dominant_gid"] = int(best["gid"])
            out["dominant_units"] = int(best["num_units"])
            out["dominant_depth"] = int(best["depth"])
    return out


# ---------------------------------------------------------------------------
# knockout attribution (shared with Analyzer._cmd_ANALYZE_KNOCKOUTS)
# ---------------------------------------------------------------------------

def knockout_profile(params, sequence, base_fitness, seed: int = 0) -> dict:
    """Per-site knockout sweep of one genotype: replace each site with
    the null instruction (op 0, nop-A) and test viability/fitness in one
    batched Test-CPU run.  Classification thresholds are the analyze
    VM's (`ANALYZE_KNOCKOUTS`): lethal fit<=0, detrimental rel<0.95,
    neutral 0.95..1.05, beneficial rel>1.05."""
    from avida_tpu.analyze.testcpu import evaluate_genomes

    seq = np.asarray(sequence, np.int8)
    # genomes longer than the buffer truncate, matching the analyze
    # VM's _padded discipline (a .spop can carry genomes wider than
    # this build's TPU_MAX_MEMORY; sweeping the loadable prefix beats
    # crashing the whole analyze script)
    seq = seq[: params.max_memory]
    L = int(len(seq))
    buf = np.zeros((L, params.max_memory), np.int8)
    for site in range(L):
        m = seq.copy()
        m[site] = 0
        buf[site, :L] = m
    r = evaluate_genomes(params, buf, np.full(L, L, np.int32), seed=seed)
    fit = np.where(r.viable, r.fitness, 0.0)
    rel = fit / max(base_fitness, 1e-30)
    return {
        "length": L,
        "lethal": int((fit <= 0).sum()),
        "detrimental": int(((fit > 0) & (rel < 0.95)).sum()),
        "neutral": int(((rel >= 0.95) & (rel <= 1.05)).sum()),
        "beneficial": int((rel > 1.05).sum()),
        "rel_fitness": rel,
    }


# ---------------------------------------------------------------------------
# .dat table writers (shared by the pipeline and the analyze VM)
# ---------------------------------------------------------------------------

def _task_names(task_names, n):
    names = list(task_names or [])
    return names if len(names) == n else [f"task{i}" for i in range(n)]


def write_census_dat(path: str, rows: list, task_names=None):
    n_tasks = len(rows[0]["task_counts"]) if rows else 0
    names = _task_names(task_names, n_tasks)
    f = DatFile(path, "Avida phenotype census",
                ["Genotype ID", "Num units", "Depth", "Length", "Viable",
                 "Fitness", "Merit", "Gestation time", "Tasks mask"]
                + [n.capitalize() for n in names])
    for r in rows:
        f.write_row([r["gid"], r["num_units"], r["depth"], r["length"],
                     int(r["viable"]), r["fitness"], r["merit"],
                     r["gestation"], r["tasks_mask"]]
                    + [int(x) for x in r["task_counts"]])
    f.close()


def write_knockout_dat(path: str, rows: list):
    f = DatFile(path, "Knockout attribution",
                ["Genotype ID", "Num units", "Length", "Num lethal",
                 "Num detrimental", "Num neutral", "Num beneficial",
                 "Base fitness"])
    for r in rows:
        f.write_row([r["gid"], r["num_units"], r["length"], r["lethal"],
                     r["detrimental"], r["neutral"], r["beneficial"],
                     r["base_fitness"]])
    f.close()


def write_lineage_dat(path: str, rows: list):
    f = DatFile(path, "Dominant lineage replay (root first)",
                ["Depth", "Genotype ID", "Parent ID", "Update born",
                 "Length", "Fitness", "Gestation time", "Tasks mask",
                 "Tasks gained"])
    for r in rows:
        f.write_row([r["depth"], r["gid"], r["parent_gid"],
                     r["update_born"], r["length"], r["fitness"],
                     r["gestation"], r["tasks_mask"], r["tasks_gained"]])
    f.close()


# ---------------------------------------------------------------------------
# the pipeline
# ---------------------------------------------------------------------------

class AnalyticsPipeline:
    """Composes the census / knockout / lineage passes over RunTables
    and routes the results through the observability spine (analytics
    runlog, .dat tables, analytics.prom).  One instance per data dir;
    the content-keyed metrics cache persists across run() calls, so the
    live incremental census only ever evaluates genotypes it has not
    seen before."""

    def __init__(self, params, task_names, data_dir: str, seed: int = 0,
                 knockout_top: int = 4, metrics=None):
        self.params = params
        self.task_names = list(task_names or [])
        self.data_dir = data_dir
        self.analysis_dir = os.path.join(data_dir, ANALYSIS_DIR)
        self.seed = int(seed)
        self.knockout_top = int(knockout_top)
        if metrics is None:
            from avida_tpu.systematics.test_metrics import GenomeTestMetrics
            metrics = GenomeTestMetrics(params)
        self.metrics = metrics
        self.census_count = 0
        self.knockout_sweeps_total = 0
        self.knockout_sites_total = 0   # sandbox lanes spent on sweeps
        # content-keyed sweep memo (the GenomeTestMetrics pattern): a
        # stable dominant genotype must not re-pay its L-lane sweep at
        # every live-mode refresh
        self._ko_cache: dict = {}
        self.last_summary = None

    # -- pass plumbing ----------------------------------------------------

    def _live_genotypes(self, tables: RunTables) -> list:
        """Live genotypes, most-abundant first (lowest gid on ties --
        the arbiter's dominant() ordering, so row 0 IS the dominant)."""
        gs = [g for g in tables.arbiter.genotypes.values()
              if g.num_units > 0]
        gs.sort(key=lambda g: (-g.num_units, g.gid))
        return gs

    def _records_for(self, genotypes: list) -> list:
        G = len(genotypes)
        L = self.params.max_memory
        buf = np.zeros((G, L), np.int8)
        lens = np.zeros(G, np.int32)
        for i, g in enumerate(genotypes):
            n = min(g.length, L)
            buf[i, :n] = np.asarray(g.sequence, np.int8)[:n]
            lens[i] = n
        return self.metrics.get_records(buf, lens, seed=self.seed)

    # -- the three batched passes ----------------------------------------

    def census(self, tables: RunTables) -> list:
        """Phenotype census: one row per live genotype (sandbox task
        profile, fitness, gestation), most-abundant first."""
        gs = self._live_genotypes(tables)
        recs = self._records_for(gs)
        rows = []
        for g, r in zip(gs, recs):
            rows.append({
                "gid": g.gid, "num_units": g.num_units, "depth": g.depth,
                "length": g.length, "viable": r["viable"],
                "fitness": r["fitness"], "merit": r["merit"],
                "gestation": r["gestation"],
                "tasks_mask": tasks_mask(r["tasks"]),
                "task_counts": [int(x) for x in r["tasks"]],
            })
        self.census_count += 1
        return rows

    def knockouts(self, tables: RunTables) -> list:
        """Per-site knockout sweeps over the dominant + threshold
        genotypes (most-abundant first, capped at `knockout_top` --
        sweeps are L sandbox evaluations each, the expensive pass)."""
        if self.knockout_top <= 0:
            return []
        gs = self._live_genotypes(tables)
        sel = [g for g in gs if g.threshold]
        if gs and gs[0] not in sel:
            sel.insert(0, gs[0])            # dominant always swept
        sel = sel[: self.knockout_top]
        rows = []
        for g, rec in zip(sel, self._records_for(sel)):
            key = np.asarray(g.sequence, np.int8).tobytes()
            prof = self._ko_cache.get(key)
            if prof is None:
                prof = knockout_profile(self.params, g.sequence,
                                        rec["fitness"], seed=self.seed)
                self._ko_cache[key] = prof
                self.knockout_sweeps_total += 1
                self.knockout_sites_total += prof["length"]
            rows.append({"gid": g.gid, "num_units": g.num_units,
                         "base_fitness": rec["fitness"], **prof})
        return rows

    def lineage(self, tables: RunTables) -> list:
        """Lineage replay: the arbiter parent chain from the dominant
        genotype back to its retained root, RECALCULATEd step by step
        (cached -- ancestors seen by an earlier census cost nothing),
        emitted root-first with per-depth task acquisitions."""
        gs = self._live_genotypes(tables)
        if not gs:
            return []
        arb = tables.arbiter
        chain, seen = [], set()
        g = gs[0]
        while g is not None and g.gid not in seen:
            seen.add(g.gid)
            chain.append(g)
            g = arb.genotypes.get(g.parent_gid) if g.parent_gid >= 0 \
                else None
        chain.reverse()                     # root first
        recs = self._records_for(chain)
        rows, prev_mask = [], 0
        for depth, (g, r) in enumerate(zip(chain, recs)):
            mask = tasks_mask(r["tasks"])
            rows.append({
                "depth": depth, "gid": g.gid, "parent_gid": g.parent_gid,
                "update_born": g.update_born, "length": g.length,
                "fitness": r["fitness"], "gestation": r["gestation"],
                "tasks_mask": mask, "tasks_gained": mask & ~prev_mask,
            })
            prev_mask = mask
        return rows

    # -- composition + publication ----------------------------------------

    def run(self, tables: RunTables, knockouts: bool = True,
            lineage: bool = True, write_tables: bool = True,
            durable: bool = True) -> dict:
        """All passes over one set of tables; returns (and publishes)
        the summary: `{"record": "analytics"}` runlog line, `.dat`
        tables under DATA_DIR/analysis/ and DATA_DIR/analytics.prom."""
        ev0 = self.metrics.evaluations
        t0 = time.perf_counter()
        census_rows = self.census(tables)
        census_ms = (time.perf_counter() - t0) * 1e3
        ev_census = self.metrics.evaluations - ev0

        lineage_rows, lineage_ms = [], 0.0
        if lineage:
            t0 = time.perf_counter()
            lineage_rows = self.lineage(tables)
            lineage_ms = (time.perf_counter() - t0) * 1e3
        ev_lineage = self.metrics.evaluations - ev0 - ev_census

        ko_rows, knockout_ms = [], 0.0
        if knockouts:
            t0 = time.perf_counter()
            ko_rows = self.knockouts(tables)
            knockout_ms = (time.perf_counter() - t0) * 1e3

        dom = census_rows[0] if census_rows else None
        held = 0
        for r in census_rows:
            held |= r["tasks_mask"]
        summary = {
            "update": tables.update,
            "source": tables.path or "live",
            "organisms": int(tables.alive.sum()),
            "genotypes": len(census_rows),
            "systematics_rebuilt": bool(tables.rebuilt),
            # census/lineage genotype evaluations through the
            # content-keyed cache; knockout sweeps bypass it (one lane
            # per genome site) and are accounted separately below
            "evaluated": ev_census + ev_lineage,
            "evaluated_census": ev_census,
            "evaluated_lineage": ev_lineage,
            "evaluated_total": self.metrics.evaluations,
            "tasks_held_mask": held,
            "dominant": (None if dom is None else {
                "gid": dom["gid"], "units": dom["num_units"],
                "depth": dom["depth"], "fitness": dom["fitness"],
                "tasks_mask": dom["tasks_mask"],
            }),
            "lineage_depth": max(len(lineage_rows) - 1, 0),
            "knockout_sweeps": len(ko_rows),
            "knockout_sweeps_total": self.knockout_sweeps_total,
            "knockout_sites": sum(r["length"] for r in ko_rows),
            "knockout_sites_total": self.knockout_sites_total,
            "census_ms": round(census_ms, 3),
            "knockout_ms": round(knockout_ms, 3),
            "lineage_ms": round(lineage_ms, 3),
        }
        if write_tables:
            os.makedirs(self.analysis_dir, exist_ok=True)
            write_census_dat(os.path.join(self.analysis_dir, "census.dat"),
                             census_rows, self.task_names)
            if lineage:
                write_lineage_dat(
                    os.path.join(self.analysis_dir, "lineage.dat"),
                    lineage_rows)
            if knockouts and self.knockout_top > 0:
                write_knockout_dat(
                    os.path.join(self.analysis_dir, "knockout.dat"),
                    ko_rows)
        self.publish(summary, durable=durable)
        self.last_summary = summary
        return summary

    def publish(self, summary: dict, durable: bool = True):
        """Route one summary through the observability spine."""
        from avida_tpu.observability.exporter import write_metrics
        from avida_tpu.observability.runlog import append_record

        os.makedirs(self.analysis_dir, exist_ok=True)
        append_record(os.path.join(self.analysis_dir, ANALYTICS_LOG),
                      dict({"record": "analytics",
                            "time": round(time.time(), 3)}, **summary),
                      max_bytes=ANALYTICS_LOG_MAX_BYTES)
        write_metrics(os.path.join(self.data_dir, ANALYTICS_METRICS_FILE),
                      render_analytics(summary), durable=durable)


def render_analytics(summary: dict) -> str:
    """analytics.prom exposition text (exporter.render_families)."""
    from avida_tpu.observability.exporter import render_families

    dom = summary.get("dominant") or {}
    fams = [
        ("avida_analytics_census_update", "gauge",
         "update number the last census describes", summary["update"]),
        ("avida_analytics_census_genotypes", "gauge",
         "live genotypes scored by the last census",
         summary["genotypes"]),
        ("avida_analytics_genotypes_evaluated_total", "counter",
         "genotype evaluations run in the Test-CPU sandbox by the "
         "census/lineage passes (knockout lanes counted separately)",
         summary["evaluated_total"]),
        ("avida_analytics_knockout_sweeps_total", "counter",
         "per-site knockout sweeps completed",
         summary["knockout_sweeps_total"]),
        ("avida_analytics_knockout_sites_total", "counter",
         "sandbox lanes spent on knockout sweeps (one per genome site)",
         summary.get("knockout_sites_total", 0)),
        ("avida_analytics_tasks_held_mask", "gauge",
         "bitmask of tasks any live genotype performs (bit 8 = EQU)",
         summary["tasks_held_mask"]),
        ("avida_analytics_dominant_genotype_id", "gauge",
         "dominant genotype id (-1 when the world is empty)",
         dom.get("gid", -1)),
        ("avida_analytics_dominant_fitness", "gauge",
         "dominant genotype sandbox fitness", dom.get("fitness", 0.0)),
        ("avida_analytics_dominant_lineage_depth", "gauge",
         "phylogenetic depth of the dominant genotype",
         dom.get("depth", 0)),
        ("avida_analytics_dominant_tasks_mask", "gauge",
         "tasks the dominant genotype performs",
         dom.get("tasks_mask", 0)),
        ("avida_analytics_heartbeat_timestamp_seconds", "gauge",
         "unix time of the last analytics export",
         round(time.time(), 3)),
    ]
    return render_families(fams)


# ---------------------------------------------------------------------------
# live mode (TPU_ANALYTICS=1): the in-run incremental census
# ---------------------------------------------------------------------------

class LiveAnalytics:
    """In-run analytics for World.run: an incremental census (plus the
    dominant-lineage replay) refreshed at checkpoint boundaries and at
    run exit, so the heartbeat answer to "what evolved?" is never staler
    than one checkpoint interval.  Knockout sweeps are off by default
    (TPU_ANALYTICS_KNOCKOUT_TOP opts in -- they cost L evaluations per
    genotype).  refresh() never raises: a broken analytics pass must not
    take down the run it is observing, and it never touches world state
    or PRNG keys, so trajectories are bit-identical with analytics on or
    off."""

    def __init__(self, world):
        cfg = world.cfg
        self.pipeline = AnalyticsPipeline(
            world.params, world.environment.task_names(), world.data_dir,
            seed=int(cfg.get("TPU_ANALYTICS_SEED", 0)),
            knockout_top=int(cfg.get("TPU_ANALYTICS_KNOCKOUT_TOP", 0)))

    def refresh(self, world, durable: bool = False):
        from avida_tpu.observability.runlog import emit_event
        if world.state is None:
            return
        try:
            tables = tables_from_world(world)
            self.pipeline.run(
                tables, knockouts=self.pipeline.knockout_top > 0,
                durable=durable)
        except Exception as e:          # noqa: BLE001 -- observability
            # must never take down the run it observes
            emit_event(world, "analytics_failed", error=str(e))


# ---------------------------------------------------------------------------
# CLI (python -m avida_tpu --analyze CKPT_DIR / scripts/analyze_tool.py)
# ---------------------------------------------------------------------------

def _peek_state_shape(ckpt_dir: str):
    """(num_cells, max_memory) of the newest generation whose manifest
    parses -- a cheap peek (no CRC sweep) used only to default
    TPU_MAX_MEMORY so the Test CPU's genome buffer matches the archived
    run's."""
    for path in ckpt_mod.restore_candidates(ckpt_dir):
        try:
            with open(os.path.join(path, ckpt_mod.MANIFEST)) as f:
                manifest = json.load(f)
            shape = manifest["arrays"]["state.tape"]["shape"]
            return int(shape[0]), int(shape[1])
        except (OSError, json.JSONDecodeError, KeyError, IndexError,
                TypeError, ValueError):
            continue
    return None


def format_summary(summary: dict) -> str:
    """Human-readable digest of one analytics summary."""
    dom = summary.get("dominant")
    held = summary.get("tasks_held_mask", 0)
    lines = [
        f"census      update {summary['update']}: "
        f"{summary['organisms']} organisms, "
        f"{summary['genotypes']} genotypes "
        f"({summary.get('evaluated_census', summary['evaluated'])} "
        f"newly evaluated)",
        f"tasks held  {held:#x} ({bin(held).count('1')} tasks)",
    ]
    if dom:
        lines.append(
            f"dominant    gid {dom['gid']} x{dom['units']}, "
            f"depth {dom['depth']}, fitness {dom['fitness']:.4g}, "
            f"tasks {dom['tasks_mask']:#x}")
    lines.append(
        f"lineage     {summary['lineage_depth']} steps replayed; "
        f"knockouts {summary['knockout_sweeps']} sweep(s)")
    if summary.get("systematics_rebuilt"):
        lines.append("note        no systematics sidecar: genotype table "
                     "rebuilt from live state (depth restarts at 0)")
    return "\n".join(lines)


def cli_main(ckpt_dir: str, config_dir=None, overrides=(), data_dir=None,
             verbose: bool = False, knockout_top: int = 4,
             census_only: bool = False, seed: int = 0) -> int:
    """Offline checkpoint-native analytics over an archived run.  No
    World.run, no donated-buffer compile: the World instance below only
    resolves config / instruction set / environment the way the run did;
    the only device programs are the Test CPU's separate jits."""
    from avida_tpu.service import EXIT_CKPT

    overrides = list(overrides)
    shape = _peek_state_shape(ckpt_dir)
    if shape is not None and not any(n == "TPU_MAX_MEMORY"
                                     for n, _ in overrides):
        overrides.append(("TPU_MAX_MEMORY", shape[1]))
    if data_dir is None:
        # fleet fault-domain layout (SPOOL/<job>/{data,ck}): analyzing
        # <job>/ck lands the results next to the run's own outputs
        sib = os.path.join(os.path.dirname(os.path.abspath(ckpt_dir)),
                           "data")
        if os.path.isdir(sib):
            data_dir = sib

    from avida_tpu.world import World
    world = World(config_dir=config_dir, overrides=overrides,
                  data_dir=data_dir)

    def on_skip(path, err):
        print(f"[avida-tpu] analytics: skipping corrupt generation "
              f"{path} ({err}); falling back", file=sys.stderr)

    try:
        tables = load_run_tables(ckpt_dir, on_skip=on_skip)
    except ckpt_mod.CheckpointError as e:
        print(f"[avida-tpu] analyze failed: {e}", file=sys.stderr)
        return EXIT_CKPT
    if tables.genome.shape[1] != world.params.max_memory:
        print(f"[avida-tpu] checkpoint genome width "
              f"{tables.genome.shape[1]} != configured TPU_MAX_MEMORY "
              f"{world.params.max_memory}; pass the run's original "
              f"config (-c/-set)", file=sys.stderr)
        return 2

    pipe = AnalyticsPipeline(world.params, world.environment.task_names(),
                             world.data_dir, seed=seed,
                             knockout_top=knockout_top)
    summary = pipe.run(tables, knockouts=not census_only)
    print(format_summary(summary))
    if verbose:
        names = "census,lineage" + ("" if census_only else ",knockout")
        print(f"tables      {pipe.analysis_dir}/{{{names}}}.dat, "
              + os.path.join(world.data_dir, ANALYTICS_METRICS_FILE))
    return 0
