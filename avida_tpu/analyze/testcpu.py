"""Sandboxed genotype evaluation: the batched Test CPU.

TPU-native equivalent of cTestCPU (avida-core/source/cpu/cTestCPU.cc:
TestGenome :190, ProcessGestation :144) + its fake world interface
(cpu/cTestCPUInterface.cc).  The reference evaluates one genotype at a time
in a sandboxed CPU, running up to TEST_CPU_TIME_MOD x length cycles until
the organism divides, then recursing into the offspring for up to
nHardware::TEST_CPU_GENERATIONS (3) generations to find the true (fixed
point) replication behavior.

Here the whole genotype batch is ONE lockstep population: each genome gets a
lane, micro-steps run until every lane divided or timed out, and the
generation recursion is a host-side loop over at most 3 batched runs (each
next round only re-runs lanes whose offspring differed from the parent).
This is the oracle behind analyze-mode RECALCULATE, dominant fitness
reporting, reversion/sterilization tests and mutational landscapes.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from avida_tpu.core.state import make_cell_inputs, zeros_population
from avida_tpu.ops.interpreter import extract_offspring, micro_step

TEST_CPU_GENERATIONS = 3   # ref nHardware::TEST_CPU_GENERATIONS

# Compile-count probe: bumped once per (re)trace of the jitted gestation
# oracle (the increment is a Python side effect, so it runs at TRACE time
# only -- a cache hit never touches it).  Census sweeps over many batch
# sizes must stay O(log G) compiles thanks to the bucket padding in
# evaluate_genomes; tests/test_analyze_pipeline.py asserts it through
# gestation_trace_count().
_GESTATION_TRACES = 0


def gestation_trace_count() -> int:
    """How many times the gestation oracle has been traced (compiled)
    in this process."""
    return _GESTATION_TRACES


def _bucket(n: int) -> int:
    """Power-of-two batch bucket: the jitted gestation oracle compiles
    one program per distinct batch SHAPE, so padding every batch up to
    the next power of two caps the compile count at O(log G_max) instead
    of one per distinct batch size (dead padded lanes never execute:
    lens == 0 means alive is False from the first cycle)."""
    return 1 << max(int(n) - 1, 0).bit_length()


@dataclass
class TestResult:
    """Per-genotype metrics (ref cCPUTestInfo accessors)."""
    viable: np.ndarray          # bool[G]  divided with a self-replicating line
    gestation_time: np.ndarray  # int32[G] cycles to (final-generation) divide
    merit: np.ndarray           # f32[G]
    fitness: np.ndarray         # f32[G]   merit / gestation
    task_counts: np.ndarray     # int32[G, R] tasks at divide
    copied_size: np.ndarray     # int32[G]
    executed_size: np.ndarray   # int32[G]
    offspring_genome: np.ndarray  # int8[G, L]
    offspring_len: np.ndarray   # int32[G]
    generations: np.ndarray     # int32[G] generations to reach a fixed point


def _sandbox_inputs(key, g):
    """Per-lane sandbox IO inputs, COUNTER-STABLE in the batch size:
    lane i draws from fold_in(key, i), so its inputs depend only on
    (key, i) -- never on how many other lanes share the batch.  A flat
    make_cell_inputs(key, g) draw would make every lane's values a
    function of g (threefry pairs counter i with i + n/2), so bucket
    padding -- or simply evaluating the same genotype in batches of
    different sizes -- would silently change input-dependent task
    profiles.  With this construction the padding in evaluate_genomes
    is value-preserving by design."""
    return jax.vmap(
        lambda i: make_cell_inputs(jax.random.fold_in(key, i), 1)[0]
    )(jnp.arange(g))


def _sandbox_state(params, genomes, lens, key):
    g = genomes.shape[0]
    st = zeros_population(g, params.max_memory, params.num_reactions,
                          params.num_global_res, params.num_spatial_res,
                          n_deme_res=params.num_deme_res)
    k_in, _ = jax.random.split(key)
    st = st.replace(
        inputs=_sandbox_inputs(k_in, g),
        deme_resources=jnp.broadcast_to(
            jnp.asarray(params.dres_initial, jnp.float32)[None, :],
            (1, params.num_deme_res)),
        tape=genomes.astype(jnp.uint8),
        genome=genomes.astype(jnp.int8),
        mem_len=lens, genome_len=lens,
        alive=lens > 0,
        merit=lens.astype(jnp.float32),
        cur_bonus=jnp.full(g, params.default_bonus, jnp.float32),
        executed_size=lens, copied_size=lens,
        max_executed=jnp.full(g, 2**30, jnp.int32),  # no aging in the sandbox
        resources=jnp.asarray(params.res_initial, jnp.float32),
        res_grid=jnp.broadcast_to(
            jnp.asarray(params.sres_initial, jnp.float32)[:, None],
            (params.num_spatial_res, g)),
    )
    return st


@partial(jax.jit, static_argnums=(0, 3))
def _run_gestation(params, genomes, lens, time_mod, key):
    """Run every lane until divide or time_mod * len cycles (one generation).

    Returns (state-after, divided[G], gestation[G], offspring[G, L],
    off_len[G]).  Mirrors cTestCPU::ProcessGestation (cTestCPU.cc:144).
    """
    global _GESTATION_TRACES
    _GESTATION_TRACES += 1          # trace-time only (compile probe)
    st = _sandbox_state(params, genomes, lens, key)
    budget = time_mod * jnp.maximum(lens, 1)
    max_t = budget.max()

    def cond(c):
        t, st = c
        active = st.alive & ~st.divide_pending & (t < budget)
        return active.any() & (t < max_t)

    def body(c):
        t, st = c
        mask = st.alive & ~st.divide_pending & (t < budget)
        st = micro_step(params, st, jax.random.fold_in(key, t), mask)
        return t + 1, st

    _, st = jax.lax.while_loop(cond, body, (jnp.int32(0), st))
    off, off_len = extract_offspring(params, st, jax.random.fold_in(key, 0x7FFFFFFF))
    return st, st.divide_pending, st.gestation_time, off, off_len


def evaluate_genomes(params, genomes, lens=None, seed: int = 0,
                 time_mod: int = 20) -> TestResult:
    """Evaluate a batch of genotypes in the sandbox (host-facing API).

    genomes: int array [G, L] (padded with anything beyond lens).
    time_mod: TEST_CPU_TIME_MOD (cAvidaConfig; default 20).
    """
    genomes = jnp.asarray(genomes)
    G, L = genomes.shape
    assert L == params.max_memory, (
        f"genome buffer width {L} != params.max_memory {params.max_memory}")
    # the sandbox evaluates the genotype itself: all mutation machinery off
    # (ref cTestCPU runs with its own context; analyze RECALCULATE expects
    # deterministic per-genotype metrics)
    params = params.replace(copy_mut_prob=0.0, divide_mut_prob=0.0,
                            divide_ins_prob=0.0, divide_del_prob=0.0,
                            point_mut_prob=0.0)
    if lens is None:
        lens = (genomes != 0).cumsum(axis=1).argmax(axis=1) + 1
    lens = jnp.asarray(lens, jnp.int32)
    # bucket-pad the batch to a power of two so sweeps over many batch
    # sizes (census over G genotypes, knockouts over L sites, lineage
    # walks) reuse O(log G) compiled gestation programs instead of
    # paying one compile per distinct size.  Padded lanes have lens == 0
    # -> never alive -> never execute; results are sliced back to G.
    G0 = G
    Gp = _bucket(G)
    if Gp != G:
        genomes = jnp.concatenate(
            [genomes, jnp.zeros((Gp - G, L), genomes.dtype)])
        lens = jnp.concatenate([lens, jnp.zeros(Gp - G, jnp.int32)])
        G = Gp
    key = jax.random.key(seed)

    cur_g, cur_len = genomes, lens
    done = np.zeros(G, bool)
    generations = np.zeros(G, np.int32)
    out = {}
    for gen in range(TEST_CPU_GENERATIONS):
        st, divided, gest, off, off_len = _run_gestation(
            params, cur_g, cur_len, time_mod, jax.random.fold_in(key, gen))
        divided_np = np.asarray(divided)
        if gen == 0:
            out = {
                "divided": divided_np.copy(),
                "gestation": np.asarray(gest).copy(),
                "merit": np.asarray(st.merit).copy(),
                "fitness": np.asarray(st.fitness).copy(),
                "tasks": np.asarray(st.last_task_count).copy(),
                "copied": np.asarray(st.child_copied_size).copy(),
                "executed": np.asarray(st.executed_size).copy(),
                "off": np.asarray(off).copy(),
                "off_len": np.asarray(off_len).copy(),
            }
        else:
            redo = ~done
            for name, val in (("divided", divided_np), ("gestation", gest),
                              ("merit", st.merit), ("fitness", st.fitness),
                              ("tasks", st.last_task_count),
                              ("copied", st.child_copied_size),
                              ("executed", st.executed_size),
                              ("off", off), ("off_len", off_len)):
                out[name][redo] = np.asarray(val)[redo]
            generations[redo] += 1
        # a lane is settled when it failed to divide or bred true
        # (offspring == input genome): ref cTestCPU generation recursion
        off_np = np.asarray(off)
        off_len_np = np.asarray(off_len)
        cur_np = np.asarray(cur_g)
        len_np = np.asarray(cur_len)
        same = (off_len_np == len_np)
        L_idx = np.arange(L)
        valid = L_idx[None, :] < np.minimum(off_len_np, len_np)[:, None]
        same &= ~np.any((off_np != cur_np) & valid, axis=1)
        done |= (~divided_np) | same
        if done.all():
            break
        # next generation: run the (new) offspring of unsettled lanes
        nxt = np.where(done[:, None], cur_np, off_np)
        nxt_len = np.where(done, len_np, off_len_np)
        cur_g, cur_len = jnp.asarray(nxt), jnp.asarray(nxt_len)

    gest = out["gestation"][:G0]
    merit = out["merit"][:G0]
    return TestResult(
        viable=out["divided"][:G0] & (gest > 0),
        gestation_time=gest,
        merit=merit,
        fitness=np.where(gest > 0, merit / np.maximum(gest, 1), 0.0),
        task_counts=out["tasks"][:G0],
        copied_size=out["copied"][:G0],
        executed_size=out["executed"][:G0],
        offspring_genome=out["off"][:G0],
        offspring_len=out["off_len"][:G0],
        generations=generations[:G0],
    )
