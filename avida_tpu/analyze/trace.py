"""Per-cycle hardware tracing.

Equivalent of the reference tracing stack: cHardwareTracer
(avida-core/source/cpu/cHardwareTracer.h:34, invoked from the inner loop at
cHardwareCPU.cc:956), cHardwareStatusPrinter (cpu/cHardwareStatusPrinter.cc
renders registers/heads/stacks per cycle for the analyze TRACE command) and
the GUI SnapshotTracer (source/viewer/OrganismTrace.cc:134).

The lockstep engine has no per-organism callback hook; instead the trace
runs the genome through the sandbox one micro-step at a time and snapshots
the architectural state after every cycle.  `collect_trace` returns the
snapshots as arrays (the GUI-facing API); `trace_genome` renders the
cHardwareStatusPrinter-style text file.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from avida_tpu.analyze.testcpu import _sandbox_state


def collect_trace(params, genome, max_cycles: int = 2000, seed: int = 0):
    """Run one genome in the sandbox, snapshotting state every cycle.

    Returns a list of dicts (one per executed cycle): the fetched opcode
    (`op`, read pre-execution through the interpreter's own fetch helper,
    ops/interpreter.fetch_opcode -- the post-hoc memory read used before
    could misreport instructions at sites the copy loop later overwrote),
    ip, read/write/flow head positions, registers, top of stack, memory
    length, divide flag.
    """
    from avida_tpu.ops.interpreter import fetch_opcode, micro_step

    genome = np.asarray(genome, np.int8)
    L = params.max_memory
    buf = np.zeros((1, L), np.int8)
    n = min(len(genome), L)
    buf[:, :n] = genome[:n]
    params = params.replace(copy_mut_prob=0.0, divide_mut_prob=0.0,
                            divide_ins_prob=0.0, divide_del_prob=0.0)
    key = jax.random.key(seed)
    st = _sandbox_state(params, jnp.asarray(buf), jnp.asarray([n], jnp.int32),
                        key)
    step = jax.jit(lambda s, k: micro_step(params, s, k, s.alive
                                           & ~s.divide_pending))
    fetch = jax.jit(lambda s: fetch_opcode(params, s))
    snaps = []
    for t in range(max_cycles):
        op = int(fetch(st)[0])
        st = step(st, jax.random.fold_in(key, t))
        snaps.append({
            "cycle": t + 1,
            "op": op,
            "ip": int(st.heads[0, 0]),
            "read": int(st.heads[0, 1]),
            "write": int(st.heads[0, 2]),
            "flow": int(st.heads[0, 3]),
            "regs": np.asarray(st.regs[0]).tolist(),
            "stack_top": int(st.stacks[0, int(st.active_stack[0]),
                                       int(st.sp[0, int(st.active_stack[0])])]),
            "mem_len": int(st.mem_len[0]),
            "divided": bool(st.divide_pending[0]),
        })
        if snaps[-1]["divided"]:
            break
    return snaps, st


def trace_genome(params, instset, genome, path: str,
                 max_cycles: int = 2000, seed: int = 0):
    """Write a cHardwareStatusPrinter-style text trace to `path`."""
    genome = np.asarray(genome, np.int8)
    snaps, st = collect_trace(params, genome, max_cycles, seed)
    names = instset.inst_names
    with open(path, "w") as f:
        f.write(f"# Trace of genome (length {len(genome)})\n")
        f.write("# " + " ".join(names[int(o)] for o in genome) + "\n\n")
        for s in snaps:
            f.write(
                f"{names[s['op']]:12s} "
                f"U:{s['cycle']} IP:{s['ip']} AX:{s['regs'][0]} "
                f"BX:{s['regs'][1]} CX:{s['regs'][2]} "
                f"R-Head:{s['read']} W-Head:{s['write']} F-Head:{s['flow']} "
                f"Mem:{s['mem_len']} Stack:{s['stack_top']}"
                + ("  DIVIDE" if s["divided"] else "") + "\n")
        f.write(f"\n# {len(snaps)} cycles"
                + (" (divided)" if snaps and snaps[-1]["divided"] else "")
                + "\n")
    return snaps
