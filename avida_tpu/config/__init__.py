from avida_tpu.config.schema import AvidaConfig, load_avida_cfg
from avida_tpu.config.instset import (InstSet, load_instset, default_instset,
                                      heads_sex_instset, transsmt_instset, experimental_instset, pred_look_instset)
from avida_tpu.config.organism import load_organism
from avida_tpu.config.environment import Environment, load_environment
from avida_tpu.config.events import Event, load_events

__all__ = [
    "AvidaConfig", "load_avida_cfg",
    "InstSet", "load_instset", "default_instset", "heads_sex_instset",
    "transsmt_instset",
    "load_organism",
    "Environment", "load_environment",
    "Event", "load_events",
]
