"""Environment (task/reaction/resource) configuration.

Parses the reference `environment.cfg` DSL (ref cEnvironment::Load,
avida-core/source/main/cEnvironment.cc:1213; REACTION lines via LoadReaction
cc:757 and LoadReactionProcess cc:142; RESOURCE via LoadResource cc:474) into
a vectorization-friendly `Environment`:

 - every supported task is a *set of 8-bit logic IDs* (the truth-table
   encoding computed by cTaskLib::SetupTests, cTaskLib.cc:369-448), so task
   evaluation on device is one `logic_id in set` membership test;
 - reactions carry process (value/type) + requisite (count window) data
   mirrored from cReactionProcess / cReactionRequisite.

Only logic-family tasks are device-evaluated today; the full 215-entry task
library (cTaskLib.cc:87+) grows here as more families are vectorized.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

# Logic-ID membership sets, transcribed from the cited checks in cTaskLib.cc
# (Task_Not cc:511, Task_Nand cc:518, Task_And cc:525, Task_OrNot cc:532,
#  Task_Or cc:541, Task_AndNot cc:548, Task_Nor cc:557, Task_Xor cc:564,
#  Task_Equ cc:571, Task_Echo cc:452).
LOGIC_TASKS = {
    "not": (15, 51, 85),
    "nand": (63, 95, 119),
    "and": (136, 160, 192),
    "orn": (175, 187, 207, 221, 243, 245),
    "or": (238, 250, 252),
    "andn": (10, 12, 34, 48, 68, 80),
    "nor": (3, 5, 17),
    "xor": (60, 90, 102),
    "equ": (153, 165, 195),
    "echo": (170, 204, 240),
    # 1-input identity tasks treated through logic ids as well
    "true": (255,),
    "false": (0,),
}
# nand-/nor-resourceDependent (cTaskLib.cc:116-117) additionally gate on a
# cell-resource threshold; mapping them to plain logic sets would silently
# run wrong physics, so they stay unsupported (load raises) until the
# resource precondition is implemented.
# The full 3-input logic family: all 68 functions, logic-ID sets
# transcribed from the registered checks (cTaskLib.cc:121-188 ->
# Task_Logic3in_AA..CP bodies).  Format-contract constants.
_LOGIC3 = {
    "AA": (1,), "AB": (22,), "AC": (23,), "AD": (104,), "AE": (105,),
    "AF": (126,), "AG": (127,), "AH": (128,), "AI": (129,), "AJ": (150,),
    "AK": (151,), "AL": (232,), "AM": (233,), "AN": (254,),
    "AO": (2, 4, 16), "AP": (6, 18, 20), "AQ": (7, 19, 21),
    "AR": (8, 32, 64), "AS": (9, 33, 65), "AT": (14, 50, 84),
    "AU": (24, 36, 66), "AV": (25, 37, 67), "AW": (30, 54, 86),
    "AX": (31, 55, 87), "AY": (40, 72, 96), "AZ": (41, 73, 97),
    "BA": (42, 76, 112), "BB": (43, 77, 113), "BC": (61, 91, 103),
    "BD": (62, 94, 118), "BE": (106, 108, 120), "BF": (107, 109, 121),
    "BG": (110, 122, 124), "BH": (111, 123, 125), "BI": (130, 132, 144),
    "BJ": (131, 133, 145), "BK": (134, 146, 148), "BL": (135, 147, 149),
    "BM": (137, 161, 193), "BN": (142, 178, 212), "BO": (143, 179, 213),
    "BP": (152, 164, 194), "BQ": (158, 182, 214), "BR": (159, 183, 215),
    "BS": (168, 200, 224), "BT": (169, 201, 225), "BU": (171, 205, 241),
    "BV": (188, 218, 230), "BW": (189, 219, 231), "BX": (190, 222, 246),
    "BY": (191, 223, 247), "BZ": (234, 236, 248), "CA": (235, 237, 249),
    "CB": (239, 251, 253), "CC": (11, 13, 35, 49, 69, 81),
    "CD": (26, 28, 38, 52, 70, 82), "CE": (27, 29, 39, 53, 71, 83),
    "CF": (44, 56, 74, 88, 98, 100), "CG": (45, 57, 75, 89, 99, 101),
    "CH": (46, 58, 78, 92, 114, 116), "CI": (47, 59, 79, 93, 115, 117),
    "CJ": (138, 140, 162, 176, 196, 208),
    "CK": (139, 141, 163, 177, 197, 209),
    "CL": (154, 156, 166, 180, 198, 210),
    "CM": (155, 157, 167, 181, 199, 211),
    "CN": (172, 184, 202, 216, 226, 228),
    "CO": (173, 185, 203, 217, 227, 229),
    "CP": (174, 186, 206, 220, 242, 244),
}
for _suffix, _ids in _LOGIC3.items():
    LOGIC_TASKS[f"logic_3{_suffix}"] = _ids
for _name in list(LOGIC_TASKS):
    LOGIC_TASKS[_name + "_dup"] = LOGIC_TASKS[_name]

PROCTYPE_ADD, PROCTYPE_MULT, PROCTYPE_POW, PROCTYPE_LIN = 0, 1, 2, 3
_PROC_TYPES = {"add": PROCTYPE_ADD, "mult": PROCTYPE_MULT, "pow": PROCTYPE_POW,
               "lin": PROCTYPE_LIN}


@dataclass
class Process:
    value: float = 1.0
    type: int = PROCTYPE_ADD
    resource: str | None = None     # None = infinite resource
    max_number: float = 1.0
    min_number: float = 0.0
    max_fraction: float = 1.0
    depletable: bool = True
    product: str | None = None      # by-product resource (DoProcesses
                                    # cc:1824-1830)
    conversion: float = 1.0         # produced = consumed * conversion


@dataclass
class Requisite:
    min_task_count: int = 0
    max_task_count: int = 2**30
    min_reaction_count: int = 0
    max_reaction_count: int = 2**30
    reactions: list = field(default_factory=list)     # required prior reactions
    noreactions: list = field(default_factory=list)   # forbidden prior reactions
    divide_only: bool = False


@dataclass
class Reaction:
    name: str
    task: str
    processes: list
    requisites: list


@dataclass
class Resource:
    name: str
    inflow: float = 0.0
    outflow: float = 0.0
    initial: float = 0.0
    geometry: str = "global"      # global | grid | torus (spatial)
    deme: bool = False            # per-deme pool (cResource::SetDemeResource)
    xdiffuse: float = 1.0         # spatial only (cSpatialResCount diffusion)
    ydiffuse: float = 1.0
    inflowx1: int = -1            # spatial inflow box (-1 = everywhere)
    inflowx2: int = -1
    inflowy1: int = -1
    inflowy2: int = -1
    # gradient (moving-peak) resources (cGradientCount.cc)
    height: int = 0               # peak height; 0 = not a gradient resource
    spread: int = 0               # cone radius
    plateau: float = -1.0         # flat-top value (-1 = pure cone)
    updatestep: int = 1           # updates between peak moves
    peakx: int = -1               # -1 = random initial position
    peaky: int = -1
    move_a_scaler: float = 1.0    # >1 enables movement

    @property
    def is_spatial(self) -> bool:
        return self.geometry != "global"

    @property
    def is_gradient(self) -> bool:
        return self.height > 0


@dataclass
class Environment:
    reactions: list = field(default_factory=list)
    resources: list = field(default_factory=list)
    input_size: int = 3
    output_size: int = 1

    @property
    def num_reactions(self) -> int:
        return len(self.reactions)

    def task_names(self):
        return [r.task for r in self.reactions]

    def reaction_names(self):
        return [r.name for r in self.reactions]

    def global_resources(self):
        return [r for r in self.resources
                if not r.is_spatial and not r.deme]

    def spatial_resources(self):
        return [r for r in self.resources if r.is_spatial]

    def deme_resources(self):
        return [r for r in self.resources if r.deme and not r.is_spatial]

    def device_tables(self):
        """Build numpy tables for the jitted task-evaluation kernel.

        Returns dict with:
          task_logic_mask: bool[NR, 256] -- logic-id membership per reaction's task
          proc_value/proc_type: per-reaction first-process params
          max_task_count/min_task_count: requisite windows
          req_reaction_mask/noreq_reaction_mask: bool[NR, NR] prior-reaction gates
        """
        nr = self.num_reactions
        mask = np.zeros((nr, 256), bool)
        value = np.zeros(nr, np.float64)
        ptype = np.zeros(nr, np.int32)
        # first-process resource binding (cReactionProcess; -1 = infinite)
        gres = {r.name: i for i, r in enumerate(self.global_resources())}
        sres = {r.name: i for i, r in enumerate(self.spatial_resources())}
        dres = {r.name: i for i, r in enumerate(self.deme_resources())}
        p_res = np.full(nr, -1, np.int32)
        p_spatial = np.zeros(nr, bool)
        p_deme = np.zeros(nr, bool)
        p_max = np.ones(nr, np.float64)
        p_frac = np.ones(nr, np.float64)
        p_depl = np.ones(nr, bool)
        p_prod = np.full(nr, -1, np.int32)
        p_prod_spatial = np.zeros(nr, bool)
        p_conv = np.ones(nr, np.float64)
        max_tc = np.full(nr, 2**30, np.int64)
        min_tc = np.zeros(nr, np.int64)
        max_rc = np.full(nr, 2**30, np.int64)
        min_rc = np.zeros(nr, np.int64)
        req_mask = np.zeros((nr, nr), bool)
        noreq_mask = np.zeros((nr, nr), bool)
        name_to_idx = {r.name: i for i, r in enumerate(self.reactions)}
        from avida_tpu.ops.tasks import MATH_TASKS
        math_names = []
        for i, r in enumerate(self.reactions):
            if r.task in MATH_TASKS:
                # math-family tasks evaluate against arithmetic candidates
                # (ops/tasks.math_performed), not the logic-id mask
                math_names.append(r.task)
            else:
                math_names.append("")
                if r.task not in LOGIC_TASKS:
                    raise ValueError(
                        f"task {r.task!r} is not in the vectorized logic or "
                        f"math task sets yet")
                mask[i, list(LOGIC_TASKS[r.task])] = True
            if r.processes:
                p = r.processes[0]
                value[i] = p.value
                ptype[i] = p.type
                p_max[i] = p.max_number
                p_frac[i] = p.max_fraction
                p_depl[i] = p.depletable
                if p.resource is not None and p.resource in gres:
                    p_res[i] = gres[p.resource]
                elif p.resource is not None and p.resource in sres:
                    p_res[i] = sres[p.resource]
                    p_spatial[i] = True
                elif p.resource is not None and p.resource in dres:
                    p_res[i] = dres[p.resource]
                    p_deme[i] = True
                elif p.resource is not None:
                    # ref cEnvironment::LoadReactionProcess errors on unknown
                    # resource names; silently treating it as infinite would
                    # quietly run a limited experiment unlimited
                    raise ValueError(
                        f"reaction {r.name!r} binds unknown resource "
                        f"{p.resource!r}")
                if p.product is not None:
                    p_conv[i] = p.conversion
                    if p.product in gres:
                        p_prod[i] = gres[p.product]
                    elif p.product in sres:
                        p_prod[i] = sres[p.product]
                        p_prod_spatial[i] = True
                    else:
                        raise ValueError(
                            f"reaction {r.name!r} produces unknown "
                            f"resource {p.product!r}")
            for q in r.requisites:
                max_tc[i] = min(max_tc[i], q.max_task_count)
                min_tc[i] = max(min_tc[i], q.min_task_count)
                max_rc[i] = min(max_rc[i], q.max_reaction_count)
                min_rc[i] = max(min_rc[i], q.min_reaction_count)
                for rn in q.reactions:
                    req_mask[i, name_to_idx[rn]] = True
                for rn in q.noreactions:
                    noreq_mask[i, name_to_idx[rn]] = True
        return {
            "task_logic_mask": mask, "proc_value": value, "proc_type": ptype,
            "max_task_count": max_tc, "min_task_count": min_tc,
            "max_reaction_count": max_rc, "min_reaction_count": min_rc,
            "req_reaction_mask": req_mask, "noreq_reaction_mask": noreq_mask,
            "proc_res_idx": p_res, "proc_res_spatial": p_spatial,
            "proc_res_deme": p_deme,
            "proc_max": p_max, "proc_frac": p_frac, "proc_depletable": p_depl,
            "proc_product_idx": p_prod,
            "proc_product_spatial": p_prod_spatial,
            "proc_conversion": p_conv,
            "task_math_name": tuple(math_names),
        }


def _parse_colon_kv(token: str):
    parts = token.split(":")
    return parts[0], parts[1:]


def load_environment(path: str) -> Environment:
    env = Environment()
    with open(path) as f:
        for raw in f:
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            tokens = line.split()
            kind = tokens[0].upper()
            if kind == "REACTION":
                name, task = tokens[1], tokens[2]
                processes, requisites = [], []
                for tok in tokens[3:]:
                    head, kvs = _parse_colon_kv(tok)
                    kv = {}
                    for item in kvs:
                        if "=" in item:
                            k, v = item.split("=", 1)
                            kv[k] = v
                    if head == "process":
                        processes.append(Process(
                            value=float(kv.get("value", 1.0)),
                            type=_PROC_TYPES[kv.get("type", "add")],
                            resource=kv.get("resource"),
                            max_number=float(kv.get("max", 1.0)),
                            min_number=float(kv.get("min", 0.0)),
                            max_fraction=float(kv.get("frac", 1.0)),
                            depletable=bool(int(kv.get("depletable", 1))),
                            product=kv.get("product"),
                            conversion=float(kv.get("conversion", 1.0)),
                        ))
                    elif head == "requisite":
                        q = Requisite()
                        if "max_count" in kv:
                            q.max_task_count = int(kv["max_count"])
                        if "min_count" in kv:
                            q.min_task_count = int(kv["min_count"])
                        if "max_reaction_count" in kv:
                            q.max_reaction_count = int(kv["max_reaction_count"])
                        if "min_reaction_count" in kv:
                            q.min_reaction_count = int(kv["min_reaction_count"])
                        if "reaction" in kv:
                            q.reactions.append(kv["reaction"])
                        if "noreaction" in kv:
                            q.noreactions.append(kv["noreaction"])
                        if "divide_only" in kv:
                            q.divide_only = bool(int(kv["divide_only"]))
                            if q.divide_only:
                                # fail loudly rather than silently running
                                # wrong physics: the lockstep engine
                                # evaluates tasks at IO, not at divide
                                # (cEnvironment::TestRequisites divide_only)
                                raise NotImplementedError(
                                    "requisite divide_only=1 is not "
                                    "supported by the lockstep engine yet; "
                                    "remove it or use the reference for "
                                    "this environment")
                        requisites.append(q)
                if not processes:
                    processes.append(Process())
                env.reactions.append(Reaction(name, task, processes, requisites))
            elif kind == "RESOURCE":
                for spec in tokens[1:]:
                    rname, kvs = _parse_colon_kv(spec)
                    kv = {}
                    for item in kvs:
                        if "=" in item:
                            k, v = item.split("=", 1)
                            kv[k] = v
                    env.resources.append(Resource(
                        name=rname,
                        deme=str(kv.get("demeresource", "0")).lower()
                        in ("1", "true"),
                        inflow=float(kv.get("inflow", 0.0)),
                        outflow=float(kv.get("outflow", 0.0)),
                        initial=float(kv.get("initial", 0.0)),
                        geometry=kv.get("geometry", "global"),
                        xdiffuse=float(kv.get("xdiffuse", 1.0)),
                        ydiffuse=float(kv.get("ydiffuse", 1.0)),
                        inflowx1=int(kv.get("inflowx1", -1)),
                        inflowx2=int(kv.get("inflowx2", -1)),
                        inflowy1=int(kv.get("inflowy1", -1)),
                        inflowy2=int(kv.get("inflowy2", -1)),
                    ))
            elif kind == "GRADIENT_RESOURCE":
                # moving-peak resources (cEnvironment::LoadGradientResource
                # cc:831 -> cGradientCount).  Core parameters only; halos,
                # hills, barriers and plateau depletion are future work.
                for spec in tokens[1:]:
                    rname, kvs = _parse_colon_kv(spec)
                    kv = {}
                    for item in kvs:
                        if "=" in item:
                            k, v = item.split("=", 1)
                            kv[k] = v
                    env.resources.append(Resource(
                        name=rname, geometry="grid",
                        # no stencil dynamics: the cone is recomputed each
                        # update, so diffusing these rows is wasted work
                        xdiffuse=0.0, ydiffuse=0.0,
                        height=int(float(kv.get("height", 8))),
                        spread=int(float(kv.get("spread", 10))),
                        plateau=float(kv.get("plateau", -1.0)),
                        updatestep=int(float(kv.get("updatestep", 1))),
                        peakx=int(float(kv.get("peakx", -1))),
                        peaky=int(float(kv.get("peaky", -1))),
                        move_a_scaler=float(kv.get("move_a_scaler", 1.0)),
                    ))
            # CELL / GRID -- planned
    return env


def default_logic9_environment() -> Environment:
    """The stock logic-9 environment (ref support/config/environment.cfg:15-23)."""
    env = Environment()
    spec = [("NOT", "not", 1.0), ("NAND", "nand", 1.0), ("AND", "and", 2.0),
            ("ORN", "orn", 2.0), ("OR", "or", 3.0), ("ANDN", "andn", 3.0),
            ("NOR", "nor", 4.0), ("XOR", "xor", 4.0), ("EQU", "equ", 5.0)]
    for name, task, val in spec:
        env.reactions.append(Reaction(
            name, task,
            [Process(value=val, type=PROCTYPE_POW)],
            [Requisite(max_task_count=1)],
        ))
    return env
