"""Events (experiment timeline) configuration.

Parses the reference `events.cfg` DSL (ref cEventList::LoadEventFile +
AddEventFileFormat, avida-core/source/main/cEventList.h:63,106):

    [trigger] [start[:interval[:stop]]] [action] [args...]

Triggers: `u`/`update`, `g`/`generation`, `i`/`immediate`, `b`/`births`
(cumulative birth count).  The reference's BIRTHS_INTERRUPT trigger
(cEventList.h:63) interrupts an update mid-flight when the count crosses;
the lockstep engine's update is atomic, so `births` fires at the next
update boundary instead -- a documented deviation of at most one update's
latency.  Start may be `begin`; stop may be `end`.  Actions are
dispatched by the host driver
(avida_tpu/world.py) against the action registry in avida_tpu/utils/actions.py
(ref: 418-action library, avida-core/source/actions/).
"""

from __future__ import annotations

from dataclasses import dataclass

TRIGGER_UPDATE = "update"
TRIGGER_GENERATION = "generation"
TRIGGER_IMMEDIATE = "immediate"
TRIGGER_BIRTHS = "births"

_TRIGGERS = {"u": TRIGGER_UPDATE, "update": TRIGGER_UPDATE,
             "g": TRIGGER_GENERATION, "generation": TRIGGER_GENERATION,
             "i": TRIGGER_IMMEDIATE, "immediate": TRIGGER_IMMEDIATE,
             "b": TRIGGER_BIRTHS, "births": TRIGGER_BIRTHS}

END = float("inf")


@dataclass
class Event:
    trigger: str
    start: float
    interval: float     # 0 = fire once
    stop: float
    action: str
    args: list

    def fires_at(self, t: float) -> bool:
        if t < self.start or t > self.stop:
            return False
        if self.interval <= 0:
            return t == self.start
        k = (t - self.start) / self.interval
        return abs(k - round(k)) < 1e-9


def _parse_timing(token: str):
    parts = token.split(":")
    def num(s):
        if s == "begin":
            return 0.0
        if s == "end":
            return END
        return float(s)
    start = num(parts[0])
    interval = num(parts[1]) if len(parts) > 1 else 0.0
    stop = num(parts[2]) if len(parts) > 2 else (END if len(parts) > 1 else start)
    return start, interval, stop


def parse_event_line(line: str) -> Event | None:
    line = line.split("#", 1)[0].strip()
    if not line:
        return None
    tokens = line.split()
    if tokens[0] in _TRIGGERS:
        trigger = _TRIGGERS[tokens[0]]
        tokens = tokens[1:]
    else:
        trigger = TRIGGER_IMMEDIATE
    # timing token is optional for immediate events
    start, interval, stop = 0.0, 0.0, 0.0
    if tokens and (tokens[0][0].isdigit() or tokens[0].split(":")[0] in ("begin", "end")):
        start, interval, stop = _parse_timing(tokens[0])
        tokens = tokens[1:]
    if not tokens:
        return None
    return Event(trigger, start, interval, stop, tokens[0], tokens[1:])


def load_events(path: str) -> list:
    events = []
    with open(path) as f:
        for raw in f:
            ev = parse_event_line(raw)
            if ev is not None:
                events.append(ev)
    return events
