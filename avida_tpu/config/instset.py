"""Instruction-set file loader.

Parses the reference's instset format (ref cHardwareManager::LoadInstSets,
avida-core/source/cpu/cHardwareManager.cc:58-147):

    INSTSET name:hw_type=N[:stack_size=S][:uops_per_cycle=U]
    INST inst-name [redundancy=..][:cost=..][:ft_cost=..][:prob_fail=..]...

Per-instruction parameters mirror cInstSet columns
(cHardwareManager.cc:222-230): redundancy (mutation weight), cost, ft_cost,
energy_cost, prob_fail, addl_time_cost, res_cost.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class InstSet:
    name: str
    hw_type: int
    inst_names: list
    redundancy: np.ndarray      # mutation weight per opcode
    cost: np.ndarray
    ft_cost: np.ndarray
    energy_cost: np.ndarray
    prob_fail: np.ndarray
    addl_time_cost: np.ndarray
    res_cost: np.ndarray = None  # resource-bin cost (cInstSet.h:69); the
    #                              tpu build refuses nonzero values at load
    params: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.res_cost is None:
            self.res_cost = np.zeros(len(self.inst_names), np.float64)

    @property
    def num_insts(self) -> int:
        return len(self.inst_names)

    def opcode(self, name: str) -> int:
        return self.inst_names.index(name)

    def mutation_weights(self) -> np.ndarray:
        """Normalized redundancy weights for random-instruction draws
        (ref cInstSet::GetRandomInst)."""
        w = self.redundancy.astype(np.float64)
        total = w.sum()
        if total <= 0:
            raise ValueError("instruction set has no positive redundancy")
        return w / total


def _parse_kv(parts):
    out = {}
    for p in parts:
        if "=" in p:
            k, v = p.split("=", 1)
            try:
                out[k] = float(v) if "." in v else int(v)
            except ValueError:
                out[k] = v
    return out


def load_instset(path: str) -> InstSet:
    name = "default"
    hw_type = 0
    params = {}
    names, red, cost, ftc, ec, pf, atc, rsc = ([], [], [], [], [], [], [],
                                               [])
    with open(path) as f:
        for raw in f:
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            tokens = line.split()
            if tokens[0] == "INSTSET":
                spec = tokens[1].split(":")
                name = spec[0]
                kv = _parse_kv(spec[1:])
                hw_type = int(kv.pop("hw_type", 0))
                params.update(kv)
            elif tokens[0] == "INST":
                spec = tokens[1].split(":")
                names.append(spec[0])
                kv = _parse_kv(spec[1:])
                red.append(kv.get("redundancy", 1))
                cost.append(kv.get("cost", 0))
                ftc.append(kv.get("ft_cost", 0))
                ec.append(kv.get("energy_cost", 0))
                pf.append(kv.get("prob_fail", 0.0))
                atc.append(kv.get("addl_time_cost", 0))
                rsc.append(kv.get("res_cost", 0.0))
    if not names:
        raise ValueError(f"no INST lines found in {path}")
    return InstSet(
        name=name, hw_type=hw_type, inst_names=names,
        redundancy=np.asarray(red, np.float64),
        cost=np.asarray(cost, np.int32),
        ft_cost=np.asarray(ftc, np.int32),
        energy_cost=np.asarray(ec, np.float64),
        prob_fail=np.asarray(pf, np.float64),
        addl_time_cost=np.asarray(atc, np.int32),
        res_cost=np.asarray(rsc, np.float64),
        params=params,
    )


_HEADS_DEFAULT_NAMES = [
    "nop-A", "nop-B", "nop-C",
    "if-n-equ", "if-less", "if-label",
    "mov-head", "jmp-head", "get-head", "set-flow",
    "shift-r", "shift-l", "inc", "dec", "push", "pop", "swap-stk", "swap",
    "add", "sub", "nand",
    "h-copy", "h-alloc", "h-divide",
    "IO", "h-search",
]


def default_instset() -> InstSet:
    """The stock heads_default set (ref support/config/instset-heads.cfg)."""
    return _make_set("heads_default", _HEADS_DEFAULT_NAMES)


_TRANSSMT_NAMES = [
    "Nop-A", "Nop-B", "Nop-C", "Nop-D",
    "Val-Shift-R", "Val-Shift-L", "Val-Nand", "Val-Add", "Val-Sub",
    "Val-Mult", "Val-Div", "Val-Mod", "Val-Inc", "Val-Dec",
    "SetMemory", "Inst-Read", "Inst-Write",
    "If-Equal", "If-Not-Equal", "If-Less", "If-Greater",
    "Head-Push", "Head-Pop", "Head-Move", "Search",
    "Push-Next", "Push-Prev", "Push-Comp",
    "Val-Delete", "Val-Copy", "IO", "Inject", "Divide-Erase", "Divide",
]


def transsmt_instset() -> InstSet:
    """The stock transsmt set (ref support/config/instset-transsmt.cfg,
    hw_type 2)."""
    s = _make_set("transsmt", _TRANSSMT_NAMES)
    s.hw_type = 2
    return s


_EXPERIMENTAL_NAMES = [
    # ref support/config/instset-experimental.cfg (hw_type=3)
    "nop-A", "nop-B", "nop-C", "nop-D",
    "if-n-equ", "if-less", "if-label", "mov-head", "jmp-head", "get-head",
    "label",
    "shift-r", "shift-l", "inc", "dec", "push", "pop", "swap-stk", "swap",
    "add", "sub", "nand",
    "h-copy", "h-alloc", "h-divide",
    "IO", "h-search",
]

_PRED_LOOK_NAMES = [
    # ref tests/avatars-pred_look/config/instset.cfg (hw_type=3)
    "nop-A", "nop-B", "nop-C", "nop-D", "nop-E", "nop-F", "nop-G", "nop-H",
    "inc", "dec", "IO", "if-not-0", "if-equ-0",
    "move", "rotate-x", "rotate-org-id", "look-ahead", "zero",
    "set-forage-target",
]


def experimental_instset() -> InstSet:
    """The stock experimental set (ref
    support/config/instset-experimental.cfg, hw_type 3)."""
    s = _make_set("experimental", _EXPERIMENTAL_NAMES)
    s.hw_type = 3
    return s


def pred_look_instset() -> InstSet:
    """The avatars-pred_look predator/prey sensing set (ref
    tests/avatars-pred_look/config/instset.cfg, hw_type 3)."""
    s = _make_set("pred_look", _PRED_LOOK_NAMES)
    s.hw_type = 3
    return s


def heads_sex_instset() -> InstSet:
    """The heads_sex set: heads_default with h-divide replaced by
    divide-sex (ref support/config/instset-heads-sex.cfg)."""
    names = ["divide-sex" if n == "h-divide" else n
             for n in _HEADS_DEFAULT_NAMES]
    return _make_set("heads_sex", names)


def _make_set(name: str, names) -> InstSet:
    n = len(names)
    ones = np.ones(n)
    zeros = np.zeros(n)
    return InstSet(
        name=name, hw_type=0, inst_names=list(names),
        redundancy=ones.copy(), cost=zeros.astype(np.int32),
        ft_cost=zeros.astype(np.int32), energy_cost=zeros.copy(),
        prob_fail=zeros.copy(), addl_time_cost=zeros.astype(np.int32),
    )
