"""Organism (.org) file loader.

The reference format is one instruction name per line with `#` comments
(ref support/config/default-heads.org; loaded via cInstSet name lookup).
Returns an int8 opcode array under the given instruction set.
"""

from __future__ import annotations

import numpy as np

from avida_tpu.config.instset import InstSet


def load_organism(path: str, instset: InstSet) -> np.ndarray:
    ops = []
    name_to_op = {n: i for i, n in enumerate(instset.inst_names)}
    with open(path) as f:
        for raw in f:
            line = raw.split("#", 1)[0].strip()
            if not line or line.startswith("#"):
                continue
            if line not in name_to_op:
                raise ValueError(f"unknown instruction {line!r} in {path}")
            ops.append(name_to_op[line])
    if not ops:
        raise ValueError(f"no instructions found in {path}")
    return np.asarray(ops, np.int8)
