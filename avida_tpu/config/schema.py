"""Typed configuration system for avida-tpu.

Re-expresses the reference's macro-reflected flag system (cAvidaConfig,
avida-core/source/main/cAvidaConfig.h:71-854 -- 428 vars in ~40 groups) as a
Python dataclass with the same variable names, defaults and `avida.cfg` file
format, so reference config files load unmodified.  Command-line `-set NAME
VALUE` overrides mirror Avida::Util::ProcessCmdLineArgs
(avida-core/source/util/CmdLine.cc:205).

Only a subset of variables is interpreted by the engine today; unknown
variables found in a config file are retained in `extras` (and warn once) so
that round-tripping and forward-compat both work.
"""

from __future__ import annotations

import dataclasses
import warnings
from dataclasses import dataclass, field, fields


def _parse_scalar(text: str):
    text = text.strip()
    try:
        return int(text)
    except ValueError:
        pass
    try:
        return float(text)
    except ValueError:
        pass
    return text


@dataclass
class AvidaConfig:
    # --- General group (cAvidaConfig.h:283+) ---
    VERBOSITY: int = 1
    RANDOM_SEED: int = -1
    SPECULATIVE: int = 1            # subsumed by lockstep batching on TPU
    POPULATION_CAP: int = 0
    POP_CAP_ELDEST: int = 0

    # --- World/topology ---
    WORLD_X: int = 60
    WORLD_Y: int = 60
    WORLD_GEOMETRY: int = 2         # nGeometry.h:30-37: 1=grid, 2=torus,
                                    # 3=clique, 4=hex, 6=lattice(z=1),
                                    # 7=random-connected, 8=scale-free
    SCALE_FREE_M: int = 3           # connections per new cell (geometry 8)
    SCALE_FREE_ALPHA: float = 1.0   # attachment power (1=linear)
    SCALE_FREE_ZERO_APPEAL: float = 0.0  # appeal of zero-degree cells
    # --- energy model (cAvidaConfig.h:649-667) ---
    ENERGY_ENABLED: int = 0
    ENERGY_GIVEN_ON_INJECT: float = 0.0
    ENERGY_GIVEN_AT_BIRTH: float = 0.0
    FRAC_PARENT_ENERGY_GIVEN_TO_ORG_AT_BIRTH: float = 0.5
    FRAC_ENERGY_DECAY_AT_ORG_BIRTH: float = 0.0
    ENERGY_CAP: float = -1.0
    NUM_CYCLES_EXC_BEFORE_0_ENERGY: int = 200
    FIX_METABOLIC_RATE: float = -1.0
    DISPERSAL_RATE: float = 1.0

    # --- File paths ---
    DATA_DIR: str = "data"
    EVENT_FILE: str = "events.cfg"
    ANALYZE_FILE: str = "analyze.cfg"
    ENVIRONMENT_FILE: str = "environment.cfg"

    # --- Mutation rates (cAvidaConfig.h mutation group) ---
    COPY_MUT_PROB: float = 0.0075
    COPY_INS_PROB: float = 0.0
    COPY_DEL_PROB: float = 0.0
    COPY_UNIFORM_PROB: float = 0.0
    COPY_SLIP_PROB: float = 0.0
    POINT_MUT_PROB: float = 0.0
    POINT_INS_PROB: float = 0.0
    POINT_DEL_PROB: float = 0.0
    DIV_MUT_PROB: float = 0.0
    DIV_INS_PROB: float = 0.0
    DIV_DEL_PROB: float = 0.0
    DIV_SLIP_PROB: float = 0.0
    DIVIDE_MUT_PROB: float = 0.0
    DIVIDE_INS_PROB: float = 0.05
    DIVIDE_DEL_PROB: float = 0.05
    DIVIDE_UNIFORM_PROB: float = 0.0
    DIVIDE_SLIP_PROB: float = 0.0
    INJECT_INS_PROB: float = 0.0
    INJECT_DEL_PROB: float = 0.0
    INJECT_MUT_PROB: float = 0.0
    PARENT_MUT_PROB: float = 0.0
    SLIP_FILL_MODE: int = 0
    MUT_RATE_SOURCE: int = 1

    # --- Birth / divide ---
    DIVIDE_FAILURE_RESETS: int = 0
    BIRTH_METHOD: int = 0           # 0=random in neighborhood (Definitions.h:67-82)
    PREFER_EMPTY: int = 1
    ALLOW_PARENT: int = 1
    DEATH_PROB: float = 0.0
    DEATH_METHOD: int = 2           # 2=die at genome_length*AGE_LIMIT insts
    AGE_LIMIT: int = 20
    AGE_DEVIATION: int = 0
    JUV_PERIOD: int = 0
    ALLOC_METHOD: int = 0           # 0=fill with default inst (op 0)
    DIVIDE_METHOD: int = 1          # 1=SPLIT: parent reset (2 offspring)
    EPIGENETIC_METHOD: int = 0
    GENERATION_INC_METHOD: int = 1  # both parent+child
    RESET_INPUTS_ON_DIVIDE: int = 0
    INHERIT_MERIT: int = 1
    INHERIT_MULTITHREAD: int = 0

    # --- Divide restrictions ---
    OFFSPRING_SIZE_RANGE: float = 2.0
    MIN_COPIED_LINES: float = 0.5
    MIN_EXE_LINES: float = 0.5
    MIN_GENOME_SIZE: int = 0
    MAX_GENOME_SIZE: int = 0
    MIN_CYCLES: int = 0
    REQUIRE_ALLOCATE: int = 1
    REQUIRED_TASK: int = -1
    REQUIRED_REACTION: int = -1
    REQUIRE_SINGLE_REACTION: int = 0
    REQUIRED_BONUS: float = 0.0
    REQUIRE_EXACT_COPY: int = 0

    # --- Recombination (sex) ---
    RECOMBINATION_PROB: float = 1.0
    MAX_BIRTH_WAIT_TIME: int = -1
    MODULE_NUM: int = 0
    CONT_REC_REGS: int = 1
    CORESPOND_REC_REGS: int = 1
    TWO_FOLD_COST_SEX: int = 0
    SAME_LENGTH_SEX: int = 0

    # --- Reversion/sterilization ---
    REVERT_FATAL: float = 0.0
    REVERT_DETRIMENTAL: float = 0.0
    REVERT_NEUTRAL: float = 0.0
    REVERT_BENEFICIAL: float = 0.0
    STERILIZE_FATAL: float = 0.0
    STERILIZE_DETRIMENTAL: float = 0.0
    STERILIZE_NEUTRAL: float = 0.0
    STERILIZE_BENEFICIAL: float = 0.0
    STERILIZE_UNSTABLE: int = 0

    # --- Time slicing (cAvidaConfig.h:544-561) ---
    AVE_TIME_SLICE: int = 30
    SLICING_METHOD: int = 1         # 0=const, 1=prob∝merit, 2=integrated
    BASE_MERIT_METHOD: int = 4      # 4=min(full, copied, executed)
    BASE_CONST_MERIT: int = 100
    DEFAULT_BONUS: float = 1.0
    MERIT_DEFAULT_BONUS: float = 0.0
    MERIT_INC_APPLY_IMMEDIATE: int = 0
    MAX_CPU_THREADS: int = 1
    THREAD_SLICING_METHOD: int = 0
    NO_CPU_CYCLE_TIME: int = 0
    MAX_LABEL_EXE_SIZE: int = 1

    # --- Hardware ---
    HARDWARE_TYPE: int = 0
    INST_SET: str = "-"
    INSTSET: str = "-"              # alias used by some configs

    # --- Test CPU ---
    TEST_CPU_TIME_MOD: int = 20

    # --- Demes ---
    NUM_DEMES: int = 1
    DEMES_USE_GERMLINE: int = 0
    DEMES_COMPETITION_STYLE: int = 0
    DEMES_TOURNAMENT_SIZE: int = 0
    GERMLINE_COPY_MUT: float = 0.0075
    DEMES_MAX_AGE: int = 500
    DEMES_MAX_BIRTHS: int = 100
    DEMES_MIGRATION_RATE: float = 0.0
    # --- Mating types / birth chamber (cAvidaConfig.h:427-440) ---
    MATING_TYPES: int = 0            # 0=off, 1=male/female pairing
    LEKKING: int = 0                 # males always wait in the chamber
    # (MODULE_NUM / CONT_REC_REGS / CORESPOND_REC_REGS live in the
    # Recombination block above)
    # --- Predator-prey (cAvidaConfig.h:814-819) ---
    PRED_PREY_SWITCH: int = -1       # -1 = no predation
    PRED_EFFICIENCY: float = 1.0
    DEMES_MIGRATION_METHOD: int = 0  # 0=any, 1=8-neighbor deme grid,
    #                                  2=list-adjacent, 4=MIGRATION_FILE matrix
    DEMES_NUM_X: int = 0             # deme-grid width for method 1
    MIGRATION_FILE: str = "-"        # DxD weight matrix for method 4

    # --- Energy model (off by default) ---
    ENERGY_ENABLED: int = 0

    # --- Parasites ---
    INJECT_METHOD: int = 0
    INFECTION_MECHANISM: int = 0
    PARASITE_VIRULENCE: float = -1.0
    PARASITE_MEM_SPACES: int = 1

    # ---- TPU-build specific knobs (no reference equivalent) ----
    # Hard cap on the per-organism memory buffer (the reference's
    # MAX_GENOME_LENGTH analogue, but this one sizes HBM tensors).
    TPU_MAX_MEMORY: int = 384
    # Safety cap on lockstep micro-steps per update (0 = uncapped: run to the
    # max sampled budget).  Uncapped matches reference scheduling semantics.
    TPU_MAX_STEPS_PER_UPDATE: int = 0
    # float dtype for merit/bonus math ("float32" is plenty: max bonus 2^25).
    TPU_FLOAT_DTYPE: str = "float32"
    # Pallas VMEM-resident cycle kernel (ops/pallas_cycles.py): 0 = auto
    # (use on TPU when the environment qualifies), 1 = force on (any
    # backend; interpret mode off-TPU), 2 = off (always XLA micro-steps).
    TPU_USE_PALLAS: int = 0
    # Budget-aware lane packing for the Pallas kernel (ops/pallas_cycles.py):
    # organisms are permuted into kernel lanes sorted by granted budget so
    # each block's while_loop runs close to its MEAN budget instead of its
    # max (the ~1.55x budget-tail waste; observability/counters.budget_tail).
    # Value = refresh period K in updates: the persistent permutation is
    # recomputed every K updates (K=1: re-sorted by this update's granted
    # vector -- the exact tail fix; K>1: sorted by merit, amortizing the
    # sort, with binomial budget noise left in the tail).  0 = off
    # (identity lanes).  The permutation rides pack/unpack as major-axis
    # row gathers -- NOT the lane-axis packed-state permute that was
    # reverted in rounds 4/5.
    TPU_LANE_PERM: int = 1
    # With TPU_LANE_PERM > 1: also refresh the permutation early whenever
    # the measured per-block budget utilization (granted.sum / lockstep
    # ceiling) of the CURRENT permutation falls below this threshold.
    TPU_LANE_PERM_MIN_UTIL: float = 0.5
    # Kernel launch sharding: the Pallas cycle kernel is shard_map'd over
    # the `cells` mesh axis (parallel/mesh.py), one independent launch per
    # shard (blocks never communicate, so the split is free).  0 = auto
    # (one shard per visible device -- single-device runs are unsharded),
    # N > 0 = exactly N shards (must not exceed the device count; tests
    # use 1 to force the unsharded reference trajectory).
    TPU_KERNEL_SHARDS: int = 0
    # Packed-resident update chunk (ops/packed_chunk.py; round 6): keep
    # the population in the Pallas kernel's [LP, N] plane layout across
    # a WHOLE update_scan chunk -- pack once, run the chunk's updates
    # with the packed-native birth flush (lane-axis rolls on the word
    # planes; ops/birth.flush_births_packed), unpack once at the chunk
    # boundary where checkpoints / trace drains / .dat readbacks already
    # synchronize.  1 = auto: engaged whenever the configuration
    # qualifies (Pallas path + torus birth fast path + asexual + no
    # demes/energy/caps/point-or-slip mutations/resource pools and
    # TPU_SYSTEMATICS=0 -- see packed_chunk.active).  0 = off: the
    # per-update pack/unpack path with TPU_LANE_PERM budget packing (the
    # round-5 engine, byte-identical behavior).  When active, the
    # resident planes are CELL-ordered, so the budget-sort lane
    # permutation is superseded (identity lanes); the budget tail is
    # attacked in-kernel instead (TPU_KERNEL_ROWSKIP row-tile skipping +
    # the per-block while_loop early exit).
    TPU_PACKED_CHUNK: int = 1
    # Fused packed-resident update (ops/packed_chunk.py; round 14): run
    # the cheap per-update phases (schedule, bank, stats) as ROW-SPACE
    # ops directly on the resident [rows, N] planes instead of
    # rebuilding the full WorldState inside the scan body, so a chunk
    # is pack-once -> scan{row phases + kernel + packed flush} ->
    # unpack-once with no full-state unpack between updates.  1 = auto:
    # engaged whenever the packed chunk itself is active and the flight
    # recorder is off (packed_chunk.fused_ineligible_reason).  0 = the
    # legacy row-space path that refreshes the canonical mirrors every
    # update (round-6..13 engine, byte-identical trajectories either
    # way -- the fused path is bit-exact by construction and gated by
    # tests/test_packed_fused.py).  Program-affecting and STATIC: a
    # serve batch must not mix values (see serve.NONSTATIC_VARS note).
    TPU_PACKED_FUSED: int = 1
    # Bit-packed resident genome plane (ops/pallas_cycles.py 5-bit
    # codec; round 14): store the genome shadow plane as 5-bit opcodes
    # packed 6-per-int32-word (ceil(L/6) rows) instead of 4 opcode
    # bytes per word (L/4 rows) -- a ~34% cut in the genome plane's HBM
    # residency at TPU_MAX_MEMORY=384 (256B -> 256B vs 384B per
    # organism; see README plane-width table).  Only the genome shadow
    # narrows: the kernel never reads it (tape/offspring planes keep
    # the byte layout the kernel's SWAR decode indexes).  Requires the
    # instruction set to fit 5-bit codes (num_insts <= 32 --
    # packed_chunk.bits_ineligible_reason is loud otherwise).  Packing
    # happens at chunk boundaries only; trajectories and checkpoint
    # bytes are identical on or off (tests/test_packed_fused.py).
    # Default off pending device-scale soak.  Program-affecting and
    # STATIC, like TPU_PACKED_FUSED.
    TPU_PACKED_BITS: int = 0
    # Persistent AOT program cache (utils/compilecache.py): 1 = the
    # engine's compiled scan programs (update_scan / multiworld_scan)
    # are AOT-serialized into an on-disk store and deserialized in
    # milliseconds by later processes with the same static config --
    # a cold-spawned serve/fleet child skips the ~25-40s compile
    # window.  0 is a HARD kill switch (the env var TPU_COMPILE_CACHE=0
    # kills it too); entries are CRC-manifested and any toolchain or
    # code drift falls back loudly to a fresh trace.  This is NOT
    # JAX_COMPILATION_CACHE_DIR (which corrupts resumed runs on this
    # toolchain -- README "Known landmines"): it is avida-tpu's own
    # store with its own integrity checks.
    TPU_COMPILE_CACHE: int = 1
    # Cache root directory ("-" = resolve from the TPU_COMPILE_CACHE_DIR
    # env var, else ~/.cache/avida_tpu/compile).  The fleet orchestrator
    # points children at SPOOL/compile-cache so one class child's
    # compile warms every sibling.
    TPU_COMPILE_CACHE_DIR: str = "-"
    # Runtime telemetry (avida_tpu/observability/): 1 = phase-fenced
    # staged updates, device counters and a telemetry.jsonl run log in
    # DATA_DIR.  Opt-in: 0 (default) compiles to the identical update
    # program as before the subsystem existed (tests/test_telemetry.py)
    # and writes no files.  Telemetry forces per-update host dispatch
    # (no update_scan chunking) and fences every phase, so expect the
    # run to be slower -- it trades throughput for attribution.
    TPU_TELEMETRY: int = 0
    # Where `jax.profiler` traces go when telemetry is on ("-" = no trace
    # capture).  The first TPU_PROFILE_UPDATES updates are captured.
    TPU_PROFILE_DIR: str = "-"
    TPU_PROFILE_UPDATES: int = 3
    # Native bit-exact checkpoints (utils/checkpoint.py): directory for
    # rolling ckpt-<update> generations ("-" = checkpointing off).  With a
    # directory set, World.run installs SIGTERM/SIGINT handlers that stop
    # at the next update-chunk boundary, save a final checkpoint and
    # return cleanly (preemption handling); World.resume() restores the
    # newest valid generation bit-exactly (falling back past corrupt
    # ones via the per-array CRC manifest).
    TPU_CKPT_DIR: str = "-"
    # Auto-save period in updates (0 = save only on preemption; requires
    # TPU_CKPT_DIR).  Saves land at update-chunk boundaries, so the
    # actual spacing can overshoot by up to one chunk (<= 128 updates).
    TPU_CKPT_EVERY: int = 0
    # Rolling retention: how many checkpoint generations to keep.
    TPU_CKPT_KEEP: int = 2
    # State invariant auditor (utils/audit.py): run audit_state every K
    # updates inside World.run (0 = only at checkpoint save/load).  A
    # violation raises StateInvariantError naming the broken invariant.
    TPU_AUDIT_EVERY: int = 0
    # Silent-corruption integrity plane (ops/digest.py +
    # utils/integrity.py; README "Integrity plane").  TPU_STATE_DIGEST=1
    # computes an order-stable u32 tree digest of the full
    # PopulationState at every update-chunk boundary -- into the
    # checkpoint manifest (`state_digest`, re-verified by --resume /
    # ckpt_tool --verify), the metrics.prom heartbeat
    # (avida_state_digest) and DATA_DIR/integrity.jsonl.  Default 0:
    # nothing is built or traced, zero cost; either way the update
    # program itself is byte-identical (the digest is a SEPARATE jit,
    # the audit_state isolation rule).
    TPU_STATE_DIGEST: int = 0
    # Sampled shadow re-execution (scrubbing): every K-th update chunk
    # is re-executed from the retained pre-chunk state and the two
    # digests compared -- on this deterministic engine any mismatch is
    # silent data corruption (StateDivergenceError, child exit 67, the
    # supervisor's `sdc` rollback).  K=1 is full lockstep redundancy
    # (~2x chunk cost); larger K samples 1/K of chunks.  Default 0 =
    # off.  Implies manifest digests at checkpoint saves.
    TPU_SCRUB_EVERY: int = 0
    # Device-side flight recorder (observability/tracer.py): 1 = record
    # structured events (births/deaths, first task triggers, scheduler
    # stalls, state anomalies) into fixed-capacity ring buffers INSIDE
    # the jitted update, drained to {"record":"trace"} runlog lines only
    # at update-chunk boundaries (no mid-chunk host sync).  Opt-in: 0
    # (default) adds no state and traces the identical update program
    # (scripts/check_jaxpr.py digest unchanged); 1 leaves the evolved
    # trajectory bit-identical (the ring is append-only side state).
    TPU_TRACE: int = 0
    # Ring capacity in events.  Overflow drops the OLDEST events and
    # counts the drops (reported on the drain record) -- it never forces
    # an early host sync.  Size for the busiest expected window: roughly
    # (births + deaths + first-task triggers) per update x updates per
    # chunk (<= 128).
    TPU_TRACE_CAP: int = 4096
    # Emit a scheduler-stall event when the lockstep block utilization of
    # the granted budget vector falls below this fraction.
    TPU_TRACE_STALL_UTIL: float = 0.25
    # Prometheus-style metrics textfile (observability/exporter.py):
    # 1 = rewrite DATA_DIR/metrics.prom atomically at every update-chunk
    # boundary (tmp + rename, like checkpoints) so an external scraper /
    # `python -m avida_tpu --status DIR` can watch a live run.  Implied
    # by TPU_TRACE=1.
    TPU_METRICS: int = 0
    # Telemetry history rings (observability/history.py): every .prom
    # publish also appends one compact sample row -- wall time, update,
    # every family value -- to a bounded `.hist.jsonl` ring beside the
    # snapshot (rotation pair, non-durable appends: the zero-sync
    # pipeline is never fenced).  The rings feed the alert plane
    # (observability/alerts.py), the `--status` rate line and
    # `scripts/metrics_tool.py query`.  Host-side only: trajectories
    # are bit-identical on or off.  The environment spelling of these
    # knobs wins over the config file so operators can flip fleets.
    TPU_METRICS_HIST: int = 1
    # Sample every K-th publish (1 = heartbeat cadence).
    TPU_METRICS_HIST_EVERY: int = 1
    # Ring rotation cap in bytes per file (the live + `.1` pair bounds
    # disk at twice this).
    TPU_METRICS_HIST_MAX_BYTES: int = 4 << 20
    # Device performance attribution plane (observability/profiler.py;
    # README "Performance attribution").  TPU_PROFILE=1 -- config OR
    # environment, the TPU_STATE_DIGEST arming pattern -- arms per-chunk
    # attribution on the scanned-chunk path: unfenced chunk walls every
    # chunk, a FENCED staged phase probe + per-leaf resident-footprint
    # accounting on the first chunk and every TPU_PROFILE_EVERY-th
    # after, per-program XLA cost/memory analysis captured at
    # compile/cache-load time (utils/compilecache.py).  Lands as
    # avida_perf_* exposition families, {"record":"perf"} lines in
    # DATA_DIR/perf.jsonl and a `--status` perf block.  Probes run on
    # device-owned COPIES of the state: trajectories are bit-identical
    # on or off; default 0 builds, fences and writes nothing.  NOT the
    # telemetry jax.profiler knobs (TPU_PROFILE_DIR/TPU_PROFILE_UPDATES
    # above): this plane rides the chunked path telemetry cannot.
    TPU_PROFILE: int = 0
    # Fenced-probe cadence in chunks (0 = first chunk only; env wins,
    # the history-knob operator convention).
    TPU_PROFILE_EVERY: int = 16
    # 1 = the first fenced probe also captures a jax.profiler trace of
    # its staged phases into DATA_DIR/profiles/ (XProf-loadable).
    TPU_PROFILE_TRACE: int = 0

    # In-run analytics (analyze/pipeline.py): 1 = refresh an incremental
    # phenotype census + dominant-lineage replay at checkpoint
    # boundaries and run exit (needs TPU_CKPT_DIR/TPU_CKPT_EVERY for the
    # mid-run cadence), publishing DATA_DIR/analytics.prom and
    # DATA_DIR/analysis/analytics.jsonl for `--status` and the fleet
    # view.  Host-side only: trajectories are bit-identical on or off.
    TPU_ANALYTICS: int = 0
    # Live-mode knockout sweeps over the top-N genotypes per refresh
    # (0 = census/lineage only; sweeps cost one sandbox evaluation per
    # genome site -- memoized by genome content, so a stable dominant
    # only pays once -- and are opt-in while the run is alive).
    TPU_ANALYTICS_KNOCKOUT_TOP: int = 0
    # Sandbox PRNG seed for the live census/knockout evaluations (the
    # offline CLI's --seed); per-lane inputs are counter-stable, so a
    # given (seed, genotype) always scores identically.
    TPU_ANALYTICS_SEED: int = 0

    extras: dict = field(default_factory=dict)

    _FIELD_NAMES = None  # class-level cache

    @classmethod
    def field_names(cls):
        if cls._FIELD_NAMES is None:
            cls._FIELD_NAMES = {f.name for f in fields(cls) if f.name != "extras"}
        return cls._FIELD_NAMES

    def set(self, name: str, value):
        """Apply one NAME VALUE pair (file line or -set override)."""
        if name in self.field_names():
            cur = getattr(self, name)
            if isinstance(cur, str):
                setattr(self, name, str(value))
            elif isinstance(cur, float):
                setattr(self, name, float(value))
            else:
                setattr(self, name, int(float(value)))
        else:
            self.extras[name] = value

    def get(self, name: str, default=None):
        if name in self.field_names():
            return getattr(self, name)
        return self.extras.get(name, default)

    def copy(self) -> "AvidaConfig":
        c = dataclasses.replace(self)
        c.extras = dict(self.extras)
        return c


def load_avida_cfg(path: str, overrides=None) -> AvidaConfig:
    """Parse an avida.cfg file (ref format: cAvidaConfig::Load, cAvidaConfig.cc:64).

    Lines are `NAME VALUE  # comment`.  `overrides` is a list of (name, value)
    applied after the file, mirroring `-set NAME VALUE`.
    """
    cfg = AvidaConfig()
    seen_unknown = set()
    with open(path) as f:
        for raw in f:
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split(None, 1)
            if len(parts) != 2:
                continue
            name, value = parts[0], _parse_scalar(parts[1])
            if name == "VERSION_ID":
                continue
            if name not in AvidaConfig.field_names() and name not in seen_unknown:
                seen_unknown.add(name)
            cfg.set(name, value)
    if seen_unknown:
        warnings.warn(
            "avida.cfg variables not yet interpreted by avida-tpu (kept in "
            f"extras): {sorted(seen_unknown)}", stacklevel=2)
    for name, value in (overrides or []):
        cfg.set(name, _parse_scalar(str(value)))
    return cfg
