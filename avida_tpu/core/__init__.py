from avida_tpu.core.state import PopulationState, WorldParams, init_population

__all__ = ["PopulationState", "WorldParams", "init_population"]
