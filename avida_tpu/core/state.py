"""Population state: structure-of-arrays tensors for the whole world.

This is the TPU-native replacement for the reference's object graph
(cPopulation -> cPopulationCell -> cOrganism -> {cHardwareCPU, cPhenotype};
see SURVEY.md §7 state layout).  One array slot per grid cell (the reference
is also cell-capacity-bounded: one organism per cell, cPopulation.cc:323), so
placement is a scatter and the `alive` mask defines occupancy.

All fields are batched over N = WORLD_X * WORLD_Y.  Organism-level fields
mirror cHardwareCPU state (cHardwareCPU.h:61-152) and cPhenotype bookkeeping
(cPhenotype.h:97-216).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct



class WorldParams(struct.PyTreeNode):
    """Static (hashable) parameters baked into the jitted update step.

    Everything here is a Python scalar / tuple, marked as pytree metadata, so
    a config change triggers recompilation (acceptable: configs are per-run).
    """
    # hardware backend (cHardwareManager factory; models/registry.py)
    hw_type: int = struct.field(pytree_node=False, default=0)
    num_registers: int = struct.field(pytree_node=False, default=3)
    num_nops: int = struct.field(pytree_node=False, default=3)
    # parasites (TransSMT; cHardwareTransSMT.cc:218-248)
    parasite_virulence: float = struct.field(pytree_node=False, default=-1.0)
    # world shape
    world_x: int = struct.field(pytree_node=False, default=60)
    world_y: int = struct.field(pytree_node=False, default=60)
    geometry: int = struct.field(pytree_node=False, default=2)  # 1=grid, 2=torus
    # memory / genome caps
    max_memory: int = struct.field(pytree_node=False, default=384)
    min_genome_len: int = struct.field(pytree_node=False, default=8)
    # instruction set (semantic tables as tuples for hashability)
    num_insts: int = struct.field(pytree_node=False, default=26)
    sem: tuple = struct.field(pytree_node=False, default=())
    mod_kind: tuple = struct.field(pytree_node=False, default=())
    default_op: tuple = struct.field(pytree_node=False, default=())
    is_nop: tuple = struct.field(pytree_node=False, default=())
    nop_mod: tuple = struct.field(pytree_node=False, default=())
    # per-instruction redundancy (mutation weight) as a cumulative
    # distribution, and execution costs (cInstSet columns; cHardwareBase
    # SingleProcess_PayPreCosts cc:1241).  Empty cost tuples = all zero.
    mut_cdf: tuple = struct.field(pytree_node=False, default=())
    inst_cost: tuple = struct.field(pytree_node=False, default=())
    inst_ft_cost: tuple = struct.field(pytree_node=False, default=())
    # per-opcode execution-failure probability / extra time_used charge
    # (cInstSet.h:66,67 prob_fail + addl_time_cost; cHardwareCPU.cc:985-1015)
    inst_prob_fail: tuple = struct.field(pytree_node=False, default=())
    inst_addl_time_cost: tuple = struct.field(pytree_node=False, default=())
    # mutation rates
    copy_mut_prob: float = struct.field(pytree_node=False, default=0.0075)
    copy_ins_prob: float = struct.field(pytree_node=False, default=0.0)
    copy_del_prob: float = struct.field(pytree_node=False, default=0.0)
    divide_mut_prob: float = struct.field(pytree_node=False, default=0.0)
    divide_ins_prob: float = struct.field(pytree_node=False, default=0.05)
    divide_del_prob: float = struct.field(pytree_node=False, default=0.05)
    div_mut_prob: float = struct.field(pytree_node=False, default=0.0)   # per-site
    divide_slip_prob: float = struct.field(pytree_node=False, default=0.0)
    point_mut_prob: float = struct.field(pytree_node=False, default=0.0)
    # divide restrictions
    offspring_size_range: float = struct.field(pytree_node=False, default=2.0)
    recombination_prob: float = struct.field(pytree_node=False, default=1.0)
    min_copied_lines: float = struct.field(pytree_node=False, default=0.5)
    min_exe_lines: float = struct.field(pytree_node=False, default=0.5)
    require_allocate: bool = struct.field(pytree_node=False, default=True)
    # scheduling
    ave_time_slice: int = struct.field(pytree_node=False, default=30)
    slicing_method: int = struct.field(pytree_node=False, default=1)
    base_merit_method: int = struct.field(pytree_node=False, default=4)
    base_const_merit: int = struct.field(pytree_node=False, default=100)
    default_bonus: float = struct.field(pytree_node=False, default=1.0)
    inherit_merit: bool = struct.field(pytree_node=False, default=True)
    max_steps_per_update: int = struct.field(pytree_node=False, default=0)
    use_pallas: int = struct.field(pytree_node=False, default=0)
    # budget-aware kernel lane packing: refresh period K of the persistent
    # lane permutation (0 = off; see TPU_LANE_PERM in config/schema.py)
    lane_perm_k: int = struct.field(pytree_node=False, default=0)
    lane_perm_min_util: float = struct.field(pytree_node=False, default=0.5)
    # kernel launch sharding over the cells mesh axis (0 = auto: every
    # visible device; see TPU_KERNEL_SHARDS in config/schema.py)
    kernel_shards: int = struct.field(pytree_node=False, default=0)
    # packed-resident update chunk (ops/packed_chunk.py): keep the
    # population in the kernel's [LP, N] plane layout across a whole
    # update_scan chunk, with the packed-native birth flush; unpack only
    # at chunk boundaries.  1 = auto (on whenever the configuration
    # qualifies -- packed_chunk.active), 0 = off (per-update pack/unpack
    # with budget-sort lane packing, the round-5 engine).  When active
    # it supersedes lane_perm_k: resident planes are cell-ordered
    # (lane_perm stays identity; see TPU_PACKED_CHUNK in config/schema)
    packed_chunk: int = struct.field(pytree_node=False, default=1)
    # fused packed-resident update: run schedule/bank/stats as row-space
    # ops on the resident planes, no full-state unpack inside the scan
    # (1 = auto -- see TPU_PACKED_FUSED; 0 = refresh canonical mirrors
    # every update, the round-6..13 row-space path)
    packed_fused: int = struct.field(pytree_node=False, default=1)
    # bit-packed genome shadow plane: 5-bit opcodes, 6 per int32 word
    # (0 = off, byte planes everywhere; see TPU_PACKED_BITS -- needs
    # num_insts <= 32, packed_chunk.bits_ineligible_reason)
    packed_bits: int = struct.field(pytree_node=False, default=0)
    # energy model (cPhenotype energy store; cAvidaConfig.h:649-667)
    energy_enabled: bool = struct.field(pytree_node=False, default=False)
    energy_given_on_inject: float = struct.field(pytree_node=False, default=0.0)
    energy_given_at_birth: float = struct.field(pytree_node=False, default=0.0)
    frac_parent_energy: float = struct.field(pytree_node=False, default=0.5)
    frac_energy_decay_birth: float = struct.field(pytree_node=False, default=0.0)
    energy_cap: float = struct.field(pytree_node=False, default=-1.0)
    num_cycles_exc: int = struct.field(pytree_node=False, default=200)
    fix_metabolic_rate: float = struct.field(pytree_node=False, default=-1.0)
    inst_energy_cost: tuple = struct.field(pytree_node=False, default=())
    dispersal_rate: float = struct.field(pytree_node=False, default=1.0)
    # systematics: device-side newborn ring buffer (chunked-run phylogeny
    # ingestion; 0 = off)
    nb_cap: int = struct.field(pytree_node=False, default=0)
    # flight recorder (observability/tracer.py): capacity of the in-state
    # event ring (0 = recorder off -- no ring arrays, no emission traced,
    # update_step jaxpr unchanged; see TPU_TRACE / TPU_TRACE_CAP)
    trace_cap: int = struct.field(pytree_node=False, default=0)
    # emit a scheduler-stall event when the lockstep block utilization of
    # the granted budget vector drops below this fraction
    trace_stall_util: float = struct.field(pytree_node=False, default=0.25)
    # deterministic device-side fault injection (utils/faultinject.py
    # `nan:` kind): (leaf_name, cell, update) -- () = off, and the
    # update_step jaxpr is unchanged (same static-gate discipline as
    # trace_cap; chaos tests only, never set in production)
    fault_nan: tuple = struct.field(pytree_node=False, default=())
    # `bitflip:` kind -- the modeled silent-data-corruption event:
    # (leaf_name, cell, bit, update), () = off with the jaxpr unchanged.
    # The flip stays finite/in-bounds (invisible to audit_state); only
    # the integrity plane's shadow re-execution catches it, because the
    # shadow replay strips this gate (World._shadow_params)
    fault_bitflip: tuple = struct.field(pytree_node=False, default=())
    # intra-organism threads (cAvidaConfig.h:558-564)
    max_cpu_threads: int = struct.field(pytree_node=False, default=1)
    thread_slicing_method: int = struct.field(pytree_node=False, default=0)
    # death
    death_method: int = struct.field(pytree_node=False, default=2)
    age_limit: int = struct.field(pytree_node=False, default=20)
    # demes (cDeme / cPopulation::CompeteDemes; SURVEY §2d)
    num_demes: int = struct.field(pytree_node=False, default=1)
    demes_use_germline: int = struct.field(pytree_node=False, default=0)
    germline_copy_mut: float = struct.field(pytree_node=False, default=0.0075)
    demes_max_age: int = struct.field(pytree_node=False, default=500)
    demes_max_births: int = struct.field(pytree_node=False, default=100)
    demes_migration_rate: float = struct.field(pytree_node=False, default=0.0)
    demes_migration_method: int = struct.field(pytree_node=False, default=0)
    mating_types: bool = struct.field(pytree_node=False, default=False)
    lekking: bool = struct.field(pytree_node=False, default=False)
    module_num: int = struct.field(pytree_node=False, default=0)
    pred_prey_switch: int = struct.field(pytree_node=False, default=-1)
    pred_efficiency: float = struct.field(pytree_node=False, default=1.0)
    demes_num_x: int = struct.field(pytree_node=False, default=0)
    # method-4 per-source-deme cumulative weights, tuple[D] of tuple[D]
    migration_cdf: tuple = struct.field(pytree_node=False, default=())
    # birth
    birth_method: int = struct.field(pytree_node=False, default=0)
    population_cap: int = struct.field(pytree_node=False, default=0)
    pop_cap_eldest: int = struct.field(pytree_node=False, default=0)
    prefer_empty: bool = struct.field(pytree_node=False, default=True)
    allow_parent: bool = struct.field(pytree_node=False, default=True)
    divide_method: int = struct.field(pytree_node=False, default=1)
    generation_inc_method: int = struct.field(pytree_node=False, default=1)
    # environment (task/reaction tables, as tuples of tuples)
    num_reactions: int = struct.field(pytree_node=False, default=9)
    task_logic_mask: tuple = struct.field(pytree_node=False, default=())
    proc_value: tuple = struct.field(pytree_node=False, default=())
    proc_type: tuple = struct.field(pytree_node=False, default=())
    max_task_count: tuple = struct.field(pytree_node=False, default=())
    min_task_count: tuple = struct.field(pytree_node=False, default=())
    req_reaction_mask: tuple = struct.field(pytree_node=False, default=())
    noreq_reaction_mask: tuple = struct.field(pytree_node=False, default=())
    task_math_name: tuple = struct.field(pytree_node=False, default=())
    # reaction -> resource bindings (cReactionProcess)
    proc_res_idx: tuple = struct.field(pytree_node=False, default=())
    proc_res_spatial: tuple = struct.field(pytree_node=False, default=())
    proc_max: tuple = struct.field(pytree_node=False, default=())
    proc_frac: tuple = struct.field(pytree_node=False, default=())
    proc_depletable: tuple = struct.field(pytree_node=False, default=())
    # reaction by-products (DoProcesses cc:1824-1830): produced into the
    # pool = consumed * conversion
    proc_product_idx: tuple = struct.field(pytree_node=False, default=())
    proc_product_spatial: tuple = struct.field(pytree_node=False, default=())
    proc_conversion: tuple = struct.field(pytree_node=False, default=())
    # per-deme resource pools (cDeme resource slice; cResource deme flag)
    num_deme_res: int = struct.field(pytree_node=False, default=0)
    dres_inflow: tuple = struct.field(pytree_node=False, default=())
    dres_outflow: tuple = struct.field(pytree_node=False, default=())
    dres_initial: tuple = struct.field(pytree_node=False, default=())
    proc_res_deme: tuple = struct.field(pytree_node=False, default=())
    # global resource pools (cResourceCount)
    num_global_res: int = struct.field(pytree_node=False, default=0)
    res_inflow: tuple = struct.field(pytree_node=False, default=())
    res_outflow: tuple = struct.field(pytree_node=False, default=())
    res_initial: tuple = struct.field(pytree_node=False, default=())
    # spatial resources (cSpatialResCount)
    num_spatial_res: int = struct.field(pytree_node=False, default=0)
    sres_inflow: tuple = struct.field(pytree_node=False, default=())
    sres_outflow: tuple = struct.field(pytree_node=False, default=())
    sres_initial: tuple = struct.field(pytree_node=False, default=())
    sres_xdiffuse: tuple = struct.field(pytree_node=False, default=())
    sres_ydiffuse: tuple = struct.field(pytree_node=False, default=())
    sres_inflow_box: tuple = struct.field(pytree_node=False, default=())
    sres_torus: tuple = struct.field(pytree_node=False, default=())
    # gradient (moving-peak) spatial resources (cGradientCount):
    # per-spatial-resource-row parameters; height 0 = ordinary diffusion
    sres_grad_height: tuple = struct.field(pytree_node=False, default=())
    sres_grad_spread: tuple = struct.field(pytree_node=False, default=())
    sres_grad_plateau: tuple = struct.field(pytree_node=False, default=())
    sres_grad_updatestep: tuple = struct.field(pytree_node=False, default=())
    sres_grad_move: tuple = struct.field(pytree_node=False, default=())
    sres_grad_peakx: tuple = struct.field(pytree_node=False, default=())
    sres_grad_peaky: tuple = struct.field(pytree_node=False, default=())

    @property
    def num_cells(self) -> int:
        return self.world_x * self.world_y


def _migration_cdf(cfg):
    """Method-4 migration: per-source-deme cumulative weight rows from the
    MIGRATION_FILE matrix (cMigrationMatrix::GetProbabilisticDemeID).  The
    parsed matrix is attached to cfg by World (which owns the config
    directory); a bare cfg with method 4 and no matrix refuses."""
    if int(cfg.DEMES_MIGRATION_METHOD) != 4:
        return ()
    mat = getattr(cfg, "_migration_matrix", None)
    if mat is None:
        raise ValueError(
            "DEMES_MIGRATION_METHOD 4 requires MIGRATION_FILE (an NxN "
            "weight matrix; cMigrationMatrix::Load)")
    rows = []
    for r in mat:
        tot = float(sum(r))
        if tot <= 0:
            raise ValueError("MIGRATION_FILE row with no positive weight")
        acc, row = 0.0, []
        for v in r:
            acc += float(v) / tot
            row.append(acc)
        rows.append(tuple(row))
    return tuple(rows)


def _fault_nan_param(cfg) -> tuple:
    """Static fault-injection flag for the `nan:` TPU_FAULT kind (the
    host-side kinds never touch params).  () in every production
    configuration."""
    from avida_tpu.utils.faultinject import nan_param
    return nan_param(cfg)


def _fault_bitflip_param(cfg) -> tuple:
    """Static flag for the `bitflip:` TPU_FAULT kind (the in-bounds SDC
    model).  () in every production configuration."""
    from avida_tpu.utils.faultinject import bitflip_param
    return bitflip_param(cfg)


def make_world_params(cfg, instset, environment) -> WorldParams:
    """Build WorldParams from parsed config objects (host side)."""
    tables = instset_tables(instset)
    env_tables = environment.device_tables()

    def tt(a):
        return tuple(map(tuple, a)) if a.ndim == 2 else tuple(a.tolist())

    if getattr(instset, "res_cost", None) is not None \
            and np.asarray(instset.res_cost).any():
        raise NotImplementedError(
            "instset res_cost (resource-bin execution costs, cInstSet.h:69) "
            "is not implemented; zero the res_cost column")
    if cfg.MAX_CPU_THREADS > 1 and instset.hw_type != 0:
        raise NotImplementedError(
            "MAX_CPU_THREADS > 1 is implemented for heads hardware only "
            "(TransSMT has its own host/parasite thread model)")
    if instset.hw_type in (1, 2) and (instset.cost.any()
                                      or instset.ft_cost.any()
                                      or instset.prob_fail.any()
                                      or instset.addl_time_cost.any()):
        raise NotImplementedError(
            "instruction costs/prob_fail/addl_time_cost are not implemented "
            "for TransSMT hardware yet; zero those columns or use heads "
            "hardware")
    for r in environment.spatial_resources():
        if r.is_gradient and (r.peakx >= cfg.WORLD_X or r.peaky >= cfg.WORLD_Y):
            raise ValueError(
                f"GRADIENT_RESOURCE {r.name!r} peak ({r.peakx},{r.peaky}) "
                f"lies outside the {cfg.WORLD_X}x{cfg.WORLD_Y} world")
    if cfg.MODULE_NUM > 0 and not cfg.CONT_REC_REGS:
        raise NotImplementedError(
            "non-continuous modular recombination (CONT_REC_REGS 0: "
            "cBirthChamber::DoModularNonContRecombination / "
            "DoModularShuffleRecombination) is not implemented; only the "
            "continuous mode (CONT_REC_REGS 1) is")
    if int(cfg.DEMES_MIGRATION_METHOD) == 3:
        raise NotImplementedError(
            "DEMES_MIGRATION_METHOD 3 (deme points) needs the deme points "
            "system, which is not modeled; use methods 0/1/2/4")
    if int(cfg.DEMES_MIGRATION_METHOD) == 1 and cfg.NUM_DEMES > 1 \
            and (cfg.DEMES_NUM_X <= 0
                 or cfg.NUM_DEMES % max(cfg.DEMES_NUM_X, 1)):
        raise ValueError(
            "DEMES_MIGRATION_METHOD 1 requires DEMES_NUM_X dividing "
            "NUM_DEMES (cPopulation.cc:5530)")
    if cfg.POPULATION_CAP and cfg.POP_CAP_ELDEST:
        raise ValueError(
            "POPULATION_CAP and POP_CAP_ELDEST are mutually exclusive "
            "carrying-capacity policies (cPopulation.cc:5192-5238)")
    return WorldParams(
        hw_type=instset.hw_type,
        num_registers=8 if instset.hw_type == 3 else 3,
        num_nops=int(sum(bool(x) for x in tables["is_nop"])) or 3,
        parasite_virulence=cfg.PARASITE_VIRULENCE,
        world_x=cfg.WORLD_X, world_y=cfg.WORLD_Y, geometry=cfg.WORLD_GEOMETRY,
        max_memory=cfg.TPU_MAX_MEMORY,
        min_genome_len=8,
        num_insts=tables["num_insts"],
        sem=tuple(tables["sem"].tolist()),
        mod_kind=tuple(tables["mod_kind"].tolist()),
        default_op=tuple(tables["default_op"].tolist()),
        is_nop=tuple(tables["is_nop"].tolist()),
        nop_mod=tuple(tables["nop_mod"].tolist()),
        mut_cdf=tuple(np.cumsum(instset.mutation_weights()).tolist()),
        inst_cost=(tuple(instset.cost.tolist())
                   if instset.cost.any() else ()),
        inst_ft_cost=(tuple(instset.ft_cost.tolist())
                      if instset.ft_cost.any() else ()),
        inst_prob_fail=(tuple(float(x) for x in instset.prob_fail)
                        if instset.prob_fail.any() else ()),
        inst_addl_time_cost=(tuple(int(x) for x in instset.addl_time_cost)
                             if instset.addl_time_cost.any() else ()),
        copy_mut_prob=cfg.COPY_MUT_PROB,
        copy_ins_prob=cfg.COPY_INS_PROB,
        copy_del_prob=cfg.COPY_DEL_PROB,
        divide_mut_prob=cfg.DIVIDE_MUT_PROB,
        divide_ins_prob=cfg.DIVIDE_INS_PROB,
        divide_del_prob=cfg.DIVIDE_DEL_PROB,
        div_mut_prob=cfg.DIV_MUT_PROB,
        divide_slip_prob=cfg.DIVIDE_SLIP_PROB,
        point_mut_prob=cfg.POINT_MUT_PROB,
        offspring_size_range=cfg.OFFSPRING_SIZE_RANGE,
        recombination_prob=cfg.RECOMBINATION_PROB,
        min_copied_lines=cfg.MIN_COPIED_LINES,
        min_exe_lines=cfg.MIN_EXE_LINES,
        require_allocate=bool(cfg.REQUIRE_ALLOCATE),
        ave_time_slice=cfg.AVE_TIME_SLICE,
        slicing_method=cfg.SLICING_METHOD,
        base_merit_method=cfg.BASE_MERIT_METHOD,
        base_const_merit=cfg.BASE_CONST_MERIT,
        default_bonus=cfg.DEFAULT_BONUS,
        inherit_merit=bool(cfg.INHERIT_MERIT),
        max_steps_per_update=cfg.TPU_MAX_STEPS_PER_UPDATE,
        use_pallas=cfg.TPU_USE_PALLAS,
        lane_perm_k=int(cfg.get("TPU_LANE_PERM", 1)),
        lane_perm_min_util=float(cfg.get("TPU_LANE_PERM_MIN_UTIL", 0.5)),
        kernel_shards=int(cfg.get("TPU_KERNEL_SHARDS", 0)),
        packed_chunk=int(cfg.get("TPU_PACKED_CHUNK", 1)),
        packed_fused=int(cfg.get("TPU_PACKED_FUSED", 1)),
        packed_bits=int(cfg.get("TPU_PACKED_BITS", 0)),
        num_demes=cfg.NUM_DEMES,
        demes_use_germline=cfg.DEMES_USE_GERMLINE,
        germline_copy_mut=cfg.GERMLINE_COPY_MUT,
        demes_max_age=cfg.DEMES_MAX_AGE,
        demes_max_births=cfg.DEMES_MAX_BIRTHS,
        demes_migration_rate=cfg.DEMES_MIGRATION_RATE,
        demes_migration_method=int(cfg.DEMES_MIGRATION_METHOD),
        mating_types=bool(cfg.MATING_TYPES),
        lekking=bool(cfg.LEKKING),
        module_num=int(cfg.MODULE_NUM),
        pred_prey_switch=int(cfg.PRED_PREY_SWITCH),
        pred_efficiency=float(cfg.PRED_EFFICIENCY),
        demes_num_x=int(cfg.DEMES_NUM_X),
        migration_cdf=_migration_cdf(cfg),
        death_method=cfg.DEATH_METHOD,
        age_limit=cfg.AGE_LIMIT,
        birth_method=cfg.BIRTH_METHOD,
        population_cap=cfg.POPULATION_CAP,
        pop_cap_eldest=cfg.POP_CAP_ELDEST,
        prefer_empty=bool(cfg.PREFER_EMPTY),
        allow_parent=bool(cfg.ALLOW_PARENT),
        divide_method=cfg.DIVIDE_METHOD,
        energy_enabled=bool(cfg.ENERGY_ENABLED),
        energy_given_on_inject=cfg.ENERGY_GIVEN_ON_INJECT,
        energy_given_at_birth=cfg.ENERGY_GIVEN_AT_BIRTH,
        frac_parent_energy=cfg.FRAC_PARENT_ENERGY_GIVEN_TO_ORG_AT_BIRTH,
        frac_energy_decay_birth=cfg.FRAC_ENERGY_DECAY_AT_ORG_BIRTH,
        energy_cap=cfg.ENERGY_CAP,
        num_cycles_exc=cfg.NUM_CYCLES_EXC_BEFORE_0_ENERGY,
        fix_metabolic_rate=cfg.FIX_METABOLIC_RATE,
        inst_energy_cost=tuple(float(x) for x in instset.energy_cost)
        if instset.energy_cost.any() else (),
        dispersal_rate=cfg.DISPERSAL_RATE,
        max_cpu_threads=max(int(cfg.MAX_CPU_THREADS), 1),
        thread_slicing_method=int(cfg.THREAD_SLICING_METHOD),
        nb_cap=2 * cfg.WORLD_X * cfg.WORLD_Y
        if cfg.get("TPU_SYSTEMATICS", 1) else 0,
        trace_cap=int(cfg.get("TPU_TRACE_CAP", 4096))
        if int(cfg.get("TPU_TRACE", 0)) else 0,
        trace_stall_util=float(cfg.get("TPU_TRACE_STALL_UTIL", 0.25)),
        fault_nan=_fault_nan_param(cfg),
        fault_bitflip=_fault_bitflip_param(cfg),
        generation_inc_method=cfg.GENERATION_INC_METHOD,
        num_reactions=len(environment.reactions),
        task_logic_mask=tt(env_tables["task_logic_mask"]),
        proc_value=tuple(env_tables["proc_value"].tolist()),
        proc_type=tuple(env_tables["proc_type"].tolist()),
        max_task_count=tuple(env_tables["max_task_count"].tolist()),
        min_task_count=tuple(env_tables["min_task_count"].tolist()),
        req_reaction_mask=tt(env_tables["req_reaction_mask"]),
        noreq_reaction_mask=tt(env_tables["noreq_reaction_mask"]),
        task_math_name=env_tables["task_math_name"],
        proc_res_idx=tuple(env_tables["proc_res_idx"].tolist()),
        proc_res_spatial=tuple(env_tables["proc_res_spatial"].tolist()),
        proc_max=tuple(env_tables["proc_max"].tolist()),
        proc_frac=tuple(env_tables["proc_frac"].tolist()),
        proc_depletable=tuple(env_tables["proc_depletable"].tolist()),
        proc_product_idx=tuple(env_tables["proc_product_idx"].tolist()),
        proc_product_spatial=tuple(
            env_tables["proc_product_spatial"].tolist()),
        proc_conversion=tuple(env_tables["proc_conversion"].tolist()),
        num_deme_res=len(environment.deme_resources()),
        dres_inflow=tuple(r.inflow for r in environment.deme_resources()),
        dres_outflow=tuple(r.outflow for r in environment.deme_resources()),
        dres_initial=tuple(r.initial for r in environment.deme_resources()),
        proc_res_deme=tuple(env_tables["proc_res_deme"].tolist()),
        num_global_res=len(environment.global_resources()),
        res_inflow=tuple(r.inflow for r in environment.global_resources()),
        res_outflow=tuple(r.outflow for r in environment.global_resources()),
        res_initial=tuple(r.initial for r in environment.global_resources()),
        num_spatial_res=len(environment.spatial_resources()),
        sres_inflow=tuple(r.inflow for r in environment.spatial_resources()),
        sres_outflow=tuple(r.outflow for r in environment.spatial_resources()),
        sres_initial=tuple(r.initial for r in environment.spatial_resources()),
        sres_xdiffuse=tuple(r.xdiffuse for r in environment.spatial_resources()),
        sres_ydiffuse=tuple(r.ydiffuse for r in environment.spatial_resources()),
        sres_inflow_box=tuple((r.inflowx1, r.inflowx2, r.inflowy1, r.inflowy2)
                              for r in environment.spatial_resources()),
        sres_torus=tuple(r.geometry == "torus"
                         for r in environment.spatial_resources()),
        sres_grad_height=tuple(r.height
                               for r in environment.spatial_resources()),
        sres_grad_spread=tuple(r.spread
                               for r in environment.spatial_resources()),
        sres_grad_plateau=tuple(r.plateau
                                for r in environment.spatial_resources()),
        sres_grad_updatestep=tuple(
            r.updatestep for r in environment.spatial_resources()),
        sres_grad_move=tuple(r.move_a_scaler > 1
                             for r in environment.spatial_resources()),
        sres_grad_peakx=tuple(r.peakx
                              for r in environment.spatial_resources()),
        sres_grad_peaky=tuple(r.peaky
                              for r in environment.spatial_resources()),
    )


def instset_tables(instset):
    from avida_tpu.models.registry import get_hardware
    mod = get_hardware(instset.hw_type)["module"]
    if len(instset.inst_names) > 64:
        raise ValueError(
            "packed-tape layout supports <= 64 instructions per set "
            "(6 opcode bits + 2 flag bits; see ops/interpreter.py)")
    return mod.build_semantic_tables(instset.inst_names)


class PopulationState(struct.PyTreeNode):
    """All per-organism (= per-cell) device state.  Shapes given for N cells,
    L = max_memory, R = num reactions."""

    # --- virtual hardware (ref cHardwareCPU.h:61-152) ---
    # One packed plane holds the memory tape AND the per-site flags
    # (ref cCPUMemory executed/copied flags): bits 0-5 opcode, bit 6
    # executed, bit 7 copied.  Packing keeps the per-cycle working set at
    # N*L bytes so the whole update loop stays VMEM-resident on TPU
    # (see ops/interpreter.py header).
    tape: jax.Array           # uint8[N, L]
    mem_len: jax.Array        # int32[N]     current memory size
    regs: jax.Array           # int32[N, 3]  AX BX CX
    heads: jax.Array          # int32[N, 4]  IP READ WRITE FLOW
    stacks: jax.Array         # int32[N, 2, 10]
    sp: jax.Array             # int32[N, 2]  stack pointers
    active_stack: jax.Array   # int32[N]
    read_label: jax.Array     # int8[N, 10]  nops most recently copied
    read_label_len: jax.Array  # int32[N]

    # --- intra-organism threads (cHardwareCPU.h m_threads; sized by
    # MAX_CPU_THREADS = T; Te = T-1 extra slots are ZERO-SIZE at the
    # default T=1, so single-threaded configs pay nothing).  The primary
    # fields above store slot 0's thread state; t_* arrays store slots
    # 1..T-1.  Thread-local per cHardwareCPU::cLocalThread: registers,
    # heads, local stack (stack 0), active-stack selector, read label.
    # Stack 1 (global) and everything else is organism-shared. ---
    # Slot 0 (the primary fields above) is ALWAYS the state of an alive
    # thread -- killing it copies another live thread into the primary
    # fields, mirroring the reference's array compaction (KillThread
    # cc:1604 copies the last thread into the killed position).  Extra
    # slots are sparse: t_alive marks occupancy, slots never move.
    t_alive: jax.Array         # bool[N, Te]  extra-slot occupancy
    main_tid: jax.Array        # int32[N]     slot 0's reference thread id
    t_ids: jax.Array           # int32[N, Te] extra slots' thread ids
    cur_thread: jax.Array      # int32[N]     active slot (0 = primary)
    t_regs: jax.Array          # int32[N, Te, NR]
    t_heads: jax.Array         # int32[N, Te, 4]
    t_stack: jax.Array         # int32[N, Te, 10]  local stack (stack 0)
    t_sp: jax.Array            # int32[N, Te]
    t_active_stack: jax.Array  # int32[N, Te]
    t_rlabel: jax.Array        # int8[N, Te, 10]
    t_rlabel_len: jax.Array    # int32[N, Te]
    mal_active: jax.Array     # bool[N]      allocate active (REQUIRE_ALLOCATE)

    # --- organism / world binding ---
    alive: jax.Array          # bool[N]
    genome: jax.Array         # int8[N, L]   birth genome (genotype identity)
    genome_len: jax.Array     # int32[N]
    inputs: jax.Array         # int32[N, 3]  cell input stream (cEnvironment::SetupInputs)
    input_ptr: jax.Array      # int32[N]
    input_buf: jax.Array      # int32[N, 3]  last 3 inputs, [0]=most recent
    input_buf_n: jax.Array    # int32[N]
    output_buf: jax.Array     # int32[N]     last output (output size 1)

    # --- phenotype (ref cPhenotype.h:97-216) ---
    merit: jax.Array          # f32[N]       scheduling weight
    cur_bonus: jax.Array      # f32[N]
    cur_task_count: jax.Array     # int32[N, R]
    task_exe_total: jax.Array     # int32[N, R]  lifetime task executions at
    #                               this CELL (never reset -- tasks_exe.dat
    #                               derives per-update counts from deltas)
    cur_reaction_count: jax.Array  # int32[N, R]
    last_task_count: jax.Array    # int32[N, R]
    time_used: jax.Array      # int32[N]
    cpu_cycles: jax.Array     # int32[N]
    gestation_start: jax.Array  # int32[N]
    gestation_time: jax.Array   # int32[N]  last gestation
    fitness: jax.Array        # f32[N]      last fitness
    last_bonus: jax.Array     # f32[N]
    last_merit_base: jax.Array  # f32[N]
    executed_size: jax.Array  # int32[N]
    copied_size: jax.Array    # int32[N]
    child_copied_size: jax.Array  # int32[N]
    generation: jax.Array     # int32[N]
    max_executed: jax.Array   # int32[N]    death threshold (DEATH_METHOD)
    num_divides: jax.Array    # int32[N]
    sterile: jax.Array        # bool[N]     divide permanently fails
                              # (STERILIZE_*, Divide_TestFitnessMeasures)
    breed_true: jax.Array     # bool[N]     born identical to parent genome
                              # (ref cPhenotype copy_true / is_breed_true)

    # --- pending birth (flushed by the birth engine each update; the
    # offspring opcodes stay in place on the tape beyond mem_len and are
    # extracted by ops/interpreter.extract_offspring at flush) ---
    divide_pending: jax.Array  # bool[N]
    off_start: jax.Array      # int32[N]   offspring start position on tape
    off_len: jax.Array        # int32[N]
    off_tape: jax.Array       # uint8[N, L] extracted offspring opcodes,
                              # aligned at 0 and zero-padded beyond off_len
                              # (written at h-divide by the Pallas kernel, or
                              # at update end by the XLA path; consumed by
                              # the birth flush -- persists so a parent whose
                              # placement lost a conflict can retry)
    off_copied_size: jax.Array  # int32[N]
    off_sex: jax.Array        # bool[N]    offspring awaits a mate (divide-sex;
                              # ref cPhenotype divide_sex + cBirthChamber)

    # --- birth chamber waiting store (ref cBirthChamber mate storage,
    # cBirthGlobalHandler): ONE waiting sexual offspring; greedy in-update
    # pairing guarantees at most one leftover per flush ---
    # phenotype mating type (MATING_TYPES runs; cPhenotype.h:411:
    # juvenile=-1 at birth, female=0, male=1)
    mating_type: jax.Array    # int32[N]
    bc_mem: jax.Array         # int8[L]    waiting offspring genome
    bc_len: jax.Array         # int32[]    its length
    bc_merit: jax.Array       # f32[]      submitting parent's merit
    bc_valid: jax.Array       # bool[]     entry occupied
    bc_type: jax.Array        # int32[]    stored offspring's parent mating
    #                           type (-1 when mating types are off)

    # --- demes (ref cDeme: per-group counters + germline; cells map to
    # demes as contiguous bands, deme = cell // (N // D)) ---
    deme_birth_count: jax.Array  # int32[D]  births since deme reset
    deme_age: jax.Array          # int32[D]  updates since deme reset
    germ_mem: jax.Array          # int8[D, L] germline genome (cGermline)
    germ_len: jax.Array          # int32[D]

    # --- energy model (cPhenotype energy_store; only meaningful when
    # ENERGY_ENABLED) ---
    energy: jax.Array          # f32[N]
    energy_spent: jax.Array    # f32[N]  lifetime energy consumed (BIRTH_METHOD
                               #         9/10 rank cells by it, cPopulation.cc:5332)

    # --- per-deme resource pools (cDeme resource slice) ---
    deme_resources: jax.Array  # f32[D, Rd]

    # --- newborn record buffer (systematics chunked ingestion; size-0
    # axes when nb_cap == 0) ---
    nb_genome: jax.Array       # int8[CAP, L]
    nb_len: jax.Array          # int32[CAP]
    nb_cell: jax.Array         # int32[CAP]
    nb_parent: jax.Array       # int32[CAP]
    nb_update: jax.Array       # int32[CAP]
    nb_count: jax.Array        # int32[] records written (may exceed CAP =
                               # overflow; the host detects and falls back)

    # --- flight recorder event ring (observability/tracer.py; the five
    # fields are None when trace_cap == 0 -- None is an EMPTY pytree, so
    # the disabled recorder contributes no jaxpr inputs and update_step
    # traces to the byte-identical program, scripts/check_jaxpr.py).
    # Append-only side state written inside the jitted update
    # (ops/update.trace_pre_phase/trace_post_phase): slot i % trace_cap
    # holds event number i, so overflow drops the OLDEST events and the
    # host recovers the drop count from the monotone cursor
    # (tr_count - trace_cap).  Nothing in the engine reads these back --
    # the evolved trajectory is bit-identical with the recorder on or
    # off (tests/test_tracer.py). ---
    tr_update: jax.Array       # int32[TCAP] update_no of event
    tr_cell: jax.Array         # int32[TCAP] cell index (-1 = world-level)
    tr_code: jax.Array         # int32[TCAP] event code (tracer.EVENT_CODES)
    tr_payload: jax.Array      # int32[TCAP] code-specific payload
    tr_count: jax.Array        # int32[]    events written since last drain
                               #            (may exceed TCAP = overflow)

    # --- experimental hardware (hw_type 3): spatial behaviour state ---
    facing: jax.Array          # int32[N]  ring direction 0-7 (cell facing;
                               # ref cPopulationCell rotation state)
    forage_target: jax.Array   # int32[N]  (Inst_SetForageTarget; predator/
                               # prey identity, -1 = unset default)

    # --- TransSMT hardware (hw_type 2; empty (size-0 axes) on heads
    # hardware).  Threads: 0 = host, 1 = parasite.  Memory spaces per
    # thread: base space (host base = the packed `tape`) + ONE auxiliary
    # write buffer.  Heads carry (space, position); spaces index
    # 0=tape, 1=aux[.,0], 2=pmem, 3=aux[.,1]. ---
    smt_aux: jax.Array        # uint8[N, T, Ls]  write buffers (space 1/3)
    smt_aux_len: jax.Array    # int32[N, T]
    pmem: jax.Array           # uint8[N, Ls]     parasite code (space 2)
    pmem_len: jax.Array       # int32[N]
    parasite_active: jax.Array  # bool[N]        thread 1 running
    smt_stacks: jax.Array     # int32[N, T, 3, 10]  local stacks AX/BX/CX
    smt_sp: jax.Array         # int32[N, T, 3]
    gstack: jax.Array         # int32[N, 10]     global stack DX
    gsp: jax.Array            # int32[N]
    smt_head_pos: jax.Array   # int32[N, T, 4]
    smt_head_space: jax.Array  # int32[N, T, 4]
    inject_pending: jax.Array  # bool[N]   parasite offspring awaiting flush
    inj_mem: jax.Array        # uint8[N, Ls]  pending injection code
    inj_len: jax.Array        # int32[N]

    # --- systematics hooks ---
    genotype_id: jax.Array    # int32[N]    host-assigned genotype ids (-1 unknown)
    parent_id: jax.Array      # int32[N]    parent cell index at birth (-1 seed)
    birth_update: jax.Array   # int32[N]

    # --- instruction cost engine (SingleProcess_PayPreCosts,
    # cHardwareBase.cc:1241): remaining cycles owed before the current
    # instruction executes, and which opcodes have paid their one-time
    # first-use cost (64-bit opcode bitmask as 2x int32) ---
    cost_wait: jax.Array       # int32[N]
    ft_paid_lo: jax.Array      # int32[N]  opcodes 0-31
    ft_paid_hi: jax.Array      # int32[N]  opcodes 32-63

    # --- per-update accounting ---
    insts_executed: jax.Array  # int32[N]  lifetime instructions executed
    budget_carry: jax.Array    # int32[N]  banked cycles (ops/update.py cap)

    # --- budget-aware kernel lane packing (ops/update.perm_phase): the
    # persistent organism<->kernel-slot indirection.  lane_perm[slot] =
    # organism packed into that kernel lane, lane_inv its inverse.  A
    # WORLD-level indirection (not per-organism state): births/deaths
    # never touch it; it is refreshed wholesale every TPU_LANE_PERM
    # updates.  Identity when the feature is off. ---
    lane_perm: jax.Array       # int32[N]  slot -> organism
    lane_inv: jax.Array        # int32[N]  organism -> slot

    # --- resources (world-level state carried with the population) ---
    resources: jax.Array       # f32[Rg]    global pools (cResourceCount)
    res_grid: jax.Array        # f32[Rs, N] spatial per-cell (cSpatialResCount)
    grad_peak: jax.Array       # int32[Rs, 2] moving-peak (x, y); -1 = unset
                               # (cGradientCount peak position)

    @property
    def mem(self) -> jax.Array:
        """Opcode view of the packed tape (int8[N, L])."""
        return (self.tape & jnp.uint8(0x3F)).astype(jnp.int8)

    @property
    def flag_exec(self) -> jax.Array:
        return (self.tape & jnp.uint8(0x40)) != 0

    @property
    def flag_copied(self) -> jax.Array:
        return (self.tape & jnp.uint8(0x80)) != 0


def zeros_population(n: int, L: int, R: int, n_global_res: int = 0,
                     n_spatial_res: int = 0, n_demes: int = 1,
                     smt: bool = False, num_registers: int = 3,
                     nb_cap: int = 0, n_deme_res: int = 0,
                     max_threads: int = 1,
                     trace_cap: int = 0) -> PopulationState:
    i32 = partial(jnp.zeros, dtype=jnp.int32)
    f32 = partial(jnp.zeros, dtype=jnp.float32)
    T = 2 if smt else 0          # SMT thread axis (host, parasite)
    Ls = L if smt else 0         # SMT memory-space width
    Tc = max(max_threads, 1)     # cHardwareCPU thread slots (1 = no threads)
    Te = Tc - 1
    return PopulationState(
        tape=jnp.zeros((n, L), jnp.uint8), mem_len=i32(n),
        regs=i32((n, num_registers)), heads=i32((n, 4)),
        stacks=i32((n, 2, 10)), sp=i32((n, 2)), active_stack=i32(n),
        read_label=jnp.zeros((n, 10), jnp.int8), read_label_len=i32(n),
        t_alive=jnp.zeros((n, Te), bool),
        main_tid=i32(n), t_ids=i32((n, Te)),
        cur_thread=i32(n),
        t_regs=i32((n, Te, num_registers)), t_heads=i32((n, Te, 4)),
        t_stack=i32((n, Te, 10)), t_sp=i32((n, Te)),
        t_active_stack=i32((n, Te)),
        t_rlabel=jnp.zeros((n, Te, 10), jnp.int8), t_rlabel_len=i32((n, Te)),
        mal_active=jnp.zeros(n, bool),
        alive=jnp.zeros(n, bool),
        genome=jnp.zeros((n, L), jnp.int8), genome_len=i32(n),
        inputs=i32((n, 3)), input_ptr=i32(n),
        input_buf=i32((n, 3)), input_buf_n=i32(n), output_buf=i32(n),
        merit=f32(n), cur_bonus=f32(n),
        cur_task_count=i32((n, R)), cur_reaction_count=i32((n, R)),
        last_task_count=i32((n, R)), task_exe_total=i32((n, R)),
        time_used=i32(n), cpu_cycles=i32(n),
        gestation_start=i32(n), gestation_time=i32(n),
        fitness=f32(n), last_bonus=f32(n), last_merit_base=f32(n),
        executed_size=i32(n), copied_size=i32(n), child_copied_size=i32(n),
        generation=i32(n), max_executed=i32(n), num_divides=i32(n),
        sterile=jnp.zeros(n, bool),
        breed_true=jnp.zeros(n, bool),
        divide_pending=jnp.zeros(n, bool),
        energy=f32(n), energy_spent=f32(n),
        deme_resources=jnp.zeros((n_demes, n_deme_res), jnp.float32),
        nb_genome=jnp.zeros((nb_cap, L), jnp.int8), nb_len=i32(nb_cap),
        nb_cell=i32(nb_cap), nb_parent=i32(nb_cap), nb_update=i32(nb_cap),
        nb_count=jnp.zeros((), jnp.int32),
        tr_update=i32(trace_cap) if trace_cap else None,
        tr_cell=i32(trace_cap) if trace_cap else None,
        tr_code=i32(trace_cap) if trace_cap else None,
        tr_payload=i32(trace_cap) if trace_cap else None,
        tr_count=jnp.zeros((), jnp.int32) if trace_cap else None,
        facing=i32(n), forage_target=jnp.full(n, -1, jnp.int32),
        off_start=i32(n), off_len=i32(n),
        off_tape=jnp.zeros((n, L), jnp.uint8),
        off_copied_size=i32(n), off_sex=jnp.zeros(n, bool),
        mating_type=jnp.full(n, -1, jnp.int32),
        bc_mem=jnp.zeros(L, jnp.int8), bc_len=jnp.zeros((), jnp.int32),
        bc_merit=jnp.zeros((), jnp.float32), bc_valid=jnp.zeros((), bool),
        bc_type=jnp.full((), -1, jnp.int32),
        deme_birth_count=i32(n_demes), deme_age=i32(n_demes),
        germ_mem=jnp.zeros((n_demes, L), jnp.int8), germ_len=i32(n_demes),
        smt_aux=jnp.zeros((n, T, Ls), jnp.uint8), smt_aux_len=i32((n, T)),
        pmem=jnp.zeros((n, Ls), jnp.uint8), pmem_len=i32(n),
        parasite_active=jnp.zeros(n, bool),
        smt_stacks=i32((n, T, 3, 10)), smt_sp=i32((n, T, 3)),
        gstack=i32((n, 10 if smt else 0)), gsp=i32(n),
        smt_head_pos=i32((n, T, 4)), smt_head_space=i32((n, T, 4)),
        inject_pending=jnp.zeros(n, bool),
        inj_mem=jnp.zeros((n, Ls), jnp.uint8), inj_len=i32(n),
        genotype_id=jnp.full(n, -1, jnp.int32), parent_id=jnp.full(n, -1, jnp.int32),
        birth_update=jnp.full(n, -1, jnp.int32),
        cost_wait=i32(n), ft_paid_lo=i32(n), ft_paid_hi=i32(n),
        insts_executed=i32(n),
        budget_carry=i32(n),
        lane_perm=jnp.arange(n, dtype=jnp.int32),
        lane_inv=jnp.arange(n, dtype=jnp.int32),
        resources=f32(n_global_res),
        res_grid=f32((n_spatial_res, n)),
        grad_peak=jnp.full((n_spatial_res, 2), -1, jnp.int32),
    )


def make_cell_inputs(key: jax.Array, n: int) -> jax.Array:
    """Patterned random inputs: top 8 bits 0x0F/0x33/0x55, low 24 random
    (ref cEnvironment::SetupInputs, cEnvironment.cc:1268-1276)."""
    low = jax.random.randint(key, (n, 3), 0, 1 << 24, dtype=jnp.int32)
    tops = jnp.array([15 << 24, 51 << 24, 85 << 24], jnp.int32)
    return tops[None, :] + low


# flight-recorder ring leaves (observability/tracer.py) -- the single
# spelling authority: the tracer's snapshot, the checkpoint loader's
# config-dependent-field reconciliation, and WORLD_LEVEL_FIELDS below
# all derive from this tuple
TRACE_RING_FIELDS = ("tr_update", "tr_cell", "tr_code", "tr_payload",
                     "tr_count")

# world-level / cell-bound fields that are NOT per-organism rows
# (lane_perm/lane_inv are [N]-shaped but index kernel SLOTS, a world-level
# indirection -- seeding an organism must not reset its entries)
WORLD_LEVEL_FIELDS = frozenset({
    "resources", "res_grid", "grad_peak",
    "bc_mem", "bc_len", "bc_merit", "bc_valid",
    "deme_birth_count", "deme_age", "germ_mem", "germ_len", "deme_resources",
    "lane_perm", "lane_inv",
    "nb_genome", "nb_len", "nb_cell", "nb_parent", "nb_update", "nb_count",
    *TRACE_RING_FIELDS,
})


def state_field_names() -> tuple:
    """Canonical ordered leaf names of PopulationState -- the single
    enumeration authority for whole-state serialization.  The native
    checkpoint writer (utils/checkpoint.py) saves exactly these fields
    and its loader refuses a manifest whose field set differs, so adding
    a field to PopulationState automatically versions the checkpoint
    format (an old checkpoint fails loudly instead of resuming with a
    silently-defaulted field)."""
    return tuple(PopulationState.__dataclass_fields__)


def state_array_specs(st: PopulationState) -> dict:
    """{field: (shape tuple, dtype str)} for every leaf of `st`.  The
    checkpoint format test cross-checks written manifests against this
    (tests/test_native_checkpoint.py), so shape/dtype drift between the
    live state and the on-disk schema fails loudly.  Fields that are
    None (the flight-recorder ring with the recorder off -- empty
    pytrees, not arrays) have no on-disk representation and are
    omitted, matching the checkpoint writer."""
    return {name: (tuple(getattr(st, name).shape),
                   str(getattr(st, name).dtype))
            for name in state_field_names()
            if getattr(st, name) is not None}


def seed_organism(params: WorldParams, st: PopulationState,
                  seed_genome: np.ndarray, key: jax.Array,
                  cell: int) -> PopulationState:
    """Write ONE fresh organism into `cell` (ref cPopulation::Inject
    cc:7377 + cPhenotype::SetupInject cc:599: merit = genome length,
    copied = executed = length).  Every per-organism field at the cell
    resets to its fresh-organism default first -- O(1) in world size, no
    full-population rebuild."""
    n, L, R = params.num_cells, params.max_memory, params.num_reactions
    blank = zeros_population(1, L, R, params.num_global_res,
                             params.num_spatial_res, 1,
                             smt=(params.hw_type in (1, 2)),
                             num_registers=params.num_registers,
                             max_threads=params.max_cpu_threads)
    c = cell
    updates = {}
    for name in st.__dataclass_fields__:
        if name in WORLD_LEVEL_FIELDS:
            continue
        v = getattr(st, name)
        if not hasattr(v, "shape") or v.ndim == 0 or v.shape[0] != n:
            continue
        updates[name] = v.at[c].set(getattr(blank, name)[0])
    st = st.replace(**updates)

    g = np.zeros(L, np.int8)
    glen = len(seed_genome)
    if glen > L:
        raise ValueError(f"seed genome length {glen} exceeds max_memory {L}")
    g[:glen] = seed_genome
    k_in, _ = jax.random.split(key)
    return st.replace(
        tape=st.tape.at[c].set(jnp.asarray(g).astype(jnp.uint8)),
        genome=st.genome.at[c].set(jnp.asarray(g)),
        mem_len=st.mem_len.at[c].set(glen),
        genome_len=st.genome_len.at[c].set(glen),
        alive=st.alive.at[c].set(True),
        merit=st.merit.at[c].set(float(glen)),
        energy=st.energy.at[c].set(params.energy_given_on_inject),
        cur_bonus=st.cur_bonus.at[c].set(params.default_bonus),
        executed_size=st.executed_size.at[c].set(glen),
        copied_size=st.copied_size.at[c].set(glen),
        max_executed=st.max_executed.at[c].set(
            params.age_limit * glen if params.death_method == 2
            else (params.age_limit if params.death_method == 1 else 2**30)),
        inputs=st.inputs.at[c].set(make_cell_inputs(k_in, 1)[0]),
    )


def init_population(params: WorldParams, seed_genome: np.ndarray,
                    key: jax.Array, inject_cell: int | None = None
                    ) -> PopulationState:
    """World with a single injected ancestor (ref ActivateOrganism +
    cPhenotype::SetupInject, cPhenotype.cc:599)."""
    n, L, R = params.num_cells, params.max_memory, params.num_reactions
    st = zeros_population(n, L, R, params.num_global_res,
                          params.num_spatial_res, params.num_demes,
                          smt=(params.hw_type in (1, 2)),
                          num_registers=params.num_registers,
                          nb_cap=params.nb_cap,
                          n_deme_res=params.num_deme_res,
                          max_threads=params.max_cpu_threads,
                          trace_cap=params.trace_cap)
    k_inputs, key = jax.random.split(key)
    st = st.replace(inputs=make_cell_inputs(k_inputs, n),
                    deme_resources=jnp.broadcast_to(
                        jnp.asarray(params.dres_initial, jnp.float32)[None, :],
                        (params.num_demes, params.num_deme_res)),
                    resources=jnp.asarray(params.res_initial, jnp.float32),
                    res_grid=jnp.broadcast_to(
                        jnp.asarray(params.sres_initial, jnp.float32)[:, None],
                        (params.num_spatial_res, n)))
    if inject_cell is None:
        inject_cell = n // 2  # reference injects cell 0; center is equivalent on a torus
    g = np.zeros(L, np.int8)
    glen = len(seed_genome)
    c = inject_cell
    st = seed_organism(params, st, seed_genome, key, c)
    g[:glen] = seed_genome
    if params.demes_use_germline:
        # every deme's germline starts at the ancestor (cGermline seeded at
        # world setup)
        st = st.replace(
            germ_mem=jnp.broadcast_to(jnp.asarray(g)[None, :],
                                      (params.num_demes, L)).astype(jnp.int8),
            germ_len=jnp.full(params.num_demes, glen, jnp.int32))
    if params.hw_type in (1, 2):
        # SMT thread base spaces: host thread at space 0, parasite at 2
        base = jnp.asarray([[0, 0, 0, 0], [2, 2, 2, 2]], jnp.int32)
        st = st.replace(smt_head_space=jnp.broadcast_to(
            base[None], (n, 2, 4)))
    return st
