from avida_tpu.models.registry import get_hardware, HARDWARE_REGISTRY

__all__ = ["get_hardware", "HARDWARE_REGISTRY"]
