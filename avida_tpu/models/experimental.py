"""The experimental virtual CPU (reference cHardwareExperimental).

Semantic instruction table for the research CPU with rich sensing
(ref: cHardwareExperimental.{cc,h} -- 8 registers at h:66, 8 nops,
sensor/movement/predation families fed by cOrgSensor; instset files
declare hw_type=3, e.g. support/config/instset-experimental.cfg and
tests/avatars-pred_look/config/instset.cfg).

Round-4 scope (the VERDICT r3 directive's done-bar): the 8-register base
plus the sensing/movement family -- every instruction in the
instset-experimental.cfg replication set and the avatars-pred_look
predator/prey set.  The remaining ~200 instructions (group behaviour,
messaging displays, resource collection variants) raise loudly at load.

Shared semantics (heads, stacks, copy loop, divide) reuse the heads
semantic opcodes; execution happens in ops/interpreter.micro_step, which
is parameterized on register/nop counts and implements the new opcodes
behind static hw_type gates.
"""

from __future__ import annotations

import numpy as np

from avida_tpu.models.heads import (InstSpec, MOD_HEAD, MOD_LABEL, MOD_NONE,
                                    MOD_REG, HEAD_IP, SEM_ADD, SEM_DEC,
                                    SEM_GET_HEAD, SEM_H_ALLOC, SEM_H_COPY,
                                    SEM_H_DIVIDE, SEM_H_SEARCH, SEM_IF_LABEL,
                                    SEM_IF_LESS, SEM_IF_N_EQU, SEM_INC, SEM_IO,
                                    SEM_JMP_HEAD, SEM_MOV_HEAD, SEM_NAND,
                                    SEM_POP, SEM_PUSH, SEM_SET_FLOW,
                                    SEM_SHIFT_L, SEM_SHIFT_R, SEM_SUB,
                                    SEM_SWAP, SEM_SWAP_STK,
                                    NUM_SEMANTIC_OPS as _HEADS_OPS)

NUM_REGISTERS = 8        # rAX..rHX (cHardwareExperimental.h:66)
NUM_NOPS = 8             # nop-A..nop-H

# nop semantic ids: 0..7 (the first 8 semantic slots are nops for this
# hardware; the interpreter only needs is_nop/nop_mod tables, so nop sem
# ids merely have to be distinct)
SEM_NOP_BASE = 100       # sentinel range for nops D..H (never dispatched)

# new semantic opcodes (continue after the heads range)
(
    SEM_ZERO,            # zero ?BX? (Inst_ZeroReg)
    SEM_IF_NOT_0,        # exec next iff ?BX? != 0 (Inst_IfNotZero)
    SEM_IF_EQU_0,        # exec next iff ?BX? == 0 (Inst_IfEqualZero)
    SEM_MOVE,            # step into the faced cell (Inst_Move cc:3138)
    SEM_ROTATE_X,        # rotate facing by ?BX? steps (Inst_RotateX cc:3441)
    SEM_ROTATE_ORG_ID,   # face the neighbor with org id ?BX? (cc:3489)
    SEM_LOOK_AHEAD,      # ray-scan the faced direction (GoLook cc:3895)
    SEM_SET_FORAGE,      # forage target <- ?BX? (Inst_SetForageTarget)
    SEM_LABEL,           # consume a label, no other effect (Inst_Label)
    SEM_ATTACK_PREY,     # kill the faced prey, absorb merit/bonus
    #                      (Inst_AttackPrey cc:5407, ExecuteAttack cc:7001)
) = range(_HEADS_OPS, _HEADS_OPS + 10)

_R = list(range(NUM_REGISTERS))

INSTRUCTIONS = {
    # flow control (heads semantics, 8-register operand space)
    "if-n-equ": InstSpec("if-n-equ", SEM_IF_N_EQU, MOD_REG, 1),
    "if-less": InstSpec("if-less", SEM_IF_LESS, MOD_REG, 1),
    "if-label": InstSpec("if-label", SEM_IF_LABEL, MOD_LABEL, 0),
    "if-not-0": InstSpec("if-not-0", SEM_IF_NOT_0, MOD_REG, 1),
    "if-equ-0": InstSpec("if-equ-0", SEM_IF_EQU_0, MOD_REG, 1),
    "mov-head": InstSpec("mov-head", SEM_MOV_HEAD, MOD_HEAD, HEAD_IP),
    "jmp-head": InstSpec("jmp-head", SEM_JMP_HEAD, MOD_HEAD, HEAD_IP),
    "get-head": InstSpec("get-head", SEM_GET_HEAD, MOD_HEAD, HEAD_IP),
    "label": InstSpec("label", SEM_LABEL, MOD_LABEL, 0,
                      "consumes a label, no other effect (Inst_Label)"),
    "set-flow": InstSpec("set-flow", SEM_SET_FLOW, MOD_REG, 2),
    # math / stack
    "shift-r": InstSpec("shift-r", SEM_SHIFT_R, MOD_REG, 1),
    "shift-l": InstSpec("shift-l", SEM_SHIFT_L, MOD_REG, 1),
    "inc": InstSpec("inc", SEM_INC, MOD_REG, 1),
    "dec": InstSpec("dec", SEM_DEC, MOD_REG, 1),
    "zero": InstSpec("zero", SEM_ZERO, MOD_REG, 1),
    "push": InstSpec("push", SEM_PUSH, MOD_REG, 1),
    "pop": InstSpec("pop", SEM_POP, MOD_REG, 1),
    "swap-stk": InstSpec("swap-stk", SEM_SWAP_STK, MOD_NONE, 0),
    "swap": InstSpec("swap", SEM_SWAP, MOD_REG, 1),
    "add": InstSpec("add", SEM_ADD, MOD_REG, 1),
    "sub": InstSpec("sub", SEM_SUB, MOD_REG, 1),
    "nand": InstSpec("nand", SEM_NAND, MOD_REG, 1),
    # biology
    "h-copy": InstSpec("h-copy", SEM_H_COPY, MOD_NONE, 0),
    "h-alloc": InstSpec("h-alloc", SEM_H_ALLOC, MOD_NONE, 0),
    "h-divide": InstSpec("h-divide", SEM_H_DIVIDE, MOD_NONE, 0),
    "IO": InstSpec("IO", SEM_IO, MOD_REG, 1),
    "h-search": InstSpec("h-search", SEM_H_SEARCH, MOD_LABEL, 0),
    # sensing / movement (the cOrgSensor-fed family)
    "move": InstSpec("move", SEM_MOVE, MOD_REG, 1),
    "rotate-x": InstSpec("rotate-x", SEM_ROTATE_X, MOD_REG, 1),
    "rotate-org-id": InstSpec("rotate-org-id", SEM_ROTATE_ORG_ID, MOD_REG, 1),
    "look-ahead": InstSpec("look-ahead", SEM_LOOK_AHEAD, MOD_REG, 1),
    "set-forage-target": InstSpec("set-forage-target", SEM_SET_FORAGE,
                                  MOD_REG, 1),
    "attack-prey": InstSpec(
        "attack-prey", SEM_ATTACK_PREY, MOD_REG, 1,
        "kill the faced prey (forage target > -2): attacker merit/bonus "
        "+= PRED_EFFICIENCY x prey's, attacker becomes a predator "
        "(forage target -2), success echoed to ?BX? "
        "(Inst_AttackPrey cc:5407; PRED_PREY_SWITCH >= 0 required)"),
}

_NOP_NAMES = ["nop-A", "nop-B", "nop-C", "nop-D", "nop-E", "nop-F",
              "nop-G", "nop-H"]


def build_semantic_tables(inst_names):
    """Same contract as models.heads.build_semantic_tables, with 8 nops
    mapping to registers/heads 0..7."""
    n = len(inst_names)
    sem = np.zeros(n, np.int32)
    mod_kind = np.zeros(n, np.int32)
    default_op = np.zeros(n, np.int32)
    is_nop = np.zeros(n, bool)
    nop_mod = np.zeros(n, np.int32)
    for op, name in enumerate(inst_names):
        if name in _NOP_NAMES:
            is_nop[op] = True
            nop_mod[op] = _NOP_NAMES.index(name)
            sem[op] = SEM_NOP_BASE + nop_mod[op]
            continue
        if name not in INSTRUCTIONS:
            raise ValueError(
                f"experimental hardware does not implement instruction "
                f"{name!r} yet (round-4 scope: replication base + "
                f"sensing/movement; see models/experimental.py)")
        spec = INSTRUCTIONS[name]
        sem[op] = spec.sem
        mod_kind[op] = spec.mod_kind
        default_op[op] = spec.default_operand
    return {
        "sem": sem, "mod_kind": mod_kind, "default_op": default_op,
        "is_nop": is_nop, "nop_mod": nop_mod, "num_insts": n,
    }
