"""The heads-based virtual CPU (reference HARDWARE_TYPE 0).

Defines the *semantic instruction table* for the classic heads hardware
(ref: cHardwareCPU, avida-core/source/cpu/cHardwareCPU.cc:79-560 -- the
static instruction library; execution semantics re-derived per-instruction
from the cited implementations, then re-expressed as batched tensor ops in
avida_tpu/ops/interpreter.py).

Architecture state per organism (ref cHardwareCPU.h:61-152):
  3 registers (AX, BX, CX), 4 heads (IP, READ, WRITE, FLOW), two 10-deep
  cyclic stacks (one active), a read-label buffer, memory with per-site
  executed/copied flags.

Instead of a 563-way function-pointer dispatch per instruction
(cHardwareCPU.cc:1079), each instruction is assigned a *semantic opcode* and
per-opcode metadata (operand kind, default operand, IP-advance class) that the
SIMD interpreter uses to execute the whole population in lockstep.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

# Registers (ref cHardwareCPU.h REG_AX/BX/CX)
REG_AX, REG_BX, REG_CX = 0, 1, 2
NUM_REGISTERS = 3
# Heads (ref nHardware.h:32)
HEAD_IP, HEAD_READ, HEAD_WRITE, HEAD_FLOW = 0, 1, 2, 3
NUM_HEADS = 4
NUM_NOPS = 3
STACK_SIZE = 10          # ref nHardware.h:34
MAX_LABEL_SIZE = 10      # ref cCodeLabel MAX_LENGTH

# Operand-modifier kinds: what a trailing nop modifies (ref
# FindModifiedRegister / FindModifiedHead / ReadLabel, cHardwareCPU.cc:1622+)
MOD_NONE, MOD_REG, MOD_HEAD, MOD_LABEL = 0, 1, 2, 3

# Semantic opcodes.  These are interpreter-internal; genome opcodes map to
# them through the loaded instruction set (cInstSet equivalent).
(
    SEM_NOP_A, SEM_NOP_B, SEM_NOP_C,
    SEM_IF_N_EQU, SEM_IF_LESS, SEM_IF_LABEL,
    SEM_MOV_HEAD, SEM_JMP_HEAD, SEM_GET_HEAD, SEM_SET_FLOW,
    SEM_SHIFT_R, SEM_SHIFT_L, SEM_INC, SEM_DEC,
    SEM_PUSH, SEM_POP, SEM_SWAP_STK, SEM_SWAP,
    SEM_ADD, SEM_SUB, SEM_NAND,
    SEM_H_COPY, SEM_H_ALLOC, SEM_H_DIVIDE,
    SEM_IO, SEM_H_SEARCH,
    SEM_H_DIVIDE_SEX,
    SEM_FORK_TH, SEM_KILL_TH, SEM_ID_TH,
    SEM_SET_MATE_MALE, SEM_SET_MATE_FEMALE, SEM_SET_MATE_JUV,
    SEM_IF_MATE_MALE, SEM_IF_MATE_FEMALE,
) = range(35)

NUM_SEMANTIC_OPS = 35


@dataclass(frozen=True)
class InstSpec:
    name: str
    sem: int
    mod_kind: int        # MOD_NONE / MOD_REG / MOD_HEAD / MOD_LABEL
    default_operand: int  # register or head index (meaning depends on kind)
    doc: str = ""


# The canonical heads_default set (ref support/config/instset-heads.cfg).
# Default operands follow the cited implementations:
#   if-n-equ/if-less/shift/inc/dec/push/pop/swap/add/sub/nand/IO -> ?BX?
#   set-flow -> ?CX?; mov-head/jmp-head/get-head -> ?IP?
INSTRUCTIONS = {
    "nop-A": InstSpec("nop-A", SEM_NOP_A, MOD_NONE, 0, "no-op; modifies neighbors"),
    "nop-B": InstSpec("nop-B", SEM_NOP_B, MOD_NONE, 0),
    "nop-C": InstSpec("nop-C", SEM_NOP_C, MOD_NONE, 0),
    "if-n-equ": InstSpec("if-n-equ", SEM_IF_N_EQU, MOD_REG, REG_BX,
                         "exec next iff ?BX? != reg-next (cHardwareCPU.cc:2190)"),
    "if-less": InstSpec("if-less", SEM_IF_LESS, MOD_REG, REG_BX,
                        "exec next iff ?BX? < reg-next (cHardwareCPU.cc:2235)"),
    "if-label": InstSpec("if-label", SEM_IF_LABEL, MOD_LABEL, 0,
                         "exec next iff complement label was just copied (cc:6914)"),
    "mov-head": InstSpec("mov-head", SEM_MOV_HEAD, MOD_HEAD, HEAD_IP,
                         "?IP? <- FLOW (cc:6809)"),
    "jmp-head": InstSpec("jmp-head", SEM_JMP_HEAD, MOD_HEAD, HEAD_IP,
                         "?IP? += CX (cc:6859)"),
    "get-head": InstSpec("get-head", SEM_GET_HEAD, MOD_HEAD, HEAD_IP,
                         "CX <- pos(?IP?) (cc:6907)"),
    "set-flow": InstSpec("set-flow", SEM_SET_FLOW, MOD_REG, REG_CX,
                         "FLOW <- ?CX? (cc:7270)"),
    "shift-r": InstSpec("shift-r", SEM_SHIFT_R, MOD_REG, REG_BX),
    "shift-l": InstSpec("shift-l", SEM_SHIFT_L, MOD_REG, REG_BX),
    "inc": InstSpec("inc", SEM_INC, MOD_REG, REG_BX),
    "dec": InstSpec("dec", SEM_DEC, MOD_REG, REG_BX),
    "push": InstSpec("push", SEM_PUSH, MOD_REG, REG_BX),
    "pop": InstSpec("pop", SEM_POP, MOD_REG, REG_BX),
    "swap-stk": InstSpec("swap-stk", SEM_SWAP_STK, MOD_NONE, 0),
    "swap": InstSpec("swap", SEM_SWAP, MOD_REG, REG_BX,
                     "swap ?BX? with reg-next (cc:2742)"),
    "add": InstSpec("add", SEM_ADD, MOD_REG, REG_BX,
                    "?BX? <- BX+CX (cc:2959)"),
    "sub": InstSpec("sub", SEM_SUB, MOD_REG, REG_BX),
    "nand": InstSpec("nand", SEM_NAND, MOD_REG, REG_BX,
                     "?BX? <- ~(BX&CX) (cc:3018)"),
    "h-copy": InstSpec("h-copy", SEM_H_COPY, MOD_NONE, 0,
                       "copy READ->WRITE w/ copy-mut; advance both (cc:7130)"),
    "h-alloc": InstSpec("h-alloc", SEM_H_ALLOC, MOD_NONE, 0,
                        "extend memory by OFFSPRING_SIZE_RANGE*len; AX<-old len (cc:3294)"),
    "h-divide": InstSpec("h-divide", SEM_H_DIVIDE, MOD_NONE, 0,
                         "divide at READ..WRITE (cc:6961,1775)"),
    "divide-sex": InstSpec(
        "divide-sex", SEM_H_DIVIDE_SEX, MOD_NONE, 0,
        "h-divide with sexual offspring: SetDivideSex(true)+CrossNum(1) "
        "then Divide_Main (Inst_HeadDivideSex, cc:7019-7023); offspring "
        "recombine in the birth chamber (cBirthChamber.cc:443)"),
    "IO": InstSpec("IO", SEM_IO, MOD_REG, REG_BX,
                   "output ?BX?, check tasks, input next (cc:4188)"),
    "h-search": InstSpec("h-search", SEM_H_SEARCH, MOD_LABEL, 0,
                         "FLOW <- after complement label; BX=dist, CX=size (cc:7245)"),
    # intra-organism threads (cHardwareCPU.cc:346-351, ForkThread cc:1505,
    # KillThread cc:1592; active only when MAX_CPU_THREADS > 1)
    "fork-th": InstSpec(
        "fork-th", SEM_FORK_TH, MOD_NONE, 0,
        "advance IP, then copy the current thread into a free slot "
        "(Inst_ForkThread cc:6732: child resumes at fork+1, parent at "
        "fork+2); fails silently at the thread cap"),
    "kill-th": InstSpec(
        "kill-th", SEM_KILL_TH, MOD_NONE, 0,
        "kill the current thread unless it is the last one (cc:1592)"),
    "id-th": InstSpec(
        "id-th", SEM_ID_TH, MOD_REG, REG_BX,
        "?BX? <- current thread id (Inst_ThreadID cc:6773)"),
    # mating types (cHardwareCPU.cc:425-430; phenotype mating_type starts
    # MATING_TYPE_JUVENILE=-1, female=0, male=1, core/Definitions.h:188)
    "set-mating-type-male": InstSpec(
        "set-mating-type-male", SEM_SET_MATE_MALE, MOD_NONE, 0,
        "become male unless already female (Inst_SetMatingTypeMale "
        "cc:10896)"),
    "set-mating-type-female": InstSpec(
        "set-mating-type-female", SEM_SET_MATE_FEMALE, MOD_NONE, 0,
        "become female unless already male (cc:10915)"),
    "set-mating-type-juvenile": InstSpec(
        "set-mating-type-juvenile", SEM_SET_MATE_JUV, MOD_NONE, 0,
        "revert to juvenile (cc:10934)"),
    "if-mating-type-male": InstSpec(
        "if-mating-type-male", SEM_IF_MATE_MALE, MOD_NONE, 0,
        "exec next iff male (Inst_IfMatingTypeMale)"),
    "if-mating-type-female": InstSpec(
        "if-mating-type-female", SEM_IF_MATE_FEMALE, MOD_NONE, 0,
        "exec next iff female"),
}

# Aliases found in reference instset files / organisms.
ALIASES = {
    "nop-a": "nop-A", "nop-b": "nop-B", "nop-c": "nop-C",
    "nop-x": "nop-A",  # placeholder; nop-X is a true no-op in extended sets
    "io": "IO",
    "div-sex": "divide-sex",   # cHardwareCPU.cc:394 registers both names
}


def build_semantic_tables(inst_names):
    """Map a loaded instruction set (opcode -> name) to interpreter tables.

    Returns a dict of numpy arrays indexed by *genome opcode*:
      sem[op]         semantic opcode
      mod_kind[op]    operand modifier kind
      default_op[op]  default operand (reg or head index)
      is_nop[op]      True for nop-A/B/C
      nop_mod[op]     register/head index a nop maps to (0 for non-nops)
    """
    n = len(inst_names)
    sem = np.zeros(n, np.int32)
    mod_kind = np.zeros(n, np.int32)
    default_op = np.zeros(n, np.int32)
    is_nop = np.zeros(n, bool)
    nop_mod = np.zeros(n, np.int32)
    for op, name in enumerate(inst_names):
        key = ALIASES.get(name, name)
        if key not in INSTRUCTIONS:
            raise ValueError(f"heads hardware does not implement instruction {name!r}")
        spec = INSTRUCTIONS[key]
        sem[op] = spec.sem
        mod_kind[op] = spec.mod_kind
        default_op[op] = spec.default_operand
        if spec.sem in (SEM_NOP_A, SEM_NOP_B, SEM_NOP_C):
            is_nop[op] = True
            nop_mod[op] = spec.sem  # nop-A=0, nop-B=1, nop-C=2
    return {
        "sem": sem, "mod_kind": mod_kind, "default_op": default_op,
        "is_nop": is_nop, "nop_mod": nop_mod, "num_insts": n,
    }
