"""Hardware factory: HARDWARE_TYPE -> semantic-table builder.

TPU-native equivalent of the cHardwareManager factory
(avida-core/source/cpu/cHardwareManager.cc:123-147, switch over 5 hardware
types).  Each entry maps a hardware type id to a module exposing
`build_semantic_tables(inst_names)` plus its default instruction-set file
name.  New hardware (transsmt, experimental, ...) registers here.
"""

from avida_tpu.models import experimental, heads, transsmt

HARDWARE_REGISTRY = {
    0: {"name": "heads", "module": heads,
        "default_instset": "instset-heads.cfg"},
    # reference numbering (core/Definitions.h eHARDWARE_TYPE): transsmt is
    # HARDWARE_TYPE 1 in the enum but instset files declare hw_type=2
    # (cHardwareManager::loadInstSet switch) -- accept both
    1: {"name": "transsmt", "module": transsmt,
        "default_instset": "instset-transsmt.cfg"},
    2: {"name": "transsmt", "module": transsmt,
        "default_instset": "instset-transsmt.cfg"},
    3: {"name": "experimental", "module": experimental,
        "default_instset": "instset-experimental.cfg"},
    # bcr, gp8 -- planned
}


def get_hardware(hw_type: int):
    if hw_type not in HARDWARE_REGISTRY:
        raise ValueError(
            f"HARDWARE_TYPE {hw_type} not supported yet "
            f"(available: {sorted(HARDWARE_REGISTRY)})")
    return HARDWARE_REGISTRY[hw_type]
