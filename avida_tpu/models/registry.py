"""Hardware factory: HARDWARE_TYPE -> semantic-table builder.

TPU-native equivalent of the cHardwareManager factory
(avida-core/source/cpu/cHardwareManager.cc:123-147, switch over 5 hardware
types).  Each entry maps a hardware type id to a module exposing
`build_semantic_tables(inst_names)` plus its default instruction-set file
name.  New hardware (transsmt, experimental, ...) registers here.
"""

from avida_tpu.models import heads

HARDWARE_REGISTRY = {
    0: {"name": "heads", "module": heads, "default_instset": "instset-heads.cfg"},
    # 1: transsmt (host-parasite stack machine) -- planned
    # 2: experimental, 3: bcr, 4: gp8 -- planned
}


def get_hardware(hw_type: int):
    if hw_type not in HARDWARE_REGISTRY:
        raise ValueError(
            f"HARDWARE_TYPE {hw_type} not supported yet "
            f"(available: {sorted(HARDWARE_REGISTRY)})")
    return HARDWARE_REGISTRY[hw_type]
