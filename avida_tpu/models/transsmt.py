"""The TransSMT virtual CPU (reference HARDWARE_TYPE 2) — stack-based,
multi-memory-space hardware for host–parasite coevolution.

Reference: cHardwareTransSMT (avida-core/source/cpu/cHardwareTransSMT.{cc,h}).
Architecture per organism (h:45-92):
  4 stacks (3 thread-local AX/BX/CX + 1 global DX, 10-deep), 4 nops
  (Nop-A..D selecting stacks/heads 0-3), 4 heads per thread carrying
  (memory_space, position), multiple memory spaces (space 0 = the genome;
  labels hash to auxiliary spaces, FindMemorySpaceLabel cc:376), one thread
  per active memory space, Inst_Inject (cc:1657) = parasite transmission
  into a neighbor's memory space, inherited/config virulence (cc:218-248)
  = probability a CPU cycle goes to the parasite thread.

Lockstep model (ops/interpreter_smt.py): 2 threads (host, parasite) x 2
memory spaces each (base space + ONE auxiliary write buffer) -- the stock
ancestors (support/config/default-transsmt*.org) use exactly one labeled
space; arbitrary label->space maps degenerate to the single aux space
(documented simplification).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

NUM_STACKS = 4         # 3 local + 1 global (h:45-47)
NUM_NOPS = 4
STACK_AX, STACK_BX, STACK_CX, STACK_DX = range(4)
HEAD_IP, HEAD_READ, HEAD_WRITE, HEAD_FLOW = range(4)
MAX_LABEL_SIZE = 3     # MAX_MEMSPACE_LABEL/label reads use short templates

# semantic opcodes (interpreter_smt dispatch)
(
    SEM_NOP,
    SEM_SHIFT_R, SEM_SHIFT_L, SEM_NAND, SEM_ADD, SEM_SUB, SEM_MULT,
    SEM_DIV, SEM_MOD, SEM_INC, SEM_DEC,
    SEM_SET_MEMORY, SEM_DIVIDE, SEM_READ, SEM_WRITE,
    SEM_IF_EQU, SEM_IF_NEQU, SEM_IF_LESS, SEM_IF_GTR,
    SEM_HEAD_PUSH, SEM_HEAD_POP, SEM_HEAD_MOVE, SEM_SEARCH,
    SEM_PUSH_NEXT, SEM_PUSH_PREV, SEM_PUSH_COMP,
    SEM_VAL_DELETE, SEM_VAL_COPY, SEM_IO, SEM_INJECT,
) = range(30)


@dataclass(frozen=True)
class SmtSpec:
    name: str
    sem: int
    doc: str = ""


_S = SmtSpec
INSTRUCTIONS = {
    "Nop-A": _S("Nop-A", SEM_NOP), "Nop-B": _S("Nop-B", SEM_NOP),
    "Nop-C": _S("Nop-C", SEM_NOP), "Nop-D": _S("Nop-D", SEM_NOP),
    "Nop-X": _S("Nop-X", SEM_NOP, "true no-op (not a modifier)"),
    "Val-Shift-R": _S("Val-Shift-R", SEM_SHIFT_R, "?BX? <- top>>1 (pop+push)"),
    "Val-Shift-L": _S("Val-Shift-L", SEM_SHIFT_L),
    "Val-Nand": _S("Val-Nand", SEM_NAND, "push ~(op1.top & op2.top) (cc:919)"),
    "Val-Add": _S("Val-Add", SEM_ADD), "Val-Sub": _S("Val-Sub", SEM_SUB),
    "Val-Mult": _S("Val-Mult", SEM_MULT), "Val-Div": _S("Val-Div", SEM_DIV),
    "Val-Mod": _S("Val-Mod", SEM_MOD),
    "Val-Inc": _S("Val-Inc", SEM_INC, "pop, push value+1 (cc:1010)"),
    "Val-Dec": _S("Val-Dec", SEM_DEC),
    "SetMemory": _S("SetMemory", SEM_SET_MEMORY,
                    "FLOW <- (aux space, 0) (cc:1567)"),
    "Divide": _S("Divide", SEM_DIVIDE,
                 "divide off the write-head's space (Divide_Main cc:438)"),
    "Divide-Erase": _S("Divide-Erase", SEM_DIVIDE),
    "Inst-Read": _S("Inst-Read", SEM_READ,
                    "push inst at ?READ? (copy-mut) + advance (cc:1304)"),
    "Inst-Write": _S("Inst-Write", SEM_WRITE,
                     "write popped inst at ?WRITE?, grow space (cc:1341)"),
    "If-Equal": _S("If-Equal", SEM_IF_EQU,
                   "skip next unless ?AX?.top == next.top (cc:1075)"),
    "If-Not-Equal": _S("If-Not-Equal", SEM_IF_NEQU),
    "If-Less": _S("If-Less", SEM_IF_LESS),
    "If-Greater": _S("If-Greater", SEM_IF_GTR),
    "Head-Push": _S("Head-Push", SEM_HEAD_PUSH, "push pos(?IP?) (cc:1133)"),
    "Head-Pop": _S("Head-Pop", SEM_HEAD_POP),
    "Head-Move": _S("Head-Move", SEM_HEAD_MOVE,
                    "?IP? <- FLOW; FLOW alone advances (cc:1151)"),
    "Search": _S("Search", SEM_SEARCH,
                 "complement-label search; BX dist, AX size, FLOW there "
                 "(cc:1172)"),
    "Push-Next": _S("Push-Next", SEM_PUSH_NEXT,
                    "dst=?src+1?: push src.pop (cc:1197)"),
    "Push-Prev": _S("Push-Prev", SEM_PUSH_PREV),
    "Push-Comp": _S("Push-Comp", SEM_PUSH_COMP),
    "Val-Delete": _S("Val-Delete", SEM_VAL_DELETE),
    "Val-Copy": _S("Val-Copy", SEM_VAL_COPY),
    "IO": _S("IO", SEM_IO, "output ?BX?.top, input push (cc:1231)"),
    "Inject": _S("Inject", SEM_INJECT,
                 "inject write-space code into faced neighbor (cc:1657)"),
}

ALIASES = {
    "nop-A": "Nop-A", "nop-B": "Nop-B", "nop-C": "Nop-C", "nop-D": "Nop-D",
}


def build_semantic_tables(inst_names):
    """opcode -> semantic tables for the SMT interpreter.  Same contract as
    models/heads.build_semantic_tables (mod_kind/default_op are unused by
    the SMT interpreter; operand resolution is per-semantic)."""
    n = len(inst_names)
    sem = np.zeros(n, np.int32)
    is_nop = np.zeros(n, bool)
    nop_mod = np.zeros(n, np.int32)
    for op, name in enumerate(inst_names):
        key = ALIASES.get(name, name)
        if key not in INSTRUCTIONS:
            raise ValueError(
                f"transsmt hardware does not implement instruction {name!r}")
        spec = INSTRUCTIONS[key]
        sem[op] = spec.sem
        # modifier nops are exactly Nop-A..D (Nop-X is a pure no-op)
        if key in ("Nop-A", "Nop-B", "Nop-C", "Nop-D"):
            is_nop[op] = True
            nop_mod[op] = ("Nop-A", "Nop-B", "Nop-C", "Nop-D").index(key)
    return {
        "sem": sem, "mod_kind": np.zeros(n, np.int32),
        "default_op": np.zeros(n, np.int32),
        "is_nop": is_nop, "nop_mod": nop_mod, "num_insts": n,
    }
