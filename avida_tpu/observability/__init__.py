"""Runtime telemetry: phase timers, device counters, structured run logs.

The reference engine exposes its run state through a 244-action print
library and per-cycle tracer hooks (cHardwareTracer, PrintActions.cc);
this package is the lockstep port's equivalent visibility layer BELOW
the .dat files -- where the update's wall time goes and what the device
actually executed:

  timeline.py -- `Timeline`: block_until_ready-fenced phase wall clocks
                 + optional jax.profiler trace capture
  counters.py -- device-side counter reductions: births/deaths, task
                 triggers, per-block budget-tail utilization, and the
                 instruction-dispatch-mix accumulator threaded through
                 ops/update.interpret_phase
  staged.py   -- `StagedUpdate`: the update's phase functions jitted
                 separately and fenced (bit-identical trajectory to the
                 fused ops/update.update_step)
  runlog.py   -- `TelemetryRecorder`/`TelemetryWriter`: telemetry.jsonl
                 (one JSON object per update: phases, counters, metadata)
  harness.py  -- the unified profiling CLI (replaces
                 scripts/profile_update.py) + bench.py's `phases` hook
  tracer.py   -- `FlightRecorder`: host drain of the device-side event
                 ring (births/deaths, first task triggers, scheduler
                 stalls, anomalies recorded INSIDE the jitted update;
                 TPU_TRACE=1) into {"record": "trace"} runlog lines
  exporter.py -- `MetricsExporter`: atomic metrics.prom heartbeat +
                 `python -m avida_tpu --status DIR`

Everything is opt-in (TPU_TELEMETRY=1 / `python -m avida_tpu --telemetry`)
and zero-cost when disabled: the production update program traces to the
identical jaxpr whether or not this package is imported
(tests/test_telemetry.py), and no files are written.
"""

# lazy barrel (PEP 562): most submodules import jax at module scope, but
# `python -m avida_tpu --status DIR` reaches exporter.py through this
# package and must stay jax-free (the whole point of the outside-the-
# process heartbeat reader) -- resolve names on first touch instead
_EXPORTS = {
    "budget_block": "counters", "budget_tail": "counters",
    "dispatch_init": "counters", "update_counters": "counters",
    "MetricsExporter": "exporter",
    "profile_phases": "harness",
    "TelemetryRecorder": "runlog", "TelemetryWriter": "runlog",
    "StagedUpdate": "staged",
    "Timeline": "timeline",
    "EVENT_CODES": "tracer", "FlightRecorder": "tracer",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib
    return getattr(
        importlib.import_module(f"avida_tpu.observability.{mod}"), name)
