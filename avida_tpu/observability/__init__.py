"""Runtime telemetry: phase timers, device counters, structured run logs.

The reference engine exposes its run state through a 244-action print
library and per-cycle tracer hooks (cHardwareTracer, PrintActions.cc);
this package is the lockstep port's equivalent visibility layer BELOW
the .dat files -- where the update's wall time goes and what the device
actually executed:

  timeline.py -- `Timeline`: block_until_ready-fenced phase wall clocks
                 + optional jax.profiler trace capture
  counters.py -- device-side counter reductions: births/deaths, task
                 triggers, per-block budget-tail utilization, and the
                 instruction-dispatch-mix accumulator threaded through
                 ops/update.interpret_phase
  staged.py   -- `StagedUpdate`: the update's phase functions jitted
                 separately and fenced (bit-identical trajectory to the
                 fused ops/update.update_step)
  runlog.py   -- `TelemetryRecorder`/`TelemetryWriter`: telemetry.jsonl
                 (one JSON object per update: phases, counters, metadata)
  harness.py  -- the unified profiling CLI (replaces
                 scripts/profile_update.py) + bench.py's `phases` hook

Everything is opt-in (TPU_TELEMETRY=1 / `python -m avida_tpu --telemetry`)
and zero-cost when disabled: the production update program traces to the
identical jaxpr whether or not this package is imported
(tests/test_telemetry.py), and no files are written.
"""

from avida_tpu.observability.counters import (budget_block, budget_tail,
                                              dispatch_init, update_counters)
from avida_tpu.observability.harness import profile_phases
from avida_tpu.observability.runlog import TelemetryRecorder, TelemetryWriter
from avida_tpu.observability.staged import StagedUpdate
from avida_tpu.observability.timeline import Timeline

__all__ = [
    "Timeline", "StagedUpdate", "TelemetryRecorder", "TelemetryWriter",
    "profile_phases", "budget_block", "budget_tail", "dispatch_init",
    "update_counters",
]
