"""Declarative alert rules evaluated over the telemetry history rings.

The detection half of the observability plane: a small rule language
(`threshold` / `rate-over-window` / `staleness`, each with an optional
`for` duration) evaluated host-side over the `.hist.jsonl` rings that
observability/history.py appends beside every .prom snapshot.  The
evaluators are the processes that ALREADY poll heartbeats -- the run
supervisor (service/supervisor.py) and the fleet orchestrator
(service/fleet.py) -- plus the standalone `scripts/metrics_tool.py
watch` for spectators; no new processes, and nothing here imports jax.

Rule shape (built-in defaults below; `alerts.json` in the data dir /
spool overrides or extends them, merged by name):

    {"name": "stall", "family": "avida_update",
     "kind": "rate", "op": "<=", "value": 0.0, "window_sec": 60,
     "for_sec": 0, "severity": "page", "action": null,
     "labels": null, "ring": "metrics", "enabled": true}

`ring` names the history ring the rule reads ("metrics" /
"multiworld" / "supervisor" / "fleet" -- the .hist.jsonl basename).
Rings are never merged across a rule: a serve batch's metrics ring
carries the batch-max update counter while its multiworld ring carries
per-tenant rows, and mixing the two would sawtooth any rate rule into
false pages every time a fresh tenant is admitted.  A rule with no
ring reads every ring the evaluator supplies (custom rules on families
that live in exactly one ring can omit it safely).  An evaluator that
does not own a rule's ring simply never fires it -- the fleet
orchestrator carries the run-level defaults harmlessly and vice
versa.

  kind=threshold   newest ring value of `family` compared `op value`;
                   labeled families collapse per sample to the WORST
                   row for the rule's direction (max for > rules, min
                   for < rules), so the alert fires when ANY series
                   trips
  kind=rate        per-second step-interpolated rate of `family` over
                   the trailing `window_sec`, compared `op value`; not
                   evaluable (never fires) until the ring spans the
                   window -- a run that just started is not stalled --
                   but a publisher that STOPPED appending still
                   evaluates (its counter definitionally went flat)
  kind=staleness   seconds since the family's newest ring sample,
                   compared > `value` (+ `for_sec`, which folds into
                   the threshold exactly -- age grows monotonically
                   between samples); an empty ring never fires (no
                   history is not evidence of staleness)

`for_sec` demands the condition hold continuously for that long before
the rule fires (evaluated statelessly by walking the ring backwards, so
a freshly-restarted evaluator reaches the same verdict).  A firing rule
resolves the moment its condition clears.

Alert state is journaled on EDGES as `{"record": "alert"}` lines in
`alerts.jsonl` beside the evaluator's journal, and exported as
`avida_alerts_firing{rule=...}` / `avida_alerts_fired_total{rule=...}`
families on the evaluator's existing .prom file.  Rules marked
`action: "degrade-hint"` additionally feed a breadcrumb into the fleet
failure tally / circuit breaker (admission pause at worst) -- this is a
detection plane, not a second supervisor: no rule ever kills a child.
"""

from __future__ import annotations

import json
import os
import time

from avida_tpu.observability import history

ALERTS_FILE = "alerts.jsonl"
RULES_FILE = "alerts.json"

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}

KINDS = ("threshold", "rate", "staleness")
SEVERITIES = ("info", "warn", "page")
ACTIONS = (None, "degrade-hint")


class Rule:
    """One declarative alert rule (see the module docstring for the
    JSON shape)."""

    __slots__ = ("name", "family", "kind", "op", "value", "window_sec",
                 "for_sec", "severity", "action", "labels", "ring",
                 "enabled")

    def __init__(self, name, family, kind, value, op=">", window_sec=60.0,
                 for_sec=0.0, severity="warn", action=None, labels=None,
                 ring=None, enabled=True):
        if kind not in KINDS:
            raise ValueError(f"alert rule {name!r}: unknown kind {kind!r} "
                             f"(one of {KINDS})")
        if op not in _OPS:
            raise ValueError(f"alert rule {name!r}: unknown op {op!r} "
                             f"(one of {sorted(_OPS)})")
        if severity not in SEVERITIES:
            raise ValueError(f"alert rule {name!r}: unknown severity "
                             f"{severity!r} (one of {SEVERITIES})")
        if action not in ACTIONS:
            raise ValueError(f"alert rule {name!r}: unknown action "
                             f"{action!r} (one of {ACTIONS})")
        self.name = str(name)
        self.family = str(family)
        self.kind = kind
        self.op = op
        try:
            # loud-but-survivable contract: a null/garbage numeric in
            # alerts.json must surface as ValueError, the one class the
            # supervisor/fleet guards catch when disabling alerts
            self.value = float(value)
            self.window_sec = float(window_sec)
            self.for_sec = float(for_sec)
        except (TypeError, ValueError) as e:
            raise ValueError(f"alert rule {name!r}: non-numeric "
                             f"value/window_sec/for_sec ({e})") from e
        self.severity = severity
        self.action = action
        self.labels = labels
        self.ring = None if ring is None else str(ring)
        self.enabled = bool(enabled)

    @property
    def agg(self):
        """How labeled rows collapse per sample: the WORST series for
        this rule's direction, so any-series-trips holds for both
        above- and below-threshold rules (history.series)."""
        return min if self.op in ("<", "<=") else max

    @classmethod
    def from_dict(cls, d) -> "Rule":
        if not isinstance(d, dict):
            raise ValueError(f"alert rule must be a JSON object: {d!r}")
        known = {"name", "family", "kind", "op", "value", "window_sec",
                 "for_sec", "severity", "action", "labels", "ring",
                 "enabled"}
        junk = set(d) - known
        if junk:
            raise ValueError(f"alert rule {d.get('name')!r}: unknown "
                             f"field(s) {sorted(junk)}")
        for req in ("name", "family", "kind", "value"):
            if req not in d:
                raise ValueError(f"alert rule needs {req!r}: {d!r}")
        return cls(**d)

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__slots__}


# ---------------------------------------------------------------------------
# built-in defaults: one rule per gauge the ROADMAP already cares about
# ---------------------------------------------------------------------------

def default_rules() -> list:
    return [
        # the heartbeat itself went quiet: the publisher wedged or died
        # (the supervisor's watchdog will act; this is the page)
        Rule("heartbeat_stale", "avida_heartbeat_timestamp_seconds",
             "staleness", 120.0, severity="page", ring="metrics"),
        # livelock: the update counter stopped advancing -- fires both
        # when publishes continue with a flat counter (wedged
        # scheduler) and when publishes stop entirely (hung chunk).
        # Pinned to the metrics ring: a serve batch's multiworld ring
        # carries PER-TENANT counters whose membership churns, which
        # a rate rule must never see
        Rule("stall", "avida_update", "rate", 0.0, op="<=",
             window_sec=60.0, severity="page", ring="metrics"),
        # world-axis batching occupancy collapsed: stragglers are
        # burning the batch's lockstep budget (PR-11 gauge)
        Rule("batch_efficiency_collapse",
             "avida_multiworld_batch_efficiency", "threshold", 0.2,
             op="<", for_sec=60.0, severity="warn", ring="multiworld"),
        # admissions cannot keep up: the queue has grown across the
        # whole window (fleet ring; PR-12 gauge)
        Rule("queue_growth", "avida_fleet_queue_depth", "rate", 0.0,
             op=">", window_sec=300.0, for_sec=300.0, severity="warn",
             ring="fleet"),
        # the integrity plane caught silent corruption (PR-14): every
        # mismatch means a rollback already happened -- page, and hint
        # the fleet that this device/class is suspect
        Rule("integrity_mismatch", "avida_integrity_mismatches_total",
             "threshold", 0.0, op=">", severity="page",
             action="degrade-hint", ring="metrics"),
        # the persistent AOT program cache is falling back to fresh
        # compiles (PR-13): cold-start windows are back
        Rule("compile_cache_errors", "avida_compile_cache_errors_total",
             "threshold", 0.0, op=">", severity="warn",
             ring="metrics"),
    ]


def load_rules(search_dir: str | None = None,
               rules_path: str | None = None) -> list:
    """Built-in defaults merged with an optional `alerts.json` override
    file (a JSON list of rule dicts; same-name entries replace the
    default -- set `"enabled": false` to drop one -- and new names
    extend the set).  A malformed file raises: a silently-ignored
    alert config is worse than a loud startup failure."""
    rules = {r.name: r for r in default_rules()}
    path = rules_path
    if path is None and search_dir:
        cand = os.path.join(search_dir, RULES_FILE)
        path = cand if os.path.exists(cand) else None
    if path:
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, list):
            raise ValueError(f"{path}: alerts.json must be a JSON list "
                             f"of rule objects")
        for d in doc:
            r = Rule.from_dict(d)
            rules[r.name] = r
    return [r for r in rules.values() if r.enabled]


# ---------------------------------------------------------------------------
# stateless evaluation over a ring's samples
# ---------------------------------------------------------------------------

def _condition_at(rule: Rule, pts: list, t: float):
    """(holds, value) of the rule's raw condition as-of time `t`
    (staleness is handled by the caller -- it needs `now`, not a
    historical as-of)."""
    if rule.kind == "threshold":
        v = history.value_asof(pts, t)
        if v is None:
            return False, None
        return _OPS[rule.op](v, rule.value), v
    if rule.kind == "rate":
        r = history.rate_over(pts, t, rule.window_sec)
        if r is None:
            return False, None
        return _OPS[rule.op](r, rule.value), r
    raise AssertionError(rule.kind)


def evaluate_rule(rule: Rule, samples: list, now: float) -> dict:
    """{"firing": bool, "value": newest observed value/rate/age,
    "since": unix time the condition started holding (when firing)}.

    `for_sec` is evaluated statelessly: the condition must hold at
    `now` AND at every as-of point back through the trailing `for_sec`
    (sample times, plus the window edge), so a freshly-restarted
    evaluator reaches the same verdict as one that watched live."""
    pts = history.series(samples, rule.family, labels=rule.labels,
                         agg=rule.agg)
    if rule.kind == "staleness":
        if not pts:
            return {"firing": False, "value": None, "since": None}
        age = now - pts[-1][0]
        # for_sec folds into the threshold: with no fresh sample the
        # age grows monotonically, so "age > value held for for_sec"
        # is EXACTLY "age > value + for_sec" (any fresh sample resets
        # both clocks at once)
        effective = rule.value + rule.for_sec
        firing = age > effective
        return {"firing": firing, "value": round(age, 3),
                "since": pts[-1][0] + effective if firing else None}
    holds, value = _condition_at(rule, pts, now)
    if not holds:
        return {"firing": False, "value": value, "since": None}
    # walk the as-of points inside [now - for_sec, now]; the condition
    # must hold at each for the rule to fire.  With for_sec == 0 the
    # edge time IS the onset -- no backwards walk: this runs on the
    # supervision hot path every alert tick, and an O(ring) scan per
    # as-of point while a counter alert stays firing would make each
    # evaluation quadratic in the ring tail
    since = now
    if rule.for_sec > 0:
        cut = now - rule.for_sec
        asof = sorted({t for t, _ in pts if cut <= t <= now} | {cut})
        for t in asof:
            h, _ = _condition_at(rule, pts, t)
            if not h:
                return {"firing": False, "value": value, "since": None}
        since = cut
    return {"firing": True, "value": value, "since": since}


def samples_for(rule: Rule, samples) -> list:
    """The sample rows a rule may see.  `samples` is either a flat
    list (the rule sees everything -- unit-test and single-ring
    callers) or a {ring_name: samples} dict, in which case a ring-
    pinned rule reads ITS ring only and an unpinned rule reads the
    time-ordered concatenation.  Rings are never merged for a pinned
    rule: one family can mean different things in different rings
    (batch-max vs per-tenant avida_update on a serve child)."""
    if not isinstance(samples, dict):
        return samples
    if rule.ring is not None:
        return samples.get(rule.ring, [])
    merged = [s for rows in samples.values() for s in rows]
    merged.sort(key=lambda r: r.get("time", 0.0))
    return merged


def evaluate(rules: list, samples, now: float | None = None) -> dict:
    """{rule name: evaluate_rule result} for every enabled rule.
    `samples` is a flat row list or a {ring: rows} dict (see
    samples_for)."""
    now = time.time() if now is None else now
    return {r.name: evaluate_rule(r, samples_for(r, samples), now)
            for r in rules}


# ---------------------------------------------------------------------------
# the stateful edge-detector the poll loops embed
# ---------------------------------------------------------------------------

class AlertPlane:
    """Owns rule evaluation for one evaluator process: journals
    firing/resolved EDGES to `alerts.jsonl` (rotation-pair, durable --
    alert history is postmortem evidence), tallies fired counts, and
    renders the `avida_alerts_*` families for the evaluator's .prom
    file.  Never raises out of observe(): a broken ring or journal must
    not take down the supervision loop that hosts it."""

    def __init__(self, rules: list, journal_path: str | None = None,
                 max_bytes: int = 4 << 20, on_transition=None):
        self.rules = {r.name: r for r in rules}
        self.journal_path = journal_path
        self.max_bytes = int(max_bytes)
        self.firing: dict = {}          # name -> since (unix time)
        self.fired_total = {r.name: 0 for r in rules}
        self.last_values: dict = {}
        # hook(rule, state_str, result) on every edge -- the fleet's
        # degrade-hint breadcrumb rides this
        self.on_transition = on_transition

    def observe(self, samples, now: float | None = None) -> list:
        """Evaluate every rule against `samples` (a flat row list or a
        {ring: rows} dict -- see samples_for); journal and return the
        edge transitions ([(rule_name, "firing"|"resolved", result),
        ...])."""
        now = time.time() if now is None else now
        transitions = []
        try:
            results = evaluate(list(self.rules.values()), samples, now)
        except Exception:
            return transitions
        for name, res in results.items():
            self.last_values[name] = res.get("value")
            was = name in self.firing
            if res["firing"] and not was:
                self.firing[name] = res.get("since") or now
                self.fired_total[name] += 1
                transitions.append((name, "firing", res))
            elif not res["firing"] and was:
                del self.firing[name]
                transitions.append((name, "resolved", res))
        for name, state, res in transitions:
            self._journal(name, state, res, now)
            if self.on_transition is not None:
                try:
                    self.on_transition(self.rules[name], state, res)
                except Exception:
                    pass
        return transitions

    def _journal(self, name: str, state: str, res: dict, now: float):
        if not self.journal_path:
            return
        rule = self.rules[name]
        rec = {"record": "alert", "rule": name, "state": state,
               "time": round(now, 3), "severity": rule.severity,
               "family": rule.family, "kind": rule.kind}
        if res.get("value") is not None:
            rec["value"] = res["value"]
        if state == "firing" and res.get("since") is not None:
            rec["since"] = round(res["since"], 3)
        if rule.action:
            rec["action"] = rule.action
        try:
            # durable append through the shared jax-free spelling of
            # the runlog rotation discipline (history.append_line)
            history.append_line(self.journal_path, rec,
                                max_bytes=self.max_bytes, durable=True)
        except OSError:
            pass

    def families(self) -> list:
        """The exporter.render_families tuples for the evaluator's
        .prom file: per-rule firing gauges (0/1 for every rule, so a
        scraper sees resolution, not sample disappearance) and the
        cumulative fired counter."""
        if not self.rules:
            return []
        return [
            ("avida_alerts_firing", "gauge",
             "1 while the named alert rule's condition holds",
             {f'rule="{n}"': int(n in self.firing)
              for n in sorted(self.rules)}),
            ("avida_alerts_fired_total", "counter",
             "alert rule firing edges since this evaluator started",
             {f'rule="{n}"': self.fired_total[n]
              for n in sorted(self.rules)}),
        ]


def read_alert_records(journal_path: str) -> list:
    """All {"record": "alert"} lines across the rotation pair, oldest
    first (the trace_tool/metrics_tool reader)."""
    out = []
    for p in (journal_path + ".1", journal_path):
        try:
            f = open(p)
        except OSError:
            continue
        with f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("record") == "alert":
                    out.append(rec)
    return out


def firing_from_metrics(metrics: dict) -> dict:
    """{rule: fired_total} for FIRING rules plus the full rule set,
    parsed from an evaluator's .prom dict -- the `--status` column's
    source.  Returns {"firing": {rule: 1}, "fired": {rule: n},
    "rules": [names]}."""
    firing, fired, names = {}, {}, set()
    for k, v in metrics.items():
        if k.startswith('avida_alerts_firing{rule="'):
            name = k.split('rule="', 1)[1].rstrip('"}')
            names.add(name)
            if v:
                firing[name] = int(v)
        elif k.startswith('avida_alerts_fired_total{rule="'):
            name = k.split('rule="', 1)[1].rstrip('"}')
            names.add(name)
            if v:
                fired[name] = int(v)
    return {"firing": firing, "fired": fired, "rules": sorted(names)}


def format_alert_status(metrics: dict) -> str | None:
    """One-line digest of an evaluator's alert families for --status
    (None when the .prom carries no alert plane)."""
    d = firing_from_metrics(metrics)
    if not d["rules"]:
        return None
    if not d["firing"]:
        total = sum(d["fired"].values())
        suffix = f", {total} fired so far" if total else ""
        return f"alerts      none firing ({len(d['rules'])} rules{suffix})"
    parts = [f"{n} FIRING ({d['fired'].get(n, 0)}x)"
             for n in sorted(d["firing"])]
    return "alerts      " + ", ".join(parts)
