"""Device-side telemetry counters.

Everything here is a small jitted reduction over existing state -- the
counters read what the engine already tracks (insts_executed,
birth_update, task_exe_total, the granted budget vector) rather than
adding bookkeeping to the hot path.  The one exception is the
instruction-dispatch mix, which needs a per-cycle accumulator threaded
through the update's while_loop: ops/update.interpret_phase takes an
optional int32[num_insts] `counters` carry and scatter-adds the opcode
under every scheduled lane's IP each cycle (ops/interpreter.fetch_opcode).
On the default single-thread path the mix sums exactly to the update's
executed-instruction count.  The Pallas kernel path does not collect the
mix (an in-kernel [num_insts] scatter per cycle is not cheap); its
harness reports the budget/phase counters only, which need no kernel
changes because `granted` is a kernel *input*.

The budget-tail counters quantify the remaining uncapped throughput gap
called out in ROUND5_NOTES.md: each kernel block's while_loop runs to
the max granted budget of ITS lanes, so

    utilization = granted.sum() / sum_b(block_size * max_b(granted))

is the fraction of lockstep lane-cycles doing useful work (1.0 = no
tail waste).  On the XLA path the whole population is one block.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def dispatch_init(params):
    """Zeroed dispatch-mix accumulator for interpret_phase's `counters`."""
    return jnp.zeros(params.num_insts, jnp.int32)


@partial(jax.jit, static_argnums=0)
def update_counters(params, st, alive_before, update_no):
    """Per-update counter block, computed AFTER the birth flush so the
    birth/death accounting matches what summarize()/light_stats() feed the
    .dat files.  Returns a dict of device scalars plus the task-execution
    lifetime totals vector (the host diffs consecutive updates, exactly
    like tasks_exe.dat).  Budget blocking lives in budget_tail."""
    alive = st.alive
    n_alive = alive.sum()
    births = (alive & (st.birth_update == update_no)).sum()
    deaths = jnp.maximum(alive_before + births - n_alive, 0)
    return {
        "organisms": n_alive,
        "births": births,
        "deaths": deaths,
        "divides_total": st.num_divides.sum(),
        "task_exe_totals": st.task_exe_total.sum(axis=0),
    }


@partial(jax.jit, static_argnums=1)
def budget_tail(granted, block):
    """Per-block budget-tail utilization of the granted budget vector.
    Returns device scalars: granted_sum, ceiling_sum (sum over blocks of
    block_size * block_max -- the lane-cycles the lockstep loop actually
    burns; the ceiling itself is ops/scheduler.block_ceiling, the SAME
    definition perm_phase's early-refresh trigger uses), block_max_max
    and block_mean_mean (mean-vs-max granted budget per block, the ~1.5x
    gap ROUND5_NOTES.md identifies)."""
    from avida_tpu.ops.scheduler import block_ceiling
    n = granted.shape[0]
    pad = (-n) % block
    g = jnp.pad(granted, (0, pad))            # padded lanes grant 0 cycles
    gb = g.reshape(-1, block)
    return {
        # f32 totals: the int32 lane-cycle sums wrap at bench scale once
        # uncapped grants pass ~20k cycles (see scheduler.block_ceiling)
        "granted_sum": granted.astype(jnp.float32).sum(),
        "ceiling_sum": block_ceiling(granted, block),
        "block_max_max": gb.max(axis=1).max(),
        "block_mean_mean": gb.mean(axis=1).mean(),
    }


def budget_block(params, n) -> int:
    """Blocking granularity of the current interpret path: the Pallas
    launch block when the kernel runs, else the whole population (the XLA
    while_loop runs every lane to the global max)."""
    from avida_tpu.ops.update import use_pallas_path
    if use_pallas_path(params):
        from avida_tpu.ops.pallas_cycles import block_dims
        return block_dims(params, n)[0]
    return n
