"""Prometheus-style metrics export: make a long run observable from outside.

World.run rewrites `DATA_DIR/metrics.prom` at every update-chunk boundary
(atomic tmp + rename, the same publish discipline as native checkpoints)
whenever TPU_METRICS=1 or the flight recorder (TPU_TRACE=1) is on.  The
file is the textfile-collector flavor of the Prometheus exposition
format: `# HELP` / `# TYPE` comment pairs followed by `name value` lines,
so a node-exporter textfile collector (or any scraper that can read a
file) picks a live run up with zero integration work.

`python -m avida_tpu --status DIR` is the human side of the same file:
it prints the last heartbeat (update number, organisms, births, trace
drops, how stale the heartbeat is) without touching the running process.

The export reads a handful of device scalars the driver already
maintains (_avida_time, _total_births, _prev_alive) plus host counters.
On the live path the readback is DEFERRED one chunk (capture refs at
boundary N, publish at boundary N+1 when that chunk has finished) so it
never fences the dispatch pipeline -- the same deferral the systematics
newborn drain and the flight-recorder drain use; only the final
exit/preempt heartbeat syncs.
"""

from __future__ import annotations

import os
import time

import numpy as np

from avida_tpu.observability import history, profiler
from avida_tpu.utils import compilecache, integrity

METRICS_FILE = "metrics.prom"
MULTIWORLD_METRICS_FILE = "multiworld.prom"

_HELP = {
    "avida_update": ("counter", "updates completed by the run"),
    "avida_organisms": ("gauge", "living organisms at the last boundary"),
    "avida_births_total": ("counter", "cumulative births"),
    "avida_deaths_last_update": ("gauge", "deaths in the last update"),
    "avida_generation_avg": ("gauge", "population average generation"),
    "avida_time": ("counter", "avida time (sum of 1/ave_gestation)"),
    "avida_insts_total": ("counter", "organism instructions executed"),
    "avida_preempted": ("gauge", "1 after a SIGTERM/SIGINT preemption"),
    "avida_trace_events_total": ("counter",
                                 "flight-recorder events drained"),
    "avida_trace_dropped_total": ("counter",
                                  "flight-recorder events dropped "
                                  "(ring overflow, oldest first)"),
    "avida_trace_code_total": ("counter",
                               "flight-recorder events by code name"),
    "avida_heartbeat_timestamp_seconds": ("gauge",
                                          "unix time of the last export"),
    "avida_state_digest": ("gauge",
                           "order-stable u32 state digest at the last "
                           "digested chunk boundary (ops/digest.py)"),
    "avida_state_digest_update": ("gauge",
                                  "update the exported state digest "
                                  "describes"),
}


def render_metrics(world) -> str:
    """The exposition text for a world's current host-visible state.
    This is the SYNCHRONOUS flavor -- `_flush_exec()` and the
    `np.asarray` readbacks fence any chunk still in flight -- so
    World.run uses it only for the exit/preempt final heartbeat; live
    chunk boundaries go through `MetricsExporter.export_deferred`, which
    never blocks the dispatch pipeline."""
    tracer = getattr(world, "tracer", None)
    organisms = (int(np.asarray(world._prev_alive))
                 if world._prev_alive is not None
                 else (int(np.asarray(world.state.alive).sum())
                       if world.state is not None else 0))
    values = {
        "avida_update": int(world.update),
        "avida_organisms": organisms,
        "avida_births_total": int(np.asarray(world._total_births)),
        "avida_deaths_last_update": int(np.asarray(world._deaths_this)),
        "avida_generation_avg": round(
            float(np.asarray(world._last_ave_gen)), 4),
        "avida_time": round(float(np.asarray(world._avida_time)), 6),
        "avida_insts_total": int(world._flush_exec()),
        "avida_preempted": int(bool(world.preempted or world._preempt)),
        "avida_heartbeat_timestamp_seconds": round(time.time(), 3),
    }
    digest = getattr(world, "state_digest", None)
    if digest is not None:
        # integrity plane armed (ops/digest.py): the last resolved
        # chunk-boundary digest + the update it describes.  Absent when
        # digesting is off, so those files stay byte-compatible.
        values["avida_state_digest"] = digest[1]
        values["avida_state_digest_update"] = digest[0]
    trace = None
    if tracer is not None:
        trace = (int(tracer.events_total), int(tracer.dropped_total),
                 dict(tracer.code_totals))
    return _render(values, trace)


def render_families(families) -> str:
    """Generic exposition renderer: families is an iterable of
    (name, kind, help, value) where value is a scalar or a
    {'label="x"': value} dict (one sample line per label set).  Shared
    by the run heartbeat below and the supervisor's own counter file
    (service/supervisor.py)."""
    lines = []
    for name, kind, help_, value in families:
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {kind}")
        if isinstance(value, dict):
            for label, v in sorted(value.items()):
                lines.append(f"{name}{{{label}}} {v}")
        else:
            lines.append(f"{name} {value}")
    return "\n".join(lines) + "\n"


def _render(values: dict, trace) -> str:
    """Exposition text from a resolved values dict (+ optional trace
    counter triple (events_total, dropped_total, code_totals)).  The
    avida_compile_cache_* families ride every flavor of the run
    heartbeat (empty for cache-off processes, so those files are
    byte-compatible with pre-cache builds)."""
    if trace is not None:
        events_total, dropped_total, _ = trace
        values = dict(values,
                      avida_trace_events_total=events_total,
                      avida_trace_dropped_total=dropped_total)
    families = [(name, *_HELP[name], value)
                for name, value in values.items()]
    if trace is not None:
        families.append(
            ("avida_trace_code_total", *_HELP["avida_trace_code_total"],
             {f'code="{code}"': count
              for code, count in trace[2].items()}))
    families += compilecache.prom_families()
    families += integrity.prom_families()
    families += profiler.prom_families()
    return render_families(families)


def write_metrics(path: str, text: str, durable: bool = True):
    """Atomic publish: a scraper never sees a half-written file.
    `durable=False` skips the fsync -- the live chunk-boundary path uses
    it so a per-update boundary (event-forced stretch=1) never pays disk
    flush latency; the rename alone keeps the file torn-proof, and the
    final exit/preempt heartbeat republishes durably anyway."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
        if durable:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)


def read_metrics(path: str) -> dict:
    """Parse an exposition file back into {name or name{labels}: float}
    (the file flavor of history.parse_exposition -- ONE parser, so ring
    samples can never disagree with .prom reads)."""
    with open(path) as f:
        return history.parse_exposition(f.read())


def format_status(metrics: dict, now: float | None = None,
                  hist_path: str | None = None) -> str:
    """Human-readable heartbeat digest of a metrics.prom dict.  With
    `hist_path` (the metrics history ring beside the snapshot,
    observability/history.py), a one-line recent-rate summary is
    appended -- honest "no history" when the ring is absent/short."""
    now = time.time() if now is None else now
    hb = metrics.get("avida_heartbeat_timestamp_seconds")
    age = f"{now - hb:.1f}s ago" if hb else "unknown"
    lines = [
        f"update      {int(metrics.get('avida_update', 0))}",
        f"organisms   {int(metrics.get('avida_organisms', 0))}",
        f"births      {int(metrics.get('avida_births_total', 0))}",
        f"insts       {int(metrics.get('avida_insts_total', 0))}",
        f"generation  {metrics.get('avida_generation_avg', 0.0):.2f}",
        f"heartbeat   {age}",
    ]
    if "avida_trace_events_total" in metrics:
        lines.append(
            f"trace       "
            f"{int(metrics['avida_trace_events_total'])} events, "
            f"{int(metrics.get('avida_trace_dropped_total', 0))} dropped")
    if "avida_compile_cache_hits_total" in metrics \
            or "avida_compile_cache_misses_total" in metrics:
        # persistent AOT program cache (utils/compilecache.py): how
        # this process got its compiled programs -- deserialized (hits)
        # vs freshly traced (misses) -- and what each side cost
        lines.append(
            f"cache       "
            f"{int(metrics.get('avida_compile_cache_hits_total', 0))} "
            f"loads "
            f"({metrics.get('avida_compile_cache_load_ms_total', 0.0):.0f}"
            f"ms), "
            f"{int(metrics.get('avida_compile_cache_misses_total', 0))} "
            f"compiles "
            f"({metrics.get('avida_compile_cache_compile_ms_total', 0.0):.0f}"
            f"ms), "
            f"{int(metrics.get('avida_compile_cache_errors_total', 0))} "
            f"fallbacks")
    if "avida_state_digest" in metrics \
            or "avida_integrity_scrubs_total" in metrics:
        # integrity plane (ops/digest.py): the last boundary digest and
        # the scrub tally -- a nonzero mismatch count here means the
        # run ALREADY hit silent corruption and was rolled back
        parts = []
        if "avida_state_digest" in metrics:
            parts.append(
                f"digest {int(metrics['avida_state_digest']) & 0xFFFFFFFF:#010x}"
                f" @u{int(metrics.get('avida_state_digest_update', 0))}")
        parts.append(
            f"{int(metrics.get('avida_integrity_scrubs_total', 0))} "
            f"scrubs")
        parts.append(
            f"{int(metrics.get('avida_integrity_mismatches_total', 0))} "
            f"mismatches")
        lines.append("integrity   " + ", ".join(parts))
    perf_line = profiler.format_status_block(metrics)
    if perf_line is not None:
        # performance attribution plane (observability/profiler.py):
        # chunk walls, last probe's phase split, resident footprint
        lines.append(perf_line)
    if hist_path is not None:
        lines.append("history     "
                     + history.recent_rate_line(hist_path, now=now))
    if metrics.get("avida_preempted"):
        lines.append("preempted   yes (resume with --resume)")
    return "\n".join(lines)


def analytics_census_digest(analytics: dict,
                            metrics: dict | None = None) -> dict:
    """The census facts every status surface renders
    (analyze/pipeline.py's analytics.prom families): census update, its
    age against the run heartbeat's update counter (None without one),
    dominant gid/lineage depth, and the tasks-held mask + popcount.
    Shared by the single-run `--status` line below and the fleet
    per-tenant column (service/fleet.py) so the derivation lives once."""
    cu = int(analytics.get("avida_analytics_census_update", 0))
    age = None
    if metrics and "avida_update" in metrics:
        age = max(int(metrics["avida_update"]) - cu, 0)
    held = int(analytics.get("avida_analytics_tasks_held_mask", 0))
    return {
        "update": cu,
        "age": age,
        "gid": int(analytics.get("avida_analytics_dominant_genotype_id",
                                 -1)),
        "depth": int(analytics.get(
            "avida_analytics_dominant_lineage_depth", 0)),
        "tasks_mask": held,
        "tasks_held": bin(held).count("1"),
    }


def format_analytics_status(metrics: dict, analytics: dict) -> str:
    """One-line digest of an analytics.prom census for `--status`."""
    d = analytics_census_digest(analytics, metrics)
    age = "" if d["age"] is None else f" (age {d['age']} updates)"
    return (f"analytics   census @ update {d['update']}{age}, "
            f"dominant gid {d['gid']} depth {d['depth']}, "
            f"tasks {d['tasks_mask']:#x} ({d['tasks_held']} held)")


def multiworld_rows(mw: dict) -> dict:
    """{world_name: {family: value}} from a multiworld.prom dict --
    the per-world {world="..."} labeled samples regrouped by world.
    Shared by the `--status` view below and the fleet per-tenant
    sub-rows (service/fleet.py) so the parse lives once."""
    rows: dict = {}
    for k, v in mw.items():
        if '{world="' not in k:
            continue
        fam, label = k.split('{world="', 1)
        rows.setdefault(label.rstrip('"}'), {})[fam] = v
    return rows


def format_multiworld_status(mw: dict) -> str:
    """The batch block of `--status` for a --worlds run: one batch
    summary line (size, in-program batch efficiency, worst straggler)
    plus one sub-row per world with its straggler lag."""
    size = int(mw.get("avida_multiworld_size", 0))
    eff = mw.get("avida_multiworld_batch_efficiency")
    rows = multiworld_rows(mw)
    lags = {n: float(d.get("avida_multiworld_straggler_lag_updates", 0.0))
            for n, d in rows.items()}
    head = f"batch       {size} worlds"
    if eff is not None:
        head += f", efficiency {float(eff):.2f}"
    if lags:
        head += f", worst straggler lag {max(lags.values()):.1f}u"
    lines = [head]
    for n in sorted(rows):
        d = rows[n]
        lines.append(
            f"  {n:<18} u{int(d.get('avida_update', 0))} "
            f"organisms {int(d.get('avida_organisms', 0))} "
            f"lag {lags.get(n, 0.0):.1f}u")
    return "\n".join(lines)


def status_main(data_dir: str, max_age: float | None = None) -> int:
    """`python -m avida_tpu --status DIR [--max-age SEC]`: print the
    last heartbeat.  Exit status is machine-consumable so external
    watchdogs/cron can alert on it: 0 = heartbeat present (and fresh,
    when --max-age is given), 1 = no metrics file, 2 = heartbeat missing
    from the file or staler than max_age seconds."""
    path = os.path.join(data_dir, METRICS_FILE)
    if not os.path.exists(path):
        print(f"no {METRICS_FILE} under {data_dir!r} (run with "
              f"TPU_METRICS=1 or TPU_TRACE=1)")
        return 1
    metrics = read_metrics(path)
    print(format_status(metrics, hist_path=history.hist_path(path)))
    mw_path = os.path.join(data_dir, MULTIWORLD_METRICS_FILE)
    if os.path.exists(mw_path):
        print(format_multiworld_status(read_metrics(mw_path)))
    sup_path = os.path.join(data_dir, "supervisor.prom")
    if os.path.exists(sup_path):
        sup = read_metrics(sup_path)
        fails = sum(v for k, v in sup.items()
                    if k.startswith("avida_supervisor_failures_total"))
        print(f"supervisor  boots {int(sup.get('avida_supervisor_boots_total', 0))}, "
              f"failures {int(fails)}, "
              f"budget {int(sup.get('avida_supervisor_retry_budget', 0))}")
        # alert column (observability/alerts.py): the supervisor's poll
        # loop evaluates the rule set over the history rings and
        # exports firing/fired families on its own .prom file
        from avida_tpu.observability.alerts import format_alert_status
        alert_line = format_alert_status(sup)
        if alert_line is not None:
            print(alert_line)
    ana_path = os.path.join(data_dir, "analytics.prom")
    if os.path.exists(ana_path):
        print(format_analytics_status(metrics, read_metrics(ana_path)))
    if max_age is not None:
        hb = metrics.get("avida_heartbeat_timestamp_seconds")
        age = None if hb is None else time.time() - hb
        if age is None or age > max_age:
            shown = "missing" if age is None else f"{age:.1f}s"
            print(f"STALE: heartbeat {shown} exceeds --max-age {max_age}s")
            return 2
    return 0


def _owner_cfg(owner):
    """The AvidaConfig governing a batch publisher's history knobs: its
    own cfg when it has one, else the first member world's (every
    member of a batch shares the static config that matters here)."""
    cfg = getattr(owner, "cfg", None)
    if cfg is None:
        worlds = getattr(owner, "worlds", None) or ()
        for w in worlds:
            if w is not None and getattr(w, "cfg", None) is not None:
                return w.cfg
    return cfg


class MetricsExporter:
    """Owns the metrics.prom path for one World.  `export()` republishes
    synchronously (run exit / preemption -- the values must be final);
    `export_deferred()` is the live chunk-boundary path and never fences
    the device."""

    def __init__(self, world, path: str | None = None):
        self.world = world
        self.path = path or os.path.join(world.data_dir, METRICS_FILE)
        self._pending = None
        # time-series ring beside the snapshot (observability/history.py):
        # one compact sample row per publish, TPU_METRICS_HIST* knobs
        # resolved env-over-config once here
        self.hist = history.HistorySink(self.path,
                                        cfg=getattr(world, "cfg", None))

    def export(self, world=None):
        text = render_metrics(world or self.world)
        write_metrics(self.path, text)
        self.hist.publish(text)

    def export_deferred(self, world=None):
        """Chunk-boundary publish with the same one-chunk deferral as the
        newborn/trace drains: capture the boundary's device scalars by
        REFERENCE now (no readback -- resolving them would fence the
        chunk just dispatched), publish the PREVIOUS boundary's capture,
        whose chunk has long finished, so `np.asarray` there is a free
        readback.  The heartbeat therefore lags live state by exactly one
        chunk, inside the "within one chunk" freshness contract."""
        w = world or self.world
        prev, self._pending = self._pending, self._snapshot(w)
        if prev is not None:
            text = self._render_snapshot(prev)
            write_metrics(self.path, text, durable=False)
            self.hist.publish(text)

    @staticmethod
    def _snapshot(w) -> dict:
        tracer = getattr(w, "tracer", None)
        return {
            "update": int(w.update),
            "organisms": w._prev_alive,      # device refs: reassigned
            "births": w._total_births,       # (not mutated) each chunk,
            "deaths": w._deaths_this,        # so holding them is safe
            "gen": w._last_ave_gen,
            "time": w._avida_time,
            # last host-flushed total: draining _pending_exec here would
            # be the very fence this path exists to avoid
            "insts": int(w._cum_insts),
            "preempted": int(bool(w.preempted or w._preempt)),
            "trace": ((int(tracer.events_total), int(tracer.dropped_total),
                       dict(tracer.code_totals))
                      if tracer is not None else None),
            # last RESOLVED digest (the integrity plane's own one-chunk
            # deferral): already a host value, no readback here
            "digest": getattr(w, "state_digest", None),
        }

    @staticmethod
    def _render_snapshot(snap: dict) -> str:
        values = {
            "avida_update": snap["update"],
            "avida_organisms": (int(np.asarray(snap["organisms"]))
                                if snap["organisms"] is not None else 0),
            "avida_births_total": int(np.asarray(snap["births"])),
            "avida_deaths_last_update": int(np.asarray(snap["deaths"])),
            "avida_generation_avg": round(
                float(np.asarray(snap["gen"])), 4),
            "avida_time": round(float(np.asarray(snap["time"])), 6),
            "avida_insts_total": snap["insts"],
            "avida_preempted": snap["preempted"],
            "avida_heartbeat_timestamp_seconds": round(time.time(), 3),
        }
        if snap.get("digest") is not None:
            values["avida_state_digest"] = snap["digest"][1]
            values["avida_state_digest_update"] = snap["digest"][0]
        return _render(values, snap["trace"])


class MultiWorldExporter:
    """Heartbeat for one MultiWorld batch (parallel/multiworld.py).

    Publishes TWO files into the batch's root data dir:

      metrics.prom      the standard single-run families carrying batch
                        AGGREGATES (update = the shared grid counter;
                        organisms / births / insts summed over worlds),
                        so the supervisor watchdog, `--status DIR` and
                        every other metrics.prom consumer read a
                        batched child exactly like a solo run;
      multiworld.prom   the per-world rows: the same families labeled
                        {world="<name>"} -- one sample per batch member
                        -- plus avida_multiworld_size.

    Live publishes are deferred one chunk (capture [W]-vector refs at
    boundary N, read them back at boundary N+1 when that chunk has
    finished) exactly like MetricsExporter; export_final is the
    synchronous exit/preempt flavor."""

    _PER_WORLD = ("avida_update", "avida_organisms", "avida_births_total",
                  "avida_deaths_last_update", "avida_generation_avg",
                  "avida_time", "avida_insts_total", "avida_preempted")

    def __init__(self, mw, path: str | None = None):
        self.mw = mw
        base = path or mw.data_dir
        self.path = os.path.join(base, METRICS_FILE)
        self.worlds_path = os.path.join(base, MULTIWORLD_METRICS_FILE)
        self._pending = None
        cfg = _owner_cfg(mw)
        self.hist = history.HistorySink(self.path, cfg=cfg)
        self.worlds_hist = history.HistorySink(self.worlds_path, cfg=cfg)

    def export_deferred(self, mw=None):
        m = mw or self.mw
        prev, self._pending = self._pending, self._snapshot(m)
        if prev is not None:
            self._publish(prev, durable=False)

    def export_final(self, mw=None):
        m = mw or self.mw
        for w in m.worlds:
            # the exit heartbeat must carry exact totals (solo
            # render_metrics flushes too); a fleet leader world shares
            # the root data dir, so no per-world export flushed for it
            w._flush_exec()
        self._pending = None
        self._publish(self._snapshot(m), durable=True)

    @staticmethod
    def _snapshot(mw) -> dict:
        return {
            "update": int(mw.update),
            "names": list(mw.names),
            "organisms": mw._prev_alive,       # [W] device refs; the
            "births": mw._total_births,        # batch loop reassigns
            "deaths": mw._deaths_this,         # (never mutates) them
            "gen": mw._last_ave_gen,
            "time": mw._avida_time,
            "insts": [int(w._cum_insts) for w in mw.worlds],
            "preempted": int(bool(mw.preempted or mw._preempt)),
            # occupancy accumulators (parallel/multiworld._scan): [W]
            # per-world trip totals, the per-update batch-max total, and
            # the update count they cover -> batch_efficiency gauge +
            # per-world straggler-lag rows
            "trips": getattr(mw, "_trips", None),
            "leader_trips": getattr(mw, "_leader_trips", None),
            "trips_updates": int(getattr(mw, "_trips_updates", 0)),
            # (update, [W] values) -- already host-resolved by the
            # integrity plane's own deferral; None when digesting is off
            "digests": getattr(mw, "state_digests", None),
        }

    def _publish(self, snap: dict, durable: bool):
        def vec(x, default=0):
            if x is None:
                return [default] * len(snap["names"])
            return np.asarray(x).tolist()

        per = {
            "avida_update": [snap["update"]] * len(snap["names"]),
            "avida_organisms": vec(snap["organisms"]),
            "avida_births_total": vec(snap["births"]),
            "avida_deaths_last_update": vec(snap["deaths"]),
            "avida_generation_avg": [round(float(v), 4)
                                     for v in vec(snap["gen"], 0.0)],
            "avida_time": [round(float(v), 6)
                           for v in vec(snap["time"], 0.0)],
            "avida_insts_total": snap["insts"],
            "avida_preempted": [snap["preempted"]] * len(snap["names"]),
        }
        agg = {
            "avida_update": snap["update"],
            "avida_organisms": int(sum(per["avida_organisms"])),
            "avida_births_total": int(sum(per["avida_births_total"])),
            "avida_deaths_last_update": int(
                sum(per["avida_deaths_last_update"])),
            "avida_generation_avg": round(
                float(np.mean(per["avida_generation_avg"])), 4),
            "avida_time": round(max(per["avida_time"]), 6),
            "avida_insts_total": int(sum(snap["insts"])),
            "avida_preempted": snap["preempted"],
            "avida_heartbeat_timestamp_seconds": round(time.time(), 3),
        }
        try:
            text = _render(agg, None)
            write_metrics(self.path, text, durable=durable)
            self.hist.publish(text)
            fams = [("avida_multiworld_size", "gauge",
                     "worlds batched into this run", len(snap["names"]))]
            fams += [(name, *_HELP[name],
                      {f'world="{n}"': v
                       for n, v in zip(snap["names"], per[name])})
                     for name in self._PER_WORLD]
            fams += self._occupancy_families(snap)
            if snap.get("digests") is not None:
                du, dvals = snap["digests"]
                fams.append(
                    ("avida_state_digest", *_HELP["avida_state_digest"],
                     {f'world="{n}"': v
                      for n, v in zip(snap["names"], dvals)}))
                fams.append(("avida_state_digest_update",
                             *_HELP["avida_state_digest_update"], du))
            fams.append(("avida_heartbeat_timestamp_seconds",
                         *_HELP["avida_heartbeat_timestamp_seconds"],
                         round(time.time(), 3)))
            wtext = render_families(fams)
            write_metrics(self.worlds_path, wtext, durable=durable)
            self.worlds_hist.publish(wtext)
        except OSError:
            pass                    # metrics must never kill the batch

    @staticmethod
    def _occupancy_families(snap: dict) -> list:
        """The world-axis occupancy gauges (PR-11 satellite).

        batch_efficiency = sum_w(trips_w) / (W * leader_trips): the
        fraction of the batch's lockstep trip-count budget doing
        per-world useful work (1.0 = every world wanted exactly the
        batch-max trips every update; the structural ceiling of
        in-program batching -- what the world-folded cycle loop /
        stacked kernel can actually deliver of it is bench.py's
        batch_efficiency throughput ratio).

        straggler_lag_updates{world=w} = (leader_trips - trips_w) /
        (leader_trips / updates): how many batch-leader updates' worth
        of cycles world w sat masked while faster tenants ran -- 0 for
        the leader, growing for a tenant whose budgets trail the
        batch."""
        trips = snap.get("trips")
        if trips is None or not snap["names"]:
            return []
        tl = [float(v) for v in np.asarray(trips).tolist()]
        leader = float(np.asarray(snap.get("leader_trips") or 0.0))
        if leader <= 0:
            # no cycle work yet (or an extinct batch): absent gauges,
            # never a falsely-perfect 1.0
            return []
        upd_n = int(snap.get("trips_updates") or 0)
        W = len(snap["names"])
        eff = sum(tl) / (W * leader)
        per_upd = (leader / upd_n) if upd_n else 0.0
        lag = [round((leader - t) / per_upd, 2) if per_upd > 0 else 0.0
               for t in tl]
        return [
            ("avida_multiworld_batch_efficiency", "gauge",
             "sum of per-world trip counts / (W x batch-max trips): "
             "in-program batching occupancy, 1.0 = no straggler waste",
             round(eff, 4)),
            ("avida_multiworld_straggler_lag_updates", "gauge",
             "batch-leader updates' worth of cycles this world spent "
             "masked behind faster tenants",
             {f'world="{n}"': v for n, v in zip(snap["names"], lag)}),
        ]


class ServeExporter:
    """Heartbeat for a ServeBatch (parallel/multiworld.ServeBatch, the
    streaming serve layer).

    Publishes the same two files as MultiWorldExporter -- metrics.prom
    (batch aggregate: the supervisor watchdog and --status read a serve
    child exactly like a solo run) and multiworld.prom (per-world
    {world="tenant"} rows for the LIVE slots) -- plus the serve-specific
    occupancy families: padded width, live/ghost slot counts, admission/
    retirement/boundary counters and the compiled-program count (the
    compile-cache warmth evidence).  Publishes are synchronous: the
    serve loop exports at checkpoint boundaries and idle ticks, where
    the batch is already host-synced."""

    _PER_WORLD = ("avida_update", "avida_organisms", "avida_births_total",
                  "avida_generation_avg", "avida_insts_total")

    def __init__(self, sb, path: str | None = None):
        self.sb = sb
        base = path or sb.data_dir
        self.path = os.path.join(base, METRICS_FILE)
        self.worlds_path = os.path.join(base, MULTIWORLD_METRICS_FILE)
        cfg = _owner_cfg(sb)
        self.hist = history.HistorySink(self.path, cfg=cfg)
        self.worlds_hist = history.HistorySink(self.worlds_path, cfg=cfg)

    def export(self, sb=None, durable: bool = False):
        from avida_tpu.parallel.multiworld import scan_trace_count
        sb = sb or self.sb
        live = sb._live()
        rows = {}
        for i, w in live:
            organisms = (int(np.asarray(w._prev_alive))
                         if w._prev_alive is not None
                         else (int(np.asarray(w.state.alive).sum())
                               if w.state is not None else 0))
            rows[sb.names[i]] = {
                "avida_update": int(w.update),
                "avida_organisms": organisms,
                "avida_births_total": int(np.asarray(w._total_births)),
                "avida_generation_avg": round(
                    float(np.asarray(w._last_ave_gen)), 4),
                "avida_insts_total": int(w._flush_exec()),
            }
        agg = {
            "avida_update": max([r["avida_update"] for r in rows.values()],
                                default=0),
            "avida_organisms": sum(r["avida_organisms"]
                                   for r in rows.values()),
            "avida_births_total": sum(r["avida_births_total"]
                                      for r in rows.values()),
            "avida_insts_total": sum(r["avida_insts_total"]
                                     for r in rows.values()),
            "avida_preempted": int(bool(sb.preempted or sb._preempt)),
            "avida_heartbeat_timestamp_seconds": round(time.time(), 3),
        }
        fams = [(name, *_HELP[name], value)
                for name, value in agg.items()]
        serve_fams = [
            ("avida_serve_width", "gauge",
             "padded batch width of this serving class", sb.width),
            ("avida_serve_live_worlds", "gauge",
             "slots occupied by live tenants", sb.num_live),
            ("avida_serve_ghost_slots", "gauge",
             "inert ghost slots holding the compiled shape warm",
             sb.num_ghosts),
            ("avida_serve_admissions_total", "counter",
             "tenants promoted into this batch", sb.admissions),
            ("avida_serve_retirements_total", "counter",
             "tenants retired from this batch (done/demoted)",
             sb.retirements),
            ("avida_serve_boundaries_total", "counter",
             "checkpoint boundaries crossed (the promotion grid)",
             sb.boundaries),
            ("avida_serve_compiles_total", "counter",
             "multiworld_scan program variants traced by this process "
             "(flat after warmup = the compile cache is doing its job)",
             scan_trace_count()),
        ] + compilecache.prom_families() + integrity.prom_families() \
            + profiler.prom_families()
        per_fams = [(name, *_HELP[name],
                     {f'world="{n}"': r[name] for n, r in rows.items()})
                    for name in self._PER_WORLD if rows]
        snap = {"names": [sb.names[i] for i, _ in live],
                "trips": (None if sb._trips is None else
                          np.asarray(sb._trips)[[i for i, _ in live]]),
                "leader_trips": sb._leader_trips,
                "trips_updates": sb._trips_updates}
        occ = MultiWorldExporter._occupancy_families(snap)
        try:
            text = render_families(fams + serve_fams)
            write_metrics(self.path, text, durable=durable)
            self.hist.publish(text)
            fams2 = [("avida_multiworld_size", "gauge",
                      "live tenants in this serving batch", sb.num_live)]
            fams2 += per_fams + serve_fams + occ
            fams2.append(("avida_heartbeat_timestamp_seconds",
                          *_HELP["avida_heartbeat_timestamp_seconds"],
                          round(time.time(), 3)))
            wtext = render_families(fams2)
            write_metrics(self.worlds_path, wtext, durable=durable)
            self.worlds_hist.publish(wtext)
        except OSError:
            pass                    # metrics must never kill serving
