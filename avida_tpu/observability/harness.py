"""Unified profiling harness: per-phase breakdown of one update's wall time.

Replaces scripts/profile_update.py.  Times each phase of
ops/update.update_step at bench scale through the SAME StagedUpdate
runner the telemetry path uses -- scheduler draw, pack / kernel / unpack
(Pallas path) or the XLA while_loop, birth flush -- plus the fused whole
update for comparison.  Run on TPU:

    python -m avida_tpu.observability.harness [world_side] [reps] [--trace]

`--trace` re-profiles with the flight recorder armed (params.trace_cap >
0), so the phase table grows the `trace` row (the in-update ring-append
cost) and a `trace_drain` line (the HOST cost of draining a full ring at
a chunk boundary, measured by `measure_trace_drain` below -- bench.py's
BENCH_TRACE=1 reports the same number as `trace_drain_ms`).

bench.py calls `profile_phases` after its headline measurement to attach
a `phases` breakdown to its JSON line.

MEASUREMENT CAVEATS (learned the hard way; see BASELINE.md):
 - repeated dispatches with IDENTICAL inputs can be elided/cached by the
   runtime and report absurdly low times -- a round-5 budget-binning
   optimization was accepted on a microbenchmark broken exactly this way
   and had to be reverted.  This harness is immune by construction: every
   rep runs the full staged update on the previous rep's evolved state,
   so no phase ever sees the same input twice;
 - per-call block_until_ready over a remote-device tunnel measures
   network round-trips (100-300 ms, noisy), not device time -- phase
   numbers are only trustworthy on a locally attached backend;
 - fencing serializes phases XLA would overlap, so the phase sum is an
   UPPER bound on the fused update (reported as `full_step` below);
 - treat end-to-end `python bench.py` deltas as ground truth (run-to-run
   noise ~ +/-2M inst/s at 102k organisms).
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp

from avida_tpu.observability.staged import StagedUpdate
from avida_tpu.observability.timeline import Timeline


def profile_phases(params, st, neighbors, key, reps=3, warmup=1,
                   update0=0, collect_dispatch=False):
    """Mean per-phase wall time over `reps` staged updates (ms), after
    `warmup` compile/warm updates.  Each rep advances the state, so no
    phase repeats an input (see module docstring).  Returns
    ({phase: ms}, final_state, total_granted)."""
    staged = StagedUpdate(params, neighbors,
                          collect_dispatch=collect_dispatch)
    u = update0
    warm_tl = Timeline()
    for _ in range(max(warmup, 1)):
        st, *_ = staged.run(st, jax.random.fold_in(key, u), u, warm_tl)
        u += 1
    tl = Timeline()
    granted_total = 0
    for _ in range(reps):
        st, _, _, granted, _ = staged.run(
            st, jax.random.fold_in(key, u), u, tl)
        granted_total += int(granted.sum())
        u += 1
    acc = tl.drain()
    return {name: ms / reps for name, ms in acc.items()}, st, granted_total


def measure_packed_chunk(params, st, neighbors, key, updates=8, reps=3):
    """End-to-end ms/update of the packed-resident chunk path
    (ops/packed_chunk.py): pack once + `updates` updates on the resident
    [LP, N] planes + unpack once, through the production update_scan.
    Returns (ms_per_update, final_state), or (None, st) when the
    configuration does not qualify (packed_chunk.active).

    Caching-immune by construction (the module-docstring caveat): every
    rep scans onward from the previous rep's evolved state with a fresh
    update-number base, so no chunk ever sees a repeated input."""
    import time

    from avida_tpu.ops import packed_chunk
    from avida_tpu.ops.update import update_scan

    if not packed_chunk.active(params, st):
        return None, st
    u0 = 1 << 20              # clear of any real update numbers
    st, _ = update_scan(params, st, updates, key, neighbors,
                        jnp.int32(u0))           # compile + warm
    jax.block_until_ready(st)
    t0 = time.perf_counter()
    for r in range(reps):
        st, _ = update_scan(params, st, updates, key, neighbors,
                            jnp.int32(u0 + (r + 1) * updates))
        st = jax.block_until_ready(st)
    ms = (time.perf_counter() - t0) * 1e3 / (reps * updates)
    return ms, st


def measure_multiworld(params, sts, neighbors, keys, updates=8, reps=3):
    """End-to-end ms/update-per-world of the batched multi-world scan
    (parallel/multiworld.multiworld_scan): W stacked worlds advance
    `updates` updates in one device program per rep.  Returns
    (ms_per_update_per_world, final_batched_state).

    Caching-immune by construction (the module-docstring caveat):
    every rep scans onward from the previous rep's evolved batched
    state with a fresh update-number base, so no dispatch ever repeats
    an input."""
    import time

    from avida_tpu.parallel.multiworld import multiworld_scan

    W = len(keys)
    bstate = jax.tree.map(lambda *xs: jnp.stack(xs), *sts)
    bkeys = jnp.stack(list(keys))
    u0 = 1 << 20              # clear of any real update numbers
    bstate, _ = multiworld_scan(params, bstate, updates, bkeys,
                                neighbors, jnp.int32(u0))   # compile+warm
    jax.block_until_ready(bstate)
    t0 = time.perf_counter()
    for r in range(reps):
        bstate, _ = multiworld_scan(params, bstate, updates, bkeys,
                                    neighbors,
                                    jnp.int32(u0 + (r + 1) * updates))
        bstate = jax.block_until_ready(bstate)
    ms = (time.perf_counter() - t0) * 1e3 / (reps * updates * W)
    return ms, bstate


def _batched_pre(params, bst, keys, u):
    from avida_tpu.ops import update as upd
    return jax.vmap(
        lambda st, k: upd._mw_pre_phase(params, st, k, u))(bst, keys)


def _batched_cycles(params, bst, k_steps, granted, max_k):
    from avida_tpu.ops import update as upd
    return upd._mw_fold_cycles_xla(params, bst, k_steps, granted, max_k)


def _batched_post(params, bst, budgets, e0, kb, ks, neighbors, u):
    from avida_tpu.ops import update as upd

    def one(st, b, e, kb1, ks1):
        st, executed = upd.bank_phase(params, st, b, e)
        return upd.birth_phase(params, st, kb1, ks1, neighbors, u)

    return jax.vmap(one)(bst, budgets, e0, kb, ks)


# module-level jits (params is static): the live profiler's probe
# (observability/profiler.py, reps=1 at TPU_PROFILE_EVERY cadence)
# compiles these stage programs ONCE per process, not once per probe
_batched_pre_jit = None
_batched_cycles_jit = None
_batched_post_jit = None


def _batched_jits():
    global _batched_pre_jit, _batched_cycles_jit, _batched_post_jit
    if _batched_pre_jit is None:
        from functools import partial
        _batched_pre_jit = partial(jax.jit, static_argnums=0)(_batched_pre)
        _batched_cycles_jit = partial(jax.jit,
                                      static_argnums=0)(_batched_cycles)
        _batched_post_jit = partial(jax.jit, static_argnums=0)(_batched_post)
    return _batched_pre_jit, _batched_cycles_jit, _batched_post_jit


def measure_batched_phases(params, bst, neighbors, bkeys, reps=3,
                           u0=1 << 21, warmup=True):
    """Fenced pre/cycles/post attribution of an ALREADY-STACKED batched
    state (the live-profiler entry point; measure_multiworld_phases
    wraps it for bench.py's list-of-states calling convention).  With
    warmup=False, rep 0 counts -- the profiler probe passes reps=1 on
    state copies whose stage programs are already warm after the first
    probe.  Returns {"pre_ms", "cycles_ms", "post_ms",
    "cycle_loop_share"}."""
    import time

    pre, cycles, post = _batched_jits()
    t = {"pre": 0.0, "cycles": 0.0, "post": 0.0}
    first = 0 if not warmup else None     # warmup: rep 0 warms compiles
    reps_total = reps + (1 if warmup else 0)
    counted = 0
    for r in range(reps_total):
        u = jnp.int32(u0 + r)
        keys_r = jax.vmap(
            lambda rk: jax.random.fold_in(rk, u0 + r))(bkeys)
        jax.block_until_ready(bst)
        t0 = time.perf_counter()
        bst, (budgets, granted, max_k, k_steps, k_birth) = pre(
            params, bst, keys_r, u)
        jax.block_until_ready(bst)
        t1 = time.perf_counter()
        e0 = bst.insts_executed
        bst = cycles(params, bst, k_steps, granted, max_k)
        jax.block_until_ready(bst)
        t2 = time.perf_counter()
        bst = post(params, bst, budgets, e0, k_birth, k_steps,
                   neighbors, u)
        jax.block_until_ready(bst)
        t3 = time.perf_counter()
        if not warmup or r > 0:
            t["pre"] += t1 - t0
            t["cycles"] += t2 - t1
            t["post"] += t3 - t2
            counted += 1
    counted = counted or 1
    total = sum(t.values()) or 1e-9
    return {
        "pre_ms": round(t["pre"] * 1e3 / counted, 3),
        "cycles_ms": round(t["cycles"] * 1e3 / counted, 3),
        "post_ms": round(t["post"] * 1e3 / counted, 3),
        "cycle_loop_share": round(t["cycles"] / total, 4),
    }


def measure_multiworld_phases(params, sts, neighbors, keys, reps=3):
    """Fenced per-phase attribution of the BATCHED update on the XLA
    world-folded path (ops/update.update_scan_batched's per-update
    engine): `pre` = the vmapped resources+schedule prologue, `cycles` =
    the ONE world-folded while_loop (the tentpole's hot loop), `post` =
    the vmapped bank+birth epilogue.  Each stage is jitted separately
    and fenced, exactly like profile_phases does for the solo update, so
    bench.py can report the cycle loop's share of the batched update.

    Caching-immune: every rep advances the evolved batched state through
    the full pre->cycles->post chain with a fresh update number.
    Returns {"pre_ms", "cycles_ms", "post_ms", "cycle_loop_share"}
    (ms per update for the whole batch; share in [0, 1])."""
    bst = jax.tree.map(lambda *xs: jnp.stack(xs), *sts)
    bkeys = jnp.stack(list(keys))
    return measure_batched_phases(params, bst, neighbors, bkeys,
                                  reps=reps)


# ---- packed-resident phase attribution (round 14) ----
#
# The packed engines (solo PackedChunk and stacked PackedWorlds) have
# their own phase structure -- boundary-crossing pack/unpack plus the
# in-scan row-space phases (schedule/kernel/bank/flush/stats) -- which
# the staged per-update runner above cannot see (it measures the
# engine the packed path REPLACED).  The measurers below stage the
# packed update's own phases, each separately jitted and fenced, with
# the in-scan ones prefixed "scan." so attribution distinguishes what
# runs inside the resident scan from what only runs at chunk
# boundaries.  Fused vs legacy row-space sub-path follows
# packed_chunk.fused_active, so the probe measures whichever body the
# production scan actually runs.

_packed_stage_jits = None
_packed_worlds_stage_jits = None


def _packed_stages():
    global _packed_stage_jits
    if _packed_stage_jits is not None:
        return _packed_stage_jits
    from functools import partial

    from avida_tpu.ops import birth as birth_ops
    from avida_tpu.ops import packed_chunk as pch
    from avida_tpu.ops import pallas_cycles, update as upd

    def pack_fn(params, st):
        return pch.pack_chunk(params, st)

    def sched_fn(params, pc, key, update_no):
        k_budget, k_steps, k_birth = jax.random.split(key, 3)
        if pch.fused_active(params):
            st = pc.st
            alive_before = pch.alive_rows(pc.ivec).sum()
            budgets, granted, _ = pch._schedule_rows(
                params, pc.ivec, pc.fvec, st.budget_carry, k_budget)
        else:
            alive_before = pc.st.alive.sum()
            st = upd.resource_phase(params, pc.st, key, update_no)
            budgets, granted, _ = upd.schedule_phase(params, st, k_budget)
        ivec = pc.ivec.at[pallas_cycles.IV_GRANTED].set(granted)
        e0 = ivec[pallas_cycles.IV_INSTS_EXEC]
        return (pc.replace(st=st, ivec=ivec),
                (budgets, e0, alive_before, k_steps, k_birth))

    def kernel_fn(params, pc, k_steps):
        tape_t, off_t, ivec, fvec = pch._launch(
            params, (pc.tape_t, pc.off_t, pc.ivec, pc.fvec), k_steps,
            upd.static_cap(params))
        return pc.replace(tape_t=tape_t, off_t=off_t, ivec=ivec,
                          fvec=fvec)

    def bank_fn(params, pc, budgets, e0):
        st, executed_this = pch._bank_rows(params, pc.st, pc.ivec,
                                           budgets, e0)
        return pc.replace(st=st), executed_this.sum()

    def flush_fn(params, pc, k_birth, update_no):
        planes, st = birth_ops.flush_births_packed(
            params, pc.st, k_birth,
            (pc.tape_t, pc.off_t, pc.gen_t, pc.ivec, pc.fvec),
            update_no, fresh_mirrors=not pch.fused_active(params))
        tape_t, off_t, gen_t, ivec, fvec = planes
        return pc.replace(st=st, tape_t=tape_t, off_t=off_t, gen_t=gen_t,
                          ivec=ivec, fvec=fvec)

    def stats_fn(params, pc, alive_before, update_no):
        if pch.fused_active(params):
            return pch.stats_rows(pc, alive_before, update_no)
        return upd._update_stats(params, pc.st, alive_before, update_no)

    def unpack_fn(params, pc):
        return pch.unpack_chunk(params, pc)

    jit0 = partial(jax.jit, static_argnums=0)
    _packed_stage_jits = tuple(
        jit0(f) for f in (pack_fn, sched_fn, kernel_fn, bank_fn,
                          flush_fn, stats_fn, unpack_fn))
    return _packed_stage_jits


def measure_packed_phases(params, st, neighbors, key, reps=3,
                          u0=1 << 22, warmup=True):
    """Fenced per-phase attribution of the packed-resident update
    (ops/packed_chunk.update_step_packed): boundary phases `pack` /
    `unpack` and in-scan phases `scan.schedule` / `scan.kernel` /
    `scan.bank` / `scan.flush` / `scan.stats`, each separately jitted
    and fenced on device-owned state.  Routes through whichever sub-path
    (fused row-space vs legacy) the production scan runs.  Returns
    {phase_ms keys} or {} when the packed engine is not active for this
    configuration (or the flight recorder is armed -- the staged mirror
    does not reproduce the trace phases).

    Caching-immune: every rep advances the evolved planes through the
    full phase chain with a fresh update number.  NOTE the boundary
    phases amortize over a whole chunk in production (pack/unpack once
    per TPU_CHUNK updates); the in-scan phases are the per-update
    cost."""
    import time

    from avida_tpu.ops import packed_chunk as pch

    if not pch.active(params, st) or int(getattr(params, "trace_cap", 0)):
        return {}
    pack, sched, kernel, bank, flush, stats, unpack = _packed_stages()
    names = ("pack", "scan.schedule", "scan.kernel", "scan.bank",
             "scan.flush", "scan.stats", "unpack")
    t = {n: 0.0 for n in names}
    counted = 0
    reps_total = reps + (1 if warmup else 0)
    for r in range(reps_total):
        u = jnp.int32(u0 + r)
        k = jax.random.fold_in(key, u0 + r)
        jax.block_until_ready(st)
        marks = [time.perf_counter()]

        def fence(x):
            jax.block_until_ready(x)
            marks.append(time.perf_counter())
            return x

        pc = fence(pack(params, st))
        pc, (budgets, e0, alive_before, k_steps, k_birth) = fence(
            sched(params, pc, k, u))
        pc = fence(kernel(params, pc, k_steps))
        pc, _executed = fence(bank(params, pc, budgets, e0))
        pc = fence(flush(params, pc, k_birth, u))
        fence(stats(params, pc, alive_before, u))
        st = fence(unpack(params, pc))
        if not warmup or r > 0:
            for i, n in enumerate(names):
                t[n] += marks[i + 1] - marks[i]
            counted += 1
    counted = counted or 1
    return {f"{n}_ms": round(v * 1e3 / counted, 3)
            for n, v in t.items()}


def _packed_worlds_stages():
    global _packed_worlds_stage_jits
    if _packed_worlds_stage_jits is not None:
        return _packed_worlds_stage_jits
    from functools import partial

    from avida_tpu.ops import birth as birth_ops
    from avida_tpu.ops import packed_chunk as pch
    from avida_tpu.ops import pallas_cycles, update as upd

    def pack_fn(params, bst):
        return pch.pack_worlds(params, bst)

    def sched_fn(params, pw, keys, update_no):
        un = jnp.broadcast_to(jnp.asarray(update_no, jnp.int32),
                              (pw.bst.alive.shape[0],))
        ks = jax.vmap(lambda k: jax.random.split(k, 3))(keys)
        k_budget, k_steps, k_birth = ks[:, 0], ks[:, 1], ks[:, 2]
        if pch.fused_active(params):
            st = pw.bst
            alive_before = pch.alive_rows(pw.ivec).sum(axis=1)
            budgets, granted, _ = jax.vmap(
                lambda iv, fv, bc, k: pch._schedule_rows(
                    params, iv, fv, bc, k),
                in_axes=(1, 1, 0, 0),
            )(pw.ivec, pw.fvec, st.budget_carry, k_budget)
        else:
            alive_before = pw.bst.alive.sum(axis=1)
            st = jax.vmap(
                lambda s, k, u: upd.resource_phase(params, s, k, u)
            )(pw.bst, keys, un)
            budgets, granted, _ = jax.vmap(
                lambda s, k: upd.schedule_phase(params, s, k)
            )(st, k_budget)
        ivec = pw.ivec.at[pallas_cycles.IV_GRANTED].set(granted)
        e0 = ivec[pallas_cycles.IV_INSTS_EXEC]
        return (pw.replace(bst=st, ivec=ivec),
                (budgets, e0, alive_before, k_steps, k_birth, un))

    def kernel_fn(params, pw, k_steps):
        seeds = pallas_cycles.world_seed_bases(k_steps)
        tape_t, off_t, ivec, fvec = pch._launch_worlds(
            params, (pw.tape_t, pw.off_t, pw.ivec, pw.fvec), seeds,
            upd.static_cap(params))
        return pw.replace(tape_t=tape_t, off_t=off_t, ivec=ivec,
                          fvec=fvec)

    def bank_fn(params, pw, budgets, e0):
        st, executed_this = pch._bank_rows(params, pw.bst, pw.ivec,
                                           budgets, e0)
        return pw.replace(bst=st), executed_this.sum(axis=1)

    def flush_fn(params, pw, k_birth, un):
        planes, st = birth_ops.flush_births_packed_worlds(
            params, pw.bst, k_birth,
            (pw.tape_t, pw.off_t, pw.gen_t, pw.ivec, pw.fvec),
            un, fresh_mirrors=not pch.fused_active(params))
        tape_t, off_t, gen_t, ivec, fvec = planes
        return pw.replace(bst=st, tape_t=tape_t, off_t=off_t,
                          gen_t=gen_t, ivec=ivec, fvec=fvec)

    def stats_fn(params, pw, alive_before, un):
        if pch.fused_active(params):
            return pch.stats_rows_worlds(pw, alive_before, un)
        return jax.vmap(
            lambda s, ab, u: upd._update_stats(params, s, ab, u)
        )(pw.bst, alive_before, un)

    def unpack_fn(params, pw):
        return pch.unpack_worlds(params, pw)

    jit0 = partial(jax.jit, static_argnums=0)
    _packed_worlds_stage_jits = tuple(
        jit0(f) for f in (pack_fn, sched_fn, kernel_fn, bank_fn,
                          flush_fn, stats_fn, unpack_fn))
    return _packed_worlds_stage_jits


def measure_packed_worlds_phases(params, bst, neighbors, bkeys, reps=3,
                                 u0=1 << 22, warmup=True):
    """measure_packed_phases for a W-stacked batch on the stacked
    packed engine (ops/packed_chunk.update_step_packed_worlds): same
    phase vocabulary (boundary pack/unpack + in-scan scan.* phases),
    whole-batch ms per phase.  The live profiler's batched probe entry
    point (observability/profiler.py _probe_batched) -- reps=1,
    warmup=False once the stage programs are warm.  Returns {} when the
    stacked packed engine is not active."""
    import time

    from avida_tpu.ops import packed_chunk as pch

    if not pch.batch_active(params, bst) \
            or int(getattr(params, "trace_cap", 0)):
        return {}
    pack, sched, kernel, bank, flush, stats, unpack = \
        _packed_worlds_stages()
    names = ("pack", "scan.schedule", "scan.kernel", "scan.bank",
             "scan.flush", "scan.stats", "unpack")
    t = {n: 0.0 for n in names}
    counted = 0
    reps_total = reps + (1 if warmup else 0)
    for r in range(reps_total):
        u = jnp.int32(u0 + r)
        keys_r = jax.vmap(
            lambda rk: jax.random.fold_in(rk, u0 + r))(bkeys)
        jax.block_until_ready(bst)
        marks = [time.perf_counter()]

        def fence(x):
            jax.block_until_ready(x)
            marks.append(time.perf_counter())
            return x

        pw = fence(pack(params, bst))
        pw, (budgets, e0, alive_before, k_steps, k_birth, un) = fence(
            sched(params, pw, keys_r, u))
        pw = fence(kernel(params, pw, k_steps))
        pw, _executed = fence(bank(params, pw, budgets, e0))
        pw = fence(flush(params, pw, k_birth, un))
        fence(stats(params, pw, alive_before, un))
        bst = fence(unpack(params, pw))
        if not warmup or r > 0:
            for i, n in enumerate(names):
                t[n] += marks[i + 1] - marks[i]
            counted += 1
    counted = counted or 1
    return {f"{n}_ms": round(v * 1e3 / counted, 3)
            for n, v in t.items()}


def measure_trace_drain(cap=4096, n_updates=16, reps=5):
    """Host cost (ms) of one flight-recorder chunk-boundary drain at its
    worst case: a FULL ring of `cap` events spread over `n_updates`
    update labels, written as {"record": "trace"} lines to a throwaway
    runlog.  Pure host work (numpy gather + JSONL append) -- measures the
    per-boundary price of TPU_TRACE=1 beyond the in-update ring appends
    (the `trace` phase in profile_phases)."""
    import shutil
    import tempfile
    import time

    import numpy as np

    from avida_tpu.observability.tracer import EV_BIRTH, FlightRecorder

    class _Stub:                      # the drain only touches data_dir
        telemetry = None
        _dat_append = False

    stub = _Stub()
    stub.data_dir = tempfile.mkdtemp(prefix="trace-drain-")
    rec = FlightRecorder(stub)
    ev = np.arange(cap, dtype=np.int32)
    snap = {"tr_update": ev % max(n_updates, 1),
            "tr_cell": ev % 997,
            "tr_code": np.full(cap, EV_BIRTH, np.int32),
            "tr_payload": ev,
            "tr_count": np.int32(cap),
            "update_at": n_updates, "host_events": []}
    try:
        rec.drain(dict(snap))          # warm the writer/open path
        t0 = time.perf_counter()
        for _ in range(reps):
            rec.drain(dict(snap))
        ms = (time.perf_counter() - t0) * 1e3 / reps
    finally:
        rec.close()
        shutil.rmtree(stub.data_dir, ignore_errors=True)
    return ms


def measure_analytics(genotypes=12, reps=1, mem=320):
    """census_ms / knockout_ms of the analytics pipeline's two batched
    passes (analyze/pipeline.py) on a synthetic genotype table: a cold
    census over `genotypes` distinct ancestor variants (fresh
    content-keyed cache each rep, so every genotype pays a sandbox
    gestation -- the worst case; live incremental refreshes only pay for
    NEW genotypes) and one full per-site knockout sweep of the stock
    ancestor.  Compile time is excluded by a warm pass; reps vary the
    sandbox seed so no dispatch repeats an input (module-docstring
    caveat).  bench.py's BENCH_ANALYZE=1 reports both fields."""
    import time

    import numpy as np

    from avida_tpu.analyze.pipeline import knockout_profile
    from avida_tpu.config import AvidaConfig
    from avida_tpu.config.environment import default_logic9_environment
    from avida_tpu.config.instset import default_instset
    from avida_tpu.core.state import make_world_params
    from avida_tpu.systematics.test_metrics import GenomeTestMetrics
    from avida_tpu.world import default_ancestor

    cfg = AvidaConfig()
    cfg.WORLD_X = 1
    cfg.WORLD_Y = 1
    cfg.TPU_MAX_MEMORY = mem
    iset = default_instset()
    params = make_world_params(cfg, iset, default_logic9_environment())
    anc = default_ancestor(iset)
    L = len(anc)
    buf = np.zeros((genotypes, params.max_memory), np.int8)
    lens = np.full(genotypes, L, np.int32)
    for i in range(genotypes):
        buf[i, :L] = anc
        if i:                          # single-site variants of the stock
            site = 10 + (i % 60)       # replicator (mostly viable)
            buf[i, site] = (int(anc[site]) + i) % params.num_insts
    base = GenomeTestMetrics(params).get_records(buf, lens)[0]["fitness"]
    t0 = time.perf_counter()
    for r in range(reps):
        GenomeTestMetrics(params).get_records(buf, lens, seed=r + 1)
    census_ms = (time.perf_counter() - t0) * 1e3 / reps

    knockout_profile(params, anc, base)                   # compile warm
    t0 = time.perf_counter()
    for r in range(reps):
        knockout_profile(params, anc, base, seed=r + 1)
    knockout_ms = (time.perf_counter() - t0) * 1e3 / reps
    return {"census_ms": round(census_ms, 2),
            "knockout_ms": round(knockout_ms, 2)}


def _timeit_chain(fn, st, key, u0, reps):
    """Mean wall time of the FUSED update over a chain of evolving states
    (distinct inputs per call; one fence at the end of the chain)."""
    import time
    st, _ = fn(st, jax.random.fold_in(key, u0), jnp.int32(u0))   # warm
    jax.block_until_ready(st)
    t0 = time.perf_counter()
    for i in range(reps):
        st, _ = fn(st, jax.random.fold_in(key, u0 + 1 + i),
                   jnp.int32(u0 + 1 + i))
    jax.block_until_ready(st)
    return (time.perf_counter() - t0) / reps


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    sys.path.insert(0, ".")
    from bench import build
    from avida_tpu.ops.update import update_step, use_pallas_path

    trace = "--trace" in argv
    argv = [a for a in argv if not a.startswith("--")]
    world = int(argv[0]) if argv else 320
    reps = int(argv[1]) if len(argv) > 1 else 5
    params, st, neighbors, key = build(world, world, 256, seed=100)
    if trace:
        # arm the flight recorder: ring fields on the state, trace_pre/
        # trace_post phases in the staged run (ops/update.py)
        cap = 4096
        params = params.replace(trace_cap=cap)
        st = st.replace(tr_update=jnp.zeros(cap, jnp.int32),
                        tr_cell=jnp.zeros(cap, jnp.int32),
                        tr_code=jnp.zeros(cap, jnp.int32),
                        tr_payload=jnp.zeros(cap, jnp.int32),
                        tr_count=jnp.zeros((), jnp.int32))
    n = params.num_cells
    cap = params.max_steps_per_update or "uncapped"
    path = "pallas" if use_pallas_path(params) else "xla_while_loop"
    print(f"world {world}x{world} = {n} cells, L={params.max_memory}, "
          f"cap={cap}, platform={jax.devices()[0].platform}, path={path}")

    # advance a few updates so state is "typical" (fused path)
    for u in range(3):
        key, k = jax.random.split(key)
        st, _ = update_step(params, st, k, neighbors, jnp.int32(u))
    jax.block_until_ready(st)

    k_run = jax.random.key(1234)
    phases, st2, granted = profile_phases(params, st, neighbors, k_run,
                                          reps=reps, warmup=1, update0=3)
    per_update = granted / reps
    total = sum(phases.values())
    for name, ms in phases.items():
        print(f"{name:12s} {ms:8.2f} ms")
    print(f"{'sum':12s} {total:8.2f} ms   "
          f"({per_update / total * 1e3 / 1e6:.1f} M inst/s staged)")

    t_full = _timeit_chain(
        lambda s, k, u: update_step(params, s, k, neighbors, u),
        st, k_run, 100, reps)
    print(f"{'full_step':12s} {t_full * 1e3:8.2f} ms   "
          f"({per_update / t_full / 1e6:.1f} M inst/s end-to-end fused)")
    pcms, _ = measure_packed_chunk(params, st2, neighbors,
                                   jax.random.key(4321))
    if pcms is not None:
        print(f"{'packed_chunk':12s} {pcms:8.2f} ms   "
              f"(ms/update of the resident-plane chunk scan; compare "
              f"pack+kernel+unpack+birth above)")
    if trace:
        print(f"{'trace_drain':12s} {measure_trace_drain():8.2f} ms   "
              f"(host drain of a full 4096-event ring per chunk boundary)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
