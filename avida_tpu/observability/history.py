"""On-disk time-series rings beside every .prom snapshot.

The metrics plane (observability/exporter.py and the supervisor/fleet
counter files) rewrites point-in-time `.prom` snapshots every heartbeat
-- an operator can see the CURRENT update rate, queue depth or SDC
count, but never a rate, a trend, or "when did this start?".  This
module keeps the recent past: each heartbeat publish additionally
appends ONE compact sample row -- wall time, update counter, and the
numeric value of every family the publish just rendered -- to a bounded
JSONL ring beside the snapshot:

    metrics.prom      ->  metrics.hist.jsonl
    multiworld.prom   ->  multiworld.hist.jsonl
    supervisor.prom   ->  supervisor.hist.jsonl
    fleet.prom        ->  fleet.hist.jsonl

Sample rows are `{"record": "sample", "time": T, "update": U, "v":
{family-or-family{labels}: value, ...}}`.  The ring reuses
runlog.append_record's rotation-pair discipline (live file + one `.1`
aside, atomic rename at the byte cap) with NON-DURABLE appends, so the
zero-sync dispatch pipeline is never fenced by an fsync; a crash can
only tear the final line, which every reader here tolerates.

Knobs (environment, or config vars for World-owned exporters -- the
env spelling wins so operators can arm/disarm whole fleets):

    TPU_METRICS_HIST            1 (default) = append history at every
                                publish; 0 = byte-compatible no-op (no
                                ring file is ever created)
    TPU_METRICS_HIST_EVERY      sample every K-th publish (default 1 =
                                heartbeat cadence)
    TPU_METRICS_HIST_MAX_BYTES  rotation cap per ring file (default
                                4 MiB; the pair bounds disk at 2x)

Everything here is host-side bookkeeping: trajectories are bit-identical
with history on or off and the solo update_step jaxpr digest is
untouched (gated in tests/test_alerts.py).
"""

from __future__ import annotations

import json
import os
import time

HIST_SUFFIX = ".hist.jsonl"
DEFAULT_MAX_BYTES = 4 << 20


def hist_path(prom_path: str) -> str:
    """The ring path beside a snapshot: `<dir>/metrics.prom` ->
    `<dir>/metrics.hist.jsonl` (non-.prom paths just append the
    suffix)."""
    base, ext = os.path.splitext(prom_path)
    if ext == ".prom":
        return base + HIST_SUFFIX
    return prom_path + HIST_SUFFIX


def parse_exposition(text: str) -> dict:
    """{name or name{labels}: float} from Prometheus exposition text --
    the string flavor of exporter.read_metrics, shared by the history
    sink so a sample row carries exactly what the publish rendered."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


class HistoryKnobs:
    """Resolved TPU_METRICS_HIST* knobs.  `cfg` (an AvidaConfig, when
    the publisher owns one) supplies defaults; the environment wins so
    an operator can flip a whole fleet without touching configs."""

    def __init__(self, enabled: bool = True, every: int = 1,
                 max_bytes: int = DEFAULT_MAX_BYTES):
        self.enabled = bool(enabled)
        self.every = max(int(every), 1)
        self.max_bytes = max(int(max_bytes), 1 << 14)

    @classmethod
    def resolve(cls, env=None, cfg=None) -> "HistoryKnobs":
        env = os.environ if env is None else env

        def knob(name, default):
            if name in env:
                return env[name]
            if cfg is not None:
                v = cfg.get(name, None)
                if v is not None:
                    return v
            return default

        return cls(enabled=int(knob("TPU_METRICS_HIST", 1)),
                   every=int(knob("TPU_METRICS_HIST_EVERY", 1)),
                   max_bytes=int(knob("TPU_METRICS_HIST_MAX_BYTES",
                                      DEFAULT_MAX_BYTES)))


def append_line(path: str, rec: dict, max_bytes: int = DEFAULT_MAX_BYTES,
                durable: bool = False):
    """THE jax-free spelling of runlog.append_record's rotation-pair
    bounded append (importing runlog would pull jax into spectator
    tooling): a file that would grow past `max_bytes` is first moved
    aside to `<path>.1` (atomic rename, clobbering the previous aside)
    and the record starts a fresh file.  Shared by the sample ring
    below and the alert journal (observability/alerts.py) so the
    rotation discipline lives once on the jax-free side."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    line = json.dumps(rec) + "\n"
    if max_bytes:
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        if size and size + len(line) > max_bytes:
            os.replace(path, path + ".1")
    with open(path, "a") as f:
        f.write(line)
        f.flush()
        if durable:
            os.fsync(f.fileno())


class HistorySink:
    """Owns the ring beside one .prom path.  `publish(text)` is called
    by the exporter right after the snapshot rename with the exposition
    text it just wrote; appends are non-durable and never raise --
    history must not take down the heartbeat it is recording."""

    def __init__(self, prom_path: str, env=None, cfg=None,
                 knobs: HistoryKnobs | None = None):
        self.path = hist_path(prom_path)
        self.knobs = knobs or HistoryKnobs.resolve(env=env, cfg=cfg)
        self._publishes = 0

    def publish(self, text: str, now: float | None = None):
        if not self.knobs.enabled:
            return
        self._publishes += 1
        if (self._publishes - 1) % self.knobs.every:
            return
        try:
            values = parse_exposition(text)
            append_sample(self.path, values, now=now,
                          max_bytes=self.knobs.max_bytes)
        except Exception:
            pass


def append_sample(path: str, values: dict, now: float | None = None,
                  max_bytes: int = DEFAULT_MAX_BYTES):
    """Append one sample row to a ring, rotating at the byte cap (the
    runlog.append_record rotation-pair discipline, non-durable: no
    fsync -- a torn final line is tolerated by read_samples)."""
    rec = {"record": "sample",
           "time": round(time.time() if now is None else now, 3)}
    if "avida_update" in values:
        rec["update"] = int(values["avida_update"])
    rec["v"] = values
    append_line(path, rec, max_bytes=max_bytes, durable=False)


def read_samples(path: str, window_sec: float | None = None,
                 now: float | None = None,
                 tail_bytes: int | None = None) -> list:
    """Sample rows across the rotation pair (`<path>.1` then the live
    file), oldest first, torn/garbage lines skipped.  `window_sec`
    drops rows older than `now - window_sec`; `tail_bytes` caps how
    much of EACH file is read from the end (the alert evaluator's hot
    path -- a poll loop must not re-parse megabytes every tick)."""
    out = []
    for p in (path + ".1", path):
        try:
            with open(p, "rb") as f:
                if tail_bytes is not None:
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    if size > tail_bytes:
                        f.seek(size - tail_bytes)
                        f.readline()        # skip the partial line
                    else:
                        f.seek(0)
                data = f.read()
        except OSError:
            continue
        for line in data.splitlines():
            try:
                rec = json.loads(line)
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue
            if rec.get("record") != "sample" or "v" not in rec:
                continue
            out.append(rec)
    out.sort(key=lambda r: r.get("time", 0.0))
    if window_sec is not None:
        cutoff = (time.time() if now is None else now) - window_sec
        out = [r for r in out if r.get("time", 0.0) >= cutoff]
    return out


def series(samples: list, family: str, labels: str | None = None,
           agg=max) -> list:
    """[(time, value)] for one family, oldest first.  A bare family
    name matches both its unlabeled sample and every labeled row
    (`family{label}`); labeled rows collapse per sample through `agg`
    (default max).  An alert that should fire when ANY labeled series
    trips passes the aggregator matching its direction: max for
    above-threshold rules, min for below-threshold ones -- the worst
    series either way (observability/alerts.py picks this from the
    rule's op).  `labels` is a substring filter on the label part."""
    out = []
    prefix = family + "{"
    for rec in samples:
        vals = []
        for k, v in rec["v"].items():
            if k == family:
                vals.append(v)
            elif k.startswith(prefix):
                if labels is None or labels in k[len(prefix):-1]:
                    vals.append(v)
        if vals:
            out.append((rec.get("time", 0.0), agg(vals)))
    return out


def value_asof(points: list, t: float):
    """Step interpolation: the newest sample value at or before `t`
    (None when no sample that old exists)."""
    best = None
    for pt, pv in points:
        if pt <= t:
            best = pv
        else:
            break
    return best


def rate_over(points: list, t: float, window_sec: float):
    """Per-second rate of a (monotone or not) series over
    [t - window, t], step-interpolated: (v(t) - v(t - window)) /
    window.  None when the ring does not yet span the window -- a run
    that just started cannot honestly be called stalled.  A series
    whose newest sample predates the whole window still evaluates (the
    publisher stopped; its counter definitionally did not advance)."""
    if not points or window_sec <= 0:
        return None
    v_now = value_asof(points, t)
    v_then = value_asof(points, t - window_sec)
    if v_now is None or v_then is None:
        return None
    return (v_now - v_then) / window_sec


_QUANT = (0.5, 0.95)


def summarize(samples: list, family: str, window_sec: float | None = None,
              now: float | None = None, labels: str | None = None) -> dict:
    """Windowed digest of one family: count/min/max/p50/p95, first and
    last values, and the per-second rate across the window span --
    `metrics_tool.py query`'s engine."""
    now = time.time() if now is None else now
    if window_sec is not None:
        samples = [r for r in samples
                   if r.get("time", 0.0) >= now - window_sec]
    pts = series(samples, family, labels=labels)
    if not pts:
        return {"family": family, "count": 0}
    vals = sorted(v for _, v in pts)
    n = len(vals)

    def q(frac):
        return vals[min(int(frac * (n - 1) + 0.5), n - 1)]

    t0, v0 = pts[0]
    t1, v1 = pts[-1]
    span = t1 - t0
    return {
        "family": family, "count": n,
        "min": vals[0], "max": vals[-1],
        "p50": q(_QUANT[0]), "p95": q(_QUANT[1]),
        "first": v0, "last": v1,
        "span_sec": round(span, 3),
        "rate_per_sec": round((v1 - v0) / span, 6) if span > 0 else None,
    }


def recent_rate_line(path: str, family: str = "avida_update",
                     beats: int = 10, now: float | None = None) -> str:
    """The `--status` sparkline: per-second rate of a counter over the
    last `beats` ring samples, split into an older and a newer half so
    a trend reads at a glance (`upd/s last 10 beats: 12.1 -> 11.8`).
    Honest when there is nothing to summarize."""
    unit = "upd/s" if family == "avida_update" else f"{family}/s"
    samples = read_samples(path, tail_bytes=256 << 10)
    pts = series(samples, family)[-beats:]
    if len(pts) < 3:
        if not os.path.exists(path) and not os.path.exists(path + ".1"):
            return "no history (TPU_METRICS_HIST=0 or no publishes yet)"
        return f"no history ({len(pts)} sample(s) in the ring)"

    def seg_rate(seg):
        (t0, v0), (t1, v1) = seg[0], seg[-1]
        return (v1 - v0) / (t1 - t0) if t1 > t0 else 0.0

    mid = len(pts) // 2
    older = seg_rate(pts[:mid + 1])
    newer = seg_rate(pts[mid:])
    t_now = time.time() if now is None else now
    age = t_now - pts[-1][0]
    return (f"{unit} last {len(pts)} beats: {older:.2f} -> {newer:.2f}"
            f" (newest sample {age:.0f}s ago)")


def prune(path: str, keep_bytes: int = 256 << 10) -> dict:
    """`metrics_tool.py prune`: drop the `.1` aside and trim the live
    ring to its newest `keep_bytes` tail (whole lines, atomic rewrite).
    Returns {"removed_bytes": N, "kept_bytes": M}."""
    removed = 0
    try:
        removed += os.path.getsize(path + ".1")
        os.remove(path + ".1")
    except OSError:
        pass
    kept = 0
    try:
        size = os.path.getsize(path)
    except OSError:
        return {"removed_bytes": removed, "kept_bytes": 0}
    if size > keep_bytes:
        with open(path, "rb") as f:
            f.seek(size - keep_bytes)
            f.readline()                    # align to a whole line
            tail = f.read()
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(tail)
        os.replace(tmp, path)
        removed += size - len(tail)
        kept = len(tail)
    else:
        kept = size
    return {"removed_bytes": removed, "kept_bytes": kept}
