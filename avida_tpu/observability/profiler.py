"""Device performance attribution plane (README "Performance attribution").

Answers the three questions ROADMAP items 1 and 4 are blocked on:

  which PROGRAM?   per-compiled-program XLA cost analysis (flops, bytes
                   accessed, transcendentals) and memory analysis
                   (argument / output / temp / generated-code HBM),
                   captured by utils/compilecache.call at compile time
                   and carried in the cache-entry manifest so a cached
                   load reports the SAME numbers as the fresh compile
                   (keyed by the compile-cache signature);
  which PHASE?     opt-in (TPU_PROFILE=1) per-chunk attribution: every
                   chunk's boundary-to-boundary wall is accumulated
                   unfenced (zero-sync -- the deferred-export pipeline
                   is never touched), and every TPU_PROFILE_EVERY-th
                   chunk takes a FENCED probe: a harness-style staged
                   pre/cycles/post timing run on device-owned COPIES of
                   the live state, so the evolved trajectory stays
                   bit-identical with profiling on or off;
  which BYTES?     resident-state footprint per PopulationState leaf --
                   padded (`nbytes` ground truth) vs live bytes (scaled
                   by occupancy and mean genome length: the bit-packing
                   headroom number), per-world + ghost overhead for
                   MultiWorld / ServeBatch batches.

Everything lands in the existing observability grammars, never a
parallel one: `avida_perf_*` families on every exporter flavor (empty
when off -- the compilecache.prom_families byte-compatibility
contract), {"record": "perf"} lines in DATA_DIR/perf.jsonl (runlog
rotation pair), a perf block in `--status`, phase spans in `trace_tool
fleet`, and `scripts/perf_tool.py report/diff/campaign` on top.

Arming follows the integrity-plane pattern (utils/integrity.py):
config nonzero OR environment nonzero -- the suite pins the env side
to 0 (tests/conftest.py) and dedicated tests opt back in through
config overrides.  NOT the same knobs as the telemetry subsystem's
TPU_PROFILE_DIR/TPU_PROFILE_UPDATES (jax.profiler capture under
TPU_TELEMETRY): TPU_PROFILE arms THIS plane on the scanned-chunk
path, where telemetry cannot go without killing throughput.

Measurement rules inherited from rounds 12-15 (BASELINE.md): probes
never dispatch repeated identical inputs (each probe runs one staged
update on a copy of the CURRENT evolved state), and headline numbers
are direct fenced attributions, not end-to-end wall deltas.

Host-importable without jax: every jax touch is inside a function
(scripts/perf_tool.py reads this module's file formats from plain
hosts).
"""

from __future__ import annotations

import json
import os
import time

PERF_FILE = "perf.jsonl"
PROFILES_DIR = "profiles"
_PERF_MAX_BYTES = 16 << 20

# ---------------------------------------------------------------------------
# arming (the integrity.digest_enabled pattern: config OR env)
# ---------------------------------------------------------------------------


def enabled(cfg=None) -> bool:
    """TPU_PROFILE nonzero in the config OR the environment arms the
    attribution plane.  Off (default) builds nothing, fences nothing
    and writes nothing -- exporter files stay byte-identical."""
    if cfg is not None and int(cfg.get("TPU_PROFILE", 0) or 0):
        return True
    return bool(int(os.environ.get("TPU_PROFILE", "0") or 0))


def trace_enabled(cfg=None) -> bool:
    """TPU_PROFILE_TRACE=1: the first fenced probe also captures a
    jax.profiler trace of its staged phases into DATA_DIR/profiles/."""
    if cfg is not None and int(cfg.get("TPU_PROFILE_TRACE", 0) or 0):
        return True
    return bool(int(os.environ.get("TPU_PROFILE_TRACE", "0") or 0))


def probe_every(cfg=None) -> int:
    """Fenced-probe cadence in chunks (first chunk always probes;
    0 = first chunk only).  Env wins over config here -- cadence is an
    operator knob, like the history sampling knobs."""
    v = os.environ.get("TPU_PROFILE_EVERY", "")
    if v not in ("", None):
        try:
            return int(v)
        except ValueError:
            pass
    if cfg is not None:
        return int(cfg.get("TPU_PROFILE_EVERY", 16) or 0)
    return 16


# ---------------------------------------------------------------------------
# module state (one process = one attribution report, like compilecache)
# ---------------------------------------------------------------------------

_programs: dict = {}            # cache key -> program report
_chunk = {
    "chunks": 0,                # chunks dispatched under profiling
    "updates": 0,               # updates those chunks covered
    "wall_ms": 0.0,             # boundary-to-boundary wall (unfenced)
    "wall_chunks": 0,           # intervals accumulated into wall_ms
    "fenced_ms": 0.0,           # dispatch->ready wall of probed chunks
    "fenced_chunks": 0,
    "probes": 0,                # fenced probes taken
    "probe_ms": 0.0,            # host+device wall spent inside probes
}
_phases: dict = {}              # phase name -> ms (last probe)
_cycle_share = None             # cycle-loop share of the last probe
_footprint = None               # last state_footprint() result


def counters() -> dict:
    return dict(_chunk)


def program_reports() -> dict:
    """{cache key: program report} captured so far this process."""
    return {k: dict(v) for k, v in _programs.items()}


def reset_for_tests():
    global _cycle_share, _footprint
    _programs.clear()
    _phases.clear()
    _cycle_share = None
    _footprint = None
    for k in _chunk:
        _chunk[k] = 0 if isinstance(_chunk[k], int) else 0.0


# ---------------------------------------------------------------------------
# per-program XLA cost / memory capture (compilecache.call hooks)
# ---------------------------------------------------------------------------

# the cost-analysis keys worth carrying (the rest are per-op breakdowns
# whose spellings vary by jax version)
_COST_KEYS = ("flops", "bytes accessed", "transcendentals",
              "optimal_seconds")
_MEMORY_ATTRS = ("argument_size_in_bytes", "output_size_in_bytes",
                 "temp_size_in_bytes", "generated_code_size_in_bytes",
                 "alias_size_in_bytes", "host_temp_size_in_bytes")


def program_perf(compiled) -> dict:
    """{"cost": ..., "memory": ...} from a jax.stages.Compiled --
    best-effort per backend (either analysis may be unimplemented;
    absent halves are {})."""
    out = {"cost": {}, "memory": {}}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):     # older jax: one per device
            cost = cost[0] if cost else {}
        for k in _COST_KEYS:
            v = cost.get(k)
            if v is not None:
                out["cost"][k.replace(" ", "_")] = float(v)
    except Exception:
        pass
    try:
        mem = compiled.memory_analysis()
        for attr in _MEMORY_ATTRS:
            v = getattr(mem, attr, None)
            if v is not None:
                out["memory"][attr] = int(v)
    except Exception:
        pass
    return out


def note_program(key: str, tag: str, chunk: int, compiled, source: str,
                 cfg=None, manifest: dict | None = None):
    """Record one compiled scan program's cost/memory report, keyed by
    its compile-cache signature.  `source` is "compile" (fresh
    lower().compile()), "cache_load" (deserialized -- numbers come from
    the entry manifest's `perf` block when the storing process captured
    one, so cached and fresh runs report EQUAL numbers), "aot" (cache
    disabled but profiling armed), or "memo" (an in-process memo hit
    whose program predates the report -- same executable, same
    numbers).  No-op unless the plane is armed (and deduped per key),
    so note-hooks in compilecache.call cost nothing by default."""
    if not enabled(cfg) or key in _programs:
        return
    perf = None
    if manifest is not None:
        perf = manifest.get("perf")
    if perf is None:
        perf = program_perf(compiled)
    _programs[key] = {
        "tag": tag,
        "chunk": int(chunk),
        "source": source,
        "cost": dict(perf.get("cost", {})),
        "memory": dict(perf.get("memory", {})),
    }


# ---------------------------------------------------------------------------
# resident-state footprint (per PopulationState leaf)
# ---------------------------------------------------------------------------


def packed_planes_footprint(params, N: int, W: int = 1) -> dict:
    """Resident-plane byte accounting of the packed chunk engine
    (ops/packed_chunk.py) for an N-cell world (x W batched worlds):
    per-plane rows/bytes from the kernel layout, the bit-packed vs
    unpacked genome-shadow comparison (the TPU_PACKED_BITS HBM-savings
    number), and bytes-per-organism totals.  Pure shape math -- no
    device transfer, callable without a live packed state."""
    from avida_tpu.ops import packed_chunk, pallas_cycles

    _, _, L = pallas_cycles._dims(params, N, int(params.max_memory))
    NI, _, _, _ = pallas_cycles._layout(params, L)
    LP = L // 4
    L5 = pallas_cycles.words5(L)
    bits = packed_chunk.bits_active(params)
    gen_rows = L5 if bits else LP
    lanes = int(N) * int(W)
    planes = {
        "tape_t": {"rows": LP, "bytes": 4 * LP * lanes},
        "off_t": {"rows": LP, "bytes": 4 * LP * lanes},
        "gen_t": {"rows": gen_rows, "bytes": 4 * gen_rows * lanes,
                  "unpacked_bytes": 4 * LP * lanes},
        "ivec": {"rows": int(NI), "bytes": 4 * int(NI) * lanes},
        "fvec": {"rows": int(pallas_cycles.NF),
                 "bytes": 4 * int(pallas_cycles.NF) * lanes},
    }
    total = sum(p["bytes"] for p in planes.values())
    unpacked_total = total - planes["gen_t"]["bytes"] \
        + planes["gen_t"]["unpacked_bytes"]
    out = {
        "packed_bits": int(bits),
        "planes": planes,
        "total_bytes": total,
        "bytes_per_org": round(total / lanes, 2) if lanes else 0.0,
        # the bits=0 comparator (equals total when the codec is off)
        "unpacked_total_bytes": unpacked_total,
        "saved_bytes": unpacked_total - total,
    }
    reason = packed_chunk.bits_ineligible_reason(params)
    if reason and int(getattr(params, "packed_bits", 0)):
        out["bits_fallback_reason"] = reason
    return out


def state_footprint(st, names=None, num_ghosts: int = 0,
                    params=None) -> dict:
    """Padded vs live byte accounting of one PopulationState (or a
    [W]-stacked batch of them).

    Padded bytes per leaf are `nbytes` ground truth (shape x itemsize,
    no device transfer).  Live bytes scale every cell-axis leaf by the
    alive fraction, and genome-shaped [.., N, L] leaves additionally by
    the mean live genome length / L -- the bit-packing headroom number
    ROADMAP item 4 needs.  Exactly two scalar readbacks (alive count,
    mean genome length); None leaves (tracer rings off, unused
    subsystems) are skipped like core/state.state_array_specs.

    Batched states ([W, N, ...]; `names`/`num_ghosts` from the driver)
    additionally report per-world bytes and the ghost-slot overhead.

    With `params` given and the packed chunk engine active, a
    `packed_planes` block (packed_planes_footprint) reports what is
    ACTUALLY resident mid-chunk -- the kernel planes, per world on
    batched paths -- including the bit-packed vs unpacked genome-shadow
    bytes under TPU_PACKED_BITS."""
    import numpy as np

    from avida_tpu.core.state import state_field_names

    alive = np.asarray(st.alive)
    batched = alive.ndim == 2
    W = alive.shape[0] if batched else 1
    N = alive.shape[-1]
    L = int(st.genome.shape[-1])
    n_alive = int(alive.sum())
    alive_frac = n_alive / float(alive.size) if alive.size else 0.0
    glen = np.asarray(st.genome_len)
    mean_len = (float((glen * alive).sum()) / n_alive) if n_alive else 0.0
    len_frac = mean_len / L if L else 0.0

    cell_axis = 1 if batched else 0
    leaves, total, live_total = {}, 0, 0.0
    for name in state_field_names():
        x = getattr(st, name, None)
        if x is None:
            continue
        b = int(x.nbytes)
        frac = 1.0
        shape = tuple(x.shape)
        if len(shape) > cell_axis and shape[cell_axis] == N:
            frac = alive_frac
            if shape[-1:] == (L,) and len(shape) == cell_axis + 2:
                frac *= len_frac
        lb = b * frac
        leaves[name] = {"bytes": b, "live_bytes": int(round(lb)),
                        "shape": list(shape), "dtype": str(x.dtype)}
        total += b
        live_total += lb

    out = {
        "total_bytes": total,
        "live_bytes": int(round(live_total)),
        "alive_frac": round(alive_frac, 4),
        "genome_len_frac": round(len_frac, 4),
        "leaves": leaves,
    }
    if batched:
        out["worlds"] = W
        out["per_world_bytes"] = total // W if W else 0
        out["ghost_slots"] = int(num_ghosts)
        out["ghost_bytes"] = (total // W) * int(num_ghosts) if W else 0
        if names:
            out["world_names"] = list(names)
    if params is not None:
        from avida_tpu.ops import packed_chunk
        if packed_chunk.active(params):
            pp = packed_planes_footprint(params, N, W)
            if batched and W:
                pp["per_world_bytes"] = pp["total_bytes"] // W
            out["packed_planes"] = pp
    return out


# ---------------------------------------------------------------------------
# the per-driver chunk hook (World / MultiWorld / ServeBatch)
# ---------------------------------------------------------------------------


class ChunkProfiler:
    """Per-driver attribution hooks around the chunk dispatch.

    chunk_begin(k) stamps the dispatch; chunk_end_solo/_batched
    accumulates the unfenced boundary-to-boundary wall and, on probe
    chunks (first chunk, then every TPU_PROFILE_EVERY-th), fences the
    freshly scanned state, runs a staged phase probe on device-owned
    COPIES of it (trajectory bit-identity: the copies are discarded),
    refreshes the footprint accounting and appends a {"record":"perf"}
    line.  The unfenced path costs two perf_counter() calls and a few
    dict adds per chunk -- the <2%-of-chunk-wall budget is measured by
    bench.py's BENCH_PROF=1 arm."""

    def __init__(self, data_dir: str, cfg=None, kind: str = "solo"):
        self.data_dir = data_dir
        self.kind = kind
        self.every = probe_every(cfg)
        self.trace = trace_enabled(cfg)
        self._chunk_no = 0
        self._probe = False
        self._t0 = None
        self._last_end = None
        self._staged = None             # solo probe runner, built lazily
        self._traced = False            # one-shot jax.profiler capture

    # ---- the hot path ----

    def chunk_begin(self, k: int):
        self._chunk_no += 1
        self._probe = (self._chunk_no == 1
                       or (self.every > 0
                           and self._chunk_no % self.every == 0))
        self._t0 = time.perf_counter()

    def _chunk_end(self, k: int, state) -> bool:
        import jax

        now = time.perf_counter()
        _chunk["chunks"] += 1
        _chunk["updates"] += int(k)
        if self._last_end is not None:
            _chunk["wall_ms"] += (now - self._last_end) * 1e3
            _chunk["wall_chunks"] += 1
        probe = self._probe
        if probe:
            jax.block_until_ready(state)
            _chunk["fenced_ms"] += (time.perf_counter() - self._t0) * 1e3
            _chunk["fenced_chunks"] += 1
        self._last_end = time.perf_counter()
        return probe

    def chunk_end_solo(self, world, k: int):
        """Boundary hook for World._scan_updates (state is
        world.state, update counter still pre-chunk)."""
        if not self._chunk_end(k, world.state):
            return
        t0 = time.perf_counter()
        phases = self._run_traced(self._probe_solo, world)
        fp = state_footprint(world.state, params=world.params)
        self._finish_probe(phases, fp, int(world.update) + int(k), k)
        _chunk["probe_ms"] += (time.perf_counter() - t0) * 1e3

    def chunk_end_batched(self, owner, k: int, names=None,
                          num_ghosts: int = 0, update: int | None = None):
        """Boundary hook for MultiWorld._scan / ServeBatch._scan
        (owner.bstate is the [W]-stacked batch, update counters already
        advanced; ServeBatch passes its leader update explicitly --
        members advance on their own counters)."""
        if not self._chunk_end(k, owner.bstate):
            return
        t0 = time.perf_counter()
        phases = self._run_traced(self._probe_batched, owner)
        fp = state_footprint(owner.bstate, names=names,
                             num_ghosts=num_ghosts,
                             params=getattr(owner, "params", None))
        if update is None:
            update = int(getattr(owner, "update", 0))
        self._finish_probe(phases, fp, int(update), k)
        _chunk["probe_ms"] += (time.perf_counter() - t0) * 1e3

    def final(self, state, update: int, names=None, num_ghosts: int = 0,
              params=None):
        """Exit-path refresh: the run is already synced, so the closing
        footprint + perf record are free readbacks (the final-heartbeat
        discipline)."""
        if state is None:
            return
        try:
            fp = state_footprint(state, names=names, num_ghosts=num_ghosts,
                                 params=params)
        except Exception:
            return
        self._finish_probe({}, fp, int(update), 0, final=True)

    # ---- probes (device-owned copies; discarded -- bit-identity) ----

    def _run_traced(self, probe_fn, owner) -> dict:
        """Run one phase probe, wrapping the FIRST one in a
        jax.profiler trace when TPU_PROFILE_TRACE is armed.  A probe
        failure (pallas-path batch, OOM on the copies, backend without
        the staged programs) degrades to whole-chunk attribution only
        -- profiling must never take down the run."""
        import jax

        tracing = self.trace and not self._traced
        if tracing:
            self._traced = True
            try:
                jax.profiler.start_trace(
                    os.path.join(self.data_dir, PROFILES_DIR))
            except Exception:
                tracing = False
        try:
            return probe_fn(owner)
        except Exception:
            return {}
        finally:
            if tracing:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass

    def _probe_solo(self, world) -> dict:
        import jax

        from avida_tpu.ops import packed_chunk

        if packed_chunk.active(world.params, world.state):
            # the packed engine has its own phase structure (boundary
            # pack/unpack + in-scan row-space phases) -- stage THOSE,
            # not the per-update engine the packed path replaced
            from avida_tpu.observability.harness import \
                measure_packed_phases
            st = jax.tree.map(jax.numpy.copy, world.state)
            t = measure_packed_phases(
                world.params, st, world.neighbors, world._run_key,
                reps=1, warmup=self._staged is None)
            self._staged = "packed"      # stage programs warm after 1st
            return {k[:-3]: v for k, v in t.items() if k.endswith("_ms")}

        from avida_tpu.observability.staged import StagedUpdate
        from avida_tpu.observability.timeline import Timeline

        if self._staged is None or self._staged == "packed":
            self._staged = StagedUpdate(world.params, world.neighbors,
                                        collect_dispatch=False)
        st = jax.tree.map(jax.numpy.copy, world.state)
        u = int(world.update)
        tl = Timeline()
        self._staged.run(st, jax.random.fold_in(world._run_key, u), u, tl)
        return tl.drain()

    def _probe_batched(self, owner) -> dict:
        from avida_tpu.observability.harness import measure_batched_phases
        from avida_tpu.ops import packed_chunk
        from avida_tpu.ops.update import use_pallas_path

        if use_pallas_path(owner.params):
            if packed_chunk.batch_active(owner.params, owner.bstate):
                # the stacked packed engine stages its own phases
                # (boundary pack/unpack + in-scan scan.* row-space
                # phases; observability/harness.py)
                import jax

                from avida_tpu.observability.harness import \
                    measure_packed_worlds_phases
                bst = jax.tree.map(jax.numpy.copy, owner.bstate)
                warm = self._staged is None
                self._staged = "packed-worlds"
                t = measure_packed_worlds_phases(
                    owner.params, bst, owner.neighbors, owner._run_keys,
                    reps=1, warmup=warm)
                return {k[:-3]: v for k, v in t.items()
                        if k.endswith("_ms")}
            # the staged pre/cycles/post split only exists on the XLA
            # world-folded path; packed-kernel batches keep whole-chunk
            # attribution (fenced_ms) + the jax.profiler trace
            return {}
        import jax

        bst = jax.tree.map(jax.numpy.copy, owner.bstate)
        t = measure_batched_phases(owner.params, bst, owner.neighbors,
                                   owner._run_keys, reps=1)
        global _cycle_share
        _cycle_share = t.pop("cycle_loop_share", None)
        return {k[:-3]: v for k, v in t.items() if k.endswith("_ms")}

    # ---- publication ----

    def _finish_probe(self, phases: dict, fp: dict, update: int, k: int,
                      final: bool = False):
        global _footprint
        if phases:
            _phases.clear()
            _phases.update({n: round(float(ms), 4)
                            for n, ms in phases.items()})
        _footprint = fp
        if not final:
            _chunk["probes"] += 1
        rec = {
            "record": "perf",
            "time": round(time.time(), 3),
            "kind": self.kind,
            "update": int(update),
            "chunk_updates": int(k),
            "final": bool(final),
            "chunks": _chunk["chunks"],
            "chunk_wall_ms": _mean(_chunk["wall_ms"],
                                   _chunk["wall_chunks"]),
            "chunk_fenced_ms": _mean(_chunk["fenced_ms"],
                                     _chunk["fenced_chunks"]),
            "phases": dict(_phases),
            "state_bytes": fp.get("total_bytes", 0),
            "state_live_bytes": fp.get("live_bytes", 0),
            "alive_frac": fp.get("alive_frac", 0.0),
            "genome_len_frac": fp.get("genome_len_frac", 0.0),
            "leaves": {n: lf["bytes"]
                       for n, lf in fp.get("leaves", {}).items()},
            "programs": len(_programs),
        }
        if _cycle_share is not None:
            rec["cycle_loop_share"] = round(float(_cycle_share), 4)
        for extra in ("per_world_bytes", "ghost_slots", "ghost_bytes"):
            if extra in fp:
                rec[extra] = fp[extra]
        if "packed_planes" in fp:
            rec["packed_planes"] = fp["packed_planes"]
        append_perf_record(self.data_dir, rec)


def _mean(total: float, n: int) -> float:
    return round(total / n, 3) if n else 0.0


def append_perf_record(data_dir: str, rec: dict):
    """One {"record":"perf"} JSONL line into DATA_DIR/perf.jsonl --
    the runlog rotation-pair grammar, non-durable appends (probe
    boundaries must not pay fsync; the integrity.jsonl precedent)."""
    from avida_tpu.observability.runlog import append_record

    try:
        append_record(os.path.join(data_dir, PERF_FILE), rec,
                      max_bytes=_PERF_MAX_BYTES, durable=False)
    except Exception:
        pass                    # attribution must never kill the run


def read_perf_records(data_dir: str) -> list:
    from avida_tpu.observability.runlog import read_records

    return [r for r in read_records(os.path.join(data_dir, PERF_FILE))
            if r.get("record") == "perf"]


# ---------------------------------------------------------------------------
# exposition families (exporter._render / ServeExporter.export hook)
# ---------------------------------------------------------------------------


def _program_label(key: str, rec: dict) -> str:
    return f'program="{rec["tag"]}:{key[:8]}"'


def prom_families() -> list:
    """The avida_perf_* families, render_families shaped.  Empty when
    the plane never armed -- profiling-off processes publish
    byte-identical metrics files (the compilecache.prom_families
    contract)."""
    if not (_chunk["chunks"] or _programs):
        return []
    fams = [
        ("avida_perf_chunks_total", "counter",
         "update chunks dispatched under the attribution plane",
         _chunk["chunks"]),
        ("avida_perf_updates_total", "counter",
         "updates covered by profiled chunks", _chunk["updates"]),
        ("avida_perf_probes_total", "counter",
         "fenced phase/footprint probes taken", _chunk["probes"]),
        ("avida_perf_chunk_wall_ms", "gauge",
         "mean boundary-to-boundary chunk wall, unfenced (pipeline "
         "throughput view)", _mean(_chunk["wall_ms"],
                                   _chunk["wall_chunks"])),
        ("avida_perf_chunk_fenced_ms", "gauge",
         "mean dispatch-to-ready wall of probed chunks (device view)",
         _mean(_chunk["fenced_ms"], _chunk["fenced_chunks"])),
        ("avida_perf_probe_ms", "gauge",
         "mean host+device wall of one fenced probe (the plane's "
         "amortized cost)", _mean(_chunk["probe_ms"], _chunk["probes"])),
    ]
    if _phases:
        fams.append(
            ("avida_perf_phase_ms", "gauge",
             "per-phase ms of the last staged probe (pre/cycles/post "
             "on batches; the staged solo phases otherwise)",
             {f'phase="{n}"': v for n, v in _phases.items()}))
    if _cycle_share is not None:
        fams.append(
            ("avida_perf_cycle_loop_share", "gauge",
             "cycle while_loop share of the last probed batched update",
             round(float(_cycle_share), 4)))
    if _programs:
        fams.append(
            ("avida_perf_programs_total", "counter",
             "compiled scan programs with captured cost/memory "
             "analysis", len(_programs)))
        flops, acc, hbm = {}, {}, {}
        for key, rec in _programs.items():
            label = _program_label(key, rec)
            c, m = rec["cost"], rec["memory"]
            if "flops" in c:
                flops[label] = int(c["flops"])
            if "bytes_accessed" in c:
                acc[label] = int(c["bytes_accessed"])
            if m:
                hbm[label] = int(sum(m.values()))
        if flops:
            fams.append(("avida_perf_program_flops", "gauge",
                         "XLA cost-analysis flops per execution of this "
                         "compiled program", flops))
        if acc:
            fams.append(("avida_perf_program_bytes_accessed", "gauge",
                         "XLA cost-analysis bytes accessed per execution",
                         acc))
        if hbm:
            fams.append(("avida_perf_program_hbm_bytes", "gauge",
                         "memory-analysis HBM per program (argument + "
                         "output + temp + generated code)", hbm))
    fp = _footprint
    if fp is not None:
        fams += [
            ("avida_perf_state_bytes", "gauge",
             "resident PopulationState bytes, padded (nbytes ground "
             "truth)", fp["total_bytes"]),
            ("avida_perf_state_live_bytes", "gauge",
             "occupancy- and genome-length-scaled live bytes (the "
             "bit-packing headroom bound)", fp["live_bytes"]),
            ("avida_perf_state_leaf_bytes", "gauge",
             "padded bytes per PopulationState leaf",
             {f'leaf="{n}"': rec["bytes"]
              for n, rec in fp["leaves"].items()}),
        ]
        if "packed_planes" in fp:
            pp = fp["packed_planes"]
            fams.append(
                ("avida_perf_packed_plane_bytes", "gauge",
                 "resident packed-engine plane bytes (the mid-chunk HBM "
                 "truth; gen_t narrows under TPU_PACKED_BITS)",
                 {f'plane="{n}"': p["bytes"]
                  for n, p in pp["planes"].items()}))
            fams.append(
                ("avida_perf_packed_bytes_per_org", "gauge",
                 "resident packed-plane bytes per organism slot",
                 pp["bytes_per_org"]))
            if pp.get("saved_bytes"):
                fams.append(
                    ("avida_perf_packed_saved_bytes", "gauge",
                     "plane bytes saved by the 5-bit genome codec vs "
                     "the byte layout", pp["saved_bytes"]))
        if "per_world_bytes" in fp:
            fams.append(("avida_perf_world_state_bytes", "gauge",
                         "resident bytes per batched world slot",
                         fp["per_world_bytes"]))
        if fp.get("ghost_bytes"):
            fams.append(("avida_perf_ghost_state_bytes", "gauge",
                         "resident bytes held by inert ghost slots "
                         "(the serve padding overhead)",
                         fp["ghost_bytes"]))
    return fams


def format_status_block(metrics: dict) -> str | None:
    """The `--status` perf line from a metrics.prom dict (exporter
    format_status hook) -- None when the plane never published."""
    if "avida_perf_chunks_total" not in metrics:
        return None
    parts = [
        f"chunk {metrics.get('avida_perf_chunk_wall_ms', 0.0):.1f}ms "
        f"wall / {metrics.get('avida_perf_chunk_fenced_ms', 0.0):.1f}ms "
        f"fenced",
        f"{int(metrics.get('avida_perf_probes_total', 0))} probes",
    ]
    phases = {k.split('phase="', 1)[1].rstrip('"}'): v
              for k, v in metrics.items()
              if k.startswith('avida_perf_phase_ms{')}
    if phases:
        parts.append("phases " + " ".join(
            f"{n}={v:.1f}" for n, v in phases.items()))
    if "avida_perf_state_bytes" in metrics:
        tb = metrics["avida_perf_state_bytes"]
        lb = metrics.get("avida_perf_state_live_bytes", 0.0)
        live_pct = (lb / tb * 100.0) if tb else 0.0
        parts.append(f"state {tb / 2**20:.1f}MiB "
                     f"({live_pct:.0f}% live)")
    if "avida_perf_programs_total" in metrics:
        parts.append(
            f"{int(metrics['avida_perf_programs_total'])} programs")
    return "perf        " + ", ".join(parts)


# ---------------------------------------------------------------------------
# bench provenance (the self-describing-artifact half)
# ---------------------------------------------------------------------------

PROVENANCE_SCHEMA = "avida-bench-v1"
# the apples-to-apples fields perf_tool diff refuses to cross
PROVENANCE_STRICT = ("platform", "device_kind", "device_count", "x64",
                     "code")


def bench_provenance(run_time: float | None = None) -> dict:
    """The provenance block every bench.py JSON line carries: the
    compile-cache toolchain facts (jax/jaxlib versions, backend,
    device kind/count, x64, the repo code digest -- ONE spelling,
    utils/compilecache._toolchain) plus the TPU_*/BENCH_* knob
    environment and the caller-passed run timestamp."""
    from avida_tpu.utils.compilecache import _toolchain

    env = {k: v for k, v in sorted(os.environ.items())
           if k.startswith(("TPU_", "BENCH_")) and v != ""}
    out = {"schema": PROVENANCE_SCHEMA, **_toolchain(), "env": env}
    if run_time is not None:
        out["generated_at"] = round(float(run_time), 3)
    return out


def provenance_mismatches(a: dict, b: dict) -> list:
    """The strict-field disagreements between two provenance blocks --
    what makes a diff apples-to-oranges.  Either side absent -> a
    single loud "no provenance" entry."""
    if not a or not b:
        return [("provenance", "absent" if not a else "present",
                 "absent" if not b else "present")]
    out = []
    for f in PROVENANCE_STRICT:
        if a.get(f) != b.get(f):
            out.append((f, a.get(f), b.get(f)))
    return out


def load_bench_json(path: str) -> dict:
    """One bench artifact from `path`: a JSON object, or the LAST
    object line of a JSONL stream (bench.py --sweep / piped output)."""
    with open(path) as f:
        text = f.read()
    try:
        return json.loads(text)
    except ValueError:
        last = None
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    last = json.loads(line)
                except ValueError:
                    continue
        if last is None:
            raise
        return last
