"""Structured run logs: telemetry.jsonl emitter + the World-facing recorder.

One JSON object per line:

  {"record": "meta", ...}     -- once, at the first telemetry update: run
                                 metadata (seed, world geometry, backend,
                                 interpret path, instruction names)
  {"record": "update", ...}   -- per update: phase wall-time breakdown
                                 (ms), counter snapshot (births, deaths,
                                 executed instructions, per-task triggers,
                                 budget-tail utilization, dispatch mix)

Counter semantics are chosen to reconcile EXACTLY with the .dat outputs
of the same run (tests/test_telemetry.py):

  births        == count.dat / average.dat births for this update
                   (alive & birth_update == u, i.e. post-flush survivors)
  executed      == count.dat "insts executed this update"
  task_triggers == the tasks_exe.dat row for this update (host diff of
                   the device-side lifetime totals, same as the action)

`TelemetryRecorder` owns the Timeline, the StagedUpdate runner and the
writer; World delegates run_update to it when TPU_TELEMETRY is on.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from avida_tpu.observability.counters import (budget_block, budget_tail,
                                              update_counters)
from avida_tpu.observability.staged import StagedUpdate
from avida_tpu.observability.timeline import Timeline


class TelemetryWriter:
    """Append-only JSONL file, flushed per record."""

    def __init__(self, path: str, mode: str = "w"):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, mode)

    def write(self, record: dict):
        self._f.write(json.dumps(record) + "\n")
        self._f.flush()

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None


def trim_update_records(path: str, max_update: int):
    """Resume continuity for telemetry.jsonl: drop per-update records
    at or past the restored update (a crash that outran the last
    auto-save leaves newer records on disk; re-run updates would
    otherwise appear twice).  STRICT cutoff: update records are labeled
    with the index of the update being executed, so a checkpoint at
    update N owns records 0..N-1 and the resumed run re-emits from N.
    Flight-recorder {"record": "trace"} lines carry the same per-update
    labeling and trim identically.  Analytics census records
    ({"record": "analytics"}, analyze/pipeline.py) trim on a STRICT
    cutoff instead (update > max_update): a census is labeled with the
    checkpoint boundary it DESCRIBES, so the census at the restored
    update is valid evidence of exactly the state the resume restores
    (and is never re-emitted until the next boundary), while censuses
    past it describe a rolled-back timeline and must not survive as
    evidence of what the replayed run evolved.  Meta/event records
    carry no update number and are kept.  Atomic rewrite; missing file
    is a no-op."""
    if not os.path.exists(path):
        return
    kept = []
    dropped = 0
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                dropped += 1          # torn tail line from the crash
                continue
            kind = rec.get("record")
            u = int(rec.get("update", -1))
            if (kind in ("update", "trace") and u >= max_update) \
                    or (kind == "analytics" and u > max_update):
                dropped += 1
                continue
            kept.append(line)
    if dropped:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.writelines(kept)
        os.replace(tmp, path)


def append_record(path: str, rec: dict, max_bytes: int | None = None,
                  durable: bool = True):
    """Crash-safe single-record append for OUT-OF-PROCESS writers (the
    run supervisor's {"record": "supervisor"} events, the fleet
    orchestrator's {"record": "fleet"} journal): open, append one line,
    fsync, close -- no handle is held across a child process's
    lifetime, and a torn tail can only ever be the final line (which
    every runlog reader already tolerates).

    Rotation: with `max_bytes` set, a file that would grow past the cap
    is first moved aside to `<path>.1` (atomic rename, clobbering the
    previous aside) and the record starts a fresh file -- a long heal
    loop cannot grow supervisor.jsonl/fleet.jsonl without bound, and a
    crash between the rename and the append loses nothing (both files
    survive, the record was never acknowledged).  Readers that need
    history beyond the live file read `<path>.1` first (see
    read_records)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    line = json.dumps(rec) + "\n"
    if max_bytes:
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        if size and size + len(line) > max_bytes:
            os.replace(path, path + ".1")
    with open(path, "a") as f:
        f.write(line)
        f.flush()
        if durable:
            # durable=False is the hot-loop flavor (the integrity
            # plane's per-chunk digest records): skip the per-record
            # fsync -- a crash can only tear the final line, which
            # every runlog reader already tolerates
            os.fsync(f.fileno())


def read_records(path: str) -> list:
    """All JSON records across the rotation pair (`<path>.1` then
    `<path>`), oldest first, torn/garbage lines skipped.  The journal
    reader for replay-on-restart consumers (service/fleet.py) and the
    ops tooling (scripts/fleet_tool.py)."""
    out = []
    for p in (path + ".1", path):
        try:
            f = open(p)
        except OSError:
            continue
        with f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue            # torn tail from a crash
    return out


def emit_event(world, event: str, **fields):
    """Structured out-of-band run event ({"record": "event", ...}).

    The checkpoint/resume machinery (utils/checkpoint.py) and any other
    robustness path report through this: the record lands in
    telemetry.jsonl when the run has an open telemetry writer, and is
    always echoed to stderr so headless runs without telemetry still
    surface warnings (checkpoint corruption fallback, preemption,
    invariant trips).  Never raises -- a logging failure must not take
    down the save/restore path it is reporting on."""
    import sys

    rec = {"record": "event", "event": event, "time": time.time(), **fields}
    try:
        tel = getattr(world, "telemetry", None)
        if tel is not None and tel._writer is not None:
            tel._writer.write(rec)
    except Exception:
        pass
    detail = " ".join(f"{k}={v}" for k, v in fields.items())
    print(f"[avida-tpu] {event}" + (f": {detail}" if detail else ""),
          file=sys.stderr)


class TelemetryRecorder:
    """Drives phase-fenced updates for a World and emits telemetry.jsonl.

    Lazy: nothing is built and no file is opened until the first update
    runs under telemetry, so constructing a World with TPU_TELEMETRY=0
    (or never running one with it on) writes nothing."""

    def __init__(self, world, profile_dir: str | None = None,
                 profile_updates: int = 3):
        self.world = world
        self.timeline = Timeline()
        self.profile_dir = profile_dir
        self.profile_updates = max(int(profile_updates), 0)
        self._staged: StagedUpdate | None = None
        self._writer: TelemetryWriter | None = None
        self._block = None
        self._task_prev = None
        self._updates_run = 0
        self._pending = None        # device handles awaiting emit

    # ---- lazy setup ----

    def _ensure(self):
        if self._staged is None:
            w = self.world
            self._staged = StagedUpdate(w.params, w.neighbors)
            self._block = budget_block(w.params, w.params.num_cells)
        if self._writer is None:
            w = self.world
            # append on reopen (a World.run() close followed by more
            # updates must not truncate earlier records)
            reopen = getattr(self, "_log_opened", False)
            self._writer = TelemetryWriter(
                os.path.join(w.data_dir, "telemetry.jsonl"),
                mode=("a" if reopen else "w"))
            self._log_opened = True
            if reopen:
                return
            dev = jax.devices()[0]
            self._writer.write({
                "record": "meta",
                "time": time.time(),
                "seed": int(w.cfg.RANDOM_SEED),
                "world": [w.params.world_x, w.params.world_y],
                "num_cells": int(w.params.num_cells),
                "max_memory": int(w.params.max_memory),
                "hw_type": int(w.params.hw_type),
                "max_steps_per_update": int(w.params.max_steps_per_update),
                "platform": dev.platform,
                "device": getattr(dev, "device_kind", str(dev)),
                "num_devices": jax.device_count(),
                "interpret_path": ("pallas" if self._staged.pallas
                                   else "xla_while_loop"),
                "budget_block": int(self._block),
                "dispatch_mix": self._staged.collect_dispatch,
                "inst_names": list(w.instset.inst_names),
                "task_names": list(w.environment.task_names()),
            })

    # ---- the update path (called from World.run_update) ----

    def update(self, world):
        """Run world's next update phase-fenced.  Returns the executed
        count (device scalar) and leaves the record pending until
        emit()."""
        self._ensure()
        if self._task_prev is None:
            # tasks-trigger diff baseline = totals BEFORE the first
            # telemetry update (nonzero for restored/mid-run states)
            self._task_prev = np.asarray(
                jnp.sum(world.state.task_exe_total, axis=0), np.int64)
        if self.profile_dir and self._updates_run == 0 \
                and self.profile_updates > 0:
            self.timeline.start_trace(self.profile_dir)

        tl = self.timeline
        u = world.update
        key = tl.run("schedule",
                     lambda: jax.random.fold_in(world._run_key, u))
        st, executed, dispatch, granted, alive_before = self._staged.run(
            world.state, key, u, tl)
        world.state = st

        counters = tl.run("counters", lambda: update_counters(
            world.params, st, alive_before, jnp.int32(u)))
        tail = tl.run("counters", lambda: budget_tail(granted, self._block))

        # host bookkeeping, mirroring ops/update.update_scan's per-update
        # outputs for the chunk-of-1 case (avida time, generation
        # triggers, birth/death device scalars)
        from avida_tpu.ops.update import light_stats
        ave_gest, ave_gen, n_alive, births = tl.run(
            "counters", lambda: light_stats(world.params, st, jnp.int32(u)))
        with tl.phase("counters"):
            dt = jnp.where(ave_gest > 0,
                           1.0 / jnp.maximum(ave_gest, 1e-9), 0.0)
            world._avida_time = world._avida_time + dt
            world._last_ave_gen = ave_gen
            world._deaths_this = counters["deaths"]
            world._prev_alive = n_alive
            world._total_births = world._total_births + births

        self._pending = (u, executed, dispatch, counters, tail)
        self._updates_run += 1
        if self.timeline._tracing and self._updates_run >= self.profile_updates:
            self.timeline.stop_trace()
        return executed

    def emit(self, world):
        """Write the pending update record (called at the end of
        World.run_update, after reversion/systematics so their host
        phases land in the same record)."""
        if self._pending is None:
            return
        u, executed, dispatch, counters, tail = self._pending
        self._pending = None

        task_totals = np.asarray(counters["task_exe_totals"], np.int64)
        task_triggers = task_totals - self._task_prev
        self._task_prev = task_totals

        # wall = span from this record's first bracketed phase to now; the
        # phases subdivide it (sum ~= wall minus inter-phase python
        # overhead).  Loop time between records is not update work and is
        # excluded.
        wall_ms = self.timeline.window_seconds() * 1e3
        phases = {k: round(v, 4) for k, v in self.timeline.drain().items()}

        granted_sum = int(tail["granted_sum"])
        ceiling = int(tail["ceiling_sum"])
        rec = {
            "record": "update",
            "update": int(u),
            "wall_ms": round(wall_ms, 4),
            "phases": phases,
            "counters": {
                "executed": int(executed),
                "organisms": int(counters["organisms"]),
                "births": int(counters["births"]),
                "deaths": int(counters["deaths"]),
                "divides_total": int(counters["divides_total"]),
                "task_triggers": [int(x) for x in task_triggers],
                "budget": {
                    "granted": granted_sum,
                    "ceiling": ceiling,
                    "utilization": round(granted_sum / ceiling, 4)
                    if ceiling else 1.0,
                    "block_max_max": int(tail["block_max_max"]),
                    "block_mean_mean": round(
                        float(tail["block_mean_mean"]), 2),
                },
            },
        }
        if dispatch is not None:
            rec["counters"]["dispatch_mix"] = [
                int(x) for x in np.asarray(dispatch)]
        self._writer.write(rec)

    def seed_task_totals(self, totals):
        """Reset the tasks-trigger diff baseline (state restore)."""
        self._task_prev = np.asarray(totals, np.int64)

    def close(self):
        self.timeline.stop_trace()
        if self._writer is not None:
            self._writer.close()
            self._writer = None
