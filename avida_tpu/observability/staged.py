"""Phase-by-phase execution of one update, for timing attribution.

`StagedUpdate` runs EXACTLY the phase functions that
ops/update.update_step fuses -- resource_phase, schedule_phase,
interpret_phase (split into pack / kernel / unpack on the Pallas path,
mirroring run_cycles), bank_phase, birth_phase -- but jits each phase
separately and fences between them, so a Timeline can attribute wall
time per phase.  The state trajectory is bit-identical to the fused
update_step given the same key (tests/test_telemetry.py asserts this):
the phases are the same traced code in the same order, only the jit
boundaries differ.

Cost model: fencing serializes phases that XLA would otherwise overlap
and each boundary round-trips the full state through HBM, so a staged
update is strictly slower than the fused one.  That is the telemetry
trade: attribution over throughput.  It is opt-in (TPU_TELEMETRY) and
the fused path is untouched when it is off.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from avida_tpu.observability import counters as counters_mod
from avida_tpu.ops.update import (bank_phase, birth_phase, interpret_phase,
                                  perm_phase, resource_phase, schedule_phase,
                                  static_cap, trace_post_phase,
                                  trace_pre_phase, use_pallas_path)


class StagedUpdate:
    """Per-phase jitted update runner.

    collect_dispatch: thread the instruction-dispatch-mix accumulator
    through the interpret while_loop.  Only meaningful on the
    single-threaded heads-hardware XLA path: the Pallas kernel does not
    collect it (observability/counters.py), fetch_opcode reads the heads
    IP over st.tape which the SMT interpreters (hw_type 1/2) do not use
    as their instruction pointer, and under MAX_CPU_THREADS > 1 only one
    of the T per-slice thread sub-steps would be sampled -- all three are
    gated off rather than emitting plausible-looking garbage.
    """

    def __init__(self, params, neighbors, collect_dispatch=True):
        self.params = params
        self.neighbors = neighbors
        self.pallas = use_pallas_path(params)
        self.cap = static_cap(params)
        self.collect_dispatch = (collect_dispatch and not self.pallas
                                 and params.hw_type == 0
                                 and params.max_cpu_threads <= 1)
        cap = self.cap

        self._resource = jax.jit(
            lambda st, key, u: resource_phase(params, st, key, u))
        self._schedule = jax.jit(
            lambda st, k: schedule_phase(params, st, k))
        self._perm = jax.jit(
            lambda st, g, u: perm_phase(params, st, g, u))
        if self.pallas:
            from avida_tpu.ops import pallas_cycles
            use_perm = int(getattr(params, "lane_perm_k", 0)) > 0
            shards = pallas_cycles.kernel_shards(params)
            self._pack = jax.jit(
                lambda st, g: pallas_cycles.pack_state(
                    params, st, g, st.lane_perm if use_perm else None,
                    shards))
            self._kernel = jax.jit(
                lambda packed, k: pallas_cycles.run_packed(
                    params, packed, k, cap))
            self._unpack = jax.jit(
                lambda st, packed: pallas_cycles.unpack_state(
                    params, st, packed,
                    st.lane_inv if use_perm else None))
        else:
            if self.collect_dispatch:
                self._interpret = jax.jit(
                    lambda st, k, g, mk: interpret_phase(
                        params, st, k, g, mk, cap,
                        counters_mod.dispatch_init(params)))
            else:
                self._interpret = jax.jit(
                    lambda st, k, g, mk: interpret_phase(
                        params, st, k, g, mk, cap))
        # flight recorder (same phase functions the fused update_step
        # gates on the static trace_cap -- staged stays bit-identical
        # with the recorder on)
        self.trace = int(getattr(params, "trace_cap", 0)) > 0
        if self.trace:
            self._trace_pre = jax.jit(
                lambda st, g, u: trace_pre_phase(params, st, g, u))
            self._trace_post = jax.jit(
                lambda st, snap, u: trace_post_phase(params, st, snap, u))
        # chaos-test NaN injection: the fused update_step gates this on
        # the same static flag -- staged must mirror it or the two paths
        # diverge under TPU_FAULT (tests assert bit-identity)
        self.fault = bool(getattr(params, "fault_nan", ()))
        if self.fault:
            from avida_tpu.utils.faultinject import nan_phase
            self._fault = jax.jit(lambda st, u: nan_phase(params, st, u))
        # ... and the in-bounds SDC model (`bitflip:` kind), same rule
        self.fault_flip = bool(getattr(params, "fault_bitflip", ()))
        if self.fault_flip:
            from avida_tpu.utils.faultinject import bitflip_phase
            self._fault_flip = jax.jit(
                lambda st, u: bitflip_phase(params, st, u))
        self._bank = jax.jit(
            lambda st, budgets, e0: bank_phase(params, st, budgets, e0))
        self._birth = jax.jit(
            lambda st, kb, ks, u: birth_phase(params, st, kb, ks,
                                              neighbors, u))
        self._alive_sum = jax.jit(lambda st: st.alive.sum())

    def run(self, st, key, update_no, timeline):
        """One update, phase-fenced into `timeline`.  Returns
        (st, executed, dispatch_counts | None, granted, alive_before)."""
        tl = timeline
        update_no, k_budget, k_steps, k_birth = tl.run(
            "schedule",
            lambda: (jnp.int32(update_no),) + tuple(jax.random.split(key, 3)))
        alive_before = tl.run("counters", self._alive_sum, st)
        st = tl.run("resources", self._resource, st, key, update_no)
        budgets, granted, max_k = tl.run("schedule", self._schedule,
                                         st, k_budget)
        st = tl.run("schedule", self._perm, st, granted, update_no)
        tsnap = None
        if self.trace:
            st, tsnap = tl.run("trace", self._trace_pre, st, granted,
                               update_no)
        executed0 = st.insts_executed
        if self.pallas:
            packed = tl.run("pack", self._pack, st, granted)
            packed = tl.run("kernel", self._kernel, packed, k_steps)
            st = tl.run("unpack", self._unpack, st, packed)
            dispatch = None
        else:
            st, dispatch = tl.run("while_loop", self._interpret,
                                  st, k_steps, granted, max_k)
        st, executed = tl.run("bank", self._bank, st, budgets, executed0)
        st = tl.run("birth_flush", self._birth, st, k_birth, k_steps,
                    update_no)
        if self.fault:
            st = tl.run("fault", self._fault, st, update_no)
        if self.fault_flip:
            st = tl.run("fault", self._fault_flip, st, update_no)
        if self.trace:
            st = tl.run("trace", self._trace_post, st, tsnap, update_no)
        return st, executed, dispatch, granted, alive_before
