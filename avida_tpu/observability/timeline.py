"""Phase wall-clock timers with device fencing.

A `Timeline` brackets named phases of an update (pack, kernel, birth
flush, events, host I/O, ...) and accumulates wall time per phase name.
Device phases MUST be fenced -- JAX dispatch is asynchronous, so an
unfenced bracket measures enqueue time, not execution time.  Use
`Timeline.run(name, fn, *args)` for device work (it calls the function
and `jax.block_until_ready`s its output inside the bracket) and the
`Timeline.phase(name)` context manager for host-side work.

Measurement caveats inherited from the retired scripts/profile_update.py
(learned the hard way; BASELINE.md):

 - repeated dispatches with IDENTICAL inputs can be elided/cached by the
   runtime and report absurdly low times.  The staged harness
   (observability/harness.py) is immune by construction: every rep feeds
   the previous rep's evolved state, so no two calls see equal inputs;
 - per-call block_until_ready over a remote-device tunnel measures
   network round-trips (100-300 ms, noisy), not device time.  Phase
   timings are only trustworthy on a locally attached backend; treat
   end-to-end `python bench.py` deltas as ground truth either way.

Optional `jax.profiler` trace capture: `start_trace(dir)` / `stop_trace()`
wrap the profiler so a telemetry run can drop an XProf trace of its first
few updates next to the phase numbers.
"""

from __future__ import annotations

import time
from contextlib import contextmanager

import jax


class Timeline:
    """Accumulates {phase name -> seconds} between `drain()` calls."""

    def __init__(self):
        self._acc: dict[str, float] = {}
        self._order: list[str] = []
        self._window_start: float | None = None
        self._tracing = False

    # ---- phase brackets ----

    def add(self, name: str, seconds: float):
        if name not in self._acc:
            self._acc[name] = 0.0
            self._order.append(name)
        self._acc[name] += seconds

    def _open(self) -> float:
        t0 = time.perf_counter()
        if self._window_start is None:
            self._window_start = t0      # first bracket since last drain
        return t0

    def run(self, name: str, fn, *args):
        """Time `fn(*args)` as phase `name`, fencing the output.  Returns
        the (ready) output."""
        t0 = self._open()
        out = fn(*args)
        out = jax.block_until_ready(out)
        self.add(name, time.perf_counter() - t0)
        return out

    @contextmanager
    def phase(self, name: str):
        """Host-side phase bracket (no fence -- use for file I/O, event
        dispatch, python-side bookkeeping)."""
        t0 = self._open()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    # ---- readout ----

    def window_seconds(self) -> float:
        """Wall time from the first bracket opened since the last drain
        to now (the span the accumulated phases subdivide)."""
        if self._window_start is None:
            return 0.0
        return time.perf_counter() - self._window_start

    def drain(self) -> dict[str, float]:
        """Return accumulated {name: milliseconds} in first-seen order and
        reset the accumulator."""
        out = {n: self._acc[n] * 1e3 for n in self._order}
        self._acc = {}
        self._order = []
        self._window_start = None
        return out

    def peek_ms(self) -> dict[str, float]:
        return {n: self._acc[n] * 1e3 for n in self._order}

    # ---- jax.profiler trace capture ----

    def start_trace(self, profile_dir: str) -> bool:
        """Begin an XProf trace into `profile_dir` (idempotent; returns
        whether a trace is now running)."""
        if self._tracing:
            return True
        try:
            jax.profiler.start_trace(profile_dir)
            self._tracing = True
        except Exception as e:
            # profiler unavailable on this backend, unwritable dir, or a
            # trace already active -- the run continues without a trace,
            # but say why instead of silently dropping the capture
            import sys
            print(f"[avida-tpu] warning: jax.profiler trace capture into "
                  f"{profile_dir!r} failed ({e}); continuing without a "
                  f"trace", file=sys.stderr)
            self._tracing = False
        return self._tracing

    def stop_trace(self):
        if self._tracing:
            try:
                jax.profiler.stop_trace()
            finally:
                self._tracing = False
