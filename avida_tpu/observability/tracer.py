"""Device-side flight recorder: event codes + the host drain.

The reference engine's forensic story is per-event logs (cStats event
counters feeding the analyze mode) and per-cycle tracer hooks
(cHardwareTracer); the lockstep port's equivalent must not sync the
device mid-chunk, so it records into fixed-capacity ring buffers CARRIED
IN PopulationState (tr_update/tr_cell/tr_code/tr_payload/tr_count --
world-level fields, like lane_perm) and drains them to the host only at
update-chunk boundaries, the same deferred-snapshot pipeline the
systematics newborn drain uses (world.py).

Event catalogue (device side emits in ops/update.trace_pre_phase /
trace_post_phase; host paths append through record_host_event):

  code  name         cell        payload
  1     birth        newborn     parent cell index at birth
  2     death        dead cell   genotype id before the update (-1 unknown)
  3     task_first   cell        bitmask of newly first-executed tasks
  4     sched_stall  -1          block utilization x 10000
  5     anom_merit   cell        1 (non-finite/negative merit on alive)
  6     anom_head    cell        instruction pointer value
  7     revert       newborn     parent cell (host: offspring reverted)
  8     sterilize    newborn     fitness category (host: sterilized)

Overflow semantics: slot i % cap holds event number i, so a full ring
drops the OLDEST events; the monotone tr_count cursor recovers the drop
count at drain time (reported as "dropped" on the window's first trace
record).  The recorder never forces an early host sync.

Drained events land in the existing runlog (telemetry.jsonl) as one
{"record": "trace", "update": u, "events": [[cell, code, payload], ...]}
line per update -- trimmed on resume by runlog.trim_update_records
exactly like per-update telemetry records.  scripts/trace_tool.py
converts the runlog to a Chrome/Perfetto trace.json and back.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

from avida_tpu.core.state import TRACE_RING_FIELDS as _RING_FIELDS

EV_BIRTH = 1
EV_DEATH = 2
EV_TASK_FIRST = 3
EV_SCHED_STALL = 4
EV_ANOM_MERIT = 5
EV_ANOM_HEAD = 6
EV_REVERT = 7
EV_STERILIZE = 8

EVENT_CODES = {
    EV_BIRTH: "birth",
    EV_DEATH: "death",
    EV_TASK_FIRST: "task_first",
    EV_SCHED_STALL: "sched_stall",
    EV_ANOM_MERIT: "anom_merit",
    EV_ANOM_HEAD: "anom_head",
    EV_REVERT: "revert",
    EV_STERILIZE: "sterilize",
}

# highest code the DEVICE ring can contain: EV_REVERT/EV_STERILIZE are
# host-side merges (FlightRecorder.record_host) that never enter tr_code
# -- the auditor bounds live ring entries by this, not max(EVENT_CODES)
DEVICE_MAX_CODE = EV_ANOM_HEAD


def ring_order(count: int, cap: int) -> np.ndarray:
    """Chronological slot order of a drained ring: event number i lives
    at slot i % cap, so with count <= cap the slots are 0..count-1 and
    with overflow the surviving events are numbers count-cap..count-1
    (oldest dropped)."""
    if count <= cap:
        return np.arange(count, dtype=np.int64)
    return np.arange(count - cap, count, dtype=np.int64) % cap


class FlightRecorder:
    """Host half of the flight recorder: deferred ring drains, drop
    accounting, host-path events (reversion), and the runlog writer.

    The device half lives in ops/update.py (emission) and core/state.py
    (the ring fields); World.run drives the snapshot/drain pipeline at
    chunk boundaries."""

    def __init__(self, world):
        self.world = world
        self.events_total = 0
        self.dropped_total = 0
        self.code_totals = {name: 0 for name in EVENT_CODES.values()}
        self.last_drain_update = None
        self._host_events = []      # (update, cell, code, payload)
        self._own_writer = None
        self._log_opened = False

    # ---- host-path emission (reversion, future host events) ----

    def record_host_event(self, update: int, cell: int, code: int,
                          payload: int):
        """Queue a host-side event for the next drain (merged into the
        per-update trace records alongside the device ring's events)."""
        self._host_events.append(
            (int(update), int(cell), int(code), int(payload)))

    # ---- the drain pipeline (mirrors World._snapshot_newborns) ----

    def snapshot(self, world) -> dict:
        """Device-side copy of the ring + cursor reset, for a DEFERRED
        drain: the copies are async device ops (no host sync); the host
        ingests the snapshot one chunk later.  Ring rows past tr_count
        are scratch after this (exactly like nb_* rows past nb_count)."""
        st = world.state
        snap = {name: jnp.copy(getattr(st, name)) for name in _RING_FIELDS}
        snap["update_at"] = world.update
        snap["host_events"], self._host_events = self._host_events, []
        world.state = st.replace(tr_count=jnp.zeros((), jnp.int32))
        return snap

    def drain(self, snap: dict):
        """Host-sync a snapshot and append per-update trace records to
        the runlog.  A host sync point -- call only at event/report/exit
        boundaries (World.run's pipeline)."""
        count = int(np.asarray(snap["tr_count"]))
        cap = int(snap["tr_code"].shape[0])
        dropped = max(count - cap, 0)
        per_update: dict[int, list] = {}
        if count > 0 and cap > 0:
            order = ring_order(count, cap)
            ups = np.asarray(snap["tr_update"])[order]
            cells = np.asarray(snap["tr_cell"])[order]
            codes = np.asarray(snap["tr_code"])[order]
            pays = np.asarray(snap["tr_payload"])[order]
            for u, c, k, p in zip(ups.tolist(), cells.tolist(),
                                  codes.tolist(), pays.tolist()):
                per_update.setdefault(int(u), []).append([c, k, p])
        for u, c, k, p in snap.get("host_events", ()):
            per_update.setdefault(int(u), []).append([c, k, p])
        if not per_update and not dropped:
            self.last_drain_update = snap["update_at"]
            return
        w = self._writer()
        first = True
        for u in sorted(per_update):
            events = per_update[u]
            rec = {"record": "trace", "update": u, "events": events}
            if first and dropped:
                rec["dropped"] = dropped
            first = False
            w.write(rec)
            self.events_total += len(events)
            for c, k, p in events:
                name = EVENT_CODES.get(k)
                if name is not None:
                    self.code_totals[name] += 1
        self.dropped_total += dropped
        self.last_drain_update = snap["update_at"]

    # ---- writer plumbing ----

    def _writer(self):
        """The runlog writer: the telemetry recorder's when telemetry is
        on (trace records interleave with its update records in the same
        telemetry.jsonl), else a lazily opened writer of our own on the
        same path.  Reopens append (a second run(), or a checkpoint
        resume, must not truncate earlier records)."""
        w = self.world
        tel = getattr(w, "telemetry", None)
        if tel is not None:
            tel._ensure()
            return tel._writer
        if self._own_writer is None:
            from avida_tpu.observability.runlog import TelemetryWriter
            reopen = self._log_opened or getattr(w, "_dat_append", False)
            self._own_writer = TelemetryWriter(
                os.path.join(w.data_dir, "telemetry.jsonl"),
                mode=("a" if reopen else "w"))
            self._log_opened = True
        return self._own_writer

    def close(self):
        if self._own_writer is not None:
            self._own_writer.close()
            self._own_writer = None

    # ---- checkpoint integration (utils/checkpoint.py host block) ----

    def to_snapshot(self) -> dict:
        return {
            "events_total": int(self.events_total),
            "dropped_total": int(self.dropped_total),
            "code_totals": dict(self.code_totals),
            "last_drain_update": self.last_drain_update,
        }

    def from_snapshot(self, snap: dict):
        self.events_total = int(snap.get("events_total", 0))
        self.dropped_total = int(snap.get("dropped_total", 0))
        self.code_totals.update(snap.get("code_totals", {}))
        self.last_drain_update = snap.get("last_drain_update")
        self._host_events = []
        # resume continuity: append to the preempted run's runlog
        if os.path.exists(os.path.join(self.world.data_dir,
                                       "telemetry.jsonl")):
            self._log_opened = True
