"""Birth engine: placement of pending offspring as a batched scatter.

Replaces the reference's immediate in-update birth path
(cPopulation::ActivateOffspring cc:621 -> PositionOffspring cc:5185 ->
ActivateOrganism cc:1320) with an end-of-update flush: every organism with a
pending offspring picks a target cell (BIRTH_METHOD 0: random neighbor;
PREFER_EMPTY; ALLOW_PARENT), conflicts resolve deterministically (lowest
parent index wins; losers stay pending and retry next update -- a documented
lockstep semantic, SURVEY.md §7 step 5), and all winners scatter their
offspring state in one shot.

Offspring phenotype initialization mirrors cPhenotype::SetupOffspring
(cPhenotype.cc:349): merit inherited from the parent's post-DivideReset
merit, copied size from child_copied_size, last_* stats from the parent.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def neighbor_table(world_x: int, world_y: int, geometry: int) -> np.ndarray:
    """Static [N, 8] neighbor cell ids (ref cPopulation::SetupCellGrid
    cc:323 + cTopology.h wiring; geometry 1=bounded grid, 2=torus).

    For bounded grids, out-of-world neighbors are replaced by the cell itself
    (self-loops never win placement over real neighbors when empty cells are
    preferred; matches the reference's shorter connection lists closely
    enough for the lockstep engine)."""
    n = world_x * world_y
    out = np.zeros((n, 8), np.int32)
    offs = [(-1, -1), (-1, 0), (-1, 1), (0, -1), (0, 1), (1, -1), (1, 0), (1, 1)]
    for y in range(world_y):
        for x in range(world_x):
            c = y * world_x + x
            for k, (dy, dx) in enumerate(offs):
                ny, nx = y + dy, x + dx
                if geometry == 2:  # torus
                    ny %= world_y
                    nx %= world_x
                    out[c, k] = ny * world_x + nx
                else:              # bounded grid
                    if 0 <= ny < world_y and 0 <= nx < world_x:
                        out[c, k] = ny * world_x + nx
                    else:
                        out[c, k] = c
    return out


def flush_births(params, st, key, neighbors, update_no):
    """Place pending offspring.  neighbors: int32[N, 8] static table."""
    n, L = st.tape.shape
    rows = jnp.arange(n)
    k_place, k_inputs, k_off = jax.random.split(key, 3)
    # a parent that died while its offspring waited loses the offspring too
    # (the reference's pending birth dies with the parent's cell state)
    pending = st.divide_pending & st.alive

    # ---- target selection (PositionOffspring, cc:5185; BIRTH_METHOD 0) ----
    cand = neighbors                                  # [N, 8]
    if params.allow_parent:
        cand = jnp.concatenate([cand, rows[:, None]], axis=1)   # [N, 9]
    ncand = cand.shape[1]
    occupied = st.alive[cand]                         # [N, C]
    u = jax.random.uniform(k_place, (n, ncand))
    score = u
    if params.prefer_empty:
        score = score + jnp.where(~occupied, 10.0, 0.0)
    choice = jnp.argmax(score, axis=1)
    target = cand[rows, choice]                       # [N]

    # ---- conflict resolution: lowest parent index claims the cell ----
    # claim[j] = min index of a pending parent targeting cell j (BIG if none).
    # Every claimed cell receives exactly one birth, from parent claim[j];
    # this turns placement into a clean per-cell *gather* with no scatter
    # conflicts.
    BIG = jnp.int32(2**30)
    claim = jnp.full(n, BIG, jnp.int32)
    claim = claim.at[jnp.where(pending, target, rows)].min(
        jnp.where(pending, rows, BIG))
    births = claim < BIG                   # bool[N]: cell receives a newborn
    parent_idx = jnp.clip(claim, 0, n - 1)  # int[N]: who fathered it
    won = pending & (claim[target] == rows)

    # materialize offspring genomes (deferred h-divide half + divide
    # mutations; ops/interpreter.extract_offspring)
    from avida_tpu.core.state import make_cell_inputs
    from avida_tpu.ops.interpreter import extract_offspring, pack_tape
    off_mem, off_len = extract_offspring(params, st, k_off)
    fresh_inputs = make_cell_inputs(k_inputs, n)

    # breed-true: offspring genome identical to parent's birth genome
    # (ref cPhenotype copy_true; feeds count.dat/average.dat breed stats)
    cols = jnp.arange(L)
    same_site = (off_mem == st.genome) | (cols[None, :] >= off_len[:, None])
    is_breed_true = (off_len == st.genome_len) & same_site.all(axis=1)

    max_exec = jnp.where(
        params.death_method == 2, params.age_limit * off_len,
        jnp.where(params.death_method == 1, params.age_limit, 2**30))

    # Fields that genuinely depend on the parent and must be gathered by
    # parent index (the expensive part: two [N, L] row gathers + a dozen
    # [N] gathers).  Everything else on a newborn is a constant/fresh value
    # and is written directly at the target cell with no gather at all --
    # splitting these was worth ~2x on the whole birth flush at 100k cells.
    parent_updates = {
        "mem_len": off_len,
        "genome": off_mem, "genome_len": off_len,
        "merit": st.merit,                       # parent post-DivideReset merit
        "last_task_count": st.last_task_count,   # inherited expectation
        "gestation_time": st.gestation_time,     # parent's (SetupOffspring)
        "fitness": st.fitness, "last_bonus": st.last_bonus,
        "last_merit_base": st.last_merit_base,
        "executed_size": st.executed_size,
        "copied_size": st.child_copied_size,
        "generation": st.generation,             # parent already incremented
        "max_executed": max_exec,
        "breed_true": is_breed_true,
        "parent_id": rows.astype(jnp.int32),
    }
    const_updates = {
        "regs": 0, "heads": 0, "stacks": 0, "sp": 0, "active_stack": 0,
        "read_label": jnp.int8(0), "read_label_len": 0,
        "mal_active": False, "alive": True,
        "input_ptr": 0, "input_buf": 0, "input_buf_n": 0, "output_buf": 0,
        "cur_bonus": jnp.asarray(params.default_bonus, st.cur_bonus.dtype),
        "cur_task_count": 0, "cur_reaction_count": 0,
        "time_used": 0, "cpu_cycles": 0, "gestation_start": 0,
        "child_copied_size": 0, "num_divides": 0,
        "divide_pending": False, "off_start": 0, "off_len": 0,
        "off_copied_size": 0, "genotype_id": -1,
        "birth_update": update_no, "insts_executed": 0, "budget_carry": 0,
    }

    new_fields = {}
    for name, src in parent_updates.items():
        dst = getattr(st, name)
        mask = births.reshape((n,) + (1,) * (src.ndim - 1))
        new_fields[name] = jnp.where(mask, src[parent_idx], dst)
    # the newborn tape is the gathered offspring byte plane with flag bits
    # clear: reuse the genome gather instead of gathering a second [N, L]
    # plane
    new_fields["tape"] = jnp.where(births[:, None],
                                   pack_tape(new_fields["genome"]), st.tape)
    for name, val in const_updates.items():
        dst = getattr(st, name)
        mask = births.reshape((n,) + (1,) * (dst.ndim - 1))
        new_fields[name] = jnp.where(mask, jnp.asarray(val, dst.dtype), dst)
    # fresh per-cell input stream for the newborn (cell property, not
    # inherited -- indexed by target cell, so no gather either)
    new_fields["inputs"] = jnp.where(births[:, None], fresh_inputs, st.inputs)

    st = st.replace(**new_fields)
    # winners' (and dead parents') pending flags clear; living losers retry
    # next update; a parent cell overwritten by a newborn is already governed
    # by the newborn state
    cleared = jnp.where(won | ~st.alive, False, st.divide_pending)
    st = st.replace(divide_pending=cleared)
    return st
