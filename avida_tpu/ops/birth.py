"""Birth engine: placement of pending offspring as a batched scatter.

Replaces the reference's immediate in-update birth path
(cPopulation::ActivateOffspring cc:621 -> PositionOffspring cc:5185 ->
ActivateOrganism cc:1320) with an end-of-update flush: every organism with a
pending offspring picks a target cell (BIRTH_METHOD 0: random neighbor;
PREFER_EMPTY; ALLOW_PARENT), conflicts resolve deterministically (lowest
parent index wins; losers stay pending and retry next update -- a documented
lockstep semantic, SURVEY.md §7 step 5), and all winners scatter their
offspring state in one shot.

Offspring phenotype initialization mirrors cPhenotype::SetupOffspring
(cPhenotype.cc:349): merit inherited from the parent's post-DivideReset
merit, copied size from child_copied_size, last_* stats from the parent.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from avida_tpu.models.heads import MAX_LABEL_SIZE, SEM_H_DIVIDE_SEX


def has_divide_sex(params) -> bool:
    """Static: does the loaded instruction set contain divide-sex?"""
    return any(int(s) == SEM_H_DIVIDE_SEX for s in params.sem)


def _roll_right(plane, r, L):
    """Per-row circular roll: out[n, q] = plane[n, (q - r[n]) mod L], as
    log2(L) static jnp.roll steps (no per-row gather)."""
    r = r % L
    out = plane
    k, b = 1, 0
    while k < L:
        bit = (r >> b) & 1
        out = jnp.where((bit == 1)[:, None], jnp.roll(out, k, axis=1), out)
        k <<= 1
        b += 1
    return out


def recombine_sexual(params, st, key, off_mem, off_len, pending):
    """Birth-chamber mate pairing + one-region crossover, lockstep style.

    The reference stores a sexual offspring in the birth chamber until a
    mate arrives, then swaps a random region between the two genomes and
    mixes merits by the cut fraction (cBirthChamber::SubmitOffspring
    cc:443, DoBasicRecombination cc:290, RegionSwap cc:178).  Lockstep
    model: all sexual offspring pending at flush time -- the waiting store
    entry first, then cells in index order -- pair consecutively (rank r
    mates rank r^1).  Greedy pairing leaves at most ONE leftover, which
    moves INTO the single-entry store and its parent resumes (no stall --
    exactly the reference's waiting semantics).  Each paired parent row
    builds the child that keeps its own genome's flanks; the merit mix
    follows the content (stay/cut weighting), which reproduces the
    reference's majority-rule GenomeSwap pairing of genome and merit.  The
    row paired WITH the store is a dual parent: it also carries the
    store-flank child, which flush_births places as a second birth.
    Documented deviation: children are placed near their flank parent (the
    store child near its mate's parent) rather than both near the
    chamber-submitting parent.

    Returns (off_mem, off_len, child_merit, placeable_pending, dual, and
    the dual store-child fields (mem, len, merit), plus the updated store
    tuple (bc_mem, bc_len, bc_merit, bc_valid)).
    """
    n, L = off_mem.shape
    rows = jnp.arange(n)
    sexp = pending & st.off_sex
    has_store = st.bc_valid
    dropped = jnp.zeros(n, bool)

    if params.mating_types:
        # MATING_TYPES pairing (cBirthMatingTypeGlobalHandler::
        # SelectOffspring): juvenile parents lose their offspring, male-
        # and female-parent offspring pair by per-type rank (male rank r
        # mates female rank r); the single-slot store carries its parent's
        # type and occupies rank 0 of its own list.  LEKKING collapses in
        # lockstep: males waiting then females selecting is the same
        # symmetric pairing.  Excess waiters beyond the one store slot are
        # dropped (bounded-store deviation, as in the asex path).
        #
        # Per-type ranks are RANDOMLY permuted each flush (one uniform
        # draw per row, ranked within type), so which male mates which
        # female is a fresh random matching -- the reference draws a
        # random eligible mate per offspring; deterministic
        # rank-by-cell-index pairing made mate choice a function of grid
        # position (round-5 advisor; README documented deviations).
        ptype = st.mating_type
        juv_drop = sexp & (ptype == -1)
        sexp = sexp & ~juv_drop
        is_m = sexp & (ptype == 1)
        is_f = sexp & (ptype == 0)
        store_m = has_store & (st.bc_type == 1)
        store_f = has_store & (st.bc_type == 0)
        u_pair = jax.random.uniform(jax.random.fold_in(key, 0x9A13), (n,))

        def rand_rank(mask):
            # rank of each mask row among mask rows, ordered by u_pair
            # (masked rows sort to the end and get ranks >= mask.sum())
            order = jnp.argsort(jnp.where(mask, u_pair, jnp.inf))
            return jnp.zeros(n, jnp.int32).at[order].set(
                jnp.arange(n, dtype=jnp.int32))

        rank_m = rand_rank(is_m) + store_m.astype(jnp.int32)
        rank_f = rand_rank(is_f) + store_f.astype(jnp.int32)
        rank = jnp.where(is_m, rank_m, rank_f)
        tot_m = is_m.sum() + store_m.astype(jnp.int32)
        tot_f = is_f.sum() + store_f.astype(jnp.int32)
        pairs = jnp.minimum(tot_m, tot_f)
        paired = sexp & (rank < pairs)
        row_of_m = jnp.zeros(n, jnp.int32).at[
            jnp.where(is_m, rank_m, n)].set(rows.astype(jnp.int32),
                                            mode="drop")
        row_of_f = jnp.zeros(n, jnp.int32).at[
            jnp.where(is_f, rank_f, n)].set(rows.astype(jnp.int32),
                                            mode="drop")
        rc = jnp.clip(rank, 0, n - 1)
        mate_row = jnp.where(is_m, row_of_f[rc], row_of_m[rc])
        store_paired = paired & (rank == 0) & jnp.where(is_m, store_f,
                                                        store_m)
        dropped = juv_drop
    else:
        # rank sexual rows by cell index, shifted by 1 when the store
        # entry is occupied (the store is rank 0); rank r mates rank r^1
        rank = jnp.cumsum(sexp) - 1 + has_store.astype(jnp.int32)
        total = sexp.sum() + has_store.astype(jnp.int32)
        mate_rank = rank ^ 1
        paired = sexp & (mate_rank < total)
        store_paired = sexp & paired & (mate_rank == 0) & has_store
        rank_to_row = jnp.zeros(n, jnp.int32).at[
            jnp.where(sexp, rank, n)].set(rows.astype(jnp.int32),
                                          mode="drop")
        mate_row = rank_to_row[jnp.clip(mate_rank, 0, n - 1)]

    # mate genome/length/merit come from the store for the store-paired row
    mate_mem = jnp.where(store_paired[:, None], st.bc_mem[None, :].astype(jnp.int8),
                         off_mem[mate_row])
    mate_len = jnp.where(store_paired, st.bc_len,
                         jnp.where(paired, off_len[mate_row], 1))
    mate_len = jnp.maximum(mate_len, 1)
    mate_merit = jnp.where(store_paired, st.bc_merit.astype(st.merit.dtype),
                           st.merit[mate_row])
    own_len = jnp.maximum(off_len, 1)

    # per-pair draws: both members must see identical randomness, so draw
    # per-row and read the pair representative's values (the store-paired
    # row is its own representative)
    k_rec, k_s, k_e = jax.random.split(key, 3)
    pair_lo = jnp.where(store_paired, rows, jnp.minimum(rows, mate_row))
    u_rec = jax.random.uniform(k_rec, (n,))[pair_lo]
    f0 = jax.random.uniform(k_s, (n,))[pair_lo]
    f1 = jax.random.uniform(k_e, (n,))[pair_lo]
    if params.module_num > 0:
        # continuous modular recombination: crossover points snap to
        # module boundaries (DoModularContRecombination,
        # cBirthChamber.cc:316-330: start/end modules drawn uniformly)
        M = float(params.module_num)
        f0 = jnp.floor(f0 * M) / M
        f1 = jnp.floor(f1 * M) / M
    start_frac = jnp.minimum(f0, f1)
    end_frac = jnp.maximum(f0, f1)
    cut_frac = end_frac - start_frac

    s0 = (start_frac * own_len.astype(jnp.float32)).astype(jnp.int32)
    e0 = (end_frac * own_len.astype(jnp.float32)).astype(jnp.int32)
    s1 = (start_frac * mate_len.astype(jnp.float32)).astype(jnp.int32)
    e1 = (end_frac * mate_len.astype(jnp.float32)).astype(jnp.int32)
    size0 = e0 - s0
    size1 = e1 - s1
    new_len = off_len - size0 + size1
    new_len_mate = mate_len - size1 + size0
    # RegionSwap refuses illegal offspring on either side (cc:193-196)
    legal = ((new_len >= params.min_genome_len) & (new_len <= L) &
             (new_len_mate >= params.min_genome_len) & (new_len_mate <= L))
    do_rec = paired & (u_rec < params.recombination_prob) & legal

    # own-flank child = own[:s0] ++ mate[s1:e1] ++ own[e0:]
    cols = jnp.arange(L)
    mate_shifted = _roll_right(mate_mem, s0 - s1, L)
    own_shifted = _roll_right(off_mem, s0 + size1 - e0, L)
    child = jnp.where(cols[None, :] < s0[:, None], off_mem,
                      jnp.where(cols[None, :] < (s0 + size1)[:, None],
                                mate_shifted, own_shifted))
    child = jnp.where(cols[None, :] < new_len[:, None], child, jnp.int8(0))

    # store-flank child (only meaningful on the dual row) =
    # mate[:s1] ++ own[s0:e0] ++ mate[e1:]
    own_shifted2 = _roll_right(off_mem, s1 - s0, L)
    mate_shifted2 = _roll_right(mate_mem, s1 + size0 - e1, L)
    child2 = jnp.where(cols[None, :] < s1[:, None], mate_mem,
                       jnp.where(cols[None, :] < (s1 + size0)[:, None],
                                 own_shifted2, mate_shifted2))
    child2 = jnp.where(cols[None, :] < new_len_mate[:, None], child2,
                       jnp.int8(0))
    dual = store_paired
    dual_mem = jnp.where(do_rec[:, None], child2, mate_mem)
    dual_len = jnp.where(do_rec, new_len_mate, mate_len)

    stay = 1.0 - cut_frac
    # merit mixing: merit' = own*stay + mate*cut (DoBasicRecombination)
    child_merit = jnp.where(
        do_rec,
        (st.merit * stay + mate_merit * cut_frac).astype(st.merit.dtype),
        st.merit)
    dual_merit = jnp.where(
        do_rec, (mate_merit * stay + st.merit * cut_frac).astype(st.merit.dtype),
        mate_merit)

    off_mem = jnp.where(do_rec[:, None], child, off_mem)
    off_len = jnp.where(do_rec, new_len, off_len)

    # the odd one out moves into the store and its parent resumes; with
    # mating types there can be several unpaired waiters -- the lowest-
    # index one takes the slot, the rest are dropped (bounded store)
    unpaired = sexp & ~paired
    leftover = unpaired & (jnp.cumsum(unpaired) == 1)      # <=1 row
    dropped = dropped | (unpaired & ~leftover)
    # the occupant keeps its slot: a leftover only moves in when the slot
    # is empty or was consumed by a pairing this flush (in the asex path a
    # leftover implies exactly that, so this is a no-op there); otherwise
    # the newcomer is dropped too
    slot_free = ~has_store | store_paired.any()
    dropped = dropped | (leftover & ~slot_free)
    leftover = leftover & slot_free
    any_left = leftover.any()
    left_sel = leftover[:, None]
    new_bc_mem = jnp.where(any_left,
                           jnp.sum(jnp.where(left_sel, off_mem, 0), axis=0,
                                   dtype=jnp.int32).astype(jnp.int8),
                           st.bc_mem)
    new_bc_len = jnp.where(any_left,
                           jnp.sum(jnp.where(leftover, off_len, 0)),
                           st.bc_len)
    new_bc_merit = jnp.where(
        any_left,
        jnp.sum(jnp.where(leftover, st.merit, 0)).astype(jnp.float32),
        st.bc_merit.astype(jnp.float32))
    # store consumed when something paired with it; (re)filled by leftover
    new_bc_valid = jnp.where(any_left, True,
                             has_store & ~store_paired.any())

    new_bc_type = jnp.where(
        any_left,
        jnp.sum(jnp.where(leftover, st.mating_type, 0)).astype(jnp.int32)
        if params.mating_types else jnp.int32(-1),
        st.bc_type)
    placeable = pending & ~leftover & ~dropped
    store = (new_bc_mem, new_bc_len, new_bc_merit, new_bc_valid,
             new_bc_type)
    return (off_mem, off_len, child_merit, placeable,
            dual, dual_mem, dual_len, dual_merit, store)


_OFFS_2D = ((-1, -1), (-1, 0), (-1, 1), (0, -1), (0, 1), (1, -1), (1, 0),
            (1, 1))


def _fast_torus_placement(params, k_place, pending, alive, time_used, merit):
    """Target selection + conflict resolution for the torus fast path
    (local_torus_fast_path: BIRTH_METHOD 0-3, torus, asexual, no demes/
    caps) on cell-indexed [N] vectors -- 9 rolls + selects, no gathers.

    Factored out of flush_births (round 6) so the packed-native flush
    (flush_births_packed) shares the EXACT placement semantics and PRNG
    draw order with the canonical one; the claim/choice algebra is
    documented at the claim-resolution comment in flush_births.

    Returns (pending, births, parent_idx, won, dir_idx) where
    dir_idx[cell] = index into the placement offsets (_OFFS_2D + optional
    parent slot) of the direction the newborn at `cell` came FROM (-1 =
    no birth) -- the by-parent data movement is then a dir_idx-select
    over static rolls, for [N]-vectors and [LP, N] planes alike."""
    n = alive.shape[0]
    rows = jnp.arange(n)
    bm = params.birth_method
    wx, wy = params.world_x, params.world_y
    offs_all = _OFFS_2D + (((0, 0),) if params.allow_parent else ())
    ncand = len(offs_all)

    def nbr(x, k):
        dy, dx = offs_all[k]
        return _roll2d(x, -dy, -dx, wx, wy)

    occupied = jnp.stack([nbr(alive, k) for k in range(ncand)], axis=1)
    u = jax.random.uniform(k_place, (n, ncand))
    # empty-first lexicographic pick; see flush_births for why a shared
    # empty_bonus score would break the random tiebreak in float32
    empty_cand = ~occupied
    has_empty = empty_cand.any(axis=1)
    empty_pick = jnp.argmax(jnp.where(empty_cand, u, -1.0), axis=1)

    def pick_empty_first(occ_score):
        return jnp.where(has_empty, empty_pick,
                         jnp.argmax(occ_score, axis=1))

    if bm == 0:            # RANDOM neighbor (PREFER_EMPTY optional)
        choice = (pick_empty_first(u) if params.prefer_empty
                  else jnp.argmax(u, axis=1))
    elif bm == 1:          # AGE: replace the oldest neighbor; empty first
        occ_age = jnp.where(
            occupied,
            jnp.stack([nbr(time_used, k) for k in range(ncand)], axis=1), 0)
        choice = pick_empty_first(occ_age.astype(jnp.float32) + u)
    elif bm == 2:          # MERIT: replace the lowest-merit neighbor
        occ_merit = jnp.where(
            occupied,
            jnp.stack([nbr(merit, k) for k in range(ncand)], axis=1), 0)
        choice = pick_empty_first(-occ_merit.astype(jnp.float32) + u)
    else:                  # bm == 3, EMPTY: only empty cells qualify
        choice = empty_pick
    if bm == 3:
        # no empty candidate -> the parent keeps waiting
        pending = pending & ~occupied.all(axis=1)

    BIG = jnp.int32(2**30)
    claim = jnp.full(n, BIG, jnp.int32)
    dir_idx = jnp.full(n, -1, jnp.int32)
    pk_l, hit_l = [], []
    for k in range(ncand):
        dy, dx = offs_all[k]
        pk = _roll2d(rows, dy, dx, wx, wy)        # id of cell j - off_k
        pend_k = _roll2d(pending, dy, dx, wx, wy)
        ch_k = _roll2d(choice, dy, dx, wx, wy)
        hit = pend_k & (ch_k == k)                # that parent targets j
        claim = jnp.minimum(claim, jnp.where(hit, pk, BIG))
        pk_l.append(pk)
        hit_l.append(hit)
    for k in range(ncand):
        dir_idx = jnp.where(hit_l[k] & (pk_l[k] == claim), k, dir_idx)
    births = claim < BIG
    parent_idx = jnp.clip(claim, 0, n - 1)
    claim_at_tgt = jnp.full(n, BIG, jnp.int32)
    for k in range(ncand):
        claim_at_tgt = jnp.where(choice == k, nbr(claim, k), claim_at_tgt)
    won = pending & (claim_at_tgt == rows)
    return pending, births, parent_idx, won, dir_idx


def _roll2d(x, dy, dx, world_x, world_y):
    """Torus-shift a cell-indexed array: out[c] = x[cell at (y-dy, x-dx)],
    i.e. the value of the neighbor in direction (-dy,-dx) -- a pure
    streaming op (two static rolls), no gather."""
    n = world_x * world_y
    g = x.reshape((world_y, world_x) + x.shape[1:])
    g = jnp.roll(g, (dy, dx), axis=(0, 1))
    return g.reshape((n,) + x.shape[1:])


def local_torus_fast_path(params, sexual: bool) -> bool:
    """True when birth placement is strictly neighbor-local on a torus:
    every parent->target displacement is one of 9 static 2-D offsets, so
    all by-parent data movement is expressible as rolls + selects.  TPU
    gathers/scatters pay a per-row cost (~0.1 us x N at 100k cells);
    rolls stream at full bandwidth -- this path is worth ~6x on the whole
    birth flush at bench scale."""
    return (params.geometry == 2
            and params.birth_method in (0, 1, 2, 3)
            and params.num_demes <= 1
            and not sexual
            and params.world_x > 2 and params.world_y > 2
            and params.population_cap == 0 and params.pop_cap_eldest == 0)


def neighbor_table(world_x: int, world_y: int, geometry: int,
                   seed: int = 0, scale_free_m: int = 3,
                   scale_free_alpha: float = 1.0,
                   scale_free_zero_appeal: float = 0.0) -> np.ndarray:
    """Static [N, C] neighbor cell ids, -1 = padding slot (ref
    cPopulation::SetupCellGrid cc:376-394 switching over nGeometry.h:30-37
    via the cTopology.h builders).  Geometries:

      1 GRID   bounded 8-neighborhood (edge cells have shorter lists)
      2 TORUS  wrapped 8-neighborhood (C=8, no padding)
      3 CLIQUE every cell connects to every other (build_clique h:103)
      4 HEX    grid minus the NE/SW diagonals (build_hex h:119)
      6 LATTICE 3-D lattice with z=1 == bounded grid (build_lattice h:137)
      7 RANDOM_CONNECTED random bidirectional graph grown to connectivity
               (build_random_connected_network h:232)
      8 SCALE_FREE preferential-attachment graph, P ~ (deg/|E|)^alpha +
               zero_appeal, m edges per new vertex (build_scale_free h:376)

    GLOBAL (0) and PARTIAL (5) are declared in nGeometry.h but have no
    case in the reference's own SetupCellGrid switch -- they raise here
    too.  Random geometries are frozen at world construction from `seed`
    (the reference also builds them once at setup)."""
    n = world_x * world_y
    # column k of the table MUST be the _OFFS_2D[k] displacement: the torus
    # fast path (local_torus_fast_path) replaces gathers on this table with
    # rolls by _OFFS_2D[k], so the two orderings may never diverge
    offs = _OFFS_2D

    def grid_like(skip=()):
        out = np.full((n, 8), -1, np.int32)
        for y in range(world_y):
            for x in range(world_x):
                c = y * world_x + x
                col = 0
                for k, (dy, dx) in enumerate(offs):
                    if (dy, dx) in skip:
                        continue
                    ny, nx = y + dy, x + dx
                    if 0 <= ny < world_y and 0 <= nx < world_x:
                        out[c, col] = ny * world_x + nx
                        col += 1
        return out

    if geometry == 2:              # torus
        out = np.zeros((n, 8), np.int32)
        for y in range(world_y):
            for x in range(world_x):
                c = y * world_x + x
                for k, (dy, dx) in enumerate(offs):
                    out[c, k] = ((y + dy) % world_y) * world_x \
                        + (x + dx) % world_x
        return out
    if geometry in (1, 6):         # bounded grid; lattice with z=1 == grid
        return grid_like()
    if geometry == 4:              # hex: drop NE (-1,+1) and SW (+1,-1)
        return grid_like(skip={(-1, 1), (1, -1)})
    if geometry == 3:              # clique
        out = np.full((n, n - 1), -1, np.int32)
        ids = np.arange(n)
        for c in range(n):
            out[c] = np.concatenate([ids[:c], ids[c + 1:]])
        return out
    if geometry in (7, 8):
        rng = np.random.default_rng(seed + geometry)
        adj = [set() for _ in range(n)]
        if geometry == 7:          # random connected network
            connected = set()
            for i in range(n):
                j = i
                while j == i:
                    j = int(rng.integers(0, n))
                if j not in adj[i]:
                    adj[i].add(j)
                    adj[j].add(i)
                    connected.update((i, j))
            # grow to a single component like the reference's fix-up pass:
            # connect any stranded cell to a connected one
            comp = {0}
            frontier = [0]
            while frontier:
                c = frontier.pop()
                for d in adj[c]:
                    if d not in comp:
                        comp.add(d)
                        frontier.append(d)
            for i in range(n):
                if i not in comp:
                    j = int(rng.choice(sorted(comp)))
                    adj[i].add(j)
                    adj[j].add(i)
                    comp.add(i)
        else:                      # scale-free (build_scale_free h:376)
            adj[0].add(1)
            adj[1].add(0)
            edge_count = 1
            for u in range(2, n):
                to_add = min(u, scale_free_m)
                added = 0
                v = 0
                while added < to_add:
                    if v not in adj[u] and v != u:
                        p = (len(adj[v]) / edge_count) ** scale_free_alpha \
                            + scale_free_zero_appeal
                        if rng.random() < min(p, 1.0):
                            adj[u].add(v)
                            adj[v].add(u)
                            edge_count += 1
                            added += 1
                    v += 1
                    if v >= u:
                        v = 0
        deg = max(1, max(len(a) for a in adj))
        out = np.full((n, deg), -1, np.int32)
        for c in range(n):
            for k, d in enumerate(sorted(adj[c])):
                out[c, k] = d
        return out
    raise NotImplementedError(
        f"WORLD_GEOMETRY {geometry}: GLOBAL (0) and PARTIAL (5) have no "
        f"builder in the reference's cPopulation::SetupCellGrid either "
        f"(cPopulation.cc:376-394); supported: 1-4, 6-8")


def flush_births(params, st, key, neighbors, update_no, use_off_tape=False):
    """Place pending offspring.  neighbors: int32[N, 8] static table.

    use_off_tape: True only from update_step, which guarantees st.off_tape
    holds every pending offspring (kernel- or XLA-extracted).  Direct
    callers (tests, hand-built states) keep the tape-suffix barrel
    extraction."""
    n, L = st.tape.shape
    rows = jnp.arange(n)
    k_place, k_inputs, k_off, k_sex = jax.random.split(key, 4)
    # a parent that died while its offspring waited loses the offspring too
    # (the reference's pending birth dies with the parent's cell state)
    pending = st.divide_pending & st.alive

    # materialize offspring genomes (deferred h-divide half + divide
    # mutations; ops/interpreter.extract_offspring)
    from avida_tpu.core.state import make_cell_inputs
    from avida_tpu.ops.interpreter import extract_offspring, pack_tape
    off_mem, off_len = extract_offspring(
        params, st, k_off, use_off_tape=use_off_tape and params.hw_type == 0)
    fresh_inputs = make_cell_inputs(k_inputs, n)

    # sexual offspring pair + recombine in the birth chamber BEFORE
    # placement (mutations precede SubmitOffspring in the reference too);
    # the odd one out moves into the waiting store and leaves `pending`
    child_merit = st.merit
    sexual = has_divide_sex(params)
    leftover = jnp.zeros(n, bool)
    face_drop = None   # BIRTH_METHOD 7 on hw 3: invalid-facing drops
    dual = jnp.zeros(n, bool)
    dual_mem = dual_len = dual_merit = None
    store = None
    if sexual:
        (off_mem, off_len, child_merit, pending,
         dual, dual_mem, dual_len, dual_merit, store) = recombine_sexual(
            params, st, k_sex, off_mem, off_len, pending)
        leftover = (st.divide_pending & st.alive) & ~pending

    # ---- target selection (PositionOffspring, cc:5185: the 12
    # ePOSITION_OFFSPRING methods, Definitions.h:67-82) ----
    bm = params.birth_method
    fast = local_torus_fast_path(params, sexual)
    wx, wy = params.world_x, params.world_y
    offs_all = _OFFS_2D + (((0, 0),) if params.allow_parent else ())

    def nbr(x, k):
        """x at candidate k of each cell (torus fast path): a roll."""
        dy, dx = offs_all[k]
        return _roll2d(x, -dy, -dx, wx, wy)

    if fast:
        # strictly neighbor-local placement on a torus: selection AND
        # conflict resolution collapse to rolls + selects.  The helper
        # shares these exact semantics and PRNG draws with the
        # packed-native flush (flush_births_packed).
        pending, births, parent_idx, won, dir_idx = _fast_torus_placement(
            params, k_place, pending, st.alive, st.time_used, st.merit)

        def by_parent(x):
            out = jnp.zeros_like(x)
            for k, (dy, dx) in enumerate(offs_all):
                sel = dir_idx == k
                out = jnp.where(sel.reshape((n,) + (1,) * (x.ndim - 1)),
                                _roll2d(x, dy, dx, wx, wy), out)
            return out

    else:
        cand = neighbors                                  # [N, C]
        pad = cand < 0           # -1 slots (short connection lists); a padded
        cand = jnp.where(pad, rows[:, None], cand)        # slot never wins
        if params.num_demes > 1:
            # deme-local placement: candidates in a different deme collapse to
            # the parent cell (births stay inside the group; cross-deme birth
            # happens only through migration below).  Bands align with shards,
            # so this also keeps placement traffic on-device (ops/demes.py).
            cpd = params.num_cells // params.num_demes
            same_deme = (cand // cpd) == (rows // cpd)[:, None]
            cand = jnp.where(same_deme, cand, rows[:, None])
        if params.allow_parent and bm in (0, 1, 2, 3):
            cand = jnp.concatenate([cand, rows[:, None]], axis=1)   # [N, C+1]
            pad = jnp.concatenate(
                [pad, jnp.zeros((n, 1), bool)], axis=1)
        ncand = cand.shape[1]
        occupied = st.alive[cand]                         # [N, C]
        u = jax.random.uniform(k_place, (n, ncand))
        # Empty-first methods pick lexicographically: a uniformly-random empty
        # candidate when one exists, else the best occupied one.  (Adding a
        # large empty_bonus to a shared score would swallow the random
        # tiebreak in float32 -- 1e12 + u rounds back to 1e12 -- making every
        # "random among ties" pick deterministically lowest-index.)
        real = ~pad              # padding slots (short connection lists) never
        #                          win unless the cell has no real candidate
        empty_cand = real & ~occupied
        has_empty = empty_cand.any(axis=1)
        empty_pick = jnp.argmax(jnp.where(empty_cand, u, -1.0), axis=1)

        def pick_empty_first(occ_score):
            occ_pick = jnp.argmax(jnp.where(real, occ_score, -jnp.inf), axis=1)
            return jnp.where(has_empty, empty_pick, occ_pick)

        if bm == 0:            # RANDOM neighbor (PREFER_EMPTY optional)
            if params.prefer_empty:
                choice = pick_empty_first(u)
            else:
                choice = jnp.argmax(jnp.where(real, u, -1.0), axis=1)
        elif bm == 1:          # AGE: replace the oldest neighbor; empty first
            # stale stats of DEAD former occupants must not leak into scores
            occ_age = jnp.where(occupied, st.time_used[cand], 0)
            choice = pick_empty_first(occ_age.astype(jnp.float32) + u)
        elif bm == 2:          # MERIT: replace the lowest-merit neighbor
            occ_merit = jnp.where(occupied, st.merit[cand], 0)
            choice = pick_empty_first(-occ_merit.astype(jnp.float32) + u)
        elif bm == 3:          # EMPTY: only empty neighbor cells qualify
            choice = empty_pick
        else:
            choice = jnp.argmax(jnp.where(real, u, -1.0), axis=1)
        target = cand[rows, choice]                       # [N]
        if bm == 3:
            # no empty candidate -> the parent keeps waiting (the reference
            # simply fails the birth)
            pending = pending & ~occupied.all(axis=1)
        elif bm == 4:          # FULL_SOUP_RANDOM: anywhere in the world/deme
            if params.num_demes > 1:
                cpd = params.num_cells // params.num_demes
                r = jax.random.randint(jax.random.fold_in(k_place, 4), (n,), 0,
                                       cpd, dtype=jnp.int32)
                target = (rows // cpd) * cpd + r
            else:
                target = jax.random.randint(jax.random.fold_in(k_place, 4),
                                            (n,), 0, n, dtype=jnp.int32)
        elif bm == 5:          # FULL_SOUP_ELDEST (reaper queue analogue):
            # everyone targets the globally oldest slot (empty cells count as
            # infinitely old); lowest parent index wins the claim
            age = jnp.where(st.alive, st.time_used, 2**30)
            target = jnp.full(n, jnp.argmax(age), jnp.int32)
        elif bm == 6:          # DEME_RANDOM
            cpd = params.num_cells // max(params.num_demes, 1)
            r = jax.random.randint(jax.random.fold_in(k_place, 6), (n,), 0,
                                   cpd, dtype=jnp.int32)
            target = (rows // cpd) * cpd + r
        elif bm == 7:          # PARENT_FACING (cPopulation.cc:5259): the faced
            # connection.  Experimental hardware (hw 3) has real facing state
            # (rotate-x / rotate-org-id), so the offspring goes one step in
            # the parent's facing direction; heads hardware models no
            # rotation, so facing = connection 0 (documented deviation)
            if params.hw_type == 3:
                from avida_tpu.ops.interpreter import _facing_step
                ftgt, fvalid = _facing_step(params, rows, st.facing,
                                            jnp.ones_like(rows))
                target = jnp.where(fvalid, ftgt, rows)
                # Off-grid facing on a bounded geometry can never produce a
                # birth (the reference cannot reach this state: its facing
                # indexes the connection list, which only holds in-grid
                # cells).  The offspring is DROPPED and the parent resumes --
                # same policy as the mating-type store drops.  Retrying
                # instead would livelock the parent permanently: a
                # divide-pending organism is excluded from exec_mask, so it
                # could never execute rotate-x to fix its facing.
                face_drop = pending & ~fvalid
                pending = pending & fvalid
            else:
                target = jnp.where(neighbors[:, 0] < 0, rows, neighbors[:, 0])
        elif bm == 8:          # NEXT_CELL
            target = (rows + 1) % n
        elif bm == 9:          # FULL_SOUP_ENERGY_USED (cPopulation.cc:5332):
            # the cell whose occupant has used the most energy (time used when
            # the energy model is off); empty cells count as INT_MAX, i.e.
            # preferred; random tiebreak
            used9 = (st.energy_spent if params.energy_enabled
                     else st.time_used.astype(jnp.float32))
            u9 = jax.random.uniform(jax.random.fold_in(k_place, 9), (n,))
            any_dead = (~st.alive).any()
            dead_pick = jnp.argmax(jnp.where(st.alive, -1.0, u9))
            live_pick = jnp.argmax(jnp.where(st.alive, used9 + u9, -jnp.inf))
            target = jnp.full(n, jnp.where(any_dead, dead_pick, live_pick),
                              jnp.int32)
        elif bm == 10:         # NEIGHBORHOOD_ENERGY_USED (cc:5400): same rule
            # among the parent's connections (empty-first, random tiebreak,
            # padded slots excluded -- same lexicographic pick as bm 0-3)
            used10 = (st.energy_spent if params.energy_enabled
                      else st.time_used.astype(jnp.float32))
            choice10 = pick_empty_first(
                jnp.where(occupied, used10[cand], 0.0) + u)
            target = cand[rows, choice10]
        elif bm == 11:         # DISPERSAL (cc:5363): a Poisson(DISPERSAL_RATE)
            # number of random single-cell hops from the parent (capped at 8)
            k11 = jax.random.fold_in(k_place, 11)
            hops = jnp.clip(jax.random.poisson(
                jax.random.fold_in(k11, 0), params.dispersal_rate, (n,)),
                0, 8).astype(jnp.int32)
            wx, wy = params.world_x, params.world_y
            y = rows // wx
            x = rows % wx
            for h in range(8):
                kd = jax.random.fold_in(k11, h + 1)
                d = jax.random.randint(kd, (n,), 0, 8, jnp.int32)
                step = h < hops
                dy = jnp.where(d < 3, -1, jnp.where(d < 5, 0, 1))
                dx_t = jnp.asarray([-1, 0, 1, -1, 1, -1, 0, 1], jnp.int32)
                dx = dx_t[d]
                if params.geometry == 2:
                    y = jnp.where(step, (y + dy) % wy, y)
                    x = jnp.where(step, (x + dx) % wx, x)
                else:
                    y = jnp.where(step, jnp.clip(y + dy, 0, wy - 1), y)
                    x = jnp.where(step, jnp.clip(x + dx, 0, wx - 1), x)
            target = y * wx + x
        if params.num_demes > 1 and bm in (5, 7, 8):
            # global/absolute targets must still respect deme boundaries:
            # a cross-deme target collapses to the parent cell (only
            # DEMES_MIGRATION_RATE crosses demes)
            cpd = params.num_cells // params.num_demes
            target = jnp.where(target // cpd == rows // cpd, target, rows)
        if params.num_demes > 1 and params.demes_migration_rate > 0:
            # DEMES_MIGRATION_RATE: migrating offspring land in another deme
            # picked by DEMES_MIGRATION_METHOD (cPopulation.cc:5508-5600):
            #   0 uniform over the other demes, 1 random 8-neighbor on the
            #   DEMES_NUM_X deme grid, 2 list-adjacent (+/-1), 4 weight-matrix
            #   (MIGRATION_FILE; cMigrationMatrix::GetProbabilisticDemeID);
            # then a uniform random cell of the target deme.
            k_mig, k_mcell, k_mdeme = jax.random.split(
                jax.random.fold_in(k_place, 1), 3)
            migrate = (jax.random.uniform(k_mig, (n,))
                       < params.demes_migration_rate) & pending
            cpd = params.num_cells // params.num_demes
            D = params.num_demes
            home = rows // cpd
            mm = params.demes_migration_method
            if mm == 0:
                d_r = jax.random.randint(k_mdeme, (n,), 0, D - 1,
                                         dtype=jnp.int32)
                mig_deme = jnp.where(d_r >= home, d_r + 1, d_r)
            elif mm == 1:
                xs = params.demes_num_x
                ys = D // xs
                d8 = jax.random.randint(k_mdeme, (n,), 0, 8, dtype=jnp.int32)
                dy = jnp.asarray([-1, -1, -1, 0, 0, 1, 1, 1], jnp.int32)[d8]
                dx = jnp.asarray([-1, 0, 1, -1, 1, -1, 0, 1], jnp.int32)[d8]
                mx = (home % xs + dx + xs) % xs
                my = (home // xs + dy + ys) % ys
                mig_deme = mx + xs * my
            elif mm == 2:
                pm = jax.random.randint(k_mdeme, (n,), 0, 2,
                                        dtype=jnp.int32) * 2 - 1
                mig_deme = (home + pm + D) % D
            elif mm == 4:
                u_d = jax.random.uniform(k_mdeme, (n,))
                cdf = jnp.asarray(params.migration_cdf, jnp.float32)  # [D, D]
                row_cdf = cdf[home]                                   # [n, D]
                mig_deme = (u_d[:, None] >= row_cdf).sum(
                    axis=1).astype(jnp.int32)
                mig_deme = jnp.clip(mig_deme, 0, D - 1)
            else:
                raise NotImplementedError(
                    f"DEMES_MIGRATION_METHOD {mm}")
            mig_cell = mig_deme * cpd + jax.random.randint(
                k_mcell, (n,), 0, cpd, dtype=jnp.int32)
            target = jnp.where(migrate, mig_cell, target)

        # ---- conflict resolution: lowest parent index claims the cell ----
        # claim[j] = min index of a pending parent targeting cell j (BIG if none).
        # Every claimed cell receives exactly one birth, from parent claim[j];
        # this turns placement into a clean per-cell *gather* with no scatter
        # conflicts.  On the torus fast path (_fast_torus_placement above) the
        # scatter-min, the claim[target] gather, and every later by-parent
        # gather become 9 rolls + selects (local_torus_fast_path).
        BIG = jnp.int32(2**30)
        claim = jnp.full(n, BIG, jnp.int32)
        claim = claim.at[jnp.where(pending, target, rows)].min(
            jnp.where(pending, rows, BIG))
        births = claim < BIG               # bool[N]: cell receives a newborn
        parent_idx = jnp.clip(claim, 0, n - 1)  # int[N]: who fathered it
        won = pending & (claim[target] == rows)

        def by_parent(x):
            return x[parent_idx]

    # breed-true: offspring genome identical to parent's birth genome
    # (ref cPhenotype copy_true; feeds count.dat/average.dat breed stats)
    cols = jnp.arange(L)
    same_site = (off_mem == st.genome) | (cols[None, :] >= off_len[:, None])
    is_breed_true = (off_len == st.genome_len) & same_site.all(axis=1)

    max_exec = jnp.where(
        params.death_method == 2, params.age_limit * off_len,
        jnp.where(params.death_method == 1, params.age_limit, 2**30))

    # Fields that genuinely depend on the parent and must be gathered by
    # parent index (the expensive part: two [N, L] row gathers + a dozen
    # [N] gathers).  Everything else on a newborn is a constant/fresh value
    # and is written directly at the target cell with no gather at all --
    # splitting these was worth ~2x on the whole birth flush at 100k cells.
    parent_updates = {
        "mem_len": off_len,
        "genome": off_mem, "genome_len": off_len,
        "merit": child_merit,                    # parent post-DivideReset
                                                 # merit; recombination-mixed
                                                 # for sexual pairs
        "last_task_count": st.last_task_count,   # inherited expectation
        "gestation_time": st.gestation_time,     # parent's (SetupOffspring)
        "fitness": st.fitness, "last_bonus": st.last_bonus,
        "last_merit_base": st.last_merit_base,
        "executed_size": st.executed_size,
        "copied_size": st.child_copied_size,
        # GENERATION_INC_METHOD 1 (default): parent incremented at divide,
        # child copies it; method 0: only the child increments
        # (cPhenotype::SetupOffspring cc:476)
        "generation": st.generation + (
            0 if params.generation_inc_method == 1 else 1),
        "max_executed": max_exec,
        "breed_true": is_breed_true,
        "parent_id": rows.astype(jnp.int32),
    }
    const_updates = {
        "regs": 0, "heads": 0, "stacks": 0, "sp": 0, "active_stack": 0,
        "read_label": jnp.int8(0), "read_label_len": 0,
        "mal_active": False, "alive": True, "sterile": False,
        "input_ptr": 0, "input_buf": 0, "input_buf_n": 0, "output_buf": 0,
        "cur_bonus": jnp.asarray(params.default_bonus, st.cur_bonus.dtype),
        "cur_task_count": 0, "cur_reaction_count": 0,
        "time_used": 0, "cpu_cycles": 0, "gestation_start": 0,
        "child_copied_size": 0, "num_divides": 0,
        "divide_pending": False, "off_start": 0, "off_len": 0,
        "off_tape": jnp.uint8(0),
        "off_copied_size": 0, "genotype_id": -1,
        "birth_update": update_no, "insts_executed": 0, "budget_carry": 0,
        # cost engine starts clean (no inherited debt or paid ft bits)
        "cost_wait": 0, "ft_paid_lo": 0, "ft_paid_hi": 0,
        "energy_spent": 0.0,
        # offspring start single-threaded (slot 0 only)
        "t_alive": False, "main_tid": 0, "t_ids": 0, "cur_thread": 0,
        "t_regs": 0, "t_heads": 0, "t_stack": 0, "t_sp": 0,
        "t_active_stack": 0, "t_rlabel": jnp.int8(0), "t_rlabel_len": 0,
        "mating_type": -1,     # offspring are juvenile (cPhenotype.cc:433)
        # TransSMT state (size-0 axes on heads hardware; writes are no-ops)
        "smt_aux": jnp.uint8(0), "smt_aux_len": 0,
        "pmem": jnp.uint8(0), "pmem_len": 0, "parasite_active": False,
        "smt_stacks": 0, "smt_sp": 0, "gstack": 0, "gsp": 0,
        "smt_head_pos": 0, "inject_pending": False,
        "inj_mem": jnp.uint8(0), "inj_len": 0,
    }

    if params.hw_type == 3:
        # experimental hardware: offspring inherit the forage target
        # (cPhenotype::SetupOffspring forage inheritance)
        parent_updates["forage_target"] = st.forage_target
    if params.energy_enabled:
        # energy split at birth (cPhenotype::SetupOffspring energy branch +
        # FRAC_PARENT_ENERGY_GIVEN_TO_ORG_AT_BIRTH / decay): the child
        # receives its share when the birth actually lands; merit follows
        # the energy (ConvertEnergyToMerit)
        from avida_tpu.ops.interpreter import convert_energy_to_merit
        keep = (1.0 - params.frac_energy_decay_birth)
        child_energy = st.energy * keep * params.frac_parent_energy \
            + params.energy_given_at_birth
        if params.energy_cap > 0:
            child_energy = jnp.minimum(child_energy, params.energy_cap)
        parent_updates["energy"] = child_energy
        parent_updates["merit"] = convert_energy_to_merit(
            params, child_energy).astype(st.merit.dtype)
    new_fields = {}
    for name, src in parent_updates.items():
        dst = getattr(st, name)
        mask = births.reshape((n,) + (1,) * (src.ndim - 1))
        new_fields[name] = jnp.where(mask, by_parent(src), dst)
    # the newborn tape is the gathered offspring byte plane with flag bits
    # clear: reuse the genome gather instead of gathering a second [N, L]
    # plane
    new_fields["tape"] = jnp.where(births[:, None],
                                   pack_tape(new_fields["genome"]), st.tape)
    for name, val in const_updates.items():
        dst = getattr(st, name)
        mask = births.reshape((n,) + (1,) * (dst.ndim - 1))
        new_fields[name] = jnp.where(mask, jnp.asarray(val, dst.dtype), dst)
    # fresh per-cell input stream for the newborn (cell property, not
    # inherited -- indexed by target cell, so no gather either)
    new_fields["inputs"] = jnp.where(births[:, None], fresh_inputs, st.inputs)
    if params.hw_type == 3:
        # newborns face a random ring direction (cPopulationCell random
        # rotation at activation)
        k_face = jax.random.fold_in(key, 0xFACE)
        new_fields["facing"] = jnp.where(
            births, jax.random.randint(k_face, (n,), 0, 8, jnp.int32),
            st.facing)
    if params.hw_type in (1, 2):
        # newborn SMT thread bases: host at space 0, parasite at space 2
        base = jnp.asarray([[0, 0, 0, 0], [2, 2, 2, 2]],
                           st.smt_head_space.dtype)
        new_fields["smt_head_space"] = jnp.where(
            births[:, None, None], base[None], st.smt_head_space)

    if sexual:
        # second child of the store-paired dual row: place at another of
        # the dual parent's neighbor cells, avoiding every cell already
        # claimed this flush (at most one dual row exists, so dual
        # placements never conflict with each other)
        claimed2 = births[cand]                           # [N, C]
        score2 = u - jnp.where(claimed2, 100.0, 0.0) \
            - jnp.where(jnp.arange(ncand)[None, :] == choice[:, None],
                        200.0, 0.0)
        if params.prefer_empty:
            score2 = score2 + jnp.where(~occupied, 10.0, 0.0)
        choice2 = jnp.argmax(score2, axis=1)
        target2 = cand[rows, choice2]
        dual_born = dual & won & ~births[target2]
        b2 = jnp.zeros(n, bool).at[jnp.where(dual_born, target2, n)].set(
            True, mode="drop")
        p2 = jnp.full(n, 0, jnp.int32).at[
            jnp.where(dual_born, target2, n)].set(rows.astype(jnp.int32),
                                                  mode="drop")

        def apply_dual(nf):
            parent2 = {
                "mem_len": dual_len, "genome": dual_mem,
                "genome_len": dual_len, "merit": dual_merit,
                "last_task_count": st.last_task_count,
                "gestation_time": st.gestation_time, "fitness": st.fitness,
                "last_bonus": st.last_bonus,
                "last_merit_base": st.last_merit_base,
                "executed_size": st.executed_size,
                "copied_size": st.child_copied_size,
                "generation": st.generation + (
                    0 if params.generation_inc_method == 1 else 1),
                "max_executed": jnp.where(
                    params.death_method == 2, params.age_limit * dual_len,
                    jnp.where(params.death_method == 1, params.age_limit,
                              2**30)),
                "breed_true": jnp.zeros(n, bool),
                "parent_id": rows.astype(jnp.int32),
            }
            nf = dict(nf)
            for name, srca in parent2.items():
                dst = nf[name]
                mask = b2.reshape((n,) + (1,) * (srca.ndim - 1))
                nf[name] = jnp.where(mask, srca[p2], dst)
            nf["tape"] = jnp.where(
                b2[:, None], pack_tape(nf["genome"]), nf["tape"])
            for name, val in const_updates.items():
                dst = nf[name]
                mask = b2.reshape((n,) + (1,) * (dst.ndim - 1))
                nf[name] = jnp.where(mask, jnp.asarray(val, dst.dtype), dst)
            nf["inputs"] = jnp.where(b2[:, None], fresh_inputs, nf["inputs"])
            return nf

        # the dual merge doubles the flush's field writes; gate it on a
        # dual birth actually happening this flush (usually absent)
        new_fields = jax.lax.cond(dual_born.any(), apply_dual,
                                  lambda nf: dict(nf), new_fields)
        births = births | b2

    if st.nb_genome.shape[0] > 0:
        # append this flush's newborns to the device-side record buffer
        # (host systematics drains it at chunk boundaries; world.py)
        CAP = st.nb_genome.shape[0]
        rank = jnp.cumsum(births.astype(jnp.int32)) - 1
        slot = st.nb_count + rank
        ok = births & (slot < CAP)
        idx = jnp.where(ok, slot, CAP)          # CAP = dropped
        st_nb = dict(
            nb_genome=st.nb_genome.at[idx].set(
                new_fields["genome"], mode="drop"),
            nb_len=st.nb_len.at[idx].set(new_fields["genome_len"],
                                         mode="drop"),
            nb_cell=st.nb_cell.at[idx].set(rows.astype(jnp.int32),
                                           mode="drop"),
            nb_parent=st.nb_parent.at[idx].set(
                jnp.where(births, parent_idx, -1), mode="drop"),
            nb_update=st.nb_update.at[idx].set(
                jnp.full(n, update_no, jnp.int32), mode="drop"),
            nb_count=st.nb_count + births.sum(),
        )
        new_fields.update(st_nb)

    if params.num_demes > 1:
        # per-deme birth tally (cDeme::IncBirthCount; feeds CompeteDemes
        # competition_type 1 and the BIRTHS replication trigger)
        cpd = params.num_cells // params.num_demes
        db = births.reshape(params.num_demes, cpd).sum(axis=1)
        new_fields["deme_birth_count"] = st.deme_birth_count + db

    st = st.replace(**new_fields)
    if sexual:
        bc_mem, bc_len, bc_merit, bc_valid, bc_type = store
        # transactional store: if the dual row existed but its store child
        # could not be placed (placement conflict), the original waiting
        # entry is NOT consumed -- unless a new leftover already took the
        # single slot (bounded-store drop, documented)
        restore = dual.any() & ~b2.any() & ~bc_valid
        bc_mem = jnp.where(restore, st.bc_mem, bc_mem)
        bc_len = jnp.where(restore, st.bc_len, bc_len)
        bc_merit = jnp.where(restore, st.bc_merit, bc_merit)
        bc_valid = bc_valid | restore
        st = st.replace(bc_mem=bc_mem, bc_len=bc_len, bc_merit=bc_merit,
                        bc_valid=bc_valid, bc_type=bc_type)
    # winners' (and dead parents') pending flags clear; a leftover sexual
    # offspring moved into the birth-chamber store, so its parent resumes
    # too; a BIRTH_METHOD 7 parent whose facing points off-grid drops its
    # offspring and resumes (the birth can never succeed -- retrying would
    # livelock it out of exec_mask forever); living losers retry next
    # update; a parent cell overwritten by a newborn is already governed
    # by the newborn state
    resumes = won | leftover | ~st.alive
    if face_drop is not None:
        resumes = resumes | face_drop
    cleared = jnp.where(resumes, False, st.divide_pending)
    st = st.replace(divide_pending=cleared,
                    off_sex=st.off_sex & cleared)
    if params.energy_enabled:
        # the winning parent keeps (1-decay)(1-frac) of its energy; its
        # merit tracks the new store (cPhenotype::DivideReset energy branch)
        from avida_tpu.ops.interpreter import convert_energy_to_merit
        keep = (1.0 - params.frac_energy_decay_birth)
        parent_after = st.energy * keep * (1.0 - params.frac_parent_energy)
        new_energy = jnp.where(won, parent_after, st.energy)
        st = st.replace(
            energy=new_energy,
            merit=jnp.where(won, convert_energy_to_merit(
                params, new_energy).astype(st.merit.dtype), st.merit))
    if params.population_cap > 0 or params.pop_cap_eldest > 0:
        # carrying capacity (cPopulation::PositionOffspring pop-cap kills,
        # cc:5192-5238): when the population exceeds the cap, kill the
        # excess -- random victims for POPULATION_CAP, the oldest for
        # POP_CAP_ELDEST -- sparing this update's newborns
        cap = params.population_cap or params.pop_cap_eldest
        eligible = st.alive & ~births       # newborns are spared
        excess = jnp.minimum(jnp.maximum(st.alive.sum() - cap, 0),
                             eligible.sum())
        k_cap = jax.random.fold_in(key, 0xCAB)
        if params.pop_cap_eldest > 0:
            score = jnp.where(eligible,
                              st.time_used.astype(jnp.float32)
                              + jax.random.uniform(k_cap, (n,)), -1.0)
        else:
            score = jnp.where(eligible,
                              jax.random.uniform(k_cap, (n,)), -1.0)
        order = jnp.argsort(-score)
        rank = jnp.zeros(n, jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))
        st = st.replace(alive=st.alive & ~(rank < excess))
    if params.hw_type in (1, 2):
        # a winning SMT parent's offspring buffer resets to the 1-inst
        # blank (Divide_Main tail, cHardwareTransSMT.cc:485)
        st = st.replace(
            smt_aux=st.smt_aux.at[:, 0].set(
                jnp.where(won[:, None], jnp.uint8(0), st.smt_aux[:, 0])),
            smt_aux_len=st.smt_aux_len.at[:, 0].set(
                jnp.where(won, 1, st.smt_aux_len[:, 0])))
        st = flush_injections(params, st, jax.random.fold_in(key, 17),
                              neighbors)
    return st


# ---------------------------------------------------------------------------
# Packed-native birth flush (round-6 tentpole).
#
# Under the packed-resident update chunk (ops/packed_chunk.py) the
# population state lives in the Pallas kernel's [LP, N] word-plane layout
# for a whole chunk of updates, in CELL-ordered lanes.  The flush below
# re-implements flush_births' torus fast path DIRECTLY on those planes:
# per-byte operations become SWAR word algebra (helpers `_pk_*`), the
# by-parent data movement becomes lane-axis rolls on [LP, N] (the same 9
# static rolls _fast_torus_placement uses for its [N] vectors), and NO
# traced lane-axis gather of a packed plane ever happens -- the data
# movement that sank the round-4/5 budget-binning attempts.
#
# Bit-exactness contract: flush_births_packed(pack(st)) == pack(
# flush_births(st)) for every eligible configuration -- same PRNG key
# splits, same draw shapes, same placement algebra (shared via
# _fast_torus_placement).  tests/test_packed_chunk.py holds this.
# ---------------------------------------------------------------------------


def _pk_rows(LP):
    return jnp.arange(LP, dtype=jnp.int32)[:, None]


def _pk_bytemask(m):
    """int32 mask of the m lowest bytes of a word, m in [0, 4] (same
    algebra as the kernel's bytemask; m broadcasts to [LP, N])."""
    r = jnp.where(m <= 0, 0, 0xFF)
    r = jnp.where(m >= 2, 0xFFFF, r)
    r = jnp.where(m >= 3, 0xFFFFFF, r)
    return jnp.where(m >= 4, -1, r)


def _pk_range_mask(LP, lo, hi):
    """int32[LP, N] byte mask selecting tape positions [lo, hi) of a
    packed [LP, N] word plane (lo/hi are [N] position vectors)."""
    base = _pk_rows(LP) * 4
    return (_pk_bytemask(jnp.clip(hi - base, 0, 4))
            & ~_pk_bytemask(jnp.clip(lo - base, 0, 4)))


def _pk_set_byte(plane, pos, val):
    """Set the byte at position pos[lane] to val[lane] (int32 0..255)."""
    LP = plane.shape[0]
    sh = (pos & 3) * 8
    hit = _pk_rows(LP) == (pos >> 2)
    return jnp.where(hit,
                     (plane & ~(jnp.int32(255) << sh))
                     | (val.astype(jnp.int32) << sh), plane)


def _pk_shift_r1(plane):
    """Byte-funnel shift right by ONE position: out[q] = in[q - 1]
    (position 0 gets 0)."""
    up = jnp.concatenate(
        [jnp.zeros((1, plane.shape[1]), jnp.int32), plane[:-1]], axis=0)
    return (plane << 8) | ((up >> 24) & 0xFF)


def _pk_shift_l1(plane):
    """Byte-funnel shift left by ONE position: out[q] = in[q + 1]."""
    down = jnp.concatenate(
        [plane[1:], jnp.zeros((1, plane.shape[1]), jnp.int32)], axis=0)
    return ((plane >> 8) & 0x00FFFFFF) | (down << 24)


def _pk5_prefix_mask(L5, hi):
    """int32[L5, N] 5-bit-field mask selecting positions [0, hi) of a
    5-bit-packed [L5, N] plane (hi is a [N] position vector) -- the
    codec counterpart of _pk_range_mask(LP, 0, hi).  Max 6 live fields
    per word = 30 payload bits, so the full-word mask is 0x3FFFFFFF and
    the shift never touches the sign bit."""
    m = jnp.clip(hi[None, :]
                 - jnp.arange(L5, dtype=jnp.int32)[:, None] * 6, 0, 6)
    return (jnp.int32(1) << (5 * m)) - 1


def _pk_to_plane5(plane, L5):
    """Byte word plane int32[LP, N] (opcodes < 32 per byte) -> 5-bit
    word plane int32[L5, N] (pallas_cycles._pack_words5 layout).  The
    flush's bridge between the kernel's byte-layout offspring plane and
    the bit-packed genome shadow under TPU_PACKED_BITS=1."""
    LP, n = plane.shape
    b = jnp.stack([(plane >> (8 * k)) & 0x1F for k in range(4)],
                  axis=1).reshape(LP * 4, n)
    pad = L5 * 6 - LP * 4
    if pad > 0:
        b = jnp.pad(b, ((0, pad), (0, 0)))
    g = b[:L5 * 6].reshape(L5, 6, n)
    sh = (jnp.arange(6, dtype=jnp.int32) * 5)[None, :, None]
    return (g << sh).sum(axis=1).astype(jnp.int32)


def _pk_roll2d(x, dy, dx, wx, wy):
    """Torus-shift along the LAST (cell/lane) axis: the [LP, N]-plane /
    [K, N]-matrix counterpart of _roll2d (same displacement semantics:
    out[..., c] = x[..., cell at (y-dy, x-dx)])."""
    lead = x.shape[:-1]
    g = x.reshape(lead + (wy, wx))
    g = jnp.roll(g, (dy, dx), axis=(-2, -1))
    return g.reshape(lead + (wy * wx,))


def _pk_extract_offspring(params, key, off_t, off_len, genome_len,
                          divide_pending):
    """extract_offspring's divide-mutation half on the packed [LP, N]
    offspring plane (the barrel extraction itself already happened at
    the divide cycle, in-kernel).  Mirrors ops/interpreter.
    extract_offspring's PRNG draw-for-draw (same key splits, shapes and
    order) so the packed flush stays bit-exact vs the canonical one.
    DIVIDE_SLIP_PROB is not ported (packed_chunk.active gates it off).

    Returns (off plane int32[LP, N], off_len int32[N])."""
    from avida_tpu.ops.interpreter import random_inst
    LP, n = off_t.shape
    L0 = params.max_memory
    zeros_n = jnp.zeros(n, jnp.int32)
    fullL = jnp.full(n, LP * 4, jnp.int32)

    off = off_t & _pk_range_mask(LP, zeros_n, off_len)
    gsize = genome_len.astype(jnp.float32)
    max_sz = jnp.minimum(L0, (gsize * params.offspring_size_range
                              ).astype(jnp.int32))
    div_m = divide_pending

    k_u, k_mpos, k_ipos, k_dpos, k_iinst = jax.random.split(key, 5)
    u_mut = jax.random.uniform(k_u, (n, 3))
    r_inst2 = random_inst(params, k_iinst, (n, 2))

    def ins1(off, off_len, ipos, iv, do):
        sel = _pk_range_mask(LP, ipos + 1, fullL)
        out = (_pk_shift_r1(off) & sel) | (off & ~sel)
        out = _pk_set_byte(out, ipos, iv)
        return (jnp.where(do[None, :], out, off),
                jnp.where(do, off_len + 1, off_len))

    def del1(off, off_len, dpos, do):
        sel = _pk_range_mask(LP, dpos, fullL)
        out = (_pk_shift_l1(off) & sel) | (off & ~sel)
        out = out & _pk_range_mask(LP, zeros_n, off_len - 1)
        return (jnp.where(do[None, :], out, off),
                jnp.where(do, off_len - 1, off_len))

    if params.div_mut_prob > 0:
        k_dm = jax.random.fold_in(key, 0xD1)
        n_sub = jnp.clip(jax.random.binomial(
            k_dm, jnp.maximum(off_len, 1).astype(jnp.float32),
            params.div_mut_prob), 0, 8).astype(jnp.int32)
        for k in range(8):
            kk = jax.random.fold_in(k_dm, k + 1)
            site = jax.random.randint(kk, (n,), 0, jnp.maximum(off_len, 1))
            rv = random_inst(params, jax.random.fold_in(kk, 3), (n,))
            do = div_m & (k < n_sub) & (off_len > 0)
            off = jnp.where(do[None, :], _pk_set_byte(off, site, rv), off)
    if params.divide_mut_prob > 0:
        mpos = jax.random.randint(k_mpos, (n,), 0, jnp.maximum(off_len, 1))
        do_sub = div_m & (u_mut[:, 0] < params.divide_mut_prob) \
            & (off_len > 0)
        off = jnp.where(do_sub[None, :],
                        _pk_set_byte(off, mpos, r_inst2[:, 0]), off)
    if params.divide_ins_prob > 0:
        ipos = jax.random.randint(k_ipos, (n,), 0,
                                  jnp.maximum(off_len, 1) + 1)
        do_ins = div_m & (u_mut[:, 1] < params.divide_ins_prob) \
            & (off_len + 1 <= max_sz)
        off, off_len = ins1(off, off_len, ipos, r_inst2[:, 1], do_ins)
    if params.divide_del_prob > 0:
        dpos = jax.random.randint(k_dpos, (n,), 0, jnp.maximum(off_len, 1))
        do_del = div_m & (u_mut[:, 2] < params.divide_del_prob) \
            & (off_len - 1 >= params.min_genome_len)
        off, off_len = del1(off, off_len, dpos, do_del)

    KMAX = 4
    if params.copy_ins_prob > 0 or params.copy_del_prob > 0:
        k_ci, k_cd = jax.random.split(jax.random.fold_in(key, 0xC0), 2)
        cl = jnp.maximum(off_len, 1).astype(jnp.float32)
        if params.copy_ins_prob > 0:
            n_ins = jnp.clip(jax.random.binomial(
                k_ci, cl, params.copy_ins_prob), 0, KMAX).astype(jnp.int32)
            for k in range(KMAX):
                kk = jax.random.fold_in(k_ci, k + 1)
                ipos2 = jax.random.randint(kk, (n,), 0,
                                           jnp.maximum(off_len, 1) + 1)
                iv = random_inst(params, jax.random.fold_in(kk, 7), (n,))
                do = div_m & (k < n_ins) & (off_len + 1 <= max_sz)
                off, off_len = ins1(off, off_len, ipos2, iv, do)
        if params.copy_del_prob > 0:
            n_del = jnp.clip(jax.random.binomial(
                k_cd, cl, params.copy_del_prob), 0, KMAX).astype(jnp.int32)
            for k in range(KMAX):
                kk = jax.random.fold_in(k_cd, k + 1)
                dpos2 = jax.random.randint(kk, (n,), 0,
                                           jnp.maximum(off_len, 1))
                do = div_m & (k < n_del) \
                    & (off_len - 1 >= params.min_genome_len)
                off, off_len = del1(off, off_len, dpos2, do)
    return off, off_len


def flush_births_packed(params, st, key, planes, update_no,
                        fresh_mirrors=True):
    """flush_births' torus fast path on resident kernel planes.

    planes = (tape_t, off_t, gen_t, ivec, fvec): the [LP, N] opcode /
    offspring word planes, the genome shadow plane ([LP, N] bytes, or
    [ceil(L/6), N] 5-bit fields under TPU_PACKED_BITS=1 --
    packed_chunk.bits_active) plus the [NI, N] / [NF, N] scalar planes,
    CELL-ordered (identity lane mapping -- packed residency supersedes
    the budget-sort lane permutation; ops/packed_chunk.py).
    `st` is the canonical carrier whose [N, L] planes are stale between
    chunk boundaries; this always updates the per-cell columns the
    boundary unpack cannot rebuild (breed_true / parent_id /
    birth_update / genotype_id / budget_carry / mating_type /
    energy_spent).  `fresh_mirrors=True` (the legacy row-space body, and
    any run with the flight recorder armed) additionally refreshes the
    plane-backed mirrors (alive / merit / gestation_time / generation,
    plus the trace-visible extras under TPU_TRACE) so mid-chunk readers
    see canonical fields; the fused body (ops/packed_chunk.
    fused_active) passes False and lets them go stale until the
    chunk-boundary unpack rebuilds them.

    Returns (planes', st')."""
    from avida_tpu.core.state import make_cell_inputs
    from avida_tpu.ops import pallas_cycles as pc
    tape_t, off_t, gen_t, ivec, fvec = planes
    LP, n = tape_t.shape
    R = params.num_reactions
    NI, LW, IV_COPIED_BM, IV_DYN = pc._layout(params, LP * 4)
    wx, wy = params.world_x, params.world_y
    rows = jnp.arange(n)
    zeros_n = jnp.zeros(n, jnp.int32)

    k_place, k_inputs, k_off, k_sex = jax.random.split(key, 4)
    del k_sex              # asexual only (packed_chunk.active gates)

    flags = ivec[pc.IV_FLAGS]
    alive = (flags & pc.FLAG_ALIVE) != 0
    divide_pending = (flags & pc.FLAG_DIVPEND) != 0
    pending = divide_pending & alive

    off_len0 = ivec[pc.IV_OFF_LEN]
    genome_len = ivec[pc.IV_GENOME_LEN]
    merit = fvec[pc.FV_MERIT]
    off_w, off_len = _pk_extract_offspring(
        params, k_off, off_t, off_len0, genome_len, divide_pending)
    fresh_inputs = make_cell_inputs(k_inputs, n)
    child_merit = merit                       # asexual: parent's merit

    pending, births, parent_idx, won, dir_idx = _fast_torus_placement(
        params, k_place, pending, alive, ivec[pc.IV_TIME_USED], merit)

    # breed-true: wordwise compare of the (mutated) offspring against the
    # parent's birth genome, masked to the offspring's positions.  Under
    # the 5-bit genome codec the offspring plane is bridged into codec
    # layout first (opcodes < 32, so the 5-bit compare decides exactly
    # the byte compare) and that bridged plane doubles as the newborn
    # genome write below.
    from avida_tpu.ops import packed_chunk as pk_chunk
    bits5 = pk_chunk.bits_active(params)
    if bits5:
        off_w5 = _pk_to_plane5(off_w, gen_t.shape[0])
        diff = (off_w5 ^ gen_t) & _pk5_prefix_mask(gen_t.shape[0], off_len)
    else:
        diff = (off_w ^ gen_t) & _pk_range_mask(LP, zeros_n, off_len)
    is_breed_true = (off_len == genome_len) & ~jnp.any(diff != 0, axis=0)

    max_exec = jnp.where(
        params.death_method == 2, params.age_limit * off_len,
        jnp.where(params.death_method == 1, params.age_limit, 2**30))

    offs_all = _OFFS_2D + (((0, 0),) if params.allow_parent else ())

    def by_parent(x):
        """dir_idx-select over the 9 static rolls, for [.., N] arrays --
        the packed counterpart of flush_births' fast-path by_parent."""
        out = jnp.zeros_like(x)
        for k, (dy, dx) in enumerate(offs_all):
            sel = dir_idx == k
            out = jnp.where(sel, _pk_roll2d(x, dy, dx, wx, wy), out)
        return out

    # one batched roll-select for every parent-sourced scalar (the
    # canonical flush gathers these rows by parent index; here they ride
    # two stacked matrices -- ints and floats -- through the same rolls)
    gim_inc = 0 if params.generation_inc_method == 1 else 1
    imat = jnp.stack(
        [off_len, max_exec, ivec[pc.IV_GEST_TIME], ivec[pc.IV_EXEC_SIZE],
         ivec[pc.IV_CHILD_COPIED], ivec[pc.IV_GENERATION] + gim_inc,
         is_breed_true.astype(jnp.int32)]
        + [ivec[IV_DYN + 2 * R + r] for r in range(R)], axis=0)
    fmat = jnp.stack(
        [child_merit, fvec[pc.FV_FITNESS], fvec[pc.FV_LAST_BONUS],
         fvec[pc.FV_LAST_MERIT_BASE]], axis=0)
    mvi = by_parent(imat)
    mvf = by_parent(fmat)
    (mv_len, mv_maxexec, mv_gest, mv_exec, mv_copied, mv_gen,
     mv_breed) = (mvi[k] for k in range(7))
    mv_last_task = mvi[7:]
    mv_merit, mv_fitness, mv_last_bonus, mv_last_mb = (
        mvf[k] for k in range(4))

    mv_plane = by_parent(off_w)               # the one [LP, N] movement

    # ---- newborn scatter: zero-reset rows, then the value rows ----
    b = births
    bi = b[None, :]
    zmask = np.zeros(NI, bool)
    zrows = [pc.IV_ACTIVE_STACK, pc.IV_READ_LABEL_LEN, pc.IV_INPUT_PTR,
             pc.IV_INPUT_BUF_N, pc.IV_OUTPUT_BUF, pc.IV_TIME_USED,
             pc.IV_CPU_CYCLES, pc.IV_GEST_START, pc.IV_CHILD_COPIED,
             pc.IV_NUM_DIVIDES, pc.IV_OFF_START, pc.IV_OFF_LEN,
             pc.IV_OFF_COPIED, pc.IV_INSTS_EXEC, pc.IV_COST_WAIT,
             pc.IV_FT_LO, pc.IV_FT_HI, pc.IV_OFF_SEX]
    zrows += [pc.IV_REGS + k for k in range(3)]
    zrows += [pc.IV_HEADS + k for k in range(4)]
    zrows += [pc.IV_SP + k for k in range(2)]
    zrows += [pc.IV_INPUT_BUF + k for k in range(3)]
    zrows += [pc.IV_READ_LABEL + k for k in range(MAX_LABEL_SIZE)]
    zrows += [pc.IV_STACKS + k for k in range(20)]
    zrows += [pc.IV_EXEC_BM + w for w in range(LW)]
    zrows += [IV_COPIED_BM + w for w in range(LW)]
    zrows += [IV_DYN + r for r in range(R)]            # cur_task
    zrows += [IV_DYN + R + r for r in range(R)]        # cur_reaction
    zmask[zrows] = True
    ivec = jnp.where(jnp.asarray(zmask)[:, None] & bi, 0, ivec)

    def setrow(i, val):
        return ivec.at[i].set(jnp.where(b, val, ivec[i]))

    ivec = setrow(pc.IV_MEM_LEN, mv_len)
    ivec = setrow(pc.IV_GENOME_LEN, mv_len)
    ivec = setrow(pc.IV_COPIED_SIZE, mv_copied)
    ivec = setrow(pc.IV_MAX_EXEC, mv_maxexec)
    ivec = setrow(pc.IV_GEST_TIME, mv_gest)
    ivec = setrow(pc.IV_EXEC_SIZE, mv_exec)
    ivec = setrow(pc.IV_GENERATION, mv_gen)
    for k in range(3):
        ivec = setrow(pc.IV_INPUTS + k, fresh_inputs[:, k])
    for r in range(R):
        ivec = setrow(IV_DYN + 2 * R + r, mv_last_task[r])

    fvec = fvec.at[pc.FV_MERIT].set(jnp.where(b, mv_merit, merit))
    fvec = fvec.at[pc.FV_CUR_BONUS].set(
        jnp.where(b, jnp.float32(params.default_bonus),
                  fvec[pc.FV_CUR_BONUS]))
    fvec = fvec.at[pc.FV_FITNESS].set(
        jnp.where(b, mv_fitness, fvec[pc.FV_FITNESS]))
    fvec = fvec.at[pc.FV_LAST_BONUS].set(
        jnp.where(b, mv_last_bonus, fvec[pc.FV_LAST_BONUS]))
    fvec = fvec.at[pc.FV_LAST_MERIT_BASE].set(
        jnp.where(b, mv_last_mb, fvec[pc.FV_LAST_MERIT_BASE]))

    tape_t = jnp.where(bi, mv_plane, tape_t)
    gen_t = jnp.where(bi, by_parent(off_w5) if bits5 else mv_plane, gen_t)
    off_t = jnp.where(bi, 0, off_t)

    # flags: newborns get ALIVE only; winners/dead parents resume; the
    # kernel-internal NEWDIV bit clears for everyone (the per-update path
    # clears it implicitly at every pack -- resident planes must too, or
    # the next launch would re-extract stale offspring over live tapes)
    flags_b = jnp.where(b, jnp.int32(pc.FLAG_ALIVE), flags)
    alive_post = (flags_b & pc.FLAG_ALIVE) != 0
    divp_b = (flags_b & pc.FLAG_DIVPEND) != 0
    resumes = won | ~alive_post
    cleared = jnp.where(resumes, False, divp_b)
    flags_final = ((flags_b & ~(pc.FLAG_DIVPEND | pc.FLAG_NEWDIV))
                   | jnp.where(cleared, pc.FLAG_DIVPEND, 0))
    ivec = ivec.at[pc.IV_FLAGS].set(flags_final)
    off_sex_b = jnp.where(b, 0, ivec[pc.IV_OFF_SEX])
    ivec = ivec.at[pc.IV_OFF_SEX].set(
        jnp.where(cleared, off_sex_b, 0))

    # canonical per-cell columns the packed chunk keeps FRESH on `st`:
    # always the ones the chunk-boundary unpack cannot rebuild; the
    # plane-backed mirrors only when a mid-chunk reader needs them
    # (fresh_mirrors -- see the docstring)
    upd = dict(
        breed_true=jnp.where(b, mv_breed != 0, st.breed_true),
        parent_id=jnp.where(b, parent_idx, st.parent_id),
        birth_update=jnp.where(b, jnp.int32(update_no), st.birth_update),
        genotype_id=jnp.where(b, -1, st.genotype_id),
        budget_carry=jnp.where(b, 0, st.budget_carry),
        mating_type=jnp.where(b, -1, st.mating_type),
        energy_spent=jnp.where(b, 0.0, st.energy_spent),
    )
    if fresh_mirrors:
        upd.update(
            alive=alive_post,
            merit=fvec[pc.FV_MERIT],
            gestation_time=ivec[pc.IV_GEST_TIME],
            generation=ivec[pc.IV_GENERATION],
        )
    if int(getattr(params, "trace_cap", 0)):
        # trace emission reads these canonical fields mid-chunk
        # (ops/update.trace_pre_phase / trace_post_phase)
        upd.update(
            mem_len=ivec[pc.IV_MEM_LEN],
            heads=jnp.stack([ivec[pc.IV_HEADS + k] for k in range(4)],
                            axis=1),
            task_exe_total=jnp.stack(
                [ivec[IV_DYN + 3 * R + r] for r in range(R)], axis=1),
        )
    st = st.replace(**upd)
    return (tape_t, off_t, gen_t, ivec, fvec), st


def flush_births_packed_worlds(params, bst, keys, planes, update_no,
                               fresh_mirrors=True):
    """World-blocked packed birth flush for a stacked multi-world chunk
    (ops/packed_chunk.update_step_packed_worlds).

    `planes` carry lanes split per world ([LP, W, N] / [NI, W, N] /
    [NF, W, N]); `bst`/`keys` carry the leading world axis.  The flush
    is the per-world flush vmapped over that axis, which makes the
    world-boundary guarantee STRUCTURAL: every lane-axis roll
    (_pk_roll2d), byte-funnel shift and newborn scatter runs inside one
    world's own [LP, N] block, so a birth landing on the last lane of a
    world can never read or write the next world's first lane
    (tests/test_multiworld.py's boundary cross-talk guard), and each
    world consumes its own flush key exactly as its solo run does.
    `update_no` is scalar or [W] (per-world counters, the dynamic
    serving batch): newborns are stamped with their OWN world's update
    number either way."""
    update_no = jnp.broadcast_to(jnp.asarray(update_no, jnp.int32),
                                 (bst.alive.shape[0],))
    return jax.vmap(
        lambda st, key, pl5, un: flush_births_packed(
            params, st, key, pl5, un, fresh_mirrors=fresh_mirrors),
        in_axes=(0, 0, 1, 0), out_axes=(1, 0),
    )(bst, keys, planes, update_no)


def flush_injections(params, st, key, neighbors):
    """Parasite transmission: each organism with a staged injection
    (inject_pending from Inst_Inject) targets a random neighbor; infection
    succeeds when the target is alive and not already parasitized
    (ParasiteInfectHost, cHardwareTransSMT.cc:375-417: inject fails on an
    occupied memory-space label -- our single parasite slot is the
    equivalent).  Conflicts resolve lowest-injector-wins; a failed
    injection loses the parasite (as in the reference).  The new parasite
    thread starts at (space 2, position 0)."""
    n, L = st.tape.shape
    rows = jnp.arange(n)
    pend = st.inject_pending & st.alive
    choice = jax.random.randint(key, (n,), 0, neighbors.shape[1],
                                dtype=jnp.int32)
    target = neighbors[rows, choice]
    ok = pend & st.alive[target] & ~st.parasite_active[target]

    BIG = jnp.int32(2**30)
    claim = jnp.full(n, BIG, jnp.int32)
    claim = claim.at[jnp.where(ok, target, rows)].min(
        jnp.where(ok, rows, BIG))
    infected = (claim < BIG) & st.alive & ~st.parasite_active
    src = jnp.clip(claim, 0, n - 1)

    st = st.replace(
        pmem=jnp.where(infected[:, None], st.inj_mem[src], st.pmem),
        pmem_len=jnp.where(infected, st.inj_len[src], st.pmem_len),
        parasite_active=st.parasite_active | infected,
        smt_head_pos=st.smt_head_pos.at[:, 1].set(
            jnp.where(infected[:, None], 0, st.smt_head_pos[:, 1])),
        smt_head_space=st.smt_head_space.at[:, 1].set(
            jnp.where(infected[:, None], 2, st.smt_head_space[:, 1])),
        smt_stacks=st.smt_stacks.at[:, 1].set(
            jnp.where(infected[:, None, None], 0, st.smt_stacks[:, 1])),
        smt_sp=st.smt_sp.at[:, 1].set(
            jnp.where(infected[:, None], 0, st.smt_sp[:, 1])),
        # every staged injection is consumed, success or not
        inject_pending=jnp.where(pend, False, st.inject_pending),
    )
    return st


def birth_death_masks(alive_before, st, update_no):
    """Per-cell (born, died) masks for the flight recorder
    (observability/tracer.py; called from ops/update.trace_post_phase).
    born = alive newborns the flush placed this update; died = cells
    alive at the update's start that are now empty OR now hold this
    update's newborn (the occupant was overwritten -- the reference's
    birth-displacement death).  Matches the birth/death accounting the
    telemetry counters and count.dat use (births = post-flush survivors;
    deaths = alive_before + births - alive_after)."""
    born = st.alive & (st.birth_update == update_no)
    died = alive_before & (~st.alive | born)
    return born, died
