"""Demes: group structure, competition, replication, germlines.

TPU-native re-expression of the reference deme machinery (cDeme,
avida-core/source/main/cDeme.h:52; cPopulation::CompeteDemes
cPopulation.cc ~4800, ReplicateDemes / ReplaceDeme; germlines
main/cGermline.h:31).  Demes are CONTIGUOUS cell bands -- deme d owns
cells [d*C, (d+1)*C) with C = num_cells // num_demes -- so every per-deme
reduction is a reshape to [D, C] plus an axis-1 reduction, and deme
replacement is a block gather on the leading axis.  The band layout is
also the shard layout (parallel/mesh.py shards the cell axis in
contiguous bands), so deme boundaries coincide with shard boundaries
whenever num_demes % n_devices == 0: deme-local placement then produces
ZERO cross-device traffic outside migration and compete/replicate events
(SURVEY §2g.4: demes are the natural shard axis).

Organism copies during deme replacement follow the reference's
InjectClone semantics (cPopulation.cc:7377): same genome and merit, fresh
hardware and lifetime state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from avida_tpu.core.state import make_cell_inputs

# ReplicateDemes triggers (cPopulation::ReplicateDemes switch order)
(TRIGGER_ALL, TRIGGER_FULL, TRIGGER_CORNERS, TRIGGER_AGE, TRIGGER_BIRTHS,
 TRIGGER_PREDICATE) = range(6)


def cells_per_deme(params) -> int:
    n, d = params.num_cells, params.num_demes
    if n % d:
        raise ValueError(f"num_cells {n} not divisible by NUM_DEMES {d}")
    return n // d


def deme_of_cells(params):
    """int32[N]: deme index of every cell."""
    return jnp.arange(params.num_cells) // cells_per_deme(params)


def _deme_mean(params, alive, x):
    """Per-deme mean of x over living organisms -> f32[D]."""
    D = params.num_demes
    C = cells_per_deme(params)
    xa = jnp.where(alive, x.astype(jnp.float32), 0.0).reshape(D, C)
    cnt = alive.reshape(D, C).sum(axis=1)
    return xa.sum(axis=1) / jnp.maximum(cnt, 1), cnt


def deme_fitness(params, st, competition_type):
    """f32[D] deme fitness (cPopulation::CompeteDemes switch,
    competition_type 0-6; 3 (mutation-rate) is per-organism div-type and
    degenerates to constant here, 5/6 use the same last-gestation fitness
    as 2/4 -- the repo keeps one fitness notion)."""
    D = params.num_demes
    if competition_type == 0:
        return jnp.ones(D, jnp.float32)
    if competition_type == 1:
        return st.deme_birth_count.astype(jnp.float32)
    if competition_type in (2, 5):
        mean, _ = _deme_mean(params, st.alive, st.fitness)
        return mean
    if competition_type in (4, 6):
        mean, _ = _deme_mean(params, st.alive, st.fitness)
        # rank k (1 = best) -> fitness 2^-k
        rank = 1 + (mean[:, None] < mean[None, :]).sum(axis=1)
        return jnp.exp2(-rank.astype(jnp.float32))
    if competition_type == 3:
        return jnp.ones(D, jnp.float32)
    raise NotImplementedError(f"CompeteDemes competition_type {competition_type}")


def _blockify(params, x):
    """Reshape a per-cell array to [D, C, ...]."""
    D = params.num_demes
    C = cells_per_deme(params)
    return x.reshape((D, C) + x.shape[1:])


def _replace_blocks(params, st, src, replaced, key):
    """Rebuild per-cell state so deme d's block is an InjectClone copy of
    deme src[d]'s block where replaced[d]; untouched demes keep their
    state.  Copies genome + merit; hardware/lifetime state is newborn-
    fresh (InjectClone / SetupClone semantics)."""
    n, L = st.tape.shape
    D = params.num_demes

    def blk_gather(x):
        b = _blockify(params, x)
        g = b[src]
        sel = replaced.reshape((D,) + (1,) * (g.ndim - 1))
        return jnp.where(sel, g, b).reshape(x.shape)

    genome = blk_gather(st.genome)
    genome_len = blk_gather(st.genome_len)
    alive = blk_gather(st.alive)
    merit = blk_gather(st.merit)
    gestation = blk_gather(st.gestation_time)
    fitness = blk_gather(st.fitness)
    generation = blk_gather(st.generation)

    rep_cells = replaced[deme_of_cells(params)]        # bool[N]
    updates = _clone_reset(params, st, rep_cells, genome, genome_len, alive,
                           merit, key)
    # clones inherit the source organisms' last-gestation history
    # (InjectClone -> SetupClone keeps merit/fitness/gestation context)
    for name, val in (("gestation_time", gestation), ("fitness", fitness),
                      ("generation", generation)):
        dst = getattr(st, name)
        updates[name] = jnp.where(rep_cells, val, dst)
    return st.replace(**updates)


# Per-cell fields a freshly (re)seeded organism zeroes.  Keyed off one list
# so deme replacement and germline seeding can't drift apart; new
# PopulationState per-cell fields with newborn-zero semantics go HERE.
_ZERO_FIELDS = [
    "regs", "heads", "stacks", "sp", "active_stack", "read_label",
    "read_label_len", "input_ptr", "input_buf", "input_buf_n",
    "output_buf", "cur_task_count", "cur_reaction_count",
    "last_task_count", "time_used", "cpu_cycles", "gestation_start",
    "child_copied_size", "num_divides", "off_start", "off_len",
    "off_copied_size", "insts_executed", "budget_carry",
    "last_bonus", "last_merit_base",
    # TransSMT hardware state (size-0 axes on heads hardware)
    "smt_aux", "smt_aux_len", "pmem", "pmem_len", "smt_stacks", "smt_sp",
    "gstack", "gsp", "smt_head_pos", "inj_mem", "inj_len",
    "cost_wait", "ft_paid_lo", "ft_paid_hi",
]
_FALSE_FIELDS = ["mal_active", "breed_true", "divide_pending", "off_sex",
                 "parasite_active", "inject_pending", "sterile"]


def _clone_reset(params, st, sel_cells, genome, genome_len, alive, merit,
                 key):
    """Field updates installing `genome`/`merit` at sel_cells with fresh
    hardware + lifetime state (InjectClone / SetupClone semantics,
    cPopulation.cc:7377).  Returns the updates dict for st.replace."""
    n = st.tape.shape[0]
    max_exec = jnp.where(
        params.death_method == 2, params.age_limit * genome_len,
        jnp.where(params.death_method == 1, params.age_limit, 2**30))
    fresh = {
        "genome": genome, "genome_len": genome_len, "alive": alive,
        "merit": merit,
        "tape": (genome.astype(jnp.uint8) & jnp.uint8(0x3F)),
        "mem_len": genome_len,
        "executed_size": genome_len, "copied_size": genome_len,
        "max_executed": max_exec,
    }
    updates = {}
    for name, val in fresh.items():
        dst = getattr(st, name)
        sel = sel_cells.reshape((n,) + (1,) * (dst.ndim - 1))
        updates[name] = jnp.where(sel, val, dst)
    for name in _ZERO_FIELDS:
        dst = getattr(st, name)
        sel = sel_cells.reshape((n,) + (1,) * (dst.ndim - 1))
        updates[name] = jnp.where(sel, jnp.zeros_like(dst), dst)
    for name in _FALSE_FIELDS:
        updates[name] = jnp.where(sel_cells, False, getattr(st, name))
    updates["cur_bonus"] = jnp.where(
        sel_cells, jnp.asarray(params.default_bonus, st.cur_bonus.dtype),
        st.cur_bonus)
    updates["genotype_id"] = jnp.where(sel_cells, -1, st.genotype_id)
    updates["parent_id"] = jnp.where(sel_cells, -1, st.parent_id)
    updates["birth_update"] = jnp.where(sel_cells, -1, st.birth_update)
    updates["inputs"] = jnp.where(sel_cells[:, None],
                                  make_cell_inputs(key, n), st.inputs)
    if params.hw_type in (1, 2):
        base = jnp.asarray([[0, 0, 0, 0], [2, 2, 2, 2]],
                           st.smt_head_space.dtype)
        updates["smt_head_space"] = jnp.where(
            sel_cells[:, None, None], base[None], st.smt_head_space)
    return updates


def compete_demes(params, st, key, competition_type):
    """Fitness-proportional deme selection + wholesale replacement
    (cPopulation::CompeteDemes tail: roulette draw per slot, then copy)."""
    D = params.num_demes
    k_pick, k_inputs = jax.random.split(key)
    fit = deme_fitness(params, st, competition_type)
    total = fit.sum()
    p = jnp.where(total > 0, fit / jnp.maximum(total, 1e-30),
                  jnp.full(D, 1.0 / D))
    src = jax.random.choice(k_pick, D, shape=(D,), p=p)
    replaced = src != jnp.arange(D)
    st = _replace_blocks(params, st, src, replaced, k_inputs)
    # germlines follow their deme (cGermline copied on deme replication)
    if params.demes_use_germline:
        sel = replaced
        st = st.replace(
            germ_mem=jnp.where(sel[:, None], st.germ_mem[src], st.germ_mem),
            germ_len=jnp.where(sel, st.germ_len[src], st.germ_len))
    # all demes reset their counters after competition
    return st.replace(deme_birth_count=jnp.zeros(D, jnp.int32),
                      deme_age=jnp.zeros(D, jnp.int32))


def _mutate_germline(params, germ_mem, germ_len, key):
    """Per-site germline copy mutations (GERMLINE_COPY_MUT,
    ReplaceDeme's germline mutation step)."""
    from avida_tpu.ops.interpreter import random_inst
    D, L = germ_mem.shape
    u = jax.random.uniform(key, (D, L))
    r = random_inst(params, jax.random.fold_in(key, 1),
                    (D, L)).astype(jnp.int8)
    in_g = jnp.arange(L)[None, :] < germ_len[:, None]
    hit = (u < params.germline_copy_mut) & in_g
    return jnp.where(hit, r, germ_mem)


def replicate_demes(params, st, key, rep_trigger, predicates=()):
    """Replicate triggered demes into random target demes
    (cPopulation::ReplicateDemes -> ReplicateDeme -> ReplaceDeme).

    Trigger 0=all non-empty, 1=full, 2=corners occupied, 3=age >=
    DEMES_MAX_AGE, 4=births >= DEMES_MAX_BIRTHS.  Each triggered source
    picks a random other deme; conflicts resolve lowest-source-wins
    (lockstep semantic).  With germlines (DEMES_USE_GERMLINE=1) the
    target is cleared and seeded at its center cell with a mutated copy
    of the source germline, which becomes both demes' new germline;
    without, the target becomes an InjectClone copy of the source.
    Source counters reset either way."""
    D = params.num_demes
    C = cells_per_deme(params)
    k_t, k_m, k_inputs, k_seed = jax.random.split(key, 4)

    occ = st.alive.reshape(D, C)
    cnt = occ.sum(axis=1)
    if rep_trigger == TRIGGER_ALL:
        trig = cnt > 0
    elif rep_trigger == TRIGGER_FULL:
        trig = cnt == C
    elif rep_trigger == TRIGGER_CORNERS:
        trig = occ[:, 0] & occ[:, C - 1]
    elif rep_trigger == TRIGGER_AGE:
        trig = st.deme_age >= params.demes_max_age
    elif rep_trigger == TRIGGER_BIRTHS:
        trig = st.deme_birth_count >= params.demes_max_births
    elif rep_trigger == TRIGGER_PREDICATE:
        # DEME_TRIGGER_PREDICATE (cPopulation.cc:3008) over attached
        # cDemeResourceThresholdPredicate conditions (cDemePredicate.h:57:
        # deme resource level vs threshold).  Evaluated at event time
        # against the current level (the reference's sticky
        # previously-satisfied latch collapses to this under per-event
        # evaluation).
        if not predicates:
            raise ValueError(
                "ReplicateDemes sat-deme-predicate needs at least one "
                "Pred_DemeResourceThresholdPredicate event first")
        trig = jnp.zeros(D, bool)
        for res_idx, op, value in predicates:
            lvl = st.deme_resources[:, res_idx]
            if op == ">=":
                trig = trig | (lvl >= value)
            elif op == "<=":
                trig = trig | (lvl <= value)
            else:
                raise ValueError(f"predicate operator {op!r} (>=, <=)")
        trig = trig & (cnt > 0)
    else:
        raise NotImplementedError(f"ReplicateDemes trigger {rep_trigger}")

    # random target != source; lowest triggered source claims a target
    off = jax.random.randint(k_t, (D,), 1, max(D, 2), dtype=jnp.int32)
    tgt = (jnp.arange(D) + off) % D
    BIG = jnp.int32(2**30)
    claim = jnp.full(D, BIG, jnp.int32).at[
        jnp.where(trig, tgt, D)].min(
        jnp.where(trig, jnp.arange(D), BIG), mode="drop")
    replaced = claim < BIG
    src = jnp.clip(claim, 0, D - 1)
    # a source that is itself replaced by a lower-index source this round
    # still counts as having replicated (counters reset below)

    if params.demes_use_germline:
        germ = _mutate_germline(params, st.germ_mem[src], st.germ_len[src],
                                k_m)
        st = _clear_and_seed(params, st, replaced, germ, st.germ_len[src],
                             k_inputs)
        # the mutated germline becomes BOTH demes' germline (ReplaceDeme
        # installs it in source and target)
        src_updated = jnp.zeros(D, bool).at[
            jnp.where(replaced, src, D)].set(True, mode="drop")
        back = jnp.zeros(D, jnp.int32).at[
            jnp.where(replaced, src, D)].set(jnp.arange(D), mode="drop")
        germ_of = jnp.where(replaced[:, None], germ,
                            jnp.where(src_updated[:, None],
                                      germ[back], st.germ_mem))
        len_of = jnp.where(replaced, st.germ_len[src],
                           jnp.where(src_updated, st.germ_len[src][back],
                                     st.germ_len))
        st = st.replace(germ_mem=germ_of, germ_len=len_of)
    else:
        st = _replace_blocks(params, st, src, replaced, k_inputs)

    fired = trig | replaced
    return st.replace(
        deme_birth_count=jnp.where(fired, 0, st.deme_birth_count),
        deme_age=jnp.where(fired, 0, st.deme_age))


def _clear_and_seed(params, st, replaced, seed_mem, seed_len, key):
    """Kill every organism in replaced demes and inject the seed genome at
    each deme's center cell (germline seeding, ReplaceDeme + SeedDeme)."""
    n, L = st.tape.shape
    D = params.num_demes
    C = cells_per_deme(params)
    rep_cells = replaced[deme_of_cells(params)]
    center = (jnp.arange(n) % C) == (C // 2)
    seed_cell = rep_cells & center
    d_of = deme_of_cells(params)
    seed_genome = seed_mem[d_of]            # [N, L] (selects its deme's seed)
    seed_length = seed_len[d_of]

    # seed genome/merit live only at the center cell; every other cell in
    # the band is cleared (alive=False makes the rest of its fresh state
    # irrelevant); germline seeds also zero gestation history
    updates = _clone_reset(params, st, rep_cells, seed_genome, seed_length,
                           seed_cell, seed_length.astype(st.merit.dtype),
                           key)
    for name in ("gestation_time", "fitness", "generation"):
        dst = getattr(st, name)
        updates[name] = jnp.where(rep_cells, jnp.zeros_like(dst), dst)
    return st.replace(**updates)
