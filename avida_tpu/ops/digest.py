"""Device-side state digest: the jitted half of the integrity plane.

`state_digest(st)` reduces an entire PopulationState to ONE u32 on
device -- an order-stable tree digest (position-salted u32 mix-and-fold
per leaf, sorted-name combine across leaves) that agrees bit-for-bit
with the numpy reference in utils/integrity.py.  World.run computes it
at update-chunk boundaries when TPU_STATE_DIGEST / TPU_SCRUB_EVERY are
armed; the value lands in the checkpoint manifest (`state_digest`), the
metrics.prom heartbeat (`avida_state_digest`) and a per-chunk
{"record": "integrity"} runlog line, and the sampled shadow
re-execution (scrubbing) compares live vs replayed digests to catch
silent data corruption (README "Integrity plane").

Isolation rule (the audit_state precedent, utils/audit.py): this is a
SEPARATE jit from ops/update.update_step.  With the integrity plane off
nothing here is ever traced, and with it on the update program itself is
still byte-identical -- scripts/check_jaxpr.py's digest is unchanged
either way (gated in tests/test_integrity.py).  The digest program
donates nothing: digesting a state leaves it usable.

Why the digest can be trusted across engines: the XLA, per-update
Pallas and packed-resident paths produce bit-identical states (the
repo's standing equivalence proofs), and the digest is a pure function
of state bytes -- so one digest spelling serves every path, and a
mismatch between a live chunk and its deterministic replay is evidence
of corruption, never of engine choice.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from avida_tpu.core.state import state_field_names
from avida_tpu.utils.integrity import (C_FOLD, C_IDX, C_MIX, FNV_OFFSET,
                                       FNV_PRIME, name_salt)


def _leaf_words(x: jax.Array) -> jax.Array:
    """u32 word stream of one leaf -- the traced mirror of
    utils/integrity.leaf_words (bools as 0/1, one-byte dtypes
    zero-extended, four-byte dtypes bit-cast; row-major order)."""
    if x.dtype == jnp.bool_:
        return x.astype(jnp.uint32).reshape(-1)
    if x.dtype.itemsize == 1:
        return jax.lax.bitcast_convert_type(
            x, jnp.uint8).astype(jnp.uint32).reshape(-1)
    if x.dtype.itemsize == 4:
        return jax.lax.bitcast_convert_type(x, jnp.uint32).reshape(-1)
    raise ValueError(
        f"state digest supports 1- and 4-byte leaves only (got {x.dtype})")


def _fold_words(w: jax.Array) -> jax.Array:
    """u32[n] -> u32 scalar; mirror of utils/integrity.fold_words."""
    n = w.shape[0]
    if n:
        idx = jax.lax.iota(jnp.uint32, n)
        h = (w ^ (idx * jnp.uint32(C_IDX))) * jnp.uint32(C_MIX)
        h = h ^ (h >> jnp.uint32(15))
        x = jax.lax.reduce(h, jnp.uint32(0),
                           lambda a, b: jax.lax.bitwise_xor(a, b), (0,))
    else:
        x = jnp.uint32(0)
    d = (x ^ jnp.uint32((n * C_IDX) & 0xFFFFFFFF)) * jnp.uint32(C_FOLD)
    return d ^ (d >> jnp.uint32(13))


def _digest_impl(st) -> jax.Array:
    """The full tree digest (u32 scalar).  Leaves fold in SORTED field
    name order with a per-name crc32 salt -- the combine arithmetic is
    traced on scalars, the salts are trace-time constants, so the
    compiled program is a handful of fused reduces over the state."""
    d = jnp.uint32(FNV_OFFSET)
    for name in sorted(state_field_names()):
        leaf = getattr(st, name)
        if leaf is None:
            continue        # disabled flight-recorder ring: no on-disk
            #                 representation either, so host agrees
        ld = _fold_words(_leaf_words(leaf))
        d = (d ^ (ld ^ jnp.uint32(name_salt(name)))) * jnp.uint32(FNV_PRIME)
        d = d ^ (d >> jnp.uint32(17))
    return d


_jit_solo = None
_jit_batched = None


def state_digest(st) -> jax.Array:
    """u32 device scalar digest of one PopulationState (separate jit;
    nothing donated).  `int(...)` on the result is the host readback --
    defer it one chunk on the hot path (the exporter deferral pattern)
    so digesting never fences the dispatch pipeline."""
    global _jit_solo
    if _jit_solo is None:
        _jit_solo = jax.jit(_digest_impl)
    return _jit_solo(st)


def state_digest_batched(bst) -> jax.Array:
    """u32[W] per-world digests of a world-stacked batch state (the
    MultiWorld/ServeBatch flavor): vmap of the solo digest, so batch
    member w's digest equals the digest its solo run would compute on
    the identical state -- the cross-driver comparison the serve
    rollback relies on."""
    global _jit_batched
    if _jit_batched is None:
        _jit_batched = jax.jit(jax.vmap(_digest_impl))
    return _jit_batched(bst)
