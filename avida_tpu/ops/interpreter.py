"""The SIMD lockstep interpreter: one instruction for every organism at once.

This replaces the reference's per-organism inner hot loop
(cHardwareCPU::SingleProcess, avida-core/source/cpu/cHardwareCPU.cc:908-1060,
and its 563-way function-pointer dispatch at cc:1079) with *instruction-class
batching*: every semantic opcode's effect is computed as masked batched tensor
ops over the whole population, then merged.  There is no per-organism control
flow -- organisms at different opcodes are different lanes of the same tensor
program, which is what makes the design map onto the TPU's vector units and
lets XLA fuse the whole step into a few kernels.

Per-instruction semantics are re-derived from the cited reference
implementations (see avida_tpu/models/heads.py docstrings for the map).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from avida_tpu.models.heads import (
    MOD_HEAD, MOD_LABEL, MOD_NONE, MOD_REG,
    SEM_ADD, SEM_DEC, SEM_GET_HEAD, SEM_H_ALLOC, SEM_H_COPY, SEM_H_DIVIDE,
    SEM_H_SEARCH, SEM_IF_LABEL, SEM_IF_LESS, SEM_IF_N_EQU, SEM_INC, SEM_IO,
    SEM_JMP_HEAD, SEM_MOV_HEAD, SEM_NAND, SEM_POP, SEM_PUSH, SEM_SET_FLOW,
    SEM_SHIFT_L, SEM_SHIFT_R, SEM_SUB, SEM_SWAP, SEM_SWAP_STK,
    HEAD_IP, HEAD_READ, HEAD_WRITE, HEAD_FLOW, MAX_LABEL_SIZE,
)
from avida_tpu.ops import tasks as tasks_ops


def _adjust(pos, mlen):
    """Head adjustment (ref cHeadCPU::fullAdjust, cHeadCPU.cc:28): negative
    positions clamp to 0, positions beyond memory wrap modulo memory size."""
    mlen = jnp.maximum(mlen, 1)
    return jnp.where(pos < 0, 0, pos % mlen)


def micro_step(params, st, key, exec_mask):
    """Execute one CPU cycle for every organism where exec_mask is set.

    Equivalent to one pass of the reference hot loop (Avida2Driver.cc:111-116)
    over every scheduled organism simultaneously.  Returns the new state.
    """
    n, L = st.mem.shape
    rows = jnp.arange(n)
    cols = jnp.arange(L)

    # instruction-set tables (trace-time constants)
    sem_t = jnp.asarray(params.sem, jnp.int32)
    mod_kind_t = jnp.asarray(params.mod_kind, jnp.int32)
    default_op_t = jnp.asarray(params.default_op, jnp.int32)
    is_nop_t = jnp.asarray(params.is_nop, bool)
    nop_mod_t = jnp.asarray(params.nop_mod, jnp.int32)
    num_insts = params.num_insts

    mlen = jnp.maximum(st.mem_len, 1)
    ip = _adjust(st.heads[:, HEAD_IP], mlen)
    cur_op = st.mem[rows, ip].astype(jnp.int32)
    cur_op = jnp.clip(cur_op, 0, num_insts - 1)
    sem = jnp.where(exec_mask, sem_t[cur_op], -1)

    def is_op(s):
        return sem == s

    # ---- operand resolution (FindModifiedRegister/Head, cc:1622,1663) ----
    next_pos = _adjust(ip + 1, mlen)
    next_op = jnp.clip(st.mem[rows, next_pos].astype(jnp.int32), 0, num_insts - 1)
    next_is_nop = is_nop_t[next_op]
    mod_kind = jnp.where(exec_mask, mod_kind_t[cur_op], MOD_NONE)
    wants_mod = (mod_kind == MOD_REG) | (mod_kind == MOD_HEAD)
    has_mod = wants_mod & next_is_nop
    operand = jnp.where(has_mod, nop_mod_t[next_op], default_op_t[cur_op])
    consumed = has_mod.astype(jnp.int32)

    # ---- label read (ReadLabel, cc:1484: nop run after IP, max 10) ----
    has_label = mod_kind == MOD_LABEL
    loff = jnp.arange(MAX_LABEL_SIZE, dtype=jnp.int32)
    lab_pos = _adjust(ip[:, None] + 1 + loff[None, :], mlen[:, None])  # [N,10]
    lab_ops = jnp.clip(st.mem[rows[:, None], lab_pos].astype(jnp.int32),
                       0, num_insts - 1)
    lab_isnop = is_nop_t[lab_ops]
    lab_run = jnp.cumprod(lab_isnop.astype(jnp.int32), axis=1)
    label_len = jnp.where(has_label, lab_run.sum(axis=1), 0)
    label = nop_mod_t[lab_ops]                                          # [N,10]
    consumed = jnp.where(has_label, label_len, consumed)

    # ---- executed flags (SetFlagExecuted in SingleProcess + helpers) ----
    flag_exec = st.flag_exec
    flag_exec = flag_exec.at[rows, ip].set(flag_exec[rows, ip] | exec_mask)
    nop_exec = has_mod  # the consumed modifier nop is marked executed
    flag_exec = flag_exec.at[rows, next_pos].set(flag_exec[rows, next_pos] | nop_exec)
    # first label nop marked (MAX_LABEL_EXE_SIZE=1, cAvidaConfig default)
    lab0 = lab_pos[:, 0]
    lab0_exec = has_label & (label_len > 0)
    flag_exec = flag_exec.at[rows, lab0].set(flag_exec[rows, lab0] | lab0_exec)

    # ---- register reads (pre-update values) ----
    regs0 = st.regs
    val = regs0[rows, operand]          # ?reg? for MOD_REG ops
    next_reg = (operand + 1) % 3
    val2 = regs0[rows, next_reg]
    bx = regs0[:, 1]
    cx = regs0[:, 2]

    # ---- PRNG draws for this step ----
    k_mut, k_in1, k_ins, k_del, k_mpos, k_ipos, k_dpos, k_iinst = \
        jax.random.split(key, 8)
    u_copy_mut = jax.random.uniform(k_mut, (n,))
    rand_inst = jax.random.randint(k_in1, (n,), 0, num_insts, dtype=jnp.int32)

    # ---- stacks (cCPUStack.h:59-77: push decrements sp, pop reads+zeros) ----
    a = st.active_stack
    spa = st.sp[rows, a]
    push_m = is_op(SEM_PUSH)
    pop_m = is_op(SEM_POP)
    sp_push = (spa + 9) % 10
    pop_val = st.stacks[rows, a, spa]
    stacks = st.stacks
    stacks = stacks.at[rows, a, sp_push].set(
        jnp.where(push_m, val, stacks[rows, a, sp_push]))
    stacks = stacks.at[rows, a, spa].set(
        jnp.where(pop_m, 0, stacks[rows, a, spa]))
    new_spa = jnp.where(push_m, sp_push, jnp.where(pop_m, (spa + 1) % 10, spa))
    sp = st.sp.at[rows, a].set(new_spa)
    active_stack = jnp.where(is_op(SEM_SWAP_STK), 1 - a, a)

    # ---- h-search (cc:7245: complement label, find-forward from origin) ----
    lbl_c = (label + 1) % 3             # complement rotation (Rotate(1,3))
    srch = is_op(SEM_H_SEARCH)
    # match[o, q] = complement label occurs at memory offset q
    match = jnp.ones((n, L), bool)
    for k in range(MAX_LABEL_SIZE):
        pk = jnp.minimum(cols[None, :] + k, L - 1)
        opk = jnp.clip(st.mem[rows[:, None], pk].astype(jnp.int32), 0, num_insts - 1)
        mk = is_nop_t[opk] & (nop_mod_t[opk] == lbl_c[:, k:k + 1])
        match = match & jnp.where(k < label_len[:, None], mk, True)
    match = match & ((cols[None, :] + label_len[:, None]) <= mlen[:, None])
    match = match & (label_len[:, None] > 0)
    found = match.any(axis=1)
    q_found = jnp.argmax(match, axis=1)
    ip_after_label = _adjust(ip + label_len, mlen)   # IP sits on last label nop
    search_head = jnp.where(found, q_found + label_len - 1, ip_after_label)
    search_bx = search_head - ip_after_label
    search_cx = label_len
    new_flow_srch = _adjust(search_head + 1, mlen)

    # ---- if-label (cc:6914: complement label vs recently-copied label) ----
    rl_match = (st.read_label_len == label_len)
    for k in range(MAX_LABEL_SIZE):
        rl_match = rl_match & jnp.where(
            k < label_len,
            st.read_label[:, k].astype(jnp.int32) == lbl_c[:, k], True)

    # ---- conditionals: extra IP advance when condition fails ----
    skip = jnp.zeros(n, bool)
    skip = jnp.where(is_op(SEM_IF_N_EQU), val == val2, skip)
    skip = jnp.where(is_op(SEM_IF_LESS), val >= val2, skip)
    skip = jnp.where(is_op(SEM_IF_LABEL), ~rl_match, skip)

    # ---- h-alloc (Inst_MaxAlloc cc:3294 + Allocate_Main cc:1707) ----
    alloc_m0 = is_op(SEM_H_ALLOC)
    old_len = mlen
    alloc_size = jnp.minimum(
        (params.offspring_size_range * old_len.astype(jnp.float32)).astype(jnp.int32),
        L - old_len)
    alloc_ok = (alloc_size >= 1)
    if params.require_allocate:
        alloc_ok = alloc_ok & ~st.mal_active
    alloc_ok = alloc_ok & (old_len <= (alloc_size.astype(jnp.float32)
                                       * params.offspring_size_range).astype(jnp.int32))
    alloc_m = alloc_m0 & alloc_ok
    new_len_alloc = old_len + alloc_size
    # ALLOC_METHOD 0: fill with default instruction (op 0)
    fill_zone = (cols[None, :] >= old_len[:, None]) & (cols[None, :] < new_len_alloc[:, None])
    mem = jnp.where((alloc_m[:, None] & fill_zone), jnp.int8(0), st.mem)
    mem_len = jnp.where(alloc_m, new_len_alloc, st.mem_len)
    mal_active = st.mal_active | alloc_m

    # ---- h-copy (cc:7130: read->write with copy mutation, advance both) ----
    copy_m = is_op(SEM_H_COPY)
    rp = _adjust(st.heads[:, HEAD_READ], mlen)
    wp = _adjust(st.heads[:, HEAD_WRITE], mlen)
    read_inst = jnp.clip(mem[rows, rp].astype(jnp.int32), 0, num_insts - 1)
    do_mut = copy_m & (u_copy_mut < params.copy_mut_prob)
    written = jnp.where(do_mut, rand_inst, read_inst)
    mem = mem.at[rows, wp].set(
        jnp.where(copy_m, written.astype(jnp.int8), mem[rows, wp]))
    flag_copied = st.flag_copied
    flag_copied = flag_copied.at[rows, wp].set(flag_copied[rows, wp] | copy_m)
    # read-label tracking uses the PRE-mutation instruction (ReadInst cc:1459)
    ri_nop = is_nop_t[read_inst] & copy_m
    ri_clear = (~is_nop_t[read_inst]) & copy_m
    rl_len = st.read_label_len
    can_append = ri_nop & (rl_len < MAX_LABEL_SIZE)
    read_label = st.read_label.at[rows, jnp.clip(rl_len, 0, MAX_LABEL_SIZE - 1)].set(
        jnp.where(can_append, nop_mod_t[read_inst].astype(jnp.int8),
                  st.read_label[rows, jnp.clip(rl_len, 0, MAX_LABEL_SIZE - 1)]))
    read_label_len = jnp.where(ri_clear, 0,
                               jnp.where(can_append, rl_len + 1, rl_len))

    # ---- h-divide (Inst_HeadDivide cc:6961 -> Divide_Main cc:1775) ----
    div_try = is_op(SEM_H_DIVIDE)
    div_point = rp
    child_end = jnp.where(wp == 0, mlen, wp)
    child_size = child_end - div_point
    parent_size = div_point
    gsize = st.genome_len
    fsize = gsize.astype(jnp.float32)
    min_sz = jnp.maximum(params.min_genome_len,
                         (fsize / params.offspring_size_range).astype(jnp.int32))
    max_sz = jnp.minimum(L, (fsize * params.offspring_size_range).astype(jnp.int32))
    exec_count = (flag_exec & (cols[None, :] < parent_size[:, None])).sum(axis=1)
    copy_zone = ((cols[None, :] >= parent_size[:, None]) &
                 (cols[None, :] < (parent_size + child_size)[:, None]))
    copied_count = (flag_copied & copy_zone).sum(axis=1)
    viable = ((child_size >= min_sz) & (child_size <= max_sz) &
              (parent_size >= min_sz) & (parent_size <= max_sz) &
              (exec_count >= (parent_size.astype(jnp.float32)
                              * params.min_exe_lines).astype(jnp.int32)) &
              (copied_count >= (child_size.astype(jnp.float32)
                                * params.min_copied_lines).astype(jnp.int32)) &
              ~st.divide_pending)   # lockstep: one pending birth per organism
    div_m = div_try & viable

    # offspring genome extraction: off[q] = mem[div_point + q], q < child_size
    src = jnp.minimum(div_point[:, None] + cols[None, :], L - 1)
    off_raw = mem[rows[:, None], src]
    off_mask = cols[None, :] < child_size[:, None]
    off = jnp.where(off_mask, off_raw, jnp.int8(0))
    off_len = child_size

    # divide mutations (Divide_DoMutations, cHardwareBase.cc:296: point sub,
    # then single insertion, then single deletion; stock rates 0/0.05/0.05)
    u_mut = jax.random.uniform(k_ins, (n, 3))
    r_inst2 = jax.random.randint(k_iinst, (n, 2), 0, num_insts, dtype=jnp.int32)
    # point substitution
    if params.divide_mut_prob > 0:
        mpos = jax.random.randint(k_mpos, (n,), 0, jnp.maximum(off_len, 1))
        do_sub = div_m & (u_mut[:, 0] < params.divide_mut_prob) & (off_len > 0)
        off = off.at[rows, jnp.clip(mpos, 0, L - 1)].set(
            jnp.where(do_sub, r_inst2[:, 0].astype(jnp.int8),
                      off[rows, jnp.clip(mpos, 0, L - 1)]))
    # single insertion
    if params.divide_ins_prob > 0:
        ipos = jax.random.randint(k_ipos, (n,), 0, jnp.maximum(off_len, 1) + 1)
        do_ins = div_m & (u_mut[:, 1] < params.divide_ins_prob) & (off_len + 1 <= max_sz)
        shifted = jnp.where(cols[None, :] > ipos[:, None],
                            off[rows[:, None], jnp.maximum(cols[None, :] - 1, 0)],
                            off)
        inserted = shifted.at[rows, jnp.clip(ipos, 0, L - 1)].set(
            r_inst2[:, 1].astype(jnp.int8))
        off = jnp.where(do_ins[:, None], inserted, off)
        off_len = jnp.where(do_ins, off_len + 1, off_len)
    # single deletion
    if params.divide_del_prob > 0:
        dpos = jax.random.randint(k_dpos, (n,), 0, jnp.maximum(off_len, 1))
        do_del = div_m & (u_mut[:, 2] < params.divide_del_prob) & (off_len - 1 >= params.min_genome_len)
        deleted = jnp.where(cols[None, :] >= dpos[:, None],
                            off[rows[:, None], jnp.minimum(cols[None, :] + 1, L - 1)],
                            off)
        deleted = jnp.where(cols[None, :] >= (off_len - 1)[:, None], jnp.int8(0), deleted)
        off = jnp.where(do_del[:, None], deleted, off)
        off_len = jnp.where(do_del, off_len - 1, off_len)

    # ---- IO + task evaluation (Inst_TaskIO cc:4188; SURVEY §3.4) ----
    io_m = is_op(SEM_IO)
    env_tables = tasks_ops.env_tables_to_device(params)
    logic_id = tasks_ops.compute_logic_id(st.input_buf, st.input_buf_n, val)
    new_bonus, new_tc, new_rc, _ = tasks_ops.apply_reactions(
        env_tables, io_m, logic_id, st.cur_bonus,
        st.cur_task_count, st.cur_reaction_count)
    value_in = st.inputs[rows, st.input_ptr % 3]
    input_ptr = jnp.where(io_m, st.input_ptr + 1, st.input_ptr)
    input_buf = jnp.where(io_m[:, None],
                          jnp.stack([value_in, st.input_buf[:, 0],
                                     st.input_buf[:, 1]], axis=1),
                          st.input_buf)
    input_buf_n = jnp.where(io_m, jnp.minimum(st.input_buf_n + 1, 3),
                            st.input_buf_n)
    output_buf = jnp.where(io_m, val, st.output_buf)
    cur_bonus = jnp.where(io_m, new_bonus, st.cur_bonus)
    cur_task_count = jnp.where(io_m[:, None], new_tc, st.cur_task_count)
    cur_reaction_count = jnp.where(io_m[:, None], new_rc, st.cur_reaction_count)

    # ---- register writes ----
    res = val
    wrote = jnp.zeros(n, bool)
    for s, v in ((SEM_SHIFT_R, val >> 1), (SEM_SHIFT_L, val << 1),
                 (SEM_INC, val + 1), (SEM_DEC, val - 1),
                 (SEM_ADD, bx + cx), (SEM_SUB, bx - cx),
                 (SEM_NAND, ~(bx & cx)), (SEM_POP, pop_val),
                 (SEM_IO, value_in), (SEM_SWAP, val2)):
        res = jnp.where(is_op(s), v, res)
        wrote = wrote | is_op(s)

    def setreg(regs, idx, v, m):
        return regs.at[rows, idx].set(jnp.where(m, v, regs[rows, idx]))

    regs = setreg(regs0, operand, res, wrote)
    regs = setreg(regs, next_reg, val, is_op(SEM_SWAP))
    # get-head: CX <- pos of ?head? (cc:6907).  When the selected head is IP
    # itself, its position reflects the consumed modifier nop (FindModifiedHead
    # advances IP onto the nop before the head is read).
    hsel0 = jnp.where(mod_kind == MOD_HEAD, operand, HEAD_IP)
    eff_head_pos = jnp.where(hsel0 == HEAD_IP,
                             _adjust(ip + consumed, mlen),
                             _adjust(st.heads[rows, hsel0], mlen))
    regs = setreg(regs, 2, eff_head_pos, is_op(SEM_GET_HEAD))
    regs = setreg(regs, 0, old_len, alloc_m)            # h-alloc: AX <- old size
    regs = setreg(regs, 1, search_bx, srch)             # h-search: BX dist
    regs = setreg(regs, 2, search_cx, srch)             # h-search: CX size
    # divide (DIVIDE_METHOD 1): hardware reset -> registers cleared
    regs = jnp.where(div_m[:, None], 0, regs)

    # ---- head writes ----
    heads = st.heads
    mov_m = is_op(SEM_MOV_HEAD)
    jmp_m = is_op(SEM_JMP_HEAD)
    hsel = hsel0
    hpos = eff_head_pos
    flow0 = _adjust(heads[:, HEAD_FLOW], mlen)
    new_hpos = jnp.where(mov_m, flow0, _adjust(hpos + cx, mlen))
    heads = heads.at[rows, hsel].set(
        jnp.where(mov_m | jmp_m, new_hpos, heads[rows, hsel]))
    setflow_m = is_op(SEM_SET_FLOW)
    heads = heads.at[:, HEAD_FLOW].set(
        jnp.where(setflow_m, _adjust(val, mlen),
                  jnp.where(srch, new_flow_srch, heads[:, HEAD_FLOW])))
    # h-copy advances READ/WRITE (with eager wrap, cHeadCPU.h:78)
    heads = heads.at[:, HEAD_READ].set(
        jnp.where(copy_m, _adjust(rp + 1, mlen), heads[:, HEAD_READ]))
    heads = heads.at[:, HEAD_WRITE].set(
        jnp.where(copy_m, _adjust(wp + 1, mlen), heads[:, HEAD_WRITE]))

    # ---- IP advance ----
    # mov-head targeting IP suppresses the end-of-cycle advance (cc:6809);
    # a successful divide resets the CPU (DIVIDE_METHOD 1 -> IP=0).
    mov_ip = mov_m & (hsel == HEAD_IP)
    jmp_ip = jmp_m & (hsel == HEAD_IP)
    ip_seq = _adjust(ip + consumed + skip.astype(jnp.int32) + 1, mlen)
    # jmp-head on IP: jump from the post-modifier position, then advance
    jmp_tgt = _adjust(_adjust(ip + consumed + cx, mlen) + 1, mlen)
    ip_new = jnp.where(jmp_ip, jmp_tgt, ip_seq)
    ip_new = jnp.where(mov_ip, flow0, ip_new)
    ip_new = jnp.where(div_m, 0, ip_new)
    ip_new = jnp.where(exec_mask, ip_new, st.heads[:, HEAD_IP])
    heads = heads.at[:, HEAD_IP].set(ip_new)

    # ---- divide: parent reset + pending offspring ----
    mem_len = jnp.where(div_m, div_point, mem_len)
    flag_exec = jnp.where(div_m[:, None], False, flag_exec)
    flag_copied = jnp.where(div_m[:, None], False, flag_copied)
    heads = jnp.where(div_m[:, None], 0, heads)
    stacks = jnp.where(div_m[:, None, None], 0, stacks)
    sp = jnp.where(div_m[:, None], 0, sp)
    active_stack = jnp.where(div_m, 0, active_stack)
    read_label_len = jnp.where(div_m, 0, read_label_len)
    mal_active = jnp.where(div_m, False, mal_active)

    # phenotype DivideReset (cPhenotype.cc:824): merit from size & bonus
    merit_base = _calc_size_merit(params, gsize, st.copied_size, exec_count)
    fdt = st.merit.dtype
    new_merit = merit_base.astype(fdt) * cur_bonus if params.inherit_merit \
        else merit_base.astype(fdt)
    gestation = st.time_used + 1 - st.gestation_start  # +1: this cycle counts
    new_fitness = new_merit / jnp.maximum(gestation, 1).astype(fdt)

    merit = jnp.where(div_m, new_merit, st.merit)
    fitness = jnp.where(div_m, new_fitness, st.fitness)
    gestation_time = jnp.where(div_m, gestation, st.gestation_time)
    last_bonus = jnp.where(div_m, cur_bonus, st.last_bonus)
    last_merit_base = jnp.where(div_m, merit_base.astype(fdt), st.last_merit_base)
    last_task_count = jnp.where(div_m[:, None], cur_task_count, st.last_task_count)
    executed_size = jnp.where(div_m, exec_count, st.executed_size)
    child_copied_size = jnp.where(div_m, copied_count, st.child_copied_size)
    cur_bonus = jnp.where(div_m, params.default_bonus, cur_bonus)
    cur_task_count = jnp.where(div_m[:, None], 0, cur_task_count)
    cur_reaction_count = jnp.where(div_m[:, None], 0, cur_reaction_count)
    generation = jnp.where(div_m, st.generation + 1, st.generation)
    num_divides = jnp.where(div_m, st.num_divides + 1, st.num_divides)

    # ---- time accounting + death (SingleProcess tail, cc:1047-1051) ----
    time_used = st.time_used + exec_mask.astype(jnp.int32)
    cpu_cycles = st.cpu_cycles + exec_mask.astype(jnp.int32)
    gestation_start = jnp.where(div_m, time_used, st.gestation_start)
    died = exec_mask & (st.max_executed > 0) & (time_used >= st.max_executed)
    alive = st.alive & ~died
    insts_executed = st.insts_executed + exec_mask.astype(jnp.int32)

    return st.replace(
        mem=mem, mem_len=mem_len, flag_exec=flag_exec, flag_copied=flag_copied,
        regs=regs, heads=heads, stacks=stacks, sp=sp, active_stack=active_stack,
        read_label=read_label, read_label_len=read_label_len,
        mal_active=mal_active, alive=alive,
        input_ptr=input_ptr, input_buf=input_buf, input_buf_n=input_buf_n,
        output_buf=output_buf,
        merit=merit, cur_bonus=cur_bonus,
        cur_task_count=cur_task_count, cur_reaction_count=cur_reaction_count,
        last_task_count=last_task_count,
        time_used=time_used, cpu_cycles=cpu_cycles,
        gestation_start=gestation_start, gestation_time=gestation_time,
        fitness=fitness, last_bonus=last_bonus, last_merit_base=last_merit_base,
        executed_size=executed_size, child_copied_size=child_copied_size,
        generation=generation, num_divides=num_divides,
        divide_pending=st.divide_pending | div_m,
        off_mem=jnp.where(div_m[:, None], off, st.off_mem),
        off_len=jnp.where(div_m, off_len, st.off_len),
        off_copied_size=jnp.where(div_m, copied_count, st.off_copied_size),
        insts_executed=insts_executed,
    )


def _calc_size_merit(params, genome_len, copied_size, executed_size):
    """cPhenotype::CalcSizeMerit (cPhenotype.cc, BASE_MERIT_METHOD switch)."""
    m = params.base_merit_method
    if m == 0:
        return jnp.full_like(genome_len, params.base_const_merit).astype(jnp.float32)
    if m == 1:
        return copied_size.astype(jnp.float32)
    if m == 2:
        return executed_size.astype(jnp.float32)
    if m == 3:
        return genome_len.astype(jnp.float32)
    if m == 4:
        return jnp.minimum(jnp.minimum(genome_len, copied_size),
                           executed_size).astype(jnp.float32)
    if m == 5:
        least = jnp.minimum(jnp.minimum(genome_len, copied_size), executed_size)
        return jnp.sqrt(least.astype(jnp.float32))
    raise NotImplementedError(f"BASE_MERIT_METHOD {m}")
