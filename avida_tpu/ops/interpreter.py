"""The SIMD lockstep interpreter: one instruction for every organism at once.

This replaces the reference's per-organism inner hot loop
(cHardwareCPU::SingleProcess, avida-core/source/cpu/cHardwareCPU.cc:908-1060,
and its 563-way function-pointer dispatch at cc:1079) with *instruction-class
batching*: every semantic opcode's effect is computed as masked batched tensor
ops over the whole population, then merged.  There is no per-organism control
flow -- organisms at different opcodes are different lanes of the same tensor
program.

TPU kernel design (measured on v5e; see git history for the microbenchmarks):

* **One packed tape.**  Memory opcode + per-site executed/copied flags
  (ref cCPUMemory per-site flags) live in a single uint8 plane:
  bits 0-5 opcode (<=64 instructions), bit 6 executed, bit 7 copied.
  The whole per-step working set is then ~N*L bytes and stays VMEM-resident
  across the update's while_loop instead of round-tripping HBM.
* **No element gathers.**  A per-row `mem[rows, ip]` gather costs ~3x a full
  dense pass on TPU, and 2-D per-row-offset gathers are ~400x.  Every read
  is a masked reduction (`sum(where(cols == pos, tape, 0))`), every write a
  masked select, and label matching uses *static* shifts (pad+slice).
* **Rare-op gating.**  h-search, label reads, divide viability, IO/task
  evaluation and h-alloc each run under `lax.cond` on "any lane wants it
  this cycle", so their full-width passes are skipped on the (common)
  cycles where no organism executes them.
* **Deferred offspring extraction.**  h-divide only records the split point
  (off_start/off_len); the per-row variable shift that materializes the
  offspring genome runs once per update in the birth engine (ops/birth.py)
  as a log2(L)-step barrel shift, not in the per-cycle loop.

Per-instruction semantics are re-derived from the cited reference
implementations (see avida_tpu/models/heads.py docstrings for the map).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from avida_tpu.models.heads import (
    MOD_HEAD, MOD_LABEL, MOD_NONE, MOD_REG,
    SEM_ADD, SEM_DEC, SEM_GET_HEAD, SEM_H_ALLOC, SEM_H_COPY, SEM_H_DIVIDE,
    SEM_H_DIVIDE_SEX,
    SEM_H_SEARCH, SEM_IF_LABEL, SEM_IF_LESS, SEM_IF_N_EQU, SEM_INC, SEM_IO,
    SEM_JMP_HEAD, SEM_MOV_HEAD, SEM_NAND, SEM_POP, SEM_PUSH, SEM_SET_FLOW,
    SEM_SHIFT_L, SEM_SHIFT_R, SEM_SUB, SEM_SWAP, SEM_SWAP_STK,
    SEM_FORK_TH, SEM_KILL_TH, SEM_ID_TH,
    SEM_SET_MATE_MALE, SEM_SET_MATE_FEMALE, SEM_SET_MATE_JUV,
    SEM_IF_MATE_MALE, SEM_IF_MATE_FEMALE,
    HEAD_IP, HEAD_READ, HEAD_WRITE, HEAD_FLOW, MAX_LABEL_SIZE,
)
from avida_tpu.core.state import WORLD_LEVEL_FIELDS as _WORLD_LEVEL_FIELDS
from avida_tpu.ops import tasks as tasks_ops

# packed-tape layout
OP_MASK = jnp.uint8(0x3F)     # bits 0-5: opcode
EXEC_BIT = jnp.uint8(0x40)    # bit 6: executed flag (cCPUMemory FlagExecuted)
COPIED_BIT = jnp.uint8(0x80)  # bit 7: copied flag (FlagCopied)


def pack_tape(ops):
    """Opcode array (int8) -> packed tape (uint8, flags clear)."""
    return ops.astype(jnp.uint8) & OP_MASK


def tape_ops(tape):
    return (tape & OP_MASK).astype(jnp.int32)


def random_inst(params, key, shape):
    """Redundancy-weighted random instruction draw (cInstSet::GetRandomInst,
    cpu/cInstSet.h:52): inverse-CDF over the per-opcode mutation weights.
    Uniform sets short-circuit to randint."""
    cdf = params.mut_cdf
    n_i = params.num_insts
    if not cdf or all(abs(cdf[k] - (k + 1) / n_i) < 1e-12
                      for k in range(n_i)):
        return jax.random.randint(key, shape, 0, n_i, dtype=jnp.int32)
    u = jax.random.uniform(key, shape)
    op = jnp.zeros(shape, jnp.int32)
    for k in range(n_i - 1):
        op = op + (u >= cdf[k]).astype(jnp.int32)
    return op


def _adjust(pos, mlen):
    """Head adjustment (ref cHeadCPU::fullAdjust, cHeadCPU.cc:28): negative
    positions clamp to 0, positions beyond memory wrap modulo memory size."""
    mlen = jnp.maximum(mlen, 1)
    return jnp.where(pos < 0, 0, pos % mlen)


def _shift_left(plane, k):
    """plane[:, i] <- plane[:, i+k], zero-filled at the end (static shift)."""
    if k == 0:
        return plane
    pad = jnp.zeros_like(plane[:, :k])
    return jnp.concatenate([plane[:, k:], pad], axis=1)


def barrel_shift_left(plane, shift, L):
    """Per-row left-rotate-free shift: out[n, q] = plane[n, q + shift[n]]
    (zero beyond the end).  log2(L) static shifts instead of a 2-D gather
    (which is ~400x slower on TPU)."""
    out = plane
    k = 1
    b = 0
    while k < L:
        bit = (shift >> b) & 1
        out = jnp.where((bit == 1)[:, None], _shift_left(out, k), out)
        k <<= 1
        b += 1
    return out


def fetch_opcode(params, st):
    """Opcode under every organism's IP, as micro_step will fetch it (same
    fullAdjust + masked single-site reduction -- no gather).  One [N, L]
    pass; consumers are the telemetry dispatch-mix counter
    (observability/counters.py, threaded through ops/update.interpret_phase)
    and the per-cycle tracer (analyze/trace.py)."""
    n, L = st.tape.shape
    cols = jnp.arange(L)
    mlen = jnp.maximum(st.mem_len, 1)
    ip = _adjust(st.heads[:, HEAD_IP], mlen)
    m_ip = cols[None, :] == ip[:, None]
    op = jnp.sum(jnp.where(m_ip, (st.tape & OP_MASK).astype(jnp.int32), 0),
                 axis=1)
    return jnp.clip(op, 0, params.num_insts - 1)


def micro_step(params, st, key, exec_mask, return_signals=False,
               charge_time=True):
    """Execute one CPU cycle for every organism where exec_mask is set.

    Equivalent to one pass of the reference hot loop (Avida2Driver.cc:111-116)
    over every scheduled organism simultaneously.  Returns the new state.

    For MAX_CPU_THREADS > 1 this is the per-thread core: the threaded
    wrapper (micro_step_threads) feeds it the ACTIVE thread's view of the
    per-thread fields (regs/heads/local stack/read label; st.main_tid
    holds the active thread's id for id-th) and asks for `return_signals`
    -- (new_state, {fork, kill, div, child_ip}) -- to run the slot
    bookkeeping itself.  `charge_time=False` skips the per-cycle
    time_used/cpu_cycles/insts_executed charge (THREAD_SLICING_METHOD 1
    charges once per slice, not per thread; cHardwareCPU.cc:930).
    """
    n, L = st.tape.shape
    cols = jnp.arange(L)
    tape = st.tape

    # instruction-set tables (trace-time constants)
    sem_t = jnp.asarray(params.sem, jnp.int32)
    mod_kind_t = jnp.asarray(params.mod_kind, jnp.int32)
    default_op_t = jnp.asarray(params.default_op, jnp.int32)
    is_nop_t = jnp.asarray(params.is_nop, bool)
    nop_mod_t = jnp.asarray(params.nop_mod, jnp.int32)
    num_insts = params.num_insts

    mlen = jnp.maximum(st.mem_len, 1)
    ip = _adjust(st.heads[:, HEAD_IP], mlen)
    rp = _adjust(st.heads[:, HEAD_READ], mlen)
    wp = _adjust(st.heads[:, HEAD_WRITE], mlen)

    # ================= THE read traversal =================
    # Reductions over [N, L] are the dominant per-cycle cost on TPU.  The
    # three single-site fetches (instruction at IP, at IP+1, at READ) are
    # packed into ONE weighted reduction: each mask contributes the raw
    # packed tape byte into its own 8-bit lane of a single int32
    # (sum(tape32 * w) with w = m_ip + m_ip1<<8 + m_rp<<16; the masks each
    # select exactly one column, so the byte lanes never carry).  The
    # divide-viability flag counts pack into a second reduction, the label
    # window needs two more (30 bits each) -- 4 passes total instead of 6,
    # with no intermediate plane materialization and no [N,L] `%`.
    tape32 = tape.astype(jnp.int32)
    ops_plane = tape32 & 63
    inwin = cols[None, :] < mlen[:, None]
    rel0 = cols[None, :] - (ip + 1)[:, None]
    rel = rel0 + jnp.where(rel0 < 0, mlen[:, None], 0)      # (c - ip - 1) mod mlen
    lab_sh = jnp.where(rel < 5, rel, rel - 5) * 6
    lab_lo_m = inwin & (rel < 5)
    lab_hi_m = inwin & (rel >= 5) & (rel < MAX_LABEL_SIZE)
    m_ip = cols[None, :] == ip[:, None]
    m_ip1 = cols[None, :] == (ip + 1)[:, None]
    m_rp = cols[None, :] == rp[:, None]
    # divide viability zones (pre-step flag state; see adjustment below)
    parent_size = rp
    child_end = jnp.where(wp == 0, mlen, wp)
    child_size = child_end - parent_size
    in_parent = cols[None, :] < parent_size[:, None]
    copy_zone = ((cols[None, :] >= parent_size[:, None]) &
                 (cols[None, :] < child_end[:, None]))

    def msum(mask, plane):
        return jnp.sum(jnp.where(mask, plane, 0), axis=1, dtype=jnp.int32)

    w1 = (m_ip.astype(jnp.int32) + (m_ip1.astype(jnp.int32) << 8)
          + (m_rp.astype(jnp.int32) << 16))
    r1 = jnp.sum(tape32 * w1, axis=1, dtype=jnp.int32)
    flags_exec = (tape32 >> 6) & 1
    flags_copied = tape32 >> 7
    r2 = msum(in_parent, flags_exec) + (msum(copy_zone, flags_copied) << 16)
    lab_lo = msum(lab_lo_m, ops_plane << jnp.minimum(lab_sh, 30))
    lab_hi = msum(lab_hi_m, ops_plane << jnp.minimum(lab_sh, 30))
    s_ip = r1 & 255                 # packed tape byte at IP
    s_ip1 = (r1 >> 8) & 255         # packed tape byte at IP+1 (0 past end)
    s_rp = (r1 >> 16) & 63          # opcode at READ head
    exec_count0 = r2 & 0xFFFF
    copied_count = r2 >> 16
    # ======================================================

    cur_op = jnp.clip(s_ip & 63, 0, num_insts - 1)
    ip_exec_already = ((s_ip >> 6) & 1) != 0

    # ---- instruction cost engine (SingleProcess_PayPreCosts,
    # cHardwareBase.cc:1241): an instruction with cost c consumes c cycles,
    # executing on the last; ft_cost adds a one-time surcharge per opcode
    # per organism.  Zero-cost sets (the default) compile this away. ----
    has_costs = bool(params.inst_cost) or bool(params.inst_ft_cost)
    if has_costs:
        cost_t = jnp.asarray(params.inst_cost or (0,) * num_insts, jnp.int32)
        ftc_t = jnp.asarray(params.inst_ft_cost or (0,) * num_insts,
                            jnp.int32)
        ft_bit = jnp.where(
            cur_op < 32, (st.ft_paid_lo >> jnp.clip(cur_op, 0, 31)) & 1,
            (st.ft_paid_hi >> jnp.clip(cur_op - 32, 0, 31)) & 1)
        # total cycles for this instruction = max(cost, 1) + one-time ft
        # surcharge: cost c alone = c cycles, ft alone = 1 + ft cycles
        total_cost = jnp.maximum(cost_t[cur_op], 1) + \
            jnp.where(ft_bit == 0, ftc_t[cur_op], 0)
        eff_exec = exec_mask & (
            (st.cost_wait == 1) | ((st.cost_wait == 0) & (total_cost <= 1)))
        cost_wait = jnp.where(
            exec_mask,
            jnp.where(st.cost_wait > 0, st.cost_wait - 1,
                      jnp.where(total_cost > 1, total_cost - 1, 0)),
            st.cost_wait)
        # ft surcharge is paid once the instruction actually executes
        pay_ft = eff_exec & (ft_bit == 0)
        ft_paid_lo = jnp.where(pay_ft & (cur_op < 32),
                               st.ft_paid_lo | (1 << jnp.clip(cur_op, 0, 31)),
                               st.ft_paid_lo)
        ft_paid_hi = jnp.where(pay_ft & (cur_op >= 32),
                               st.ft_paid_hi |
                               (1 << jnp.clip(cur_op - 32, 0, 31)),
                               st.ft_paid_hi)
    else:
        eff_exec = exec_mask
        cost_wait = st.cost_wait
        ft_paid_lo, ft_paid_hi = st.ft_paid_lo, st.ft_paid_hi

    # ---- probabilistic execution failure (cHardwareCPU.cc:988-990:
    # the instruction still pays its costs, is flagged executed, and IP
    # advances, but the effect is suppressed; the following nop modifier
    # is NOT consumed -- it executes as a no-op next cycle, matching the
    # reference's per-cycle timing) ----
    if params.inst_prob_fail:
        pf_t = jnp.asarray(params.inst_prob_fail, jnp.float32)
        u_fail = jax.random.uniform(jax.random.fold_in(key, 0xFA11), (n,))
        inst_failed = eff_exec & (u_fail < pf_t[cur_op])
    else:
        inst_failed = jnp.zeros(n, bool)
    sem = jnp.where(eff_exec & ~inst_failed, sem_t[cur_op], -1)

    def is_op(s):
        return sem == s

    # ---- operand resolution (FindModifiedRegister/Head, cc:1622,1663) ----
    next_pos = _adjust(ip + 1, mlen)
    op0 = (tape[:, 0] & OP_MASK).astype(jnp.int32)          # wrap target
    next_op = jnp.where(ip == mlen - 1, op0, s_ip1 & 63)
    next_op = jnp.clip(next_op, 0, num_insts - 1)
    next_is_nop = is_nop_t[next_op]
    mod_kind = jnp.where(exec_mask & ~inst_failed, mod_kind_t[cur_op],
                         MOD_NONE)
    wants_mod = (mod_kind == MOD_REG) | (mod_kind == MOD_HEAD)
    has_mod = wants_mod & next_is_nop
    operand = jnp.where(has_mod, nop_mod_t[next_op], default_op_t[cur_op])
    consumed = has_mod.astype(jnp.int32)

    # ---- label decode (ReadLabel, cc:1484: nop run after IP, max 10) ----
    has_label = mod_kind == MOD_LABEL
    lab_ops = jnp.stack(
        [(lab_lo >> (6 * k)) & 63 for k in range(5)]
        + [(lab_hi >> (6 * k)) & 63 for k in range(5)], axis=1)  # [N,10]
    lab_ops = jnp.clip(lab_ops, 0, num_insts - 1)
    lab_isnop = is_nop_t[lab_ops]
    # genomes shorter than the label window can alias back onto the label
    # instruction itself; a wrapped-past-origin position is not part of a run
    loff = jnp.arange(MAX_LABEL_SIZE, dtype=jnp.int32)
    in_range = (loff[None, :] + 1) <= (mlen - 1)[:, None]
    lab_run = jnp.cumprod((lab_isnop & in_range).astype(jnp.int32), axis=1)
    label_len = jnp.where(has_label, lab_run.sum(axis=1), 0)
    label = nop_mod_t[lab_ops]                              # [N,10]
    consumed = jnp.where(has_label, label_len, consumed)

    # ---- executed flags (SetFlagExecuted in SingleProcess + helpers) ----
    lab0_exec = has_label & (label_len > 0)
    nop_exec = has_mod | lab0_exec  # modifier/first-label nop marked executed
    exec_here = m_ip & eff_exec[:, None]
    exec_next = (cols[None, :] == next_pos[:, None]) & nop_exec[:, None]
    tape = tape | jnp.where(exec_here | exec_next, EXEC_BIT, jnp.uint8(0))

    # ---- register reads (pre-update values) ----
    # NR = 3 for heads, 8 for experimental (cHardwareExperimental.h:66)
    NR = params.num_registers
    regs0 = st.regs
    r_onehot = jnp.arange(NR)[None, :] == operand[:, None]  # [N,NR]
    val = jnp.sum(jnp.where(r_onehot, regs0, 0), axis=1)
    next_reg = (operand + 1) % NR
    r2_onehot = jnp.arange(NR)[None, :] == next_reg[:, None]
    val2 = jnp.sum(jnp.where(r2_onehot, regs0, 0), axis=1)
    bx = regs0[:, 1]
    cx = regs0[:, 2]

    # ---- PRNG draws for this step ----
    k_mut, k_in1 = jax.random.split(key, 2)
    u_copy_mut = jax.random.uniform(k_mut, (n,))
    rand_inst = random_inst(params, k_in1, (n,))

    # ---- stacks (cCPUStack.h:59-77: push decrements sp, pop reads+zeros) ----
    a1 = st.active_stack[:, None] == jnp.arange(2)[None, :]     # [N,2]
    spa = jnp.sum(jnp.where(a1, st.sp, 0), axis=1)
    push_m = is_op(SEM_PUSH)
    pop_m = is_op(SEM_POP)
    sp_push = (spa + 9) % 10
    slot = jnp.arange(10)[None, None, :]
    cur_slot = a1[:, :, None] & (slot == spa[:, None, None])
    push_slot = a1[:, :, None] & (slot == sp_push[:, None, None])
    pop_val = jnp.sum(jnp.where(cur_slot, st.stacks, 0), axis=(1, 2))
    stacks = jnp.where(push_slot & push_m[:, None, None],
                       val[:, None, None], st.stacks)
    stacks = jnp.where(cur_slot & pop_m[:, None, None], 0, stacks)
    new_spa = jnp.where(push_m, sp_push, jnp.where(pop_m, (spa + 1) % 10, spa))
    sp = jnp.where(a1, new_spa[:, None], st.sp)
    active_stack = jnp.where(is_op(SEM_SWAP_STK), 1 - st.active_stack,
                             st.active_stack)

    # ---- h-search (cc:7245: complement label, find-forward from origin) ----
    lbl_c = (label + 1) % params.num_nops   # complement (Rotate(1, #nops))
    srch = is_op(SEM_H_SEARCH)

    def search_block(_):
        # match[o, q] = complement label occurs at memory offset q.
        # Shifted nop planes replace per-row gathers; the loop is bounded by
        # the LONGEST label actually being searched this cycle (labels are
        # 1-3 nops in practice, MAX_LABEL_SIZE=10 is the ceiling), with
        # dynamic slices doing the shifting.
        ops_plane = (tape & OP_MASK).astype(jnp.int32)
        clipped = jnp.clip(ops_plane, 0, num_insts - 1)
        isnop_plane = is_nop_t[clipped]
        nopval_plane = jnp.where(isnop_plane, nop_mod_t[clipped],
                                 jnp.int32(-1))
        nv_pad = jnp.pad(nopval_plane, ((0, 0), (0, MAX_LABEL_SIZE)),
                         constant_values=-2)
        lmax = jnp.max(jnp.where(srch, label_len, 0))

        def body(k, match):
            shifted = jax.lax.dynamic_slice_in_dim(nv_pad, k, L, axis=1)
            want = jax.lax.dynamic_slice_in_dim(lbl_c, k, 1, axis=1)  # [N,1]
            mk = shifted == want
            return match & (mk | (k >= label_len)[:, None])

        match = jax.lax.fori_loop(0, lmax, body, jnp.ones((n, L), bool))
        match = match & ((cols[None, :] + label_len[:, None]) <= mlen[:, None])
        match = match & (label_len[:, None] > 0)
        return match.any(axis=1), jnp.argmax(match, axis=1)

    found, q_found = jax.lax.cond(
        srch.any(), search_block,
        lambda _: (jnp.zeros(n, bool), jnp.zeros(n, jnp.int32)), None)
    ip_after_label = _adjust(ip + label_len, mlen)   # IP sits on last label nop
    search_head = jnp.where(found, q_found + label_len - 1, ip_after_label)
    search_bx = search_head - ip_after_label
    search_cx = label_len
    new_flow_srch = _adjust(search_head + 1, mlen)

    # ---- if-label (cc:6914: complement label vs recently-copied label) ----
    rl_match = (st.read_label_len == label_len)
    for k in range(MAX_LABEL_SIZE):
        rl_match = rl_match & jnp.where(
            k < label_len,
            st.read_label[:, k].astype(jnp.int32) == lbl_c[:, k], True)

    # ---- conditionals: extra IP advance when condition fails ----
    skip = jnp.zeros(n, bool)
    skip = jnp.where(is_op(SEM_IF_N_EQU), val == val2, skip)
    skip = jnp.where(is_op(SEM_IF_LESS), val >= val2, skip)
    skip = jnp.where(is_op(SEM_IF_LABEL), ~rl_match, skip)
    skip = jnp.where(is_op(SEM_IF_MATE_MALE), st.mating_type != 1, skip)
    skip = jnp.where(is_op(SEM_IF_MATE_FEMALE), st.mating_type != 0, skip)
    if params.hw_type == 3:
        from avida_tpu.models.experimental import SEM_IF_EQU_0, SEM_IF_NOT_0
        skip = jnp.where(is_op(SEM_IF_NOT_0), val == 0, skip)
        skip = jnp.where(is_op(SEM_IF_EQU_0), val != 0, skip)

    # ---- h-alloc (Inst_MaxAlloc cc:3294 + Allocate_Main cc:1707) ----
    alloc_m0 = is_op(SEM_H_ALLOC)
    old_len = mlen
    alloc_size = jnp.minimum(
        (params.offspring_size_range * old_len.astype(jnp.float32)).astype(jnp.int32),
        L - old_len)
    alloc_ok = (alloc_size >= 1)
    if params.require_allocate:
        alloc_ok = alloc_ok & ~st.mal_active
    alloc_ok = alloc_ok & (old_len <= (alloc_size.astype(jnp.float32)
                                       * params.offspring_size_range).astype(jnp.int32))
    # an un-flushed offspring lives beyond mem_len; allocating would overwrite
    # it, so the parent stalls until the end-of-update birth flush (documented
    # lockstep semantic; divides are immediately followed by flush in the ref)
    alloc_ok = alloc_ok & ~st.divide_pending
    alloc_m = alloc_m0 & alloc_ok
    new_len_alloc = old_len + alloc_size

    # ALLOC_METHOD 0: fill with default instruction (op 0), flags clear.
    # (Elementwise write; fuses into the single tape-write traversal below.)
    fill_zone = ((cols[None, :] >= old_len[:, None]) &
                 (cols[None, :] < new_len_alloc[:, None]))
    tape = jnp.where(alloc_m[:, None] & fill_zone, jnp.uint8(0), tape)
    mem_len = jnp.where(alloc_m, new_len_alloc, st.mem_len)
    mal_active = st.mal_active | alloc_m

    # ---- h-copy (cc:7130: read->write with copy mutation, advance both) ----
    # (read-head opcode s_rp came from the read traversal; a same-cycle
    # h-alloc never coincides with h-copy on the same lane, so the pre-alloc
    # read is identical)
    copy_m = is_op(SEM_H_COPY)
    read_inst = jnp.clip(s_rp, 0, num_insts - 1)
    do_mut = copy_m & (u_copy_mut < params.copy_mut_prob)
    written = jnp.where(do_mut, rand_inst, read_inst)
    # write sets the copied flag; the executed flag at the site persists
    # (ref cCPUMemory::SetFlagCopied does not clear FlagExecuted)
    packed = written.astype(jnp.uint8) | COPIED_BIT
    w_onehot = (cols[None, :] == wp[:, None]) & copy_m[:, None]
    tape = jnp.where(w_onehot, packed[:, None] | (tape & EXEC_BIT), tape)
    # read-label tracking uses the PRE-mutation instruction (ReadInst cc:1459)
    ri_nop = is_nop_t[read_inst] & copy_m
    ri_clear = (~is_nop_t[read_inst]) & copy_m
    rl_len = st.read_label_len
    can_append = ri_nop & (rl_len < MAX_LABEL_SIZE)
    rl_slot = jnp.arange(MAX_LABEL_SIZE)[None, :] == rl_len[:, None]
    read_label = jnp.where(rl_slot & can_append[:, None],
                           nop_mod_t[read_inst][:, None].astype(jnp.int8),
                           st.read_label)
    read_label_len = jnp.where(ri_clear, 0,
                               jnp.where(can_append, rl_len + 1, rl_len))

    # ---- h-divide (Inst_HeadDivide cc:6961 -> Divide_Main cc:1775);
    # divide-sex (Inst_HeadDivideSex cc:7019) is the same division with the
    # offspring flagged sexual -- it waits for a mate in the birth engine ----
    div_sex_try = is_op(SEM_H_DIVIDE_SEX)
    div_try = is_op(SEM_H_DIVIDE) | div_sex_try
    div_point = rp
    gsize = st.genome_len
    fsize = gsize.astype(jnp.float32)
    min_sz = jnp.maximum(params.min_genome_len,
                         (fsize / params.offspring_size_range).astype(jnp.int32))
    max_sz = jnp.minimum(L, (fsize * params.offspring_size_range).astype(jnp.int32))

    # viability flag counts came from the read traversal (pre-step flags);
    # the reference marks the h-divide site executed before counting, so add
    # it when this cycle's fetch is the first execution of that site
    exec_count = exec_count0 + jnp.where(
        div_try & ~ip_exec_already & (ip < parent_size), 1, 0)
    viable = ((child_size >= min_sz) & (child_size <= max_sz) &
              (parent_size >= min_sz) & (parent_size <= max_sz) &
              (exec_count >= (parent_size.astype(jnp.float32)
                              * params.min_exe_lines).astype(jnp.int32)) &
              (copied_count >= (child_size.astype(jnp.float32)
                                * params.min_copied_lines).astype(jnp.int32)) &
              ~st.divide_pending &  # lockstep: one pending birth per organism
              ~st.sterile)          # STERILIZE_*: divide permanently fails
    div_m = div_try & viable

    # offspring extraction is DEFERRED: record the split; ops/birth.py
    # materializes the genome (barrel shift + divide mutations) at flush
    off_start = jnp.where(div_m, div_point, st.off_start)
    off_len = jnp.where(div_m, child_size, st.off_len)

    # ---- IO + task evaluation (Inst_TaskIO cc:4188; SURVEY §3.4) ----
    io_m = is_op(SEM_IO)
    in_slot = jnp.arange(3)[None, :] == (st.input_ptr % 3)[:, None]
    value_in = jnp.sum(jnp.where(in_slot, st.inputs, 0), axis=1)

    def io_block(_):
        env_tables = tasks_ops.env_tables_to_device(params)
        logic_id = tasks_ops.compute_logic_id(st.input_buf, st.input_buf_n, val)
        return tasks_ops.apply_reactions(
            params, env_tables, io_m, logic_id, st.cur_bonus,
            st.cur_task_count, st.cur_reaction_count,
            st.resources, st.res_grid, st.deme_resources,
            input_buf=st.input_buf, input_buf_n=st.input_buf_n,
            output=val)[:6]

    # Round-6 satellite (ROUND5 item 3): at steady state SOME organism
    # performs IO on nearly every cycle, so the any-lane cond around the
    # task pipeline fired ~always and its branch barrier cost more than
    # the masked row ops it guarded.  For infinite-resource environments
    # (no resource-bound reactions, no by-products, no deme bindings --
    # stock logic-9 qualifies) the pipeline is pure mask algebra whose
    # io_m=False case returns the inputs bit-identically, so it runs
    # unconditionally on TPU backends.  Resource-bound environments keep
    # the cond (their false branch must not touch the pools), and so
    # does the CPU backend: there the branch costs nothing and the
    # pipeline is real scalar work -- measured +20-80% per XLA update on
    # the 1-core test host in the no-IO regime (round-6 A/B), which
    # would blow the tier-1 budget for zero TPU benefit.  The platform
    # probe is the PROCESS default backend, same trace-time routing rule
    # as ops/update.use_pallas_path: valid because nothing in-tree jits
    # micro_step with an explicit backend/device override (don't start
    # -- a CPU-pinned trace inside a TPU process would take the
    # unconditional branch and pay the CPU cost this gate avoids).
    _io_uncond = (all(r < 0 for r in params.proc_res_idx)
                  and all(pi < 0
                          for pi in getattr(params, "proc_product_idx", ()))
                  and params.num_global_res == 0
                  and params.num_spatial_res == 0
                  and params.num_deme_res == 0
                  and jax.devices()[0].platform == "tpu")
    if _io_uncond:
        (new_bonus, new_tc, new_rc, resources, res_grid,
         deme_resources) = io_block(None)
    else:
        (new_bonus, new_tc, new_rc, resources, res_grid,
         deme_resources) = jax.lax.cond(
            io_m.any(), io_block,
            lambda _: (st.cur_bonus, st.cur_task_count,
                       st.cur_reaction_count, st.resources, st.res_grid,
                       st.deme_resources),
            None)
    # lifetime per-cell task executions (tasks_exe.dat source; the delta
    # from cur_task_count is exactly this cycle's performances)
    task_exe_total = st.task_exe_total + (new_tc - st.cur_task_count)
    input_ptr = jnp.where(io_m, st.input_ptr + 1, st.input_ptr)
    input_buf = jnp.where(io_m[:, None],
                          jnp.stack([value_in, st.input_buf[:, 0],
                                     st.input_buf[:, 1]], axis=1),
                          st.input_buf)
    input_buf_n = jnp.where(io_m, jnp.minimum(st.input_buf_n + 1, 3),
                            st.input_buf_n)
    output_buf = jnp.where(io_m, val, st.output_buf)
    cur_bonus = jnp.where(io_m, new_bonus, st.cur_bonus)
    cur_task_count = jnp.where(io_m[:, None], new_tc, st.cur_task_count)
    cur_reaction_count = jnp.where(io_m[:, None], new_rc, st.cur_reaction_count)

    # ---- register writes ----
    res = val
    wrote = jnp.zeros(n, bool)
    a1m, a2m = (val, val2) if params.hw_type == 3 else (bx, cx)
    for s, v in ((SEM_SHIFT_R, val >> 1), (SEM_SHIFT_L, val << 1),
                 (SEM_INC, val + 1), (SEM_DEC, val - 1),
                 (SEM_ADD, a1m + a2m), (SEM_SUB, a1m - a2m),
                 (SEM_NAND, ~(a1m & a2m)), (SEM_POP, pop_val),
                 (SEM_IO, value_in), (SEM_SWAP, val2),
                 (SEM_ID_TH, st.main_tid)):
        res = jnp.where(is_op(s), v, res)
        wrote = wrote | is_op(s)

    def setreg(regs, idx, v, m):
        oh = (jnp.arange(NR)[None, :] == idx[:, None]) & m[:, None]
        return jnp.where(oh, v[:, None], regs)

    def setreg_c(regs, idx, v, m):  # constant register index
        oh = (jnp.arange(NR)[None, :] == idx) & m[:, None]
        return jnp.where(oh, v[:, None], regs)

    regs = setreg(regs0, operand, res, wrote)
    regs = setreg(regs, next_reg, val, is_op(SEM_SWAP))
    # get-head: CX <- pos of ?head? (cc:6907).  When the selected head is IP
    # itself, its position reflects the consumed modifier nop (FindModifiedHead
    # advances IP onto the nop before the head is read).
    hsel0 = jnp.where(mod_kind == MOD_HEAD, operand, HEAD_IP)
    h_onehot = jnp.arange(4)[None, :] == hsel0[:, None]     # [N,4]
    head_sel = jnp.sum(jnp.where(h_onehot, st.heads, 0), axis=1)
    eff_head_pos = jnp.where(hsel0 == HEAD_IP,
                             _adjust(ip + consumed, mlen),
                             _adjust(head_sel, mlen))
    regs = setreg_c(regs, 2, eff_head_pos, is_op(SEM_GET_HEAD))
    regs = setreg_c(regs, 0, old_len, alloc_m)          # h-alloc: AX <- old size
    regs = setreg_c(regs, 1, search_bx, srch)           # h-search: BX dist
    regs = setreg_c(regs, 2, search_cx, srch)           # h-search: CX size
    # divide (DIVIDE_METHOD 1): hardware reset -> registers cleared
    regs = jnp.where(div_m[:, None], 0, regs)
    if params.hw_type == 3:
        (regs, facing, forage_target, move_won, move_tgt,
         atk_ok, atk_tgt) = _exp_spatial(params, st, sem, operand, val,
                                         regs, setreg)
    else:
        facing, forage_target = st.facing, st.forage_target
        move_won = None

    # ---- head writes ----
    heads = st.heads
    mov_m = is_op(SEM_MOV_HEAD)
    jmp_m = is_op(SEM_JMP_HEAD)
    flow0 = _adjust(heads[:, HEAD_FLOW], mlen)
    new_hpos = jnp.where(mov_m, flow0, _adjust(eff_head_pos + cx, mlen))
    mv = (mov_m | jmp_m)
    heads = jnp.where(h_onehot & mv[:, None], new_hpos[:, None], heads)
    setflow_m = is_op(SEM_SET_FLOW)
    new_flow = jnp.where(setflow_m, _adjust(val, mlen),
                         jnp.where(srch, new_flow_srch, heads[:, HEAD_FLOW]))
    heads = heads.at[:, HEAD_FLOW].set(new_flow)
    # h-copy advances READ/WRITE (with eager wrap, cHeadCPU.h:78)
    heads = heads.at[:, HEAD_READ].set(
        jnp.where(copy_m, _adjust(rp + 1, mlen), heads[:, HEAD_READ]))
    heads = heads.at[:, HEAD_WRITE].set(
        jnp.where(copy_m, _adjust(wp + 1, mlen), heads[:, HEAD_WRITE]))

    # ---- IP advance ----
    # mov-head targeting IP suppresses the end-of-cycle advance (cc:6809);
    # a successful divide resets the CPU (DIVIDE_METHOD 1 -> IP=0).
    mov_ip = mov_m & (hsel0 == HEAD_IP)
    jmp_ip = jmp_m & (hsel0 == HEAD_IP)
    fork_try = is_op(SEM_FORK_TH)
    ip_seq = _adjust(ip + consumed + skip.astype(jnp.int32) + 1
                     + fork_try.astype(jnp.int32), mlen)
    # jmp-head on IP: jump from the post-modifier position, then advance
    jmp_tgt = _adjust(_adjust(ip + consumed + cx, mlen) + 1, mlen)
    ip_new = jnp.where(jmp_ip, jmp_tgt, ip_seq)
    ip_new = jnp.where(mov_ip, flow0, ip_new)
    ip_new = jnp.where(div_m, 0, ip_new)
    ip_new = jnp.where(eff_exec, ip_new, st.heads[:, HEAD_IP])
    heads = heads.at[:, HEAD_IP].set(ip_new)

    # ---- divide: parent reset + pending offspring ----
    mem_len = jnp.where(div_m, div_point, mem_len)
    # clear per-site flags on divided rows (offspring opcodes stay in place
    # beyond mem_len until the birth flush extracts them)
    tape = jnp.where(div_m[:, None], tape & OP_MASK, tape)
    heads = jnp.where(div_m[:, None], 0, heads)
    stacks = jnp.where(div_m[:, None, None], 0, stacks)
    sp = jnp.where(div_m[:, None], 0, sp)
    active_stack = jnp.where(div_m, 0, active_stack)
    read_label_len = jnp.where(div_m, 0, read_label_len)
    mal_active = jnp.where(div_m, False, mal_active)
    if has_costs:
        # hardware reset clears pending cost debt; first-time costs reset
        # per gestation (cHardwareTransSMT Divide_Main resets m_inst_ft_cost)
        cost_wait = jnp.where(div_m, 0, cost_wait)
        ft_paid_lo = jnp.where(div_m, 0, ft_paid_lo)
        ft_paid_hi = jnp.where(div_m, 0, ft_paid_hi)

    # energy model: charge the instruction's energy cost
    # (cPhenotype::ReduceEnergy via SingleProcess_PayPreCosts energy branch,
    # cHardwareBase.cc:1241; cPhenotype.cc:1974)
    energy = st.energy
    energy_spent = st.energy_spent
    if params.energy_enabled and params.inst_energy_cost:
        ecost_t = jnp.asarray(params.inst_energy_cost, jnp.float32)
        charge = jnp.where(exec_mask, ecost_t[jnp.clip(cur_op, 0,
                                                       num_insts - 1)], 0.0)
        # only energy actually available is consumed (store floors at 0)
        spent = jnp.minimum(charge, energy)
        energy = energy - spent
        energy_spent = energy_spent + spent

    # phenotype DivideReset (cPhenotype.cc:824): merit from size & bonus
    merit_base = _calc_size_merit(params, gsize, st.copied_size, exec_count)
    fdt = st.merit.dtype
    new_merit = merit_base.astype(fdt) * cur_bonus if params.inherit_merit \
        else merit_base.astype(fdt)
    if params.energy_enabled:
        # merit = ConvertEnergyToMerit(energy) (cPhenotype.cc:2403); the
        # parent->child energy split applies at the birth flush (documented
        # lockstep deviation: the reference splits at ActivateOffspring,
        # which immediately follows divide)
        new_merit = convert_energy_to_merit(params, energy).astype(fdt)
    gestation = st.time_used + 1 - st.gestation_start  # +1: this cycle counts
    new_fitness = new_merit / jnp.maximum(gestation, 1).astype(fdt)

    merit = jnp.where(div_m, new_merit, st.merit)
    fitness = jnp.where(div_m, new_fitness, st.fitness)
    gestation_time = jnp.where(div_m, gestation, st.gestation_time)
    last_bonus = jnp.where(div_m, cur_bonus, st.last_bonus)
    last_merit_base = jnp.where(div_m, merit_base.astype(fdt), st.last_merit_base)
    last_task_count = jnp.where(div_m[:, None], cur_task_count, st.last_task_count)
    executed_size = jnp.where(div_m, exec_count, st.executed_size)
    child_copied_size = jnp.where(div_m, copied_count, st.child_copied_size)
    cur_bonus = jnp.where(div_m, params.default_bonus, cur_bonus)
    cur_task_count = jnp.where(div_m[:, None], 0, cur_task_count)
    cur_reaction_count = jnp.where(div_m[:, None], 0, cur_reaction_count)
    # GENERATION_INC_METHOD 1 (GENERATION_INC_BOTH, default): the parent's
    # generation also increments at divide (cPhenotype::DivideReset
    # cc:1052); method 0 increments only the offspring (ops/birth.py)
    generation = jnp.where(div_m & (params.generation_inc_method == 1),
                           st.generation + 1, st.generation)
    num_divides = jnp.where(div_m, st.num_divides + 1, st.num_divides)

    # ---- time accounting + death (SingleProcess tail, cc:1047-1051) ----
    charge = exec_mask if charge_time else jnp.zeros_like(exec_mask)
    time_used = st.time_used + charge.astype(jnp.int32)
    if params.inst_addl_time_cost:
        # cHardwareCPU.cc:985,1015: IncTimeUsed(addl_time_cost) on top of
        # the regular cycle -- charged even when prob_fail suppressed the
        # effect (the fetch precedes the failure draw)
        atc_t = jnp.asarray(params.inst_addl_time_cost, jnp.int32)
        time_used = time_used + jnp.where(eff_exec, atc_t[cur_op], 0)
    cpu_cycles = st.cpu_cycles + charge.astype(jnp.int32)
    if params.divide_method != 0:
        # DIVIDE_METHOD 1/2 (SPLIT/BIRTH): the parent is "a second child" --
        # its clock fully resets at divide (cPhenotype::DivideReset
        # cc:1037-1039: gestation_start = cpu_cycles = time_used = 0)
        time_used = jnp.where(div_m, 0, time_used)
        cpu_cycles = jnp.where(div_m, 0, cpu_cycles)
        gestation_start = jnp.where(div_m, 0, st.gestation_start)
    else:
        # DIVIDE_METHOD 0: mother untouched; subsequent gestations measure
        # from the divide point (DivideReset cc:853-854)
        gestation_start = jnp.where(div_m, time_used, st.gestation_start)
    # mating-type transitions (Inst_SetMatingType*, cc:10896-10946:
    # male<->female transitions fail; juvenile always settable)
    mating_type = st.mating_type
    mating_type = jnp.where(
        is_op(SEM_SET_MATE_MALE) & (mating_type != 0), 1, mating_type)
    mating_type = jnp.where(
        is_op(SEM_SET_MATE_FEMALE) & (mating_type != 1), 0, mating_type)
    mating_type = jnp.where(is_op(SEM_SET_MATE_JUV), -1, mating_type)

    died = exec_mask & (st.max_executed > 0) & (time_used >= st.max_executed)
    alive = st.alive & ~died
    insts_executed = st.insts_executed + charge.astype(jnp.int32)

    new_st = st.replace(
        tape=tape, mem_len=mem_len,
        regs=regs, heads=heads, stacks=stacks, sp=sp, active_stack=active_stack,
        read_label=read_label, read_label_len=read_label_len,
        mal_active=mal_active, alive=alive,
        input_ptr=input_ptr, input_buf=input_buf, input_buf_n=input_buf_n,
        output_buf=output_buf,
        merit=merit, cur_bonus=cur_bonus,
        cur_task_count=cur_task_count, cur_reaction_count=cur_reaction_count,
        task_exe_total=task_exe_total,
        last_task_count=last_task_count,
        time_used=time_used, cpu_cycles=cpu_cycles,
        gestation_start=gestation_start, gestation_time=gestation_time,
        fitness=fitness, last_bonus=last_bonus, last_merit_base=last_merit_base,
        executed_size=executed_size, child_copied_size=child_copied_size,
        generation=generation, num_divides=num_divides,
        divide_pending=st.divide_pending | div_m,
        off_start=off_start, off_len=off_len,
        off_copied_size=jnp.where(div_m, copied_count, st.off_copied_size),
        off_sex=jnp.where(div_m, div_sex_try, st.off_sex),
        insts_executed=insts_executed,
        cost_wait=cost_wait, ft_paid_lo=ft_paid_lo, ft_paid_hi=ft_paid_hi,
        resources=resources, res_grid=res_grid,
        deme_resources=deme_resources,
        facing=facing, forage_target=forage_target,
        energy=energy, energy_spent=energy_spent,
        mating_type=mating_type,
    )
    if params.hw_type == 3:
        if params.pred_prey_switch >= 0:
            new_st = _apply_attacks(params, new_st, st, atk_ok, atk_tgt)
        new_st = _apply_moves(new_st, move_won, move_tgt)
    if return_signals:
        return new_st, {
            "fork": fork_try, "kill": is_op(SEM_KILL_TH), "div": div_m,
            # the forked thread resumes at fork+1 (parent advanced to
            # fork+2 by ip_seq's extra step)
            "child_ip": _adjust(ip + 1, mlen),
        }
    return new_st


# ring of facing directions, clockwise from north (experimental hardware;
# ref cPopulationCell connection-list rotation order)
_RING = ((-1, 0), (-1, 1), (0, 1), (1, 1), (1, 0), (1, -1), (0, -1), (-1, -1))


def _facing_step(params, rows, facing, dist):
    """Cell `dist` steps from each row's cell in its facing direction.
    Returns (cell_id, valid): torus wraps; bounded grids invalidate rays
    that leave the world."""
    wx, wy = params.world_x, params.world_y
    y0 = rows // wx
    x0 = rows % wx
    dy = jnp.zeros_like(rows)
    dx = jnp.zeros_like(rows)
    for k, (ky, kx) in enumerate(_RING):
        sel = facing == k
        dy = jnp.where(sel, ky, dy)
        dx = jnp.where(sel, kx, dx)
    y = y0 + dy * dist
    x = x0 + dx * dist
    if params.geometry == 2:
        return (y % wy) * wx + (x % wx), jnp.ones_like(rows, bool)
    valid = (y >= 0) & (y < wy) & (x >= 0) & (x < wx)
    return jnp.clip(y, 0, wy - 1) * wx + jnp.clip(x, 0, wx - 1), valid


def _exp_spatial(params, st, sem, operand, val, regs, setreg):
    """Experimental-hardware spatial semantics: rotate-x, rotate-org-id,
    look-ahead, set-forage-target, and move INTENTS (applied after the
    state merge by _apply_moves).

    Re-derived from cHardwareExperimental.cc: Inst_RotateX (cc:3441,
    facing += ?BX? mod 8, result echoed to the register), Inst_RotateOrgID
    (cc:3489, face the neighbor whose org id -- cell index here -- matches
    ?BX?), Inst_Move (cc:3138, step into the faced cell; success flag to
    ?BX?), Inst_SetForageTarget, and GoLook (cc:3895) writing the 8-field
    sensor result into registers ?BX?..?BX?+7.  The sensor subset
    implemented: habitat -2 (organism search) along the facing ray,
    reporting distance / count / id / forage target of the first organism
    seen (cOrgSensor::FindOrg)."""
    from avida_tpu.models.experimental import (
        SEM_LOOK_AHEAD, SEM_MOVE, SEM_ROTATE_ORG_ID, SEM_ROTATE_X,
        SEM_SET_FORAGE, SEM_ZERO)
    n = st.alive.shape[0]
    rows = jnp.arange(n)
    NR = params.num_registers

    def is_op(x):
        return sem == x

    # rotate-x: facing += val (mod 8), register echoes the rotation
    rotx = is_op(SEM_ROTATE_X)
    facing = jnp.where(rotx, (st.facing + val) % 8, st.facing)
    regs = setreg(regs, operand, jnp.where(rotx, val % 8, 0), rotx)

    # rotate-org-id: face the ring direction whose neighbor cell holds the
    # sought organism (org id = cell index)
    rotid = is_op(SEM_ROTATE_ORG_ID)
    for k in range(8):
        nb, valid = _facing_step(params, rows, jnp.full(n, k, jnp.int32),
                                 jnp.ones_like(rows))
        hit = rotid & valid & st.alive[nb] & (nb == val)
        facing = jnp.where(hit, k, facing)

    # set-forage-target
    setft = is_op(SEM_SET_FORAGE)
    forage_target = jnp.where(setft, val, st.forage_target)

    # zero ?BX?
    regs = setreg(regs, operand, jnp.zeros(n, jnp.int32), is_op(SEM_ZERO))

    # look-ahead (gated: the [N, D] ray scan only runs when some lane looks)
    look = is_op(SEM_LOOK_AHEAD)
    D = max(params.world_x, params.world_y)

    def ray(_):
        dists = jnp.arange(1, D + 1)
        cells, valid = jax.vmap(
            lambda d: _facing_step(params, rows, facing, d),
            out_axes=1)(dists)                      # [N, D]
        occ = st.alive[cells] & valid & (cells != rows[:, None])
        found = occ.any(axis=1)
        first = jnp.argmax(occ, axis=1)             # ray index of first org
        tgt_cell = cells[rows, jnp.clip(first, 0, D - 1)]
        dist = jnp.where(found, first + 1, -1)
        return (found, dist, tgt_cell,
                jnp.where(found, st.forage_target[tgt_cell], -9))

    found, dist, tgt_cell, tgt_ft = jax.lax.cond(
        look.any(), ray,
        lambda _: (jnp.zeros(n, bool), jnp.full(n, -1, jnp.int32),
                   rows, jnp.full(n, -9, jnp.int32)), None)
    # GoLook register outputs (reg_defs, cc:3910-3918), organism habitat
    look_out = (jnp.full(n, -2, jnp.int32),                # habitat
                dist,                                      # distance
                jnp.zeros(n, jnp.int32),                   # search_type
                jnp.where(found, tgt_cell, -1),            # id_sought
                found.astype(jnp.int32),                   # count
                jnp.zeros(n, jnp.int32),                   # value
                jnp.full(n, -9, jnp.int32),                # group
                tgt_ft)                                    # ft
    for j, ov in enumerate(look_out):
        regs = setreg(regs, (operand + j) % NR, ov, look)

    # move: intent -> conflict resolution (lowest mover index claims the
    # faced empty cell; semantics per the birth engine's lockstep rule)
    move = is_op(SEM_MOVE)
    mtgt, mvalid = _facing_step(params, rows, facing, jnp.ones_like(rows))
    intend = move & mvalid & ~st.alive[mtgt] & st.alive
    BIG = jnp.int32(2**30)
    claim = jnp.full(n, BIG, jnp.int32)
    claim = claim.at[jnp.where(intend, mtgt, rows)].min(
        jnp.where(intend, rows, BIG))
    won = intend & (claim[mtgt] == rows)
    regs = setreg(regs, operand, won.astype(jnp.int32), move)

    # attack-prey (Inst_AttackPrey cc:5407 -> ExecuteAttack cc:7001):
    # faced living prey (forage target > -2) dies; attacker gains
    # PRED_EFFICIENCY x its merit/bonus and becomes a predator.  The
    # attack-chance roll and reaction/res-bin transfer are not modeled
    # (documented); simultaneous attackers of one prey each gain
    # (lockstep deviation).
    from avida_tpu.models.experimental import SEM_ATTACK_PREY
    atk = is_op(SEM_ATTACK_PREY)
    if params.pred_prey_switch >= 0:
        atgt, avalid = _facing_step(params, rows, facing,
                                    jnp.ones_like(rows))
        atk_ok = (atk & avalid & st.alive[atgt]
                  & (st.forage_target[atgt] > -2) & (atgt != rows))
    else:
        atgt = rows
        atk_ok = jnp.zeros_like(atk)
    regs = setreg(regs, operand, atk_ok.astype(jnp.int32), atk)
    return regs, facing, forage_target, won, mtgt, atk_ok, atgt


# world-level / cell-bound fields that do NOT travel with a moving organism
# cell-bound / world-level state that must NOT relocate with a moving
# organism: the cell input stream plus every WORLD_LEVEL field (resource
# pools, birth chamber, deme state, lane permutation, the newborn and
# flight-recorder ring buffers) -- deriving from the state module's
# authority keeps future world-level fields out of the move gather
# automatically (a [CAP]-shaped ring with CAP == N would otherwise be
# silently permuted)
_NON_ORG_FIELDS = frozenset({"inputs"}) | _WORLD_LEVEL_FIELDS


def _apply_attacks(params, st, pre, atk_ok, atk_tgt):
    """Resolve this cycle's attack-prey kills (ExecuteAttack cc:7001):
    prey stats are read from the PRE-cycle state, the prey dies, every
    successful attacker gains PRED_EFFICIENCY x the prey's merit and
    bonus and turns predator (MakePred: forage target -2)."""
    n = st.alive.shape[0]
    eff = params.pred_efficiency
    prey_merit = pre.merit[atk_tgt]
    prey_bonus = pre.cur_bonus[atk_tgt]
    killed = jnp.zeros(n, bool).at[
        jnp.where(atk_ok, atk_tgt, n)].set(True, mode="drop")
    return st.replace(
        merit=jnp.where(atk_ok, (st.merit + prey_merit * eff
                                 ).astype(st.merit.dtype), st.merit),
        cur_bonus=jnp.where(atk_ok, (st.cur_bonus + prey_bonus * eff
                                     ).astype(st.cur_bonus.dtype),
                            st.cur_bonus),
        forage_target=jnp.where(atk_ok, -2, st.forage_target),
        alive=st.alive & ~killed,
    )


def _apply_moves(st, won, target):
    """Relocate move winners into their target cells: a permutation gather
    over every organism-bound field (the cell-bound input stream stays).
    Gated on any move actually happening this cycle."""
    n = st.alive.shape[0]
    rows = jnp.arange(n)
    perm = rows.at[jnp.where(won, target, n)].set(rows, mode="drop")
    perm = perm.at[jnp.where(won, rows, n)].set(
        jnp.where(won, target, rows), mode="drop")

    def do(stx):
        updates = {}
        for name in stx.__dataclass_fields__:
            if name in _NON_ORG_FIELDS:
                continue
            v = getattr(stx, name)
            if not hasattr(v, "shape") or v.ndim == 0 or v.shape[0] != n:
                continue
            updates[name] = v[perm]
        return stx.replace(**updates)

    return jax.lax.cond(won.any(), do, lambda x: x, st)


def extract_offspring(params, st, key, use_off_tape=False):
    """Materialize pending offspring genomes: off[n, q] = opcodes[n,
    off_start[n] + q] for q < off_len[n], with divide mutations applied
    (Divide_DoMutations, cHardwareBase.cc:296: point sub, single insertion,
    single deletion; stock rates 0/0.05/0.05).

    Runs once per update in the birth engine -- the deferred half of
    h-divide.  Returns (off int8[N, L], off_len int32[N]).

    `use_off_tape=True` (the birth flush on heads hardware) skips the
    [N, L] barrel shift and reads the pre-extracted st.off_tape plane,
    which ops/update.update_step guarantees is current at flush time
    (written at the divide cycle by the Pallas kernel, or by one masked
    end-of-update roll on the XLA path).  Direct callers (Test CPU,
    unit tests) that drive micro_step themselves leave it False.

    TransSMT hardware divides off the host write buffer instead of a tape
    suffix (Divide_Main, cHardwareTransSMT.cc:438); the divide-mutation
    machinery below is shared."""
    n, L = st.tape.shape
    rows = jnp.arange(n)
    cols = jnp.arange(L)
    off_len = st.off_len
    if params.hw_type in (1, 2):
        off = st.smt_aux[:, 0].astype(jnp.int8)
    elif use_off_tape:
        off = st.off_tape.astype(jnp.int8)
    else:
        ops = tape_ops(st.tape).astype(jnp.int8)
        off = barrel_shift_left(ops, st.off_start, L)
    off = jnp.where(cols[None, :] < off_len[:, None], off, jnp.int8(0))

    gsize = st.genome_len.astype(jnp.float32)
    min_sz = jnp.maximum(params.min_genome_len,
                         (gsize / params.offspring_size_range).astype(jnp.int32))
    max_sz = jnp.minimum(L, (gsize * params.offspring_size_range).astype(jnp.int32))
    div_m = st.divide_pending

    k_u, k_mpos, k_ipos, k_dpos, k_iinst = jax.random.split(key, 5)
    u_mut = jax.random.uniform(k_u, (n, 3))
    r_inst2 = random_inst(params, k_iinst, (n, 2))
    # DIV_MUT_PROB: per-SITE substitution rate applied on divide
    # (cHardwareBase::Divide_DoMutations cc:434: num_mut ~ Binomial(len, p),
    # each hitting a uniform random site); capped at 8 substitutions per
    # divide -- the tail beyond 8 is negligible at any sane rate
    if params.div_mut_prob > 0:
        k_dm = jax.random.fold_in(key, 0xD1)
        n_sub = jnp.clip(jax.random.binomial(
            k_dm, jnp.maximum(off_len, 1).astype(jnp.float32),
            params.div_mut_prob), 0, 8).astype(jnp.int32)
        for k in range(8):
            kk = jax.random.fold_in(k_dm, k + 1)
            site = jax.random.randint(kk, (n,), 0, jnp.maximum(off_len, 1))
            rv = random_inst(params, jax.random.fold_in(kk, 3), (n,))
            do = div_m & (k < n_sub) & (off_len > 0)
            hit = (cols[None, :] == site[:, None]) & do[:, None]
            off = jnp.where(hit, rv[:, None].astype(jnp.int8), off)
    # point substitution
    if params.divide_mut_prob > 0:
        mpos = jax.random.randint(k_mpos, (n,), 0, jnp.maximum(off_len, 1))
        do_sub = div_m & (u_mut[:, 0] < params.divide_mut_prob) & (off_len > 0)
        sub_mask = (cols[None, :] == mpos[:, None]) & do_sub[:, None]
        off = jnp.where(sub_mask, r_inst2[:, 0:1].astype(jnp.int8), off)
    # single insertion
    if params.divide_ins_prob > 0:
        ipos = jax.random.randint(k_ipos, (n,), 0, jnp.maximum(off_len, 1) + 1)
        do_ins = div_m & (u_mut[:, 1] < params.divide_ins_prob) & (off_len + 1 <= max_sz)
        shifted = jnp.where(cols[None, :] > ipos[:, None],
                            jnp.pad(off, ((0, 0), (1, 0)))[:, :L], off)
        ins_mask = cols[None, :] == ipos[:, None]
        inserted = jnp.where(ins_mask, r_inst2[:, 1:2].astype(jnp.int8), shifted)
        off = jnp.where(do_ins[:, None], inserted, off)
        off_len = jnp.where(do_ins, off_len + 1, off_len)
    # single deletion
    if params.divide_del_prob > 0:
        dpos = jax.random.randint(k_dpos, (n,), 0, jnp.maximum(off_len, 1))
        do_del = div_m & (u_mut[:, 2] < params.divide_del_prob) & (off_len - 1 >= params.min_genome_len)
        deleted = jnp.where(cols[None, :] >= dpos[:, None],
                            jnp.pad(off, ((0, 0), (0, 1)))[:, 1:], off)
        deleted = jnp.where(cols[None, :] >= (off_len - 1)[:, None],
                            jnp.int8(0), deleted)
        off = jnp.where(do_del[:, None], deleted, off)
        off_len = jnp.where(do_del, off_len - 1, off_len)

    # COPY_INS_PROB / COPY_DEL_PROB (cHardwareBase::Divide_DoMutations
    # copy-lifetime insert/delete): the reference applies these per h-copy;
    # the lockstep engine applies the statistically equivalent
    # Binomial(copied, p) count of single-site insertions/deletions to the
    # offspring at divide time (documented deviation: the parent's write
    # trajectory is unaffected), capped at 4 each per divide (the tail
    # probability beyond 4 is negligible at any sane rate).
    KMAX = 4
    if params.copy_ins_prob > 0 or params.copy_del_prob > 0:
        k_ci, k_cd = jax.random.split(jax.random.fold_in(key, 0xC0), 2)
        cl = jnp.maximum(off_len, 1).astype(jnp.float32)
        if params.copy_ins_prob > 0:
            n_ins = jnp.clip(jax.random.binomial(
                k_ci, cl, params.copy_ins_prob), 0, KMAX).astype(jnp.int32)
            for k in range(KMAX):
                kk = jax.random.fold_in(k_ci, k + 1)
                ipos2 = jax.random.randint(kk, (n,), 0,
                                           jnp.maximum(off_len, 1) + 1)
                iv = random_inst(params, jax.random.fold_in(kk, 7), (n,))
                do = div_m & (k < n_ins) & (off_len + 1 <= max_sz)
                shifted = jnp.where(cols[None, :] > ipos2[:, None],
                                    jnp.pad(off, ((0, 0), (1, 0)))[:, :L],
                                    off)
                ins = jnp.where(cols[None, :] == ipos2[:, None],
                                iv[:, None].astype(jnp.int8), shifted)
                off = jnp.where(do[:, None], ins, off)
                off_len = jnp.where(do, off_len + 1, off_len)
        if params.copy_del_prob > 0:
            n_del = jnp.clip(jax.random.binomial(
                k_cd, cl, params.copy_del_prob), 0, KMAX).astype(jnp.int32)
            for k in range(KMAX):
                kk = jax.random.fold_in(k_cd, k + 1)
                dpos2 = jax.random.randint(kk, (n,), 0,
                                           jnp.maximum(off_len, 1))
                do = div_m & (k < n_del) & (off_len - 1 >= params.min_genome_len)
                deleted = jnp.where(cols[None, :] >= dpos2[:, None],
                                    jnp.pad(off, ((0, 0), (0, 1)))[:, 1:],
                                    off)
                deleted = jnp.where(cols[None, :] >= (off_len - 1)[:, None],
                                    jnp.int8(0), deleted)
                off = jnp.where(do[:, None], deleted, off)
                off_len = jnp.where(do, off_len - 1, off_len)

    # DIVIDE_SLIP_PROB (cHardwareBase::doSlipMutation cc:621): duplicate or
    # delete a random region [p1, p2), direction random.
    if params.divide_slip_prob > 0:
        k_s = jax.random.fold_in(key, 0x51)
        u_s, u_dir = jax.random.uniform(k_s, (n,)),             jax.random.uniform(jax.random.fold_in(k_s, 1), (n,))
        pa = jax.random.randint(jax.random.fold_in(k_s, 2), (n,), 0,
                                jnp.maximum(off_len, 1))
        pb = jax.random.randint(jax.random.fold_in(k_s, 3), (n,), 0,
                                jnp.maximum(off_len, 1))
        p1 = jnp.minimum(pa, pb)
        p2 = jnp.maximum(pa, pb)
        size = p2 - p1
        want = div_m & (u_s < params.divide_slip_prob) & (size > 0)
        dup = want & (u_dir < 0.5) & (off_len + size <= max_sz)
        dele = want & (u_dir >= 0.5) & (off_len - size >= params.min_genome_len)
        from avida_tpu.ops.birth import _roll_right
        # duplicate: out[q] = off[q] for q < p2, off[q - size] after
        dup_plane = jnp.where(cols[None, :] < p2[:, None], off,
                              _roll_right(off, size, L))
        # delete: out[q] = off[q] for q < p1, off[q + size] after
        del_plane = jnp.where(cols[None, :] < p1[:, None], off,
                              _roll_right(off, -size, L))
        off = jnp.where(dup[:, None], dup_plane,
                        jnp.where(dele[:, None], del_plane, off))
        off_len = jnp.where(dup, off_len + size,
                            jnp.where(dele, off_len - size, off_len))
        off = jnp.where(cols[None, :] < off_len[:, None], off, jnp.int8(0))
    return off, off_len


def convert_energy_to_merit(params, energy):
    """cPhenotype::ConvertEnergyToMerit (cPhenotype.cc:2403): 100 x energy
    / NUM_CYCLES_EXC_BEFORE_0_ENERGY, or a fixed metabolic rate."""
    if params.fix_metabolic_rate > 0.0:
        return jnp.full_like(energy, 100.0 * params.fix_metabolic_rate)
    return 100.0 * energy / max(params.num_cycles_exc, 1)


def _calc_size_merit(params, genome_len, copied_size, executed_size):
    """cPhenotype::CalcSizeMerit (cPhenotype.cc, BASE_MERIT_METHOD switch)."""
    m = params.base_merit_method
    if m == 0:
        return jnp.full_like(genome_len, params.base_const_merit).astype(jnp.float32)
    if m == 1:
        return copied_size.astype(jnp.float32)
    if m == 2:
        return executed_size.astype(jnp.float32)
    if m == 3:
        return genome_len.astype(jnp.float32)
    if m == 4:
        return jnp.minimum(jnp.minimum(genome_len, copied_size),
                           executed_size).astype(jnp.float32)
    if m == 5:
        least = jnp.minimum(jnp.minimum(genome_len, copied_size), executed_size)
        return jnp.sqrt(least.astype(jnp.float32))
    raise NotImplementedError(f"BASE_MERIT_METHOD {m}")


def micro_step_threads(params, st, key, exec_mask):
    """One scheduler cycle under MAX_CPU_THREADS > 1 (cHardwareCPU
    SingleProcess thread loop, cc:930-1060): per THREAD_SLICING_METHOD
    (cAvidaConfig.h:561), execute 1 (method 0) or num_threads (method 1)
    thread sub-steps; each sub-step advances cur_thread to the next live
    slot, runs the shared core on that thread's view of the per-thread
    state, then scatters the results back and applies fork-th / kill-th /
    divide slot bookkeeping.

    Documented deviations from the reference's dense thread array: slots
    do not move on kill (except the slot-0 compaction that preserves the
    "primary fields = a live thread" invariant), so round-robin order
    after mid-stack kills can differ; after any kill, scheduling resumes
    from slot 0."""
    reps = params.max_cpu_threads if params.thread_slicing_method == 1 else 1
    # The per-lane live-thread count is fixed ONCE at the top of the slice
    # (the reference fixes num_inst_exec = GetNumThreads() before its loop,
    # cHardwareCPU.cc:936): a thread forked by an earlier sub-step of this
    # slice must neither raise the sub-step gate nor be scheduled until the
    # next slice, so the slice-start t_alive snapshot also bounds which
    # slots the round-robin advance may select (intersected with the live
    # set so a thread killed mid-slice stops being scheduled immediately).
    n_thr0 = 1 + st.t_alive.sum(axis=1)
    sched_alive0 = st.t_alive
    for r in range(reps):
        st = _thread_substep(params, st, jax.random.fold_in(key, r),
                             exec_mask, charge_time=(r == 0), rep=r,
                             n_live=n_thr0, sched_alive=sched_alive0)
    return st


def _thread_substep(params, st, key, exec_mask, charge_time, rep,
                    n_live=None, sched_alive=None):
    T = params.max_cpu_threads
    Te = T - 1
    cols = jnp.arange(Te)
    if n_live is None:
        n_live = 1 + st.t_alive.sum(axis=1)
    if sched_alive is None:
        sched_alive = st.t_alive
    # method 1 executes each live thread once per slice: sub-step r only
    # runs lanes that still had an r+1-th thread at slice start
    sub_mask = exec_mask & (n_live > rep) if rep else exec_mask

    def slot_alive(cand):
        if Te == 0:
            return cand == 0
        extra = ((cols[None, :] == (cand - 1)[:, None]) & st.t_alive
                 & sched_alive).any(axis=1)
        return (cand == 0) | extra

    # advance cur_thread to the next live slot (m_cur_thread++ wrap,
    # cc:946-948; dead slots are skipped)
    cur0 = st.cur_thread
    cur = cur0
    found = jnp.zeros_like(exec_mask)
    for k in range(1, T + 1):
        cand = (cur0 + k) % T
        al = slot_alive(cand)
        cur = jnp.where(~found & al, cand, cur)
        found = found | al
    cur = jnp.where(sub_mask, cur, cur0)

    onehot = ((cols[None, :] == (cur - 1)[:, None])
              & (cur[:, None] > 0)) if Te else jnp.zeros((cur.shape[0], 0),
                                                         bool)
    is_extra = cur > 0

    def pick(main, extra):
        """Active-thread view of a per-thread field (slot 0 = main)."""
        if Te == 0:
            return main
        exp = onehot.reshape(onehot.shape + (1,) * (extra.ndim - 2))
        v = jnp.sum(jnp.where(exp, extra, 0), axis=1)
        m = is_extra.reshape((-1,) + (1,) * (main.ndim - 1))
        return jnp.where(m, v.astype(main.dtype), main)

    local_stack = pick(st.stacks[:, 0], st.t_stack)
    view = st.replace(
        regs=pick(st.regs, st.t_regs),
        heads=pick(st.heads, st.t_heads),
        stacks=jnp.stack([local_stack, st.stacks[:, 1]], axis=1),
        sp=jnp.stack([pick(st.sp[:, 0], st.t_sp), st.sp[:, 1]], axis=1),
        active_stack=pick(st.active_stack, st.t_active_stack),
        read_label=pick(st.read_label, st.t_rlabel),
        read_label_len=pick(st.read_label_len, st.t_rlabel_len),
        main_tid=pick(st.main_tid, st.t_ids),
        cur_thread=cur)

    nv, sig = micro_step(params, view, key, sub_mask,
                         return_signals=True, charge_time=charge_time)

    # ---- scatter the view's per-thread results back into slot `cur` ----
    # a divide from an extra-slot thread resets the ORGANISM: the reset
    # view (IP 0, cleared regs/stacks/labels; Divide_Main -> Reset) lands
    # in slot 0, not in the soon-to-be-killed extra slot
    wrote_main = sub_mask & (~is_extra | sig["div"])
    oh_w = (onehot & (sub_mask & is_extra & ~sig["div"])[:, None]
            if Te else onehot)

    def put_main(old_main, new_val):
        m = wrote_main.reshape((-1,) + (1,) * (old_main.ndim - 1))
        return jnp.where(m, new_val.astype(old_main.dtype), old_main)

    def put_extra(old_extra, new_val):
        if Te == 0:
            return old_extra
        exp = oh_w.reshape(oh_w.shape + (1,) * (old_extra.ndim - 2))
        return jnp.where(exp, jnp.expand_dims(new_val, 1).astype(
            old_extra.dtype), old_extra)

    st2 = nv.replace(
        regs=put_main(st.regs, nv.regs),
        heads=put_main(st.heads, nv.heads),
        stacks=jnp.stack([put_main(st.stacks[:, 0], nv.stacks[:, 0]),
                          nv.stacks[:, 1]], axis=1),
        sp=jnp.stack([put_main(st.sp[:, 0], nv.sp[:, 0]),
                      nv.sp[:, 1]], axis=1),
        active_stack=put_main(st.active_stack, nv.active_stack),
        read_label=put_main(st.read_label, nv.read_label),
        read_label_len=put_main(st.read_label_len, nv.read_label_len),
        main_tid=st.main_tid, cur_thread=cur,
        t_regs=put_extra(st.t_regs, nv.regs),
        t_heads=put_extra(st.t_heads, nv.heads),
        t_stack=put_extra(st.t_stack, nv.stacks[:, 0]),
        t_sp=put_extra(st.t_sp, nv.sp[:, 0]),
        t_active_stack=put_extra(st.t_active_stack, nv.active_stack),
        t_rlabel=put_extra(st.t_rlabel, nv.read_label),
        t_rlabel_len=put_extra(st.t_rlabel_len, nv.read_label_len),
        t_alive=st.t_alive, t_ids=st.t_ids)

    if Te == 0:
        return st2

    # ---- fork-th: copy the post-instruction active thread into the
    # lowest free slot with the lowest unused thread id (ForkThread
    # cc:1505-1524); silently fails at the cap ----
    free = ~st2.t_alive
    ffs = free & (jnp.cumsum(free.astype(jnp.int32), axis=1) == 1)
    can_fork = sig["fork"] & free.any(axis=1)
    put = ffs & can_fork[:, None]
    # lowest unused reference id among 0..T-1
    new_id = jnp.zeros_like(cur)
    taken_running = jnp.zeros_like(exec_mask)
    for v in range(T):
        used_v = (st2.main_tid == v) | (
            (st2.t_ids == v) & st2.t_alive).any(axis=1)
        pickv = ~taken_running & ~used_v
        new_id = jnp.where(pickv, v, new_id)
        taken_running = taken_running | ~used_v
    child_heads = nv.heads.at[:, HEAD_IP].set(sig["child_ip"])

    def fork_into(old_extra, new_val):
        exp = put.reshape(put.shape + (1,) * (old_extra.ndim - 2))
        return jnp.where(exp, jnp.expand_dims(new_val, 1).astype(
            old_extra.dtype), old_extra)

    st2 = st2.replace(
        t_alive=st2.t_alive | put,
        t_ids=jnp.where(put, new_id[:, None], st2.t_ids),
        t_regs=fork_into(st2.t_regs, nv.regs),
        t_heads=fork_into(st2.t_heads, child_heads),
        t_stack=fork_into(st2.t_stack, nv.stacks[:, 0]),
        t_sp=fork_into(st2.t_sp, nv.sp[:, 0]),
        t_active_stack=fork_into(st2.t_active_stack, nv.active_stack),
        t_rlabel=fork_into(st2.t_rlabel, nv.read_label),
        t_rlabel_len=fork_into(st2.t_rlabel_len, nv.read_label_len),
    )

    # ---- kill-th: fails with one thread (cc:1595); killing slot 0 moves
    # the LAST live extra thread into the primary fields (the reference's
    # compaction), killing an extra slot just frees it ----
    can_kill = sig["kill"] & (1 + st2.t_alive.sum(axis=1) > 1)
    kill_extra = can_kill & is_extra
    kill0 = can_kill & ~is_extra
    la = st2.t_alive & (jnp.cumsum(
        st2.t_alive[:, ::-1].astype(jnp.int32), axis=1)[:, ::-1] == 1)

    def last_val(extra):
        exp = la.reshape(la.shape + (1,) * (extra.ndim - 2))
        return jnp.sum(jnp.where(exp, extra, 0), axis=1)

    def move0(main, extra):
        m = kill0.reshape((-1,) + (1,) * (main.ndim - 1))
        return jnp.where(m, last_val(extra).astype(main.dtype), main)

    dead = jnp.where(kill_extra[:, None], onehot,
                     jnp.where(kill0[:, None], la,
                               jnp.zeros_like(st2.t_alive)))
    st2 = st2.replace(
        regs=move0(st2.regs, st2.t_regs),
        heads=move0(st2.heads, st2.t_heads),
        stacks=jnp.stack([move0(st2.stacks[:, 0], st2.t_stack),
                          st2.stacks[:, 1]], axis=1),
        sp=jnp.stack([move0(st2.sp[:, 0], st2.t_sp), st2.sp[:, 1]], axis=1),
        active_stack=move0(st2.active_stack, st2.t_active_stack),
        read_label=move0(st2.read_label, st2.t_rlabel),
        read_label_len=move0(st2.read_label_len, st2.t_rlabel_len),
        main_tid=jnp.where(kill0, last_val(st2.t_ids), st2.main_tid),
        t_alive=st2.t_alive & ~dead,
        cur_thread=jnp.where(can_kill, 0, st2.cur_thread),
    )

    # ---- divide: the parent resets to a single thread (Divide_Main ->
    # Reset; extra slots die, id chart resets) ----
    div = sig["div"]
    return st2.replace(
        t_alive=jnp.where(div[:, None], False, st2.t_alive),
        cur_thread=jnp.where(div, 0, st2.cur_thread),
        main_tid=jnp.where(div, 0, st2.main_tid),
    )


def anomaly_masks(params, st):
    """Audit-adjacent per-cell anomaly masks for the flight recorder
    (observability/tracer.py; ops/update.trace_post_phase).  These mirror
    the cheapest-to-explain invariants the auditor (utils/audit.py)
    checks wholesale -- non-finite/negative merit on a living organism
    and an instruction pointer outside [0, mem_len) after _adjust
    semantics -- but attribute them to the CELL at the update they first
    appear (trace_post_phase diffs these masks against the pre-update
    snapshot), so a tripped audit at update N has per-cell forensics in
    the runlog instead of only an aggregate count.  Returns
    (bad_merit, bad_head, head_payload)."""
    mlen = jnp.maximum(st.mem_len, 1)
    bad_merit = st.alive & (~jnp.isfinite(st.merit) | (st.merit < 0))
    ip = st.heads[:, 0]
    bad_head = st.alive & ((ip < 0) | (ip >= mlen))
    return bad_merit, bad_head, ip
