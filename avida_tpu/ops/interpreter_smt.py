"""Lockstep interpreter for the TransSMT hardware (models/transsmt.py).

One SMT CPU cycle for the whole population as masked tensor ops, mirroring
cHardwareTransSMT::SingleProcess (avida-core/source/cpu/cHardwareTransSMT.cc
~255-330): pick the executing thread (host or parasite, by virulence),
fetch from the thread IP's (memory_space, position), resolve the nop
modifier, dispatch on semantic opcode.

Memory-space model (see models/transsmt.py header): 4 spaces per organism
  0: the genome tape (packed, shares PopulationState.tape)
  1: host write buffer    (smt_aux[:, 0])
  2: parasite code        (pmem)
  3: parasite write buffer (smt_aux[:, 1])
Thread 0 (host) starts at (0, 0); thread 1 (parasite) at (2, 0).
SetMemory points FLOW at the calling thread's write buffer.

Divide (host thread) submits smt_aux[:,0][:wpos] as offspring through the
shared birth engine; Inject (either thread) stages its write buffer into
inj_mem for flush-time infection of a neighbor (Inst_Inject cc:1657,
ParasiteInfectHost cc:375).  PARASITE_VIRULENCE is the per-cycle
probability the parasite thread runs (cc:242-249); -1 = fair alternation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from avida_tpu.models.transsmt import (HEAD_FLOW, HEAD_IP, HEAD_READ,
                                       HEAD_WRITE, MAX_LABEL_SIZE, SEM_ADD,
                                       SEM_DEC, SEM_DIV, SEM_DIVIDE,
                                       SEM_HEAD_MOVE, SEM_HEAD_POP,
                                       SEM_HEAD_PUSH, SEM_IF_EQU, SEM_IF_GTR,
                                       SEM_IF_LESS, SEM_IF_NEQU, SEM_INC,
                                       SEM_INJECT, SEM_IO, SEM_MOD, SEM_MULT,
                                       SEM_NAND, SEM_PUSH_COMP, SEM_PUSH_NEXT,
                                       SEM_PUSH_PREV, SEM_READ, SEM_SEARCH,
                                       SEM_SET_MEMORY, SEM_SHIFT_L,
                                       SEM_SHIFT_R, SEM_SUB, SEM_VAL_COPY,
                                       SEM_VAL_DELETE, SEM_WRITE, STACK_AX,
                                       STACK_BX)
from avida_tpu.ops import tasks as tasks_ops

MIN_INJECT_SIZE = 8      # nHardwareTransSMT MIN_INJECT_SIZE


def _space_planes(st):
    """The four memory-space opcode planes + their sizes."""
    planes = [
        (st.tape & jnp.uint8(0x3F)).astype(jnp.int32),
        st.smt_aux[:, 0].astype(jnp.int32),
        st.pmem.astype(jnp.int32),
        st.smt_aux[:, 1].astype(jnp.int32),
    ]
    sizes = [st.mem_len, st.smt_aux_len[:, 0], st.pmem_len,
             st.smt_aux_len[:, 1]]
    return planes, sizes


def _read_at(planes, sizes, space, pos):
    """opcode at (space, pos) per organism; 0 beyond the space's length."""
    n = space.shape[0]
    L = planes[0].shape[1]
    cols = jnp.arange(L)
    out = jnp.zeros(n, jnp.int32)
    for k, (pl, sz) in enumerate(zip(planes, sizes)):
        m = (space == k)[:, None] & (cols[None, :] == pos[:, None]) \
            & (cols[None, :] < sz[:, None])
        out = out + jnp.sum(jnp.where(m, pl, 0), axis=1)
    return out


def _space_size(sizes, space):
    out = jnp.zeros_like(space)
    for k, sz in enumerate(sizes):
        out = jnp.where(space == k, sz, out)
    return out


def micro_step_smt(params, st, key, exec_mask):
    """One TransSMT CPU cycle for every organism where exec_mask is set."""
    n, L = st.tape.shape
    cols = jnp.arange(L)
    sem_t = jnp.asarray(params.sem, jnp.int32)
    is_nop_t = jnp.asarray(params.is_nop, bool)
    nop_mod_t = jnp.asarray(params.nop_mod, jnp.int32)
    num_insts = params.num_insts

    k_thr, k_mut, k_inst = jax.random.split(key, 3)

    # ---- thread selection (virulence; cc:242-249) ----
    v = params.parasite_virulence if params.parasite_virulence >= 0 else 0.5
    run_parasite = st.parasite_active & (
        jax.random.uniform(k_thr, (n,)) < v) & exec_mask
    t = run_parasite.astype(jnp.int32)            # active thread id [N]

    def thr(x):
        """Select the active thread's row of an [N, T, ...] field."""
        return jnp.where(
            (t == 1).reshape((n,) + (1,) * (x.ndim - 2)), x[:, 1], x[:, 0])

    planes, sizes = _space_planes(st)
    head_pos = thr(st.smt_head_pos)               # [N, 4]
    head_space = thr(st.smt_head_space)           # [N, 4]
    ip_s = head_space[:, HEAD_IP]
    ip_sz = jnp.maximum(_space_size(sizes, ip_s), 1)
    ip_p = head_pos[:, HEAD_IP] % ip_sz

    cur_op = jnp.clip(_read_at(planes, sizes, ip_s, ip_p), 0, num_insts - 1)
    sem = jnp.where(exec_mask, sem_t[cur_op], -1)

    def is_op(s):
        return sem == s

    # ---- nop modifier (FindModifiedStack/Head, cc:... inline helpers) ----
    nxt_p = (ip_p + 1) % ip_sz
    next_op = jnp.clip(_read_at(planes, sizes, ip_s, nxt_p), 0, num_insts - 1)
    has_mod = is_nop_t[next_op]
    mod_val = nop_mod_t[next_op]                  # 0-3
    consumed = has_mod.astype(jnp.int32)

    # per-semantic default stacks/heads
    dflt = jnp.zeros(n, jnp.int32) + STACK_BX
    for s in (SEM_IF_EQU, SEM_IF_NEQU, SEM_IF_LESS, SEM_IF_GTR):
        dflt = jnp.where(is_op(s), STACK_AX, dflt)
    dflt = jnp.where(is_op(SEM_PUSH_NEXT), STACK_AX, dflt)
    operand = jnp.where(has_mod, mod_val, dflt)

    head_dflt = jnp.full(n, HEAD_IP, jnp.int32)
    head_dflt = jnp.where(is_op(SEM_READ), HEAD_READ, head_dflt)
    head_dflt = jnp.where(is_op(SEM_WRITE), HEAD_WRITE, head_dflt)
    head_op = jnp.where(has_mod & (mod_val < 4), mod_val, head_dflt)

    # ---- label read for Search/SetMemory (<=3 nops after IP): the run of
    # nops IS the label (ReadLabel cc:1521) ----
    lp = ip_p
    lab_len = jnp.zeros(n, jnp.int32)
    running = jnp.ones(n, bool)
    lab_vals = []
    for k in range(MAX_LABEL_SIZE):
        lp = (lp + 1) % ip_sz
        op_k = jnp.clip(_read_at(planes, sizes, ip_s, lp), 0, num_insts - 1)
        isn = is_nop_t[op_k] & running
        lab_vals.append(jnp.where(isn, nop_mod_t[op_k], -1))
        lab_len = lab_len + isn.astype(jnp.int32)
        running = running & is_nop_t[op_k]
    has_label_sem = is_op(SEM_SEARCH) | is_op(SEM_SET_MEMORY) \
        | is_op(SEM_INJECT)
    consumed = jnp.where(has_label_sem, lab_len, consumed)

    # ---- stacks: unified [N, 4, 10] view (3 local of active thread +
    # global) ----
    local = thr(st.smt_stacks)                    # [N, 3, 10]
    local_sp = thr(st.smt_sp)                     # [N, 3]
    stacks = jnp.concatenate([local, st.gstack[:, None, :]], axis=1)
    sps = jnp.concatenate([local_sp, st.gsp[:, None]], axis=1)  # [N, 4]

    def top(stk_idx):
        slot = (jnp.arange(4)[None, :, None] == stk_idx[:, None, None]) & \
            (jnp.arange(10)[None, None, :] ==
             jnp.sum(jnp.where(jnp.arange(4)[None, :] == stk_idx[:, None],
                               sps, 0), axis=1)[:, None, None])
        return jnp.sum(jnp.where(slot, stacks, 0), axis=(1, 2))

    def sp_of(stk_idx):
        return jnp.sum(jnp.where(jnp.arange(4)[None, :] == stk_idx[:, None],
                                 sps, 0), axis=1)

    # operand stacks
    src1 = operand
    nxt_stack = (operand + 1) % 4
    prv_stack = (operand + 3) % 4
    op2 = jnp.where(has_mod, nxt_stack, (dflt + 1) % 4)

    v1 = top(src1)
    v2 = top(op2)

    # ---- PRNG ----
    from avida_tpu.ops.interpreter import random_inst as _ri
    u_mut = jax.random.uniform(k_mut, (n,))
    rand_inst = _ri(params, k_inst, (n,))

    # ---- compute push/pop plan ----
    # Each instruction does at most one pop from `pop_stack` and one push of
    # `push_val` onto `push_stack` (-1 = none).
    pop_stack = jnp.full(n, -1, jnp.int32)
    push_stack = jnp.full(n, -1, jnp.int32)
    push_val = jnp.zeros(n, jnp.int32)

    def plan(mask, pops, pushes, val=None):
        """Record this instruction's (at most one) pop and push."""
        nonlocal pop_stack, push_stack, push_val
        if pops is not None:
            pop_stack = jnp.where(mask, pops, pop_stack)
        if pushes is not None:
            push_stack = jnp.where(mask, pushes, push_stack)
            push_val = jnp.where(mask, val, push_val)

    # Val unary ops: pop src (== dst), push f(value)   (cc:983-1028)
    for s, f in ((SEM_SHIFT_R, lambda x: x >> 1), (SEM_SHIFT_L, lambda x: x << 1),
                 (SEM_INC, lambda x: x + 1), (SEM_DEC, lambda x: x - 1)):
        m = is_op(s)
        plan(m, src1, src1, f(v1))
    # Val binary ops: push f(op1.top, op2.top) onto dst=op1 (no pop; cc:919)
    z2 = jnp.where(v2 == 0, 1, v2)
    for s, val in ((SEM_NAND, ~(v1 & v2)), (SEM_ADD, v1 + v2),
                   (SEM_SUB, v1 - v2), (SEM_MULT, v1 * v2),
                   (SEM_DIV, v1 // z2), (SEM_MOD, v1 % z2)):
        m = is_op(s)
        if s in (SEM_DIV, SEM_MOD):
            m = m & (v2 != 0)
        plan(m, None, src1, val)
    # Val-Copy: push src.top onto dst (dst=?BX?, src=?dst?) -- both resolve
    # to the same modified stack in the common case
    plan(is_op(SEM_VAL_COPY), None, src1, v1)
    # Val-Delete: pop
    plan(is_op(SEM_VAL_DELETE), src1, None)
    # Push-Next / Push-Prev / Push-Comp (cc:1197-1225): the modifier
    # selects the SOURCE (already in src1); dst = next/prev of it.
    # Push-Comp's no-second-nop fallback is FindPreviousStack in the
    # reference too (FindModifiedComplementStack's else branch) -- a
    # faithful quirk, not a bug here.
    plan(is_op(SEM_PUSH_NEXT), src1, nxt_stack, v1)
    plan(is_op(SEM_PUSH_PREV), src1, prv_stack, v1)
    plan(is_op(SEM_PUSH_COMP), src1, prv_stack, v1)
    # Head-Push: push pos of ?IP? head onto BX (single-modifier model: a
    # nop selects the HEAD; dst stays STACK_BX)
    hsel = jnp.sum(jnp.where(jnp.arange(4)[None, :] == head_op[:, None],
                             head_pos, 0), axis=1)
    plan(is_op(SEM_HEAD_PUSH), None, jnp.full(n, STACK_BX), hsel)
    # Head-Pop: pop ?BX?, head write happens below
    headpop_val = v1
    plan(is_op(SEM_HEAD_POP), src1, None)

    # ---- Search (cc:1172): complement label (rotate +2 mod 4) in IP space
    srch = is_op(SEM_SEARCH)
    lbl_c = [jnp.where(x >= 0, (x + 2) % 4, -2) for x in lab_vals]

    def search_block(_):
        # match positions in the IP's space
        found = jnp.full(n, -1, jnp.int32)
        best = jnp.full(n, L + 1, jnp.int32)
        # scan each space plane for the complement label, positions after IP
        for k, (pl, sz) in enumerate(zip(planes, sizes)):
            clipped = jnp.clip(pl, 0, num_insts - 1)
            nv = jnp.where(is_nop_t[clipped], nop_mod_t[clipped], -3)
            match = jnp.ones((n, L), bool)
            for q in range(MAX_LABEL_SIZE):
                shifted = jnp.concatenate(
                    [nv[:, q:], jnp.full((n, q), -4, jnp.int32)], axis=1) \
                    if q else nv
                match = match & (
                    (shifted == lbl_c[q][:, None]) | (q >= lab_len)[:, None])
            match = match & (cols[None, :] < sz[:, None]) & \
                (lab_len > 0)[:, None] & (ip_s == k)[:, None]
            # circular search forward from IP: rank positions by distance
            dist = (cols[None, :] - ip_p[:, None]) % jnp.maximum(
                sz[:, None], 1)
            dist = jnp.where(match, dist, L + 1)
            dmin = dist.min(axis=1)
            pos = jnp.argmin(dist, axis=1)
            better = dmin < best
            found = jnp.where(better, pos, found)
            best = jnp.where(better, dmin, best)
        return found

    found_pos = jax.lax.cond(srch.any(), search_block,
                             lambda _: jnp.full(n, -1, jnp.int32), None)
    srch_hit = srch & (found_pos >= 0) & (found_pos != ip_p)
    srch_miss = srch & ~srch_hit

    # ---- SetMemory (cc:1567): FLOW <- (write buffer of thread, 0);
    # empty label -> (base space, 0)
    setmem = is_op(SEM_SET_MEMORY)
    aux_space = jnp.where(t == 1, 3, 1)
    base_space = jnp.where(t == 1, 2, 0)
    setmem_space = jnp.where(lab_len > 0, aux_space, base_space)

    # ---- Inst-Read (cc:1304) ----
    read_m = is_op(SEM_READ)
    r_space = jnp.sum(jnp.where(jnp.arange(4)[None, :] == head_op[:, None],
                                head_space, 0), axis=1)
    r_sz = jnp.maximum(_space_size(sizes, r_space), 1)
    r_pos = jnp.sum(jnp.where(jnp.arange(4)[None, :] == head_op[:, None],
                              head_pos, 0), axis=1) % r_sz
    read_inst = _read_at(planes, sizes, r_space, r_pos)
    do_mut = read_m & (u_mut < params.copy_mut_prob) & (t == 0)
    read_val = jnp.where(do_mut, rand_inst, read_inst)
    # single-modifier model: the nop selects the HEAD (first FindModified*
    # call in Inst_HeadRead); the stack keeps its STACK_AX default
    plan(read_m, None, jnp.full(n, STACK_AX), read_val)

    # ---- Inst-Write (cc:1341) ----
    write_m = is_op(SEM_WRITE)
    w_space = jnp.where(write_m,
                        jnp.sum(jnp.where(jnp.arange(4)[None, :] ==
                                          head_op[:, None], head_space, 0),
                                axis=1), 0)
    w_sz0 = _space_size(sizes, w_space)
    w_pos = jnp.sum(jnp.where(jnp.arange(4)[None, :] == head_op[:, None],
                              head_pos, 0), axis=1)
    # grow-by-one then adjust (write buffer extension)
    grow = write_m & (w_pos >= w_sz0 - 1) & (w_sz0 < L)
    w_sz = jnp.where(grow, w_sz0 + 1, jnp.maximum(w_sz0, 1))
    w_pos = w_pos % jnp.maximum(w_sz, 1)
    w_stack = jnp.full(n, STACK_AX)    # modifier selects the head, not src
    w_val0 = top(w_stack)
    w_val = jnp.where((w_val0 < 0) | (w_val0 >= num_insts), 0, w_val0)
    plan(write_m, w_stack, None)

    # ---- IO (cc:1231): host thread only updates phenotype/tasks ----
    io_m = is_op(SEM_IO)
    io_stack = jnp.where(has_mod, mod_val, jnp.full(n, STACK_BX))
    value_out = top(io_stack)
    in_slot = jnp.arange(3)[None, :] == (st.input_ptr % 3)[:, None]
    value_in = jnp.sum(jnp.where(in_slot, st.inputs, 0), axis=1)
    plan(io_m, None, io_stack, value_in)
    io_host = io_m & (t == 0)

    def io_block(_):
        env_tables = tasks_ops.env_tables_to_device(params)
        logic_id = tasks_ops.compute_logic_id(st.input_buf, st.input_buf_n,
                                              value_out)
        return tasks_ops.apply_reactions(
            params, env_tables, io_host, logic_id, st.cur_bonus,
            st.cur_task_count, st.cur_reaction_count,
            st.resources, st.res_grid, st.deme_resources,
            input_buf=st.input_buf, input_buf_n=st.input_buf_n,
            output=value_out)[:6]

    (new_bonus, new_tc, new_rc, resources, res_grid,
     deme_resources) = jax.lax.cond(
        io_host.any(), io_block,
        lambda _: (st.cur_bonus, st.cur_task_count, st.cur_reaction_count,
                   st.resources, st.res_grid, st.deme_resources), None)
    input_ptr = jnp.where(io_m, st.input_ptr + 1, st.input_ptr)
    input_buf = jnp.where(io_m[:, None],
                          jnp.stack([value_in, st.input_buf[:, 0],
                                     st.input_buf[:, 1]], axis=1),
                          st.input_buf)
    input_buf_n = jnp.where(io_m, jnp.minimum(st.input_buf_n + 1, 3),
                            st.input_buf_n)
    cur_bonus = jnp.where(io_host, new_bonus, st.cur_bonus)
    cur_task_count = jnp.where(io_host[:, None], new_tc, st.cur_task_count)
    cur_reaction_count = jnp.where(io_host[:, None], new_rc,
                                   st.cur_reaction_count)
    task_exe_total = st.task_exe_total + jnp.where(
        io_host[:, None], new_tc - st.cur_task_count, 0)

    # ---- conditionals (skip next on false) ----
    skip = ((is_op(SEM_IF_EQU) & (v1 != v2))
            | (is_op(SEM_IF_NEQU) & (v1 == v2))
            | (is_op(SEM_IF_LESS) & (v1 >= v2))
            | (is_op(SEM_IF_GTR) & (v1 <= v2)))

    # ---- Divide (host thread; Divide_Main cc:438) ----
    div_try = is_op(SEM_DIVIDE) & (t == 0)
    wh_space = head_space[:, HEAD_WRITE]
    wh_pos = head_pos[:, HEAD_WRITE]
    child_size = wh_pos
    psize = jnp.maximum(st.mem_len, 1)
    fsize = psize.astype(jnp.float32)
    min_sz = jnp.maximum(params.min_genome_len,
                         (fsize / params.offspring_size_range)
                         .astype(jnp.int32))
    max_sz = jnp.minimum(L, (fsize * params.offspring_size_range)
                         .astype(jnp.int32))
    div_m = (div_try & (wh_space == 1)
             & (child_size >= min_sz) & (child_size <= max_sz)
             & ~st.divide_pending & ~st.sterile)

    # ---- Inject (either thread; cc:1657) ----
    inj_try = is_op(SEM_INJECT)
    inj_space_ok = jnp.where(t == 1, wh_space == 3, wh_space == 1)
    inj_m = (inj_try & inj_space_ok & (wh_pos >= MIN_INJECT_SIZE)
             & ~st.inject_pending)
    inj_src = jnp.where((t == 1)[:, None], st.smt_aux[:, 1],
                        st.smt_aux[:, 0])
    inj_mem = jnp.where(inj_m[:, None], inj_src, st.inj_mem)
    inj_len = jnp.where(inj_m, wh_pos, st.inj_len)
    # the injecting thread's write buffer resets (cc:1693)
    aux_reset_inj = inj_m

    # ---- apply stack plan ----
    slot_idx = jnp.arange(10)[None, None, :]
    stk_idx = jnp.arange(4)[None, :, None]
    # pop first (Val-Inc pops then pushes; Push-* pop src push dst)
    do_pop = exec_mask & (pop_stack >= 0)
    pop_sp = sp_of(jnp.clip(pop_stack, 0, 3))
    pop_slot = (stk_idx == pop_stack[:, None, None]) & \
        (slot_idx == pop_sp[:, None, None]) & do_pop[:, None, None]
    stacks = jnp.where(pop_slot, 0, stacks)
    sps = jnp.where((jnp.arange(4)[None, :] == pop_stack[:, None]) &
                    do_pop[:, None], (sps + 1) % 10, sps)
    # then push
    do_push = exec_mask & (push_stack >= 0)
    push_sp = (sp_of(jnp.clip(push_stack, 0, 3)) + 9) % 10
    push_slot = (stk_idx == push_stack[:, None, None]) & \
        (slot_idx == push_sp[:, None, None]) & do_push[:, None, None]
    stacks = jnp.where(push_slot, push_val[:, None, None], stacks)
    sps = jnp.where((jnp.arange(4)[None, :] == push_stack[:, None]) &
                    do_push[:, None], push_sp[:, None], sps)

    # ---- head updates ----
    onehot_h = jnp.arange(4)[None, :] == head_op[:, None]
    new_pos = head_pos
    new_space = head_space
    # Head-Move: ?IP? <- FLOW; FLOW itself just advances (cc:1151)
    mv = is_op(SEM_HEAD_MOVE)
    mv_flow = mv & (head_op == HEAD_FLOW)
    mv_other = mv & ~mv_flow
    new_pos = jnp.where(onehot_h & mv_other[:, None],
                        head_pos[:, HEAD_FLOW][:, None], new_pos)
    new_space = jnp.where(onehot_h & mv_other[:, None],
                          head_space[:, HEAD_FLOW][:, None], new_space)
    new_pos = new_pos.at[:, HEAD_FLOW].set(
        jnp.where(mv_flow, head_pos[:, HEAD_FLOW] + 1,
                  new_pos[:, HEAD_FLOW]))
    # Head-Pop: ?IP? <- (popped value, same space)
    hp = is_op(SEM_HEAD_POP)
    new_pos = jnp.where(onehot_h & hp[:, None], headpop_val[:, None],
                        new_pos)
    # Search results -> FLOW (cc:1172)
    new_pos = new_pos.at[:, HEAD_FLOW].set(
        jnp.where(srch_hit, found_pos,
                  jnp.where(srch_miss, ip_p + 1,
                            new_pos[:, HEAD_FLOW])))
    new_space = new_space.at[:, HEAD_FLOW].set(
        jnp.where(srch, ip_s, new_space[:, HEAD_FLOW]))
    # Search pushes: hit -> BX=dist+len+1, AX=len; miss -> BX=0
    srch_size = (found_pos - ip_p) % jnp.maximum(ip_sz, 1) + lab_len + 1
    sps, stacks = _push2(stacks, sps, srch_hit, STACK_BX, srch_size,
                         exec_mask)
    sps, stacks = _push2(stacks, sps, srch_hit, STACK_AX, lab_len, exec_mask)
    sps, stacks = _push2(stacks, sps, srch_miss, STACK_BX,
                         jnp.zeros(n, jnp.int32), exec_mask)
    # SetMemory -> FLOW
    new_pos = new_pos.at[:, HEAD_FLOW].set(
        jnp.where(setmem, 0, new_pos[:, HEAD_FLOW]))
    new_space = new_space.at[:, HEAD_FLOW].set(
        jnp.where(setmem, setmem_space, new_space[:, HEAD_FLOW]))
    # Inst-Read / Inst-Write advance their heads
    adv = (read_m | write_m)
    new_pos = jnp.where(onehot_h & adv[:, None], new_pos + 1, new_pos)

    # ---- memory-space writes (Inst-Write) ----
    smt_aux = st.smt_aux
    pmem = st.pmem
    tape = st.tape
    mem_len = st.mem_len
    aux_len = st.smt_aux_len
    pmem_len = st.pmem_len
    for k in range(4):
        wm = write_m & (w_space == k) & exec_mask
        site = (cols[None, :] == w_pos[:, None]) & wm[:, None]
        if k == 0:
            tape = jnp.where(site, (w_val.astype(jnp.uint8)
                                    | jnp.uint8(0x80))[:, None], tape)
            mem_len = jnp.where(wm, jnp.maximum(mem_len, w_sz), mem_len)
        elif k == 2:
            pmem = jnp.where(site, w_val.astype(jnp.uint8)[:, None], pmem)
            pmem_len = jnp.where(wm, jnp.maximum(pmem_len, w_sz), pmem_len)
        else:
            ti = 0 if k == 1 else 1
            smt_aux = smt_aux.at[:, ti].set(
                jnp.where(site, w_val.astype(jnp.uint8)[:, None],
                          smt_aux[:, ti]))
            aux_len = aux_len.at[:, ti].set(
                jnp.where(wm, jnp.maximum(aux_len[:, ti], w_sz),
                          aux_len[:, ti]))

    # inject: reset the injecting thread's write buffer
    for ti in range(2):
        m = aux_reset_inj & (t == ti)
        smt_aux = smt_aux.at[:, ti].set(
            jnp.where(m[:, None], jnp.uint8(0), smt_aux[:, ti]))
        aux_len = aux_len.at[:, ti].set(jnp.where(m, 1, aux_len[:, ti]))

    # ---- divide bookkeeping (deferred to flush) ----
    off_len = jnp.where(div_m, child_size, st.off_len)
    # phenotype DivideReset (shared semantics with the heads engine)
    gestation = st.time_used + 1 - st.gestation_start
    merit_base = jnp.minimum(st.mem_len, child_size).astype(st.merit.dtype)
    new_merit = jnp.where(div_m, merit_base * cur_bonus
                          if params.inherit_merit else merit_base, st.merit)
    fitness = jnp.where(div_m, new_merit /
                        jnp.maximum(gestation, 1).astype(st.merit.dtype),
                        st.fitness)
    gestation_time = jnp.where(div_m, gestation, st.gestation_time)
    generation = jnp.where(div_m, st.generation + 1, st.generation)
    num_divides = jnp.where(div_m, st.num_divides + 1, st.num_divides)
    last_task_count = jnp.where(div_m[:, None], cur_task_count,
                                st.last_task_count)
    cur_task_count = jnp.where(div_m[:, None], 0, cur_task_count)
    cur_reaction_count = jnp.where(div_m[:, None], 0, cur_reaction_count)
    cur_bonus2 = jnp.where(div_m, params.default_bonus, cur_bonus)
    last_bonus = jnp.where(div_m, cur_bonus, st.last_bonus)

    # ---- IP advance ----
    mv_ip = mv_other & (head_op == HEAD_IP)
    ip_next = (ip_p + consumed + skip.astype(jnp.int32) + 1) % ip_sz
    new_pos = new_pos.at[:, HEAD_IP].set(
        jnp.where(mv_ip, new_pos[:, HEAD_IP],         # Head-Move: no advance
                  jnp.where(exec_mask, ip_next, new_pos[:, HEAD_IP])))
    new_pos = jnp.where(div_m[:, None], 0, new_pos)
    new_space = jnp.where(div_m[:, None], base_space[:, None], new_space)

    # ---- scatter thread state back ----
    t1 = (t == 1) & exec_mask
    t0 = (t == 0) & exec_mask
    smt_head_pos = st.smt_head_pos
    smt_head_space = st.smt_head_space
    smt_head_pos = smt_head_pos.at[:, 0].set(
        jnp.where(t0[:, None], new_pos, smt_head_pos[:, 0]))
    smt_head_pos = smt_head_pos.at[:, 1].set(
        jnp.where(t1[:, None], new_pos, smt_head_pos[:, 1]))
    smt_head_space = smt_head_space.at[:, 0].set(
        jnp.where(t0[:, None], new_space, smt_head_space[:, 0]))
    smt_head_space = smt_head_space.at[:, 1].set(
        jnp.where(t1[:, None], new_space, smt_head_space[:, 1]))
    smt_stacks = st.smt_stacks
    smt_sp = st.smt_sp
    smt_stacks = smt_stacks.at[:, 0].set(
        jnp.where(t0[:, None, None], stacks[:, :3], smt_stacks[:, 0]))
    smt_stacks = smt_stacks.at[:, 1].set(
        jnp.where(t1[:, None, None], stacks[:, :3], smt_stacks[:, 1]))
    smt_sp = smt_sp.at[:, 0].set(
        jnp.where(t0[:, None], sps[:, :3], smt_sp[:, 0]))
    smt_sp = smt_sp.at[:, 1].set(
        jnp.where(t1[:, None], sps[:, :3], smt_sp[:, 1]))
    gstack = jnp.where(exec_mask[:, None], stacks[:, 3], st.gstack)
    gsp = jnp.where(exec_mask, sps[:, 3], st.gsp)

    # divide resets the whole CPU (DIVIDE_METHOD 1 SPLIT, cc:492-496):
    # both threads' heads/stacks, parasite wiped
    smt_head_pos = jnp.where(div_m[:, None, None], 0, smt_head_pos)
    base_spaces = jnp.asarray([[0, 0, 0, 0], [2, 2, 2, 2]], jnp.int32)
    smt_head_space = jnp.where(div_m[:, None, None], base_spaces[None],
                               smt_head_space)
    smt_stacks = jnp.where(div_m[:, None, None, None], 0, smt_stacks)
    smt_sp = jnp.where(div_m[:, None, None], 0, smt_sp)
    gstack = jnp.where(div_m[:, None], 0, gstack)
    gsp = jnp.where(div_m, 0, gsp)
    parasite_active = jnp.where(div_m, False, st.parasite_active)
    pmem_len = jnp.where(div_m, 0, pmem_len)

    # ---- time + death ----
    time_used = st.time_used + exec_mask.astype(jnp.int32)
    died = exec_mask & (st.max_executed > 0) & (time_used >= st.max_executed)
    alive = st.alive & ~died
    insts_executed = st.insts_executed + exec_mask.astype(jnp.int32)
    gestation_start = jnp.where(div_m, time_used, st.gestation_start)

    return st.replace(
        tape=tape, mem_len=mem_len,
        smt_aux=smt_aux, smt_aux_len=aux_len, pmem=pmem, pmem_len=pmem_len,
        parasite_active=parasite_active,
        smt_stacks=smt_stacks, smt_sp=smt_sp, gstack=gstack, gsp=gsp,
        smt_head_pos=smt_head_pos, smt_head_space=smt_head_space,
        inject_pending=st.inject_pending | inj_m,
        inj_mem=inj_mem, inj_len=inj_len,
        divide_pending=st.divide_pending | div_m,
        off_start=jnp.zeros_like(st.off_start), off_len=off_len,
        off_copied_size=jnp.where(div_m, off_len, st.off_copied_size),
        merit=new_merit, fitness=fitness, gestation_time=gestation_time,
        generation=generation, num_divides=num_divides,
        gestation_start=gestation_start,
        last_task_count=last_task_count, cur_task_count=cur_task_count,
        cur_reaction_count=cur_reaction_count, cur_bonus=cur_bonus2,
        task_exe_total=task_exe_total,
        last_bonus=last_bonus,
        input_ptr=input_ptr, input_buf=input_buf, input_buf_n=input_buf_n,
        time_used=time_used, cpu_cycles=st.cpu_cycles +
        exec_mask.astype(jnp.int32),
        alive=alive, insts_executed=insts_executed,
        resources=resources, res_grid=res_grid,
        deme_resources=deme_resources,
    )


def _push2(stacks, sps, mask, stack_id, val, exec_mask):
    """Push val onto a FIXED stack id where mask&exec_mask (helper for
    Search's multi-push)."""
    m = mask & exec_mask
    new_sp = (sps[:, stack_id] + 9) % 10
    slot = (jnp.arange(10)[None, :] == new_sp[:, None]) & m[:, None]
    stacks = stacks.at[:, stack_id].set(
        jnp.where(slot, val[:, None], stacks[:, stack_id]))
    sps = sps.at[:, stack_id].set(jnp.where(m, new_sp, sps[:, stack_id]))
    return sps, stacks
