"""Packed-resident update chunk: the round-6 perf tentpole.

The per-update Pallas path (ops/update.interpret_phase -> pallas_cycles.
run_cycles) round-trips the whole population between the canonical
[N, L] PopulationState layout and the kernel's [LP, N] word-plane layout
on EVERY update -- pack + unpack + the [N, L] birth flush were ~13 ms of
the ~31 ms update at bench scale (ROUND5_NOTES).  This module instead
makes the packed layout the RESIDENT representation across a whole
update chunk:

  pack ONCE  ->  scan{ schedule -> kernel launch -> packed birth flush }
             ->  unpack ONCE at the chunk boundary

Chunk boundaries are exactly where the World driver already
synchronizes -- checkpoints, flight-recorder drains, newborn drains and
.dat readbacks all happen between update_scan calls -- so everything
host-visible still sees canonical [N, L] state (tests/
test_native_checkpoint.py, tests/test_tracer.py).

Layout contract: the resident planes are CELL-ordered (identity lane
mapping).  The packed-native birth flush (ops/birth.flush_births_packed)
moves offspring between neighbor cells with lane-axis ROLLS on [LP, N],
which is only meaningful in grid order -- so packed residency SUPERSEDES
the budget-sort lane permutation (TPU_LANE_PERM): ops/update.perm_phase
keeps the identity mapping whenever this path is active, for the
per-update reference path too, keeping the two bit-exact (same kernel
lane assignment => same per-lane PRNG streams).  The budget tail the
permutation used to pack away is attacked inside the kernel instead:
level-1 per-block while_loop early exit + level-2 row-tile skipping
(ops/pallas_cycles.py, TPU_KERNEL_ROWSKIP) and the per-block histogram
attribution in ops/scheduler.py.

The canonical `st` rides along inside PackedChunk as a carrier: its
world-level fields (resources, PRNG-independent tables, trace rings) and
a small set of per-cell scalar mirrors (alive, merit, gestation_time,
generation, birth_update, parent_id, genotype_id, breed_true,
budget_carry -- plus heads/mem_len/task_exe_total when the flight
recorder is armed) stay FRESH every update, so scheduling, light-stats
and trace emission read canonical fields mid-chunk.  Its [N, L] planes
are stale between boundaries and are rebuilt by unpack_chunk.

TPU_PACKED_CHUNK=0 disables the path entirely (the per-update
pack/unpack path with lane packing is then exactly the round-5 engine).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from avida_tpu.ops import birth as birth_ops
from avida_tpu.ops import pallas_cycles


class PackedChunk(struct.PyTreeNode):
    """Resident chunk state: canonical carrier + the five planes."""
    st: object               # PopulationState (see module docstring)
    tape_t: jax.Array        # int32[LP, N] opcode word plane
    off_t: jax.Array         # int32[LP, N] extracted-offspring plane
    gen_t: jax.Array         # int32[LP, N] birth-genome plane
    ivec: jax.Array          # int32[NI, N] per-organism scalars
    fvec: jax.Array          # f32[NF, N]  float phenotype scalars


def active(params, st=None) -> bool:
    """Static routing predicate: may this configuration keep state
    packed across a chunk?  Everything here is trace-time (params +
    state SHAPES), so update_scan / update_step / bench all agree.

    Requirements beyond the kernel's own `eligible`: the torus birth
    fast path (the packed flush is roll-based), asexual, no demes /
    energy / population caps, no point or slip mutations (per-site
    [N, L] sweeps / variable-size region moves stay canonical), no
    resource pools (resource_phase must not read stale planes), no
    device-side fault injection, and an EMPTY newborn ring (systematics
    records gather newborn genomes row-wise -- a lane-axis gather in
    packed space; run with TPU_SYSTEMATICS=0 for the packed path)."""
    from avida_tpu.ops.update import use_pallas_path
    if int(getattr(params, "packed_chunk", 1)) == 0:
        return False
    if params.hw_type != 0 or params.max_cpu_threads > 1:
        return False
    if not use_pallas_path(params):
        return False
    if birth_ops.has_divide_sex(params):
        return False
    if not birth_ops.local_torus_fast_path(params, sexual=False):
        return False
    if params.point_mut_prob > 0 or params.divide_slip_prob > 0:
        return False
    if params.num_global_res or params.num_spatial_res \
            or params.num_deme_res:
        return False
    if getattr(params, "fault_nan", ()):
        return False
    if st is not None and st.nb_genome.shape[0] > 0:
        return False
    return True


def pack_chunk(params, st) -> PackedChunk:
    """Canonical state -> resident planes (traced; once per chunk).
    Identity lane mapping by contract (see module docstring)."""
    n, L0 = st.tape.shape
    quad = pallas_cycles.pack_state(params, st, jnp.zeros(n, jnp.int32),
                                    None, 1)
    tape_t, off_t, ivec, fvec = (p[:, :n] for p in quad)
    L = tape_t.shape[0] * 4
    genp = jnp.pad(st.genome.astype(jnp.uint8), ((0, 0), (0, L - L0)))
    gen_t = pallas_cycles._pack_words(genp, L).T
    return PackedChunk(st=st, tape_t=tape_t, off_t=off_t, gen_t=gen_t,
                       ivec=ivec, fvec=fvec)


def unpack_chunk(params, pc: PackedChunk):
    """Resident planes -> canonical state (traced; once per chunk).
    restore_ro=True: births updated the kernel-read-only rows
    (genome_len / copied_size / max_executed / inputs) in-plane."""
    st = pc.st
    n, L0 = st.tape.shape
    st = pallas_cycles.unpack_state(
        params, st, (pc.tape_t, pc.off_t, pc.ivec, pc.fvec),
        None, restore_ro=True)
    L = pc.gen_t.shape[0] * 4
    genome = pallas_cycles._unpack_words(pc.gen_t.T, L)[:, :L0]
    return st.replace(genome=genome.astype(jnp.int8))


def _launch(params, planes, key, cap):
    """One kernel launch over the resident planes: pad lanes to the
    shard/block quantum, run, slice back.  At bench scale (102400 cells,
    512-lane blocks) the pad is empty and this is the bare launch."""
    tape_t, off_t, ivec, fvec = planes
    n = tape_t.shape[1]
    shards = pallas_cycles.kernel_shards(params)
    _, n_pad, _ = pallas_cycles._dims(params, n, params.max_memory, shards)
    pad = n_pad - n

    def padl(x):
        return jnp.pad(x, ((0, 0), (0, pad))) if pad else x

    out = pallas_cycles.run_packed(
        params, (padl(tape_t), padl(off_t), padl(ivec), padl(fvec)),
        key, cap)
    if pad:
        out = tuple(o[:, :n] for o in out)
    return out


def update_step_packed(params, pc: PackedChunk, key, neighbors, update_no):
    """One update on resident planes -- the packed mirror of
    ops/update.update_step's phase order (resources -> schedule ->
    [trace_pre] -> kernel -> bank -> birth -> [trace_post]), consuming
    the identical PRNG splits so the trajectory is bit-exact vs the
    per-update path (tests/test_packed_chunk.py).  Returns
    (pc', executed_this_update)."""
    from avida_tpu.ops import update as upd
    IV_GRANTED = pallas_cycles.IV_GRANTED
    IV_INSTS = pallas_cycles.IV_INSTS_EXEC

    k_budget, k_steps, k_birth = jax.random.split(key, 3)

    st = upd.resource_phase(params, pc.st, key, update_no)
    budgets, granted, max_k = upd.schedule_phase(params, st, k_budget)
    del max_k            # the kernel derives its own per-block ceiling
    ivec = pc.ivec.at[IV_GRANTED].set(granted)

    if params.trace_cap:
        st, tsnap = upd.trace_pre_phase(params, st, granted, update_no)

    executed0 = ivec[IV_INSTS]
    tape_t, off_t, ivec, fvec = _launch(
        params, (pc.tape_t, pc.off_t, ivec, pc.fvec), k_steps,
        upd.static_cap(params))

    # bank_phase on rows (same values as ops/update.bank_phase on the
    # unpacked state: insts_executed and alive are ivec-backed)
    executed_this = ivec[IV_INSTS] - executed0
    alive_k = (ivec[pallas_cycles.IV_FLAGS] & pallas_cycles.FLAG_ALIVE) != 0
    carry = jnp.clip(budgets - executed_this, 0,
                     100 * params.ave_time_slice)
    st = st.replace(budget_carry=jnp.where(alive_k, carry, 0))
    executed = executed_this.sum()

    planes, st = birth_ops.flush_births_packed(
        params, st, k_birth, (tape_t, off_t, pc.gen_t, ivec, fvec),
        update_no)

    if params.trace_cap:
        st = upd.trace_post_phase(params, st, tsnap, update_no)

    tape_t, off_t, gen_t, ivec, fvec = planes
    return pc.replace(st=st, tape_t=tape_t, off_t=off_t, gen_t=gen_t,
                      ivec=ivec, fvec=fvec), executed
