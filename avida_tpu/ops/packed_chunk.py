"""Packed-resident update chunk: the round-6 perf tentpole.

The per-update Pallas path (ops/update.interpret_phase -> pallas_cycles.
run_cycles) round-trips the whole population between the canonical
[N, L] PopulationState layout and the kernel's [LP, N] word-plane layout
on EVERY update -- pack + unpack + the [N, L] birth flush were ~13 ms of
the ~31 ms update at bench scale (ROUND5_NOTES).  This module instead
makes the packed layout the RESIDENT representation across a whole
update chunk:

  pack ONCE  ->  scan{ schedule -> kernel launch -> packed birth flush }
             ->  unpack ONCE at the chunk boundary

Chunk boundaries are exactly where the World driver already
synchronizes -- checkpoints, flight-recorder drains, newborn drains and
.dat readbacks all happen between update_scan calls -- so everything
host-visible still sees canonical [N, L] state (tests/
test_native_checkpoint.py, tests/test_tracer.py).

Layout contract: the resident planes are CELL-ordered (identity lane
mapping).  The packed-native birth flush (ops/birth.flush_births_packed)
moves offspring between neighbor cells with lane-axis ROLLS on [LP, N],
which is only meaningful in grid order -- so packed residency SUPERSEDES
the budget-sort lane permutation (TPU_LANE_PERM): ops/update.perm_phase
keeps the identity mapping whenever this path is active, for the
per-update reference path too, keeping the two bit-exact (same kernel
lane assignment => same per-lane PRNG streams).  The budget tail the
permutation used to pack away is attacked inside the kernel instead:
level-1 per-block while_loop early exit + level-2 row-tile skipping
(ops/pallas_cycles.py, TPU_KERNEL_ROWSKIP) and the per-block histogram
attribution in ops/scheduler.py.

The canonical `st` rides along inside PackedChunk as a carrier: its
world-level fields (resources, PRNG-independent tables, trace rings)
stay canonical, its [N, L] planes are stale between boundaries and are
rebuilt by unpack_chunk.  What happens to the per-cell scalar MIRRORS
depends on the sub-path (round 14):

  fused (TPU_PACKED_FUSED=1, default; fused_ineligible_reason):
    schedule/bank/stats run in ROW space directly on the resident
    ivec/fvec planes and the birth flush skips the mirror refresh --
    the scan body never materializes an [N]-vector mirror it does not
    strictly need.  Only the columns unpack_state cannot rebuild
    (birth_update, parent_id, genotype_id, breed_true, budget_carry,
    mating_type, energy_spent) stay canonically maintained; alive /
    merit / gestation_time / generation go stale mid-chunk and are
    rebuilt once at the boundary.
  legacy row-space (TPU_PACKED_FUSED=0, or flight recorder armed):
    the round-6..13 body -- the flush refreshes alive, merit,
    gestation_time, generation (plus heads/mem_len/task_exe_total
    under TPU_TRACE) every update so mid-chunk readers (trace
    emission) see fresh mirrors.

Both bodies consume the identical PRNG splits and write the identical
planes, so trajectories are bit-exact across sub-paths
(tests/test_packed_fused.py).

Second round-14 axis, TPU_PACKED_BITS=1 (default off): the genome
shadow plane drops from byte layout (int32[L/4, N], 4 opcodes/word) to
a 5-bit codec (int32[ceil(L/6), N], 6 opcodes/word) -- a ~34% cut in
that plane's HBM residency.  Only the shadow narrows: the kernel never
reads it, and tape/offspring planes keep the byte layout the kernel's
SWAR decode indexes.  Requires num_insts <= 32 (bits_ineligible_reason
is loud otherwise).

TPU_PACKED_CHUNK=0 disables the path entirely (the per-update
pack/unpack path with lane packing is then exactly the round-5 engine).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from avida_tpu.ops import birth as birth_ops
from avida_tpu.ops import pallas_cycles


class PackedChunk(struct.PyTreeNode):
    """Resident chunk state: canonical carrier + the five planes."""
    st: object               # PopulationState (see module docstring)
    tape_t: jax.Array        # int32[LP, N] opcode word plane
    off_t: jax.Array         # int32[LP, N] extracted-offspring plane
    gen_t: jax.Array         # int32[LP, N] birth-genome plane
    ivec: jax.Array          # int32[NI, N] per-organism scalars
    fvec: jax.Array          # f32[NF, N]  float phenotype scalars


def ineligible_reason(params, nb_ring: bool = False) -> str | None:
    """Why this configuration cannot keep state packed across a chunk
    (None = eligible).  The single spelling of the routing predicate:
    `active` below delegates here, and the multi-world driver
    (parallel/multiworld.py) reports this string in the runlog when a
    batch falls back to the per-update engine, so a fleet operator can
    see WHY a batch is not on the pack-once/unpack-once path.

    `nb_ring`: whether the state carries a non-empty newborn ring
    (systematics records gather newborn genomes row-wise -- a lane-axis
    gather in packed space; run with TPU_SYSTEMATICS=0 for the packed
    path).

    Requirements beyond the kernel's own `eligible`: the torus birth
    fast path (the packed flush is roll-based), asexual, no demes /
    energy / population caps, no point or slip mutations (per-site
    [N, L] sweeps / variable-size region moves stay canonical), no
    resource pools (resource_phase must not read stale planes), and no
    device-side fault injection."""
    from avida_tpu.ops.update import use_pallas_path
    if int(getattr(params, "packed_chunk", 1)) == 0:
        return "TPU_PACKED_CHUNK=0"
    if params.hw_type != 0 or params.max_cpu_threads > 1:
        return "non-heads hardware or multi-threaded CPUs (XLA path)"
    if not use_pallas_path(params):
        return ("Pallas cycle kernel off for this run "
                "(TPU_USE_PALLAS / eligibility / backend)")
    if birth_ops.has_divide_sex(params):
        return "divide-sex instruction set (recombination is canonical)"
    if not birth_ops.local_torus_fast_path(params, sexual=False):
        return ("birth placement off the torus fast path (geometry / "
                "birth method / demes / population caps)")
    if params.point_mut_prob > 0 or params.divide_slip_prob > 0:
        return "point or slip mutations (per-site sweeps stay canonical)"
    if params.num_global_res or params.num_spatial_res \
            or params.num_deme_res:
        return "resource pools (resource_phase reads canonical planes)"
    if getattr(params, "fault_nan", ()) \
            or getattr(params, "fault_bitflip", ()):
        return "device-side fault injection armed (TPU_FAULT nan:/bitflip:)"
    if nb_ring:
        return ("systematics newborn ring in use (TPU_SYSTEMATICS=1; "
                "newborn-record gathers stay canonical)")
    return None


def active(params, st=None) -> bool:
    """Static routing predicate: may this configuration keep state
    packed across a chunk?  Everything here is trace-time (params +
    state SHAPES), so update_scan / update_step / bench all agree.
    See ineligible_reason for the individual gates."""
    return ineligible_reason(
        params, st is not None and st.nb_genome.shape[0] > 0) is None


def batch_active(params, bst) -> bool:
    """`active` for a W-stacked batch state (leading world axis on
    every leaf): the static gates are per-config, so world 0 answers
    for the whole (static-equal) batch."""
    return active(params, jax.tree.map(lambda x: x[0], bst))


def fused_ineligible_reason(params) -> str | None:
    """Why the packed scan body cannot run its cheap phases in ROW
    space (None = eligible; only meaningful when the packed chunk
    itself is active).  Fused means schedule/bank/stats read the
    resident ivec/fvec rows directly and the birth flush skips the
    per-update canonical-mirror refresh, so between chunk boundaries
    the carrier's per-cell mirrors (alive, merit, gestation_time,
    generation) go STALE -- anything that reads them mid-chunk
    disqualifies the path."""
    if int(getattr(params, "packed_fused", 1)) == 0:
        return "TPU_PACKED_FUSED=0"
    if int(getattr(params, "trace_cap", 0)):
        return ("flight recorder armed (TPU_TRACE: trace emission reads "
                "the canonical mirrors mid-chunk)")
    return None


def fused_active(params) -> bool:
    """Static routing predicate for the fused (row-space phases, stale
    mirrors) packed scan body.  Callers must already have checked
    `active` -- this only answers WHICH packed body runs."""
    return fused_ineligible_reason(params) is None


def bits_ineligible_reason(params) -> str | None:
    """Why the genome shadow plane cannot ride the 5-bit codec (None =
    eligible; only meaningful when the packed chunk is active).  The
    codec truncates every stored value to 5 bits, so the whole live
    instruction set must fit."""
    if int(getattr(params, "packed_bits", 0)) == 0:
        return "TPU_PACKED_BITS=0"
    if int(params.num_insts) > 32:
        return ("opcode count > packable width (num_insts=%d does not "
                "fit 5-bit codes)" % int(params.num_insts))
    return None


def bits_active(params) -> bool:
    """Static routing predicate for the 5-bit genome shadow plane."""
    return bits_ineligible_reason(params) is None


def engine_report(params, nb_ring: bool = False) -> dict:
    """One dict describing which packed sub-path this configuration
    routes to -- the vocabulary `MultiWorld._report_engine` journals and
    `--status` prints, so a silent fallback (fused -> legacy row-space,
    bits armed but ineligible) is loud.  Keys:
      engine: 'packed' | 'per-update'   (+ fallback_reason when the
              latter)
      sub_path: 'fused' | 'row-space'   (packed only; + fused_fallback_
              reason when a fused-capable build fell back)
      packed_bits: 0|1 (+ bits_fallback_reason when armed but refused)
    """
    reason = ineligible_reason(params, nb_ring)
    if reason is not None:
        return {"engine": "per-update", "fallback_reason": reason}
    rep = {"engine": "packed"}
    freason = fused_ineligible_reason(params)
    if freason is None:
        rep["sub_path"] = "fused"
    else:
        rep["sub_path"] = "row-space"
        rep["fused_fallback_reason"] = freason
    breason = bits_ineligible_reason(params)
    rep["packed_bits"] = 0 if breason else 1
    if breason and int(getattr(params, "packed_bits", 0)):
        rep["bits_fallback_reason"] = breason    # armed but refused: loud
    return rep


# ---- fused row-space phases (round 14) ----
#
# With the flight recorder off, nothing inside the scan body needs the
# canonical per-cell mirrors: schedule reads alive+merit (ivec flag row,
# fvec merit row), bank reads insts_executed+alive (ivec rows), stats
# reads alive/gestation/generation (ivec rows) + birth_update (a
# canonical column the flush maintains because unpack_state cannot
# rebuild it).  So the fused body runs those phases on the plane rows
# and tells the flush to skip the mirror refresh entirely -- the
# per-update XLA round-trip over the [N]-vector mirrors disappears, and
# the mirrors are rebuilt exactly once at the chunk boundary by
# unpack_chunk.  resource_phase is statically an identity under packed
# eligibility (no global/spatial/deme pools, no gradient rows --
# ineligible_reason gates them all out) and its PRNG is an internal
# fold_in, not one of the update's three splits, so the fused body
# skips it outright; bit-exactness is the existing packed-vs-XLA test
# ladder plus tests/test_packed_fused.py.
#
# Fusing schedule INTO the Pallas kernel was evaluated and rejected:
# budget sampling draws from jax.random's threefry stream
# (slicing methods 1/2), which the kernel's per-lane PRNG cannot
# reproduce bit-exactly, and granted budgets already enter the kernel
# as a plane row (IV_GRANTED) -- there is no boundary crossing left to
# save, only the [N]-elementwise carry/cap math, which XLA fuses into
# the surrounding ops for free.


def alive_rows(ivec):
    """bool[..., N] alive mask straight off the resident flag row --
    the fused path's replacement for the st.alive mirror (elementwise,
    so it serves solo [NI, N] and stacked [NI, W, N] planes alike)."""
    return (ivec[pallas_cycles.IV_FLAGS] & pallas_cycles.FLAG_ALIVE) != 0


def _schedule_rows(params, ivec, fvec, budget_carry, k_budget):
    """schedule_phase in row space: merit-proportional budgets from the
    resident alive/merit rows + the carry/cap grant.  Same spelling as
    ops/update.schedule_phase (via compute_budgets_from /
    schedule_grant), so the sampled budgets are bit-identical to the
    mirror-reading path."""
    from avida_tpu.ops import scheduler as sched_ops
    from avida_tpu.ops import update as upd
    budgets = sched_ops.compute_budgets_from(
        params, alive_rows(ivec), fvec[pallas_cycles.FV_MERIT], k_budget)
    return upd.schedule_grant(params, budgets, budget_carry)


def _stats_vals(ivec, birth_update, update_no):
    """light_stats in row space (ops/update.light_stats_vals over the
    resident rows + the canonical birth_update column the flush keeps
    fresh)."""
    from avida_tpu.ops import update as upd
    return upd.light_stats_vals(
        alive_rows(ivec), ivec[pallas_cycles.IV_GEST_TIME],
        ivec[pallas_cycles.IV_GENERATION], birth_update, update_no)


def stats_rows(pc: PackedChunk, alive_before, update_no):
    """_update_stats for the fused scan body: the per-update host
    bookkeeping tuple (births, deaths, dt, ave_gen, n_alive) computed
    from resident rows instead of the (stale) canonical mirrors."""
    from avida_tpu.ops import update as upd
    return upd._update_stats_from(
        _stats_vals(pc.ivec, pc.st.birth_update, update_no), alive_before)


def stats_rows_worlds(pw: "PackedWorlds", alive_before, update_no):
    """stats_rows over stacked [rows, W, N] planes: vmapped per world
    (ivec world axis is axis 1; the canonical birth_update column and
    alive_before lead with the world axis)."""
    from avida_tpu.ops import update as upd
    return jax.vmap(
        lambda iv, bu, ab, un: upd._update_stats_from(
            _stats_vals(iv, bu, un), ab),
        in_axes=(1, 0, 0, 0),
    )(pw.ivec, pw.bst.birth_update, alive_before, update_no)


def pack_chunk(params, st) -> PackedChunk:
    """Canonical state -> resident planes (traced; once per chunk).
    Identity lane mapping by contract (see module docstring)."""
    n, L0 = st.tape.shape
    quad = pallas_cycles.pack_state(params, st, jnp.zeros(n, jnp.int32),
                                    None, 1)
    tape_t, off_t, ivec, fvec = (p[:, :n] for p in quad)
    L = tape_t.shape[0] * 4
    genp = jnp.pad(st.genome.astype(jnp.uint8), ((0, 0), (0, L - L0)))
    if bits_active(params):
        # 5-bit genome shadow: ceil(L/6) word rows instead of L/4.  The
        # kernel never reads this plane, so only pack/flush/unpack
        # speak the codec.  Lossless because every genome byte is an
        # opcode < num_insts <= 32 (beyond-length bytes are zero by the
        # extraction/injection invariant; tests/test_packed_fused.py
        # checks the round trip on evolved states).
        gen_t = pallas_cycles._pack_words5(genp, L).T
    else:
        gen_t = pallas_cycles._pack_words(genp, L).T
    return PackedChunk(st=st, tape_t=tape_t, off_t=off_t, gen_t=gen_t,
                       ivec=ivec, fvec=fvec)


def unpack_chunk(params, pc: PackedChunk):
    """Resident planes -> canonical state (traced; once per chunk).
    restore_ro=True: births updated the kernel-read-only rows
    (genome_len / copied_size / max_executed / inputs) in-plane."""
    st = pc.st
    n, L0 = st.tape.shape
    st = pallas_cycles.unpack_state(
        params, st, (pc.tape_t, pc.off_t, pc.ivec, pc.fvec),
        None, restore_ro=True)
    L = pc.tape_t.shape[0] * 4      # gen_t rows differ under the codec
    if bits_active(params):
        genome = pallas_cycles._unpack_words5(pc.gen_t.T, L)[:, :L0]
    else:
        genome = pallas_cycles._unpack_words(pc.gen_t.T, L)[:, :L0]
    return st.replace(genome=genome.astype(jnp.int8))


def _launch(params, planes, key, cap):
    """One kernel launch over the resident planes: pad lanes to the
    shard/block quantum, run, slice back.  At bench scale (102400 cells,
    512-lane blocks) the pad is empty and this is the bare launch."""
    tape_t, off_t, ivec, fvec = planes
    n = tape_t.shape[1]
    shards = pallas_cycles.kernel_shards(params)
    _, n_pad, _ = pallas_cycles._dims(params, n, params.max_memory, shards)
    pad = n_pad - n

    def padl(x):
        return jnp.pad(x, ((0, 0), (0, pad))) if pad else x

    out = pallas_cycles.run_packed(
        params, (padl(tape_t), padl(off_t), padl(ivec), padl(fvec)),
        key, cap)
    if pad:
        out = tuple(o[:, :n] for o in out)
    return out


def _bank_rows(params, st, ivec, budgets, executed0):
    """bank_phase in row space -- same values as ops/update.bank_phase
    on the unpacked state (insts_executed and alive are ivec-backed).
    Elementwise, so it serves both the solo [N] and the stacked
    multi-world [W, N] steps from ONE spelling: a change to the carry
    clamp or alive gating cannot break the solo-vs-stacked bit-exactness
    contract.  Returns (st, executed_this); callers reduce
    executed_this over their own lane axes."""
    executed_this = ivec[pallas_cycles.IV_INSTS_EXEC] - executed0
    alive_k = (ivec[pallas_cycles.IV_FLAGS] & pallas_cycles.FLAG_ALIVE) != 0
    carry = jnp.clip(budgets - executed_this, 0,
                     100 * params.ave_time_slice)
    st = st.replace(budget_carry=jnp.where(alive_k, carry, 0))
    return st, executed_this


def update_step_packed(params, pc: PackedChunk, key, neighbors, update_no):
    """One update on resident planes -- the packed mirror of
    ops/update.update_step's phase order (resources -> schedule ->
    [trace_pre] -> kernel -> bank -> birth -> [trace_post]), consuming
    the identical PRNG splits so the trajectory is bit-exact vs the
    per-update path (tests/test_packed_chunk.py).  Returns
    (pc', executed_this_update)."""
    from avida_tpu.ops import update as upd
    IV_GRANTED = pallas_cycles.IV_GRANTED
    IV_INSTS = pallas_cycles.IV_INSTS_EXEC

    k_budget, k_steps, k_birth = jax.random.split(key, 3)

    fused = fused_active(params)
    if fused:
        # row-space schedule straight off the resident planes;
        # resource_phase is statically an identity under packed
        # eligibility and its PRNG is an internal fold_in, so skipping
        # it is bit-exact (see the fused block comment above)
        st = pc.st
        budgets, granted, max_k = _schedule_rows(
            params, pc.ivec, pc.fvec, st.budget_carry, k_budget)
    else:
        st = upd.resource_phase(params, pc.st, key, update_no)
        budgets, granted, max_k = upd.schedule_phase(params, st, k_budget)
    del max_k            # the kernel derives its own per-block ceiling
    ivec = pc.ivec.at[IV_GRANTED].set(granted)

    if params.trace_cap:     # implies not fused (fused_ineligible_reason)
        st, tsnap = upd.trace_pre_phase(params, st, granted, update_no)

    executed0 = ivec[IV_INSTS]
    tape_t, off_t, ivec, fvec = _launch(
        params, (pc.tape_t, pc.off_t, ivec, pc.fvec), k_steps,
        upd.static_cap(params))

    st, executed_this = _bank_rows(params, st, ivec, budgets, executed0)
    executed = executed_this.sum()

    planes, st = birth_ops.flush_births_packed(
        params, st, k_birth, (tape_t, off_t, pc.gen_t, ivec, fvec),
        update_no, fresh_mirrors=not fused)

    if params.trace_cap:
        st = upd.trace_post_phase(params, st, tsnap, update_no)

    tape_t, off_t, gen_t, ivec, fvec = planes
    return pc.replace(st=st, tape_t=tape_t, off_t=off_t, gen_t=gen_t,
                      ivec=ivec, fvec=fvec), executed


# ---- stacked multi-world residency (PR 11 Stage 2) ----
#
# A fleet batch of W static-equal worlds keeps ALL of them resident in
# packed layout for a whole chunk: each plane grows a world axis in the
# middle ([rows, W, N], world-major lanes), the per-update kernel
# launch flattens it onto the lane axis ([rows, W*N] -- one grid, one
# launch, per-world PRNG seed bases; pallas_cycles.run_packed_stacked)
# and the birth flush runs world-blocked (birth_ops.
# flush_births_packed_worlds: every roll stays inside one world's
# plane).  Pack once, scan the chunk, unpack once -- the multi-world
# mirror of PackedChunk, bit-exact per world vs the solo packed scan.


class PackedWorlds(struct.PyTreeNode):
    """Resident multi-world chunk state: batched canonical carrier
    (leading world axis, like MultiWorld's bstate) + the five planes
    with lanes split [rows, W, N]."""
    bst: object              # PopulationState, every leaf [W, ...]
    tape_t: jax.Array        # int32[LP, W, N]
    off_t: jax.Array         # int32[LP, W, N]
    gen_t: jax.Array         # int32[LP, W, N]
    ivec: jax.Array          # int32[NI, W, N]
    fvec: jax.Array          # f32[NF, W, N]


def pack_worlds(params, bst) -> PackedWorlds:
    """Batched canonical state -> stacked resident planes (traced; once
    per chunk).  vmap of pack_chunk with the world axis moved behind
    the row axis, so every plane keeps rows leading (the kernel's
    sublane dimension) and worlds contiguous on lanes."""
    pc = jax.vmap(lambda st: pack_chunk(params, st))(bst)

    def mv(x):
        return jnp.moveaxis(x, 0, 1)

    return PackedWorlds(bst=pc.st, tape_t=mv(pc.tape_t),
                        off_t=mv(pc.off_t), gen_t=mv(pc.gen_t),
                        ivec=mv(pc.ivec), fvec=mv(pc.fvec))


def unpack_worlds(params, pw: PackedWorlds):
    """Stacked resident planes -> batched canonical state (traced; once
    per chunk) -- the inverse of pack_worlds."""
    def mv(x):
        return jnp.moveaxis(x, 1, 0)

    pc = PackedChunk(st=pw.bst, tape_t=mv(pw.tape_t), off_t=mv(pw.off_t),
                     gen_t=mv(pw.gen_t), ivec=mv(pw.ivec),
                     fvec=mv(pw.fvec))
    return jax.vmap(lambda p: unpack_chunk(params, p))(pc)


def _launch_worlds(params, planes, seeds, cap):
    """One stacked kernel launch over W worlds' resident planes: pad
    each world's lanes to the block quantum, flatten the world axis
    onto lanes (world-major -- blocks never straddle worlds), launch,
    slice back."""
    from avida_tpu.ops import pallas_cycles as pc
    n = planes[0].shape[2]
    W = planes[0].shape[1]
    B, n_pad, _ = pc._dims(params, n, params.max_memory, 1)
    pad = n_pad - n

    def flat(x):
        if pad:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, pad)))
        return x.reshape(x.shape[0], W * n_pad)

    out = pc.run_packed_stacked(params, tuple(flat(x) for x in planes),
                                seeds, cap, B)
    return tuple(o.reshape(o.shape[0], W, n_pad)[:, :, :n] for o in out)


def update_step_packed_worlds(params, pw: PackedWorlds, keys, neighbors,
                              update_no):
    """One update for W worlds on stacked resident planes -- the
    multi-world mirror of update_step_packed, phase for phase, with the
    cheap phases vmapped over the world axis and the kernel cycle loop
    run as ONE stacked launch.  Consumes each world's solo PRNG splits
    exactly (split per world, randint seed per world, flush key per
    world), so each world is bit-exact vs its solo packed scan.
    `update_no` is scalar (aligned batch) or [W] (each world its own
    counter -- the dynamic serving batch); either way every phase sees
    its own world's update number.  Returns
    (pw', executed[W], trips[W])."""
    from avida_tpu.ops import update as upd
    IV_GRANTED = pallas_cycles.IV_GRANTED
    IV_INSTS = pallas_cycles.IV_INSTS_EXEC

    ks = jax.vmap(lambda k: jax.random.split(k, 3))(keys)
    k_budget, k_steps, k_birth = ks[:, 0], ks[:, 1], ks[:, 2]
    update_no = jnp.broadcast_to(jnp.asarray(update_no, jnp.int32),
                                 (pw.bst.alive.shape[0],))

    fused = fused_active(params)
    if fused:
        st = pw.bst
        budgets, granted, max_k = jax.vmap(
            lambda iv, fv, bc, k: _schedule_rows(params, iv, fv, bc, k),
            in_axes=(1, 1, 0, 0),
        )(pw.ivec, pw.fvec, st.budget_carry, k_budget)
    else:
        st = jax.vmap(
            lambda s, k, un: upd.resource_phase(params, s, k, un)
        )(pw.bst, keys, update_no)
        budgets, granted, max_k = jax.vmap(
            lambda s, k: upd.schedule_phase(params, s, k))(st, k_budget)
    ivec = pw.ivec.at[IV_GRANTED].set(granted)

    if params.trace_cap:
        st, tsnap = jax.vmap(
            lambda s, g, un: upd.trace_pre_phase(params, s, g, un)
        )(st, granted, update_no)

    executed0 = ivec[IV_INSTS]
    seeds = pallas_cycles.world_seed_bases(k_steps)
    tape_t, off_t, ivec, fvec = _launch_worlds(
        params, (pw.tape_t, pw.off_t, ivec, pw.fvec), seeds,
        upd.static_cap(params))

    st, executed_this = _bank_rows(params, st, ivec, budgets, executed0)
    executed = executed_this.sum(axis=1)

    planes, st = birth_ops.flush_births_packed_worlds(
        params, st, k_birth, (tape_t, off_t, pw.gen_t, ivec, fvec),
        update_no, fresh_mirrors=not fused)

    if params.trace_cap:
        st = jax.vmap(
            lambda s, sn, un: upd.trace_post_phase(params, s, sn, un)
        )(st, tsnap, update_no)

    tape_t, off_t, gen_t, ivec, fvec = planes
    return pw.replace(bst=st, tape_t=tape_t, off_t=off_t, gen_t=gen_t,
                      ivec=ivec, fvec=fvec), executed, max_k
