"""Pallas TPU kernel: a whole update's micro-cycles in one kernel launch.

This is the performance core of the framework.  The XLA lockstep path
(ops/interpreter.micro_step inside ops/update.update_step's while_loop)
round-trips every [N, L] plane through HBM on every CPU cycle; at 100k
organisms that costs ~1.7 ms/cycle and caps throughput far below the 1e8
org-inst/s target.  This kernel instead runs ALL K cycles of an update for a
block of B organisms with every byte of their state resident in VMEM:

  HBM traffic per update  = 2 x state size        (one load, one store)
  per-cycle work          = VMEM-resident VPU ops only

Layout: organisms live on the LANE dimension (128-wide) --
  tape_t : int32[L/4, N] opcode planes, 4 consecutive positions packed per
                         word (byte j of word w = position 4w+j; 6-bit
                         opcodes ONLY -- executed/copied site flags live in
                         packed int32 bitplanes inside ivec, 1 bit/site).
                         Every tape pass is SWAR over 4x fewer elements
                         than the v2 byte layout (the round-5 rewrite).
  off_t  : int32[L/4, N] extracted-offspring planes, same packing
  ivec   : int32[NI, N]  every int32 per-organism scalar, one row each
  fvec   : f32[NF, N]    float phenotype scalars
so per-organism scalars are [1, B] lane vectors (4 vregs at B=512) and the
tape reductions reduce over sublanes, producing lane vectors directly --
no orientation changes anywhere in the cycle body.

Design notes (v2 -- the round-4 performance rewrite):

* ONE merged tape traversal per cycle.  The only tape mutations are the
  h-copy byte at the write head and the h-alloc zone zeroing; both are
  DEFERRED one cycle (pending-write / pending-zero ivec rows) and applied
  at the start of the next cycle's read traversal, collapsing the separate
  read and write passes of v1 into a single load-apply-store-extract pass.
  Deferral is semantically exact: within a cycle nothing reads the byte an
  h-copy just wrote, and reads in later cycles see it applied.

* Site flags as bitplanes.  cCPUMemory's per-site executed/copied flags are
  int32 bitmasks ([L/32, B] rows in ivec) instead of tape bits 6/7.  Flag
  set/clear is a handful of [LW, B] ops, and the divide-viability counts
  (Divide_CheckViable, cHardwareBase.cc:140) are masked popcounts over the
  bitplanes -- v1's gated whole-tape zone pass is gone.

* Eager-5 label window.  The per-cycle traversal packs only the first 5
  label positions (one int32 accumulator); the full MAX_LABEL_SIZE=10
  window runs as a gated second pass only when some lane is actually
  executing a label instruction whose first 5 window slots are all nops --
  rare in practice (real labels are 1-3 nops).

* In-kernel offspring extraction.  At h-divide, the offspring sequence
  [read-head, write-head) is extracted into the off_t plane by a gated
  per-lane barrel roll (log2(L) conditional sublane rotations), so the
  birth flush never pays the [N, L] lane-axis shift that dominated it.
  off_t is persistent state (PopulationState.off_tape): a parent whose
  placement lost a conflict retries from it next update.

* Per-block budget stop.  Each block's internal while_loop runs only to
  the max granted budget of ITS organisms.  Budget-aware lane packing
  (TPU_LANE_PERM; run_cycles + ops/update.perm_phase) permutes organisms
  into budget-sorted lanes via major-axis row gathers in pack/unpack,
  cutting the per-block max from ~1.55x to ~1.03x of the mean without
  the lane-axis packed-state permute that was reverted in rounds 4/5.

* Sharded launches.  Blocks never communicate, so the launch splits into
  one shard_map shard per device over the `cells` mesh axis (run_packed;
  TPU_KERNEL_SHARDS) with per-shard PRNG seed bases keeping the sharded
  trajectory bit-identical to the unsharded one.  This is what makes the
  kernel the fast path on multi-chip meshes -- pallas_call itself has no
  GSPMD partitioning rule.

Semantics are the heads hardware exactly as ops/interpreter.micro_step
implements it (same reference citations apply, cHardwareCPU.cc:908-1079);
the only divergences are (a) the PRNG stream (pltpu.prng_random_bits
instead of threefry -- RNG parity is impossible anyway, SURVEY.md §7 hard
part 5) and (b) the fast path precondition below.

Fast-path precondition (`eligible(params)`): reactions must not bind
resources (stock logic-9 qualifies: all processes are infinite-resource).
Then the cycle loop is per-organism pure and blocks are independent, so the
kernel needs no cross-block communication.  Resource-bound environments fall
back to the XLA path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from avida_tpu.models.heads import (
    MOD_HEAD, MOD_LABEL, MOD_NONE, MOD_REG,
    SEM_ADD, SEM_DEC, SEM_GET_HEAD, SEM_H_ALLOC, SEM_H_COPY, SEM_H_DIVIDE,
    SEM_H_DIVIDE_SEX,
    SEM_H_SEARCH, SEM_IF_LABEL, SEM_IF_LESS, SEM_IF_N_EQU, SEM_INC, SEM_IO,
    SEM_JMP_HEAD, SEM_MOV_HEAD, SEM_NAND, SEM_POP, SEM_PUSH, SEM_SET_FLOW,
    SEM_SHIFT_L, SEM_SHIFT_R, SEM_SUB, SEM_SWAP, SEM_SWAP_STK,
    HEAD_IP, HEAD_READ, HEAD_WRITE, HEAD_FLOW, MAX_LABEL_SIZE,
)

# ---- ivec row layout (fixed rows; the bitplane/dyn tail is L/R-dependent,
# see _layout) ----
IV_MEM_LEN = 0
IV_ACTIVE_STACK = 1
IV_READ_LABEL_LEN = 2
IV_INPUT_PTR = 3
IV_INPUT_BUF_N = 4
IV_OUTPUT_BUF = 5
IV_TIME_USED = 6
IV_CPU_CYCLES = 7
IV_GEST_START = 8
IV_GEST_TIME = 9
IV_EXEC_SIZE = 10
IV_CHILD_COPIED = 11
IV_GENERATION = 12
IV_NUM_DIVIDES = 13
IV_OFF_START = 14
IV_OFF_LEN = 15
IV_OFF_COPIED = 16
IV_INSTS_EXEC = 17
IV_FLAGS = 18            # bit0 mal_active, bit1 alive, bit2 divide_pending
IV_GENOME_LEN = 19       # ro
IV_MAX_EXEC = 20         # ro
IV_GRANTED = 21          # ro
IV_COPIED_SIZE = 22      # ro (merit calc input)
IV_REGS = 23             # 3 rows
IV_HEADS = 26            # 4 rows
IV_SP = 30               # 2 rows
IV_INPUT_BUF = 32        # 3 rows
IV_INPUTS = 35           # 3 rows, ro
IV_READ_LABEL = 38       # 10 rows
IV_STACKS = 48           # 20 rows (stack-major: stack*10 + depth)
IV_PW_POS = 68           # deferred h-copy write: position (-1 = none)
IV_PW_VAL = 69           # deferred h-copy write: opcode
IV_PZ_START = 70         # deferred zero range [start, end) (alloc zone)
IV_PZ_END = 71
IV_COST_WAIT = 72        # cost-engine cycles owed (SingleProcess_PayPreCosts)
IV_FT_LO = 73            # one-time ft_cost paid bitmask, opcodes 0-31
IV_FT_HI = 74            # opcodes 32-63
IV_OFF_SEX = 75          # offspring awaits a mate (divide-sex)
IV_EXEC_BM = 76          # LW rows: executed-site bitplane (LW = L/32)
# COPIED_BM at IV_EXEC_BM + LW; task/reaction rows at IV_EXEC_BM + 2*LW

FV_MERIT = 0
FV_CUR_BONUS = 1
FV_FITNESS = 2
FV_LAST_BONUS = 3
FV_LAST_MERIT_BASE = 4
NF = 8

FLAG_MAL, FLAG_ALIVE, FLAG_DIVPEND, FLAG_STERILE = 1, 2, 4, 8
# kernel-internal: lane divided during THIS launch (offspring extraction
# runs once post-loop -- the divided parent stalls, so its child region in
# the tape is frozen until then); never escapes to PopulationState
FLAG_NEWDIV = 16

DEFAULT_BLOCK = 512
CHUNK = 64           # sublane rows per register-resident traversal chunk

# Debug/profiling knob: comma-separated feature names whose kernel code is
# compiled OUT (semantics break!) to measure their cost by ablation, e.g.
# TPU_KERNEL_ABLATE=search,extract python -m avida_tpu.observability.harness
import os as _os
_ABLATE = frozenset(
    f for f in _os.environ.get("TPU_KERNEL_ABLATE", "").split(",") if f)

# Round-6 kernel scheduling knobs (A/B escape hatches; both default ON and
# both are SEMANTICALLY EXACT -- flipping them changes performance only):
#
# TPU_KERNEL_ROWSKIP=0 disables two-level scheduling's row-tile skip: the
# per-cycle tape traversals (merged read/apply pass + h-search scan) run
# over all LP word rows again instead of stopping at the live extent of
# the lanes still executing.
# TPU_TASKS_UNCOND=0 restores the jnp.any(io_m) cond around the task
# pipeline (ROUND5 item 3: at steady state some lane in a 512-wide block
# does IO nearly every cycle, so the cond fired ~always and its barrier
# cost more than the row ops it guarded).
_ROWSKIP = _os.environ.get("TPU_KERNEL_ROWSKIP", "1") != "0"
_TASKS_UNCOND = _os.environ.get("TPU_TASKS_UNCOND", "1") != "0"
# two-level traversal tile height in word rows: divides CHUNK, and LP is
# always a CHUNK multiple (_dims pads L), so tiles never straddle the end
TCH = 16


def eligible(params) -> bool:
    """True when the per-organism fast path is semantically exact: no
    reaction binds a resource (every process is infinite-resource), so one
    update's cycles never couple organisms through shared pools, and the
    instruction set contains no semantics the kernel doesn't implement.

    Round 5 widened the kernel to cover instruction costs (cost/ft_cost/
    prob_fail/addl_time_cost engines), redundancy-weighted mutation draws,
    and divide-sex (the kernel records the off_sex flag; pairing and
    recombination stay in the birth flush).  Remaining exclusions: the
    energy model, reaction by-products, math-family tasks, and
    resource-bound reactions."""
    if params.max_cpu_threads > 1:
        return False     # intra-organism threads run on the XLA path
    from avida_tpu.models.heads import (SEM_FORK_TH, SEM_ID_TH,
                                        SEM_IF_MATE_FEMALE,
                                        SEM_IF_MATE_MALE, SEM_KILL_TH,
                                        SEM_SET_MATE_FEMALE,
                                        SEM_SET_MATE_JUV,
                                        SEM_SET_MATE_MALE)
    if any(int(s) in (SEM_FORK_TH, SEM_KILL_TH, SEM_ID_TH,
                      SEM_SET_MATE_MALE, SEM_SET_MATE_FEMALE,
                      SEM_SET_MATE_JUV, SEM_IF_MATE_MALE,
                      SEM_IF_MATE_FEMALE)
           for s in params.sem):
        return False     # thread and mating-type instructions exist only
        #                  in the XLA interpreter
    if params.energy_enabled:
        return False     # energy store/merit not implemented in-kernel
    if any(pi >= 0 for pi in getattr(params, "proc_product_idx", ())):
        return False     # by-products couple organisms through pools
    if any(getattr(params, "task_math_name", ())):
        return False     # in-kernel reactions evaluate logic ids only
    return all(r < 0 for r in params.proc_res_idx)


def _layout(params, L):
    """(NI, LW, iv_copied_bm, iv_dyn) for a CHUNK-padded tape height L."""
    LW = L // 32
    iv_copied = IV_EXEC_BM + LW
    iv_dyn = IV_EXEC_BM + 2 * LW
    R = params.num_reactions
    ni = iv_dyn + 4 * R          # cur_task, cur_reaction, last_task, exe_total
    ni = (ni + 7) & ~7           # sublane-pad
    return ni, LW, iv_copied, iv_dyn


def _sel_table(op, table):
    """table[op] for a [1,B] opcode vector via a static select chain (no
    vector gather on TPU; the table is a trace-time tuple)."""
    out = jnp.zeros_like(op)
    for k, v in enumerate(table):
        if v:
            out = jnp.where(op == k, jnp.int32(int(v)), out)
    return out


def _fsel_table(op, table):
    """Float variant of _sel_table."""
    out = jnp.zeros(op.shape, jnp.float32)
    for k, v in enumerate(table):
        if v:
            out = jnp.where(op == k, jnp.float32(float(v)), out)
    return out


def _bitmask_lookup(op, bits):
    """bits[op] for a boolean table packed into two int32 masks (variable
    per-lane shift -- O(1) in table size)."""
    lo = 0
    hi = 0
    for k, b in enumerate(bits):
        if b:
            if k < 32:
                lo |= 1 << k
            else:
                hi |= 1 << (k - 32)
    lo_v = jnp.right_shift(jnp.uint32(lo),
                           jnp.clip(op, 0, 31).astype(jnp.uint32)) & 1
    if hi:
        hi_v = jnp.right_shift(jnp.uint32(hi),
                               jnp.clip(op - 32, 0, 31).astype(jnp.uint32)) & 1
        return jnp.where(op < 32, lo_v, hi_v) == 1
    return jnp.where(op < 32, lo_v, jnp.uint32(0)) == 1


def _multibit_lookup(op, table, nbits):
    """table[op] (values < 2**nbits) for a [1,B] opcode vector via per-bit
    packed masks and variable shifts: nbits x ~4 ops instead of a
    len(table) x 2 select chain."""
    opc = jnp.clip(op, 0, 31).astype(jnp.uint32)
    oph = jnp.clip(op - 32, 0, 31).astype(jnp.uint32)
    two_words = len(table) > 32
    out = jnp.zeros_like(op)
    for b in range(nbits):
        lo = 0
        hi = 0
        for k, v in enumerate(table):
            if (int(v) >> b) & 1:
                if k < 32:
                    lo |= 1 << k
                else:
                    hi |= 1 << (k - 32)
        if not (lo or hi):
            continue
        bit = (jnp.uint32(lo) >> opc) & 1
        if two_words:
            # hi == 0 must still force the bit to 0 for op >= 32 (the lo
            # lookup above clipped op to 31 and would leak inst 31's bit)
            bit = jnp.where(op < 32, bit, (jnp.uint32(hi) >> oph) & 1)
        out = out | (bit << b).astype(jnp.int32)
    return out


def _popcount32(x):
    # unsigned SWAR popcount (int32 inputs may carry bit 31; arithmetic
    # shifts would smear it, so everything runs in uint32)
    x = x.astype(jnp.uint32)
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return ((x * jnp.uint32(0x01010101)) >> 24).astype(jnp.int32)


def _word_range_mask(lw_rows, lo, hi):
    """int32[LW, B] bitmask selecting bit positions [lo, hi) of the
    L-bit-long per-lane bitplane (lo/hi are [1, B] site indices)."""
    base = lw_rows * 32
    lo_w = jnp.clip(lo - base, 0, 32)
    hi_w = jnp.clip(hi - base, 0, 32)
    full = jnp.int32(-1)
    m_lo = jnp.where(lo_w >= 32, 0,
                     full << jnp.minimum(lo_w, 31).astype(jnp.uint32))
    m_hi = jnp.where(hi_w >= 32, 0,
                     full << jnp.minimum(hi_w, 31).astype(jnp.uint32))
    return m_lo & ~m_hi


def _set_bit(bm, lw_rows, pos, cond):
    """Set bit `pos` ([1,B]) in the [LW,B] bitplane where cond ([1,B])."""
    bit = (jnp.int32(1) << (pos & 31).astype(jnp.uint32))
    hit = (lw_rows == (pos >> 5)) & cond
    return bm | jnp.where(hit, bit, 0)


def _read_bit(bm, lw_rows, pos):
    """Bit `pos` ([1,B]) of the [LW,B] bitplane -> bool[1,B]."""
    word = jnp.sum(jnp.where(lw_rows == (pos >> 5), bm, 0),
                   axis=0, keepdims=True)
    return ((word.astype(jnp.uint32) >> (pos & 31).astype(jnp.uint32))
            & 1) != 0


def _logic_id(i0, i1, i2, n_in, output):
    """Port of tasks.compute_logic_id on [1,B] lane vectors using SWAR
    popcounts instead of a [N,32,8] truth-table tensor (cTaskLib.cc:369)."""
    lo_bits = []
    ok = None
    for c in range(8):
        m0 = i0 if (c & 1) else ~i0
        m1 = i1 if (c & 2) else ~i1
        m2 = i2 if (c & 4) else ~i2
        mask = m0 & m1 & m2
        cnt = _popcount32(mask)
        ones = _popcount32(mask & output)
        consistent = (ones == 0) | (ones == cnt)
        ok = consistent if ok is None else (ok & consistent)
        lo_bits.append((ones > 0).astype(jnp.int32))
    # fill rules for missing inputs (cTaskLib.cc:419-433)
    lo_bits[1] = jnp.where(n_in < 1, lo_bits[0], lo_bits[1])
    lo_bits[2] = jnp.where(n_in < 2, lo_bits[0], lo_bits[2])
    lo_bits[3] = jnp.where(n_in < 2, lo_bits[1], lo_bits[3])
    for c in range(4):
        lo_bits[4 + c] = jnp.where(n_in < 3, lo_bits[c], lo_bits[4 + c])
    logic = sum(lo_bits[c] << c for c in range(8))
    return jnp.where(ok, logic, -1)


def _task_performed(lid, logic_mask_row):
    """logic_mask_row[lid] where logic_mask_row is a static bool[256]:
    pack into 8 int32 words, select word by lid>>5, shift by lid&31."""
    words = []
    for w in range(8):
        word = 0
        for b in range(32):
            if logic_mask_row[w * 32 + b]:
                word |= 1 << b
        words.append(word)
    widx = lid >> 5
    word_v = jnp.zeros_like(lid, dtype=jnp.uint32)
    for w, word in enumerate(words):
        if word:
            word_v = jnp.where(widx == w, jnp.uint32(word), word_v)
    return (jnp.right_shift(word_v, (lid & 31).astype(jnp.uint32)) & 1) == 1


def _make_kernel(params, L, B, num_steps, interpret=False):
    """Build the kernel body (params/L/B/num_steps are trace-time consts).

    L is the CHUNK-padded tape height; semantic memory limits (h-alloc
    growth cap, h-divide max offspring size) use the TRUE configured
    max_memory so padding never changes physics."""
    L0 = params.max_memory
    LP = L // 4              # packed tape height: 4 opcode bytes per int32
    R = params.num_reactions
    NI, LW, IV_COPIED_BM, IV_DYN = _layout(params, L)
    num_insts = params.num_insts
    sem_tab = params.sem
    mod_tab = params.mod_kind
    def_tab = params.default_op
    nop_tab = params.is_nop
    nmod_tab = params.nop_mod
    # default-layout fast path: nops are opcodes 0..2 with identity mods,
    # turning every nop lookup into a single compare
    nops_prefix = (all(bool(nop_tab[k]) == (k < 3) for k in range(num_insts))
                   and tuple(int(x) for x in nmod_tab[:3]) == (0, 1, 2))
    # packed-metadata lookup: meta = sem | mod_kind<<5 | default_op<<7
    meta_tab = tuple((int(sem_tab[k]) | (int(mod_tab[k]) << 5)
                      | (int(def_tab[k]) << 7)) for k in range(num_insts))
    fdt = jnp.float32

    def adjust(pos, mlen):
        # cHeadCPU::fullAdjust: negative -> 0, >= len wraps modulo
        return jnp.where(pos < 0, 0, pos % mlen)

    def adjust1(pos, mlen):
        # cheap adjust for pos guaranteed in [0, 2*mlen)
        return jnp.where(pos >= mlen, pos - mlen, pos)

    def kernel(seed_ref, tape_in, off_in, ivec_in, fvec_in,
               tape_ref, off_ref, ivec_ref, fvec_ref):
        # work entirely on the (aliased) output blocks: copy once, mutate
        # in VMEM across all cycles, write-back handled by the pipeline
        tape_ref[...] = tape_in[...]
        off_ref[...] = off_in[...]
        ivec_ref[...] = ivec_in[...]
        fvec_ref[...] = fvec_in[...]
        if (params.copy_mut_prob > 0 or params.inst_prob_fail) \
                and not interpret:
            # seed_ref is block-mapped (BlockSpec (1,) over the per-block
            # seed vector): the host bakes the block's global offset --
            # and, for a stacked multi-world launch (run_packed_stacked),
            # the block's WORLD seed base -- into seed_ref[0], so the
            # kernel body needs no program_id arithmetic
            pltpu.prng_seed(seed_ref[0])

        granted = ivec_ref[IV_GRANTED, :][None, :]
        # index planes (built in-kernel: closure constants are not allowed)
        wrows = jax.lax.broadcasted_iota(jnp.int32, (LP, B), 0)
        reg_rows = jax.lax.broadcasted_iota(jnp.int32, (3, B), 0)
        head_rows = jax.lax.broadcasted_iota(jnp.int32, (4, B), 0)
        stk_rows = jax.lax.broadcasted_iota(jnp.int32, (20, B), 0)
        lw_rows = jax.lax.broadcasted_iota(jnp.int32, (LW, B), 0)

        def bytemask(m):
            """Mask of the m lowest bytes of an int32 word, m in [0, 4]."""
            r = jnp.where(m <= 0, 0, 0xFF)
            r = jnp.where(m >= 2, 0xFFFF, r)
            r = jnp.where(m >= 3, 0xFFFFFF, r)
            return jnp.where(m >= 4, -1, r)

        def apply_pending(tc, wrows_c, pw_pos, pw_val, pz_s, pz_e):
            # deferred h-copy byte write (pw_pos = -1 when none: -1 >> 2
            # = -1 matches no word row)
            sh = (pw_pos & 3) * 8
            tc = jnp.where(wrows_c == (pw_pos >> 2),
                           (tc & ~(255 << sh)) | (pw_val << sh), tc)
            # deferred h-alloc zeroing of byte range [pz_s, pz_e)
            lo = jnp.clip(pz_s - wrows_c * 4, 0, 4)
            hi = jnp.clip(pz_e - wrows_c * 4, 0, 4)
            return tc & ~(bytemask(hi) & ~bytemask(lo))

        def cycle_body(s, _):
            def u24(tag):
                """24 random bits per lane as int32 [1, B].  On TPU:
                the stateful hardware PRNG (uint32 -> f32 casts are
                unsupported in Mosaic; the top 24 bits fit an int32
                exactly).  In interpret mode (CPU tests): a counter-based
                splitmix-style hash of (seed, block, cycle, lane, tag) --
                pltpu.prng_* has no CPU lowering.  seed_ref[0] is the
                block-mapped per-block seed; for SOLO launches the host
                passes the same seed to every interpret-mode block (the
                historical stream, kept so recorded trajectories stay
                valid), while a stacked multi-world launch passes each
                world its own seed base."""
                if not interpret:
                    b = pltpu.bitcast(pltpu.prng_random_bits((1, B)),
                                      jnp.uint32)
                    return (b[0, :][None, :] >> 8).astype(jnp.int32)
                # (no pl.program_id here: the hlo interpreter lacks a
                # CPU lowering for it; blocks share the stream pattern,
                # which is fine for the test-only interpret mode)
                x = (seed_ref[0]
                     + s * jnp.int32(-1640531527) + tag * 40503
                     + jax.lax.broadcasted_iota(jnp.int32, (1, B), 1))
                x = (x ^ ((x >> 16) & 0xFFFF)) * jnp.int32(0x45d9f3b)
                x = (x ^ ((x >> 16) & 0xFFFF)) * jnp.int32(0x45d9f3b)
                x = x ^ ((x >> 16) & 0xFFFF)
                return x & 0xFFFFFF

            mlen = jnp.maximum(ivec_ref[IV_MEM_LEN, :][None, :], 1)
            flags = ivec_ref[IV_FLAGS, :][None, :]
            alive = (flags & FLAG_ALIVE) != 0
            mal_active = (flags & FLAG_MAL) != 0
            divide_pending = (flags & FLAG_DIVPEND) != 0
            exec_mask = alive & (s < granted) & ~divide_pending

            # heads are maintained in [0, mlen) by every writer (division
            # resets to 0, advances use adjust1, jumps use adjust), so the
            # per-read re-adjust of the XLA path is a provable no-op here
            heads = ivec_ref[pl.ds(IV_HEADS, 4), :]           # [4, B]
            ip = heads[HEAD_IP, :][None, :]
            rp = heads[HEAD_READ, :][None, :]
            wp = heads[HEAD_WRITE, :][None, :]
            parent_size = rp
            child_end = jnp.where(wp == 0, mlen, wp)
            child_size = child_end - parent_size

            pw_pos = ivec_ref[IV_PW_POS, :][None, :]
            pw_val = ivec_ref[IV_PW_VAL, :][None, :]
            pz_s = ivec_ref[IV_PZ_START, :][None, :]
            pz_e = ivec_ref[IV_PZ_END, :][None, :]

            # ---- THE merged traversal (packed words, one chunk pass at
            # bench L): apply last cycle's deferred tape writes, store, and
            # lift the per-cycle single-word reads into [1, B] lane vectors
            # via masked sums.  The words collected: the IP word, the
            # READ-head word, and the 4 words spanning the 10-byte label
            # window base (ip+1); the wrap-around window tail lives in
            # words 0-2, read directly after the store. ----
            #
            # Two-level scheduling, level 2 (TPU_KERNEL_ROWSKIP): level 1
            # is the per-block while_loop stopping at the block's max
            # granted budget; level 2 bounds each cycle's traversals to
            # the word rows any lane still NEEDS -- the live memory
            # extent of budget-unexhausted lanes plus the deferred-write
            # reach of every lane.  Lanes whose budget is exhausted stop
            # constraining the bound, so whole TCH-row tiles above it are
            # skipped (their loads, stores and masked sums never issue).
            # Semantically exact: every consumer of a masked lane's tape
            # bytes is already exec-gated, and pending writes/zeroes are
            # covered by pend_b (an exhausted lane's final deferred write
            # still lands the cycle after its last execution).  Each
            # tile's work runs under a scalar predicate -- pl.when for
            # the apply/store pass, a value-returning lax.cond (with ref
            # reads) for the sums; both constructs are long-proven in
            # this kernel.
            ipw = ip >> 2
            rpw = rp >> 2
            labw = (ip + 1) >> 2
            if _ROWSKIP:
                need_b = jnp.max(jnp.where(exec_mask, mlen, 1))
                pend_b = jnp.maximum(jnp.max(pw_pos + 1), jnp.max(pz_e))
                bound_w = (jnp.maximum(need_b, pend_b) + 3) >> 2
            else:
                bound_w = None
            TRAV = TCH if _ROWSKIP else CHUNK
            w_ip = jnp.zeros((1, B), jnp.int32)
            w_rp = jnp.zeros((1, B), jnp.int32)
            w_lab = [jnp.zeros((1, B), jnp.int32) for _ in range(4)]
            for c in range(0, LP, TRAV):
                cn = min(TRAV, LP - c)

                def _tile_sums(_, c=c, cn=cn):
                    # reads the POST-store tile: pending already applied,
                    # same values the pre-store accumulation saw
                    tc = tape_ref[pl.ds(c, cn), :]
                    wr = jax.lax.broadcasted_iota(
                        jnp.int32, (cn, B), 0) + c
                    return tuple(
                        jnp.sum(jnp.where(wr == w, tc, 0), axis=0,
                                keepdims=True)
                        for w in (ipw, rpw, labw, labw + 1, labw + 2,
                                  labw + 3))

                if _ROWSKIP:
                    needed = bound_w > c

                    @pl.when(needed)
                    def _apply_tile(c=c, cn=cn):
                        tc = tape_ref[pl.ds(c, cn), :]
                        wr = jax.lax.broadcasted_iota(
                            jnp.int32, (cn, B), 0) + c
                        tape_ref[pl.ds(c, cn), :] = apply_pending(
                            tc, wr, pw_pos, pw_val, pz_s, pz_e)

                    sums = jax.lax.cond(
                        needed, _tile_sums,
                        lambda _: tuple(jnp.zeros((1, B), jnp.int32)
                                        for _ in range(6)), None)
                else:
                    tc = tape_ref[pl.ds(c, cn), :]
                    wrows_c = jax.lax.broadcasted_iota(
                        jnp.int32, (cn, B), 0) + c
                    tape_ref[pl.ds(c, cn), :] = apply_pending(
                        tc, wrows_c, pw_pos, pw_val, pz_s, pz_e)
                    sums = _tile_sums(None)
                w_ip = w_ip + sums[0]
                w_rp = w_rp + sums[1]
                for j in range(4):
                    w_lab[j] = w_lab[j] + sums[2 + j]
            # wrap words for the label window (post-store = pending applied)
            w_wrap = [tape_ref[w, :][None, :] for w in range(3)]

            s_ip = (w_ip >> ((ip & 3) * 8)) & 63
            s_rp = (w_rp >> ((rp & 3) * 8)) & 63

            # label-window bytes k = 0..9 at positions (ip+1+k) mod mlen;
            # slot 0 doubles as the operand byte
            lab_bytes = []
            for k in range(MAX_LABEL_SIZE):
                p = ip + 1 + k
                wrapped = p >= mlen
                pa = p - jnp.where(wrapped, mlen, 0)
                ws = pa >> 2
                w = jnp.where(ws == labw + 1, w_lab[1],
                              jnp.where(ws == labw + 2, w_lab[2],
                                        jnp.where(ws == labw + 3, w_lab[3],
                                                  w_lab[0])))
                wv = jnp.where(ws == 1, w_wrap[1],
                               jnp.where(ws == 2, w_wrap[2], w_wrap[0]))
                w = jnp.where(wrapped, wv, w)
                lab_bytes.append((w >> ((pa & 3) * 8)) & 63)
            s_ip1 = lab_bytes[0]

            cur_op = jnp.clip(s_ip, 0, num_insts - 1)
            ebm = ivec_ref[pl.ds(IV_EXEC_BM, LW), :]          # [LW, B]
            cbm = ivec_ref[pl.ds(IV_COPIED_BM, LW), :]        # [LW, B]
            ip_exec_already = _read_bit(ebm, lw_rows, ip)
            meta = _multibit_lookup(cur_op, meta_tab, 9)

            # ---- instruction cost engine (SingleProcess_PayPreCosts,
            # cHardwareBase.cc:1241; same semantics as the XLA
            # interpreter): cost c consumes c cycles executing on the
            # last, ft_cost adds a one-time per-opcode surcharge ----
            has_costs = bool(params.inst_cost) or bool(params.inst_ft_cost)
            if has_costs:
                cost_op = _sel_table(
                    cur_op, params.inst_cost or (0,) * num_insts)
                ftc_op = _sel_table(
                    cur_op, params.inst_ft_cost or (0,) * num_insts)
                ft_lo = ivec_ref[IV_FT_LO, :][None, :]
                ft_hi = ivec_ref[IV_FT_HI, :][None, :]
                ft_bit = jnp.where(
                    cur_op < 32,
                    (ft_lo >> jnp.clip(cur_op, 0, 31)) & 1,
                    (ft_hi >> jnp.clip(cur_op - 32, 0, 31)) & 1)
                total_cost = jnp.maximum(cost_op, 1) + \
                    jnp.where(ft_bit == 0, ftc_op, 0)
                cw = ivec_ref[IV_COST_WAIT, :][None, :]
                eff_exec = exec_mask & (
                    (cw == 1) | ((cw == 0) & (total_cost <= 1)))
                cost_wait = jnp.where(
                    exec_mask,
                    jnp.where(cw > 0, cw - 1,
                              jnp.where(total_cost > 1, total_cost - 1, 0)),
                    cw)
                pay_ft = eff_exec & (ft_bit == 0)
                bit_lo = 1 << jnp.clip(cur_op, 0, 31)
                bit_hi = 1 << jnp.clip(cur_op - 32, 0, 31)
                ivec_ref[IV_FT_LO, :] = jnp.where(
                    pay_ft & (cur_op < 32), ft_lo | bit_lo, ft_lo)[0]
                ivec_ref[IV_FT_HI, :] = jnp.where(
                    pay_ft & (cur_op >= 32), ft_hi | bit_hi, ft_hi)[0]
                ivec_ref[IV_COST_WAIT, :] = cost_wait[0]
            else:
                eff_exec = exec_mask

            # ---- probabilistic execution failure (cHardwareCPU.cc:988:
            # costs paid, flagged executed, IP advances; effect and nop-
            # modifier consumption suppressed) ----
            if params.inst_prob_fail:
                u_fail = u24(2).astype(jnp.float32) * (1.0 / (1 << 24))
                pf_op = _fsel_table(cur_op, params.inst_prob_fail)
                inst_failed = eff_exec & (u_fail < pf_op)
            else:
                inst_failed = jnp.zeros((1, B), jnp.bool_)

            sem = jnp.where(eff_exec & ~inst_failed, meta & 31, -1)
            # mod_kind keys off exec_mask (not eff_exec), matching the XLA
            # interpreter exactly: the modifier nop is flagged during
            # cost-pay cycles too
            mod_kind = jnp.where(exec_mask & ~inst_failed,
                                 (meta >> 5) & 3, MOD_NONE)
            default_operand = (meta >> 7) & 3

            def is_op(x):
                return sem == x

            # ---- operand resolution (s_ip1 = label slot 0 = the byte at
            # (ip+1) mod mlen, wrap included) ----
            next_op = jnp.clip(s_ip1, 0, num_insts - 1)
            if nops_prefix:
                next_is_nop = next_op < 3
                nmod_next = next_op
            else:
                next_is_nop = _bitmask_lookup(next_op, nop_tab)
                nmod_next = _sel_table(next_op, nmod_tab)
            wants_mod = (mod_kind == MOD_REG) | (mod_kind == MOD_HEAD)
            has_mod = wants_mod & next_is_nop
            operand = jnp.where(has_mod, nmod_next, default_operand)
            consumed = has_mod.astype(jnp.int32)
            next_pos = adjust1(ip + 1, mlen)

            # ---- label decode: all 10 window slots come straight from the
            # packed-word byte assembly above (no second tape pass) ----
            has_label = mod_kind == MOD_LABEL

            def slot_nop(v):
                if nops_prefix:
                    return v < 3, v
                return _bitmask_lookup(v, nop_tab), _sel_table(v, nmod_tab)

            run = jnp.ones_like(cur_op)
            label_len = jnp.zeros_like(cur_op)
            lab_vals = []
            for k in range(MAX_LABEL_SIZE):
                isn, nv = slot_nop(jnp.clip(lab_bytes[k], 0, num_insts - 1))
                in_range = (k + 1) <= (mlen - 1)
                run = run * (isn & in_range).astype(jnp.int32)
                label_len = label_len + run
                lab_vals.append(nv)
            label_len = jnp.where(has_label, label_len, 0)
            consumed = jnp.where(has_label, label_len, consumed)
            # complement rotation; wrap-by-subtract (values beyond 2 only
            # occur at masked positions and never match a nop value)
            lbl_c = [jnp.where(v >= 2, v - 2, v + 1) for v in lab_vals]

            # ---- register reads ----
            regs = ivec_ref[pl.ds(IV_REGS, 3), :]             # [3, B]
            r_oh = reg_rows == operand
            val = jnp.sum(jnp.where(r_oh, regs, 0), axis=0, keepdims=True)
            nr = operand + 1
            next_reg = jnp.where(nr >= 3, nr - 3, nr)
            r2_oh = reg_rows == next_reg
            val2 = jnp.sum(jnp.where(r2_oh, regs, 0), axis=0, keepdims=True)
            bx = regs[1, :][None, :]
            cx = regs[2, :][None, :]

            # ---- PRNG (skipped entirely for mutation-free configs, which
            # also lets interpret-mode tests run without TPU PRNG support) ----
            uniform_mut = not params.mut_cdf or all(
                abs(params.mut_cdf[k] - (k + 1) / num_insts) < 1e-12
                for k in range(num_insts))

            if params.copy_mut_prob > 0:
                u_copy = u24(0).astype(jnp.float32) * (1.0 / (1 << 24))
                r_bits = u24(1)
                if uniform_mut:
                    rand_inst = r_bits % num_insts
                else:
                    # redundancy-weighted inverse-CDF draw
                    # (cInstSet::GetRandomInst; ops/interpreter.random_inst)
                    u_inst = r_bits.astype(jnp.float32) * (1.0 / (1 << 24))
                    rand_inst = jnp.zeros((1, B), jnp.int32)
                    for k in range(num_insts - 1):
                        rand_inst = rand_inst + (
                            u_inst >= float(params.mut_cdf[k])
                        ).astype(jnp.int32)
            else:
                u_copy = jnp.ones((1, B), jnp.float32)
                rand_inst = jnp.zeros((1, B), jnp.int32)

            # ---- stacks ----
            a_stk = ivec_ref[IV_ACTIVE_STACK, :][None, :]
            sp2 = ivec_ref[pl.ds(IV_SP, 2), :]                # [2, B]
            spa = jnp.where(a_stk == 0, sp2[0, :][None, :], sp2[1, :][None, :])
            push_m = is_op(SEM_PUSH)
            pop_m = is_op(SEM_POP)
            sp_push = jnp.where(spa == 0, 9, spa - 1)
            stacks = ivec_ref[pl.ds(IV_STACKS, 20), :]        # [20, B]
            cur_slot = stk_rows == (a_stk * 10 + spa)
            push_slot = stk_rows == (a_stk * 10 + sp_push)
            pop_val = jnp.sum(jnp.where(cur_slot, stacks, 0), axis=0,
                              keepdims=True)
            stacks = jnp.where(push_slot & push_m, val, stacks)
            stacks = jnp.where(cur_slot & pop_m, 0, stacks)
            new_spa = jnp.where(push_m, sp_push,
                                jnp.where(pop_m,
                                          jnp.where(spa == 9, 0, spa + 1),
                                          spa))
            sel0 = (a_stk == 0)
            sp_out0 = jnp.where(sel0, new_spa, sp2[0, :][None, :])
            sp_out1 = jnp.where(~sel0, new_spa, sp2[1, :][None, :])
            active_stack = jnp.where(is_op(SEM_SWAP_STK), 1 - a_stk, a_stk)

            # ---- h-search (gated on any lane searching) ----
            # SWAR window matcher over the packed tape: every byte maps to
            # a 2-bit complement code (nop-A/B/C = 0/1/2, non-nop = 3), 4
            # codes per word pack into 8 bits, and the 20-bit window
            # starting at byte b of word w is bits [2b, 2b+20) of the
            # 4-word concatenation -- one pass handles every label length
            # up to MAX_LABEL_SIZE.
            srch = is_op(SEM_H_SEARCH)

            def search_match(_):
                # packed complement label, 2 bits per slot
                c2 = jnp.zeros((1, B), jnp.int32)
                for k in range(MAX_LABEL_SIZE):
                    c2 = c2 | (jnp.clip(lbl_c[k], 0, 3) << (2 * k))
                m2 = (jnp.int32(1) << (2 * jnp.minimum(
                    label_len, MAX_LABEL_SIZE)).astype(jnp.uint32)) - 1
                c2 = c2 & m2
                ok_lane = label_len > 0
                best = jnp.full((1, B), L, jnp.int32)
                W = 3            # extra lookahead words for the 20-bit window
                for c in range(0, LP, TRAV):
                    hi = min(TRAV + W, LP - c)
                    cn = min(TRAV, LP - c)

                    def _tile_best(_, c=c, cn=cn, hi=hi):
                        tc = tape_ref[pl.ds(c, hi), :]
                        if hi < cn + W:
                            tc = jnp.concatenate(
                                [tc, jnp.full((cn + W - hi, B),
                                              0x3F3F3F3F, jnp.int32)],
                                axis=0)
                        # per-byte 2-bit complement codes (SWAR; the
                        # per-byte ==0 test is bit7 of x | (0x80 - x),
                        # borrow-free for 6-bit opcode bytes)
                        M80 = jnp.int32(-2139062144)        # 0x80808080

                        def byte_eqz(x):
                            return ((x | (M80 - x)) >> 7) & 0x01010101

                        if nops_prefix:
                            # code = min(byte, 3): byte >= 3 <=> byte>>2
                            # != 0 or byte == 3
                            b2 = (tc >> 2) & 0x3F3F3F3F
                            ge3f = ((byte_eqz(b2) ^ 0x01010101)
                                    | byte_eqz(tc ^ 0x03030303))
                            cc = (tc | (ge3f * 0xFF)) & 0x03030303
                        else:
                            cc = jnp.full_like(tc, 0x03030303)
                            for k in range(num_insts):
                                if nop_tab[k]:
                                    ek = byte_eqz(tc ^ (int(k) * 0x01010101))
                                    cc = ((cc & ~(ek * 0xFF))
                                          | (ek * int(nmod_tab[k])))
                        # pack 4 x 2-bit codes -> 8 bits per word
                        cc8 = (cc | (cc >> 6) | (cc >> 12) | (cc >> 18)) & 0xFF
                        cat = (cc8[:cn, :] | (cc8[1:cn + 1, :] << 8)
                               | (cc8[2:cn + 2, :] << 16)
                               | (cc8[3:cn + 3, :] << 24))
                        rows4 = (jax.lax.broadcasted_iota(
                            jnp.int32, (cn, B), 0) + c) * 4
                        posw = jnp.full((cn, B), L, jnp.int32)
                        for b in range(3, -1, -1):
                            hb = (((cat >> (2 * b)) & m2) == c2) & ok_lane \
                                & ((rows4 + b + label_len) <= mlen)
                            posw = jnp.where(hb, rows4 + b, posw)
                        return jnp.min(posw, axis=0, keepdims=True)

                    if _ROWSKIP:
                        # a match needs rows4 + label_len <= the searching
                        # lane's mlen <= bound_w*4, so tiles at or above
                        # the bound can never hold one (lookahead reads of
                        # skipped tiles are fine: their rows carry no
                        # un-applied pendings -- pend_b bounds those)
                        tb = jax.lax.cond(
                            bound_w > c, _tile_best,
                            lambda _: jnp.full((1, B), L, jnp.int32), None)
                    else:
                        tb = _tile_best(None)
                    best = jnp.minimum(best, tb)
                return best

            if "search" in _ABLATE:
                q_found = jnp.full((1, B), L, jnp.int32)
            else:
                q_found = jax.lax.cond(
                    jnp.any(srch & (label_len > 0)), search_match,
                    lambda _: jnp.full((1, B), L, jnp.int32), None)
            found = q_found < L
            ip_after_label = adjust1(ip + label_len, mlen)
            search_head = jnp.where(found, q_found + label_len - 1,
                                    ip_after_label)
            search_bx = search_head - ip_after_label
            search_cx = label_len
            new_flow_srch = adjust1(search_head + 1, mlen)

            # ---- if-label ----
            rl_len = ivec_ref[IV_READ_LABEL_LEN, :][None, :]
            read_label = ivec_ref[pl.ds(IV_READ_LABEL, MAX_LABEL_SIZE), :]
            rl_match = rl_len == label_len
            for k in range(MAX_LABEL_SIZE):
                rl_match = rl_match & (
                    (read_label[k, :][None, :] == lbl_c[k])
                    | (k >= label_len))

            # ---- conditionals (boolean algebra: where() on bool vectors
            # trips an unsupported i8->i1 truncation in Mosaic) ----
            skip = ((is_op(SEM_IF_N_EQU) & (val == val2))
                    | (is_op(SEM_IF_LESS) & (val >= val2))
                    | (is_op(SEM_IF_LABEL) & ~rl_match))

            # ---- h-alloc ----
            alloc_m0 = is_op(SEM_H_ALLOC)
            old_len = mlen
            alloc_size = jnp.minimum(
                (params.offspring_size_range
                 * old_len.astype(jnp.float32)).astype(jnp.int32),
                L0 - old_len)
            alloc_ok = alloc_size >= 1
            if params.require_allocate:
                alloc_ok = alloc_ok & ~mal_active
            alloc_ok = alloc_ok & (old_len <= (alloc_size.astype(jnp.float32)
                                               * params.offspring_size_range
                                               ).astype(jnp.int32))
            alloc_ok = alloc_ok & ~divide_pending
            alloc_m = alloc_m0 & alloc_ok
            new_len_alloc = old_len + alloc_size
            mem_len = jnp.where(alloc_m, new_len_alloc,
                                ivec_ref[IV_MEM_LEN, :][None, :])
            new_mal = mal_active | alloc_m

            # ---- h-copy ----
            copy_m = is_op(SEM_H_COPY)
            read_inst = jnp.clip(s_rp, 0, num_insts - 1)
            do_mut = copy_m & (u_copy < params.copy_mut_prob)
            written = jnp.where(do_mut, rand_inst, read_inst)
            if nops_prefix:
                ri_isnop = read_inst < 3
                ri_val = read_inst
            else:
                ri_isnop = _bitmask_lookup(read_inst, nop_tab)
                ri_val = _sel_table(read_inst, nmod_tab)
            ri_nop = ri_isnop & copy_m
            ri_clear = (~ri_isnop) & copy_m
            can_append = ri_nop & (rl_len < MAX_LABEL_SIZE)
            rl_rows = jax.lax.broadcasted_iota(jnp.int32, (MAX_LABEL_SIZE, B), 0)
            rl_slot = rl_rows == rl_len
            read_label = jnp.where(
                rl_slot & can_append, ri_val, read_label.astype(jnp.int32))
            read_label_len = jnp.where(
                ri_clear, 0, jnp.where(can_append, rl_len + 1, rl_len))

            # ---- h-divide ----
            div_sex_try = is_op(SEM_H_DIVIDE_SEX)
            div_try = is_op(SEM_H_DIVIDE) | div_sex_try
            gsize = ivec_ref[IV_GENOME_LEN, :][None, :]
            fsize = gsize.astype(jnp.float32)
            min_sz = jnp.maximum(params.min_genome_len,
                                 (fsize / params.offspring_size_range
                                  ).astype(jnp.int32))
            max_sz = jnp.minimum(L0, (fsize * params.offspring_size_range
                                     ).astype(jnp.int32))

            # divide-viability zone counts: masked popcounts over the site
            # bitplanes.  Unconditional: at B=256 some lane tries h-divide
            # on ~half of all cycles, and the [LW, B] popcounts are cheaper
            # than the cond barrier they used to hide behind.
            if "divcounts" not in _ABLATE:
                below_p = _word_range_mask(lw_rows, jnp.zeros_like(ip),
                                           parent_size)
                child_z = _word_range_mask(lw_rows, parent_size, child_end)
                exec_count0 = jnp.sum(_popcount32(ebm & below_p), axis=0,
                                      keepdims=True)
                copied_count = jnp.sum(_popcount32(cbm & child_z), axis=0,
                                       keepdims=True)
            else:
                exec_count0 = jnp.zeros((1, B), jnp.int32)
                copied_count = jnp.zeros((1, B), jnp.int32)
            exec_count = exec_count0 + jnp.where(
                div_try & ~ip_exec_already & (ip < parent_size), 1, 0)
            sterile_f = (flags & FLAG_STERILE) != 0
            viable = (~sterile_f &
                      (child_size >= min_sz) & (child_size <= max_sz) &
                      (parent_size >= min_sz) & (parent_size <= max_sz) &
                      (exec_count >= (parent_size.astype(jnp.float32)
                                      * params.min_exe_lines).astype(jnp.int32)) &
                      (copied_count >= (child_size.astype(jnp.float32)
                                        * params.min_copied_lines).astype(jnp.int32)) &
                      ~divide_pending)
            div_m = div_try & viable
            off_start = jnp.where(div_m, rp, ivec_ref[IV_OFF_START, :][None, :])
            off_len = jnp.where(div_m, child_size,
                                ivec_ref[IV_OFF_LEN, :][None, :])
            ivec_ref[IV_OFF_SEX, :] = jnp.where(
                div_m, div_sex_try.astype(jnp.int32),
                ivec_ref[IV_OFF_SEX, :][None, :])[0]

            # (offspring extraction happens ONCE post-loop: a divided lane
            # stalls for the rest of the launch, so its child region
            # [off_start, off_start + off_len) is frozen in the tape; the
            # FLAG_NEWDIV bit marks lanes to extract)

            # ---- IO + tasks (per-organism, infinite resources) ----
            io_m = is_op(SEM_IO)
            in_ptr = ivec_ref[IV_INPUT_PTR, :][None, :]
            inputs3 = ivec_ref[pl.ds(IV_INPUTS, 3), :]
            ptr_mod = in_ptr % 3
            value_in = jnp.where(ptr_mod == 0, inputs3[0, :][None, :],
                                 jnp.where(ptr_mod == 1, inputs3[1, :][None, :],
                                           inputs3[2, :][None, :]))
            ibuf = ivec_ref[pl.ds(IV_INPUT_BUF, 3), :]
            ibuf_n = ivec_ref[IV_INPUT_BUF_N, :][None, :]
            cur_bonus = fvec_ref[FV_CUR_BONUS, :][None, :]

            def tasks_block(_):
                i0 = jnp.where(ibuf_n > 0, ibuf[0, :][None, :], 0)
                i1 = jnp.where(ibuf_n > 1, ibuf[1, :][None, :], 0)
                i2 = jnp.where(ibuf_n > 2, ibuf[2, :][None, :], 0)
                lid = _logic_id(i0, i1, i2, ibuf_n, val)
                lid_ok = (lid >= 0) & io_m
                lidc = jnp.clip(lid, 0, 255)

                logic_mask = params.task_logic_mask   # tuple[R] of tuple[256]
                min_tc = params.min_task_count
                max_tc = params.max_task_count
                req_m = params.req_reaction_mask
                noreq_m = params.noreq_reaction_mask
                val_t = params.proc_value
                typ_t = params.proc_type

                new_bonus = cur_bonus
                performed_l = []
                rewarded_l = []
                add_sum = jnp.zeros((1, B), fdt)
                for r in range(R):
                    tc = ivec_ref[IV_DYN + r, :][None, :]
                    performed = _task_performed(lidc, logic_mask[r]) & lid_ok
                    in_window = (tc >= int(min_tc[r])) & (tc < int(max_tc[r]))
                    req_ok = jnp.ones((1, B), jnp.bool_)
                    for d in range(R):
                        if req_m[r][d]:
                            rc_d = ivec_ref[IV_DYN + R + d, :][None, :]
                            req_ok = req_ok & (rc_d != 0)
                        if noreq_m[r][d]:
                            rc_d = ivec_ref[IV_DYN + R + d, :][None, :]
                            req_ok = req_ok & (rc_d == 0)
                    rewarded = performed & in_window & req_ok
                    v = float(val_t[r])
                    t = int(typ_t[r])
                    if t == 2:      # pow: bonus *= 2^v
                        new_bonus = jnp.where(rewarded, new_bonus * (2.0 ** v),
                                              new_bonus)
                    elif t == 1:    # mult
                        if v != 0.0:
                            new_bonus = jnp.where(rewarded, new_bonus * v,
                                                  new_bonus)
                    else:           # add
                        add_sum = add_sum + jnp.where(rewarded,
                                                      jnp.float32(v), 0.0)
                    # i32, not bool: Mosaic rejects multi-i1-vector cond yields
                    performed_l.append(performed.astype(jnp.int32))
                    rewarded_l.append(rewarded.astype(jnp.int32))
                return tuple([new_bonus + add_sum] + performed_l + rewarded_l)

            def no_tasks(_):
                f = jnp.zeros((1, B), jnp.int32)
                return tuple([cur_bonus] + [f] * (2 * R))

            # Round-6 satellite (ROUND5 item 3): at steady state some lane
            # in a 512-wide block performs IO on nearly every cycle, so
            # the old jnp.any(io_m) cond fired ~always and its barrier
            # cost more than the ~R x 40 row ops it guarded -- the task
            # pipeline now runs unconditionally (identical values when no
            # lane does IO: every reward is masked by io_m).
            # TPU_TASKS_UNCOND=0 restores the gate for A/B measurement.
            if "tasks" in _ABLATE:
                outs = no_tasks(None)
            elif _TASKS_UNCOND:
                outs = tasks_block(None)
            else:
                outs = jax.lax.cond(jnp.any(io_m), tasks_block, no_tasks,
                                    None)
            new_bonus = outs[0]
            performed_l = list(outs[1:1 + R])
            rewarded_l = list(outs[1 + R:1 + 2 * R])

            input_ptr = jnp.where(io_m, in_ptr + 1, in_ptr)
            ibuf0 = jnp.where(io_m, value_in, ibuf[0, :][None, :])
            ibuf1 = jnp.where(io_m, ibuf[0, :][None, :], ibuf[1, :][None, :])
            ibuf2 = jnp.where(io_m, ibuf[1, :][None, :], ibuf[2, :][None, :])
            input_buf_n = jnp.where(io_m, jnp.minimum(ibuf_n + 1, 3), ibuf_n)
            output_buf = jnp.where(io_m, val,
                                   ivec_ref[IV_OUTPUT_BUF, :][None, :])
            cur_bonus = jnp.where(io_m, new_bonus, cur_bonus)

            # ---- register writes ----
            res = val
            wrote = jnp.zeros((1, B), jnp.bool_)
            for sm, v in ((SEM_SHIFT_R, val >> 1), (SEM_SHIFT_L, val << 1),
                          (SEM_INC, val + 1), (SEM_DEC, val - 1),
                          (SEM_ADD, bx + cx), (SEM_SUB, bx - cx),
                          (SEM_NAND, ~(bx & cx)), (SEM_POP, pop_val),
                          (SEM_IO, value_in), (SEM_SWAP, val2)):
                res = jnp.where(is_op(sm), v, res)
                wrote = wrote | is_op(sm)

            regs_new = jnp.where((reg_rows == operand) & wrote, res, regs)
            regs_new = jnp.where((reg_rows == next_reg) & is_op(SEM_SWAP),
                                 val, regs_new)
            hsel0 = jnp.where(mod_kind == MOD_HEAD, operand, HEAD_IP)
            h_oh = head_rows == hsel0
            head_sel = jnp.sum(jnp.where(h_oh, heads, 0), axis=0, keepdims=True)
            # head_sel is in [0, mlen) by the head invariant; ip+consumed
            # < 2*mlen (consumed <= mlen-1)
            eff_head_pos = jnp.where(hsel0 == HEAD_IP,
                                     adjust1(ip + consumed, mlen), head_sel)
            regs_new = jnp.where((reg_rows == 2) & is_op(SEM_GET_HEAD),
                                 eff_head_pos, regs_new)
            regs_new = jnp.where((reg_rows == 0) & alloc_m, old_len, regs_new)
            regs_new = jnp.where((reg_rows == 1) & srch, search_bx, regs_new)
            regs_new = jnp.where((reg_rows == 2) & srch, search_cx, regs_new)
            regs_new = jnp.where(div_m, 0, regs_new)

            # ---- head writes ----
            mov_m = is_op(SEM_MOV_HEAD)
            jmp_m = is_op(SEM_JMP_HEAD)
            setflow_m = is_op(SEM_SET_FLOW)
            flow0 = heads[HEAD_FLOW, :][None, :]      # in-range by invariant
            # the only TRUE modulo reductions left (arbitrary register
            # offsets); jmp-head/set-flow are rare, so compute them under a
            # block-activity gate
            def rare_mods(_):
                return (adjust(eff_head_pos + cx, mlen), adjust(val, mlen))

            jmp_pos, setflow_pos = jax.lax.cond(
                jnp.any(jmp_m | setflow_m), rare_mods,
                lambda _: (jnp.zeros((1, B), jnp.int32),
                           jnp.zeros((1, B), jnp.int32)), None)
            new_hpos = jnp.where(mov_m, flow0, jmp_pos)
            mv = mov_m | jmp_m
            heads_new = jnp.where(h_oh & mv, new_hpos, heads)
            new_flow = jnp.where(setflow_m, setflow_pos,
                                 jnp.where(srch, new_flow_srch,
                                           heads_new[HEAD_FLOW, :][None, :]))
            heads_new = jnp.where(head_rows == HEAD_FLOW, new_flow, heads_new)
            heads_new = jnp.where((head_rows == HEAD_READ) & copy_m,
                                  adjust1(rp + 1, mlen), heads_new)
            heads_new = jnp.where((head_rows == HEAD_WRITE) & copy_m,
                                  adjust1(wp + 1, mlen), heads_new)

            # ---- IP advance ----
            mov_ip = mov_m & (hsel0 == HEAD_IP)
            jmp_ip = jmp_m & (hsel0 == HEAD_IP)
            # ip+consumed+skip+1 <= 2*mlen: two conditional subtracts
            ip_seq = adjust1(adjust1(
                ip + consumed + skip.astype(jnp.int32) + 1, mlen), mlen)
            jmp_tgt = adjust1(jmp_pos + 1, mlen)
            ip_new = jnp.where(jmp_ip, jmp_tgt, ip_seq)
            ip_new = jnp.where(mov_ip, flow0, ip_new)
            ip_new = jnp.where(div_m, 0, ip_new)
            ip_new = jnp.where(eff_exec, ip_new, heads[HEAD_IP, :][None, :])
            heads_new = jnp.where(head_rows == HEAD_IP, ip_new, heads_new)

            # divide: CPU reset
            mem_len = jnp.where(div_m, rp, mem_len)
            if has_costs:
                # parent cost-engine state resets at divide (interpreter
                # ops/interpreter.py:572-574)
                ivec_ref[IV_COST_WAIT, :] = jnp.where(
                    div_m, 0, ivec_ref[IV_COST_WAIT, :][None, :])[0]
                ivec_ref[IV_FT_LO, :] = jnp.where(
                    div_m, 0, ivec_ref[IV_FT_LO, :][None, :])[0]
                ivec_ref[IV_FT_HI, :] = jnp.where(
                    div_m, 0, ivec_ref[IV_FT_HI, :][None, :])[0]
            heads_new = jnp.where(div_m, 0, heads_new)
            stacks = jnp.where(div_m, 0, stacks)
            sp_out0 = jnp.where(div_m, 0, sp_out0)
            sp_out1 = jnp.where(div_m, 0, sp_out1)
            active_stack = jnp.where(div_m, 0, active_stack)
            read_label_len = jnp.where(div_m, 0, read_label_len)
            new_mal = new_mal & ~div_m

            # ---- site-flag bitplane updates (replaces v1's tape bits 6/7)
            # exec flag at ip; at the first operand nop when one is consumed
            lab0_exec = has_label & (label_len > 0)
            nop_exec = has_mod | lab0_exec
            ebm = _set_bit(ebm, lw_rows, ip, eff_exec)
            ebm = _set_bit(ebm, lw_rows, next_pos, nop_exec)
            cbm = _set_bit(cbm, lw_rows, wp, copy_m)
            # h-alloc clears site flags across the fresh zone
            zone = _word_range_mask(lw_rows, old_len, new_len_alloc)
            clear_z = jnp.where(alloc_m, zone, 0)
            ebm = ebm & ~clear_z
            cbm = cbm & ~clear_z
            # divide clears every site flag (v1: tape &= 63)
            ebm = jnp.where(div_m, 0, ebm)
            cbm = jnp.where(div_m, 0, cbm)

            # ---- deferred tape writes for the NEXT cycle's traversal ----
            new_pw_pos = jnp.where(copy_m, wp, -1)
            new_pw_val = jnp.where(do_mut, rand_inst, read_inst)
            new_pz_s = jnp.where(alloc_m, old_len, 0)
            new_pz_e = jnp.where(alloc_m, new_len_alloc, 0)

            # ---- phenotype DivideReset ----
            copied_sz = ivec_ref[IV_COPIED_SIZE, :][None, :]
            m = params.base_merit_method
            if m == 0:
                merit_base = jnp.full((1, B), float(params.base_const_merit), fdt)
            elif m == 1:
                merit_base = copied_sz.astype(fdt)
            elif m == 2:
                merit_base = exec_count.astype(fdt)
            elif m == 3:
                merit_base = gsize.astype(fdt)
            elif m == 4:
                merit_base = jnp.minimum(jnp.minimum(gsize, copied_sz),
                                         exec_count).astype(fdt)
            else:
                least = jnp.minimum(jnp.minimum(gsize, copied_sz), exec_count)
                merit_base = jnp.sqrt(least.astype(fdt))
            new_merit = (merit_base * cur_bonus if params.inherit_merit
                         else merit_base)
            time_used0 = ivec_ref[IV_TIME_USED, :][None, :]
            gest_start = ivec_ref[IV_GEST_START, :][None, :]
            gestation = time_used0 + 1 - gest_start
            new_fitness = new_merit / jnp.maximum(gestation, 1).astype(fdt)

            merit = jnp.where(div_m, new_merit, fvec_ref[FV_MERIT, :][None, :])
            fitness = jnp.where(div_m, new_fitness,
                                fvec_ref[FV_FITNESS, :][None, :])
            gest_time = jnp.where(div_m, gestation,
                                  ivec_ref[IV_GEST_TIME, :][None, :])
            last_bonus = jnp.where(div_m, cur_bonus,
                                   fvec_ref[FV_LAST_BONUS, :][None, :])
            last_mb = jnp.where(div_m, merit_base,
                                fvec_ref[FV_LAST_MERIT_BASE, :][None, :])
            exec_size = jnp.where(div_m, exec_count,
                                  ivec_ref[IV_EXEC_SIZE, :][None, :])
            child_copied = jnp.where(div_m, copied_count,
                                     ivec_ref[IV_CHILD_COPIED, :][None, :])
            cur_bonus = jnp.where(div_m, params.default_bonus, cur_bonus)
            # GENERATION_INC_METHOD 1 (default): parent increments too
            # (cPhenotype::DivideReset cc:1052)
            gen_inc = (div_m.astype(jnp.int32)
                       if params.generation_inc_method == 1 else 0)
            generation = ivec_ref[IV_GENERATION, :][None, :] + gen_inc
            num_divides = ivec_ref[IV_NUM_DIVIDES, :][None, :] + \
                div_m.astype(jnp.int32)
            off_copied = jnp.where(div_m, copied_count,
                                   ivec_ref[IV_OFF_COPIED, :][None, :])

            # ---- time + death ----
            time_used = time_used0 + exec_mask.astype(jnp.int32)
            if params.inst_addl_time_cost:
                # extra time_used charge, even on prob_fail suppression
                # (cHardwareCPU.cc:985,1015)
                time_used = time_used + jnp.where(
                    eff_exec, _sel_table(cur_op, params.inst_addl_time_cost),
                    0)
            cpu_cycles = ivec_ref[IV_CPU_CYCLES, :][None, :] + \
                exec_mask.astype(jnp.int32)
            if params.divide_method != 0:
                # DIVIDE_METHOD 1/2: parent clock resets at divide
                # (cPhenotype::DivideReset cc:1037-1039)
                time_used = jnp.where(div_m, 0, time_used)
                cpu_cycles = jnp.where(div_m, 0, cpu_cycles)
                gest_start = jnp.where(div_m, 0, gest_start)
            else:
                gest_start = jnp.where(div_m, time_used, gest_start)
            max_exec = ivec_ref[IV_MAX_EXEC, :][None, :]
            died = exec_mask & (max_exec > 0) & (time_used >= max_exec)
            alive = alive & ~died
            insts_exec = ivec_ref[IV_INSTS_EXEC, :][None, :] + \
                exec_mask.astype(jnp.int32)
            divide_pending = divide_pending | div_m

            # ---- write back scalars ----
            ivec_ref[IV_MEM_LEN, :] = mem_len[0]
            ivec_ref[IV_ACTIVE_STACK, :] = active_stack[0]
            ivec_ref[IV_READ_LABEL_LEN, :] = read_label_len[0]
            ivec_ref[IV_INPUT_PTR, :] = input_ptr[0]
            ivec_ref[IV_INPUT_BUF_N, :] = input_buf_n[0]
            ivec_ref[IV_OUTPUT_BUF, :] = output_buf[0]
            ivec_ref[IV_TIME_USED, :] = time_used[0]
            ivec_ref[IV_CPU_CYCLES, :] = cpu_cycles[0]
            ivec_ref[IV_GEST_START, :] = gest_start[0]
            ivec_ref[IV_GEST_TIME, :] = gest_time[0]
            ivec_ref[IV_EXEC_SIZE, :] = exec_size[0]
            ivec_ref[IV_CHILD_COPIED, :] = child_copied[0]
            ivec_ref[IV_GENERATION, :] = generation[0]
            ivec_ref[IV_NUM_DIVIDES, :] = num_divides[0]
            ivec_ref[IV_OFF_START, :] = off_start[0]
            ivec_ref[IV_OFF_LEN, :] = off_len[0]
            ivec_ref[IV_OFF_COPIED, :] = off_copied[0]
            ivec_ref[IV_INSTS_EXEC, :] = insts_exec[0]
            newdiv = ((flags & FLAG_NEWDIV) != 0) | div_m
            flags_new = (jnp.where(new_mal, FLAG_MAL, 0)
                         | jnp.where(alive, FLAG_ALIVE, 0)
                         | jnp.where(divide_pending, FLAG_DIVPEND, 0)
                         | jnp.where(sterile_f, FLAG_STERILE, 0)
                         | jnp.where(newdiv, FLAG_NEWDIV, 0))
            ivec_ref[IV_FLAGS, :] = flags_new[0]
            ivec_ref[pl.ds(IV_REGS, 3), :] = regs_new
            ivec_ref[pl.ds(IV_HEADS, 4), :] = heads_new
            ivec_ref[IV_SP, :] = sp_out0[0]
            ivec_ref[IV_SP + 1, :] = sp_out1[0]
            ivec_ref[IV_INPUT_BUF, :] = ibuf0[0]
            ivec_ref[IV_INPUT_BUF + 1, :] = ibuf1[0]
            ivec_ref[IV_INPUT_BUF + 2, :] = ibuf2[0]
            ivec_ref[pl.ds(IV_READ_LABEL, MAX_LABEL_SIZE), :] = read_label
            ivec_ref[pl.ds(IV_STACKS, 20), :] = stacks
            ivec_ref[IV_PW_POS, :] = new_pw_pos[0]
            ivec_ref[IV_PW_VAL, :] = new_pw_val[0]
            ivec_ref[IV_PZ_START, :] = new_pz_s[0]
            ivec_ref[IV_PZ_END, :] = new_pz_e[0]
            ivec_ref[pl.ds(IV_EXEC_BM, LW), :] = ebm
            ivec_ref[pl.ds(IV_COPIED_BM, LW), :] = cbm
            # task/reaction counters change only on IO or divide cycles
            @pl.when(jnp.any(io_m) | jnp.any(div_m))
            def _update_task_counts():
                for r in range(R):
                    tc = ivec_ref[IV_DYN + r, :][None, :]
                    rc = ivec_ref[IV_DYN + R + r, :][None, :]
                    ltc = ivec_ref[IV_DYN + 2 * R + r, :][None, :]
                    tc_new = tc + performed_l[r]
                    rc_new = rc + rewarded_l[r]
                    ltc_new = jnp.where(div_m, tc_new, ltc)
                    tc_new = jnp.where(div_m, 0, tc_new)
                    rc_new = jnp.where(div_m, 0, rc_new)
                    ivec_ref[IV_DYN + r, :] = tc_new[0]
                    ivec_ref[IV_DYN + R + r, :] = rc_new[0]
                    ivec_ref[IV_DYN + 2 * R + r, :] = ltc_new[0]
                    # lifetime per-cell executions (never reset)
                    ivec_ref[IV_DYN + 3 * R + r, :] = (
                        ivec_ref[IV_DYN + 3 * R + r, :][None, :]
                        + performed_l[r])[0]
            fvec_ref[FV_MERIT, :] = merit[0]
            fvec_ref[FV_CUR_BONUS, :] = cur_bonus[0]
            fvec_ref[FV_FITNESS, :] = fitness[0]
            fvec_ref[FV_LAST_BONUS, :] = last_bonus[0]
            fvec_ref[FV_LAST_MERIT_BASE, :] = last_mb[0]
            return _

        # run only as many cycles as this block's largest budget needs
        block_max = jnp.minimum(jnp.max(granted), num_steps)

        def cond(carry):
            return carry[0] < block_max

        def body(carry):
            s, _ = carry
            cycle_body(s, None)
            cycle_body(s + 1, None)   # overshoot past block_max is a
            #                           fully-masked no-op cycle
            return (s + 2, 0)

        jax.lax.while_loop(cond, body, (jnp.int32(0), 0))

        # apply the last cycle's deferred tape writes so the output tape is
        # fully materialized
        pw_pos = ivec_ref[IV_PW_POS, :][None, :]
        pw_val = ivec_ref[IV_PW_VAL, :][None, :]
        pz_s = ivec_ref[IV_PZ_START, :][None, :]
        pz_e = ivec_ref[IV_PZ_END, :][None, :]
        for c in range(0, LP, CHUNK):
            cn = min(CHUNK, LP - c)
            tc = tape_ref[pl.ds(c, cn), :]
            wrows_c = jax.lax.broadcasted_iota(jnp.int32, (cn, B), 0) + c
            tc = apply_pending(tc, wrows_c, pw_pos, pw_val, pz_s, pz_e)
            tape_ref[pl.ds(c, cn), :] = tc
        ivec_ref[IV_PW_POS, :] = jnp.full((B,), -1, jnp.int32)
        ivec_ref[IV_PZ_START, :] = jnp.zeros((B,), jnp.int32)
        ivec_ref[IV_PZ_END, :] = jnp.zeros((B,), jnp.int32)

        # ---- one-shot offspring extraction for every lane that divided
        # during this launch: a per-lane barrel roll of the opcode tape by
        # the saved off_start, masked to the child's off_len bytes ----
        def extract_all(_):
            newdiv = (ivec_ref[IV_FLAGS, :][None, :] & FLAG_NEWDIV) != 0
            osr = ivec_ref[IV_OFF_START, :][None, :]
            oln = ivec_ref[IV_OFF_LEN, :][None, :]
            acc = tape_ref[...]
            rw = osr >> 2
            k = 1
            while k < LP:
                rolled = jnp.concatenate([acc[k:, :], acc[:k, :]], axis=0)
                acc = jnp.where((rw & k) != 0, rolled, acc)
                k <<= 1
            rb = osr & 3
            nxt = jnp.concatenate([acc[1:, :], acc[:1, :]], axis=0)
            shl = jnp.minimum((4 - rb) * 8, 31)   # only read when rb > 0
            comb = ((acc >> (rb * 8)) & bytemask(4 - rb)) | (nxt << shl)
            acc = jnp.where(rb == 0, acc, comb)
            km = bytemask(jnp.clip(oln - wrows * 4, 0, 4))
            return jnp.where(newdiv, acc & km, off_ref[...])

        if "extract" not in _ABLATE:
            any_newdiv = jnp.any(
                (ivec_ref[IV_FLAGS, :][None, :] & FLAG_NEWDIV) != 0)
            off_ref[...] = jax.lax.cond(any_newdiv, extract_all,
                                        lambda _: off_ref[...], None)

    return kernel, NI


def kernel_shards(params) -> int:
    """How many independent shard_map shards the kernel launch splits
    into: TPU_KERNEL_SHARDS, or (auto) one per visible device.  The
    fast-path precondition guarantees blocks are independent, so the
    split needs no cross-shard communication -- each shard runs its own
    pallas_call over its band of lanes."""
    s = int(getattr(params, "kernel_shards", 0))
    if s > jax.device_count():
        raise ValueError(
            f"TPU_KERNEL_SHARDS={s} exceeds the visible device count "
            f"({jax.device_count()}); shards map 1:1 onto devices")
    return jax.device_count() if s <= 0 else s


def _dims(params, n, L0, shards=1):
    B = min(DEFAULT_BLOCK, max(128, 1 << (n - 1).bit_length()))
    # lane padding: a whole number of blocks per SHARD (padded lanes are
    # dead: granted 0, alive 0 -- their blocks exit the while_loop
    # immediately)
    q = B * max(shards, 1)
    n_pad = ((n + q - 1) // q) * q
    # L padded to a CHUNK multiple: every `range(L // CHUNK)` traversal in
    # the kernel must cover the whole tape
    L = ((L0 + CHUNK - 1) // CHUNK) * CHUNK
    return B, n_pad, L


def block_dims(params, n):
    """(block_lanes, padded_n) of the kernel launch for an n-cell world --
    the granularity at which each block's while_loop runs to its own max
    granted budget.  The telemetry budget-tail counters
    (observability/counters.py) bin `granted` at this width."""
    B, n_pad, _ = _dims(params, n, params.max_memory)
    return B, n_pad


def _pack_words(tape, L):
    """uint8[N, L] -> int32[N, L//4] with byte j of word w = position
    4w+j (little-endian bitcast; opcode bytes are <= 63 so every word is
    non-negative and in-kernel arithmetic right shifts are safe)."""
    n = tape.shape[0]
    return jax.lax.bitcast_convert_type(
        tape.reshape(n, L // 4, 4), jnp.int32).reshape(n, L // 4)


def _unpack_words(words, L):
    """int32[N, L//4] -> uint8[N, L] (inverse of _pack_words)."""
    n = words.shape[0]
    return jax.lax.bitcast_convert_type(words, jnp.uint8).reshape(n, L)


BITS5_PER_WORD = 6          # 5-bit opcodes packed 6 per int32 word


def words5(L: int) -> int:
    """Row count of a 5-bit-packed plane covering L opcode slots."""
    return -(-L // BITS5_PER_WORD)


def _pack_words5(tape, L):
    """uint8[N, L] opcodes (< 32; TPU_PACKED_BITS requires num_insts
    <= 32) -> int32[N, ceil(L/6)] with 5-bit field f of word w = position
    6w+f.  30 payload bits per word, so every word is non-negative.  The
    genome SHADOW plane's resident layout under TPU_PACKED_BITS=1 -- the
    kernel never reads that plane, so only the host-side pack/flush/
    unpack paths speak this codec (ops/packed_chunk.py, ops/birth.py)."""
    n = tape.shape[0]
    w5 = words5(L)
    t = jnp.pad(tape.astype(jnp.int32) & 0x1F,
                ((0, 0), (0, w5 * BITS5_PER_WORD - L)))
    g = t.reshape(n, w5, BITS5_PER_WORD)
    sh = jnp.arange(BITS5_PER_WORD, dtype=jnp.int32) * 5
    return (g << sh[None, None, :]).sum(axis=2).astype(jnp.int32)


def _unpack_words5(words, L):
    """int32[N, ceil(L/6)] -> uint8[N, L] (inverse of _pack_words5)."""
    n = words.shape[0]
    sh = jnp.arange(BITS5_PER_WORD, dtype=jnp.int32) * 5
    g = (words[:, :, None] >> sh[None, None, :]) & 0x1F
    return g.reshape(n, words.shape[1] * BITS5_PER_WORD)[:, :L].astype(
        jnp.uint8)


def _flag_to_words(tape, bit, L):
    """Site flag `bit` (6 or 7) of uint8[N, L] -> int32[N, L//32] packed
    words (bit j of word w = flag of site 32w+j).

    SWAR, not a 32-wide reduce: bitcast 4 bytes to one u32, gather the 4
    flag bits into a nibble with a multiply (positions 24..27 of
    v * 0x01020408 collect bytes 0..3 in order, carry-free), then combine
    8 nibbles per word."""
    n = tape.shape[0]
    x = jax.lax.bitcast_convert_type(tape.reshape(n, L // 4, 4),
                                     jnp.uint32).reshape(n, L // 4)
    b4 = (x >> bit) & jnp.uint32(0x01010101)
    nib = ((b4 * jnp.uint32(0x01020408)) >> 24) & 0xF       # [n, L/4]
    nib = nib.astype(jnp.int32).reshape(n, L // 32, 8)
    return (nib << (jnp.arange(8, dtype=jnp.int32) * 4)[None, None, :]).sum(
        axis=2)


def _words_to_flag(words, bit, L):
    """int32[N, L//32] packed words -> uint8[N, L] with the flag at `bit`
    (inverse of _flag_to_words; SWAR spread 0x00204081)."""
    n = words.shape[0]
    nib = ((words[:, :, None] >> (jnp.arange(8, dtype=jnp.int32) * 4)
            [None, None, :]) & 0xF).astype(jnp.uint32).reshape(n, L // 4)
    b4 = (nib * jnp.uint32(0x00204081)) & jnp.uint32(0x01010101)
    by = jax.lax.bitcast_convert_type(b4 << bit, jnp.uint8)  # [n, L/4, 4]
    return by.reshape(n, L)


def pack_state(params, st, granted, perm=None, shards=1):
    """PopulationState -> (tape_t, off_t, ivec, fvec) kernel layout
    (traced).

    perm (int32[N], slot -> organism) packs organism perm[s] into kernel
    lane s -- the budget-aware lane permutation (ops/update.perm_phase).
    Every permute here is a MAJOR-axis row gather of an [N, ...] array
    (tape rows; the per-organism scalars ride ONE batched [N, K] gather),
    never a lane-axis gather of the packed planes -- the data movement
    that sank the round-4/5 budget-sort attempts (see run_cycles)."""
    n, L0 = st.tape.shape
    R = params.num_reactions
    B, n_pad, L = _dims(params, n, L0, shards)
    NI, LW, IV_COPIED_BM, IV_DYN = _layout(params, L)

    def rows(x):
        return x if perm is None else x[perm]

    def padn(x):
        return jnp.pad(x, ((0, n_pad - n),) + ((0, 0),) * (x.ndim - 1))

    # ---- tape: 4-opcodes-per-int32 word plane (byte j of word w =
    # position 4w+j; little-endian bitcast, same convention as
    # _flag_to_words) + site-flag bitplanes ----
    tape_p = jnp.pad(rows(st.tape), ((0, 0), (0, L - L0)))
    opc_t = padn(_pack_words(tape_p & jnp.uint8(63), L)).T     # [LP, n_pad]
    exec_w = _flag_to_words(tape_p, 6, L)                      # [n, LW]
    cop_w = _flag_to_words(tape_p, 7, L)
    off_p = jnp.pad(rows(st.off_tape), ((0, 0), (0, L - L0)))
    off_t = padn(_pack_words(off_p, L)).T                      # [LP, n_pad]

    iv = [None] * NI

    # per-organism scalars are collected and permuted as ONE [N, K]
    # row-gather (scal rows are stacked, transposed to organism-major,
    # gathered, transposed back) instead of K separate [N] gathers
    scal_i, scal_v = [], []

    def setrow(i, x):
        scal_i.append(i)
        scal_v.append(x.astype(jnp.int32))

    setrow(IV_MEM_LEN, st.mem_len)
    setrow(IV_ACTIVE_STACK, st.active_stack)
    setrow(IV_READ_LABEL_LEN, st.read_label_len)
    setrow(IV_INPUT_PTR, st.input_ptr)
    setrow(IV_INPUT_BUF_N, st.input_buf_n)
    setrow(IV_OUTPUT_BUF, st.output_buf)
    setrow(IV_TIME_USED, st.time_used)
    setrow(IV_CPU_CYCLES, st.cpu_cycles)
    setrow(IV_GEST_START, st.gestation_start)
    setrow(IV_GEST_TIME, st.gestation_time)
    setrow(IV_EXEC_SIZE, st.executed_size)
    setrow(IV_CHILD_COPIED, st.child_copied_size)
    setrow(IV_GENERATION, st.generation)
    setrow(IV_NUM_DIVIDES, st.num_divides)
    setrow(IV_OFF_START, st.off_start)
    setrow(IV_OFF_LEN, st.off_len)
    setrow(IV_OFF_COPIED, st.off_copied_size)
    setrow(IV_INSTS_EXEC, st.insts_executed)
    setrow(IV_FLAGS, (st.mal_active * FLAG_MAL + st.alive * FLAG_ALIVE
                      + st.divide_pending * FLAG_DIVPEND
                      + st.sterile * FLAG_STERILE))
    setrow(IV_GENOME_LEN, st.genome_len)
    setrow(IV_MAX_EXEC, st.max_executed)
    setrow(IV_GRANTED, granted)
    setrow(IV_COPIED_SIZE, st.copied_size)
    for k in range(3):
        setrow(IV_REGS + k, st.regs[:, k])
    for k in range(4):
        setrow(IV_HEADS + k, st.heads[:, k])
    for k in range(2):
        setrow(IV_SP + k, st.sp[:, k])
    for k in range(3):
        setrow(IV_INPUT_BUF + k, st.input_buf[:, k])
    for k in range(3):
        setrow(IV_INPUTS + k, st.inputs[:, k])
    for k in range(MAX_LABEL_SIZE):
        setrow(IV_READ_LABEL + k, st.read_label[:, k])
    for s_ in range(2):
        for d in range(10):
            setrow(IV_STACKS + s_ * 10 + d, st.stacks[:, s_, d])
    setrow(IV_COST_WAIT, st.cost_wait)
    setrow(IV_FT_LO, st.ft_paid_lo)
    setrow(IV_FT_HI, st.ft_paid_hi)
    setrow(IV_OFF_SEX, st.off_sex)
    iv[IV_PW_POS] = jnp.full(n_pad, -1, jnp.int32)
    iv[IV_PW_VAL] = jnp.zeros(n_pad, jnp.int32)
    iv[IV_PZ_START] = jnp.zeros(n_pad, jnp.int32)
    iv[IV_PZ_END] = jnp.zeros(n_pad, jnp.int32)
    for w in range(LW):
        iv[IV_EXEC_BM + w] = padn(exec_w[:, w])
        iv[IV_COPIED_BM + w] = padn(cop_w[:, w])
    for r in range(R):
        setrow(IV_DYN + r, st.cur_task_count[:, r])
        setrow(IV_DYN + R + r, st.cur_reaction_count[:, r])
        setrow(IV_DYN + 2 * R + r, st.last_task_count[:, r])
        setrow(IV_DYN + 3 * R + r, st.task_exe_total[:, r])

    mat = jnp.stack(scal_v, axis=0)                            # [K, n]
    if perm is not None:
        mat = mat.T[perm].T        # one organism-major row gather
    mat = jnp.pad(mat, ((0, 0), (0, n_pad - n)))
    for j, i in enumerate(scal_i):
        iv[i] = mat[j]
    for i in range(NI):
        if iv[i] is None:
            iv[i] = jnp.zeros(n_pad, jnp.int32)
    ivec = jnp.stack(iv, axis=0)                               # [NI, n_pad]

    fmat = jnp.stack([st.merit, st.cur_bonus, st.fitness, st.last_bonus,
                      st.last_merit_base], axis=0).astype(jnp.float32)
    # row order above must follow FV_MERIT..FV_LAST_MERIT_BASE = 0..4
    if perm is not None:
        fmat = fmat.T[perm].T
    fvec = jnp.pad(fmat, ((0, NF - 5), (0, n_pad - n)))        # [NF, n_pad]
    return opc_t, off_t, ivec, fvec


def _launch_packed(params, packed, block_seeds, num_steps, B, S):
    """The shared launch core: one pallas_call over `grid` blocks of B
    lanes (shard_map'd over the `cells` mesh axis when S > 1), with the
    PRNG seed delivered PER BLOCK via `block_seeds` (int32[total_blocks],
    block-mapped into SMEM).  The callers own the seed schedule:
    run_packed reproduces the historical solo streams exactly;
    run_packed_stacked gives every world its own seed base so a stacked
    launch replays each member's solo streams."""
    tape_t, off_t, ivec, fvec = packed
    LP, n_pad = tape_t.shape
    L = LP * 4
    NI, LW, _, _ = _layout(params, L)
    n_loc = n_pad // S

    interpret = jax.devices()[0].platform != "tpu"
    kernel, _ = _make_kernel(params, L, B, num_steps, interpret)
    grid = (n_loc // B,)

    def launch(seeds, tape_t, off_t, ivec, fvec):
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1,), lambda i: (i,),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((LP, B), lambda i: (0, i)),
                pl.BlockSpec((LP, B), lambda i: (0, i)),
                pl.BlockSpec((NI, B), lambda i: (0, i)),
                pl.BlockSpec((NF, B), lambda i: (0, i)),
            ],
            out_specs=[
                pl.BlockSpec((LP, B), lambda i: (0, i)),
                pl.BlockSpec((LP, B), lambda i: (0, i)),
                pl.BlockSpec((NI, B), lambda i: (0, i)),
                pl.BlockSpec((NF, B), lambda i: (0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((LP, n_loc), jnp.int32),
                jax.ShapeDtypeStruct((LP, n_loc), jnp.int32),
                jax.ShapeDtypeStruct((NI, n_loc), jnp.int32),
                jax.ShapeDtypeStruct((NF, n_loc), jnp.float32),
            ],
            input_output_aliases={1: 0, 2: 1, 3: 2, 4: 3},
            interpret=interpret,
        )(seeds, tape_t, off_t, ivec, fvec)

    if S == 1:
        return tuple(launch(block_seeds, tape_t, off_t, ivec, fvec))

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    from avida_tpu.parallel.mesh import CELL_AXIS, make_mesh

    mesh = make_mesh(jax.devices()[:S])
    lane = P(None, CELL_AXIS)
    out = shard_map(
        launch, mesh=mesh,
        # block_seeds carries each block's GLOBAL seed already, so the
        # vector shards right alongside the lanes it seeds
        in_specs=(P(CELL_AXIS), lane, lane, lane, lane),
        out_specs=(lane, lane, lane, lane),
        check_rep=False,
    )(block_seeds, tape_t, off_t, ivec, fvec)
    return tuple(out)


def kernel_seed(key):
    """The kernel PRNG seed draw -- ONE spelling (width, bound, dtype)
    shared by every launch path.  Stacked-vs-solo bit-exactness depends
    on world_seed_bases below reproducing this draw for each world's
    own k_steps key, so any change to the derivation must happen here
    and nowhere else."""
    return jax.random.randint(key, (1,), 0, 2**31 - 1, dtype=jnp.int32)


def world_seed_bases(k_steps):
    """Per-world seed bases for a stacked multi-world launch
    (int32[W]): world w's base is exactly the kernel_seed its SOLO
    launch would draw from the same k_steps_w, which is what makes
    run_packed_stacked bit-exact per world vs solo by construction.
    The single spelling shared by ops/update._mw_stack_kernel_cycles
    and ops/packed_chunk.update_step_packed_worlds."""
    return jax.vmap(kernel_seed)(k_steps)[:, 0]


def run_packed(params, packed, key, num_steps):
    """Kernel launch(es) over the packed state quad (traced).

    Single device: one pallas_call over all blocks.  Multiple shards
    (kernel_shards): the SAME launch is shard_map'd over the `cells` mesh
    axis -- pallas_call registers no GSPMD partitioning rule, so the
    manual shard_map is what keeps a sharded multi-chip update on the
    kernel instead of silently falling back to the HBM-round-tripping XLA
    while_loop.  Blocks are independent (fast-path precondition), so
    shards need no communication; each block's PRNG seed is its global
    block base (seed + global block index on TPU) so the sharded
    trajectory is bit-identical to the unsharded one."""
    tape_t, off_t, ivec, fvec = packed
    LP, n_pad = tape_t.shape
    S = kernel_shards(params)
    if S > 1 and (n_pad % S or (n_pad // S) % 128):
        S = 1                        # caller packed without shard padding
    n_loc = n_pad // S
    B = min(DEFAULT_BLOCK, n_loc)

    seed = kernel_seed(key)
    total = n_pad // B
    blk = jnp.arange(total, dtype=jnp.int32)
    if jax.devices()[0].platform == "tpu":
        block_seeds = seed + blk
    else:
        # interpret mode has no in-kernel block offset historically: all
        # of a (shard's) launch's blocks share the shard base.  Preserved
        # exactly -- every recorded interpret trajectory (tests,
        # checkpoints) depends on these streams.
        block_seeds = seed + (blk // (n_loc // B)) * (n_loc // B)
    return _launch_packed(params, (tape_t, off_t, ivec, fvec),
                          block_seeds, num_steps, B, S)


def run_packed_stacked(params, packed, world_seeds, num_steps, B):
    """ONE kernel launch over W worlds' planes stacked on the lane axis.

    `packed` is the usual quad but with n_pad = W x n_w lanes laid out
    world-major (world w owns lanes [w*n_w, (w+1)*n_w); n_w a multiple
    of the per-world block width B, so no block ever straddles a world
    boundary).  `world_seeds` (int32[W]) are the per-world seed bases --
    block b of world w seeds exactly like block b of world w's SOLO
    UNSHARDED launch (TPU: seed_w + b; interpret mode: seed_w), which
    makes the stacked launch bit-exact per world vs solo by construction
    on both backends, independent of TPU_KERNEL_SHARDS.

    This is what lets the two-level scheduler (per-block while_loop early
    exit + TPU_KERNEL_ROWSKIP) load-balance ragged budgets ACROSS
    tenants: each world's blocks run to their own max granted budget
    inside one launch, instead of every world idling on the batch-max
    trip count of a vmapped loop."""
    tape_t, off_t, ivec, fvec = packed
    LP, lanes = tape_t.shape
    W = world_seeds.shape[0]
    n_w = lanes // W
    bpw = n_w // B                   # blocks per world
    S = kernel_shards(params)
    if S > 1 and (lanes % S or (lanes // S) % 128 or (lanes // S) % B):
        S = 1                        # stacking incompatible with S shards
    blk = jnp.arange(bpw, dtype=jnp.int32)[None, :]
    if jax.devices()[0].platform == "tpu":
        block_seeds = (world_seeds[:, None] + blk).reshape(W * bpw)
    else:
        block_seeds = jnp.broadcast_to(
            world_seeds[:, None], (W, bpw)).reshape(W * bpw)
    return _launch_packed(params, (tape_t, off_t, ivec, fvec),
                          block_seeds, num_steps, B, S)


def unpack_state(params, st, packed, inv=None, restore_ro=False):
    """Kernel layout -> PopulationState, preserving untouched fields of
    `st` (genome, breed_true, resources...) (traced).

    inv (int32[N], organism -> slot) undoes the pack-time lane
    permutation: organism o's state is read back from kernel lane inv[o].
    As in pack_state, every permute is a major-axis row gather (the ivec/
    fvec planes ride one organism-major gather each).

    restore_ro=False (the per-update path) keeps the kernel-read-only
    ivec rows (IV_GENOME_LEN / IV_COPIED_SIZE / IV_MAX_EXEC / IV_INPUTS)
    out of the result -- the kernel never writes them, so callers keep
    them from the pre-pack state.  The packed-resident chunk
    (ops/packed_chunk.py) runs the birth flush ON the planes, which DOES
    update those rows; its chunk-boundary unpack passes restore_ro=True
    so the canonical state picks them up."""
    tape_o, off_o, ivec_o, fvec_o = packed
    n, L0 = st.tape.shape
    R = params.num_reactions
    L = tape_o.shape[0] * 4
    NI, LW, IV_COPIED_BM, IV_DYN = _layout(params, L)

    tape_rows = tape_o.T[:n]                                   # [n, LP]
    off_rows = off_o.T[:n]
    iv_rows = ivec_o[:, :n]                                    # [NI, n]
    fv_rows = fvec_o[:, :n]
    if inv is not None:
        tape_rows = tape_rows[inv]
        off_rows = off_rows[inv]
        iv_rows = iv_rows.T[inv].T
        fv_rows = fv_rows.T[inv].T

    def row(i):
        return iv_rows[i]

    def frow(i):
        return fv_rows[i]

    # rebuild the flag-bit tape from the packed word plane + bitplanes
    opc = _unpack_words(tape_rows, L)                          # [n, L]
    exec_w = jnp.stack([row(IV_EXEC_BM + w) for w in range(LW)], axis=1)
    cop_w = jnp.stack([row(IV_COPIED_BM + w) for w in range(LW)], axis=1)
    tape = (opc | _words_to_flag(exec_w, 6, L)
            | _words_to_flag(cop_w, 7, L))[:, :L0]

    flags = row(IV_FLAGS)
    ro = {}
    if restore_ro:
        ro = dict(
            genome_len=row(IV_GENOME_LEN),
            copied_size=row(IV_COPIED_SIZE),
            max_executed=row(IV_MAX_EXEC),
            inputs=jnp.stack([row(IV_INPUTS + k) for k in range(3)], axis=1),
        )
    return st.replace(
        **ro,
        tape=tape,
        off_tape=_unpack_words(off_rows, L)[:, :L0],
        mem_len=row(IV_MEM_LEN),
        regs=jnp.stack([row(IV_REGS + k) for k in range(3)], axis=1),
        heads=jnp.stack([row(IV_HEADS + k) for k in range(4)], axis=1),
        stacks=jnp.stack(
            [jnp.stack([row(IV_STACKS + s_ * 10 + d) for d in range(10)],
                       axis=1) for s_ in range(2)], axis=1),
        sp=jnp.stack([row(IV_SP + k) for k in range(2)], axis=1),
        active_stack=row(IV_ACTIVE_STACK),
        read_label=jnp.stack([row(IV_READ_LABEL + k).astype(jnp.int8)
                              for k in range(MAX_LABEL_SIZE)], axis=1),
        read_label_len=row(IV_READ_LABEL_LEN),
        mal_active=(flags & FLAG_MAL) != 0,
        alive=(flags & FLAG_ALIVE) != 0,
        sterile=(flags & FLAG_STERILE) != 0,
        input_ptr=row(IV_INPUT_PTR),
        input_buf=jnp.stack([row(IV_INPUT_BUF + k) for k in range(3)], axis=1),
        input_buf_n=row(IV_INPUT_BUF_N),
        output_buf=row(IV_OUTPUT_BUF),
        merit=frow(FV_MERIT), cur_bonus=frow(FV_CUR_BONUS),
        cur_task_count=jnp.stack([row(IV_DYN + r) for r in range(R)], axis=1),
        cur_reaction_count=jnp.stack([row(IV_DYN + R + r) for r in range(R)],
                                     axis=1),
        last_task_count=jnp.stack([row(IV_DYN + 2 * R + r) for r in range(R)],
                                  axis=1),
        task_exe_total=jnp.stack([row(IV_DYN + 3 * R + r) for r in range(R)],
                                 axis=1),
        time_used=row(IV_TIME_USED), cpu_cycles=row(IV_CPU_CYCLES),
        gestation_start=row(IV_GEST_START), gestation_time=row(IV_GEST_TIME),
        fitness=frow(FV_FITNESS), last_bonus=frow(FV_LAST_BONUS),
        last_merit_base=frow(FV_LAST_MERIT_BASE),
        executed_size=row(IV_EXEC_SIZE),
        child_copied_size=row(IV_CHILD_COPIED),
        generation=row(IV_GENERATION), num_divides=row(IV_NUM_DIVIDES),
        divide_pending=(flags & FLAG_DIVPEND) != 0,
        off_start=row(IV_OFF_START), off_len=row(IV_OFF_LEN),
        off_copied_size=row(IV_OFF_COPIED),
        off_sex=row(IV_OFF_SEX) != 0,
        insts_executed=row(IV_INSTS_EXEC),
        cost_wait=row(IV_COST_WAIT),
        ft_paid_lo=row(IV_FT_LO), ft_paid_hi=row(IV_FT_HI),
    )


@functools.partial(jax.jit, static_argnums=(0, 4))
def run_cycles(params, st, key, granted, num_steps):
    """Run up to `num_steps` lockstep cycles with per-organism budgets
    `granted` (int32[N]) through the VMEM-resident kernel.  Returns the new
    PopulationState.  Caller must check `eligible(params)` first.

    Budget-aware lane packing (TPU_LANE_PERM, ops/update.perm_phase): the
    persistent st.lane_perm/lane_inv indirection packs budget-sorted
    organisms into kernel lanes so each block's while_loop runs near its
    mean granted budget instead of its max (~1.55x -> ~1.03x lockstep
    ceiling).  Budget-sorted blocking was tried twice before and reverted
    -- per-lane in round 4 (~10 ms of gathers) and 8-lane-tile-granular
    in round 5 (~15 ms fused) -- because both permuted the PACKED planes
    along the minor (lane) axis.  This version permutes the UNPACKED
    [N, ...] arrays on their major axis inside pack/unpack (tape-row
    gathers plus one batched scalar-matrix gather each way) and keeps the
    permutation itself persistent state, so the sort is refreshed on the
    perm_phase schedule rather than recomputed here."""
    use_perm = int(getattr(params, "lane_perm_k", 0)) > 0
    if use_perm:
        from avida_tpu.ops import packed_chunk
        use_perm = not packed_chunk.active(params, st)
    perm = st.lane_perm if use_perm else None
    inv = st.lane_inv if use_perm else None
    packed = pack_state(params, st, granted, perm, kernel_shards(params))
    packed = run_packed(params, packed, key, num_steps)
    return unpack_state(params, st, packed, inv)

