"""Resource dynamics: global pools and spatial (per-cell) grids.

TPU-native re-expression of the reference resource engine:
 - global pools: cResourceCount (avida-core/source/main/cResourceCount.cc:207
   Setup; decay/inflow integration at cc:35 with UPDATE_STEP=1/10000) becomes
   a closed-form per-update step on a tiny f32 vector;
 - spatial resources: cSpatialResCount (main/cSpatialResCount.cc; diffusion
   `FlowAll` cc:316, sources/sinks cc:358-390) becomes one 3x3 convolution
   per update over an [R, Y, X] grid -- the reference's cell-pair flow loop
   is exactly a discrete Laplacian stencil, which is the single most
   TPU-friendly operation there is;
 - consumption: the reference serializes organisms, drawing each one's
   demand down immediately (cEnvironment::DoProcesses cc:1610).  In lockstep
   all same-cycle demands against a pool are summed and, when they exceed
   the available level, every consumer is scaled proportionally (documented
   deviation; spatial resources have one organism per cell, so their
   consumption has no contention at all).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def step_global(params, resources):
    """One update of inflow/outflow for global pools (closed form).

    level' = level + inflow - outflow * level, the reference's net change
    over one update (cResourceCount::DoUpdates integrates the same ODE in
    1e-4 substeps; for stock rates the difference is <1e-3 per update).
    """
    if params.num_global_res == 0:
        return resources
    inflow = jnp.asarray(params.res_inflow, jnp.float32)
    outflow = jnp.asarray(params.res_outflow, jnp.float32)
    return jnp.maximum(resources + inflow - outflow * resources, 0.0)


def step_gradient(params, st, key, update_no):
    """Moving-peak gradient resources (cGradientCount::UpdateCount ->
    updatePeakRes/fillinResourceValues, main/cGradientCount.cc).

    Each gradient row's grid is the cone height/(dist+1) within `spread`
    of the peak (plateau cells -- where height/(dist+1) >= 1 -- take the
    plateau value when set), recomputed every update; the peak takes a
    random-direction step every `updatestep` updates when movement is on.
    Simplifications (documented): no halos/hills/barriers, and the cone
    refreshes each update rather than modeling plateau depletion.
    """
    if not any(params.sres_grad_height):
        return st
    X, Y = params.world_x, params.world_y
    n = params.num_cells
    cx = jnp.arange(n) % X
    cy = jnp.arange(n) // X
    res_grid = st.res_grid
    grad_peak = st.grad_peak
    for r, h in enumerate(params.sres_grad_height):
        if not h:
            continue
        spread = params.sres_grad_spread[r]
        plateau = params.sres_grad_plateau[r]
        kr = jax.random.fold_in(key, r)
        px, py = grad_peak[r, 0], grad_peak[r, 1]
        # initial placement: the configured peakx/peaky, else random
        # within the world, spread-inset (generatePeak cc:?)
        k_init, k_move = jax.random.split(kr)
        unset = px < 0
        cfg_px, cfg_py = params.sres_grad_peakx[r], params.sres_grad_peaky[r]
        init_px = (jnp.int32(cfg_px) if cfg_px >= 0 else jax.random.randint(
            k_init, (), min(spread, X // 2), max(X - spread, X // 2 + 1),
            dtype=jnp.int32))
        init_py = (jnp.int32(cfg_py) if cfg_py >= 0 else jax.random.randint(
            jax.random.fold_in(k_init, 1), (),
            min(spread, Y // 2), max(Y - spread, Y // 2 + 1),
            dtype=jnp.int32))
        px = jnp.where(unset, init_px, px)
        py = jnp.where(unset, init_py, py)
        if params.sres_grad_move[r]:
            ustep = max(params.sres_grad_updatestep[r], 1)
            step_due = (update_no % ustep) == 0
            dx = jax.random.randint(k_move, (), -1, 2, dtype=jnp.int32)
            dy = jax.random.randint(jax.random.fold_in(k_move, 1), (),
                                    -1, 2, dtype=jnp.int32)
            px = jnp.clip(px + jnp.where(step_due, dx, 0), 0, X - 1)
            py = jnp.clip(py + jnp.where(step_due, dy, 0), 0, Y - 1)
        dist = jnp.sqrt(((cx - px) ** 2 + (cy - py) ** 2)
                        .astype(jnp.float32))
        cone = h / (dist + 1.0)
        if plateau >= 0:
            cone = jnp.where(cone >= 1.0, plateau, cone)
        cone = jnp.where(dist <= spread, cone, 0.0)
        res_grid = res_grid.at[r].set(cone)
        grad_peak = grad_peak.at[r, 0].set(px).at[r, 1].set(py)
    return st.replace(res_grid=res_grid, grad_peak=grad_peak)


def step_spatial(params, res_grid):
    """One update of a spatial resource: inflow box, outflow, diffusion.

    res_grid: f32[R_s, N] with N = world_x * world_y (cell-indexed, matching
    PopulationState).  Diffusion is a 3x3 stencil with per-resource X/Y
    rates; toroidal worlds wrap (ref cSpatialResCount::FlowAll cc:316).
    """
    if params.num_spatial_res == 0:
        return res_grid
    R = params.num_spatial_res
    X, Y = params.world_x, params.world_y
    g = res_grid.reshape(R, Y, X)

    inflow = jnp.asarray(params.sres_inflow, jnp.float32)      # [R]
    outflow = jnp.asarray(params.sres_outflow, jnp.float32)    # [R]
    xd = jnp.asarray(params.sres_xdiffuse, jnp.float32)        # [R]
    yd = jnp.asarray(params.sres_ydiffuse, jnp.float32)        # [R]
    torus = jnp.asarray(params.sres_torus, bool)               # [R]
    box = np.asarray(params.sres_inflow_box, np.int32).reshape(R, 4)

    # inflow into the configured box, divided among its cells (ref
    # cSpatialResCount::Source cc:362-363 `amount /= totalcells`).  Each -1
    # coordinate defaults to full range on its own axis (per-axis defaults,
    # matching the reference's unspecified-bound handling), so a partially
    # specified box never silently collapses to empty.
    xs = np.arange(X)[None, None, :]
    ys = np.arange(Y)[None, :, None]
    x1 = np.where(box[:, 0] < 0, 0, box[:, 0])
    x2 = np.where(box[:, 1] < 0, X - 1, box[:, 1])
    y1 = np.where(box[:, 2] < 0, 0, box[:, 2])
    y2 = np.where(box[:, 3] < 0, Y - 1, box[:, 3])
    in_box = ((xs >= x1[:, None, None]) & (xs <= x2[:, None, None]) &
              (ys >= y1[:, None, None]) & (ys <= y2[:, None, None]))
    box_cells = np.maximum(in_box.sum(axis=(1, 2)), 1)
    per_cell = inflow / jnp.asarray(box_cells, jnp.float32)
    g = g + jnp.where(jnp.asarray(in_box), per_cell[:, None, None], 0.0)

    # outflow (decay)
    g = g * (1.0 - outflow)[:, None, None]

    # diffusion: explicit 3x3 stencil, SUB-STEPPED so configured rates are
    # honored.  A single explicit application is only stable for
    # cx + cy <= 1/2 (cx = xdiffuse/2); the reference default
    # xdiffuse=ydiffuse=1.0 exceeds it, so the per-update flow is split into
    # ceil((xd+yd)_max) stencil applications with the coefficients divided
    # accordingly -- full configured diffusion per update, still stable,
    # mass conserved by construction.  Per-resource geometry: torus
    # resources wrap, grid resources have zero-flux edges (ref
    # cSpatialResCount geometry handling).
    def neighbors(gg, wrap):
        if wrap:
            return (jnp.roll(gg, 1, axis=2), jnp.roll(gg, -1, axis=2),
                    jnp.roll(gg, 1, axis=1), jnp.roll(gg, -1, axis=1))
        return (jnp.concatenate([gg[:, :, :1], gg[:, :, :-1]], axis=2),
                jnp.concatenate([gg[:, :, 1:], gg[:, :, -1:]], axis=2),
                jnp.concatenate([gg[:, :1, :], gg[:, :-1, :]], axis=1),
                jnp.concatenate([gg[:, 1:, :], gg[:, -1:, :]], axis=1))

    max_rate = max(float(x) + float(y)
                   for x, y in zip(params.sres_xdiffuse, params.sres_ydiffuse))
    nsub = max(int(np.ceil(max_rate)), 1)   # static: rates are config
    # clamp at 0: a (mis)configured negative rate must not invert the
    # stencil into unbounded anti-diffusion
    cx = jnp.maximum(0.5 * xd / nsub, 0.0)[:, None, None]
    cy = jnp.maximum(0.5 * yd / nsub, 0.0)[:, None, None]
    w = torus[:, None, None]
    for _ in range(nsub):
        lt, rt, ut, dt = neighbors(g, True)
        lb, rb, ub, db = neighbors(g, False)
        left = jnp.where(w, lt, lb)
        right = jnp.where(w, rt, rb)
        up = jnp.where(w, ut, ub)
        down = jnp.where(w, dt, db)
        g = g + cx * (left + right - 2.0 * g) + cy * (up + down - 2.0 * g)

    return jnp.maximum(g, 0.0).reshape(R, Y * X)


def step_deme(params, deme_resources):
    """Per-deme pool inflow/outflow (cDeme resource slice; same
    integration as the global cResourceCount)."""
    if params.num_deme_res == 0:
        return deme_resources
    inflow = jnp.asarray(params.dres_inflow, jnp.float32)[None, :]
    outflow = jnp.asarray(params.dres_outflow, jnp.float32)[None, :]
    return (deme_resources + inflow) * (1.0 - outflow)


def consume_deme(params, env_tables, rewarded, deme_resources):
    """Draw-down of deme-bound reaction resources: the global-pool
    contention rule applied independently inside each deme band (bands are
    contiguous: deme d = cells [d*cpd, (d+1)*cpd)).

    Returns (amount[N, NR] for deme-bound reactions (0 elsewhere),
             new_deme_resources[D, Rd])."""
    NR = rewarded.shape[1]
    n = rewarded.shape[0]
    D = max(params.num_demes, 1)
    cpd = n // D
    res_idx = env_tables["proc_res_idx"]
    is_deme = jnp.asarray(params.proc_res_deme, bool)
    max_num = env_tables["proc_max"]
    frac = env_tables["proc_frac"]
    depletable = env_tables["proc_depletable"]

    rw = rewarded.astype(jnp.float32)
    didx = jnp.clip(res_idx, 0, max(params.num_deme_res - 1, 0))
    # availability per (org, reaction): the org's deme pool level
    deme_avail = deme_resources[:, didx]                  # [D, NR]
    avail = jnp.repeat(deme_avail, cpd, axis=0)           # [N, NR]
    wanted = jnp.minimum(avail * frac[None, :], max_num[None, :]) * rw
    wanted = jnp.where(is_deme[None, :], wanted, 0.0)

    onehot = (jnp.arange(max(params.num_deme_res, 1))[:, None]
              == res_idx[None, :]) & is_deme[None, :]     # [Rd, NR]
    want_depl = jnp.where(depletable[None, :], wanted, 0.0)
    # per-deme demand: band-sum then project onto resource rows
    band = want_depl.reshape(D, cpd, NR).sum(axis=1)      # [D, NR]
    demand = jnp.einsum("dr,gr->dg", band, onehot.astype(jnp.float32))
    scale_res = jnp.where(demand > deme_resources,
                          deme_resources / jnp.maximum(demand, 1e-30), 1.0)
    scale_rxn = jnp.einsum("dg,gr->dr", scale_res, onehot.astype(jnp.float32))
    scale_rxn = jnp.where(depletable[None, :] & is_deme[None, :],
                          scale_rxn, 1.0)                  # [D, NR]
    got = wanted * jnp.repeat(scale_rxn, cpd, axis=0)
    drawn = jnp.where(depletable[None, :], got, 0.0)
    drawn_d = jnp.einsum("dr,gr->dg",
                         drawn.reshape(D, cpd, NR).sum(axis=1),
                         onehot.astype(jnp.float32))
    new_pools = jnp.maximum(deme_resources - drawn_d, 0.0)
    return got, new_pools


def consume(params, env_tables, rewarded, task_quality, resources, res_grid):
    """Resource draw-down for this cycle's rewarded reactions.

    rewarded: bool[N, NR] -- reaction fired for organism n this cycle.
    Returns (amount[N, NR] consumed units feeding the bonus math,
             new_resources[Rg], new_res_grid[Rs, N]).

    Mirrors cEnvironment::DoProcesses (cc:1610): each process consumes
    min(level * max_fraction, max_number) of its bound resource (times task
    quality); infinite-resource processes use max_number outright.  Same-
    cycle demands on one global pool are scaled proportionally when they
    exceed the level (lockstep semantic; see module docstring).
    """
    res_idx = env_tables["proc_res_idx"]          # i32[NR] (-1 infinite)
    spatial = env_tables["proc_res_spatial"]      # bool[NR]
    max_num = env_tables["proc_max"]              # f32[NR]
    frac = env_tables["proc_frac"]                # f32[NR]
    depletable = env_tables["proc_depletable"]    # bool[NR]

    rw = rewarded.astype(jnp.float32) * task_quality
    # deme-bound reactions are consume_deme()'s business: zero their demand
    # here so they never touch the global/spatial pools (their `amount`
    # column is overwritten with the deme result in apply_reactions)
    if params.num_deme_res:
        is_deme = jnp.asarray(params.proc_res_deme, bool)
        rw = jnp.where(is_deme[None, :], 0.0, rw)
    infinite = res_idx < 0

    # available level per (org, reaction)
    gidx = jnp.clip(res_idx, 0, max(params.num_global_res - 1, 0))
    sidx = jnp.clip(res_idx, 0, max(params.num_spatial_res - 1, 0))
    if params.num_global_res:
        avail_g = resources[gidx][None, :]                       # [1, NR]
    else:
        avail_g = jnp.zeros((1, res_idx.shape[0]), jnp.float32)
    if params.num_spatial_res:
        avail_s = res_grid[sidx, :].T                            # [N, NR]
    else:
        avail_s = jnp.zeros((1, res_idx.shape[0]), jnp.float32)
    avail = jnp.where(infinite[None, :], jnp.inf,
                      jnp.where(spatial[None, :], avail_s, avail_g))

    wanted = jnp.minimum(avail * frac[None, :], max_num[None, :]) * rw
    wanted = jnp.where(infinite[None, :], max_num[None, :] * rw, wanted)

    # ---- global pools: proportional scaling under contention.  Only
    # depletable processes draw the pool down, so only they contend; a
    # non-depletable process reads min(level*frac, max) without scaling
    # (ref cReactionProcess depletable semantics) ----
    if params.num_global_res:
        is_g = (~infinite & ~spatial)[None, :]
        want_g = jnp.where(is_g, wanted, 0.0)
        onehot = (jnp.arange(params.num_global_res)[:, None]
                  == res_idx[None, :])                           # [Rg, NR]
        want_depl = jnp.where(depletable[None, :], want_g, 0.0)
        demand = jnp.einsum("nr,gr->g", want_depl, onehot.astype(jnp.float32))
        scale_res = jnp.where(demand > resources,
                              resources / jnp.maximum(demand, 1e-30), 1.0)
        scale_rxn = jnp.einsum("g,gr->r", scale_res,
                               onehot.astype(jnp.float32))
        scale_rxn = jnp.where(infinite | spatial | ~depletable, 1.0, scale_rxn)
        got_g = want_g * scale_rxn[None, :]
        drawn = jnp.einsum("nr,gr->g",
                           jnp.where(is_g & depletable[None, :], got_g, 0.0),
                           onehot.astype(jnp.float32))
        resources = jnp.maximum(resources - drawn, 0.0)
    else:
        got_g = jnp.zeros_like(wanted)
        scale_rxn = jnp.ones(res_idx.shape[0], jnp.float32)

    # ---- spatial: one organism per cell, but multiple reactions bound to
    # the same resource can fire for that organism in one cycle, each
    # computing `wanted` from the same pre-draw cell level -- so scale all
    # depletable demands per (cell, resource) when they exceed the level,
    # exactly like the global-pool path ----
    if params.num_spatial_res:
        is_s = (~infinite & spatial)[None, :]
        want_s = jnp.where(is_s, wanted, 0.0)                    # [N, NR]
        onehot_s = (jnp.arange(params.num_spatial_res)[:, None]
                    == res_idx[None, :]).astype(jnp.float32)     # [Rs, NR]
        want_depl_s = jnp.where(depletable[None, :], want_s, 0.0)
        demand_s = jnp.einsum("nr,sr->sn", want_depl_s, onehot_s)  # [Rs, N]
        scale_sn = jnp.where(demand_s > res_grid,
                             res_grid / jnp.maximum(demand_s, 1e-30), 1.0)
        scale_nr = jnp.einsum("sn,sr->nr", scale_sn, onehot_s)   # [N, NR]
        scale_nr = jnp.where((infinite | ~spatial | ~depletable)[None, :],
                             1.0, scale_nr)
        got_s = want_s * scale_nr
        drawn_s = jnp.einsum("nr,sr->sn",
                             jnp.where(is_s & depletable[None, :], got_s, 0.0),
                             onehot_s)
        res_grid = jnp.maximum(res_grid - drawn_s, 0.0)
    else:
        got_s = jnp.zeros_like(wanted)

    amount = jnp.where(infinite[None, :], wanted,
                       jnp.where(spatial[None, :], got_s, got_g))
    return amount, resources, res_grid
