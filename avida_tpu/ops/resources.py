"""Resource dynamics: global pools and spatial (per-cell) grids.

TPU-native re-expression of the reference resource engine:
 - global pools: cResourceCount (avida-core/source/main/cResourceCount.cc:207
   Setup; decay/inflow integration at cc:35 with UPDATE_STEP=1/10000) becomes
   a closed-form per-update step on a tiny f32 vector;
 - spatial resources: cSpatialResCount (main/cSpatialResCount.cc; diffusion
   `FlowAll` cc:316, sources/sinks cc:358-390) becomes one 3x3 convolution
   per update over an [R, Y, X] grid -- the reference's cell-pair flow loop
   is exactly a discrete Laplacian stencil, which is the single most
   TPU-friendly operation there is;
 - consumption: the reference serializes organisms, drawing each one's
   demand down immediately (cEnvironment::DoProcesses cc:1610).  In lockstep
   all same-cycle demands against a pool are summed and, when they exceed
   the available level, every consumer is scaled proportionally (documented
   deviation; spatial resources have one organism per cell, so their
   consumption has no contention at all).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def step_global(params, resources):
    """One update of inflow/outflow for global pools (closed form).

    level' = level + inflow - outflow * level, the reference's net change
    over one update (cResourceCount::DoUpdates integrates the same ODE in
    1e-4 substeps; for stock rates the difference is <1e-3 per update).
    """
    if params.num_global_res == 0:
        return resources
    inflow = jnp.asarray(params.res_inflow, jnp.float32)
    outflow = jnp.asarray(params.res_outflow, jnp.float32)
    return jnp.maximum(resources + inflow - outflow * resources, 0.0)


def step_spatial(params, res_grid):
    """One update of a spatial resource: inflow box, outflow, diffusion.

    res_grid: f32[R_s, N] with N = world_x * world_y (cell-indexed, matching
    PopulationState).  Diffusion is a 3x3 stencil with per-resource X/Y
    rates; toroidal worlds wrap (ref cSpatialResCount::FlowAll cc:316).
    """
    if params.num_spatial_res == 0:
        return res_grid
    R = params.num_spatial_res
    X, Y = params.world_x, params.world_y
    g = res_grid.reshape(R, Y, X)

    inflow = jnp.asarray(params.sres_inflow, jnp.float32)      # [R]
    outflow = jnp.asarray(params.sres_outflow, jnp.float32)    # [R]
    xd = jnp.asarray(params.sres_xdiffuse, jnp.float32)        # [R]
    yd = jnp.asarray(params.sres_ydiffuse, jnp.float32)        # [R]
    torus = jnp.asarray(params.sres_torus, bool)               # [R]
    box = np.asarray(params.sres_inflow_box, np.int32).reshape(R, 4)

    # inflow into the configured box, divided among its cells (ref
    # cSpatialResCount::Source cc:362-363 `amount /= totalcells`); a box of
    # (-1,-1,-1,-1) means the whole world
    xs = np.arange(X)[None, None, :]
    ys = np.arange(Y)[None, :, None]
    x1, x2, y1, y2 = box[:, 0], box[:, 1], box[:, 2], box[:, 3]
    everywhere = (x1 < 0)[:, None, None]
    in_box = (everywhere |
              ((xs >= x1[:, None, None]) & (xs <= x2[:, None, None]) &
               (ys >= y1[:, None, None]) & (ys <= y2[:, None, None])))
    box_cells = np.maximum(in_box.sum(axis=(1, 2)), 1)
    per_cell = inflow / jnp.asarray(box_cells, jnp.float32)
    g = g + jnp.where(jnp.asarray(in_box), per_cell[:, None, None], 0.0)

    # outflow (decay)
    g = g * (1.0 - outflow)[:, None, None]

    # diffusion: explicit 3x3 stencil.  Per-axis coefficients are clamped to
    # the explicit-scheme stability bound (cx + cy <= 1/2) so any
    # xdiffuse/ydiffuse in [0, 1] -- including the reference default 1.0 --
    # diffuses instead of exploding; mass is conserved by construction.
    # Per-resource geometry: torus resources wrap, grid resources have
    # zero-flux edges (ref cSpatialResCount geometry handling).
    def neighbors(gg, wrap):
        if wrap:
            return (jnp.roll(gg, 1, axis=2), jnp.roll(gg, -1, axis=2),
                    jnp.roll(gg, 1, axis=1), jnp.roll(gg, -1, axis=1))
        return (jnp.concatenate([gg[:, :, :1], gg[:, :, :-1]], axis=2),
                jnp.concatenate([gg[:, :, 1:], gg[:, :, -1:]], axis=2),
                jnp.concatenate([gg[:, :1, :], gg[:, :-1, :]], axis=1),
                jnp.concatenate([gg[:, 1:, :], gg[:, -1:, :]], axis=1))

    lt, rt, ut, dt = neighbors(g, True)
    lb, rb, ub, db = neighbors(g, False)
    w = torus[:, None, None]
    left = jnp.where(w, lt, lb)
    right = jnp.where(w, rt, rb)
    up = jnp.where(w, ut, ub)
    down = jnp.where(w, dt, db)
    cx = jnp.clip(0.5 * xd, 0.0, 0.25)[:, None, None]
    cy = jnp.clip(0.5 * yd, 0.0, 0.25)[:, None, None]
    g = g + cx * (left + right - 2.0 * g) + cy * (up + down - 2.0 * g)

    return jnp.maximum(g, 0.0).reshape(R, Y * X)


def consume(params, env_tables, rewarded, task_quality, resources, res_grid):
    """Resource draw-down for this cycle's rewarded reactions.

    rewarded: bool[N, NR] -- reaction fired for organism n this cycle.
    Returns (amount[N, NR] consumed units feeding the bonus math,
             new_resources[Rg], new_res_grid[Rs, N]).

    Mirrors cEnvironment::DoProcesses (cc:1610): each process consumes
    min(level * max_fraction, max_number) of its bound resource (times task
    quality); infinite-resource processes use max_number outright.  Same-
    cycle demands on one global pool are scaled proportionally when they
    exceed the level (lockstep semantic; see module docstring).
    """
    res_idx = env_tables["proc_res_idx"]          # i32[NR] (-1 infinite)
    spatial = env_tables["proc_res_spatial"]      # bool[NR]
    max_num = env_tables["proc_max"]              # f32[NR]
    frac = env_tables["proc_frac"]                # f32[NR]
    depletable = env_tables["proc_depletable"]    # bool[NR]

    rw = rewarded.astype(jnp.float32) * task_quality
    infinite = res_idx < 0

    # available level per (org, reaction)
    gidx = jnp.clip(res_idx, 0, max(params.num_global_res - 1, 0))
    sidx = jnp.clip(res_idx, 0, max(params.num_spatial_res - 1, 0))
    if params.num_global_res:
        avail_g = resources[gidx][None, :]                       # [1, NR]
    else:
        avail_g = jnp.zeros((1, res_idx.shape[0]), jnp.float32)
    if params.num_spatial_res:
        avail_s = res_grid[sidx, :].T                            # [N, NR]
    else:
        avail_s = jnp.zeros((1, res_idx.shape[0]), jnp.float32)
    avail = jnp.where(infinite[None, :], jnp.inf,
                      jnp.where(spatial[None, :], avail_s, avail_g))

    wanted = jnp.minimum(avail * frac[None, :], max_num[None, :]) * rw
    wanted = jnp.where(infinite[None, :], max_num[None, :] * rw, wanted)

    # ---- global pools: proportional scaling under contention.  Only
    # depletable processes draw the pool down, so only they contend; a
    # non-depletable process reads min(level*frac, max) without scaling
    # (ref cReactionProcess depletable semantics) ----
    if params.num_global_res:
        is_g = (~infinite & ~spatial)[None, :]
        want_g = jnp.where(is_g, wanted, 0.0)
        onehot = (jnp.arange(params.num_global_res)[:, None]
                  == res_idx[None, :])                           # [Rg, NR]
        want_depl = jnp.where(depletable[None, :], want_g, 0.0)
        demand = jnp.einsum("nr,gr->g", want_depl, onehot.astype(jnp.float32))
        scale_res = jnp.where(demand > resources,
                              resources / jnp.maximum(demand, 1e-30), 1.0)
        scale_rxn = jnp.einsum("g,gr->r", scale_res,
                               onehot.astype(jnp.float32))
        scale_rxn = jnp.where(infinite | spatial | ~depletable, 1.0, scale_rxn)
        got_g = want_g * scale_rxn[None, :]
        drawn = jnp.einsum("nr,gr->g",
                           jnp.where(is_g & depletable[None, :], got_g, 0.0),
                           onehot.astype(jnp.float32))
        resources = jnp.maximum(resources - drawn, 0.0)
    else:
        got_g = jnp.zeros_like(wanted)
        scale_rxn = jnp.ones(res_idx.shape[0], jnp.float32)

    # ---- spatial: one organism per cell, no contention ----
    if params.num_spatial_res:
        is_s = (~infinite & spatial)[None, :]
        got_s = jnp.where(is_s, wanted, 0.0)                     # [N, NR]
        onehot_s = (jnp.arange(params.num_spatial_res)[:, None]
                    == res_idx[None, :])                         # [Rs, NR]
        drawn_s = jnp.einsum("nr,sr->sn",
                             jnp.where(is_s & depletable[None, :], got_s, 0.0),
                             onehot_s.astype(jnp.float32))
        res_grid = jnp.maximum(res_grid - drawn_s, 0.0)
    else:
        got_s = jnp.zeros_like(wanted)

    amount = jnp.where(infinite[None, :], wanted,
                       jnp.where(spatial[None, :], got_s, got_g))
    return amount, resources, res_grid
