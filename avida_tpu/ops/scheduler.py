"""Merit-proportional CPU-cycle allocation, lockstep style.

The reference serializes organisms through Apto schedulers
(cPopulation::BuildTimeSlicer, cPopulation.cc:7326; SLICING_METHOD semantics
at cAvidaConfig.h:545).  On TPU the stream of `Next()` picks collapses into a
per-update *instruction budget* per organism (SURVEY.md §7 step 3):

  method 0 (CONSTANT):     k_i = AVE_TIME_SLICE for every living organism
  method 1 (PROBABILISTIC):k_i ~ Binomial(UD_size, merit_i / sum(merit))
                           (independent binomials approximate the reference's
                           multinomial; documented deviation, statistically
                           equivalent at population scale)
  method 2 (INTEGRATED):   deterministic stride scheduling: k_i =
                           floor(c_i) counts of the merit-proportional share
                           with largest-remainder rounding

UD_size = AVE_TIME_SLICE * num_organisms (cWorld::CalculateUpdateSize,
cWorld.cc:247).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def compute_budgets(params, st, key):
    """Returns int32[N] per-organism instruction budgets for one update."""
    return compute_budgets_from(params, st.alive, st.merit, key)


def compute_budgets_from(params, alive, st_merit, key):
    """compute_budgets over bare (alive, merit) vectors -- the packed
    engine's fused path feeds these straight off the resident planes
    (alive from the ivec flag row, merit from the fvec row) without
    materializing a WorldState.  Same spelling as compute_budgets so
    both callers trace to the identical jaxpr."""
    num_orgs = alive.sum()
    ud_size = params.ave_time_slice * num_orgs

    if params.slicing_method == 0:
        return jnp.where(alive, params.ave_time_slice, 0).astype(jnp.int32)

    merit = jnp.where(alive, jnp.maximum(st_merit, 0.0), 0.0)
    total = merit.sum()
    # all-zero merit degenerates to constant slicing (reference merit >= 1)
    p = jnp.where(total > 0, merit / jnp.maximum(total, 1e-30), 0.0)

    if params.slicing_method == 1:
        n = alive.shape[0]
        if n >= 32768:
            # Large populations: Binomial(UD, p_i) with UD huge and p_i tiny
            # is Poisson(lam_i) to high accuracy, and lam_i ~ AVE_TIME_SLICE
            # makes the normal approximation to the Poisson accurate to a
            # relative skew of 1/sqrt(lam) ~ 0.18.  One normal draw per
            # organism instead of an iterative binomial sampler (which
            # dominated the update profile at 100k organisms).  Documented
            # deviation stacked on the already-documented multinomial ->
            # independent-binomials one.  The EQU-evolution harness
            # (scripts/equ_harness.py, results in EQU_r03.json) measures
            # first-discovery statistics under the full lockstep scheduler;
            # note this normal-approximation branch only engages at n >=
            # 32768, above the harness's 60x60 world -- at bench scale it
            # changes per-update budgets by <1 cycle rms.
            lam = p * ud_size.astype(jnp.float32)
            z = jax.random.normal(key, (n,))
            k = jnp.round(lam + jnp.sqrt(jnp.maximum(lam, 0.0)) * z)
            k = jnp.maximum(k, 0.0)
        else:
            k = jax.random.binomial(key, ud_size.astype(jnp.float32), p)
        k = jnp.where(alive, k, 0).astype(jnp.int32)
        return k

    if params.slicing_method == 2:
        # (the argsort here shows per-update [N] sorts are affordable on
        # this path -- the lane-permutation refresh in ops/update.perm_phase
        # relies on the same cost profile)
        share = p * ud_size.astype(p.dtype)
        base = jnp.floor(share)
        frac = share - base
        remainder = (ud_size - base.sum()).astype(jnp.int32)
        # largest-remainder rounding: hand out leftover cycles by frac rank
        order = jnp.argsort(-frac)
        rank = jnp.zeros_like(order).at[order].set(jnp.arange(order.shape[0]))
        k = base.astype(jnp.int32) + (rank < remainder).astype(jnp.int32)
        return jnp.where(alive, k, 0)

    raise NotImplementedError(f"SLICING_METHOD {params.slicing_method}")


def block_ceiling(granted, block: int):
    """Lockstep lane-cycle ceiling of a granted-budget vector under
    `block`-wide blocking: sum over blocks of block_size * block_max --
    the cycles the per-block while_loop actually burns (each block runs
    to the max granted budget of ITS lanes).  Shares the definition with
    observability/counters.budget_tail; traced (device scalar out).
    Returns FLOAT32: the int32 lane-cycle total wraps at bench scale
    (102k lanes) once uncapped grants pass ~20k cycles -- same overflow
    class the round-6 review caught in block_skip_fraction, fixed here
    at the primitive so every consumer (utilization, skip fraction, the
    telemetry ceiling_sum counter) is covered."""
    n = granted.shape[0]
    pad = (-n) % block
    g = jnp.pad(granted, (0, pad))           # padded lanes grant 0 cycles
    return (g.reshape(-1, block).max(axis=1).astype(jnp.float32)
            * jnp.float32(block)).sum()


def block_utilization(granted, block: int):
    """granted.sum() / block_ceiling: the fraction of lockstep lane-cycles
    doing useful work (1.0 = no budget tail).  The device-side imbalance
    statistic that triggers an early lane-permutation refresh
    (ops/update.perm_phase) and the bench's budget_tail_util field.
    Computed in float32 end-to-end (see block_ceiling): int32 lane-cycle
    totals wrap at bench scale once uncapped grants pass ~20k cycles."""
    ceil = block_ceiling(granted, block)
    return granted.astype(jnp.float32).sum() / jnp.maximum(ceil, 1.0)


def block_budget_histogram(granted, block: int):
    """Per-block (block_max int32[nb], block_sum int32[nb]) summary of a
    granted vector under `block`-wide blocking -- the two-level-
    scheduling attribution primitive: level 1 is the kernel's per-block
    while_loop running to block_max (ops/pallas_cycles.py), level 2 is
    the per-lane exec mask inside it, so block_max*block - block_sum is
    each block's budget-tail waste in lane-cycles.  Consumed by
    block_skip_fraction below (bench.py's budget_tail_skip_pct);
    exported for ad-hoc tail analysis.  Traced (device out)."""
    n = granted.shape[0]
    pad = (-n) % block
    g = jnp.pad(granted, (0, pad)).reshape(-1, block)
    return g.max(axis=1), g.sum(axis=1)


def block_skip_fraction(granted, block: int):
    """Fraction of lockstep lane-cycles the kernel's two-level scheduler
    SKIPS relative to a single global while_loop running every block to
    the global max budget: 1 - block_ceiling / (global_max * lanes).
    1.0-utilization measures the residual tail; this measures what the
    per-block early exit already saves.  Feeds bench.py's
    budget_tail_skip_pct field.  Float32 end-to-end (see block_ceiling):
    gmax * lanes overflows int32 at bench scale (102k lanes) once
    uncapped budget grants pass ~20k cycles."""
    n = granted.shape[0]
    pad = (-n) % block
    gmax = jnp.maximum(granted.max(), 1).astype(jnp.float32)
    total = gmax * jnp.float32(n + pad)
    return 1.0 - block_ceiling(granted, block) / jnp.maximum(total, 1.0)
