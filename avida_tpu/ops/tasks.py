"""Vectorized task evaluation: logic IDs and reaction rewards.

TPU-native re-expression of the IO hot path (SURVEY.md §3.4):
cOrganism::DoOutput -> cPhenotype::TestOutput -> cEnvironment::TestOutput
(cEnvironment.cc:1314) -> cTaskLib::SetupTests (cTaskLib.cc:369, the logic-ID
truth-table scan) -> TestRequisites (cc:1408) -> DoProcesses bonus math
(cc:1610,1731-1758).

The whole pipeline is batched over the population: one [N,32,8] truth-table
reduction computes every organism's logic ID, then reaction triggering,
requisite windows and pow/add/mult bonus application are masked tensor ops.
"""

from __future__ import annotations

import jax.numpy as jnp

PROCTYPE_ADD, PROCTYPE_MULT, PROCTYPE_POW, PROCTYPE_LIN = 0, 1, 2, 3


def compute_logic_id(input_buf, input_buf_n, output):
    """Batched cTaskLib::SetupTests (cTaskLib.cc:369-448).

    input_buf: int32[N,3] most-recent-first; input_buf_n: int32[N];
    output: int32[N].  Returns int32[N] logic id in [0,255], or -1 if the
    output is not a consistent pure function of the inputs.
    """
    n_in = input_buf_n
    i0 = jnp.where(n_in > 0, input_buf[:, 0], 0)
    i1 = jnp.where(n_in > 1, input_buf[:, 1], 0)
    i2 = jnp.where(n_in > 2, input_buf[:, 2], 0)

    j = jnp.arange(32, dtype=jnp.int32)
    b0 = (i0[:, None] >> j[None, :]) & 1          # [N,32]
    b1 = (i1[:, None] >> j[None, :]) & 1
    b2 = (i2[:, None] >> j[None, :]) & 1
    pos = b0 + 2 * b1 + 4 * b2                    # logic position per bit
    ob = (output[:, None] >> j[None, :]) & 1

    combos = jnp.arange(8, dtype=jnp.int32)
    onehot = (pos[:, :, None] == combos[None, None, :])          # [N,32,8]
    cnt = onehot.sum(axis=1)                                     # [N,8]
    ones = (onehot & (ob[:, :, None] == 1)).sum(axis=1)          # [N,8]
    consistent = (ones == 0) | (ones == cnt)
    func_ok = consistent.all(axis=1)

    lo = (ones > 0).astype(jnp.int32)             # defined where cnt>0
    # Fill rules for missing inputs (cTaskLib.cc:419-433): absent inputs are
    # zero, so combos with those bits set never occur; duplicate from below.
    def fill(lo, c_to, c_from, cond):
        return lo.at[:, c_to].set(jnp.where(cond, lo[:, c_from], lo[:, c_to]))
    lo = fill(lo, 1, 0, n_in < 1)
    lo = fill(lo, 2, 0, n_in < 2)
    lo = fill(lo, 3, 1, n_in < 2)
    for c in range(4):
        lo = fill(lo, 4 + c, c, n_in < 3)

    logic = (lo << combos[None, :]).sum(axis=1)
    return jnp.where(func_ok, logic, -1)


def apply_reactions(params, env_tables, io_mask, logic_id, cur_bonus,
                    cur_task_count, cur_reaction_count, resources, res_grid):
    """Trigger reactions for organisms performing IO this step.

    env_tables: dict of jnp arrays built from Environment.device_tables().
    Returns (new_bonus, new_task_count, new_reaction_count,
             new_resources, new_res_grid, any_reward[N]).

    Mirrors cEnvironment::TestOutput's reaction loop (cEnvironment.cc:1332-
    1404): each reaction fires if its task's logic-id set contains logic_id
    and its requisite windows pass; rewards consume bound resources
    (ops/resources.py) and apply pow/add/mult of value x consumed-amount to
    the bonus (DoProcesses cc:1731-1758).  Stock logic-9 uses requisite
    max_count=1 so only the first performance per gestation is rewarded.
    """
    from avida_tpu.ops import resources as res_ops

    mask = env_tables["task_logic_mask"]          # bool[R,256]
    value = env_tables["proc_value"]              # f[R]
    ptype = env_tables["proc_type"]               # i[R]
    max_tc = env_tables["max_task_count"]
    min_tc = env_tables["min_task_count"]
    req = env_tables["req_reaction_mask"]         # bool[R,R]
    noreq = env_tables["noreq_reaction_mask"]

    lid = jnp.clip(logic_id, 0, 255)
    valid = (logic_id >= 0) & io_mask             # [N]
    performed = mask[:, lid].T & valid[:, None]   # [N,R] task performed now

    # Requisite windows evaluated against pre-event counts (cc:1408-1470)
    in_window = ((cur_task_count >= min_tc[None, :]) &
                 (cur_task_count < max_tc[None, :]))
    rc_zero = (cur_reaction_count == 0)           # [N,R]
    req_ok = ~jnp.any(req[None, :, :] & rc_zero[:, None, :], axis=2)
    noreq_ok = ~jnp.any(noreq[None, :, :] & ~rc_zero[:, None, :], axis=2)

    rewarded = performed & in_window & req_ok & noreq_ok

    # resource consumption -> per-(org, reaction) amounts (1.0 if infinite)
    amount, resources, res_grid = res_ops.consume(
        params, env_tables, rewarded, 1.0, resources, res_grid)

    fdt = cur_bonus.dtype
    fval = value[None, :].astype(fdt)
    va = fval * amount.astype(fdt)                # value x consumed units
    pow_mult = jnp.where(rewarded & (ptype[None, :] == PROCTYPE_POW),
                         jnp.exp2(va), 1.0).prod(axis=1)
    mult_mult = jnp.where(rewarded & (ptype[None, :] == PROCTYPE_MULT) &
                          (va != 0), va, 1.0).prod(axis=1)
    add_sum = jnp.where(rewarded & (ptype[None, :] == PROCTYPE_ADD),
                        va, 0.0).sum(axis=1)

    new_bonus = cur_bonus * pow_mult * mult_mult + add_sum
    new_task_count = cur_task_count + performed.astype(jnp.int32)
    new_reaction_count = cur_reaction_count + rewarded.astype(jnp.int32)
    return (new_bonus, new_task_count, new_reaction_count,
            resources, res_grid, rewarded.any(axis=1))


def env_tables_to_device(params):
    """Materialize the WorldParams env tuples as jnp arrays (traced constants)."""
    return {
        "task_logic_mask": jnp.asarray(params.task_logic_mask, bool),
        "proc_value": jnp.asarray(params.proc_value, jnp.float32),
        "proc_type": jnp.asarray(params.proc_type, jnp.int32),
        "max_task_count": jnp.asarray(params.max_task_count, jnp.int32),
        "min_task_count": jnp.asarray(params.min_task_count, jnp.int32),
        "req_reaction_mask": jnp.asarray(params.req_reaction_mask, bool),
        "noreq_reaction_mask": jnp.asarray(params.noreq_reaction_mask, bool),
        "proc_res_idx": jnp.asarray(params.proc_res_idx, jnp.int32),
        "proc_res_spatial": jnp.asarray(params.proc_res_spatial, bool),
        "proc_max": jnp.asarray(params.proc_max, jnp.float32),
        "proc_frac": jnp.asarray(params.proc_frac, jnp.float32),
        "proc_depletable": jnp.asarray(params.proc_depletable, bool),
    }
