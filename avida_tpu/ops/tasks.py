"""Vectorized task evaluation: logic IDs and reaction rewards.

TPU-native re-expression of the IO hot path (SURVEY.md §3.4):
cOrganism::DoOutput -> cPhenotype::TestOutput -> cEnvironment::TestOutput
(cEnvironment.cc:1314) -> cTaskLib::SetupTests (cTaskLib.cc:369, the logic-ID
truth-table scan) -> TestRequisites (cc:1408) -> DoProcesses bonus math
(cc:1610,1731-1758).

The whole pipeline is batched over the population: one [N,32,8] truth-table
reduction computes every organism's logic ID, then reaction triggering,
requisite windows and pow/add/mult bonus application are masked tensor ops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

PROCTYPE_ADD, PROCTYPE_MULT, PROCTYPE_POW, PROCTYPE_LIN = 0, 1, 2, 3


# ---- math task family (cTaskLib.cc:191-260, Task_Math{1,2,3}in_*) ----
# Each task matches when the output equals the expression over ANY stored
# input (arity 1) / ordered pair (arity 2) / ordered triple (arity 3).
# C integer semantics: division/modulo truncate toward zero (lax.div/rem);
# sqrt/log are (int)-cast doubles on |x| / x-positive respectively.

def _isqrt(x):
    return jnp.sqrt(jnp.abs(x).astype(jnp.float32)).astype(jnp.int32)


def _ilog(x):
    # (int) log((double) x): non-positive x never matches (C UB made safe)
    safe = jnp.log(jnp.maximum(x, 1).astype(jnp.float32)).astype(jnp.int32)
    return jnp.where(x > 0, safe, jnp.int32(-(2**30)))


def _cdiv(a, b):
    return jnp.where(b != 0, jax.lax.div(a, jnp.where(b == 0, 1, b)),
                     jnp.int32(-(2**30)))


def _crem(a, b):
    return jnp.where(b != 0, jax.lax.rem(a, jnp.where(b == 0, 1, b)),
                     jnp.int32(-(2**30)))


MATH_TASKS = {
    # arity 1 (cTaskLib.cc:191-207)
    "math_1AA": (1, lambda x: 2 * x),
    "math_1AB": (1, lambda x: _cdiv(2 * x, jnp.int32(3))),
    "math_1AC": (1, lambda x: _cdiv(5 * x, jnp.int32(4))),
    "math_1AD": (1, lambda x: x * x),
    "math_1AE": (1, lambda x: x * x * x),
    "math_1AF": (1, _isqrt),
    "math_1AG": (1, _ilog),
    "math_1AH": (1, lambda x: x * x + x * x * x),
    "math_1AI": (1, lambda x: x * x + _isqrt(x)),
    "math_1AJ": (1, lambda x: jnp.abs(x)),
    "math_1AK": (1, lambda x: x - 5),
    "math_1AL": (1, lambda x: -x),
    "math_1AM": (1, lambda x: 5 * x),
    "math_1AN": (1, lambda x: _cdiv(x, jnp.int32(4))),
    "math_1AO": (1, lambda x: x - 6),
    "math_1AP": (1, lambda x: x - 7),
    "math_1AS": (1, lambda x: 3 * x),
    # arity 2 (cTaskLib.cc:210-236)
    "math_2AA": (2, lambda x, y: _isqrt(x + y)),
    "math_2AB": (2, lambda x, y: (x + y) * (x + y)),
    "math_2AC": (2, _crem),
    "math_2AD": (2, lambda x, y: _cdiv(3 * x, jnp.int32(2))
                 + _cdiv(5 * y, jnp.int32(4))),
    "math_2AE": (2, lambda x, y: jnp.abs(x - 5) + jnp.abs(y - 6)),
    "math_2AF": (2, lambda x, y: x * y - _cdiv(x, y)),
    "math_2AG": (2, lambda x, y: (x - y) * (x - y)),
    "math_2AH": (2, lambda x, y: x * x + y * y),
    "math_2AI": (2, lambda x, y: x * x + y * y * y),
    "math_2AJ": (2, lambda x, y: _cdiv(_isqrt(x) + y, x - 7)),
    "math_2AK": (2, lambda x, y: _ilog(jnp.abs(_cdiv(x, y)))),
    "math_2AL": (2, lambda x, y: _cdiv(_ilog(jnp.abs(x)), y)),
    "math_2AM": (2, lambda x, y: _cdiv(x, _ilog(jnp.abs(y)))),
    "math_2AN": (2, lambda x, y: x + y),
    "math_2AO": (2, lambda x, y: x - y),
    "math_2AP": (2, _cdiv),
    "math_2AQ": (2, lambda x, y: x * y),
    "math_2AR": (2, lambda x, y: _isqrt(x) + _isqrt(y)),
    "math_2AS": (2, lambda x, y: x + 2 * y),
    "math_2AT": (2, lambda x, y: x + 3 * y),
    "math_2AU": (2, lambda x, y: 2 * x + 3 * y),
    "math_2AV": (2, lambda x, y: x * y * y),
    # 2AX duplicates 2AT and 2AW does not exist IN THE REFERENCE TOO
    # (cTaskLib.cc:232 Task_Math2in_AX is literally X+3Y again)
    "math_2AX": (2, lambda x, y: x + 3 * y),
    "math_2AY": (2, lambda x, y: 2 * x + y),
    "math_2AZ": (2, lambda x, y: 4 * x + 6 * y),
    "math_2AAA": (2, lambda x, y: 3 * x - 2 * y),
    # arity 3 (cTaskLib.cc:239-260)
    "math_3AA": (3, lambda x, y, z: x * x + y * y + z * z),
    "math_3AB": (3, lambda x, y, z: _isqrt(x) + _isqrt(y) + _isqrt(z)),
    "math_3AC": (3, lambda x, y, z: x + 2 * y + 3 * z),
    "math_3AD": (3, lambda x, y, z: x * y * y + z * z * z),
    "math_3AE": (3, lambda x, y, z: _crem(x, y) * z),
    "math_3AF": (3, lambda x, y, z: (x + y) * (x + y) + _isqrt(y + z)),
    "math_3AG": (3, lambda x, y, z: _crem(x * y, y * z)),
    "math_3AH": (3, lambda x, y, z: x + y + z),
    "math_3AI": (3, lambda x, y, z: -x - y - z),
    "math_3AJ": (3, lambda x, y, z: (x - y) * (x - y) + (y - z) * (y - z)
                 + (z - x) * (z - x)),
    "math_3AK": (3, lambda x, y, z: (x + y) * (x + y) + (y + z) * (y + z)
                 + (z + x) * (z + x)),
    "math_3AL": (3, lambda x, y, z: (x - y) * (x - y) + (x - z) * (x - z)),
    "math_3AM": (3, lambda x, y, z: (x + y) * (x + y) + (x + z) * (x + z)),
}


def math_performed(task_name, input_buf, input_buf_n, output):
    """bool[N]: does `output` match math task `task_name` over any stored
    input combination (the reference's nested input loops, e.g.
    Task_Math2in_AA)?"""
    arity, fn = MATH_TASKS[task_name]
    ins = [input_buf[:, k] for k in range(3)]
    have = [input_buf_n > k for k in range(3)]
    hit = jnp.zeros(output.shape, bool)
    if arity == 1:
        for i in range(3):
            hit = hit | (have[i] & (output == fn(ins[i])))
    elif arity == 2:
        for i in range(3):
            for j in range(3):
                if i == j:
                    continue
                hit = hit | (have[i] & have[j] &
                             (output == fn(ins[i], ins[j])))
    else:
        import itertools
        for i, j, k in itertools.permutations(range(3)):
            hit = hit | (have[i] & have[j] & have[k] &
                         (output == fn(ins[i], ins[j], ins[k])))
    return hit


def compute_logic_id(input_buf, input_buf_n, output):
    """Batched cTaskLib::SetupTests (cTaskLib.cc:369-448).

    input_buf: int32[N,3] most-recent-first; input_buf_n: int32[N];
    output: int32[N].  Returns int32[N] logic id in [0,255], or -1 if the
    output is not a consistent pure function of the inputs.
    """
    n_in = input_buf_n
    i0 = jnp.where(n_in > 0, input_buf[:, 0], 0)
    i1 = jnp.where(n_in > 1, input_buf[:, 1], 0)
    i2 = jnp.where(n_in > 2, input_buf[:, 2], 0)

    j = jnp.arange(32, dtype=jnp.int32)
    b0 = (i0[:, None] >> j[None, :]) & 1          # [N,32]
    b1 = (i1[:, None] >> j[None, :]) & 1
    b2 = (i2[:, None] >> j[None, :]) & 1
    pos = b0 + 2 * b1 + 4 * b2                    # logic position per bit
    ob = (output[:, None] >> j[None, :]) & 1

    combos = jnp.arange(8, dtype=jnp.int32)
    onehot = (pos[:, :, None] == combos[None, None, :])          # [N,32,8]
    cnt = onehot.sum(axis=1)                                     # [N,8]
    ones = (onehot & (ob[:, :, None] == 1)).sum(axis=1)          # [N,8]
    consistent = (ones == 0) | (ones == cnt)
    func_ok = consistent.all(axis=1)

    lo = (ones > 0).astype(jnp.int32)             # defined where cnt>0
    # Fill rules for missing inputs (cTaskLib.cc:419-433): absent inputs are
    # zero, so combos with those bits set never occur; duplicate from below.
    def fill(lo, c_to, c_from, cond):
        return lo.at[:, c_to].set(jnp.where(cond, lo[:, c_from], lo[:, c_to]))
    lo = fill(lo, 1, 0, n_in < 1)
    lo = fill(lo, 2, 0, n_in < 2)
    lo = fill(lo, 3, 1, n_in < 2)
    for c in range(4):
        lo = fill(lo, 4 + c, c, n_in < 3)

    logic = (lo << combos[None, :]).sum(axis=1)
    return jnp.where(func_ok, logic, -1)


def apply_reactions(params, env_tables, io_mask, logic_id, cur_bonus,
                    cur_task_count, cur_reaction_count, resources, res_grid,
                    deme_resources=None,
                    input_buf=None, input_buf_n=None, output=None):
    """Trigger reactions for organisms performing IO this step.

    env_tables: dict of jnp arrays built from Environment.device_tables().
    Returns (new_bonus, new_task_count, new_reaction_count,
             new_resources, new_res_grid, new_deme_resources, any_reward[N]).

    Mirrors cEnvironment::TestOutput's reaction loop (cEnvironment.cc:1332-
    1404): each reaction fires if its task's logic-id set contains logic_id
    and its requisite windows pass; rewards consume bound resources
    (ops/resources.py) and apply pow/add/mult of value x consumed-amount to
    the bonus (DoProcesses cc:1731-1758).  Stock logic-9 uses requisite
    max_count=1 so only the first performance per gestation is rewarded.
    """
    from avida_tpu.ops import resources as res_ops

    mask = env_tables["task_logic_mask"]          # bool[R,256]
    value = env_tables["proc_value"]              # f[R]
    ptype = env_tables["proc_type"]               # i[R]
    max_tc = env_tables["max_task_count"]
    min_tc = env_tables["min_task_count"]
    req = env_tables["req_reaction_mask"]         # bool[R,R]
    noreq = env_tables["noreq_reaction_mask"]

    lid = jnp.clip(logic_id, 0, 255)
    valid = (logic_id >= 0) & io_mask             # [N]
    performed = mask[:, lid].T & valid[:, None]   # [N,R] task performed now
    # math-family reactions match arithmetic candidates instead of logic ids
    math_names = getattr(params, "task_math_name", ())
    if any(math_names) and input_buf is not None:
        cols = []
        for r, nm in enumerate(math_names):
            if nm:
                cols.append((r, math_performed(nm, input_buf, input_buf_n,
                                               output) & io_mask))
        for r, col in cols:
            performed = performed.at[:, r].set(col)

    # Requisite windows evaluated against pre-event counts (cc:1408-1470)
    in_window = ((cur_task_count >= min_tc[None, :]) &
                 (cur_task_count < max_tc[None, :]))
    rc_zero = (cur_reaction_count == 0)           # [N,R]
    req_ok = ~jnp.any(req[None, :, :] & rc_zero[:, None, :], axis=2)
    noreq_ok = ~jnp.any(noreq[None, :, :] & ~rc_zero[:, None, :], axis=2)

    rewarded = performed & in_window & req_ok & noreq_ok

    # resource consumption -> per-(org, reaction) amounts (1.0 if infinite)
    amount, resources, res_grid = res_ops.consume(
        params, env_tables, rewarded, 1.0, resources, res_grid)
    if params.num_deme_res and deme_resources is not None:
        amt_d, deme_resources = res_ops.consume_deme(
            params, env_tables, rewarded, deme_resources)
        is_deme = jnp.asarray(params.proc_res_deme, bool)
        amount = jnp.where(is_deme[None, :], amt_d, amount)

    # by-products: produced = consumed * conversion into the product pool
    # (DoProcesses cc:1824-1830); gated statically on any product binding
    prod_idx = tuple(getattr(params, "proc_product_idx", ()))
    if any(pi >= 0 for pi in prod_idx):
        conv = jnp.asarray(params.proc_conversion, resources.dtype)
        produced = jnp.where(rewarded, amount, 0.0) * conv[None, :]
        for r, pi in enumerate(prod_idx):
            if pi < 0:
                continue
            if params.proc_product_spatial[r]:
                res_grid = res_grid.at[pi].add(produced[:, r])
            else:
                resources = resources.at[pi].add(produced[:, r].sum())

    fdt = cur_bonus.dtype
    fval = value[None, :].astype(fdt)
    va = fval * amount.astype(fdt)                # value x consumed units
    pow_mult = jnp.where(rewarded & (ptype[None, :] == PROCTYPE_POW),
                         jnp.exp2(va), 1.0).prod(axis=1)
    mult_mult = jnp.where(rewarded & (ptype[None, :] == PROCTYPE_MULT) &
                          (va != 0), va, 1.0).prod(axis=1)
    add_sum = jnp.where(rewarded & (ptype[None, :] == PROCTYPE_ADD),
                        va, 0.0).sum(axis=1)

    new_bonus = cur_bonus * pow_mult * mult_mult + add_sum
    new_task_count = cur_task_count + performed.astype(jnp.int32)
    new_reaction_count = cur_reaction_count + rewarded.astype(jnp.int32)
    return (new_bonus, new_task_count, new_reaction_count,
            resources, res_grid, deme_resources, rewarded.any(axis=1))


def env_tables_to_device(params):
    """Materialize the WorldParams env tuples as jnp arrays (traced constants)."""
    return {
        "task_logic_mask": jnp.asarray(params.task_logic_mask, bool),
        "proc_value": jnp.asarray(params.proc_value, jnp.float32),
        "proc_type": jnp.asarray(params.proc_type, jnp.int32),
        "max_task_count": jnp.asarray(params.max_task_count, jnp.int32),
        "min_task_count": jnp.asarray(params.min_task_count, jnp.int32),
        "req_reaction_mask": jnp.asarray(params.req_reaction_mask, bool),
        "noreq_reaction_mask": jnp.asarray(params.noreq_reaction_mask, bool),
        "proc_res_idx": jnp.asarray(params.proc_res_idx, jnp.int32),
        "proc_res_spatial": jnp.asarray(params.proc_res_spatial, bool),
        "proc_max": jnp.asarray(params.proc_max, jnp.float32),
        "proc_frac": jnp.asarray(params.proc_frac, jnp.float32),
        "proc_depletable": jnp.asarray(params.proc_depletable, bool),
    }
