"""One full population update as a single jitted device program.

The reference update (Avida2Driver::Run loop body, Avida2Driver.cc:91-165 +
cPopulation::ProcessStep cc:5703) serializes UD_size = AVE_TIME_SLICE x
num_orgs organism-instruction steps.  Here the whole update runs on device:

  1. sample per-organism instruction budgets (ops/scheduler.py)
  2. a lax.while_loop of lockstep micro-steps with execution masks
     (ops/interpreter.py) until every budget is exhausted
  3. flush pending births as a batched scatter (ops/birth.py)
  4. optional point-mutation sweep (Avida2Driver.cc:146-155)

Host code only orchestrates updates and reads back stats at report
boundaries -- no per-step host/device synchronization.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from avida_tpu.ops import birth as birth_ops
from avida_tpu.ops import scheduler as sched_ops
from avida_tpu.ops.interpreter import micro_step


@partial(jax.jit, static_argnums=0)
def update_step(params, st, key, neighbors, update_no):
    """Run one update.  Returns (new_state, executed_this_update)."""
    k_budget, k_steps, k_birth = jax.random.split(key, 3)

    budgets = sched_ops.compute_budgets(params, st, k_budget)
    max_k = budgets.max()
    if params.max_steps_per_update:
        max_k = jnp.minimum(max_k, params.max_steps_per_update)
        budgets = jnp.minimum(budgets, params.max_steps_per_update)

    executed0 = st.insts_executed

    def cond(carry):
        s, _ = carry
        return s < max_k

    def body(carry):
        s, st = carry
        exec_mask = st.alive & (s < budgets)
        st = micro_step(params, st, jax.random.fold_in(k_steps, s), exec_mask)
        return s + 1, st

    _, st = jax.lax.while_loop(cond, body, (jnp.int32(0), st))

    st = birth_ops.flush_births(params, st, k_birth, neighbors, update_no)

    if params.point_mut_prob > 0:
        st = _point_mutation_sweep(params, st, jax.random.fold_in(k_steps, -1))

    executed = (st.insts_executed - executed0).sum()
    return st, executed


def _point_mutation_sweep(params, st, key):
    """Per-site point mutations once per update (Avida2Driver.cc:146-155 ->
    cHardwareBase::PointMutate cc:1087)."""
    n, L = st.mem.shape
    u = jax.random.uniform(key, (n, L))
    r = jax.random.randint(jax.random.fold_in(key, 1), (n, L), 0,
                           params.num_insts, dtype=jnp.int8)
    in_genome = jnp.arange(L)[None, :] < st.mem_len[:, None]
    hit = (u < params.point_mut_prob) & in_genome & st.alive[:, None]
    return st.replace(mem=jnp.where(hit, r, st.mem))


@partial(jax.jit, static_argnums=0)
def summarize(params, st):
    """Device-side reduction of per-update stats (feeds cStats/.dat output;
    ref cPopulation::UpdateOrganismStats cc:5847)."""
    alive = st.alive
    n_alive = alive.sum()
    denom = jnp.maximum(n_alive, 1).astype(st.merit.dtype)
    fdt = st.merit.dtype

    def avg(x):
        return jnp.where(alive, x.astype(fdt), 0).sum() / denom

    gest = jnp.where(alive, st.gestation_time, 0)
    has_gest = alive & (st.gestation_time > 0)
    gest_denom = jnp.maximum(has_gest.sum(), 1).astype(fdt)

    task_counts = (alive[:, None] & (st.last_task_count > 0)).sum(axis=0)
    task_doing = (alive[:, None] & (st.cur_task_count > 0)).sum(axis=0)

    return {
        "num_organisms": n_alive,
        "ave_merit": avg(st.merit),
        "ave_fitness": avg(st.fitness),
        "ave_gestation": jnp.where(has_gest, gest, 0).sum().astype(fdt) / gest_denom,
        "ave_genome_len": avg(st.genome_len),
        "ave_generation": avg(st.generation),
        "ave_age": avg(st.time_used),
        "max_fitness": jnp.where(alive, st.fitness, 0).max(),
        "max_merit": jnp.where(alive, st.merit, 0).max(),
        "num_births": (alive & (st.birth_update >= 0)).sum(),
        "total_insts": st.insts_executed.astype(jnp.int64).sum()
        if jax.config.jax_enable_x64 else st.insts_executed.sum(),
        "task_counts": task_counts,
        "task_doing": task_doing,
        "num_divides": st.num_divides.sum(),
    }
