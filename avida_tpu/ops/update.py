"""One full population update as a single jitted device program.

The reference update (Avida2Driver::Run loop body, Avida2Driver.cc:91-165 +
cPopulation::ProcessStep cc:5703) serializes UD_size = AVE_TIME_SLICE x
num_orgs organism-instruction steps.  Here the whole update runs on device:

  1. sample per-organism instruction budgets (ops/scheduler.py)
  2. a lax.while_loop of lockstep micro-steps with execution masks
     (ops/interpreter.py) until every budget is exhausted
  3. flush pending births as a batched scatter (ops/birth.py)
  4. optional point-mutation sweep (Avida2Driver.cc:146-155)

Host code only orchestrates updates and reads back stats at report
boundaries -- no per-step host/device synchronization.

The update is decomposed into PHASE functions (resource_phase,
schedule_phase, interpret_phase, bank_phase, birth_phase) so the same
code runs two ways: `update_step` fuses all phases into one device
program (the production path), while the telemetry harness
(avida_tpu/observability/) jits each phase separately and fences between
them to attribute wall time.  The phase split is pure code motion: with
telemetry disabled `update_step` traces to the identical jaxpr
(tests/test_telemetry.py guards this).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from avida_tpu.ops import birth as birth_ops
from avida_tpu.ops import pallas_cycles
from avida_tpu.ops import resources as res_ops
from avida_tpu.ops import scheduler as sched_ops
from avida_tpu.ops.interpreter import micro_step


def use_pallas_path(params) -> bool:
    """Trace-time routing between the VMEM-resident Pallas cycle kernel
    (ops/pallas_cycles.py) and the XLA micro-step loop.  TPU_USE_PALLAS:
    0 = auto (kernel on TPU when the environment qualifies -- any device
    count), 1 = force (kernel everywhere; interpret mode off-TPU --
    tests use this; raises if the environment disqualifies the kernel),
    2 = off.

    Multi-device runs take the kernel too: pallas_call registers no GSPMD
    partitioning rule, so pallas_cycles.run_packed shard_maps the launch
    over the `cells` mesh axis itself (one independent launch per shard;
    blocks never communicate under the fast-path precondition).  The
    birth flush stays OUTSIDE the shard_map on the ordinary GSPMD path,
    so boundary-crossing births keep tests/test_parallel.py's sharded ==
    unsharded bit-exactness guarantee."""
    if params.hw_type != 0:
        return False      # the cycle kernel implements heads hardware only
    if params.use_pallas == 2:
        return False
    if params.use_pallas == 1:
        if not pallas_cycles.eligible(params):
            raise ValueError(
                "TPU_USE_PALLAS=1 but this configuration disqualifies the "
                "Pallas cycle kernel (ops/pallas_cycles.eligible): a "
                "resource-bound reaction, by-products, math tasks, the "
                "energy model, MAX_CPU_THREADS > 1, or an instruction set "
                "with thread/mating-type instructions; use TPU_USE_PALLAS="
                "0 or 2")
        return True
    return (pallas_cycles.eligible(params)
            and jax.devices()[0].platform == "tpu")


def static_cap(params) -> int:
    """The static per-update step cap (2^31-1 when uncapped)."""
    cap = int(params.max_steps_per_update)
    return cap if cap > 0 else 2**31 - 1


def resource_phase(params, st, key, update_no):
    """Resource dynamics integrate once per update (ops/resources.py)."""
    st = st.replace(resources=res_ops.step_global(params, st.resources),
                    res_grid=res_ops.step_spatial(params, st.res_grid),
                    deme_resources=res_ops.step_deme(params,
                                                     st.deme_resources))
    return res_ops.step_gradient(params, st, jax.random.fold_in(key, 0x6AD),
                                 update_no)


def schedule_phase(params, st, k_budget):
    """Sample merit-proportional budgets and apply the burst cap.
    Returns (budgets, granted, max_k); the cap itself is static
    (static_cap)."""
    budgets = sched_ops.compute_budgets(params, st, k_budget)
    return schedule_grant(params, budgets, st.budget_carry)


def schedule_grant(params, budgets, budget_carry):
    """Carry + burst-cap half of schedule_phase, over bare vectors.  The
    packed engine's fused path calls this directly with the carry row it
    owns, skipping the WorldState mirror entirely; schedule_phase above
    is the canonical spelling so both trace identically."""
    # Budget carry-over (TPU lockstep semantic, SURVEY §7 step 3).  By
    # DEFAULT (TPU_MAX_STEPS_PER_UPDATE = 0) every organism executes its
    # full merit-proportional budget within the update -- the reference's
    # scheduling semantics exactly (burst-capped runs measurably slow
    # selective sweeps: median updates-to-EQU moved from ~3.5k to >12k
    # under a 2x cap; BASELINE.md).  Setting TPU_MAX_STEPS_PER_UPDATE > 0
    # is a throughput opt-in: within-update bursts are capped so SIMD
    # lanes stay busy on heavy-tailed merit distributions, and cycles an
    # organism earned but could not execute (cap, or the post-divide stall
    # below) are banked per-organism (up to 100 x AVE_TIME_SLICE) and
    # re-granted next update -- bounded-burst stride scheduling that
    # preserves long-run merit proportionality but time-smears fixation
    # sweeps (documented deviation).
    budgets = budgets + budget_carry
    cap = int(params.max_steps_per_update)
    if cap > 0:
        max_k = jnp.minimum(budgets.max(), cap)
        granted = jnp.minimum(budgets, max_k)
    else:                  # uncapped: reference-faithful bursts
        max_k = budgets.max()
        granted = budgets
    return budgets, granted, max_k


def scheduler_probe(params, st, seed: int = 0):
    """Deterministic re-sample of the scheduler's budget grant with a
    FIXED key, outside the run's PRNG stream.  Out-of-band consumers
    only: the state auditor's dead-lane/scheduler-consistency invariant
    (utils/audit.py) and bench.py's budget-tail facts.  Never called
    from update_step, so the production update trace is untouched
    (scripts/check_jaxpr.py digest)."""
    return schedule_phase(params, st, jax.random.key(seed))


def perm_phase(params, st, granted, update_no):
    """Refresh the persistent budget-aware lane permutation
    (st.lane_perm/lane_inv; consumed by pallas_cycles.run_cycles to pack
    budget-sorted organisms into kernel lanes).  KERNEL path only: the
    XLA while_loop has no lane blocks, and compiling the sort into every
    XLA-path update program measurably inflates suite-wide compile time
    (~+35% per update_step on CPU) for zero benefit -- so on the XLA
    path the fields stay identity and cross-engine comparisons skip them
    (tests/test_pallas.py; the permutation is transparent to physics).

    Schedule: K = lane_perm_k.  K == 1 re-sorts by THIS update's granted
    vector (exact budget packing -- kills the binomial sampling noise in
    the block tail, not just merit heterogeneity).  K > 1 amortizes the
    sort: refresh on update_no % K == 0, sorted by merit (the stable
    signal budgets are drawn from), plus an early refresh whenever the
    measured block utilization of the CURRENT permutation drops below
    lane_perm_min_util (the cheap device-side imbalance statistic --
    same definition as observability/counters.budget_tail)."""
    K = int(params.lane_perm_k)
    if K <= 0 or not use_pallas_path(params):
        return st
    from avida_tpu.ops import packed_chunk
    if packed_chunk.active(params, st):
        # packed residency supersedes lane packing: the resident planes
        # are CELL-ordered (the packed-native birth flush is lane-axis
        # rolls, only meaningful in grid order), and the per-update
        # reference path must keep the identity mapping too so both
        # paths assign the same organisms to the same kernel lanes
        # (identical per-lane PRNG streams => bit-exact trajectories)
        return st
    n = granted.shape[0]

    def refresh(_):
        key_vec = (granted if K == 1
                   else jnp.where(st.alive, st.merit, -1.0))
        p = jnp.argsort(key_vec).astype(jnp.int32)
        inv = jnp.zeros_like(p).at[p].set(jnp.arange(n, dtype=jnp.int32))
        return p, inv

    if K == 1:
        p, inv = refresh(None)
    else:
        block = pallas_cycles.block_dims(params, n)[0]
        util = sched_ops.block_utilization(granted[st.lane_perm], block)
        due = (update_no % K) == 0
        p, inv = jax.lax.cond(
            due | (util < params.lane_perm_min_util), refresh,
            lambda _: (st.lane_perm, st.lane_inv), None)
    return st.replace(lane_perm=p, lane_inv=inv)


def _trace_append(params, st, mask, cells, code, payloads, update_no):
    """Append one event per True lane of `mask` to the flight-recorder
    ring (st.tr_*).  Slot = event_number % trace_cap, so overflow
    overwrites the OLDEST events; the monotone tr_count cursor lets the
    host recover the drop count -- no early sync, ever.  Masked-off
    lanes scatter to index `cap`, which mode="drop" discards.  Pure
    append-only side state: nothing downstream reads the ring, so the
    evolved trajectory is independent of what lands here."""
    cap = int(params.trace_cap)
    m = mask.astype(jnp.int32)
    offs = jnp.cumsum(m) - 1
    total = m.sum()
    # a single batch wider than the ring would scatter the same slot
    # twice in one .at[].set (nondeterministic winner): pre-drop the
    # batch's own oldest events so only the newest `cap` write -- the
    # same drop-oldest semantics, decided before the scatter
    keep = mask & (offs >= total - cap)
    pos = jnp.where(keep, (st.tr_count + offs) % cap, cap).astype(jnp.int32)
    return st.replace(
        tr_update=st.tr_update.at[pos].set(update_no, mode="drop"),
        tr_cell=st.tr_cell.at[pos].set(cells, mode="drop"),
        tr_code=st.tr_code.at[pos].set(jnp.int32(code), mode="drop"),
        tr_payload=st.tr_payload.at[pos].set(
            payloads.astype(jnp.int32), mode="drop"),
        tr_count=st.tr_count + total,
    )


def trace_pre_phase(params, st, granted, update_no):
    """Flight recorder, first half (after schedule/perm, before the cycle
    loop): emit the scheduler-stall event and snapshot what the post-
    update emission diffs against.  Returns (st, snapshot dict).  Only
    traced when params.trace_cap > 0 -- with the recorder off update_step
    never calls this and its jaxpr is unchanged (scripts/check_jaxpr.py)."""
    from avida_tpu.observability import tracer
    from avida_tpu.ops.interpreter import anomaly_masks
    n = granted.shape[0]
    if use_pallas_path(params):
        block = pallas_cycles.block_dims(params, n)[0]
        g = granted[st.lane_perm] if int(params.lane_perm_k) > 0 else granted
    else:
        block = n                 # the XLA while_loop is one global block
        g = granted
    util = sched_ops.block_utilization(g, block)
    st = _trace_append(
        params, st,
        (util < params.trace_stall_util)[None],
        jnp.full((1,), -1, jnp.int32),
        tracer.EV_SCHED_STALL,
        jnp.round(util * 1e4).astype(jnp.int32)[None],
        update_no)
    bad_merit, bad_head, _ = anomaly_masks(params, st)
    snap = {"alive": st.alive, "genotype_id": st.genotype_id,
            "task_seen": st.task_exe_total > 0,
            "bad_merit": bad_merit, "bad_head": bad_head}
    return st, snap


def trace_post_phase(params, st, snap, update_no):
    """Flight recorder, second half (after the birth flush): births and
    deaths (with ancestry payloads), first-time task triggers at the
    cell, and audit-adjacent anomalies.  Append-only ring writes; see
    trace_pre_phase for the disabled-path guarantee."""
    from avida_tpu.observability import tracer
    from avida_tpu.ops.interpreter import anomaly_masks
    n = st.alive.shape[0]
    cells = jnp.arange(n, dtype=jnp.int32)

    born, died = birth_ops.birth_death_masks(snap["alive"], st, update_no)
    st = _trace_append(params, st, born, cells, tracer.EV_BIRTH,
                       st.parent_id, update_no)
    st = _trace_append(params, st, died, cells, tracer.EV_DEATH,
                       snap["genotype_id"], update_no)

    # first execution of a task at this cell (task_exe_total is the
    # per-cell lifetime counter, never reset): payload = bitmask of the
    # newly first-executed task columns (capped at 31 bits)
    new_task = (st.task_exe_total > 0) & ~snap["task_seen"]
    R = min(int(params.num_reactions), 31)
    bits = (new_task[:, :R].astype(jnp.int32)
            * (jnp.int32(1) << jnp.arange(R, dtype=jnp.int32))[None, :]
            ).sum(axis=1)
    st = _trace_append(params, st, new_task[:, :R].any(axis=1), cells,
                       tracer.EV_TASK_FIRST, bits, update_no)

    # rising edge only (diff vs the pre-update masks): a persistent
    # anomaly is one event at the update it appears, not one per update
    bad_merit, bad_head, ip = anomaly_masks(params, st)
    st = _trace_append(params, st, bad_merit & ~snap["bad_merit"], cells,
                       tracer.EV_ANOM_MERIT, jnp.ones(n, jnp.int32),
                       update_no)
    st = _trace_append(params, st, bad_head & ~snap["bad_head"], cells,
                       tracer.EV_ANOM_HEAD, ip, update_no)
    return st


def _cycle_step_fn(params):
    """The hardware-type micro-step dispatch -- ONE spelling shared by
    the solo cycle loop (interpret_phase) and the world-folded batched
    one (_mw_fold_cycles_xla), so a new hardware type routes both
    engines and cannot desynchronize them."""
    if params.hw_type in (1, 2):
        from avida_tpu.ops.interpreter_smt import micro_step_smt
        return micro_step_smt
    if params.max_cpu_threads > 1:
        from avida_tpu.ops.interpreter import micro_step_threads
        return micro_step_threads
    return micro_step


def _materialize_offspring(params, st, pending_before):
    """End-of-update offspring materialization for the heads XLA path:
    extract each freshly divided parent's offspring into off_tape (the
    Pallas kernel does this at the divide cycle; one masked barrel roll
    per update keeps the two paths bit-identical).  A stalled parent's
    tape is frozen, so end-of-update extraction sees exactly the
    divide-time bytes.  Shared by interpret_phase and (vmapped) the
    world-folded batched loop, so a fix here applies to both and the
    batched-vs-solo bit-exactness contract cannot silently drift."""
    from avida_tpu.ops.interpreter import barrel_shift_left, tape_ops
    new_div = st.divide_pending & ~pending_before
    L_ = st.tape.shape[1]
    ext = barrel_shift_left(
        tape_ops(st.tape).astype(jnp.uint8), st.off_start, L_)
    ext = jnp.where(jnp.arange(L_)[None, :] < st.off_len[:, None],
                    ext, jnp.uint8(0))
    return st.replace(off_tape=jnp.where(new_div[:, None], ext,
                                         st.off_tape))


def interpret_phase(params, st, k_steps, granted, max_k, cap, counters=None):
    """Run the update's lockstep cycles (Pallas kernel or XLA while_loop)
    plus the end-of-update offspring materialization.

    `counters` threads an optional telemetry block through the loop:
    int32[num_insts] dispatch-mix accumulator (opcode under each scheduled
    lane's IP, once per cycle -- sums to this update's executed count on
    the default single-thread path).  With counters=None (the production
    path) the trace is identical to the pre-telemetry code.  The Pallas
    kernel does not collect the dispatch mix (an in-kernel [num_insts]
    scatter per cycle is not cheap); it returns the accumulator unchanged
    and the harness reports budget/phase counters only."""
    if use_pallas_path(params):
        # whole-update cycle loop in one VMEM-resident kernel launch
        # (ops/pallas_cycles.py); granted == min(budgets, cap) makes the
        # per-block while_loop inside the kernel equivalent to the XLA
        # while_loop below
        st = pallas_cycles.run_cycles(params, st, k_steps, granted, int(cap))
        return st, counters

    step_fn = _cycle_step_fn(params)

    if counters is None:
        def cond(carry):
            s, _ = carry
            return s < max_k

        def body(carry):
            s, st = carry
            # a freshly divided parent stalls until the end-of-update birth
            # flush extracts its offspring from the tape (deferred h-divide;
            # ops/interpreter.py header) -- it resumes next update
            exec_mask = st.alive & (s < granted) & ~st.divide_pending
            st = step_fn(params, st, jax.random.fold_in(k_steps, s),
                         exec_mask)
            return s + 1, st

        pending_before = st.divide_pending
        _, st = jax.lax.while_loop(cond, body, (jnp.int32(0), st))
    else:
        from avida_tpu.ops.interpreter import fetch_opcode

        def cond_c(carry):
            s, _, _ = carry
            return s < max_k

        def body_c(carry):
            s, st, cnt = carry
            exec_mask = st.alive & (s < granted) & ~st.divide_pending
            op = fetch_opcode(params, st)
            cnt = cnt.at[op].add(exec_mask.astype(jnp.int32))
            st = step_fn(params, st, jax.random.fold_in(k_steps, s),
                         exec_mask)
            return s + 1, st, cnt

        pending_before = st.divide_pending
        _, st, counters = jax.lax.while_loop(
            cond_c, body_c, (jnp.int32(0), st, counters))
    if params.hw_type == 0:
        st = _materialize_offspring(params, st, pending_before)
    return st, counters


def bank_phase(params, st, budgets, executed0):
    """Bank unexecuted budget and snapshot the per-update execution count.
    Returns (st, executed): the snapshot is taken BEFORE the birth flush
    because flush_births zeroes insts_executed on every cell receiving a
    newborn, so a post-flush difference would subtract the prior
    occupant's lifetime count (undercounting, possibly negative)."""
    # bank whatever each organism earned but did not execute (cap or stall)
    executed_this = st.insts_executed - executed0
    carry = jnp.clip(budgets - executed_this, 0, 100 * params.ave_time_slice)
    st = st.replace(budget_carry=jnp.where(st.alive, carry, 0))
    executed = executed_this.sum()
    return st, executed


def birth_phase(params, st, k_birth, k_steps, neighbors, update_no):
    """Flush pending births, age demes, run the point-mutation sweep."""
    st = birth_ops.flush_births(params, st, k_birth, neighbors, update_no,
                                use_off_tape=True)

    if params.num_demes > 1:
        st = st.replace(deme_age=st.deme_age + 1)   # cDeme::IncAge per update

    if params.point_mut_prob > 0:
        st = _point_mutation_sweep(params, st, jax.random.fold_in(k_steps, 0x7FFFFFFF))
    return st


@partial(jax.jit, static_argnums=0)
def update_step(params, st, key, neighbors, update_no):
    """Run one update.  Returns (new_state, executed_this_update)."""
    k_budget, k_steps, k_birth = jax.random.split(key, 3)

    # resource dynamics integrate once per update (ops/resources.py)
    st = resource_phase(params, st, key, update_no)

    budgets, granted, max_k = schedule_phase(params, st, k_budget)
    cap = static_cap(params)

    st = perm_phase(params, st, granted, update_no)

    # flight recorder (observability/tracer.py): Python-level gate on the
    # static trace_cap, so the disabled path traces the IDENTICAL program
    if params.trace_cap:
        st, tsnap = trace_pre_phase(params, st, granted, update_no)

    executed0 = st.insts_executed

    st, _ = interpret_phase(params, st, k_steps, granted, max_k, cap)

    st, executed = bank_phase(params, st, budgets, executed0)

    st = birth_phase(params, st, k_birth, k_steps, neighbors, update_no)

    if params.fault_nan:
        # seeded device-side corruption (utils/faultinject.py `nan:`
        # kind), injected BEFORE the trace emission so the flight
        # recorder sees the anomaly onset in the same update.  Static
        # Python gate like trace_cap: with TPU_FAULT unset this traces
        # the identical program (scripts/check_jaxpr.py digest)
        from avida_tpu.utils.faultinject import nan_phase
        st = nan_phase(params, st, update_no)

    if getattr(params, "fault_bitflip", ()):
        # the modeled SDC event (utils/faultinject.py `bitflip:` kind):
        # an in-bounds single-bit flip no auditor can see -- same static
        # gate discipline; the integrity plane's shadow replay runs with
        # this gate stripped, so scrubbing detects the divergence
        from avida_tpu.utils.faultinject import bitflip_phase
        st = bitflip_phase(params, st, update_no)

    if params.trace_cap:
        st = trace_post_phase(params, st, tsnap, update_no)

    return st, executed


def _point_mutation_sweep(params, st, key):
    """Per-site point mutations once per update (Avida2Driver.cc:146-155 ->
    cHardwareBase::PointMutate cc:1087)."""
    from avida_tpu.ops.interpreter import random_inst
    n, L = st.tape.shape
    u = jax.random.uniform(key, (n, L))
    r = random_inst(params, jax.random.fold_in(key, 1),
                    (n, L)).astype(jnp.uint8)
    in_genome = jnp.arange(L)[None, :] < st.mem_len[:, None]
    hit = (u < params.point_mut_prob) & in_genome & st.alive[:, None]
    # replace opcode bits, keep flag bits
    mutated = (st.tape & jnp.uint8(0xC0)) | r
    return st.replace(tape=jnp.where(hit, mutated, st.tape))


def _update_stats(params, st, alive_before, update_no):
    """The per-update host-bookkeeping tuple shared by every scan body
    (solo / W-batched x per-update / packed-resident): light_stats plus
    the deaths balance and the avida-time delta.  One spelling, so a
    change to the deaths clamp or dt derivation applies to all four
    engines and cannot desynchronize solo vs batched bookkeeping."""
    return _update_stats_from(light_stats(params, st, update_no),
                              alive_before)


def _update_stats_from(vals, alive_before):
    """_update_stats' deaths/dt derivation over a light_stats(_vals)
    tuple -- shared with the packed engine's fused path, which computes
    the tuple from resident plane rows (light_stats_vals) instead of a
    WorldState."""
    ave_gest, ave_gen, n_alive, births = vals
    deaths = jnp.maximum(alive_before + births - n_alive, 0)
    dt = jnp.where(ave_gest > 0, 1.0 / jnp.maximum(ave_gest, 1e-9), 0.0)
    return births, deaths, dt, ave_gen, n_alive


def update_scan_impl(params, st, chunk, run_key, neighbors, u0):
    """Unjitted body of `update_scan` below -- the single spelling of the
    chunked update loop.  Exists so the multi-world batcher
    (avida_tpu/parallel/multiworld.py) can `jax.vmap` the identical
    program over a leading world axis inside its own jit: per-world
    PRNG streams stay fold_in(run_key_w, update_no), so every world in
    a batch replays the exact per-update key sequence of a solo run.
    See `update_scan` for the full contract (donation, packed residency,
    returned per-update vectors)."""
    from avida_tpu.ops import packed_chunk

    if packed_chunk.active(params, st):
        pc = packed_chunk.pack_chunk(params, st)
        fused = packed_chunk.fused_active(params)

        def pbody(pc, i):
            k = jax.random.fold_in(run_key, u0 + i)
            if fused:
                # the alive mirror is STALE mid-chunk on the fused
                # body -- read the resident flag row instead, and take
                # stats off the planes (packed_chunk.stats_rows)
                alive_before = packed_chunk.alive_rows(pc.ivec).sum()
            else:
                alive_before = pc.st.alive.sum()
            pc, executed = packed_chunk.update_step_packed(
                params, pc, k, neighbors, u0 + i)
            if fused:
                births, deaths, dt, ave_gen, n_alive = \
                    packed_chunk.stats_rows(pc, alive_before, u0 + i)
            else:
                births, deaths, dt, ave_gen, n_alive = _update_stats(
                    params, pc.st, alive_before, u0 + i)
            return pc, (executed, births, deaths, dt, ave_gen, n_alive)

        pc, outs = jax.lax.scan(pbody, pc, jnp.arange(chunk))
        return packed_chunk.unpack_chunk(params, pc), outs

    def body(st, i):
        k = jax.random.fold_in(run_key, u0 + i)
        alive_before = st.alive.sum()
        st, executed = update_step(params, st, k, neighbors, u0 + i)
        births, deaths, dt, ave_gen, n_alive = _update_stats(
            params, st, alive_before, u0 + i)
        return st, (executed, births, deaths, dt, ave_gen, n_alive)
    st, outs = jax.lax.scan(body, st, jnp.arange(chunk))
    return st, outs


@partial(jax.jit, static_argnums=(0, 2), donate_argnums=(1,))
def update_scan(params, st, chunk, run_key, neighbors, u0):
    """Run `chunk` consecutive updates in ONE device program (lax.scan).

    Per-update host dispatch costs dominate small worlds (and any remote
    device path); the World driver batches event-free stretches through
    this.  The per-update PRNG key is fold_in(run_key, update_no), making
    the random stream a pure function of the seed and the update number --
    trajectories are bit-identical however the driver chunks the run
    (chunked vs single-step, any event schedule).  Returns the final state
    plus per-update int32[chunk] vectors of executed instructions, births
    and deaths, and f32[chunk] avida-time deltas and average generations
    (all the host bookkeeping World needs, at update granularity).

    The input state is DONATED: XLA updates the ~100k-organism buffers in
    place instead of double-buffering them, so the caller's reference to
    the pre-call state is invalid afterwards (World reassigns self.state
    from the return value; any device-array the caller still needs from
    the old state must be copied out before the call).

    Packed-resident chunk (ops/packed_chunk.py, round 6): when the
    configuration qualifies, the scan keeps the population in the
    kernel's [LP, N] plane layout for the WHOLE chunk -- pack once, run
    `chunk` updates with the packed-native birth flush, unpack once here
    at the boundary (where checkpoints, trace drains and .dat readbacks
    already synchronize).  Same per-update PRNG stream, bit-exact vs the
    per-update path (tests/test_packed_chunk.py)."""
    return update_scan_impl(params, st, chunk, run_key, neighbors, u0)


# ---- the multi-world batched update (parallel/multiworld.py) ----
#
# PR 10 advanced a W-world batch by jit(vmap(update_scan_impl)), which
# was bit-exact but paid vmap's batching tax on control flow: the
# batching rule for lax.while_loop runs every iteration until EVERY
# world's cond is false and freezes finished worlds with a per-cycle
# select over every carry leaf -- measured batch_efficiency 0.07-0.12
# on CPU (BENCH_r08_local.json).  The functions below eliminate that
# structurally: the cheap per-update phases (resources / schedule /
# bank / birth flush / stats) stay vmapped, but the cycle loop is
# WORLD-FOLDED -- one lax.while_loop whose carry stacks W worlds'
# leaves on a leading axis, running to the batch-uniform trip count
# max_w(max_k_w), with per-world execution masks doing the gating.  A
# world past its own trip count contributes an all-false exec_mask, and
# a fully-masked micro_step is an exact identity (the same contract the
# solo loop relies on for budget-exhausted lanes and stalled parents),
# so no carry leaf pays a select and every world replays its solo
# trajectory bit-exactly.  On the Pallas paths the world axis is folded
# INTO the kernel launch instead (one [LP, W*N] grid; see
# pallas_cycles.run_packed_stacked and ops/packed_chunk.py).


def _mw_pre_phase(params, st, key, update_no):
    """One world's cheap pre-cycle phases -- exactly update_step's
    prologue (key split, resources, schedule, perm) -- vmapped over the
    world axis by _batched_update_step."""
    k_budget, k_steps, k_birth = jax.random.split(key, 3)
    st = resource_phase(params, st, key, update_no)
    budgets, granted, max_k = schedule_phase(params, st, k_budget)
    st = perm_phase(params, st, granted, update_no)
    return st, (budgets, granted, max_k, k_steps, k_birth)


def _mw_fold_cycles_xla(params, bst, k_steps, granted, max_k):
    """The Stage-1 tentpole: ONE while_loop advances W stacked worlds'
    lockstep cycles.  Trip count = max over worlds of the per-world
    max_k (batch-uniform); the body vmaps micro_step over the world
    axis with each world's own exec mask and per-cycle key
    fold_in(k_steps_w, s).  Worlds whose max_k is below the batch max
    run fully-masked (identity) iterations -- the only cross-world cost
    is the shared mask test, with NO per-leaf select."""
    step_fn = _cycle_step_fn(params)
    bmax = jnp.max(max_k)

    def cond(carry):
        return carry[0] < bmax

    def body(carry):
        s, bst = carry

        def one(st, kw, gw):
            exec_mask = st.alive & (s < gw) & ~st.divide_pending
            return step_fn(params, st, jax.random.fold_in(kw, s),
                           exec_mask)

        return s + 1, jax.vmap(one)(bst, k_steps, granted)

    pending_before = bst.divide_pending
    _, bst = jax.lax.while_loop(cond, body, (jnp.int32(0), bst))
    if params.hw_type == 0:
        bst = jax.vmap(
            lambda st, pb: _materialize_offspring(params, st, pb)
        )(bst, pending_before)
    return bst


def _mw_stack_kernel_cycles(params, bst, k_steps, granted, cap):
    """Stage-2's per-update flavor: the Pallas path with the world axis
    folded into the kernel -- W per-world pack_state quads stacked on
    the lane axis and launched as ONE [LP, W*n_pad] grid
    (pallas_cycles.run_packed_stacked), so each world's blocks exit
    their while_loop at their own budgets instead of idling on the
    batch-max of a vmapped launch.  Seeds mirror run_cycles draw for
    draw (randint on each world's k_steps)."""
    from avida_tpu.ops import packed_chunk
    n = bst.alive.shape[1]
    use_perm = int(getattr(params, "lane_perm_k", 0)) > 0
    if use_perm:
        use_perm = not packed_chunk.active(
            params, jax.tree.map(lambda x: x[0], bst))

    def pack_w(st, g):
        return pallas_cycles.pack_state(
            params, st, g, st.lane_perm if use_perm else None, 1)

    quads = jax.vmap(pack_w)(bst, granted)         # each [W, rows, n_pad]
    W, n_pad = quads[0].shape[0], quads[0].shape[2]
    B = pallas_cycles._dims(params, n, params.max_memory, 1)[0]
    seeds = pallas_cycles.world_seed_bases(k_steps)
    flat = tuple(jnp.moveaxis(q, 0, 1).reshape(q.shape[1], W * n_pad)
                 for q in quads)
    out = pallas_cycles.run_packed_stacked(params, flat, seeds, cap, B)
    out_w = tuple(o.reshape(o.shape[0], W, n_pad) for o in out)

    def unpack_w(st, quad):
        return pallas_cycles.unpack_state(
            params, st, quad, st.lane_inv if use_perm else None)

    return jax.vmap(unpack_w, in_axes=(0, 1))(bst, out_w)


def _batched_update_step(params, bst, keys, neighbors, update_no):
    """One update for W stacked worlds -- update_step's phase order with
    the cycle loop world-folded.  `update_no` is the [W] vector of each
    world's OWN update counter (a dynamic-membership serving batch
    carries worlds at different points of their runs; an aligned batch
    passes W copies of the shared counter, which computes bit-identically
    to the scalar it replaced).  Returns (bst, executed[W], trips[W])
    where trips is each world's own per-update trip count max_k (what
    its solo while_loop would run; the batch runs max over worlds), the
    raw material of the multiworld_batch_efficiency gauge."""
    bst, (budgets, granted, max_k, k_steps, k_birth) = jax.vmap(
        lambda st, k, un: _mw_pre_phase(params, st, k, un)
    )(bst, keys, update_no)
    cap = static_cap(params)

    if params.trace_cap:
        bst, tsnap = jax.vmap(
            lambda st, g, un: trace_pre_phase(params, st, g, un)
        )(bst, granted, update_no)

    executed0 = bst.insts_executed

    if use_pallas_path(params):
        bst = _mw_stack_kernel_cycles(params, bst, k_steps, granted, cap)
    else:
        bst = _mw_fold_cycles_xla(params, bst, k_steps, granted, max_k)

    def post(st, b, e0, kb, ks, un):
        st, executed = bank_phase(params, st, b, e0)
        st = birth_phase(params, st, kb, ks, neighbors, un)
        return st, executed

    bst, executed = jax.vmap(post)(bst, budgets, executed0, k_birth,
                                   k_steps, update_no)

    if params.fault_nan:
        from avida_tpu.utils.faultinject import nan_phase
        bst = jax.vmap(
            lambda st, un: nan_phase(params, st, un))(bst, update_no)

    if getattr(params, "fault_bitflip", ()):
        from avida_tpu.utils.faultinject import bitflip_phase
        bst = jax.vmap(
            lambda st, un: bitflip_phase(params, st, un))(bst, update_no)

    if params.trace_cap:
        bst = jax.vmap(
            lambda st, sn, un: trace_post_phase(params, st, sn, un)
        )(bst, tsnap, update_no)
    return bst, executed, max_k


def update_scan_batched(params, bst, chunk, run_keys, neighbors, u0):
    """The W-world mirror of update_scan_impl (the engine behind
    parallel/multiworld.multiworld_scan).  bst carries a leading world
    axis on every leaf; run_keys are the stacked per-world run keys.
    u0 is a scalar (every world at the same update -- the aligned
    MultiWorld batch) or a [W] vector of PER-WORLD update counters (the
    dynamic-membership serving batch, where a rider admitted mid-run
    advances from its own update while its peers continue from theirs);
    a scalar broadcasts to the vector form, and an all-equal vector
    computes bit-identically to the scalar it replaced (each world's
    PRNG stream stays fold_in(run_key_w, own_update)).  Routing mirrors
    the solo scan: the packed-resident chunk engine when the
    configuration qualifies (stacked planes, pack once / unpack once --
    ops/packed_chunk.py), else the per-update batched step above.
    Returns (bst', outs) where outs adds a 7th per-update vector to
    update_scan's six: trips[W, chunk], each world's own trip count per
    update (the straggler/efficiency attribution input)."""
    from avida_tpu.ops import packed_chunk

    u0 = jnp.broadcast_to(jnp.asarray(u0, jnp.int32),
                          (bst.alive.shape[0],))

    if packed_chunk.batch_active(params, bst):
        pw = packed_chunk.pack_worlds(params, bst)
        fused = packed_chunk.fused_active(params)

        def pbody(pw, i):
            un = u0 + i
            keys = jax.vmap(jax.random.fold_in)(run_keys, un)
            if fused:
                # stale alive mirrors mid-chunk on the fused body:
                # read the stacked flag row ([NI, W, N] -> [W, N])
                alive_before = packed_chunk.alive_rows(pw.ivec).sum(axis=1)
            else:
                alive_before = pw.bst.alive.sum(axis=1)
            pw, executed, trips = packed_chunk.update_step_packed_worlds(
                params, pw, keys, neighbors, un)
            if fused:
                births, deaths, dt, ave_gen, n_alive = \
                    packed_chunk.stats_rows_worlds(pw, alive_before, un)
            else:
                births, deaths, dt, ave_gen, n_alive = jax.vmap(
                    lambda st, ab, u: _update_stats(params, st, ab, u)
                )(pw.bst, alive_before, un)
            return pw, (executed, births, deaths, dt, ave_gen, n_alive,
                        trips)

        pw, outs = jax.lax.scan(pbody, pw, jnp.arange(chunk))
        bst = packed_chunk.unpack_worlds(params, pw)
    else:
        def body(bst, i):
            un = u0 + i
            keys = jax.vmap(jax.random.fold_in)(run_keys, un)
            alive_before = bst.alive.sum(axis=1)
            bst, executed, trips = _batched_update_step(
                params, bst, keys, neighbors, un)
            births, deaths, dt, ave_gen, n_alive = jax.vmap(
                lambda st, ab, u: _update_stats(params, st, ab, u)
            )(bst, alive_before, un)
            return bst, (executed, births, deaths, dt, ave_gen, n_alive,
                         trips)

        bst, outs = jax.lax.scan(body, bst, jnp.arange(chunk))
    # scan stacks per-update outputs on axis 0: put the world axis back
    # in front ([W, chunk], the contract PR 10's vmap established)
    return bst, jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), outs)


@partial(jax.jit, static_argnums=0)
def summarize(params, st, update_no=jnp.int32(-1)):
    """Device-side reduction of per-update stats (feeds cStats/.dat output;
    ref cPopulation::UpdateOrganismStats cc:5847).  `update_no` is the index
    of the most recently completed update (for births-this-update counts)."""
    alive = st.alive
    n_alive = alive.sum()
    denom = jnp.maximum(n_alive, 1).astype(st.merit.dtype)
    fdt = st.merit.dtype

    def avg(x):
        return jnp.where(alive, x.astype(fdt), 0).sum() / denom

    gest = jnp.where(alive, st.gestation_time, 0)
    has_gest = alive & (st.gestation_time > 0)
    gest_denom = jnp.maximum(has_gest.sum(), 1).astype(fdt)
    repro = jnp.where(has_gest,
                      1.0 / jnp.maximum(st.gestation_time, 1).astype(fdt), 0)

    task_counts = (alive[:, None] & (st.last_task_count > 0)).sum(axis=0)
    task_doing = (alive[:, None] & (st.cur_task_count > 0)).sum(axis=0)

    return {
        "num_organisms": n_alive,
        "ave_merit": avg(st.merit),
        "ave_fitness": avg(st.fitness),
        "ave_gestation": jnp.where(has_gest, gest, 0).sum().astype(fdt) / gest_denom,
        "ave_repro_rate": repro.sum() / gest_denom,
        "ave_genome_len": avg(st.genome_len),
        "ave_copied_size": avg(st.copied_size),
        "ave_executed_size": avg(st.executed_size),
        "ave_generation": avg(st.generation),
        "ave_age": avg(st.time_used),
        "max_fitness": jnp.where(alive, st.fitness, 0).max(),
        "max_merit": jnp.where(alive, st.merit, 0).max(),
        "num_births": (alive & (st.birth_update >= 0)).sum(),
        # update_no >= 0 guard: injected organisms carry the birth_update
        # sentinel -1, which must not collide with "events firing at update 0"
        "births_this_update": (alive & (update_no >= 0)
                               & (st.birth_update == update_no)).sum(),
        "num_breed_true": (alive & st.breed_true).sum(),
        "num_no_birth": (alive & (st.num_divides == 0)).sum(),
        # lifetime executed-instruction total.  With x64 disabled a plain
        # int32 sum SILENTLY WRAPS on long uncapped runs (per-cell
        # counters near 2^31 summed over 100k cells is ~2^47); the exact
        # value always rides total_insts_words (three 11-bit field sums,
        # each < 2^31 for up to ~1e6 cells -- recombine with
        # total_insts_exact()).  The scalar fallback here recombines in
        # f32: monotone and non-wrapping, ~2^-24 relative error
        # (documented approximation, NOT a wrap).
        "total_insts": st.insts_executed.astype(jnp.int64).sum()
        if jax.config.jax_enable_x64 else (
            (st.insts_executed & 0x7FF).sum().astype(jnp.float32)
            + ((st.insts_executed >> 11) & 0x7FF).sum().astype(jnp.float32)
            * jnp.float32(2048.0)
            + (st.insts_executed >> 22).sum().astype(jnp.float32)
            * jnp.float32(4194304.0)),
        "total_insts_words": jnp.stack([
            (st.insts_executed & 0x7FF).sum(),
            ((st.insts_executed >> 11) & 0x7FF).sum(),
            (st.insts_executed >> 22).sum()]),
        "task_counts": task_counts,
        "task_doing": task_doing,
        # lifetime execution totals (all cells, dead included -- the
        # counter is per-cell monotone; tasks_exe.dat diffs consecutive
        # updates on the host)
        "task_exe_totals": st.task_exe_total.sum(axis=0),
        "num_divides": st.num_divides.sum(),
    }


def total_insts_exact(words) -> int:
    """Exact lifetime executed-instruction total from summarize()'s
    total_insts_words (host side, arbitrary-precision Python ints)."""
    import numpy as _np
    w = _np.asarray(words, _np.int64)
    return int(w[0]) + (int(w[1]) << 11) + (int(w[2]) << 22)


@partial(jax.jit, static_argnums=0)
def light_stats(params, st, update_no):
    """Tiny per-update reduction for host bookkeeping (avida time,
    generation triggers, birth/death counts) -- returns device scalars, no
    host sync implied.  update_no = the update that just completed."""
    return light_stats_vals(st.alive, st.gestation_time, st.generation,
                            st.birth_update, update_no)


def light_stats_vals(alive, gestation_time, generation, birth_update,
                     update_no):
    """light_stats over the bare vectors it actually reads -- the packed
    engine's fused path feeds these straight off the resident planes
    (alive/gestation/generation from ivec rows, birth_update from the
    canonical column the flush maintains) without unpacking a
    WorldState.  One spelling with light_stats above, so the two
    engines cannot drift."""
    has = alive & (gestation_time > 0)
    gd = jnp.maximum(has.sum(), 1).astype(jnp.float32)
    ave_gest = jnp.where(has, gestation_time, 0).sum().astype(jnp.float32) / gd
    n_alive = alive.sum()
    n = jnp.maximum(n_alive, 1).astype(jnp.float32)
    ave_gen = jnp.where(alive, generation, 0).sum().astype(jnp.float32) / n
    births = (alive & (birth_update == update_no)).sum()
    return ave_gest, ave_gen, n_alive, births
