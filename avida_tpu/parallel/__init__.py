from avida_tpu.parallel.mesh import (  # noqa: F401
    CELL_AXIS, make_mesh, population_sharding, replicate,
    shard_neighbors, shard_population,
)
