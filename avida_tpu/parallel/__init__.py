from avida_tpu.parallel.mesh import (  # noqa: F401
    CELL_AXIS, make_mesh, population_sharding, replicate,
    shard_neighbors, shard_population,
)


def __getattr__(name):
    # lazy (PEP 562): multiworld pulls in the full World driver; mesh
    # consumers (bench sharded mode, tests) should not pay that import
    if name in ("MultiWorld", "multiworld_scan"):
        from avida_tpu.parallel import multiworld as _mw
        return getattr(_mw, name)
    raise AttributeError(name)
