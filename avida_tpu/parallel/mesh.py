"""Multi-device world sharding: the TPU-native replacement for avida-mp.

The reference scales by running one world per MPI rank and migrating
organisms across world boundaries with Boost.MPI point-to-point messages
(cMultiProcessWorld, avida-core/source/main/cMultiProcessWorld.cc:142-310;
SURVEY.md §2g.5, §5).  Here the *single* (larger) world is sharded across a
`jax.sharding.Mesh`: every per-cell tensor in PopulationState is partitioned
over the cell axis, the whole update step runs as one SPMD program, and
cross-shard organism placement (the migration analogue) is carried by XLA
collectives that GSPMD derives from the birth engine's gathers — riding ICI
within a slice, DCN across slices.  The per-update barrier and deterministic
migrant ordering the reference implements by hand (cc:283-310) fall out of
the lockstep SPMD model for free.

Sharding layout: the grid is laid out row-major (cell = y * world_x + x) and
sharded along the cell axis, i.e. contiguous bands of rows per device.  With
BIRTH_METHOD 0 (neighborhood placement) an offspring crosses a shard boundary
only when the parent sits in a device's edge row — the cross-device traffic
XLA emits is the halo exchange the reference implements as boundary-cell
migration (cMultiProcessWorld.cc:227-258).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CELL_AXIS = "cells"


def make_mesh(devices=None) -> Mesh:
    """1-D device mesh over the cell (population) axis."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (CELL_AXIS,))


def population_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for any per-cell tensor: partition dim 0 over the mesh."""
    return NamedSharding(mesh, P(CELL_AXIS))


# PopulationState fields whose cell axis is NOT dim 0 (see core/state.py):
# the spatial resource grid is [R_s, N], global pools have no cell axis.
_FIELD_SPECS = {"res_grid": P(None, CELL_AXIS), "resources": P(),
                "grad_peak": P(),
                # birth-chamber store: world-level, replicated
                "bc_mem": P(), "bc_len": P(), "bc_merit": P(),
                "bc_valid": P(), "bc_type": P(),
                # deme-axis state: small, replicated (the cell bands
                # themselves are the sharded axis; deme counters/germlines
                # ride along)
                "deme_birth_count": P(), "deme_age": P(),
                "germ_mem": P(), "germ_len": P(),
                "deme_resources": P(),
                "nb_genome": P(), "nb_len": P(), "nb_cell": P(),
                "nb_parent": P(), "nb_update": P(), "nb_count": P(),
                # flight-recorder event ring: world-level, replicated
                "tr_update": P(), "tr_cell": P(), "tr_code": P(),
                "tr_payload": P(), "tr_count": P()}


def shard_population(st, mesh: Mesh):
    """Place every PopulationState array with its cell axis partitioned.

    Per-organism arrays carry the cell axis as dim 0; the exceptions are
    named in _FIELD_SPECS (resource state).  Requires num_cells % mesh.size
    == 0 (choose WORLD_Y divisible by the device count; the driver-facing
    helpers below do this).
    """
    fields = {name: getattr(st, name) for name in st.__dataclass_fields__}
    placed = {
        name: jax.device_put(
            a, NamedSharding(mesh, _FIELD_SPECS.get(name, P(CELL_AXIS))))
        for name, a in fields.items() if a is not None
    }
    return st.replace(**placed)


def shard_neighbors(neighbors, mesh: Mesh):
    return jax.device_put(neighbors, population_sharding(mesh))


def replicate(x, mesh: Mesh):
    return jax.device_put(x, NamedSharding(mesh, P()))
