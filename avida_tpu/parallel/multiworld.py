"""Multi-world device batching: one compiled update_scan serving a fleet.

The fleet orchestrator (service/fleet.py) reaches "many tenants" by
spawning one process per world, so a small world -- far too small to
saturate a device, and dominated by per-update host dispatch on any
backend -- pays full launch + compile + dispatch overhead per tenant.
This module is the missing half (ROADMAP item 2): a batched **world
axis**.  W independent worlds with the SAME static configuration
(identical WorldParams -- one compiled program) but distinct seeds are
stacked on a leading axis of every PopulationState leaf and advanced by
chunks of ops/update.update_scan_batched, so W worlds progress in one
device program and aggregate throughput scales with W while compile
cost stays O(1) -- the direct analogue of batch-serving in an inference
stack.  The engine world-FOLDS the hot cycle loop rather than vmapping
it (PR 11): one while_loop at the batch-uniform trip count with
per-world exec masks on the XLA path; one stacked [LP, W*N] kernel grid
on the Pallas / packed-resident paths, where each world's blocks run to
their own budgets (per-block early exit + TPU_KERNEL_ROWSKIP
load-balance the ragged budgets across tenants).  Only the cheap
per-update phases (resources / schedule / bank / birth flush / stats)
are vmapped.

Bit-exactness contract: world w in a batch IS the solo run with seed w.

  * per-world PRNG streams stay `fold_in(run_key_w, update_no)` -- the
    batched scan vmaps the identical per-update program over per-world
    run keys, so every world replays its solo key sequence;
  * the batched run loop calls the SAME chunk planner as World.run
    (World._plan_stretch), so the batch's chunk grid -- and with it
    every event, drain, audit and checkpoint boundary -- is identical
    to each member's solo grid;
  * host accumulators (_avida_time, _total_births, ...) are lifted from
    per-world device scalars into [W] device vectors updated with the
    same per-chunk reductions, so float accumulation order per world is
    unchanged.

Checkpoints are saved PER WORLD by slicing the batched leaves back into
each member World and running the ordinary World.save_checkpoint into
that world's own TPU_CKPT_DIR -- each generation is byte-identical to
the one a solo run would have written at the same boundary, so
`--resume`, ckpt_tool, and the analytics pipeline all work unchanged on
a batch member, and a member can even continue SOLO from a batch
checkpoint (or vice versa) bit-exactly.

Eligibility: everything the chunked solo path requires
(World._chunkable -- no telemetry, no reversion tests, no
generation/births event triggers) plus no flight recorder, no live
analytics and no fault injection (their per-world host pipelines are
not sliced; run those workloads solo).  Systematics IS supported: the
per-world newborn rings are sliced and drained into each member's own
GenotypeArbiter at every chunk boundary.
"""

from __future__ import annotations

import json
import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from avida_tpu.ops.update import update_scan_batched
from avida_tpu.world import World


@partial(jax.jit, static_argnums=(0, 2), donate_argnums=(1,))
def multiworld_scan(params, bstate, chunk, run_keys, neighbors, u0):
    """Advance W worlds by `chunk` updates in ONE device program.

    bstate: a PopulationState pytree whose every leaf carries a leading
    world axis; run_keys: the stacked per-world run keys.  u0 and the
    neighbor table are shared (the batch advances on one update grid
    and static-equal configs have one world geometry).  Returns the
    batched final state plus the per-update bookkeeping vectors of
    update_scan with a leading world axis ([W, chunk]), extended with a
    seventh vector: each world's own per-update trip count (the
    efficiency/straggler attribution input).

    The engine (ops/update.update_scan_batched) world-FOLDS the cycle
    loop instead of vmapping it: one while_loop at the batch-uniform
    trip count with per-world exec masks on the XLA path, one stacked
    [LP, W*N] kernel launch on the Pallas paths -- no per-cycle select
    over carry leaves, no vmapped control flow (the PR-10 engine's
    batching tax; BENCH_r08_local.json).  Every world remains bit-exact
    vs its solo run.

    The batched state is DONATED, exactly like update_scan's."""
    return update_scan_batched(params, bstate, chunk, run_keys,
                               neighbors, u0)


def _event_key(ev):
    return (ev.trigger, ev.start, ev.interval, ev.stop, ev.action,
            tuple(ev.args))


class MultiWorld:
    """Driver for one batch of static-equal worlds (see module header).

    Build with a list of fully-constructed Worlds (distinct seeds /
    data dirs / checkpoint dirs; identical everything-static), or via
    `from_seeds` / `from_manifest`.
    """

    def __init__(self, worlds, data_dir: str | None = None):
        if not worlds:
            raise ValueError("MultiWorld needs at least one world")
        self.worlds = list(worlds)
        w0 = self.worlds[0]
        self.params = w0.params
        self.neighbors = w0.neighbors
        self.cfg = w0.cfg
        self.data_dir = data_dir or w0.data_dir
        n0 = np.asarray(w0.neighbors)
        ev0 = [_event_key(e) for e in w0.events]
        for w in self.worlds[1:]:
            if w.params != w0.params:
                raise ValueError(
                    "multi-world batch needs identical static configs "
                    "(WorldParams differ; only seeds and output dirs may "
                    "vary across a batch)")
            if not np.array_equal(np.asarray(w.neighbors), n0):
                raise ValueError(
                    "multi-world batch needs one shared world topology "
                    "(neighbor tables differ -- scale-free geometries "
                    "draw per-seed graphs and cannot batch)")
            if [_event_key(e) for e in w.events] != ev0:
                raise ValueError("multi-world batch needs one shared "
                                 "event schedule")
        for w in self.worlds:
            if not w._chunkable():
                raise ValueError(
                    "multi-world batching requires chunkable runs: no "
                    "telemetry, no offspring reversion tests, no "
                    "generation/births event triggers")
            if w.tracer is not None or w.analytics is not None \
                    or w.faults is not None:
                raise ValueError(
                    "multi-world batching does not slice the flight "
                    "recorder, live analytics or fault-injection host "
                    "pipelines; run those workloads solo")
        if len({id(w.cfg) for w in self.worlds}) != len(self.worlds) \
                and len(self.worlds) > 1:
            raise ValueError("each batch member needs its own config "
                             "object (distinct seeds / dirs)")
        self.update = w0.update
        if any(w.update != self.update for w in self.worlds):
            raise ValueError("batch members disagree on the current "
                             "update; resume() aligns them first")
        dirs = [os.path.abspath(w.data_dir) for w in self.worlds]
        if len(set(dirs)) != len(dirs):
            raise ValueError("batch members share a data_dir; each "
                             "world needs its own .dat output dir")
        self._ckpt_on = all(w._ckpt_base() for w in self.worlds)
        if self._ckpt_on:
            cks = [os.path.abspath(w._ckpt_base()) for w in self.worlds]
            if len(set(cks)) != len(cks):
                # a config-FILE TPU_CKPT_DIR reaches every member
                # verbatim (from_seeds only suffixes override-supplied
                # dirs): same-update generations would silently clobber
                # each other and a resume would restore ONE world's
                # bytes into all members
                raise ValueError(
                    "batch members share a checkpoint dir; give each "
                    "world its own TPU_CKPT_DIR (the --worlds CLI and "
                    "the fleet manifest do this per world)")
        self._exit = False
        self._preempt = False
        self.preempted = False
        self.bstate = None
        self._run_keys = None
        self._avida_time = None
        self._last_ave_gen = None
        self._deaths_this = None
        self._prev_alive = None
        self._total_births = None
        # batch-lifetime occupancy accumulators (f32 device values; fed
        # by _scan, published by MultiWorldExporter): per-world trip
        # totals, the per-update batch-max total, and the update count
        # they cover.  batch_efficiency = sum(trips) / (W * leader);
        # straggler lag_w = (leader - trips_w) / (leader / updates) --
        # how many leader-updates' worth of cycles world w spent masked
        self._trips = None
        self._leader_trips = None
        self._trips_updates = 0
        self.engine = None             # "packed-stacked" | "per-update",
        #                                set (and runlog-reported) by run()
        self._boundary_hook = None     # test seam (chaos drills): called
        #                                after every chunk boundary
        self.names = [f"w{k:03d}" for k in range(len(self.worlds))]
        self.exporter = None
        if int(self.cfg.get("TPU_METRICS", 0)):
            from avida_tpu.observability.exporter import MultiWorldExporter
            self.exporter = MultiWorldExporter(self)

    # ---- construction helpers ----

    @classmethod
    def from_seeds(cls, seeds, config_dir: str | None = None,
                   overrides=None, data_dir: str = "data",
                   ckpt_dir: str | None = None, names=None) -> "MultiWorld":
        """One world per seed, static config shared.  World k writes its
        .dat output to `<data_dir>/<name_k>` (names default w000, w001,
        ...) and, when `ckpt_dir` (or a TPU_CKPT_DIR override) is given,
        checkpoints to `<ckpt_dir>/<name_k>`."""
        overrides = list(overrides or [])
        if ckpt_dir is None:
            for n, v in overrides:
                if n == "TPU_CKPT_DIR" and str(v) not in ("-", ""):
                    ckpt_dir = str(v)
        base = [(n, v) for n, v in overrides
                if n not in ("RANDOM_SEED", "TPU_CKPT_DIR")]
        names = list(names or [f"w{k:03d}" for k in range(len(seeds))])
        entries = []
        for name, seed in zip(names, seeds):
            entries.append({
                "name": name, "seed": int(seed),
                "data_dir": os.path.join(data_dir, name),
                "ckpt_dir": (os.path.join(ckpt_dir, name)
                             if ckpt_dir else None)})
        return cls._from_entries(entries, config_dir, base, data_dir)

    @classmethod
    def from_manifest(cls, path: str, config_dir: str | None = None,
                      overrides=None,
                      data_dir: str | None = None) -> "MultiWorld":
        """Batch from a worlds.json manifest -- a list of
        {"name", "seed", "data_dir", "ckpt_dir"} entries (the fleet
        orchestrator's device-lane packing writes one per coalesced
        batch; service/fleet.py)."""
        try:
            with open(path) as f:
                entries = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ValueError(f"{path}: unreadable worlds manifest ({e})")
        if not isinstance(entries, list) or not entries:
            raise ValueError(f"{path}: worlds manifest must be a "
                             f"non-empty JSON list")
        for k, e in enumerate(entries):
            # operator-facing input: refuse with a one-line reason (the
            # --worlds CLI maps ValueError to exit 2), never a KeyError
            # traceback a supervisor would crash-loop on
            if not isinstance(e, dict) or not str(e.get("data_dir", "")):
                raise ValueError(f"{path}: entry {k} must be an object "
                                 f"with at least 'seed' and 'data_dir'")
            try:
                int(e["seed"])
            except (KeyError, TypeError, ValueError):
                raise ValueError(f"{path}: entry {k} needs an integer "
                                 f"'seed'")
        base = [(n, v) for n, v in (overrides or [])
                if n not in ("RANDOM_SEED", "TPU_CKPT_DIR")]
        return cls._from_entries(entries, config_dir, base,
                                 data_dir or os.path.dirname(path))

    @classmethod
    def _from_entries(cls, entries, config_dir, base_overrides, data_dir):
        worlds = []
        for e in entries:
            ov = list(base_overrides) + [("RANDOM_SEED", int(e["seed"]))]
            if e.get("ckpt_dir"):
                ov.append(("TPU_CKPT_DIR", e["ckpt_dir"]))
            worlds.append(World(config_dir=config_dir, overrides=ov,
                                data_dir=e["data_dir"]))
        mw = cls(worlds, data_dir=data_dir)
        mw.names = [str(e.get("name", f"w{k:03d}"))
                    for k, e in enumerate(entries)]
        return mw

    # ---- batched <-> per-world state movement ----

    def _stack(self):
        """Stack the member states (and the per-world host accumulator
        scalars) onto the leading world axis.  Member .state references
        are dropped: the batched buffers are donated every chunk and the
        members get fresh slices back at the next host boundary."""
        if self.bstate is not None:
            return
        self.bstate = jax.tree.map(lambda *xs: jnp.stack(xs),
                                   *[w.state for w in self.worlds])
        self._run_keys = jnp.stack([w._run_key for w in self.worlds])
        self._avida_time = jnp.stack(
            [jnp.asarray(w._avida_time, jnp.float32) for w in self.worlds])
        self._last_ave_gen = jnp.stack(
            [jnp.asarray(w._last_ave_gen, jnp.float32)
             for w in self.worlds])
        self._deaths_this = jnp.stack(
            [jnp.asarray(w._deaths_this, jnp.int32) for w in self.worlds])
        self._prev_alive = (
            None if any(w._prev_alive is None for w in self.worlds)
            else jnp.stack([jnp.asarray(w._prev_alive, jnp.int32)
                            for w in self.worlds]))
        self._total_births = jnp.stack(
            [jnp.asarray(w._total_births, jnp.int32) for w in self.worlds])
        for w in self.worlds:
            w.state = None

    def _sync_worlds(self):
        """Slice the batched state + accumulators back into each member
        World (a host boundary: events, checkpoints, audits, run exit).
        Slices are materialized copies, so they survive the next chunk's
        donation of the batched buffers."""
        if self.bstate is None:
            return
        for i, w in enumerate(self.worlds):
            w.state = jax.tree.map(lambda x, i=i: x[i], self.bstate)
            w.update = self.update
            w._avida_time = self._avida_time[i]
            w._last_ave_gen = self._last_ave_gen[i]
            w._deaths_this = self._deaths_this[i]
            w._prev_alive = (None if self._prev_alive is None
                             else self._prev_alive[i])
            w._total_births = self._total_births[i]
            w._summary_cache_update = None
        self.bstate = None

    # ---- the batched run loop (mirrors World.run's chunk grid) ----

    def _scan(self, k: int):
        """One batched chunk: W worlds x k updates, one device program.
        The same per-chunk accumulator updates as World._scan_updates,
        vectorized over the world axis (same per-world float order).
        The extra `trips` vector feeds the batch-efficiency /
        straggler-lag gauges: trips[w, u] is world w's OWN trip count
        at update u, while the batch ran max over worlds."""
        self.bstate, (executed, births, deaths, dts, ave_gens, n_alive,
                      trips) = \
            multiworld_scan(self.params, self.bstate, k, self._run_keys,
                            self.neighbors, jnp.int32(self.update))
        self._avida_time = self._avida_time + dts.sum(axis=1)
        self._last_ave_gen = ave_gens[:, -1]
        self._deaths_this = deaths[:, -1]
        self._prev_alive = n_alive[:, -1]
        self._total_births = self._total_births + births.sum(axis=1)
        # f32 accumulators: int32 trip totals wrap on long uncapped runs
        # (~1e5-trip updates x ~1e5 updates); the gauges they feed are
        # ratios, where f32's 2^-24 relative error is irrelevant
        self._trips = self._trips + trips.sum(axis=1).astype(jnp.float32)
        self._leader_trips = (self._leader_trips
                              + trips.max(axis=0).sum().astype(jnp.float32))
        self._trips_updates += k
        for i, w in enumerate(self.worlds):
            w._pending_exec.append(executed[i])
        self.update += k
        for w in self.worlds:
            w.update = self.update

    def _events_due(self) -> bool:
        for ev in self.worlds[0].events:
            if ev.trigger == "update" and ev.fires_at(self.update):
                return True
            if ev.trigger == "immediate" and self.update == 0:
                return True
        return False

    def _drain_newborns(self, at: int):
        """Slice the batched newborn rings and feed each member's own
        GenotypeArbiter, synchronously at every chunk boundary -- the
        same window boundaries (and therefore the same record grouping
        and death resolution) as the member's solo run.

        `at` is the update number stamped on the drain window.  Solo
        runs stamp a >1-update chunk with the post-chunk update
        (World._snapshot_newborns) but a single-stepped update with the
        update just run (run_update drains BEFORE World.run advances
        the counter) -- the caller passes the matching value so the
        serialized last_drain_update, and every systematics.process
        call, stays identical to the solo run's.

        Per-world snap entries stay DEVICE slices: _feed_systematics
        reads the nb_* rings only up to nb_count and touches the wide
        arrays (genome/birth_update/parent_id) solely in the overflow
        fallback, so eagerly np.asarray-ing the [W, N, L] genome plane
        here would fence the device for tens of MB per boundary that
        are almost never read."""
        if self.worlds[0].systematics is None:
            return
        for i, w in enumerate(self.worlds):
            snap = {name: getattr(self.bstate, name)[i]
                    for name in World._NB_SNAP_FIELDS}
            snap["update_at"] = at
            snap["win_start"] = w._last_drain_update
            w._last_drain_update = at
            w._feed_systematics(snap)
        self.bstate = self.bstate.replace(
            nb_count=jnp.zeros((len(self.worlds),), jnp.int32))

    # the solo handler verbatim (same `_preempt` attribute contract,
    # including the second-Ctrl-C escalation and the off-main-thread
    # guard) -- one spelling, so a future fix applies to both drivers
    _install_preempt_handlers = World._install_preempt_handlers

    def _report_engine(self):
        """Make the batch's chunk engine explicit and LOUD: a batch that
        cannot take the stacked packed-resident path (ops/packed_chunk.
        pack once -> stacked kernel scan -> unpack once) silently ran
        the per-update engine before this PR; now the choice lands in
        the runlog ({"record": "event"} + stderr echo) with the exact
        ineligibility reason, so a fleet operator can see why a batch
        is not on the fast path.  Called once per run()."""
        from avida_tpu.observability import runlog
        from avida_tpu.ops import packed_chunk
        w0 = self.worlds[0]
        # params.nb_cap is the static source of the newborn-ring gate
        # (>0 iff TPU_SYSTEMATICS; the ring arrays are shaped from it),
        # so the report matches what batch_active actually routes on
        reason = packed_chunk.ineligible_reason(self.params,
                                                self.params.nb_cap > 0)
        self.engine = "packed-stacked" if reason is None else "per-update"
        fields = {"engine": self.engine, "worlds": len(self.worlds)}
        if reason is not None:
            fields["fallback_reason"] = reason
        runlog.emit_event(w0, "multiworld_engine", **fields)
        return reason

    def save_checkpoints(self):
        """One ordinary per-world checkpoint generation each, into each
        member's own TPU_CKPT_DIR -- byte-identical to the generation a
        solo run would publish at this boundary."""
        self._sync_worlds()
        for w in self.worlds:
            w.save_checkpoint()
            if self._world_exports(w):
                # per-world heartbeat refresh: the boundary already
                # synced, so the readback is free -- fleet --status
                # member sub-rows stay no staler than one save interval
                w.exporter.export(w)

    def _world_exports(self, w) -> bool:
        """A member writes its own metrics.prom unless that path IS the
        batch aggregate's (the fleet's leader world shares the root
        data dir; its rows live in multiworld.prom instead)."""
        if w.exporter is None:
            return False
        return (self.exporter is None
                or os.path.abspath(w.exporter.path)
                != os.path.abspath(self.exporter.path))

    def resume(self, at_update: int | None = None) -> int:
        """Restore every member from its own checkpoint dir, aligned on
        one common update: the newest update for which EVERY member
        retains a generation (intersection, not min-of-newest: with a
        short retention an ahead member may have pruned the update a
        behind member fell back to -- skipping to the next common
        update recovers instead of wedging).  A generation that fails
        CRC drops the whole candidate update and the next-newest
        common one is tried.  Returns the aligned update."""
        from avida_tpu.utils import checkpoint as ckpt_mod
        if at_update is None:
            sets = []
            for w in self.worlds:
                ups = {ckpt_mod.generation_update(p)
                       for p in ckpt_mod.restore_candidates(
                           w._ckpt_base())}
                sets.append({u for u in ups if u >= 0})
            common = set.intersection(*sets) if sets else set()
            if not common:
                raise ckpt_mod.CheckpointError(
                    "no checkpoint update common to every batch member "
                    "(mixed progress resumes aligned or not at all)")
            candidates = sorted(common, reverse=True)
        else:
            candidates = [int(at_update)]
        last_err = None
        for u in candidates:
            try:
                for w in self.worlds:
                    restored = w.resume(at_update=u)
                    assert restored == u
            except ckpt_mod.CheckpointMismatchError:
                raise
            except ckpt_mod.CheckpointError as e:
                last_err = e
                continue
            self.update = u
            return u
        raise last_err or ckpt_mod.CheckpointError("batch resume failed")

    def run(self, max_updates: int | None = None):
        """The batched master loop.  Structurally World.run with the
        device work vectorized over the world axis: one shared chunk
        grid (World._plan_stretch on the common update counter), host
        boundaries -- events, newborn drains, audits, auto-saves,
        preemption -- at exactly the updates each member's solo run
        would have them.  Returns total instructions executed across
        the batch this call."""
        for w in self.worlds:
            if w.state is None:
                w.process_events()
                if w.state is None:
                    w.inject()
        start_insts = sum(w._cum_insts for w in self.worlds)
        ckpt_every = int(self.cfg.get("TPU_CKPT_EVERY", 0))
        audit_every = int(self.cfg.get("TPU_AUDIT_EVERY", 0))
        max_stretch = int(self.cfg.get("TPU_MAX_STRETCH", 0))
        self.preempted = False
        self._preempt = False
        for w in self.worlds:
            w.preempted = False
            w._preempt = False
        if self._trips is None:
            self._trips = jnp.zeros((len(self.worlds),), jnp.float32)
            self._leader_trips = jnp.float32(0)
        self._report_engine()
        handlers = self._install_preempt_handlers() if self._ckpt_on else {}
        last_ckpt = self.update
        last_audit = self.update
        sysm_on = self.worlds[0].systematics is not None
        try:
            self._stack()
            while not self._exit and not self._preempt:
                if max_updates is not None and self.update >= max_updates:
                    break
                if self._events_due():
                    self._sync_worlds()
                    for w in self.worlds:
                        w.process_events()
                    if any(w._exit for w in self.worlds):
                        self._exit = True
                        break
                    self._stack()
                else:
                    # solo runs call the (idempotent) process_events at
                    # the top of EVERY iteration; with nothing due its
                    # only effect is this cursor -- mirror it so
                    # checkpoints stay byte-identical to solo ones
                    for w in self.worlds:
                        w._events_done_for = self.update
                stretch = self.worlds[0]._plan_stretch(max_updates,
                                                       max_stretch)
                self._scan(stretch)
                if sysm_on:
                    # single-stepped updates drain with the pre-advance
                    # update number, exactly like solo run_update (see
                    # _drain_newborns)
                    self._drain_newborns(self.update if stretch > 1
                                         else self.update - 1)
                for w in self.worlds:
                    if len(w._pending_exec) >= 256:
                        w._flush_exec()
                if sysm_on and self.update % 100 == 0:
                    for w in self.worlds:
                        w.systematics.prune_extinct(keep_ancestry=True)
                if self.exporter is not None:
                    self.exporter.export_deferred(self)
                audit_due = (audit_every
                             and self.update - last_audit >= audit_every)
                ckpt_due = (self._ckpt_on and ckpt_every
                            and self.update - last_ckpt >= ckpt_every)
                if audit_due or ckpt_due:
                    # one sync + one restack even when both cadences
                    # land on the same boundary
                    self._sync_worlds()
                    if audit_due:
                        from avida_tpu.utils.audit import check_invariants
                        for w in self.worlds:
                            check_invariants(self.params, w.state,
                                             where=f"update {self.update}")
                        last_audit = self.update
                    if ckpt_due:
                        self.save_checkpoints()
                        last_ckpt = self.update
                    self._stack()
                if self._boundary_hook is not None:
                    self._boundary_hook(self)
            self._sync_worlds()
            self.preempted = self._preempt
            for w in self.worlds:
                w._preempt = self._preempt
            if self._preempt and self._ckpt_on:
                for w in self.worlds:
                    w.save_checkpoint()
            elif self._ckpt_on and int(self.cfg.get("TPU_CKPT_FINAL", 0)) \
                    and self.update != last_ckpt:
                for w in self.worlds:
                    w.save_checkpoint()
            for w in self.worlds:
                w.preempted = self._preempt
                if self._world_exports(w) and w.state is not None:
                    w.exporter.export(w)
            if self.exporter is not None:
                self.exporter.export_final(self)
        finally:
            import signal as _signal
            for s, h in handlers.items():
                try:
                    _signal.signal(s, h)
                except (ValueError, OSError):
                    pass
            for w in self.worlds:
                for f in w._files.values():
                    try:
                        f.close()
                    except Exception:
                        pass
                w._files = {}
                w._dat_append = True
        return sum(w._flush_exec() for w in self.worlds) - start_insts

    @property
    def num_worlds(self) -> int:
        return len(self.worlds)
