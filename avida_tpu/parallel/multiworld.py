"""Multi-world device batching: one compiled update_scan serving a fleet.

The fleet orchestrator (service/fleet.py) reaches "many tenants" by
spawning one process per world, so a small world -- far too small to
saturate a device, and dominated by per-update host dispatch on any
backend -- pays full launch + compile + dispatch overhead per tenant.
This module is the missing half (ROADMAP item 2): a batched **world
axis**.  W independent worlds with the SAME static configuration
(identical WorldParams -- one compiled program) but distinct seeds are
stacked on a leading axis of every PopulationState leaf and advanced by
chunks of ops/update.update_scan_batched, so W worlds progress in one
device program and aggregate throughput scales with W while compile
cost stays O(1) -- the direct analogue of batch-serving in an inference
stack.  The engine world-FOLDS the hot cycle loop rather than vmapping
it (PR 11): one while_loop at the batch-uniform trip count with
per-world exec masks on the XLA path; one stacked [LP, W*N] kernel grid
on the Pallas / packed-resident paths, where each world's blocks run to
their own budgets (per-block early exit + TPU_KERNEL_ROWSKIP
load-balance the ragged budgets across tenants).  Only the cheap
per-update phases (resources / schedule / bank / birth flush / stats)
are vmapped.

Bit-exactness contract: world w in a batch IS the solo run with seed w.

  * per-world PRNG streams stay `fold_in(run_key_w, update_no)` -- the
    batched scan vmaps the identical per-update program over per-world
    run keys, so every world replays its solo key sequence;
  * the batched run loop calls the SAME chunk planner as World.run
    (World._plan_stretch), so the batch's chunk grid -- and with it
    every event, drain, audit and checkpoint boundary -- is identical
    to each member's solo grid;
  * host accumulators (_avida_time, _total_births, ...) are lifted from
    per-world device scalars into [W] device vectors updated with the
    same per-chunk reductions, so float accumulation order per world is
    unchanged.

Checkpoints are saved PER WORLD by slicing the batched leaves back into
each member World and running the ordinary World.save_checkpoint into
that world's own TPU_CKPT_DIR -- each generation is byte-identical to
the one a solo run would have written at the same boundary, so
`--resume`, ckpt_tool, and the analytics pipeline all work unchanged on
a batch member, and a member can even continue SOLO from a batch
checkpoint (or vice versa) bit-exactly.

Eligibility: everything the chunked solo path requires
(World._chunkable -- no telemetry, no reversion tests, no
generation/births event triggers) plus no flight recorder, no live
analytics and no fault injection (their per-world host pipelines are
not sliced; run those workloads solo).  Systematics IS supported: the
per-world newborn rings are sliced and drained into each member's own
GenotypeArbiter at every chunk boundary.
"""

from __future__ import annotations

import json
import os
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from avida_tpu.ops.update import update_scan_batched
from avida_tpu.world import World


# trace-time probe (the testcpu.gestation_trace_count pattern): the
# Python increment runs only when jit TRACES a new (params, chunk,
# shapes) variant, so the counter counts compiled program variants --
# the serving layer's cache-warmth evidence (a rider admitted into a
# ghost slot of a warm batch must NOT bump it; tests/test_serve_batch)
_SCAN_TRACES = 0


def scan_trace_count() -> int:
    """How many multiworld_scan program variants this process traced."""
    return _SCAN_TRACES


def _compilecache_loads() -> int:
    from avida_tpu.utils import compilecache
    return compilecache.cache_load_count()


@partial(jax.jit, static_argnums=(0, 2), donate_argnums=(1,))
def multiworld_scan(params, bstate, chunk, run_keys, neighbors, u0):
    """Advance W worlds by `chunk` updates in ONE device program.

    bstate: a PopulationState pytree whose every leaf carries a leading
    world axis; run_keys: the stacked per-world run keys.  u0 and the
    neighbor table are shared (the batch advances on one update grid
    and static-equal configs have one world geometry).  Returns the
    batched final state plus the per-update bookkeeping vectors of
    update_scan with a leading world axis ([W, chunk]), extended with a
    seventh vector: each world's own per-update trip count (the
    efficiency/straggler attribution input).

    The engine (ops/update.update_scan_batched) world-FOLDS the cycle
    loop instead of vmapping it: one while_loop at the batch-uniform
    trip count with per-world exec masks on the XLA path, one stacked
    [LP, W*N] kernel launch on the Pallas paths -- no per-cycle select
    over carry leaves, no vmapped control flow (the PR-10 engine's
    batching tax; BENCH_r08_local.json).  Every world remains bit-exact
    vs its solo run.

    u0 is a shared scalar (the aligned MultiWorld batch) or a [W]
    vector of per-world update counters (the ServeBatch dynamic
    membership path -- each world advances from its OWN update, so its
    PRNG stream and event grid stay exactly its solo run's).

    The batched state is DONATED, exactly like update_scan's."""
    global _SCAN_TRACES
    _SCAN_TRACES += 1
    return update_scan_batched(params, bstate, chunk, run_keys,
                               neighbors, u0)


def _event_key(ev):
    return (ev.trigger, ev.start, ev.interval, ev.stop, ev.action,
            tuple(ev.args))


class MultiWorld:
    """Driver for one batch of static-equal worlds (see module header).

    Build with a list of fully-constructed Worlds (distinct seeds /
    data dirs / checkpoint dirs; identical everything-static), or via
    `from_seeds` / `from_manifest`.
    """

    def __init__(self, worlds, data_dir: str | None = None):
        if not worlds:
            raise ValueError("MultiWorld needs at least one world")
        self.worlds = list(worlds)
        w0 = self.worlds[0]
        self.params = w0.params
        self.neighbors = w0.neighbors
        self.cfg = w0.cfg
        self.data_dir = data_dir or w0.data_dir
        n0 = np.asarray(w0.neighbors)
        ev0 = [_event_key(e) for e in w0.events]
        for w in self.worlds[1:]:
            if w.params != w0.params:
                raise ValueError(
                    "multi-world batch needs identical static configs "
                    "(WorldParams differ; only seeds and output dirs may "
                    "vary across a batch)")
            if not np.array_equal(np.asarray(w.neighbors), n0):
                raise ValueError(
                    "multi-world batch needs one shared world topology "
                    "(neighbor tables differ -- scale-free geometries "
                    "draw per-seed graphs and cannot batch)")
            if [_event_key(e) for e in w.events] != ev0:
                raise ValueError("multi-world batch needs one shared "
                                 "event schedule")
        for w in self.worlds:
            if not w._chunkable():
                raise ValueError(
                    "multi-world batching requires chunkable runs: no "
                    "telemetry, no offspring reversion tests, no "
                    "generation/births event triggers")
            if w.tracer is not None or w.analytics is not None \
                    or w.faults is not None:
                raise ValueError(
                    "multi-world batching does not slice the flight "
                    "recorder, live analytics or fault-injection host "
                    "pipelines; run those workloads solo")
        if len({id(w.cfg) for w in self.worlds}) != len(self.worlds) \
                and len(self.worlds) > 1:
            raise ValueError("each batch member needs its own config "
                             "object (distinct seeds / dirs)")
        self.update = w0.update
        if any(w.update != self.update for w in self.worlds):
            raise ValueError("batch members disagree on the current "
                             "update; resume() aligns them first")
        dirs = [os.path.abspath(w.data_dir) for w in self.worlds]
        if len(set(dirs)) != len(dirs):
            raise ValueError("batch members share a data_dir; each "
                             "world needs its own .dat output dir")
        self._ckpt_on = all(w._ckpt_base() for w in self.worlds)
        if self._ckpt_on:
            cks = [os.path.abspath(w._ckpt_base()) for w in self.worlds]
            if len(set(cks)) != len(cks):
                # a config-FILE TPU_CKPT_DIR reaches every member
                # verbatim (from_seeds only suffixes override-supplied
                # dirs): same-update generations would silently clobber
                # each other and a resume would restore ONE world's
                # bytes into all members
                raise ValueError(
                    "batch members share a checkpoint dir; give each "
                    "world its own TPU_CKPT_DIR (the --worlds CLI and "
                    "the fleet manifest do this per world)")
        self._exit = False
        self._preempt = False
        self.preempted = False
        self.bstate = None
        self._run_keys = None
        self._avida_time = None
        self._last_ave_gen = None
        self._deaths_this = None
        self._prev_alive = None
        self._total_births = None
        # batch-lifetime occupancy accumulators (f32 device values; fed
        # by _scan, published by MultiWorldExporter): per-world trip
        # totals, the per-update batch-max total, and the update count
        # they cover.  batch_efficiency = sum(trips) / (W * leader);
        # straggler lag_w = (leader - trips_w) / (leader / updates) --
        # how many leader-updates' worth of cycles world w spent masked
        self._trips = None
        self._leader_trips = None
        self._trips_updates = 0
        self.engine = None             # "packed-stacked" | "per-update",
        #                                set (and runlog-reported) by run()
        self._boundary_hook = None     # test seam (chaos drills): called
        #                                after every chunk boundary
        # silent-corruption integrity plane (ops/digest.py; the solo
        # World knobs, batched: per-world [W] digests at every chunk
        # boundary, sampled whole-batch shadow re-execution).  Batch
        # members cannot arm fault injection (refused above), so the
        # shadow replay runs the identical compiled program.
        from avida_tpu.utils import integrity as _integrity
        self._digest_on = _integrity.digest_enabled(self.cfg)
        self._scrub_every = _integrity.scrub_every(self.cfg)
        self._chunk_no = 0
        self._digest_pending = None    # (update, device u32[W]) deferred
        self.state_digests = None      # (update, [W] values) last resolved
        self._last_verified_update = self.update
        self.names = [f"w{k:03d}" for k in range(len(self.worlds))]
        self.exporter = None
        if int(self.cfg.get("TPU_METRICS", 0)):
            from avida_tpu.observability.exporter import MultiWorldExporter
            self.exporter = MultiWorldExporter(self)
        # performance attribution plane (observability/profiler.py):
        # batched flavor -- fenced pre/cycles/post probes on COPIES of
        # the stacked state (XLA fold path; packed-kernel batches keep
        # whole-chunk attribution), per-world footprint rows
        self.profiler = None
        from avida_tpu.observability import profiler as _profiler
        if _profiler.enabled(self.cfg):
            self.profiler = _profiler.ChunkProfiler(
                self.data_dir, self.cfg, kind="multiworld")

    # ---- construction helpers ----

    @classmethod
    def from_seeds(cls, seeds, config_dir: str | None = None,
                   overrides=None, data_dir: str = "data",
                   ckpt_dir: str | None = None, names=None) -> "MultiWorld":
        """One world per seed, static config shared.  World k writes its
        .dat output to `<data_dir>/<name_k>` (names default w000, w001,
        ...) and, when `ckpt_dir` (or a TPU_CKPT_DIR override) is given,
        checkpoints to `<ckpt_dir>/<name_k>`."""
        overrides = list(overrides or [])
        if ckpt_dir is None:
            for n, v in overrides:
                if n == "TPU_CKPT_DIR" and str(v) not in ("-", ""):
                    ckpt_dir = str(v)
        base = [(n, v) for n, v in overrides
                if n not in ("RANDOM_SEED", "TPU_CKPT_DIR")]
        names = list(names or [f"w{k:03d}" for k in range(len(seeds))])
        entries = []
        for name, seed in zip(names, seeds):
            entries.append({
                "name": name, "seed": int(seed),
                "data_dir": os.path.join(data_dir, name),
                "ckpt_dir": (os.path.join(ckpt_dir, name)
                             if ckpt_dir else None)})
        return cls._from_entries(entries, config_dir, base, data_dir)

    @classmethod
    def from_manifest(cls, path: str, config_dir: str | None = None,
                      overrides=None,
                      data_dir: str | None = None) -> "MultiWorld":
        """Batch from a worlds.json manifest -- a list of
        {"name", "seed", "data_dir", "ckpt_dir"} entries (the fleet
        orchestrator's device-lane packing writes one per coalesced
        batch; service/fleet.py)."""
        try:
            with open(path) as f:
                entries = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise ValueError(f"{path}: unreadable worlds manifest ({e})")
        if not isinstance(entries, list) or not entries:
            raise ValueError(f"{path}: worlds manifest must be a "
                             f"non-empty JSON list")
        for k, e in enumerate(entries):
            # operator-facing input: refuse with a one-line reason (the
            # --worlds CLI maps ValueError to exit 2), never a KeyError
            # traceback a supervisor would crash-loop on
            if not isinstance(e, dict) or not str(e.get("data_dir", "")):
                raise ValueError(f"{path}: entry {k} must be an object "
                                 f"with at least 'seed' and 'data_dir'")
            try:
                int(e["seed"])
            except (KeyError, TypeError, ValueError):
                raise ValueError(f"{path}: entry {k} needs an integer "
                                 f"'seed'")
        base = [(n, v) for n, v in (overrides or [])
                if n not in ("RANDOM_SEED", "TPU_CKPT_DIR")]
        return cls._from_entries(entries, config_dir, base,
                                 data_dir or os.path.dirname(path))

    @classmethod
    def _from_entries(cls, entries, config_dir, base_overrides, data_dir):
        worlds = []
        for e in entries:
            ov = list(base_overrides) + [("RANDOM_SEED", int(e["seed"]))]
            if e.get("ckpt_dir"):
                ov.append(("TPU_CKPT_DIR", e["ckpt_dir"]))
            worlds.append(World(config_dir=config_dir, overrides=ov,
                                data_dir=e["data_dir"]))
        mw = cls(worlds, data_dir=data_dir)
        mw.names = [str(e.get("name", f"w{k:03d}"))
                    for k, e in enumerate(entries)]
        return mw

    # ---- batched <-> per-world state movement ----

    def _stack(self):
        """Stack the member states (and the per-world host accumulator
        scalars) onto the leading world axis.  Member .state references
        are dropped: the batched buffers are donated every chunk and the
        members get fresh slices back at the next host boundary."""
        if self.bstate is not None:
            return
        self.bstate = jax.tree.map(lambda *xs: jnp.stack(xs),
                                   *[w.state for w in self.worlds])
        self._run_keys = jnp.stack([w._run_key for w in self.worlds])
        self._avida_time = jnp.stack(
            [jnp.asarray(w._avida_time, jnp.float32) for w in self.worlds])
        self._last_ave_gen = jnp.stack(
            [jnp.asarray(w._last_ave_gen, jnp.float32)
             for w in self.worlds])
        self._deaths_this = jnp.stack(
            [jnp.asarray(w._deaths_this, jnp.int32) for w in self.worlds])
        self._prev_alive = (
            None if any(w._prev_alive is None for w in self.worlds)
            else jnp.stack([jnp.asarray(w._prev_alive, jnp.int32)
                            for w in self.worlds]))
        self._total_births = jnp.stack(
            [jnp.asarray(w._total_births, jnp.int32) for w in self.worlds])
        for w in self.worlds:
            w.state = None

    def _sync_worlds(self):
        """Slice the batched state + accumulators back into each member
        World (a host boundary: events, checkpoints, audits, run exit).
        Slices are materialized copies, so they survive the next chunk's
        donation of the batched buffers."""
        if self.bstate is None:
            return
        for i, w in enumerate(self.worlds):
            w.state = jax.tree.map(lambda x, i=i: x[i], self.bstate)
            w.update = self.update
            w._avida_time = self._avida_time[i]
            w._last_ave_gen = self._last_ave_gen[i]
            w._deaths_this = self._deaths_this[i]
            w._prev_alive = (None if self._prev_alive is None
                             else self._prev_alive[i])
            w._total_births = self._total_births[i]
            w._summary_cache_update = None
        self.bstate = None

    # ---- the batched run loop (mirrors World.run's chunk grid) ----

    def _scan(self, k: int):
        """One batched chunk: W worlds x k updates, one device program.
        The same per-chunk accumulator updates as World._scan_updates,
        vectorized over the world axis (same per-world float order).
        The extra `trips` vector feeds the batch-efficiency /
        straggler-lag gauges: trips[w, u] is world w's OWN trip count
        at update u, while the batch ran max over worlds."""
        from avida_tpu.utils import compilecache
        if self.profiler is not None:
            self.profiler.chunk_begin(k)
        pre = None
        if self._scrub_every > 0:
            self._chunk_no += 1
            if self._chunk_no % self._scrub_every == 0:
                # pre-chunk copies: multiworld_scan donates the batched
                # buffers, so live and shadow each consume their own
                pre = (jax.tree.map(jnp.copy, self.bstate), self.update)
        self.bstate, (executed, births, deaths, dts, ave_gens, n_alive,
                      trips) = \
            compilecache.call(
                multiworld_scan, "multiworld_scan",
                (self.params, self.bstate, k, self._run_keys,
                 self.neighbors, jnp.int32(self.update)),
                cfg=self.cfg, log=self._compile_cache_log)
        self._avida_time = self._avida_time + dts.sum(axis=1)
        self._last_ave_gen = ave_gens[:, -1]
        self._deaths_this = deaths[:, -1]
        self._prev_alive = n_alive[:, -1]
        self._total_births = self._total_births + births.sum(axis=1)
        # f32 accumulators: int32 trip totals wrap on long uncapped runs
        # (~1e5-trip updates x ~1e5 updates); the gauges they feed are
        # ratios, where f32's 2^-24 relative error is irrelevant
        self._trips = self._trips + trips.sum(axis=1).astype(jnp.float32)
        self._leader_trips = (self._leader_trips
                              + trips.max(axis=0).sum().astype(jnp.float32))
        self._trips_updates += k
        for i, w in enumerate(self.worlds):
            w._pending_exec.append(executed[i])
        self.update += k
        for w in self.worlds:
            w.update = self.update
        if self.profiler is not None:
            self.profiler.chunk_end_batched(self, k, names=self.names)
        if self._digest_on or pre is not None:
            self._integrity_boundary(k, pre)

    # ---- silent-corruption integrity plane (batched flavor) ----

    def _engine_label(self) -> str:
        from avida_tpu.ops import packed_chunk
        from avida_tpu.ops.update import use_pallas_path
        if not use_pallas_path(self.params):
            return "xla-fold"
        if self.engine != "packed-stacked":
            return "pallas-stacked"
        label = "pallas-packed-stacked"
        if packed_chunk.fused_active(self.params):
            label += "+fused"
        if packed_chunk.bits_active(self.params):
            label += "+bits5"
        return label

    def _resolve_digests(self, pending):
        import time as _time
        from avida_tpu.utils import integrity
        u, dev = pending
        t0 = _time.monotonic()
        vals = [int(x) for x in np.asarray(dev)]
        integrity.note_digest_ms((_time.monotonic() - t0) * 1e3)
        self.state_digests = (u, vals)
        integrity.append_integrity_record(
            self.data_dir, "digest", update=u,
            digests={n: f"{v:#010x}"
                     for n, v in zip(self.names, vals)})

    def _flush_digest(self):
        prev, self._digest_pending = self._digest_pending, None
        if prev is not None:
            self._resolve_digests(prev)

    def _integrity_boundary(self, k: int, pre):
        """The solo World._integrity_boundary, vectorized: one batched
        digest ([W] per-world values -- each equals the digest its solo
        run would compute, by the bit-exactness contract), and when the
        chunk was sampled a whole-batch shadow replay whose mismatching
        worlds are NAMED in the raised error."""
        import time as _time

        from avida_tpu.ops.digest import state_digest_batched
        from avida_tpu.utils import integrity
        u1 = self.update
        t0 = _time.monotonic()
        d_live = state_digest_batched(self.bstate)
        integrity.note_digest_ms((_time.monotonic() - t0) * 1e3)
        self._flush_digest()
        if pre is None:
            self._digest_pending = (u1, d_live)
            return
        from avida_tpu.utils import compilecache
        pre_b, u0 = pre
        integrity.note_scrub()
        shadow_b, _outs = compilecache.call(
            multiworld_scan, "multiworld_scan",
            (self.params, pre_b, k, self._run_keys,
             self.neighbors, jnp.int32(u0)),
            cfg=self.cfg, log=self._compile_cache_log)
        t0 = _time.monotonic()
        d_shadow = state_digest_batched(shadow_b)
        live = np.asarray(d_live)
        shad = np.asarray(d_shadow)
        integrity.note_digest_ms((_time.monotonic() - t0) * 1e3)
        bad = [self.names[i] for i in range(len(self.worlds))
               if int(live[i]) != int(shad[i])]
        if bad:
            integrity.note_mismatch()
            engine = self._engine_label()
            integrity.append_integrity_record(
                self.data_dir, "scrub", update=u1, chunk_updates=k,
                ok=False, worlds=bad, engine=engine,
                last_verified_update=self._last_verified_update)
            from avida_tpu.observability.runlog import emit_event
            from avida_tpu.utils.integrity import StateDivergenceError
            emit_event(self.worlds[0], "state_divergence", update=u1,
                       worlds=",".join(bad))
            raise StateDivergenceError(
                f"silent state divergence in updates [{u0}, {u1}) of "
                f"world(s) {', '.join(bad)}: live digests != shadow "
                f"replay (engine {engine}, "
                f"last_verified_update={self._last_verified_update})")
        self._last_verified_update = u1
        vals = [int(x) for x in live]
        if self._digest_on:
            self.state_digests = (u1, vals)
            integrity.append_integrity_record(
                self.data_dir, "digest", update=u1,
                digests={n: f"{v:#010x}"
                         for n, v in zip(self.names, vals)})
        integrity.append_integrity_record(
            self.data_dir, "scrub", update=u1, chunk_updates=k, ok=True)

    def _compile_cache_log(self, **fields):
        """compile_cache journal shim for the batch's cached program
        constructions -- lands in the lead member's telemetry when
        armed, stderr always (runlog.emit_event)."""
        from avida_tpu.observability.runlog import emit_event
        emit_event(self.worlds[0], "compile_cache", **fields)

    def _events_due(self) -> bool:
        for ev in self.worlds[0].events:
            if ev.trigger == "update" and ev.fires_at(self.update):
                return True
            if ev.trigger == "immediate" and self.update == 0:
                return True
        return False

    def _drain_newborns(self, at: int):
        """Slice the batched newborn rings and feed each member's own
        GenotypeArbiter, synchronously at every chunk boundary -- the
        same window boundaries (and therefore the same record grouping
        and death resolution) as the member's solo run.

        `at` is the update number stamped on the drain window.  Solo
        runs stamp a >1-update chunk with the post-chunk update
        (World._snapshot_newborns) but a single-stepped update with the
        update just run (run_update drains BEFORE World.run advances
        the counter) -- the caller passes the matching value so the
        serialized last_drain_update, and every systematics.process
        call, stays identical to the solo run's.

        Per-world snap entries stay DEVICE slices: _feed_systematics
        reads the nb_* rings only up to nb_count and touches the wide
        arrays (genome/birth_update/parent_id) solely in the overflow
        fallback, so eagerly np.asarray-ing the [W, N, L] genome plane
        here would fence the device for tens of MB per boundary that
        are almost never read."""
        if self.worlds[0].systematics is None:
            return
        for i, w in enumerate(self.worlds):
            snap = {name: getattr(self.bstate, name)[i]
                    for name in World._NB_SNAP_FIELDS}
            snap["update_at"] = at
            snap["win_start"] = w._last_drain_update
            w._last_drain_update = at
            w._feed_systematics(snap)
        self.bstate = self.bstate.replace(
            nb_count=jnp.zeros((len(self.worlds),), jnp.int32))

    # the solo handler verbatim (same `_preempt` attribute contract,
    # including the second-Ctrl-C escalation and the off-main-thread
    # guard) -- one spelling, so a future fix applies to both drivers
    _install_preempt_handlers = World._install_preempt_handlers

    def _report_engine(self):
        """Make the batch's chunk engine explicit and LOUD: a batch that
        cannot take the stacked packed-resident path (ops/packed_chunk.
        pack once -> stacked kernel scan -> unpack once) silently ran
        the per-update engine before this PR; now the choice lands in
        the runlog ({"record": "event"} + stderr echo) with the exact
        ineligibility reason, so a fleet operator can see why a batch
        is not on the fast path.  Called once per run()."""
        from avida_tpu.observability import runlog
        from avida_tpu.ops import packed_chunk
        w0 = self.worlds[0]
        # params.nb_cap is the static source of the newborn-ring gate
        # (>0 iff TPU_SYSTEMATICS; the ring arrays are shaped from it),
        # so the report matches what batch_active actually routes on
        rep = packed_chunk.engine_report(self.params,
                                         self.params.nb_cap > 0)
        reason = rep.get("fallback_reason")
        self.engine = "packed-stacked" if reason is None else "per-update"
        self.engine_report = rep
        fields = {"engine": self.engine, "worlds": len(self.worlds)}
        # sub-path vocabulary (fused vs legacy row-space vs per-update
        # fallback, bits armed/refused) rides the same event, so a
        # silent downgrade inside the packed engine is as loud as the
        # packed->per-update one
        for k in ("fallback_reason", "sub_path", "fused_fallback_reason",
                  "packed_bits", "bits_fallback_reason"):
            if k in rep:
                fields[k] = rep[k]
        runlog.emit_event(w0, "multiworld_engine", **fields)
        return reason

    def save_checkpoints(self):
        """One ordinary per-world checkpoint generation each, into each
        member's own TPU_CKPT_DIR -- byte-identical to the generation a
        solo run would publish at this boundary."""
        self._sync_worlds()
        for w in self.worlds:
            w.save_checkpoint()
            if self._world_exports(w):
                # per-world heartbeat refresh: the boundary already
                # synced, so the readback is free -- fleet --status
                # member sub-rows stay no staler than one save interval
                w.exporter.export(w)

    def _world_exports(self, w) -> bool:
        """A member writes its own metrics.prom unless that path IS the
        batch aggregate's (the fleet's leader world shares the root
        data dir; its rows live in multiworld.prom instead)."""
        if w.exporter is None:
            return False
        return (self.exporter is None
                or os.path.abspath(w.exporter.path)
                != os.path.abspath(self.exporter.path))

    def resume(self, at_update: int | None = None) -> int:
        """Restore every member from its own checkpoint dir, aligned on
        one common update: the newest update for which EVERY member
        retains a generation (intersection, not min-of-newest: with a
        short retention an ahead member may have pruned the update a
        behind member fell back to -- skipping to the next common
        update recovers instead of wedging).  A generation that fails
        CRC drops the whole candidate update and the next-newest
        common one is tried.  Returns the aligned update."""
        from avida_tpu.utils import checkpoint as ckpt_mod
        if at_update is None:
            sets = []
            for w in self.worlds:
                ups = {ckpt_mod.generation_update(p)
                       for p in ckpt_mod.restore_candidates(
                           w._ckpt_base())}
                sets.append({u for u in ups if u >= 0})
            common = set.intersection(*sets) if sets else set()
            if not common:
                raise ckpt_mod.CheckpointError(
                    "no checkpoint update common to every batch member "
                    "(mixed progress resumes aligned or not at all)")
            candidates = sorted(common, reverse=True)
        else:
            candidates = [int(at_update)]
        last_err = None
        for u in candidates:
            try:
                for w in self.worlds:
                    restored = w.resume(at_update=u)
                    assert restored == u
            except ckpt_mod.CheckpointMismatchError:
                raise
            except ckpt_mod.CheckpointError as e:
                last_err = e
                continue
            self.update = u
            # every member's restored generation passed the manifest
            # digest check -- the scrub verification horizon restarts
            # here (the solo World.resume rule)
            self._last_verified_update = u
            return u
        raise last_err or ckpt_mod.CheckpointError("batch resume failed")

    def run(self, max_updates: int | None = None):
        """The batched master loop.  Structurally World.run with the
        device work vectorized over the world axis: one shared chunk
        grid (World._plan_stretch on the common update counter), host
        boundaries -- events, newborn drains, audits, auto-saves,
        preemption -- at exactly the updates each member's solo run
        would have them.  Returns total instructions executed across
        the batch this call."""
        for w in self.worlds:
            if w.state is None:
                w.process_events()
                if w.state is None:
                    w.inject()
        start_insts = sum(w._cum_insts for w in self.worlds)
        ckpt_every = int(self.cfg.get("TPU_CKPT_EVERY", 0))
        audit_every = int(self.cfg.get("TPU_AUDIT_EVERY", 0))
        max_stretch = int(self.cfg.get("TPU_MAX_STRETCH", 0))
        self.preempted = False
        self._preempt = False
        for w in self.worlds:
            w.preempted = False
            w._preempt = False
        if self._trips is None:
            self._trips = jnp.zeros((len(self.worlds),), jnp.float32)
            self._leader_trips = jnp.float32(0)
        self._report_engine()
        handlers = self._install_preempt_handlers() if self._ckpt_on else {}
        last_ckpt = self.update
        last_audit = self.update
        sysm_on = self.worlds[0].systematics is not None
        try:
            self._stack()
            while not self._exit and not self._preempt:
                if max_updates is not None and self.update >= max_updates:
                    break
                if self._events_due():
                    self._sync_worlds()
                    for w in self.worlds:
                        w.process_events()
                    if any(w._exit for w in self.worlds):
                        self._exit = True
                        break
                    self._stack()
                else:
                    # solo runs call the (idempotent) process_events at
                    # the top of EVERY iteration; with nothing due its
                    # only effect is this cursor -- mirror it so
                    # checkpoints stay byte-identical to solo ones
                    for w in self.worlds:
                        w._events_done_for = self.update
                stretch = self.worlds[0]._plan_stretch(max_updates,
                                                       max_stretch)
                self._scan(stretch)
                if sysm_on:
                    # single-stepped updates drain with the pre-advance
                    # update number, exactly like solo run_update (see
                    # _drain_newborns)
                    self._drain_newborns(self.update if stretch > 1
                                         else self.update - 1)
                for w in self.worlds:
                    if len(w._pending_exec) >= 256:
                        w._flush_exec()
                if sysm_on and self.update % 100 == 0:
                    for w in self.worlds:
                        w.systematics.prune_extinct(keep_ancestry=True)
                if self.exporter is not None:
                    self.exporter.export_deferred(self)
                audit_due = (audit_every
                             and self.update - last_audit >= audit_every)
                ckpt_due = (self._ckpt_on and ckpt_every
                            and self.update - last_ckpt >= ckpt_every)
                if audit_due or ckpt_due:
                    # one sync + one restack even when both cadences
                    # land on the same boundary
                    self._sync_worlds()
                    if audit_due:
                        from avida_tpu.utils.audit import check_invariants
                        for w in self.worlds:
                            check_invariants(self.params, w.state,
                                             where=f"update {self.update}")
                        last_audit = self.update
                    if ckpt_due:
                        self.save_checkpoints()
                        last_ckpt = self.update
                    self._stack()
                if self._boundary_hook is not None:
                    self._boundary_hook(self)
            self._sync_worlds()
            self._flush_digest()
            self.preempted = self._preempt
            for w in self.worlds:
                w._preempt = self._preempt
            if self._preempt and self._ckpt_on:
                for w in self.worlds:
                    w.save_checkpoint()
            elif self._ckpt_on and int(self.cfg.get("TPU_CKPT_FINAL", 0)) \
                    and self.update != last_ckpt:
                for w in self.worlds:
                    w.save_checkpoint()
            for w in self.worlds:
                w.preempted = self._preempt
                if self._world_exports(w) and w.state is not None:
                    w.exporter.export(w)
            # (no profiler.final here: the batch is unstacked at exit
            # -- the last probe's batched footprint already stands, and
            # export_final republishes it via prom_families)
            if self.exporter is not None:
                self.exporter.export_final(self)
        finally:
            import signal as _signal
            for s, h in handlers.items():
                try:
                    _signal.signal(s, h)
                except (ValueError, OSError):
                    pass
            for w in self.worlds:
                for f in w._files.values():
                    try:
                        f.close()
                    except Exception:
                        pass
                w._files = {}
                w._dat_append = True
        return sum(w._flush_exec() for w in self.worlds) - start_insts

    @property
    def num_worlds(self) -> int:
        return len(self.worlds)


# ---------------------------------------------------------------------------
# ServeBatch: ghost-padded dynamic membership (the streaming serve layer)
# ---------------------------------------------------------------------------

def pow2_floor(n: int) -> int:
    """Largest power of two <= max(n, 1) -- the serve loop's stretch
    quantizer.  Chunk length is a STATIC jit argument, so an arbitrary
    gap-to-next-boundary would compile one scan program per distinct
    gap; quantizing to powers of two bounds the compiled set to
    log2(cap) variants, all warm after the first few boundaries."""
    return 1 << (max(int(n), 1).bit_length() - 1)


class ServeBatch:
    """A fixed-width, dynamic-membership serving batch (ROADMAP item 2:
    "from spool to service").

    Where MultiWorld freezes membership at construction and requires
    every member at the same update, ServeBatch is built at a fixed
    padded width W (a power-of-two batchability class, the way
    analyze/testcpu.py bucket-pads Test-CPU batches) and serves a
    CHURNING population of tenants: slots hold either a live tenant
    World or an inert GHOST -- an all-dead copy of the template state.
    A fully-masked world is an exact identity (the PR-11 world-fold
    contract for budget-exhausted lanes, proven by the ragged-budget
    tests), so a ghost contributes zero trips, zero device work beyond
    the shared launch, and -- because every engine phase is world-local
    (vmapped or world-blocked) -- cannot perturb any live world by a
    single bit.

    Because the compiled scan's shapes are pinned by W (not by the live
    member count), membership churn never changes the program: a rider
    promoted into a ghost slot at a checkpoint boundary reaches its
    first executed update on the ALREADY-COMPILED program
    (scan_trace_count() is the in-tree probe), and a demoted member
    frees its slot back to ghost without a recompile on either side.

    Per-world update counters (the u0 vector of update_scan_batched)
    let tenants ride at different points of their runs: each world's
    PRNG stream stays fold_in(run_key_w, own_update) and its event grid
    stays its solo grid, so every tenant's trajectory is bit-exact vs
    its uninterrupted solo run.  (Host-side f32 `_avida_time` can
    differ in last bits from a solo run when the chunk split differs --
    the long-standing cross-chunking caveat; all device state, PRNG
    streams, .dat-visible values and integer accumulators are exact.)

    Membership protocol (the fleet serve pool drives this; a human can
    too): `control_path` is an atomically-rewritten JSON document

        {"width": W, "shutdown": false,
         "members": [{"name", "seed", "data_dir", "ckpt_dir",
                      "max_updates"}, ...]}

    reconciled at every checkpoint boundary: members present in the
    control and not in a slot are ADMITTED (resumed from their own
    ckpt_dir when generations exist -- the solo<->batch free-transition
    contract -- else injected fresh); live slots absent from the
    control are RETIRED (final checkpoint, .dat files closed, slot
    back to ghost).  A member reaching its max_updates (or an Exit
    event) retires as "done".  The batch reports back through
    DATA_DIR/serve.json (atomic) plus the metrics.prom heartbeat and
    multiworld.prom per-world rows (exporter.ServeExporter), and
    keeps serving -- idle with zero tenants it sleeps host-side,
    holding every compiled program warm, until TPU_SERVE_IDLE_SEC
    expires or the control sets "shutdown": true.

    SIGTERM preempts exactly like a solo run: every live tenant saves
    a final checkpoint and the process exits cleanly for the
    supervisor to relaunch with --resume."""

    def __init__(self, width: int, control_path: str, data_dir: str,
                 config_dir: str | None = None, overrides=None,
                 world_factory=None, clock=time.time, sleep=time.sleep):
        if width < 1:
            raise ValueError("ServeBatch width must be >= 1")
        self.width = int(width)
        self.control_path = control_path
        self.data_dir = data_dir
        self._config_dir = config_dir
        self._overrides = list(overrides or [])
        self._factory = world_factory or self._config_factory
        self._clock = clock
        self._sleep = sleep

        # the template/ghost world: same static config as every member
        # (seed irrelevant -- a ghost never executes), its state turned
        # all-dead.  Dead lanes get zero grants (the audited scheduler
        # invariant), so a ghost's trip count is 0 every update.
        gw = self._factory({"name": "__ghost__", "seed": 0,
                            "data_dir": os.path.join(data_dir, ".ghost"),
                            "ckpt_dir": None})
        if gw.tracer is not None or gw.analytics is not None \
                or gw.faults is not None or not gw._chunkable():
            raise ValueError(
                "serve batches need chunkable configs with no flight "
                "recorder, live analytics or fault injection (the same "
                "rules as --worlds; run those workloads solo)")
        gw.process_events()
        if gw.state is None:
            gw.inject()
        self.params = gw.params
        self.neighbors = gw.neighbors
        self.cfg = gw.cfg
        self._ghost_state = gw.state.replace(
            alive=jnp.zeros_like(gw.state.alive))
        self._ghost_key = gw._run_key
        gw.state = None
        for f in gw._files.values():
            try:
                f.close()
            except Exception:
                pass
        gw._files = {}
        self._ghost_events = [_event_key(e) for e in gw.events]

        self.slots: list = [None] * self.width
        self.names: list = [None] * self.width
        self.max_updates: list = [None] * self.width
        self.finished: dict = {}        # name -> {"state", "update", ...}
        self.bstate = None
        self._run_keys = None
        self._avida_time = None
        self._last_ave_gen = None
        self._deaths_this = None
        self._prev_alive = None
        self._total_births = None
        self._trips = jnp.zeros((self.width,), jnp.float32)
        self._leader_trips = jnp.float32(0)
        self._trips_updates = 0
        self.admissions = 0
        self.retirements = 0
        self.boundaries = 0
        self._exit = False
        self._preempt = False
        self.preempted = False
        self._shutdown = False
        self._boundary_hook = None      # test seam: after each
        #                                 checkpoint-boundary reconcile
        self._sysm_on = bool(int(self.cfg.get("TPU_SYSTEMATICS", 1)))
        # silent-corruption integrity plane, serve flavor: per-world
        # digests + sampled whole-batch shadow replay, but a mismatching
        # TENANT is demoted ALONE (suspect generations quarantined, slot
        # back to ghost, outcome "sdc" for the pool to requeue) while
        # classmates keep serving -- only a diverging GHOST slot (which
        # runs a zero-trip identity and cannot legitimately change)
        # escalates to a batch-wide StateDivergenceError
        from avida_tpu.utils import integrity as _integrity
        self._digest_on = _integrity.digest_enabled(self.cfg)
        self._scrub_every = _integrity.scrub_every(self.cfg)
        self._chunk_no = 0
        self._verified = [0] * self.width   # per-slot verified horizon
        self.state_digests = None           # (boundary, {name: value})
        # the batchability-class signature the pool stamped into the
        # control file (absent on hand-written controls): stored into
        # compile-cache entry manifests so cache_tool can attribute an
        # entry to its serve class
        self._serve_sig = (self._read_control() or {}).get("sig")
        self.exporter = None
        if int(self.cfg.get("TPU_METRICS", 0)):
            from avida_tpu.observability.exporter import ServeExporter
            self.exporter = ServeExporter(self)
        # performance attribution plane, serve flavor: the batched
        # probe + per-slot footprint (ghost overhead included -- the
        # padding-cost number ROADMAP item 4 wants from serving)
        self.profiler = None
        from avida_tpu.observability import profiler as _profiler
        if _profiler.enabled(self.cfg):
            self.profiler = _profiler.ChunkProfiler(
                self.data_dir, self.cfg, kind="serve")

    # the solo preemption contract verbatim (shared spelling)
    _install_preempt_handlers = World._install_preempt_handlers

    def request_stop(self):
        self._exit = True

    # ---- membership ----

    def _config_factory(self, entry):
        ov = [(n, v) for n, v in self._overrides
              if n not in ("RANDOM_SEED", "TPU_CKPT_DIR")]
        ov.append(("RANDOM_SEED", int(entry["seed"])))
        if entry.get("ckpt_dir"):
            ov.append(("TPU_CKPT_DIR", entry["ckpt_dir"]))
        return World(config_dir=self._config_dir, overrides=ov,
                     data_dir=entry["data_dir"])

    def _live(self) -> list:
        return [(i, w) for i, w in enumerate(self.slots) if w is not None]

    def _member_exports(self, w) -> bool:
        """A member writes its own metrics.prom unless its data dir IS
        the batch root's (the MultiWorld._world_exports rule)."""
        return (w.exporter is not None
                and os.path.abspath(w.data_dir)
                != os.path.abspath(self.data_dir))

    @property
    def num_live(self) -> int:
        return sum(1 for w in self.slots if w is not None)

    @property
    def num_ghosts(self) -> int:
        return self.width - self.num_live

    def _log(self, msg: str):
        import sys
        print(f"[serve] {msg}", file=sys.stderr)

    def _compile_cache_log(self, **fields):
        """compile_cache journal shim for the serve child's cached
        program constructions (stderr via runlog.emit_event; no member
        owns the batch-wide program, so no telemetry writer)."""
        from avida_tpu.observability.runlog import emit_event
        emit_event(None, "compile_cache", **fields)

    def _read_control(self):
        try:
            with open(self.control_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None                 # absent/torn: keep serving as-is

    def admit(self, entry) -> bool:
        """Place one tenant into a free ghost slot (requires a synced
        batch).  Resumes from the entry's own checkpoint dir when
        generations exist, else starts fresh.  Returns True when the
        tenant occupies a slot (False: rejected or already finished,
        recorded in `finished` for the status file)."""
        from avida_tpu.utils.checkpoint import (CheckpointError,
                                                restore_candidates)
        name = str(entry["name"])
        free = [i for i, w in enumerate(self.slots) if w is None]
        if not free:
            self.finished[name] = {"state": "rejected",
                                   "reason": "no free slot"}
            return False
        try:
            w = self._factory(entry)
        except (ValueError, OSError) as e:
            self.finished[name] = {"state": "rejected", "reason": str(e)}
            return False
        reason = self._ineligible(w)
        if reason is not None:
            self.finished[name] = {"state": "rejected", "reason": reason}
            for f in w._files.values():
                try:
                    f.close()
                except Exception:
                    pass
            return False
        if w._ckpt_base() and restore_candidates(w._ckpt_base()):
            try:
                w.resume()
            except CheckpointError as e:
                self.finished[name] = {"state": "rejected",
                                       "reason": f"resume failed: {e}"}
                return False
        else:
            w.process_events()
            if w.state is None:
                w.inject()
        cap = entry.get("max_updates")
        cap = None if cap is None else int(cap)
        if cap is not None and w.update >= cap:
            # already complete (e.g. readmitted after a crash that
            # outran the done ack): report done without a slot
            self.finished[name] = {"state": "done", "update": w.update,
                                   "insts": w._cum_insts}
            return False
        i = free[0]
        self.slots[i] = w
        self.names[i] = name
        self.max_updates[i] = cap
        # the admitted state is digest-verified (resume re-checks the
        # manifest digest) or freshly injected -- either way the scrub
        # verification horizon for this slot starts here
        self._verified[i] = w.update
        self.finished.pop(name, None)
        self.admissions += 1
        self._log(f"admit {name} -> slot {i} at update {w.update}"
                  + (f" (budget {cap})" if cap is not None else ""))
        return True

    def _ineligible(self, w) -> str | None:
        """Why a candidate World cannot join this batch (None = it
        can).  The MultiWorld static-equality rules, per slot."""
        if w.params != self.params:
            return ("static config differs from the batch class "
                    "(WorldParams mismatch)")
        if not np.array_equal(np.asarray(w.neighbors),
                              np.asarray(self.neighbors)):
            return "world topology differs from the batch class"
        if [_event_key(e) for e in w.events] != self._ghost_events:
            return "event schedule differs from the batch class"
        if not w._chunkable() or w.tracer is not None \
                or w.analytics is not None or w.faults is not None:
            return ("unchunkable config (telemetry/reversion/"
                    "generation triggers) or per-run host pipeline "
                    "(trace/analytics/faults)")
        taken_d = {os.path.abspath(x.data_dir) for _, x in self._live()}
        if os.path.abspath(w.data_dir) in taken_d:
            return "data_dir already served by another slot"
        if w._ckpt_base():
            taken_c = {os.path.abspath(x._ckpt_base())
                       for _, x in self._live() if x._ckpt_base()}
            if os.path.abspath(w._ckpt_base()) in taken_c:
                return "ckpt_dir already served by another slot"
        return None

    def _retire(self, i: int, state: str, save: bool = True):
        """Free slot i back to ghost (requires a synced batch): final
        checkpoint (the demotion/completion handoff artifact -- a
        demoted tenant resumes solo or in another batch from it,
        bit-exactly), .dat files closed, outcome recorded for the
        status file."""
        w = self.slots[i]
        name = self.names[i]
        if save and w._ckpt_base() and w.state is not None:
            from avida_tpu.utils.checkpoint import (generation_update,
                                                    list_generations)
            gens = list_generations(w._ckpt_base())
            if not gens or generation_update(gens[-1]) != w.update:
                # skip the re-save when the boundary autosave just
                # published this very update (retirement at a
                # checkpoint boundary -- the common case)
                w.save_checkpoint()
        if self._member_exports(w) and w.state is not None:
            w.exporter.export(w)        # final per-tenant heartbeat
        insts = w._flush_exec()
        for f in w._files.values():
            try:
                f.close()
            except Exception:
                pass
        w._files = {}
        w._dat_append = True
        self.finished[name] = {"state": state, "update": w.update,
                               "insts": insts}
        if len(self.finished) > 4096:
            self.finished.pop(next(iter(self.finished)))
        self.slots[i] = None
        self.names[i] = None
        self.max_updates[i] = None
        self._verified[i] = 0
        self.retirements += 1
        self._log(f"retire {name} ({state}) at update {w.update}")

    def _reconcile(self) -> bool:
        """Converge membership to the control file (requires a synced
        batch).  Returns True when membership changed."""
        ctl = self._read_control()
        if ctl is None:
            return False
        self._shutdown = bool(ctl.get("shutdown"))
        want = {}
        for e in ctl.get("members") or []:
            if isinstance(e, dict) and e.get("name") is not None:
                want[str(e["name"])] = e
        changed = False
        for i, w in self._live():
            if self.names[i] not in want:
                self._retire(i, "retired")      # demotion (cancel)
                changed = True
        current = {self.names[i] for i, _ in self._live()}
        for name, e in want.items():
            if name in current or name in self.finished:
                continue                # finished waits for the ack
            changed |= self.admit(e)
        for name in list(self.finished):
            if name not in want:
                # ack: the pool saw the outcome and dropped the member
                # from the control (or demoted it) -- forget it so a
                # future resubmission under the same name readmits
                del self.finished[name]
        return changed

    # ---- batched <-> per-world state movement (ghost-aware) ----

    def _stack(self):
        if self.bstate is not None:
            return
        sts, keys, avt, gen, dth, pal, tb = [], [], [], [], [], [], []
        for w in self.slots:
            if w is None:
                sts.append(self._ghost_state)
                keys.append(self._ghost_key)
                avt.append(jnp.float32(0))
                gen.append(jnp.float32(0))
                dth.append(jnp.int32(0))
                pal.append(jnp.int32(0))
                tb.append(jnp.int32(0))
            else:
                sts.append(w.state)
                keys.append(w._run_key)
                avt.append(jnp.asarray(w._avida_time, jnp.float32))
                gen.append(jnp.asarray(w._last_ave_gen, jnp.float32))
                dth.append(jnp.asarray(w._deaths_this, jnp.int32))
                pal.append(jnp.int32(0) if w._prev_alive is None
                           else jnp.asarray(w._prev_alive, jnp.int32))
                tb.append(jnp.asarray(w._total_births, jnp.int32))
                w.state = None
        self.bstate = jax.tree.map(lambda *xs: jnp.stack(xs), *sts)
        self._run_keys = jnp.stack(keys)
        self._avida_time = jnp.stack(avt)
        self._last_ave_gen = jnp.stack(gen)
        self._deaths_this = jnp.stack(dth)
        self._prev_alive = jnp.stack(pal)
        self._total_births = jnp.stack(tb)

    def _sync_worlds(self):
        if self.bstate is None:
            return
        for i, w in self._live():
            w.state = jax.tree.map(lambda x, i=i: x[i], self.bstate)
            w._avida_time = self._avida_time[i]
            w._last_ave_gen = self._last_ave_gen[i]
            w._deaths_this = self._deaths_this[i]
            w._prev_alive = self._prev_alive[i]
            w._total_births = self._total_births[i]
            w._summary_cache_update = None
        self.bstate = None

    def _scan(self, k: int):
        """One serving chunk: all live worlds advance k updates from
        their OWN update counters (the u0 vector), ghosts run zero-trip
        identities in their slots."""
        u0 = jnp.asarray([0 if w is None else w.update
                          for w in self.slots], jnp.int32)
        from avida_tpu.utils import compilecache
        if self.profiler is not None:
            self.profiler.chunk_begin(k)
        pre = None
        if self._scrub_every > 0:
            self._chunk_no += 1
            if self._chunk_no % self._scrub_every == 0:
                pre = (jax.tree.map(jnp.copy, self.bstate), u0)
        self.bstate, (executed, births, deaths, dts, ave_gens, n_alive,
                      trips) = \
            compilecache.call(
                multiworld_scan, "multiworld_scan",
                (self.params, self.bstate, k, self._run_keys,
                 self.neighbors, u0),
                cfg=self.cfg, log=self._compile_cache_log,
                sig=self._serve_sig)
        self._avida_time = self._avida_time + dts.sum(axis=1)
        self._last_ave_gen = ave_gens[:, -1]
        self._deaths_this = deaths[:, -1]
        self._prev_alive = n_alive[:, -1]
        self._total_births = self._total_births + births.sum(axis=1)
        tl = trips.astype(jnp.float32)
        self._trips = self._trips + tl.sum(axis=1)
        self._leader_trips = self._leader_trips + tl.max(axis=0).sum()
        self._trips_updates += k
        for i, w in self._live():
            w._pending_exec.append(executed[i])
            w.update += k
        if self.profiler is not None:
            live = self._live()
            self.profiler.chunk_end_batched(
                self, k, names=[self.names[i] for i, _ in live],
                num_ghosts=self.num_ghosts,
                update=max((w.update for _, w in live), default=0))
        if self._digest_on or pre is not None:
            # BEFORE the newborn drain: the shadow replay reproduces the
            # raw post-scan state (the drain zeroes nb_count afterwards)
            self._integrity_boundary(k, pre)
        if self._sysm_on:
            self._drain_newborns(k)

    def _drain_newborns(self, k: int):
        """Per-world systematics drain with per-world stamps: each
        world's window is stamped with ITS update (post-chunk for k>1,
        the solo run_update pre-advance convention for k=1), so each
        member's phylogeny matches its solo run exactly."""
        if not self._sysm_on:
            return
        for i, w in self._live():
            if w.systematics is None:
                continue
            snap = {name: getattr(self.bstate, name)[i]
                    for name in World._NB_SNAP_FIELDS}
            at = w.update if k > 1 else w.update - 1
            snap["update_at"] = at
            snap["win_start"] = w._last_drain_update
            w._last_drain_update = at
            w._feed_systematics(snap)
        self.bstate = self.bstate.replace(
            nb_count=jnp.zeros((self.width,), jnp.int32))

    # ---- silent-corruption integrity plane (serve flavor) ----

    def _integrity_boundary(self, k: int, pre):
        """Per-chunk digests + sampled shadow replay for a serving
        batch.  The synchronous flavor (the serve loop syncs at every
        checkpoint boundary anyway): digests resolve immediately into
        serve.json/state_digests.  A mismatching live tenant rolls back
        ALONE -- suspect generations (saved past its verified horizon)
        quarantined, slot freed to ghost, outcome "sdc" in `finished`
        for the pool to journal + requeue -- while classmates keep
        serving.  A mismatching GHOST slot means the batch itself (or
        the engine) corrupted: batch-wide StateDivergenceError, child
        exit 67."""
        import time as _time

        from avida_tpu.ops.digest import state_digest_batched
        from avida_tpu.utils import integrity
        from avida_tpu.utils.integrity import StateDivergenceError
        t0 = _time.monotonic()
        d_live = state_digest_batched(self.bstate)
        if pre is None:
            vals = [int(x) for x in np.asarray(d_live)]
            integrity.note_digest_ms((_time.monotonic() - t0) * 1e3)
            self._record_digests(vals)
            return
        live = np.asarray(d_live)
        integrity.note_digest_ms((_time.monotonic() - t0) * 1e3)
        from avida_tpu.utils import compilecache
        pre_b, u0 = pre
        integrity.note_scrub()
        shadow_b, _outs = compilecache.call(
            multiworld_scan, "multiworld_scan",
            (self.params, pre_b, k, self._run_keys,
             self.neighbors, u0),
            cfg=self.cfg, log=self._compile_cache_log,
            sig=self._serve_sig)
        t0 = _time.monotonic()
        shad = np.asarray(state_digest_batched(shadow_b))
        integrity.note_digest_ms((_time.monotonic() - t0) * 1e3)
        bad = [i for i in range(self.width)
               if int(live[i]) != int(shad[i])]
        if not bad:
            for i, w in self._live():
                self._verified[i] = w.update
            self._record_digests([int(x) for x in live])
            integrity.append_integrity_record(
                self.data_dir, "scrub", boundary=self.boundaries,
                chunk_updates=k, ok=True)
            return
        ghosts_bad = [i for i in bad if self.slots[i] is None]
        if ghosts_bad:
            integrity.note_mismatch()
            raise StateDivergenceError(
                f"silent state divergence in GHOST slot(s) {ghosts_bad} "
                f"of a serving batch -- a zero-trip identity changed, "
                f"the whole batch is suspect (width {self.width}, "
                f"last chunk {k} updates)")
        self._sync_worlds()
        for i in bad:
            w = self.slots[i]
            name = self.names[i]
            integrity.note_mismatch()
            quarantined = []
            if w._ckpt_base():
                from avida_tpu.utils.checkpoint import quarantine_after
                quarantined = quarantine_after(w._ckpt_base(),
                                               self._verified[i])
            integrity.append_integrity_record(
                self.data_dir, "scrub", ok=False, world=name,
                update=int(w.update), chunk_updates=k,
                last_verified_update=self._verified[i],
                quarantined=len(quarantined))
            self._log(
                f"SDC: {name} diverged from its shadow replay in its "
                f"updates [{int(w.update) - k}, {int(w.update)}); "
                f"quarantined {len(quarantined)} suspect generation(s) "
                f"past update {self._verified[i]}; demoting -- "
                f"classmates keep serving")
            verified = self._verified[i]
            self._retire(i, "sdc", save=False)
            self.finished[name]["last_verified_update"] = verified
            self.finished[name]["quarantined"] = len(quarantined)
        for i, w in self._live():
            self._verified[i] = w.update
        self._stack()

    def _record_digests(self, vals: list):
        from avida_tpu.utils import integrity
        named = {self.names[i]: f"{vals[i]:#010x}"
                 for i, _ in self._live()}
        self.state_digests = (self.boundaries, vals)
        if self._digest_on and named:
            integrity.append_integrity_record(
                self.data_dir, "digest", boundary=self.boundaries,
                digests=named)

    # ---- status + metrics ----

    def _write_status(self, idle: bool = False):
        members = {}
        for i, w in self._live():
            members[self.names[i]] = {
                "state": "live", "update": int(w.update),
                "max_updates": self.max_updates[i],
                "organisms": (int(np.asarray(w.state.alive).sum())
                              if w.state is not None else None)}
        status = {
            "record": "serve", "time": self._clock(),
            "width": self.width, "live": self.num_live,
            "ghosts": self.num_ghosts, "idle": bool(idle),
            "boundaries": self.boundaries,
            "admissions": self.admissions,
            "retirements": self.retirements,
            "compiles": scan_trace_count(),
            # warm-start evidence's other half: programs deserialized
            # from the persistent AOT cache (utils/compilecache.py) --
            # a cold child warming from a sibling's executables shows
            # cache_loads == program count with compiles == 0
            "cache_loads": _compilecache_loads(),
            "preempted": bool(self.preempted or self._preempt),
            "shutdown": self._shutdown,
            "members": members,
            "finished": dict(self.finished),
        }
        path = os.path.join(self.data_dir, "serve.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(self.data_dir, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(status, f, indent=1)
                f.write("\n")
            os.replace(tmp, path)
        except OSError:
            pass                        # status must not kill serving

    def _publish(self, idle: bool = False, final: bool = False):
        self._write_status(idle=idle)
        if self.exporter is not None:
            self.exporter.export(self, durable=final)

    # ---- the serve loop ----

    def serve(self) -> int:
        """Serve until shutdown / idle timeout / preemption.  Returns
        the number of checkpoint boundaries crossed."""
        boundary_every = int(self.cfg.get("TPU_CKPT_EVERY", 0)) or 32
        max_stretch = int(self.cfg.get("TPU_MAX_STRETCH", 0))
        idle_sec = float(self.cfg.get("TPU_SERVE_IDLE_SEC", 600))
        poll_sec = float(self.cfg.get("TPU_SERVE_POLL_SEC", 1.0))
        cap = 8 if self._sysm_on else 128
        if max_stretch > 0:
            cap = min(cap, max_stretch)
        cap = pow2_floor(cap)
        self._preempt = False
        self.preempted = False
        handlers = self._install_preempt_handlers()
        since_boundary = 0
        idle_since = None
        try:
            if int(self.cfg.get("TPU_SERVE_WARM", 1)):
                # compile-cache warmup: scan every power-of-two chunk
                # length on the ALL-GHOST batch (zero trips -- the
                # masked identity makes each warm scan almost free at
                # run time) BEFORE any tenant arrives, so no admission
                # ever waits on a compile: a rider promoted later hits
                # only already-traced programs (scan_trace_count is
                # flat across churn; tests/test_serve_batch.py)
                sizes, k = [], 1
                while k <= min(cap, boundary_every):
                    sizes.append(k)
                    k <<= 1
                self._log(f"warming scan programs: chunk sizes {sizes}")
                self._stack()
                for k in sizes:
                    self._scan(k)
                self._sync_worlds()
                self._log(
                    f"warm: {scan_trace_count()} traced, "
                    f"{_compilecache_loads()} loaded from the persistent "
                    f"compile cache")
            self._reconcile()
            self._publish(idle=not self._live())
            while not self._exit and not self._preempt:
                # retire members that hit their budget (or an Exit
                # event) FIRST, before any event processing -- the solo
                # loop breaks at its max_updates check before touching
                # events, and mirroring that ordering keeps the final
                # retirement checkpoint byte-identical to the solo
                # TPU_CKPT_FINAL generation (same events_done_for
                # cursor) whenever the chunk grids coincide
                for i, w in self._live():
                    if w._exit or (self.max_updates[i] is not None
                                   and w.update >= self.max_updates[i]):
                        self._sync_worlds()
                        self._retire(i, "done")
                live = self._live()
                if not live:
                    now = self._clock()
                    if idle_since is None:
                        idle_since = now
                    if self._shutdown:
                        self._log("shutdown requested; exiting")
                        break
                    if idle_sec > 0 and now - idle_since > idle_sec:
                        self._log(f"idle past {idle_sec:.0f}s; exiting")
                        break
                    self._sleep(poll_sec)
                    if self._reconcile():
                        idle_since = None
                    self._publish(idle=not self._live())
                    continue
                idle_since = None
                # per-world event boundary work at the PRE-chunk
                # updates (solo process_events ordering, including the
                # events_done_for cursor each world's checkpoint
                # serializes)
                if any(w._events_fire_now() for _, w in live):
                    self._sync_worlds()
                    for _, w in live:
                        w.process_events()
                else:
                    for _, w in live:
                        w._events_done_for = w.update
                if any(w._exit for _, w in live):
                    continue            # Exit events retire at the top
                gap = min(
                    (min(w._next_event_due(),
                         float("inf") if self.max_updates[i] is None
                         else self.max_updates[i]) - w.update)
                    for i, w in live)
                k = pow2_floor(int(min(float(gap),
                                       float(boundary_every
                                             - since_boundary),
                                       float(cap))))
                self._stack()
                self._scan(k)
                since_boundary += k
                if since_boundary >= boundary_every:
                    # THE checkpoint boundary, in the same iteration as
                    # the chunk that reached it (solo run-loop shape):
                    # saves, then the membership reconcile --
                    # promotions and demotions land here
                    self._sync_worlds()
                    for i, w in self._live():
                        if w._ckpt_base():
                            w.save_checkpoint()
                        if self._member_exports(w):
                            # per-tenant heartbeat refresh (the state
                            # just synced, so the readback is free):
                            # fleet --status member sub-rows and the
                            # serve bench's per-tenant instruction
                            # totals read these files
                            w.exporter.export(w)
                    since_boundary = 0
                    self.boundaries += 1
                    self._reconcile()
                    self._publish()
                    if self._boundary_hook is not None:
                        self._boundary_hook(self)
            self._sync_worlds()
            self.preempted = self._preempt
            if self._preempt:
                for i, w in self._live():
                    w._preempt = True
                    w.preempted = True
                    if w._ckpt_base():
                        w.save_checkpoint()
            self._publish(final=True)
        finally:
            import signal as _signal
            for s, h in handlers.items():
                try:
                    _signal.signal(s, h)
                except (ValueError, OSError):
                    pass
            for _, w in self._live():
                for f in w._files.values():
                    try:
                        f.close()
                    except Exception:
                        pass
                w._files = {}
                w._dat_append = True
        return self.boundaries
