"""Run-service layer: the self-healing supervisor + the fleet orchestrator.

Host-only (no jax import anywhere in this package): the supervisor is
the process that must stay alive while the run process crashes, hangs
or corrupts itself, so it watches entirely from outside -- child exit
codes, the metrics.prom heartbeat file and the checkpoint directory.
The fleet orchestrator (fleet.py) multiplexes many poll()-mode
supervisors over a spool of job specs under the same rule: it must
outlive every tenant's runtime.

Child exit codes (set by avida_tpu/__main__.py so the supervisor can
classify failures without parsing tracebacks):
"""

# sysexits-adjacent, chosen to be distinguishable from Python's generic
# exit 1 and from signal deaths (negative returncodes)
EXIT_AUDIT = 65      # StateInvariantError escaped World.run (EX_DATAERR)
EXIT_CKPT = 66       # no valid checkpoint generation on resume (EX_NOINPUT)
EXIT_SDC = 67        # StateDivergenceError: a scrub (shadow replay)
#                      caught silent data corruption (utils/integrity.py)

FAILURE_CLASSES = ("crash", "hang", "audit_violation", "corrupt_ckpt",
                   "sdc", "preempt")
