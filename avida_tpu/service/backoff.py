"""Restart pacing: exponential backoff, decorrelated jitter, retry budget.

The supervisor must neither hammer a crash-looping run (a config error
would relaunch at full speed forever) nor synchronize a fleet of
restarting tenants into a thundering herd.  The standard answer is
capped exponential backoff with DECORRELATED jitter: each delay is drawn
uniformly from [base, 3 * previous_delay] and clipped to the cap, so
consecutive delays grow roughly exponentially but two supervisors that
failed at the same instant diverge immediately.

The retry BUDGET is the give-up bound: `max_retries` consecutive
failures and the supervisor stops (a human's problem now).  A child that
stays healthy for `healthy_sec` refills the budget -- a run that fails
once a day for a month is healthy-with-hiccups, not crash-looping, and
must not exhaust a lifetime counter.

Pure host code with an injected RNG seed and no reads of the wall
clock: callers pass elapsed-healthy time in, so unit tests drive it with
a fake clock and zero real sleeps.
"""

from __future__ import annotations

import random


class RetryPolicy:
    def __init__(self, max_retries: int = 8, base: float = 1.0,
                 cap: float = 60.0, healthy_sec: float = 300.0,
                 seed: int = 0):
        if base <= 0 or cap < base:
            raise ValueError(f"need 0 < base <= cap (got {base}, {cap})")
        self.max_retries = int(max_retries)
        self.base = float(base)
        self.cap = float(cap)
        self.healthy_sec = float(healthy_sec)
        self._rng = random.Random(seed)
        self.failures = 0
        self._prev = self.base

    def can_retry(self) -> bool:
        return self.failures < self.max_retries

    def budget_left(self) -> int:
        return max(self.max_retries - self.failures, 0)

    def next_delay(self) -> float:
        """Record one failure and return the sleep before the next
        launch.  Decorrelated jitter: uniform in [base, 3*prev], clipped
        to cap.  Call only while can_retry()."""
        self.failures += 1
        delay = min(self.cap, self._rng.uniform(self.base, self._prev * 3))
        self._prev = delay
        return delay

    def note_healthy(self, healthy_elapsed: float) -> bool:
        """Report continuous-healthy child time; once it reaches
        healthy_sec the failure budget and the backoff ladder reset.
        Returns True when a reset happened."""
        if healthy_elapsed >= self.healthy_sec and (
                self.failures or self._prev != self.base):
            self.failures = 0
            self._prev = self.base
            return True
        return False
